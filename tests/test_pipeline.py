"""Fused crypto pipeline (parallel/pipeline.py): recompile guard, fused
Merkle equivalence, ring dedup, double buffering, controller steering,
supervisor composition, and the disabled-overhead bound."""
import random
import time

import numpy as np
import pytest

from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import (CpuEd25519Verifier, Ed25519Signer,
                                       JaxEd25519Verifier)
from plenum_tpu.parallel.pipeline import (CryptoPipeline,
                                          PipelineController,
                                          make_crypto_pipeline)


class FakeDeviceVerifier(JaxEd25519Verifier):
    """Records dispatched batch shapes and answers instantly (verdict
    content is irrelevant to the shape/buffer tests). Subclassing the jax
    verifier makes the pipeline treat it as device-backed (bucket pad)."""

    def __init__(self):
        super().__init__(min_batch=1)
        self.shapes: list[int] = []

    def submit_batch(self, items):
        self.shapes.append(len(items))
        return np.ones(len(items), dtype=bool)

    def collect_batch(self, token, wait=True):
        return token


class ManualDeviceVerifier(FakeDeviceVerifier):
    """Like FakeDeviceVerifier, but resolution is handed out manually —
    the double-buffer test controls exactly when a wave 'lands'."""

    def __init__(self):
        super().__init__()
        self.pending: list[dict] = []

    def submit_batch(self, items):
        self.shapes.append(len(items))
        tok = {"n": len(items), "ready": False}
        self.pending.append(tok)
        return tok

    def collect_batch(self, token, wait=True):
        if not token["ready"] and not wait:
            return None
        token["ready"] = True
        return np.ones(token["n"], dtype=bool)


def _junk_items(rng, n):
    """Unique well-FORMED triples (content correctness is not under test
    — the fake inner answers all-True). The S half's top byte is zeroed
    so S < L: the ring settles malformed/malleable lanes as False
    without dispatching them, and these must reach the device."""
    return [(rng.randbytes(20), rng.randbytes(63) + b"\x00",
             rng.randbytes(32)) for _ in range(n)]


def _fast_config(**over):
    return Config(PIPELINE_MIN_BUCKET=16, PIPELINE_MAX_BUCKET=64,
                  PIPELINE_FLUSH_WAIT=0.0, **over)


def test_recompile_guard_flat_across_mixed_waves():
    """Steady-state compile count stays FLAT across 100 mixed-size waves:
    after one warmup wave per pinned bucket shape, no novel shape may
    ever be dispatched (a recompile costs minutes on a tunneled TPU)."""
    rng = random.Random(11)
    inner = FakeDeviceVerifier()
    pipe = CryptoPipeline(ed_inner=inner, config=_fast_config())

    # warmup: one wave per bucket in the pinned ladder (16, 32, 64)
    for size in (3, 20, 40):
        tok = pipe.submit_verify(_junk_items(rng, size))
        pipe.flush()
        assert pipe.collect_verify(tok) is not None
    warm_shapes = pipe.compiled_shapes
    pipe.pin()

    for _ in range(100):
        tok = pipe.submit_verify(_junk_items(rng, rng.randint(1, 60)))
        pipe.flush()
        assert pipe.collect_verify(tok) is not None
    assert pipe.compiled_shapes == warm_shapes, \
        "steady state met a novel dispatch shape"
    assert pipe.stats["unpinned_shapes"] == 0
    # every dispatched batch landed exactly on a pinned bucket
    assert set(inner.shapes) <= {16, 32, 64}


def test_pinned_enforcement_pads_and_splits_to_compiled_shapes():
    """After pin(), a wave size with NO compiled bucket must not compile
    one: it pads up to the smallest compiled bucket that fits or splits
    at the largest — a novel mid-run shape costs a 25-45 s XLA
    retrace+compile (the measured 206 -> 5.7 TPS collapse), padding
    costs microseconds."""
    rng = random.Random(19)
    inner = FakeDeviceVerifier()
    pipe = CryptoPipeline(ed_inner=inner, config=_fast_config())
    # warm ONLY bucket 16 (the single-txn warmup shape), then pin
    tok = pipe.submit_verify(_junk_items(rng, 3))
    pipe.flush()
    assert pipe.collect_verify(tok) is not None
    assert pipe.compiled_shapes == 1
    pipe.pin()
    # 40 items would naturally pick bucket 64 — enforcement must split
    # into 16-lane waves instead (the only compiled shape)
    tok = pipe.submit_verify(_junk_items(rng, 40))
    out = pipe.collect_verify(tok, wait=True)
    assert out is not None and len(out) == 40 and out.all()
    assert set(inner.shapes) == {16}
    assert pipe.compiled_shapes == 1
    assert pipe.stats["unpinned_shapes"] == 0


def test_prewarm_compiles_ladder_then_steady_state_never_recompiles():
    rng = random.Random(29)
    inner = FakeDeviceVerifier()
    pipe = CryptoPipeline(ed_inner=inner, config=_fast_config())
    assert pipe.prewarm([16, 32]) == [16, 32]
    assert set(inner.shapes) == {16, 32}
    assert pipe.compiled_shapes == 2
    pipe.pin()
    # prewarm lanes (all-zero verkey) must not poison the verdict cache
    assert not pipe._ed_cache
    for size in (1, 10, 17, 30, 60):
        tok = pipe.submit_verify(_junk_items(rng, size))
        out = pipe.collect_verify(tok, wait=True)
        assert out is not None and len(out) == size
    assert set(inner.shapes) == {16, 32}       # 60 split as 32+{16,32}
    assert pipe.stats["unpinned_shapes"] == 0
    # a cpu-backed (unbucketed) pipeline has no shapes to compile
    assert CryptoPipeline(ed_inner=CpuEd25519Verifier(),
                          config=_fast_config()).prewarm([16]) == []


def test_bucket_padding_and_overflow_split():
    rng = random.Random(5)
    pipe = CryptoPipeline(ed_inner=FakeDeviceVerifier(),
                          config=_fast_config())
    # 150 items > max bucket 64: the wave splits, leftovers ride the next
    tok = pipe.submit_verify(_junk_items(rng, 150))
    out = pipe.collect_verify(tok, wait=True)
    assert out is not None and len(out) == 150 and out.all()
    assert pipe.stats["overflow_waves"] >= 1
    assert pipe.stats["dispatches"] >= 3          # 64 + 64 + 22


def test_malformed_lanes_settle_before_dispatch():
    """Malformed/malleable items (short sig, wrong-size vk, S >= L) are
    settled False in the ring and never ride a wave: the dispatched
    batch length always equals the padded bucket. The device verifier's
    own staging screen drops such lanes AFTER the ring pads, so letting
    them through would shrink the real device shape under the one the
    guard recorded and pin() enforced — a novel mid-run compile."""
    rng = random.Random(31)
    inner = FakeDeviceVerifier()
    pipe = CryptoPipeline(ed_inner=inner, config=_fast_config())
    good = _junk_items(rng, 10)
    bad = [
        (b"m", b"\x01" * 63, b"\x02" * 32),          # short sig
        (b"m", b"\x01" * 64, b"\x02" * 31),          # short vk
        (b"m", b"\xff" * 64, b"\x02" * 32),          # S >= L (malleable)
        (b"m", None, b"\x02" * 32),                  # not bytes at all
    ]
    tok = pipe.submit_verify(good + bad)
    out = pipe.collect_verify(tok, wait=True)
    assert list(out) == [True] * 10 + [False] * 4
    assert inner.shapes == [16], \
        "screened lanes changed the dispatched device shape"


def test_ring_dedup_across_submitters():
    """Co-hosted nodes stage IDENTICAL items; the ring dispatches each
    unique triple once and publishes the dedup ratio."""
    rng = random.Random(7)
    inner = FakeDeviceVerifier()
    pipe = CryptoPipeline(ed_inner=inner, config=_fast_config())
    items = _junk_items(rng, 10)
    v1, v2, v3 = pipe.verifier(), pipe.verifier(), pipe.verifier()
    toks = [v.submit_batch(items) for v in (v1, v2, v3)]
    pipe.flush()
    for v, tok in zip((v1, v2, v3), toks):
        got = v.collect_batch(tok, wait=True)
        assert got is not None and len(got) == 10
    assert pipe.stats["dispatched_items"] == 10      # once, not 30
    assert pipe.stats["dedup_hits"] == 20
    assert pipe.dedup_ratio() == pytest.approx(20 / 30)
    # a later identical batch rides the verdict cache: no new dispatch
    before = pipe.stats["dispatches"]
    assert v1.verify_batch(items) is not None
    assert pipe.stats["dispatches"] == before


def test_real_jax_wave_verdicts():
    """One real device wave end to end (JAX-on-CPU): good and bad
    signatures come back with the right verdicts through bucket padding
    and the wave cache."""
    signer = Ed25519Signer(seed=b"pipeline-wave-test".ljust(32, b"\0"))
    msgs = [b"wave-%d" % i for i in range(5)]
    items = [(m, signer.sign(m), signer.verkey) for m in msgs]
    items.append((b"forged", signer.sign(msgs[0]), signer.verkey))
    pipe = CryptoPipeline(
        ed_inner=JaxEd25519Verifier(min_batch=1),
        config=Config(PIPELINE_MIN_BUCKET=8, PIPELINE_MAX_BUCKET=8,
                      PIPELINE_FLUSH_WAIT=0.0))
    got = pipe.verifier().verify_batch(items)
    assert list(got) == [True] * 5 + [False]
    assert pipe.stats["dispatches"] == 1
    # cross-check vs the cpu backend on identical content
    assert list(CpuEd25519Verifier().verify_batch(items)) == list(got)


def test_double_buffer_packs_while_inflight():
    """Host packs wave N+1 while the device runs wave N; the packed wave
    dispatches the moment N resolves — without any new flush call."""
    rng = random.Random(3)
    inner = ManualDeviceVerifier()
    pipe = CryptoPipeline(ed_inner=inner, config=_fast_config())
    t1 = pipe.submit_verify(_junk_items(rng, 20))
    pipe.flush()                                   # dispatch wave 1
    assert len(inner.pending) == 1
    t2 = pipe.submit_verify(_junk_items(rng, 20))
    pipe.service(force=True)                       # packs wave 2 only
    assert len(inner.pending) == 1, "dispatched while device busy"
    assert pipe._ed_packed is not None, "wave 2 not packed during flight"
    inner.pending[0]["ready"] = True               # wave 1 lands
    pipe.service()
    assert len(inner.pending) == 2, "packed wave did not auto-dispatch"
    inner.pending[1]["ready"] = True
    assert pipe.collect_verify(t1) is not None
    assert pipe.collect_verify(t2) is not None


def test_controller_steering_replay_identical():
    """Bucket floor grows on overflow, shrinks on chronic pad waste;
    flush wait shrinks when queue wait breaks the SLO. Decisions are a
    pure function of clock-stamped samples — two identical runs decide
    identically."""

    def run():
        clock = {"t": 0.0}
        cfg = Config(PIPELINE_MIN_BUCKET=16, PIPELINE_MAX_BUCKET=256,
                     PIPELINE_CONTROL_INTERVAL=1.0, PIPELINE_SLO_P95=0.05)
        ctl = PipelineController(cfg, lambda: clock["t"])
        log = []
        # phase 1: overflowing waves -> floor must grow
        for _ in range(8):
            clock["t"] += 0.3
            ctl.note_wave(0.001, 256, 256, overflowed=True)
            log.append((ctl.bucket_floor, round(ctl.flush_wait, 6)))
        grown = ctl.bucket_floor
        # phase 2: tiny fills -> floor decays back
        for _ in range(12):
            clock["t"] += 0.3
            ctl.note_wave(0.001, 2, grown, overflowed=False)
            log.append((ctl.bucket_floor, round(ctl.flush_wait, 6)))
        shrunk = ctl.bucket_floor
        # phase 3: queue waits past the SLO -> flush wait halves
        for _ in range(8):
            clock["t"] += 0.3
            ctl.note_wave(0.2, 12, 16, overflowed=False)
            log.append((ctl.bucket_floor, round(ctl.flush_wait, 6)))
        return grown, shrunk, ctl.flush_wait, log, ctl.decisions

    g1, s1, w1, log1, d1 = run()
    g2, s2, w2, log2, d2 = run()
    assert g1 > 16, "overflow did not grow the bucket floor"
    assert s1 < g1, "pad waste did not shrink the floor"
    assert w1 < Config().PIPELINE_FLUSH_WAIT, \
        "SLO-breaking queue wait did not shrink the flush hold"
    assert (g1, s1, w1, log1, d1) == (g2, s2, w2, log2, d2), \
        "controller decisions are not replay-identical"


def test_bls_lane_ring_dedup():
    """Identical BLS triples staged by co-hosted submitters settle on ONE
    inner batch_verify over the deduped union."""
    calls = []

    class FakeBls:
        def batch_verify(self, items):
            calls.append(list(items))
            return [True] * len(items)

    pipe = CryptoPipeline(ed_inner=FakeDeviceVerifier(),
                          bls_inner=FakeBls(), config=_fast_config())
    items = [("sig%d" % i, b"msg", "vk%d" % i) for i in range(6)]
    t1 = pipe.submit_bls(items)
    t2 = pipe.submit_bls(items)           # the co-hosted twin
    assert pipe.collect_bls(t1) == [True] * 6
    assert pipe.collect_bls(t2) == [True] * 6
    assert len(calls) == 1 and len(calls[0]) == 6
    assert pipe.stats["bls_unique"] == 6
    assert pipe.stats["dedup_hits"] >= 6


def test_sha_lane_and_tree_hasher_dedup():
    """The pipelined tree hasher's digests match hashlib exactly, and two
    replicas hashing the SAME leaf wave pay the work once."""
    from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
    from plenum_tpu.ledger.tree_hasher import TreeHasher

    pipe = CryptoPipeline(ed_inner=FakeDeviceVerifier(),
                          config=_fast_config())
    h1, h2 = pipe.tree_hasher(), pipe.tree_hasher()
    ref = TreeHasher()
    leaves = [b"txn-%d" % i for i in range(40)]
    assert h1.hash_leaves(leaves) == ref.hash_leaves(leaves)
    pairs = list(zip(ref.hash_leaves(leaves[0::2]),
                     ref.hash_leaves(leaves[1::2])))
    assert h1.hash_children_batch(pairs) == ref.hash_children_batch(pairs)
    before_unique = pipe.stats["sha_unique"]
    assert h2.hash_leaves(leaves) == ref.hash_leaves(leaves)
    assert pipe.stats["sha_unique"] == before_unique, \
        "replica twin re-hashed cached leaves"
    # whole trees through the pipelined hasher agree with pure python
    t_ref = CompactMerkleTree(TreeHasher())
    t_pipe = CompactMerkleTree(pipe.tree_hasher())
    rng = random.Random(23)
    for _ in range(10):
        chunk = [rng.randbytes(rng.randint(1, 40))
                 for _ in range(rng.randint(1, 30))]
        t_ref.extend_batch(chunk)
        t_pipe.extend_batch(chunk)
        assert t_ref.root_hash == t_pipe.root_hash


def test_fused_merkle_root_equivalence_random():
    """Fused-wave appends (one device program for all wide interior
    levels) produce byte-identical roots and proofs vs the pure-Python
    hasher across random leaf sets and arbitrary base alignments."""
    from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
    from plenum_tpu.ledger.tree_hasher import JaxTreeHasher, TreeHasher

    rng = random.Random(41)
    ref = CompactMerkleTree(TreeHasher())
    # min_batch huge: leaf hashing stays on hashlib; ONLY the fused
    # interior path is under test (fuse_min=2 forces it for every wave)
    fused = CompactMerkleTree(JaxTreeHasher(min_batch=10**9, fuse_min=2))
    total = 0
    for step in range(25):
        chunk = [rng.randbytes(rng.randint(1, 60))
                 for _ in range(rng.randint(1, 40))]
        ref.extend_batch(chunk)
        fused.extend_batch(chunk)
        total += len(chunk)
        assert ref.root_hash == fused.root_hash, f"root diverged @{step}"
        assert ref.tree_size == fused.tree_size
    for m in (0, 1, total // 3, total - 1):
        assert ref.inclusion_proof(m) == fused.inclusion_proof(m)
    for m in (1, 2, total // 2, total):
        assert ref.consistency_proof(m) == fused.consistency_proof(m)


def test_supervisor_composition_wedge_falls_back():
    """The pipeline dispatches THROUGH the supervised verifier: a wedged
    device degrades a wave to hedged CPU verdicts (correct, bounded) and
    the breaker records the failure — device_flap composes unchanged."""
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.supervisor import (CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)

    faulty = FaultyVerifier(CpuEd25519Verifier())
    sup = SupervisedVerifier(
        faulty, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=1, cooldown=60.0),
        budget=DeadlineBudget(base=0.2, min_s=0.1, warm_max=0.3,
                              cold_max=0.3))
    pipe = CryptoPipeline(ed_inner=sup, config=_fast_config())
    signer = Ed25519Signer(seed=b"pipe-flap".ljust(32, b"\0"))
    items = [(b"m%d" % i, signer.sign(b"m%d" % i), signer.verkey)
             for i in range(3)]
    faulty.wedge()
    got = pipe.verifier().verify_batch(items)
    assert list(got) == [True, True, True]
    assert sup.stats["hedge_wins"] + sup.stats["fallback_batches"] >= 1
    assert sup.stats["verdict_forks"] == 0
    # breaker open: the next wave routes straight to CPU, unpadded
    fresh = [(b"x%d" % i, signer.sign(b"x%d" % i), signer.verkey)
             for i in range(3)]
    got2 = pipe.verifier().verify_batch(fresh)
    assert list(got2) == [True, True, True]
    assert sup.stats["open_circuit_fallbacks"] >= 1


def test_disabled_pipeline_overhead_bound():
    """CRYPTO_PIPELINE=False (or a cpu backend) returns None from the
    construction seam, and the per-prod-cycle disabled cost — the
    `pipeline is not None` gate — stays NullTracer-grade: under 2% of a
    1 ms/txn budget across 1000 checks."""
    assert make_crypto_pipeline(Config(CRYPTO_PIPELINE=False), "jax") is None
    assert make_crypto_pipeline(Config(), "cpu") is None
    from plenum_tpu.node.bootstrap import NodeBootstrap
    comp = NodeBootstrap("OverheadNode").build()
    assert comp.pipeline is None
    assert not type(comp.authenticator.core_authenticator.verifier
                    ).__name__.startswith("Pipeline")
    n = 1000
    t0 = time.perf_counter()
    hits = 0
    for _ in range(n):
        if comp.pipeline is not None:     # the exact prod-loop gate
            hits += 1
    per_check = (time.perf_counter() - t0) / n
    assert hits == 0
    assert per_check < 0.02e-3, \
        f"disabled gate costs {per_check * 1e6:.2f}us per prod cycle"


def test_make_crypto_pipeline_constructs_for_device_backends():
    pipe = make_crypto_pipeline(Config(), "jax")
    assert pipe is not None
    from plenum_tpu.parallel.supervisor import find_supervisor
    assert find_supervisor(pipe.verifier()) is not None, \
        "pipeline verifier chain hides the supervisor from node wiring"


# --- multi-device lanes (ISSUE 14 tentpole) --------------------------------

def _multi_pipe(n_lanes=4, **over):
    from plenum_tpu.parallel.pipeline import MultiDeviceCryptoPipeline
    inners = [FakeDeviceVerifier() for _ in range(n_lanes)]
    pipe = MultiDeviceCryptoPipeline(ed_inners=inners,
                                     config=_fast_config(**over),
                                     threaded=False)
    return pipe, inners


def test_multidevice_placement_and_dispatch_spread():
    """Co-hosted shard tags pin to distinct chips (tag % lanes); the
    unhinted path spreads waves over every healthy lane; per-lane
    dispatch counts and device_state tell the story."""
    rng = random.Random(53)
    pipe, inners = _multi_pipe(4)
    assert [pipe.place(t) for t in range(6)] == [0, 1, 2, 3, 0, 1]
    # hinted: lane 2 gets the wave, nobody else
    tok = pipe.submit_verify(_junk_items(rng, 8), lane=2)
    out = pipe.collect_verify(tok, wait=True)
    assert out is not None and len(out) == 8
    assert pipe.lanes[2].stats["dispatches"] == 1
    assert all(pipe.lanes[k].stats["dispatches"] == 0 for k in (0, 1, 3))
    # unhinted: waves spread across all lanes
    toks = [pipe.submit_verify(_junk_items(rng, 8)) for _ in range(8)]
    for t in toks:
        assert pipe.collect_verify(t, wait=True) is not None
    spread = [l.stats["dispatches"] for l in pipe.lanes]
    assert all(d >= 1 for d in spread), spread
    state = pipe.device_state()
    assert [d["lane"] for d in state] == [0, 1, 2, 3]
    assert sum(d["dispatches"] for d in state) == sum(spread)
    assert pipe.summary()["lanes"] == 4


def test_multidevice_per_lane_prewarm_pin_enforcement():
    """prewarm compiles each chip's OWN ladder; after pin() every lane
    enforces ITS compiled shapes (pad up / split), so steady state never
    recompiles on ANY chip — the per-lane twin of the PR 8 guard."""
    rng = random.Random(59)
    pipe, inners = _multi_pipe(3)
    assert pipe.prewarm([16, 32]) == [16, 32]
    for inner in inners:
        assert set(inner.shapes) == {16, 32}
    warm = pipe.compiled_shapes
    pipe.pin()
    for _ in range(60):
        tok = pipe.submit_verify(_junk_items(rng, rng.randint(1, 60)),
                                 lane=rng.randint(0, 5))
        assert pipe.collect_verify(tok, wait=True) is not None
    assert pipe.compiled_shapes == warm, \
        "steady state met a novel dispatch shape on some lane"
    assert pipe.stats["unpinned_shapes"] == 0
    for inner in inners:
        assert set(inner.shapes) <= {16, 32}


def test_multidevice_one_lane_breaker_isolation():
    """A wedged chip opens THAT lane's breaker only: its pinned waves
    degrade to host fallback, the other lanes keep dispatching to their
    devices, and unhinted traffic routes around the sick chip."""
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.pipeline import MultiDeviceCryptoPipeline
    from plenum_tpu.parallel.supervisor import (CLOSED, CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    faulties, sups = [], []
    for k in range(3):
        f = FaultyVerifier(CpuEd25519Verifier(), device_index=k)
        s = SupervisedVerifier(
            f, fallback=CpuEd25519Verifier(),
            breaker=CircuitBreaker(fail_threshold=1, cooldown=60.0),
            budget=DeadlineBudget(base=0.2, min_s=0.1, warm_max=0.3,
                                  cold_max=0.3),
            label=f"lane{k}")
        faulties.append(f)
        sups.append(s)
    pipe = MultiDeviceCryptoPipeline(ed_inners=sups,
                                     config=_fast_config(),
                                     threaded=False)
    signer = Ed25519Signer(seed=b"lane-iso".ljust(32, b"\0"))
    mk = lambda tag, n=3: [
        (b"%s-%d" % (tag, i), signer.sign(b"%s-%d" % (tag, i)),
         signer.verkey) for i in range(n)]
    faulties[1].wedge()
    got = pipe.collect_verify(pipe.submit_verify(mk(b"w"), lane=1),
                              wait=True)
    assert list(got) == [True] * 3          # host fallback, correct
    assert sups[1].breaker.state != CLOSED
    assert sups[0].breaker.state == CLOSED
    assert sups[2].breaker.state == CLOSED
    # unhinted traffic avoids the open lane entirely
    for i in range(4):
        pipe.collect_verify(pipe.submit_verify(mk(b"u%d" % i)), wait=True)
    assert pipe.lanes[1].stats["dispatches"] == 1   # only its pinned wave
    assert (pipe.lanes[0].stats["dispatches"]
            + pipe.lanes[2].stats["dispatches"]) >= 4
    # telemetry story: device_state names the sick chip
    state = {d["lane"]: d["breaker"] for d in pipe.device_state()}
    assert state[1] == "open" and state[0] == "closed"


def test_multidevice_threaded_lanes_concurrent_dispatch():
    """Threaded lanes (the device-pinned production shape) resolve waves
    from worker threads: N in-flight waves make progress without the
    pump blocking on any one of them."""
    import threading

    class SlowDev(FakeDeviceVerifier):
        def __init__(self):
            super().__init__()
            self.gate = threading.Event()

        def submit_batch(self, items):
            self.shapes.append(len(items))
            return np.ones(len(items), dtype=bool)

        def collect_batch(self, token, wait=True):
            self.gate.wait(timeout=5.0)
            return token

    from plenum_tpu.parallel.pipeline import MultiDeviceCryptoPipeline
    rng = random.Random(61)
    inners = [SlowDev() for _ in range(3)]
    pipe = MultiDeviceCryptoPipeline(ed_inners=inners,
                                     config=_fast_config(),
                                     threaded=True)
    toks = [pipe.submit_verify(_junk_items(rng, 8), lane=k)
            for k in range(3)]
    pipe.service(force=True)
    assert all(l.inflight is not None for l in pipe.lanes), \
        "three waves must fly CONCURRENTLY, one per lane"
    for inner in inners:
        inner.gate.set()
    for t in toks:
        out = pipe.collect_verify(t, wait=True)
        assert out is not None and len(out) == 8
    pipe.close()


def test_single_device_path_is_pr8_pipeline_exactly():
    """n_devices == 1 pools pay NO sharding overhead: the construction
    seam returns the PR 8 CryptoPipeline CLASS itself (no lane
    indirection on the hot path), and per-op submit/collect cost stays
    within noise of a directly-built PR 8 ring."""
    from plenum_tpu.parallel.pipeline import MultiDeviceCryptoPipeline
    p1 = make_crypto_pipeline(Config(PIPELINE_DEVICES=1), "jax")
    assert type(p1) is CryptoPipeline, \
        "single-device pool got the multi-device class"
    assert not isinstance(p1, MultiDeviceCryptoPipeline)
    # default config IS the single-device config
    assert Config().PIPELINE_DEVICES == 1

    def drive(pipe, n_ops=60):
        rng = random.Random(67)
        t0 = time.perf_counter()
        for _ in range(n_ops):
            tok = pipe.submit_verify(_junk_items(rng, 8))
            pipe.collect_verify(tok, wait=True)
        return (time.perf_counter() - t0) / n_ops

    baseline = CryptoPipeline(ed_inner=FakeDeviceVerifier(),
                              config=_fast_config())
    seamed = make_crypto_pipeline(
        Config(PIPELINE_MIN_BUCKET=16, PIPELINE_MAX_BUCKET=64,
               PIPELINE_FLUSH_WAIT=0.0, PIPELINE_DEVICES=1),
        "jax", ed_inner=FakeDeviceVerifier())
    drive(baseline, 10)                     # warm both paths
    drive(seamed, 10)
    per_base = drive(baseline)
    per_seam = drive(seamed)
    # same class, same code: anything past 3x is a real regression, not
    # host noise (the loop is pure-python ring work, ~tens of us/op)
    assert per_seam < per_base * 3 + 1e-3, \
        f"single-device seam {per_seam * 1e6:.0f}us/op vs PR 8 " \
        f"{per_base * 1e6:.0f}us/op"


# --- commit-wave (cmt) lane pin ladder ---------------------------------------

class RecordingCmtEngine:
    """Device-style commitment engine fake: records dispatched wave
    sizes (the compiled-shape story is all these tests care about) and
    answers each job with a distinct marker — commitment semantics are
    covered by the state-commitment suite."""

    def __init__(self):
        self.shapes: list[int] = []

    def run_jobs(self, jobs):
        self.shapes.append(len(jobs))
        return [("res", job) for job in jobs]


def _cmt_jobs(tag, n):
    """n unique well-formed commit jobs (content irrelevant: the fake
    engine answers markers; uniqueness defeats the ring's dedup)."""
    return [("commit", 16, ((i, tag * 1000 + i),)) for i in range(n)]


def test_prewarm_cmt_compiles_ladder_and_rejects_non_pow2():
    """prewarm_cmt runs one all-pad wave per bucket through the engine
    (a lane that cannot compile must fail loudly in warmup, never
    degrade silently under load) and notes the shapes onto the cmt pin
    ladder; non-pow2 buckets are rejected before touching the device."""
    eng = RecordingCmtEngine()
    pipe = CryptoPipeline(cmt_inner=eng, config=_fast_config())
    assert pipe.prewarm_cmt([8, 4]) == [4, 8]
    assert eng.shapes == [4, 8]
    assert pipe._cmt_buckets() == [4, 8]
    with pytest.raises(ValueError):
        pipe.prewarm_cmt([6])
    # a short prewarm wave is a loud failure, not a silent degrade
    class Short:
        def run_jobs(self, jobs):
            return []
    with pytest.raises(RuntimeError):
        CryptoPipeline(cmt_inner=Short(),
                       config=_fast_config()).prewarm_cmt([4])
    # engine-less (host) pipelines still note the enforcement ladder
    host = CryptoPipeline(config=_fast_config())
    assert host.prewarm_cmt([16]) == [16]
    assert host._cmt_buckets() == [16]


def test_pinned_cmt_novel_shape_pads_and_splits_not_recompiles():
    """The cmt twin of the ed pin guard: after prewarm_cmt + pin(), a
    novel mid-run cmt wave size pads up to the smallest compiled bucket
    that fits or splits at the largest — never a fresh compile (the
    same XLA retrace a novel ed shape costs on a device MSM engine)."""
    eng = RecordingCmtEngine()
    pipe = CryptoPipeline(cmt_inner=eng, config=_fast_config())
    assert pipe.prewarm_cmt([4, 8]) == [4, 8]
    warm = pipe.compiled_shapes
    pipe.pin()
    eng.shapes.clear()
    # 5 unique jobs: pads up to bucket 8 (smallest compiled that fits)
    jobs = _cmt_jobs(1, 5)
    out = pipe.collect_commitment(pipe.submit_commitment(jobs))
    assert out == [("res", j) for j in jobs]
    assert eng.shapes == [8]
    # 21 unique jobs: split 8 + 8 at the ladder cap, tail padded to 8
    jobs = _cmt_jobs(2, 21)
    out = pipe.collect_commitment(pipe.submit_commitment(jobs))
    assert out == [("res", j) for j in jobs]
    assert set(eng.shapes) == {8}
    assert pipe.compiled_shapes == warm, \
        "steady state met a novel cmt dispatch shape"
    assert pipe.stats["unpinned_shapes"] == 0


def test_cmt_hlev_levels_bypass_engine_but_ride_the_fused_flush():
    """"hlev" hashing levels never reach the MSM engine (no engine
    implements them): a mixed flush dispatches the commit jobs to the
    engine at a pinned bucket while the hash level resolves in the same
    wave — and the flush still lands on zero unpinned shapes."""
    import hashlib
    eng = RecordingCmtEngine()
    pipe = CryptoPipeline(cmt_inner=eng, config=_fast_config())
    pipe.prewarm_cmt([4])
    pipe.pin()
    eng.shapes.clear()
    lev = ("hlev", "sha3", (b"node-a", b"node-b"))
    jobs = _cmt_jobs(3, 2) + [lev]
    out = pipe.collect_commitment(pipe.submit_commitment(jobs))
    assert out[:2] == [("res", j) for j in jobs[:2]]
    assert out[2] == tuple(hashlib.sha3_256(m).digest()
                           for m in (b"node-a", b"node-b"))
    assert eng.shapes == [4]          # 2 commit jobs padded to bucket 4
    assert pipe.stats["unpinned_shapes"] == 0


# --- cross-host federation (parallel/federation.py) --------------------------

def _fed_pipe(n_local=2, n_remote=1, **over):
    from plenum_tpu.parallel.federation import FederatedCryptoPipeline
    locals_ = [FakeDeviceVerifier() for _ in range(n_local)]
    remotes = [FakeDeviceVerifier() for _ in range(n_remote)]
    pipe = FederatedCryptoPipeline(
        ed_inners=locals_, remote_inners=remotes,
        hosts=[f"/tmp/fake{j}.sock" for j in range(n_remote)],
        config=_fast_config(**over), threaded=False)
    return pipe, locals_, remotes


def test_federated_stolen_items_never_double_verified():
    """Work-stealing moves whole, fully-unplanned tokens, so each
    distinct item reaches exactly ONE device exactly once even while
    waves migrate between backlogged lanes: dispatched_items (which
    counts unique reals, not pads) equals the distinct items submitted."""
    rng = random.Random(71)
    pipe, locals_, remotes = _fed_pipe(
        2, 1, PIPELINE_STEAL_THRESHOLD=4, PIPELINE_STEAL_COOLDOWN=0.0)
    n_items = 0
    toks = []
    for i in range(30):
        t = pipe.submit_verify(_junk_items(rng, 5), lane=0)
        t.lane_hint = None          # eligible: only the PIN blocks a steal
        toks.append(t)
        n_items += 5
    pipe._balance()
    assert pipe.stats["steals"] >= 1, "backlog never migrated"
    for t in toks:
        out = pipe.collect_verify(t, wait=True)
        assert out is not None and len(out) == 5
    assert pipe.stats["dispatched_items"] == n_items, \
        "a stolen item was dispatched more (or less) than once"
    assert pipe.stats["stolen_items"] >= 1
    # the remote lane really absorbed work
    assert pipe.lanes[2].stats["dispatched_items"] >= 1


def test_federated_pinned_placement_honored():
    """place() maps pinned shard tags onto LOCAL chips only, and a
    pinned token never migrates off its chip — a backlogged pinned lane
    keeps its own queue (its fallback chain is its own supervisor)."""
    rng = random.Random(73)
    pipe, _, _ = _fed_pipe(2, 2, PIPELINE_STEAL_THRESHOLD=1,
                           PIPELINE_STEAL_COOLDOWN=0.0)
    assert [pipe.place(t) for t in range(5)] == [0, 1, 0, 1, 0]
    toks = [pipe.submit_verify(_junk_items(rng, 2), lane=0)
            for _ in range(40)]
    pre = pipe._lane_backlog(pipe.lanes[0])
    pipe._balance()
    assert pipe.stats["steals"] == 0, "a pinned token migrated"
    assert pipe._lane_backlog(pipe.lanes[0]) == pre
    for t in toks:
        assert pipe.collect_verify(t, wait=True) is not None
    assert pipe.lanes[0].stats["dispatched_items"] == 80
    assert all(l.stats["dispatched_items"] == 0 for l in pipe.lanes[1:])


def test_federated_steal_hysteresis_never_oscillates():
    """Symmetric load on two lanes: neither clears the occupancy-delta
    threshold, so zero steals — and after a genuine steal the per-pair
    cooldown blocks the immediate reverse flow (anti-flap)."""
    rng = random.Random(79)
    pipe, _, _ = _fed_pipe(2, 0, PIPELINE_STEAL_THRESHOLD=8,
                           PIPELINE_STEAL_COOLDOWN=60.0)
    for i in range(20):                     # 20 items each, symmetric
        for lane in (0, 1):
            t = pipe.submit_verify(_junk_items(rng, 1), lane=lane)
            t.lane_hint = None
    for _ in range(50):
        pipe._balance()
    assert pipe.stats["steals"] == 0, "symmetric load oscillated"
    # now a real imbalance: one steal fires, the echo is suppressed
    for i in range(30):
        t = pipe.submit_verify(_junk_items(rng, 1), lane=0)
        t.lane_hint = None
    pipe._balance()
    assert pipe.stats["steals"] == 1
    # tilt the load the OTHER way: the delta now clears the threshold in
    # reverse, but the per-pair cooldown must hold the echo (anti-flap)
    for i in range(30):
        t = pipe.submit_verify(_junk_items(rng, 1), lane=1)
        t.lane_hint = None
    for _ in range(50):
        pipe._balance()
    assert pipe.stats["steals"] == 1, "steal echoed back within cooldown"


def test_federated_breaker_evacuates_to_local_lanes():
    """An open remote breaker evacuates that lane's queue back to
    HOST-LOCAL lanes unconditionally (no threshold, no cooldown) — the
    crypto_host_down steal-back contract."""
    import types
    rng = random.Random(83)
    pipe, locals_, remotes = _fed_pipe(2, 1, PIPELINE_STEAL_THRESHOLD=10 ** 6,
                                       PIPELINE_STEAL_COOLDOWN=60.0)
    # queue unhinted work onto the remote lane directly
    for i in range(10):
        t = pipe.submit_verify(_junk_items(rng, 2))
        t.lane_hint = None
    # drain whatever landed locally so only the remote queue remains
    remote = pipe.lanes[2]
    for lane in pipe.lanes[:2]:
        lane.staged.clear()
        lane.first_staged = None
    if not remote.staged:                   # ensure the remote has work
        t = pipe.submit_verify(_junk_items(rng, 2))
        t.lane_hint = None
        remote.staged.append(t)
    remotes[0].breaker = types.SimpleNamespace(state="open")
    pre = pipe._lane_backlog(remote)
    assert pre > 0
    pipe._balance()
    assert pipe._lane_backlog(remote) == 0, "open lane kept its queue"
    assert sum(pipe._lane_backlog(l) for l in pipe.lanes[:2]) == pre
    assert pipe.stats["steals"] >= 1


def test_federated_idle_dead_host_rejoins_via_pump():
    """Placement routes AROUND an open lane and evacuation empties its
    queue, so a dead host's supervisor sees no traffic at all — nothing
    on the submit/collect path would ever run its probe. The ring pump
    must drive recovery itself (service() -> supervisor.pump_recovery):
    after the host heals, pumping ALONE re-closes the breaker (re-warm
    included) and fresh waves reach the host again."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.federation import FederatedCryptoPipeline
    from plenum_tpu.parallel.supervisor import (CLOSED, CircuitBreaker,
                                                SupervisedVerifier)

    class DyingHost(CpuEd25519Verifier):
        def __init__(self):
            super().__init__()
            self.dead = False
            self.rewarms = 0

        def rewarm(self):
            if self.dead:
                raise ConnectionError("host down")
            self.rewarms += 1

        def submit_batch(self, items):
            if self.dead:
                raise ConnectionError("host down")
            return super().submit_batch(items)

    clock = [0.0]
    host = DyingHost()
    sup = SupervisedVerifier(
        host, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=1, cooldown=1.0,
                               now=lambda: clock[0]),
        now=lambda: clock[0], label="remote0")
    pipe = FederatedCryptoPipeline(
        ed_inners=[FakeDeviceVerifier() for _ in range(2)],
        remote_inners=[sup], hosts=["/tmp/fake0.sock"],
        config=_fast_config(PIPELINE_STEAL_THRESHOLD=10 ** 6,
                            PIPELINE_STEAL_COOLDOWN=60.0),
        threaded=False)
    remote = pipe.lanes[2]
    rng = random.Random(97)

    def through_remote(n):
        t = pipe.submit_verify(_junk_items(rng, n))
        t.lane_hint = None
        for lane in pipe.lanes:
            if t in lane.staged:
                lane.staged.remove(t)
                if not lane.staged:
                    lane.first_staged = None
        remote.staged.append(t)
        if remote.first_staged is None:
            remote.first_staged = clock[0]
        return t

    # the host dies with one wave headed its way: the supervisor falls
    # back (the wave still settles) and the breaker opens
    host.dead = True
    tok = through_remote(2)
    pipe.service(force=True)
    assert pipe.collect_verify(tok, wait=True) is not None
    assert sup.breaker.state != CLOSED
    assert remote.degraded()

    # heal, then pump service() with ZERO traffic anywhere: recovery
    # must come from the pump, not from batches the lane never gets
    host.dead = False
    clock[0] += 2.0                       # past the cooldown
    for _ in range(4):
        pipe.service()
    assert sup.breaker.state == CLOSED, \
        "idle open lane never probed: pump_recovery not driven"
    assert host.rewarms >= 1, "re-admission skipped the re-warm"
    assert not remote.degraded()

    # rejoin is real: a fresh wave through the lane hits the device path
    dev_before = sup.stats["device_batches"]
    tok = through_remote(2)
    pipe.service(force=True)
    assert pipe.collect_verify(tok, wait=True) is not None
    assert sup.stats["device_batches"] > dev_before


def test_federated_zero_remote_constructs_pr14_class_exactly():
    """PIPELINE_REMOTE_HOSTS unset -> the construction seam returns the
    PR 14 classes THEMSELVES (no federation subclass anywhere on the
    hot path), and the federated subclass's pump overhead with zero
    remotes stays within noise of the PR 14 ring (microbench pin)."""
    from plenum_tpu.parallel.federation import FederatedCryptoPipeline
    from plenum_tpu.parallel.pipeline import MultiDeviceCryptoPipeline
    assert Config().PIPELINE_REMOTE_HOSTS == ""
    p1 = make_crypto_pipeline(Config(PIPELINE_DEVICES=1), "jax")
    assert type(p1) is CryptoPipeline
    p2 = make_crypto_pipeline(Config(PIPELINE_DEVICES=2), "jax")
    assert type(p2) is MultiDeviceCryptoPipeline
    assert not isinstance(p2, FederatedCryptoPipeline)
    p2.close()
    # hosts set -> the factory takes the federation branch, which fails
    # FAST on an unreachable roster entry (operator error, not a silent
    # single-host fallback)
    with pytest.raises((OSError, RuntimeError)):
        make_crypto_pipeline(
            Config(PIPELINE_DEVICES=1,
                   PIPELINE_REMOTE_HOSTS="/tmp/nonexistent-fed.sock"),
            "jax")

    def drive(pipe, n_ops=60):
        rng = random.Random(89)
        t0 = time.perf_counter()
        for _ in range(n_ops):
            tok = pipe.submit_verify(_junk_items(rng, 8))
            pipe.collect_verify(tok, wait=True)
        return (time.perf_counter() - t0) / n_ops

    base, _ = _multi_pipe(2)
    fed, _, _ = _fed_pipe(2, 0)
    drive(base, 10)
    drive(fed, 10)
    per_base = drive(base)
    per_fed = drive(fed)
    assert per_fed < per_base * 3 + 1e-3, \
        f"zero-remote federation {per_fed * 1e6:.0f}us/op vs PR 14 " \
        f"{per_base * 1e6:.0f}us/op"
