"""Seeded randomized view-change fuzzing over the deterministic SimNetwork.

Reference test model: plenum/test/consensus/view_change/test_sim_view_change.py
+ test/simulation/sim_network.py:98 — many seeds, random latencies, drops and
primary failures injected mid-protocol; every run must preserve SAFETY (no
two nodes commit different txns at the same seq_no) and, once the fault
heals, LIVENESS (pending requests get ordered under some primary).

Every scenario is a pure function of its seed: SimNetwork randomness, fault
choice, fault timing and traffic all derive from SimRandom(seed), so any
failing seed replays exactly.
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
from plenum_tpu.common.request import Request
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.execution import txn as txn_lib
from plenum_tpu.network import Discard, Deliver, SimRandom, match_dst, match_frm
from plenum_tpu.network.sim_network import match_type

from test_pool import Pool, signed_nym

FAST = dict(Max3PCBatchWait=0.05,
            PRIMARY_HEALTH_CHECK_FREQ=0.5,
            ORDERING_PROGRESS_TIMEOUT=2.0,
            STATE_FRESHNESS_UPDATE_INTERVAL=3.0,
            VIEW_CHANGE_TIMEOUT=8.0,
            NEW_VIEW_TIMEOUT=4.0)

N_SEEDS = 100

# --- flight-recorder failure artifacts --------------------------------------
# Every scenario tracks its pool here; a failing rung dumps ALL nodes'
# flight-recorder rings (span events + anomalies: the pool's last-seconds
# story) to a temp dir and names it in the assertion, so a fuzz failure
# arrives debuggable instead of as a bare seed number.
_SCENARIO_POOLS: list = []


def _track(pool):
    _SCENARIO_POOLS.clear()
    _SCENARIO_POOLS.append(pool)
    return pool


def _dump_flight_artifacts(label: str):
    import os
    import tempfile
    if not _SCENARIO_POOLS:
        return None
    pool = _SCENARIO_POOLS[0]
    out = tempfile.mkdtemp(prefix=f"plenum_flight_{label}_")
    dumped = 0
    for name, node in sorted(pool.nodes.items()):
        tracer = getattr(node, "tracer", None)
        if tracer is not None and getattr(tracer, "enabled", False):
            tracer.dump(os.path.join(out, f"{name}-flight.json"))
            dumped += 1
    return out if dumped else None


def _run_with_artifacts(scenario, seed: int) -> None:
    try:
        scenario(seed)
    except AssertionError as e:
        artifacts = _dump_flight_artifacts(f"seed{seed}")
        if artifacts is not None:
            raise AssertionError(
                f"{e} [flight-recorder rings of all nodes: "
                f"{artifacts}]") from e
        raise
    except BaseException:
        # crash bugs (and Ctrl-C) still get their artifacts, but the
        # original exception TYPE re-raises untouched — wrapping a
        # KeyboardInterrupt as AssertionError would turn an abort into a
        # recorded failure and keep the sweep running
        import sys
        artifacts = _dump_flight_artifacts(f"seed{seed}")
        if artifacts is not None:
            print(f"[flight-recorder rings of all nodes: {artifacts}]",
                  file=sys.stderr)
        raise
    finally:
        _SCENARIO_POOLS.clear()


def _domain_txns(node) -> list[str]:
    ledger = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
    return [txn_lib.txn_digest(ledger.get_by_seq_no(i)) or str(i)
            for i in range(1, ledger.size + 1)]


def assert_safety(pool) -> None:
    """No fork: every pair of domain ledgers agrees on their common prefix."""
    chains = {n: _domain_txns(node) for n, node in pool.nodes.items()}
    names = list(chains)
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            common = min(len(chains[a]), len(chains[b]))
            assert chains[a][:common] == chains[b][:common], \
                f"FORK between {a} and {b}: {chains[a]} vs {chains[b]}"


def run_scenario(seed: int) -> None:
    rng = SimRandom(seed * 7919 + 17)
    # draw the scenario FIRST: scenario 3 needs a durable pool (crash-
    # recovery with stable storage), the rest an in-memory one — building
    # both would double every seed's setup cost
    scenario = rng.integer(0, 5)
    durable = None
    if scenario == 3:
        import tempfile
        durable = tempfile.mkdtemp(prefix="plenum_fuzz_s3_")
        pool = _track(Pool(seed=seed,
                           config=Config(**FAST, kv_backend="native"),
                           data_dir=durable))
    else:
        pool = _track(Pool(seed=seed, config=Config(**FAST)))
    primary = pool.nodes["Alpha"].master_replica.data.primary_name

    users = [Ed25519Signer(seed=(b"fuzz%d-%d" % (seed, i)).ljust(32, b"\0")[:32])
             for i in range(3)]
    reqs = [signed_nym(pool.trustee, u, i + 1) for i, u in enumerate(users)]

    if scenario == 0:
        # primary blackout at a random moment while traffic flows
        pool.submit(reqs[0])
        pool.run(rng.float(0.0, 1.5))
        rules = [pool.net.add_rule(Discard(), match_dst(primary)),
                 pool.net.add_rule(Discard(), match_frm(primary))]
        pool.submit(reqs[1], to=[n for n in pool.names if n != primary])
        pool.run(25.0)
        survivors = [n for n in pool.names if n != primary]
        for n in survivors:
            assert pool.nodes[n].master_replica.view_no >= 1, \
                f"seed {seed}: {n} stuck in view 0"
            assert len(_domain_txns(pool.nodes[n])) >= 3, \
                f"seed {seed}: {n} lost requests across the view change"
    elif scenario == 1:
        # lossy network: drop a random slice of consensus traffic for a
        # while, then heal; MessageReq/catchup must recover — a view change
        # may or may not happen, both are legal
        p_drop = rng.float(0.1, 0.4)
        victim = pool.names[rng.integer(0, 3)]
        rule = pool.net.add_rule(Discard(probability=p_drop),
                                 match_dst(victim))
        pool.submit(reqs[0])
        pool.run(rng.float(2.0, 5.0))
        pool.net.remove_rule(rule)
        pool.submit(reqs[1])
        pool.run(20.0)
        sizes = {len(_domain_txns(pool.nodes[n])) for n in pool.names
                 if n != victim}
        assert sizes == {3}, f"seed {seed}: healed pool did not order: {sizes}"
    elif scenario == 2:
        # slow new-primary: the view change itself runs under heavy random
        # delay on the next primary's traffic (concurrent VC pressure — the
        # first VC can time out and escalate to view+2; any view >= 1 with
        # all traffic ordered is a pass)
        next_primary = pool.nodes["Alpha"].replicas.master.data.validators[1]
        pool.net.add_rule(Deliver(rng.float(0.5, 1.0), rng.float(1.5, 4.0)),
                          match_frm(next_primary))
        rules = [pool.net.add_rule(Discard(), match_dst(primary)),
                 pool.net.add_rule(Discard(), match_frm(primary))]
        pool.submit(reqs[0], to=[n for n in pool.names if n != primary])
        pool.run(40.0)
        survivors = [n for n in pool.names if n != primary]
        views = {pool.nodes[n].master_replica.view_no for n in survivors}
        assert all(v >= 1 for v in views), f"seed {seed}: views {views}"
        for n in survivors:
            assert len(_domain_txns(pool.nodes[n])) >= 2, \
                f"seed {seed}: {n} did not order after delayed VC"
    elif scenario == 3:
        # quorum loss then heal: TWO nodes crash at a random moment (the
        # survivors drop below weak-quorum connectivity -> the
        # NetworkInconsistencyWatcher fires and marks a resync); the
        # crashed pair returns FROM ITS DURABLE STATE (crash-recovery
        # with stable storage — restarting 2 of 4 from genesis would be
        # amnesia x2 > f, outside the BFT fault model, and genuinely
        # forks the audit ledger), catches up, and the survivors must
        # ALSO resync — then everyone orders new traffic.
        import shutil
        pool.submit(reqs[0])
        pool.run(rng.float(1.0, 4.0))
        dead = [n for n in pool.names if n != primary][:2] \
            if rng.integer(0, 2) else [primary,
                                       [n for n in pool.names
                                        if n != primary][0]]
        for n in dead:
            pool.crash_node(n)
        pool.run(rng.float(0.5, 2.0))
        for n in pool.names:
            if n not in dead:
                assert pool.nodes[n]._needs_resync, \
                    f"seed {seed}: {n} never noticed losing quorum"
        for n in dead:
            pool.start_node(n)
        pool.net.connect_all()
        for n in dead:
            pool.nodes[n].start_catchup()
        pool.run(20.0)
        pool.submit(reqs[1])
        pool.run(20.0)
        try:
            sizes = {len(_domain_txns(node))
                     for node in pool.nodes.values()}
            assert sizes == {3}, f"seed {seed}: healed pool diverged: {sizes}"
            for n in pool.names:
                if n not in dead:
                    assert not pool.nodes[n]._needs_resync, \
                        f"seed {seed}: {n} still marked inconsistent"
        finally:
            shutil.rmtree(durable, ignore_errors=True)
            import gc
            gc.collect()    # crash_node leaks handles by design (real
            #                 crashes do); a multi-thousand-seed sweep in
            #                 one interpreter needs them reaped promptly
    elif scenario == 4:
        # BYZANTINE LIES: one non-primary node's outbound 3PC messages are
        # randomly mutated in flight (type-preserving field corruption —
        # digests, seq/view numbers, roots — exactly what a malicious
        # peer's process could emit). f=1 tolerates one liar: SAFETY must
        # hold unconditionally and the pool must keep ordering.
        from plenum_tpu.common.node_messages import (Commit, PrePrepare,
                                                     Prepare)
        from plenum_tpu.network import Mutate
        import dataclasses
        liar = [n for n in pool.names if n != primary][rng.integer(0, 2)]

        def corrupt(msg, rng=rng):
            kind = rng.integer(0, 3)
            try:
                if kind == 0 and hasattr(msg, "digest") and msg.digest:
                    return dataclasses.replace(
                        msg, digest="f" * len(msg.digest))
                if kind == 1 and hasattr(msg, "pp_seq_no"):
                    return dataclasses.replace(
                        msg, pp_seq_no=msg.pp_seq_no + rng.integer(1, 3))
                if kind == 2 and hasattr(msg, "state_root") and \
                        getattr(msg, "state_root", ""):
                    return dataclasses.replace(msg, state_root="0" * 64)
                if hasattr(msg, "view_no"):
                    return dataclasses.replace(
                        msg, view_no=msg.view_no + rng.integer(1, 2))
            except Exception:
                return None     # unmutable shape: drop it (also byzantine)
            return msg

        pool.net.add_rule(Mutate(corrupt, probability=rng.float(0.3, 0.9)),
                          match_frm(liar),
                          match_type((PrePrepare, Prepare, Commit)))
        pool.submit(reqs[0])
        pool.run(10.0)
        pool.submit(reqs[1])
        pool.run(20.0)
        honest = [n for n in pool.names if n != liar]
        sizes = {len(_domain_txns(pool.nodes[n])) for n in honest}
        assert sizes == {3}, \
            f"seed {seed}: honest nodes failed to order under lies: {sizes}"
    else:
        # lagging node crawls through the whole view change (multi-second
        # random delays both ways — it cannot block the VC quorum, only
        # trail it), then heals and must converge into the new view.
        # NOTE a third cut-off node would break the n-f=3 quorum at n=4;
        # lag, not partition, is the strongest fault that keeps VC live.
        # lag must stay under NEW_VIEW_TIMEOUT: with only 3 live votes at
        # n=4, a laggard slower than the VC timers means NO view can ever
        # stabilize (cascading view changes) — correct BFT behavior, but
        # then there is no liveness to assert until the network heals
        laggard = [n for n in pool.names if n != primary][rng.integer(0, 2)]
        lag_rules = [
            pool.net.add_rule(Deliver(1.0, rng.float(1.5, 3.0)),
                              match_dst(laggard)),
            pool.net.add_rule(Deliver(1.0, rng.float(1.5, 3.0)),
                              match_frm(laggard))]
        pool.net.add_rule(Discard(), match_dst(primary))
        pool.net.add_rule(Discard(), match_frm(primary))
        active = [n for n in pool.names if n not in (primary, laggard)]
        pool.submit(reqs[0], to=active)
        pool.run(30.0)
        for rule in lag_rules:
            pool.net.remove_rule(rule)
        pool.run(15.0)
        node = pool.nodes[laggard]
        if node.master_replica.view_no == 0 or \
                len(_domain_txns(node)) < 2:
            node.start_catchup()          # trailing node syncs explicitly
            pool.run(15.0)
        assert node.master_replica.view_no >= 1, \
            f"seed {seed}: laggard never adopted the new view"
        assert len(_domain_txns(node)) >= 2, \
            f"seed {seed}: laggard did not catch up the VC-era txns"
    assert_safety(pool)


# --- scenario kind `device_flap`: the crypto plane is the fault -------------
# A seed-driven relay wedge/drop/corrupt hits the pool's SHARED device
# verifier mid-consensus. The plane supervisor must degrade every node to
# hedged CPU verdicts (no request stalls past its per-batch deadline
# budget — measured from the supervisor's stall accounting, not asserted
# by sleeping), keep ordering throughout, and after the seeded heal the
# breaker must re-warm + re-admit the device with ordering latency back
# at the pre-fault level. Runs as its OWN seed sweep rather than widening
# run_scenario's rng.integer(0, 5) draw, which would silently remap every
# historical seed of the six existing kinds.


def _order_and_time(pool, req, expect_size: float, timeout: float = 25.0):
    """Submit and run until every node's domain ledger reaches
    expect_size; -> sim seconds it took, or None on timeout."""
    t0 = pool.timer.get_current_time()
    pool.submit(req)
    elapsed = 0.0
    while elapsed < timeout:
        pool.run(0.5)
        elapsed += 0.5
        if all(len(_domain_txns(pool.nodes[n])) >= expect_size
               for n in pool.names):
            return pool.timer.get_current_time() - t0
    return None


def run_device_flap_scenario(seed: int) -> None:
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.supervisor import (CLOSED, CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    rng = SimRandom(seed * 104729 + 71)
    faulty = FaultyVerifier(CpuEd25519Verifier())
    sup = SupervisedVerifier(
        faulty, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=2,
                               cooldown=rng.float(0.5, 1.5)),
        budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                              warm_max=1.0, cold_max=1.0))
    pool = _track(Pool(seed=seed, config=Config(**FAST), verifier=sup))
    # the supervisor's whole state machine runs on SIM time: any failing
    # seed replays exactly
    sup.set_clock(pool.timer.get_current_time)
    faulty.set_clock(pool.timer.get_current_time)

    users = [Ed25519Signer(seed=(b"flap%d-%d" % (seed, i))
                           .ljust(32, b"\0")[:32]) for i in range(4)]
    reqs = [signed_nym(pool.trustee, u, i + 1) for i, u in enumerate(users)]

    # pre-fault: device-backed ordering, timed
    pre = _order_and_time(pool, reqs[0], 2)
    assert pre is not None, f"seed {seed}: healthy pool failed to order"
    assert sup.stats["device_batches"] >= 1, "traffic never hit the device"

    # fault the plane MID-consensus: request in flight, then the relay
    # wedges (replies lost) / drops (refuses) / corrupts (dies mid-read)
    kind = ("wedge", "drop", "corrupt")[rng.integer(0, 2)]
    pool.submit(reqs[1])
    pool.run(rng.float(0.0, 0.3))
    getattr(faulty, kind)()
    during = _order_and_time(pool, reqs[2], 4)
    assert during is not None, \
        f"seed {seed}: pool stopped ordering under device {kind}"
    st = sup.supervisor_stats()
    assert st["fallback_batches"] >= 1, \
        f"seed {seed}: no CPU fallback recorded under {kind}"
    # MEASURED stall bound: no dispatch waited past its deadline budget
    # (+2 prod ticks of poll granularity)
    assert st["max_stall_s"] <= st["max_budget_s"] + 0.3, \
        f"seed {seed}: stall {st['max_stall_s']:.2f}s past budget " \
        f"{st['max_budget_s']:.2f}s"

    # heal: traffic drives the cooldown -> probe -> re-warm -> re-admit
    faulty.heal()
    waited = 0.0
    while sup.breaker.state != CLOSED and waited < 30.0:
        pool.run(1.0)
        waited += 1.0
        # probes only advance on plane calls; idle pools still heal
        # because periodic node traffic (freshness checks) may be sparse,
        # so nudge with a tiny verify
        sup.verify_batch([(b"heal-nudge-%d-%f" % (seed, waited),
                           b"\0" * 64, b"\0" * 32)])
    assert sup.breaker.state == CLOSED, \
        f"seed {seed}: breaker never re-closed after heal ({kind})"
    assert st["verdict_forks"] == 0 and \
        sup.stats["verdict_forks"] == 0, "hedge forked backend verdicts"
    assert faulty.rewarms >= 1, "re-admission skipped the re-warm"

    # recovery: post-heal ordering latency back at the pre-fault level
    post = _order_and_time(pool, reqs[3], 5)
    assert post is not None, f"seed {seed}: pool dead after heal"
    assert post <= pre + 1.5, \
        f"seed {seed}: post-heal ordering {post:.1f}s vs pre {pre:.1f}s"
    tok = sup.submit_batch([(b"readmit-%d" % seed, b"\0" * 64, b"\0" * 32)])
    assert tok.kind == "dev", "device not re-admitted after close"
    sup.collect_batch(tok)
    assert_safety(pool)


def run_device_flap_with_pipeline(seed: int) -> None:
    """device_flap with the FUSED CRYPTO PIPELINE enabled: the pool's
    client-auth, BLS batch checks, and Merkle hashing all ride one shared
    ring (parallel/pipeline.py) whose ed25519 waves dispatch through the
    supervised faulty device. The fault must compose exactly as without
    the pipeline: breaker opens -> hedged CPU fallback keeps ordering ->
    re-warm re-admits the device and fresh waves hit it again — and the
    pool's verdicts/ledgers stay identical-safe throughout."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.pipeline import CryptoPipeline
    from plenum_tpu.parallel.supervisor import (CLOSED, CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    rng = SimRandom(seed * 92821 + 37)
    faulty = FaultyVerifier(CpuEd25519Verifier())
    sup = SupervisedVerifier(
        faulty, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=2,
                               cooldown=rng.float(0.5, 1.5)),
        budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                              warm_max=1.0, cold_max=1.0))
    pipeline = CryptoPipeline(ed_inner=sup, config=Config(**FAST))
    pool = _track(Pool(seed=seed, config=Config(**FAST),
                       pipeline=pipeline))
    # node construction re-pins the pipeline clock to the pool timer; the
    # fault plane needs the same sim clock so failing seeds replay
    sup.set_clock(pool.timer.get_current_time)
    faulty.set_clock(pool.timer.get_current_time)

    users = [Ed25519Signer(seed=(b"pflap%d-%d" % (seed, i))
                           .ljust(32, b"\0")[:32]) for i in range(4)]
    reqs = [signed_nym(pool.trustee, u, i + 1) for i, u in enumerate(users)]

    pre = _order_and_time(pool, reqs[0], 2)
    assert pre is not None, f"seed {seed}: healthy pipelined pool stalled"
    assert pipeline.stats["dispatches"] >= 1, "no wave ever dispatched"
    assert sup.stats["device_batches"] >= 1, \
        "waves bypassed the supervised device"

    kind = ("wedge", "drop", "corrupt")[rng.integer(0, 2)]
    pool.submit(reqs[1])
    pool.run(rng.float(0.0, 0.3))
    getattr(faulty, kind)()
    # the ring coalesces so aggressively that pool traffic alone may not
    # produce fail_threshold device waves quickly — drive fresh waves
    # through the ring until the breaker trips (bounded)
    nudges = 0
    while sup.breaker.state == CLOSED and nudges < 20:
        nudges += 1
        pool.run(0.2)
        pipeline.verifier().verify_batch(
            [(b"pipe-fault-%d-%d" % (seed, nudges), b"\0" * 64,
              b"\0" * 32)])
    assert sup.breaker.state != CLOSED, \
        f"seed {seed}: breaker never opened under {kind} with pipeline"
    during = _order_and_time(pool, reqs[2], 4)
    assert during is not None, \
        f"seed {seed}: pipelined pool stopped ordering under {kind}"
    st = sup.supervisor_stats()
    assert st["fallback_batches"] >= 1, \
        f"seed {seed}: no CPU fallback under {kind} with pipeline"
    assert st["max_stall_s"] <= st["max_budget_s"] + 0.3, \
        f"seed {seed}: stall {st['max_stall_s']:.2f}s past budget"

    faulty.heal()
    waited = 0.0
    while sup.breaker.state != CLOSED and waited < 30.0:
        pool.run(1.0)
        waited += 1.0
        # nudge THROUGH the ring: probes advance on plane calls
        pipeline.verifier().verify_batch(
            [(b"pipe-heal-%d-%f" % (seed, waited), b"\0" * 64,
              b"\0" * 32)])
    assert sup.breaker.state == CLOSED, \
        f"seed {seed}: breaker never re-closed after heal ({kind})"
    assert sup.stats["verdict_forks"] == 0, "hedge forked verdicts"
    assert faulty.rewarms >= 1, "re-admission skipped the re-warm"

    # re-admission THROUGH the pipeline: a fresh wave must hit the device
    dev_before = sup.stats["device_batches"]
    pipeline.verifier().verify_batch(
        [(b"pipe-readmit-%d" % seed, b"\0" * 64, b"\0" * 32)])
    assert sup.stats["device_batches"] > dev_before, \
        "post-heal wave did not reach the re-admitted device"
    post = _order_and_time(pool, reqs[3], 5)
    assert post is not None, f"seed {seed}: pipelined pool dead after heal"
    assert post <= pre + 1.5, \
        f"seed {seed}: post-heal ordering {post:.1f}s vs pre {pre:.1f}s"
    assert_safety(pool)


def run_device_flap_multidevice(seed: int) -> None:
    """device_flap with a PER-DEVICE fault target: the pool's crypto
    pipeline is sharded into 4 chip lanes (one supervised verifier +
    breaker each), and the seed-derived FaultPlan names ONE device index
    — every lane carries the same plan, but only the lane whose
    `device_index` matches reads the fault windows. Mid-consensus the
    targeted chip wedges; EXACTLY that lane's breaker may open (no
    ring-wide breaker), every other lane's dispatch count keeps
    advancing, aggregate ordering continues, and after the window ends
    the lane re-warms and rejoins (fresh pinned waves hit its device
    again)."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultPlan, FaultyVerifier
    from plenum_tpu.parallel.pipeline import MultiDeviceCryptoPipeline
    from plenum_tpu.parallel.supervisor import (CLOSED, CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    rng = SimRandom(seed * 48271 + 11)
    n_lanes = 4
    # ONE plan, device-targeted by the seed; a fixed window keeps the
    # scenario's phases (healthy / faulted / healed) deterministic while
    # the targeted chip and fault mode stay seed-driven
    kind = ("wedge", "drop", "corrupt")[rng.integer(0, 2)]
    plan = FaultPlan.from_seed(seed, n_devices=n_lanes, n_faults=0)
    target = plan.device
    assert target is not None and 0 <= target < n_lanes
    # the window opens mid-consensus below (windows set then; an open
    # end means the fault holds until the explicit heal)

    faulties, sups = [], []
    for k in range(n_lanes):
        faulty = FaultyVerifier(CpuEd25519Verifier(), plan=plan,
                                device_index=k)
        sup = SupervisedVerifier(
            faulty, fallback=CpuEd25519Verifier(),
            breaker=CircuitBreaker(fail_threshold=2,
                                   cooldown=rng.float(0.5, 1.5)),
            budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                                  warm_max=1.0, cold_max=1.0),
            label=f"lane{k}")
        faulties.append(faulty)
        sups.append(sup)
    pipeline = MultiDeviceCryptoPipeline(
        ed_inners=sups, config=Config(**FAST), threaded=False)
    pool = _track(Pool(seed=seed, config=Config(**FAST),
                       pipeline=pipeline))
    for obj in (*sups, *faulties):
        obj.set_clock(pool.timer.get_current_time)

    users = [Ed25519Signer(seed=(b"mdflap%d-%d" % (seed, i))
                           .ljust(32, b"\0")[:32]) for i in range(4)]
    reqs = [signed_nym(pool.trustee, u, i + 1) for i, u in enumerate(users)]

    def junk(tag: bytes, n: int = 3):
        return [(b"%s-%d-%d" % (tag, seed, i), b"\x01" * 63 + b"\x00",
                 bytes([i + 1]) * 32) for i in range(n)]

    # pre-fault: every lane dispatches
    pre = _order_and_time(pool, reqs[0], 2)
    assert pre is not None, f"seed {seed}: healthy multi-lane pool stalled"
    for k in range(n_lanes):
        pipeline.verifier(lane=k).verify_batch(junk(b"pre%d" % k))
    disp_pre = [l.stats["dispatches"] for l in pipeline.lanes]
    assert all(d >= 1 for d in disp_pre), \
        f"seed {seed}: lane never dispatched pre-fault: {disp_pre}"
    assert all(s.breaker.state == CLOSED for s in sups)

    # open the fault window MID-consensus: a request is in flight when
    # the targeted chip starts failing (every lane carries this plan;
    # only device_index == target reads the window)
    pool.submit(reqs[1])
    pool.run(rng.float(0.0, 0.3))
    plan.windows = [(pool.timer.get_current_time(), 1e9, kind)]
    pool.run(0.2)
    # pinned traffic drives the targeted lane until ITS breaker opens
    nudges = 0
    while sups[target].breaker.state == CLOSED and nudges < 30:
        nudges += 1
        pool.run(0.2)
        pipeline.verifier(lane=target).verify_batch(
            junk(b"fault%d" % nudges))
    assert sups[target].breaker.state != CLOSED, \
        f"seed {seed}: targeted lane {target} breaker never opened " \
        f"under {kind}"
    # EXACTLY one lane degrades: no ring-wide breaker open
    others = [k for k in range(n_lanes) if k != target]
    for k in others:
        assert sups[k].breaker.state == CLOSED, \
            f"seed {seed}: lane {k} breaker opened for lane " \
            f"{target}'s fault ({kind})"
    # other lanes' dispatch counts keep advancing while lane k is down
    before = [pipeline.lanes[k].stats["dispatches"] for k in others]
    for k in others:
        pipeline.verifier(lane=k).verify_batch(junk(b"during%d" % k))
    after = [pipeline.lanes[k].stats["dispatches"] for k in others]
    assert all(b > a for a, b in zip(before, after)), \
        f"seed {seed}: healthy lanes stopped dispatching: " \
        f"{before} -> {after}"
    for k in others:
        assert sups[k].stats["device_batches"] >= 1

    # aggregate ordering continues above the single-lane floor: the
    # pool keeps ordering within the healthy-ordering deadline even
    # with one chip dark (its pinned waves ride host fallback)
    during = _order_and_time(pool, reqs[2], 4)
    assert during is not None, \
        f"seed {seed}: pool stopped ordering with lane {target} dark"
    st = sups[target].supervisor_stats()
    assert st["fallback_batches"] >= 1, \
        f"seed {seed}: no host fallback on the dark lane"
    assert st["max_stall_s"] <= st["max_budget_s"] + 0.3

    # heal: the targeted verifier recovers, traffic drives the probe ->
    # re-warm -> re-admission of that ONE lane
    faulties[target].heal()
    waited = 0.0
    while sups[target].breaker.state != CLOSED and waited < 30.0:
        pool.run(1.0)
        waited += 1.0
        pipeline.verifier(lane=target).verify_batch(
            junk(b"heal%f" % waited))
    assert sups[target].breaker.state == CLOSED, \
        f"seed {seed}: lane {target} never re-closed after heal ({kind})"
    assert faulties[target].rewarms >= 1, \
        "lane re-admission skipped the re-warm"
    assert all(s.stats["verdict_forks"] == 0 for s in sups)

    # the healed lane REJOINS: a fresh pinned wave hits its device
    dev_before = sups[target].stats["device_batches"]
    pipeline.verifier(lane=target).verify_batch(junk(b"rejoin"))
    assert sups[target].stats["device_batches"] > dev_before, \
        f"seed {seed}: healed lane {target} never re-admitted traffic"
    post = _order_and_time(pool, reqs[3], 5)
    assert post is not None, f"seed {seed}: pool dead after lane heal"
    assert_safety(pool)


def _move_to_lane(pipeline, tok, lane) -> None:
    """Re-stage an unhinted token onto a specific lane (scenario
    plumbing: the federated ring only routes unhinted work to a remote
    by occupancy, which a quiet sim pool rarely exercises)."""
    src = next(l for l in pipeline.lanes if tok in l.staged)
    if src is lane:
        return
    src.staged.remove(tok)
    if not src.staged:
        src.first_staged = None
    if not lane.staged:
        lane.first_staged = pipeline._now()
    lane.staged.append(tok)


def run_crypto_host_down_scenario(seed: int) -> None:
    """crypto_host_down: a rostered REMOTE crypto host dies/wedges
    mid-consensus under the federated pipeline (parallel/federation.py).
    The pool's ring runs 2 local chip lanes plus one remote-host lane
    (in-proc stand-in for the service client: the same supervised
    submit/collect + breaker + re-warm surface, on the sim clock so
    failing seeds replay). The seeded fault window targets ONLY the
    remote: exactly its breaker opens, its queued waves steal BACK to
    the local lanes (and are never double-verified), ordering never
    stalls past the deadline budget, and after the heal the host
    re-warms and REJOINS — fresh waves hit it again."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultPlan, FaultyVerifier
    from plenum_tpu.parallel.federation import FederatedCryptoPipeline
    from plenum_tpu.parallel.supervisor import (CLOSED, CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    rng = SimRandom(seed * 62233 + 29)
    n_local = 2
    remote_idx = n_local
    kind = ("wedge", "drop", "corrupt")[rng.integer(0, 2)]
    plan = FaultPlan.from_seed(seed, n_devices=n_local + 1, n_faults=0)
    # the victim IS the scenario kind: force the plan onto the remote
    # host's lane (seed still drives fault mode, timings, cooldowns)
    plan.device = remote_idx

    faulties, sups = [], []
    for k in range(n_local + 1):
        faulty = FaultyVerifier(CpuEd25519Verifier(), plan=plan,
                                device_index=k)
        sup = SupervisedVerifier(
            faulty, fallback=CpuEd25519Verifier(),
            breaker=CircuitBreaker(fail_threshold=2,
                                   cooldown=rng.float(0.5, 1.5)),
            budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                                  warm_max=1.0, cold_max=1.0),
            label=f"lane{k}" if k < n_local else "remote0")
        faulties.append(faulty)
        sups.append(sup)
    pipeline = FederatedCryptoPipeline(
        ed_inners=sups[:n_local], remote_inners=[sups[remote_idx]],
        hosts=["sim://crypto-host-0"],
        config=Config(**FAST, PIPELINE_STEAL_THRESHOLD=4,
                      PIPELINE_STEAL_COOLDOWN=0.1),
        threaded=False)
    remote_lane = pipeline.lanes[remote_idx]
    pool = _track(Pool(seed=seed, config=Config(**FAST),
                       pipeline=pipeline))
    for obj in (*sups, *faulties):
        obj.set_clock(pool.timer.get_current_time)

    users = [Ed25519Signer(seed=(b"hdown%d-%d" % (seed, i))
                           .ljust(32, b"\0")[:32]) for i in range(4)]
    reqs = [signed_nym(pool.trustee, u, i + 1) for i, u in enumerate(users)]

    def junk(tag: bytes, n: int = 3):
        return [(b"%s-%d-%d" % (tag, seed, i), b"\x01" * 63 + b"\x00",
                 bytes([i + 1]) * 32) for i in range(n)]

    # pre-fault: ordering healthy, every lane (including the rented
    # remote) carries at least one wave
    pre = _order_and_time(pool, reqs[0], 2)
    assert pre is not None, f"seed {seed}: healthy federated pool stalled"
    for k in range(n_local):
        pipeline.verifier(lane=k).verify_batch(junk(b"pre%d" % k))
    rtok = pipeline.submit_verify(junk(b"pre-remote"))
    rtok.lane_hint = None
    _move_to_lane(pipeline, rtok, remote_lane)
    assert pipeline.collect_verify(rtok, wait=True) is not None
    assert remote_lane.stats["dispatches"] >= 1, \
        f"seed {seed}: the remote lane never carried a wave pre-fault"
    assert all(s.breaker.state == CLOSED for s in sups)

    # the host dies MID-consensus: a request is in flight when the
    # remote's fault window opens (local lanes carry the same plan but
    # only device_index == remote reads it)
    pool.submit(reqs[1])
    pool.run(rng.float(0.0, 0.3))
    plan.windows = [(pool.timer.get_current_time(), 1e9, kind)]
    pool.run(0.2)
    nudges = 0
    while sups[remote_idx].breaker.state == CLOSED and nudges < 30:
        nudges += 1
        pool.run(0.2)
        sups[remote_idx].verify_batch(junk(b"fault%d" % nudges))
    assert sups[remote_idx].breaker.state != CLOSED, \
        f"seed {seed}: remote host breaker never opened under {kind}"
    # ONLY the remote lane degrades
    for k in range(n_local):
        assert sups[k].breaker.state == CLOSED, \
            f"seed {seed}: local lane {k} breaker opened for the " \
            f"remote host's {kind}"

    # steal-back: waves queued on the dead host's lane evacuate to the
    # LOCAL lanes (unconditionally — no threshold, no cooldown) and
    # settle there exactly once
    stok = pipeline.submit_verify(junk(b"stranded", n=4))
    stok.lane_hint = None
    _move_to_lane(pipeline, stok, remote_lane)
    steals_before = pipeline.stats["steals"]
    items_before = pipeline.stats["dispatched_items"]
    pipeline.service()
    assert pipeline.stats["steals"] > steals_before, \
        f"seed {seed}: dead host's queue never stole back"
    assert pipeline._lane_backlog(remote_lane) == 0, \
        f"seed {seed}: the open lane kept queued waves"
    out = pipeline.collect_verify(stok, wait=True)
    assert out is not None and len(out) == 4
    assert pipeline.stats["dispatched_items"] - items_before == 4, \
        f"seed {seed}: a stolen wave was double-verified"

    # local lanes keep dispatching; aggregate ordering continues within
    # the deadline budget while the host is dark
    before = [pipeline.lanes[k].stats["dispatches"]
              for k in range(n_local)]
    for k in range(n_local):
        pipeline.verifier(lane=k).verify_batch(junk(b"during%d" % k))
    after = [pipeline.lanes[k].stats["dispatches"] for k in range(n_local)]
    assert all(b > a for a, b in zip(before, after)), \
        f"seed {seed}: local lanes stalled: {before} -> {after}"
    during = _order_and_time(pool, reqs[2], 4)
    assert during is not None, \
        f"seed {seed}: pool stopped ordering with the host down"
    st = sups[remote_idx].supervisor_stats()
    assert st["fallback_batches"] >= 1, \
        f"seed {seed}: no fallback recorded on the dead host's lane"
    assert st["max_stall_s"] <= st["max_budget_s"] + 0.3, \
        f"seed {seed}: stall {st['max_stall_s']:.2f}s past budget " \
        f"{st['max_budget_s']:.2f}s"
    assert pipeline.federation_state()["remote_breakers_open"] == 1

    # heal: the host returns, the probe re-warms (for a real service
    # client this is the reconnect), the breaker re-closes
    faulties[remote_idx].heal()
    waited = 0.0
    while sups[remote_idx].breaker.state != CLOSED and waited < 30.0:
        pool.run(1.0)
        waited += 1.0
        sups[remote_idx].verify_batch(junk(b"heal%f" % waited))
    assert sups[remote_idx].breaker.state == CLOSED, \
        f"seed {seed}: host breaker never re-closed after heal ({kind})"
    assert faulties[remote_idx].rewarms >= 1, \
        "host re-admission skipped the re-warm"
    assert all(s.stats["verdict_forks"] == 0 for s in sups)

    # rejoin: a fresh wave through the ring reaches the host again
    dev_before = sups[remote_idx].stats["device_batches"]
    jtok = pipeline.submit_verify(junk(b"rejoin"))
    jtok.lane_hint = None
    _move_to_lane(pipeline, jtok, remote_lane)
    assert pipeline.collect_verify(jtok, wait=True) is not None
    assert sups[remote_idx].stats["device_batches"] > dev_before, \
        f"seed {seed}: healed host never re-admitted ring traffic"
    assert pipeline.federation_state()["remote_breakers_open"] == 0
    post = _order_and_time(pool, reqs[3], 5)
    assert post is not None, f"seed {seed}: pool dead after host heal"
    assert_safety(pool)


def run_device_flap_with_commit_wave(seed: int) -> None:
    """device_flap with the fault aimed at the COMMIT-WAVE lane: the
    pool's triple-root recommit (verkle state + ledger + audit) rides a
    wedgeable device MSM engine behind the shared ring's cmt lane.
    Mid-run the engine wedges; the wave degrades exactly that traffic to
    host recommit (breaker-style, inside `_cmt_dispatch`) so roots keep
    advancing and ordering continues, the ed lane stays isolated (its
    waves keep dispatching — a cmt wedge is never ring-wide), and after
    the heal fresh cmt waves hit the engine again."""
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    from plenum_tpu.parallel.pipeline import CryptoPipeline
    from plenum_tpu.state.commitment import kzg

    class WedgeableCmtEngine:
        """Answers like the host KZG engine until wedged, then raises —
        the device-MSM failure mode `_cmt_dispatch` must absorb."""

        def __init__(self):
            self.wedged = False
            self.waves = 0

        def run_jobs(self, jobs):
            if self.wedged:
                raise RuntimeError("cmt device wedged")
            self.waves += 1
            out = []
            for job in jobs:
                if job[0] == "commit":
                    out.append(kzg.engine_for(job[1]).commit(dict(job[2])))
                elif job[0] == "multiproof":
                    out.append(kzg.prove_multi(list(job[1])))
                else:
                    out.append(None)
            return out

    rng = SimRandom(seed * 75503 + 29)
    eng = WedgeableCmtEngine()
    cfg = dict(FAST, STATE_COMMITMENT="verkle")
    pipeline = CryptoPipeline(cmt_inner=eng, config=Config(**cfg))
    pool = _track(Pool(seed=seed, config=Config(**cfg),
                       pipeline=pipeline))
    users = [Ed25519Signer(seed=(b"cwflap%d-%d" % (seed, i))
                           .ljust(32, b"\0")[:32]) for i in range(4)]
    reqs = [signed_nym(pool.trustee, u, i + 1) for i, u in enumerate(users)]

    # pre-fault: the fused ordered path engages and rides the engine
    pre = _order_and_time(pool, reqs[0], 2)
    assert pre is not None, f"seed {seed}: healthy commit-wave pool stalled"
    assert pipeline.stats["cmt_waves"] >= 1, \
        f"seed {seed}: ordered batches never built a commit wave"
    assert eng.waves >= 1, \
        f"seed {seed}: recommit jobs never reached the cmt engine"
    node = pool.nodes[pool.names[0]]
    root_pre = node.c.db.get_state(DOMAIN_LEDGER_ID).committed_head_hash

    # wedge the engine MID-consensus: a request is in flight when every
    # subsequent cmt wave starts dying on the device
    pool.submit(reqs[1])
    pool.run(rng.float(0.0, 0.3))
    eng.wedged = True
    ed_before = pipeline.stats["dispatches"]
    during = _order_and_time(pool, reqs[2], 4)
    assert during is not None, \
        f"seed {seed}: pool stopped ordering under cmt engine wedge"
    assert pipeline.stats["cmt_host_fallbacks"] >= 1, \
        f"seed {seed}: wedged cmt wave never degraded to host recommit"
    # roots ADVANCE through the degrade: the batch lands on host-resolved
    # roots, never wedges the commit drain
    root_during = node.c.db.get_state(DOMAIN_LEDGER_ID).committed_head_hash
    assert root_during != root_pre, \
        f"seed {seed}: state root froze under cmt engine wedge"
    # lane isolation: the ed lane kept dispatching (no ring-wide failure)
    assert pipeline.stats["dispatches"] > ed_before, \
        f"seed {seed}: ed lane starved by the cmt wedge"

    # heal: fresh cmt waves must hit the engine again (re-admission is
    # per-wave — the degrade never blacklists the engine)
    eng.wedged = False
    waves_before = eng.waves
    post = _order_and_time(pool, reqs[3], 5)
    assert post is not None, f"seed {seed}: pool dead after cmt heal"
    assert eng.waves > waves_before, \
        f"seed {seed}: healed cmt engine never re-admitted waves"
    assert_safety(pool)


def run_lying_reader_scenario(seed: int) -> None:
    """A Byzantine node forges read replies; the verifying read client
    must reject every forgery kind and fail over to an honest node
    within its per-rung deadline — or, when the liar strips the proof
    entirely, escalate to the f+1 broadcast (which the diverging-reader
    vote-key fix keeps sound)."""
    import copy

    from plenum_tpu.common.node_messages import Reply
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.reads import READ_PROOF, result_digest
    from test_reads import FOREVER, LyingPlane, make_driver

    rng = SimRandom(seed * 6151 + 13)
    pool = _track(Pool(seed=seed, config=Config(**FAST)))
    user = Ed25519Signer(seed=(b"liar%d" % seed).ljust(32, b"\0")[:32])
    assert _order_and_time(pool, signed_nym(pool.trustee, user, 1), 2) \
        is not None

    def forge_value(result):
        env = result.get(READ_PROOF)
        if env and env.get("entries"):
            e = env["entries"][0]
            if e.get("value"):
                e["value"] = bytes(
                    reversed(bytes.fromhex(e["value"]))).hex()
        return result

    def forge_root(result):
        env = result.get(READ_PROOF)
        if env and env.get("root_hash"):
            env["root_hash"] = "ab" * 32
            env["result_digest"] = result_digest(result).hex()
        return result

    def mismatch_ms(result):
        env = result.get(READ_PROOF)
        if env:
            ms = env["multi_signature"]
            ms[1] = list(ms[1])[:-1]     # claim a smaller participant set
            env["result_digest"] = result_digest(result).hex()
        return result

    def tamper_data(result):
        if isinstance(result.get("data"), dict):
            result["data"] = dict(result["data"], verkey="EvilVerkey1111")
            env = result.get(READ_PROOF)
            if env:                      # smart liar: re-binds the digest
                env["result_digest"] = result_digest(result).hex()
        return result

    def strip(result):
        result.pop(READ_PROOF, None)
        return result

    kind, mutate = [("forge_value", forge_value),
                    ("forge_root", forge_root),
                    ("mismatch_ms", mismatch_ms),
                    ("tamper_data", tamper_data),
                    ("strip", strip)][rng.integer(0, 4)]
    liar = pool.names[rng.integer(0, len(pool.names) - 1)]
    node = pool.nodes[liar]
    node.read_plane = LyingPlane(node.read_plane, mutate)

    driver = make_driver(pool, client="fuzz", freshness_s=FOREVER)
    q = Request("fuzz", 50, {"type": GET_NYM, "dest": user.identifier})
    order = [liar] + [n for n in pool.names if n != liar]
    t0 = pool.timer.get_current_time()
    res = driver.read(q, per_node_s=2.0, order=order)
    took = pool.timer.get_current_time() - t0
    deadline = 2.0 * len(pool.names) + 1.0
    assert took <= deadline, \
        f"seed {seed}: {kind} read took {took:.1f}s > {deadline:.1f}s"
    s = driver.stats
    if kind == "strip":
        # no proof at all -> escalate to the legacy f+1 broadcast; the
        # content vote key keeps the liar's divergent data sub-quorum
        assert res is None and s.fallbacks == 1, f"seed {seed}"
        from plenum_tpu.client.client import PoolClient
        pool.submit(q, client="fuzz-bc")
        pool.run(2.0)
        votes: dict = {}
        for name in pool.names:
            for m, c in pool.client_msgs[name]:
                if c == "fuzz-bc" and isinstance(m, Reply):
                    key = PoolClient._vote_key(
                        {"op": "REPLY", "result": copy.deepcopy(m.result)})
                    votes[key] = votes.get(key, 0) + 1
        agreed = [k for k, v in votes.items()
                  if v >= pool.nodes[liar].f + 1]
        assert len(agreed) == 1, f"seed {seed}: votes {votes}"
    else:
        assert res is not None, f"seed {seed}: {kind} never failed over"
        assert res["data"]["verkey"] == user.verkey_b58, f"seed {seed}"
        assert s.verify_failures >= 1 and s.failovers >= 1, \
            f"seed {seed}: {kind} accepted a forged reply " \
            f"({s.summary()})"
        assert s.single_reply_ok == 1 and s.fallbacks == 0, f"seed {seed}"
    assert_safety(pool)


def run_lying_reader_verkle_scenario(seed: int) -> None:
    """The lying_reader family on a VERKLE-backed pool (STATE_COMMITMENT
    config seam): a Byzantine node forges wide-commitment read replies
    and every rung must fail CLOSED and fail over to an honest node —

    * ``forge_opening``: the aggregated opening proof (pi) is tampered;
    * ``wrong_root``: the envelope cites a commitment root the pool
      never signed;
    * ``splice_multi``: one key's value is swapped INSIDE an aggregated
      multi-key answer (the 2-key TAA chain), with the result data and
      result_digest rebound by a smart liar — only the single pairing
      check can catch it;
    * ``strip``: the proof is removed entirely -> the ladder escalates
      to the f+1 broadcast, which must still agree on honest content.
    """
    import copy

    from plenum_tpu.common.node_messages import (CONFIG_LEDGER_ID, Reply)
    from plenum_tpu.common.serialization import pack as _pack
    from plenum_tpu.execution.txn import (GET_NYM,
                                          GET_TXN_AUTHOR_AGREEMENT,
                                          TXN_AUTHOR_AGREEMENT)
    from plenum_tpu.reads import READ_PROOF, result_digest
    from test_reads import FOREVER, LyingPlane, make_driver

    rng = SimRandom(seed * 7177 + 29)
    pool = _track(Pool(seed=seed,
                       config=Config(**FAST, STATE_COMMITMENT="verkle")))
    user = Ed25519Signer(seed=(b"vliar%d" % seed).ljust(32, b"\0")[:32])
    assert _order_and_time(pool, signed_nym(pool.trustee, user, 1), 2) \
        is not None, f"seed {seed}: verkle pool failed to order"
    # a TAA gives GET_TXN_AUTHOR_AGREEMENT its 2-key deref chain — the
    # aggregated MULTI-key envelope the splice rung attacks
    taa = Request(pool.trustee.identifier, 2,
                  {"type": TXN_AUTHOR_AGREEMENT, "version": "1",
                   "text": "terms %d" % seed})
    taa.signature = pool.trustee.sign_b58(taa.signing_bytes())
    pool.submit(taa)
    config_ledger = pool.nodes[pool.names[0]].c.db.get_ledger(
        CONFIG_LEDGER_ID)
    waited = 0.0
    while config_ledger.size < 1 and waited < 20.0:
        pool.run(0.5)
        waited += 0.5
    assert config_ledger.size >= 1, f"seed {seed}: TAA never ordered"
    pool.run(1.0)                    # let the config anchor land

    def forge_opening(result):
        env = result.get(READ_PROOF)
        if env and env.get("kind") == "verkle":
            pi = bytearray(bytes.fromhex(env["proof"]["pi"]))
            pi[0] ^= 0xFF
            pi[-1] ^= 0xFF
            env["proof"]["pi"] = bytes(pi).hex()
        return result

    def wrong_root(result):
        env = result.get(READ_PROOF)
        if env and env.get("kind") == "verkle":
            env["root_hash"] = "ab" * 32
            env["result_digest"] = result_digest(result).hex()
        return result

    def splice_multi(result):
        env = result.get(READ_PROOF)
        if env and env.get("kind") == "verkle" \
                and len(env.get("entries", ())) >= 2:
            # swap the terminal key's value inside the aggregated proof;
            # rebind data + digest so key chain, consistency, and digest
            # ALL pass — only the pairing check stands
            forged = dict(result.get("data") or {}, text="EVIL TERMS")
            env["entries"][-1]["value"] = _pack(forged).hex()
            result["data"] = forged
            env["result_digest"] = result_digest(result).hex()
        return result

    def strip(result):
        result.pop(READ_PROOF, None)
        return result

    kind, mutate, query = [
        ("forge_opening", forge_opening,
         {"type": GET_NYM, "dest": user.identifier}),
        ("wrong_root", wrong_root,
         {"type": GET_NYM, "dest": user.identifier}),
        ("splice_multi", splice_multi,
         {"type": GET_TXN_AUTHOR_AGREEMENT}),
        ("strip", strip,
         {"type": GET_NYM, "dest": user.identifier}),
    ][rng.integer(0, 3)]
    liar = pool.names[rng.integer(0, len(pool.names) - 1)]
    node = pool.nodes[liar]
    node.read_plane = LyingPlane(node.read_plane, mutate)

    driver = make_driver(pool, client="vfuzz", freshness_s=FOREVER)
    q = Request("vfuzz", 50, dict(query))
    order = [liar] + [n for n in pool.names if n != liar]
    t0 = pool.timer.get_current_time()
    res = driver.read(q, per_node_s=2.0, order=order)
    took = pool.timer.get_current_time() - t0
    deadline = 2.0 * len(pool.names) + 1.0
    assert took <= deadline, \
        f"seed {seed}: {kind} read took {took:.1f}s > {deadline:.1f}s"
    s = driver.stats
    if kind == "strip":
        # no proof at all -> escalate to the legacy f+1 broadcast; the
        # content vote key keeps the liar's divergent data sub-quorum
        assert res is None and s.fallbacks == 1, f"seed {seed}"
        from plenum_tpu.client.client import PoolClient
        pool.submit(q, client="vfuzz-bc")
        pool.run(2.0)
        votes: dict = {}
        for name in pool.names:
            for m, c in pool.client_msgs[name]:
                if c == "vfuzz-bc" and isinstance(m, Reply):
                    key = PoolClient._vote_key(
                        {"op": "REPLY", "result": copy.deepcopy(m.result)})
                    votes[key] = votes.get(key, 0) + 1
        agreed = [k for k, v in votes.items()
                  if v >= pool.nodes[liar].f + 1]
        assert len(agreed) == 1, f"seed {seed}: votes {votes}"
    else:
        assert res is not None, f"seed {seed}: {kind} never failed over"
        env = res.get(READ_PROOF) or {}
        assert env.get("kind") == "verkle", \
            f"seed {seed}: honest reply not verkle ({env.get('kind')})"
        if kind == "splice_multi":
            assert len(env.get("entries", ())) >= 2, \
                f"seed {seed}: splice rung got a single-key envelope"
            assert res["data"]["text"] == "terms %d" % seed, f"seed {seed}"
        else:
            assert res["data"]["verkey"] == user.verkey_b58, f"seed {seed}"
        assert s.verify_failures >= 1 and s.failovers >= 1, \
            f"seed {seed}: {kind} accepted a forged verkle reply " \
            f"({s.summary()})"
        assert s.single_reply_ok == 1 and s.fallbacks == 0, f"seed {seed}"
    assert_safety(pool)


# --- scenario kind `client_flood`: the FRONT DOOR is under attack -----------
# Seed-driven bursts of hot clients (including bad-signature floods) hit
# per-node ingress planes while honest steady clients keep writing. The
# plane must shed the surplus EXPLICITLY (LoadShed replies, bounded
# queues), bad-signature floods must die in the batched verifier without
# ever reaching the pool, honest traffic must keep ordering within its
# SLO, and the node's raw client inbox must never wedge. Composable with
# the crypto-plane fault (device_flap's supervised verifier): a shed
# storm during CPU fallback stays bounded.


def _ingress_order_and_time(pool, ingress, req, expect_size: float,
                            timeout: float = 25.0, inbox_peaks=None):
    """Submit through EVERY node's ingress plane; -> sim seconds until
    every node's domain ledger reaches expect_size, or None."""
    t0 = pool.timer.get_current_time()
    for n in pool.names:
        ingress[n].submit(req.to_dict(), "steady")
    elapsed = 0.0
    while elapsed < timeout:
        pool.run(0.5)
        elapsed += 0.5
        if inbox_peaks is not None:
            inbox_peaks.append(max(len(pool.nodes[n]._client_inbox)
                                   for n in pool.names))
        if all(len(_domain_txns(pool.nodes[n])) >= expect_size
               for n in pool.names):
            return pool.timer.get_current_time() - t0
    return None


def run_client_flood_scenario(seed: int, faulted_plane=None) -> None:
    from plenum_tpu.client.sim_clients import burst_writes
    from plenum_tpu.common.node_messages import LoadShed
    from plenum_tpu.ingress import IngressPlane

    rng = SimRandom(seed * 48611 + 7)
    cap = rng.integer(2, 6)
    config = Config(**FAST, INGRESS_CLIENT_QUEUE_CAP=cap,
                    INGRESS_SLO_P95=0.2, INGRESS_CONTROL_INTERVAL=0.5)
    verifier = faulted_plane[0] if faulted_plane is not None else None
    pool = _track(Pool(seed=seed, config=config, verifier=verifier))
    if faulted_plane is not None:
        sup, faulty = faulted_plane
        sup.set_clock(pool.timer.get_current_time)
        faulty.set_clock(pool.timer.get_current_time)
    ingress = {n: IngressPlane(pool.nodes[n]) for n in pool.names}
    inbox_peaks: list[int] = []
    # live telemetry rides the fuzz: every node's snapshots feed ONE
    # aggregator; the flood below MUST fire the ingress burn-rate alert
    # (and the healthy pre-flood phase must fire none)
    from plenum_tpu.observability import FleetAggregator
    agg = FleetAggregator(config=config)
    for n in pool.names:
        pool.nodes[n].telemetry.add_sink(agg.ingest)

    def ingress_burn_pages():
        return [a for a in agg.alerts
                if a.kind == "slo_burn.ingress" and a.severity == "page"]

    users = [Ed25519Signer(seed=(b"cf%d-%d" % (seed, i)).ljust(32, b"\0")[:32])
             for i in range(2)]
    honest = [signed_nym(pool.trustee, u, i + 1)
              for i, u in enumerate(users)]

    # pre-flood: honest ordering through the plane, timed (the SLO datum)
    pre = _ingress_order_and_time(pool, ingress, honest[0], 2,
                                  inbox_peaks=inbox_peaks)
    assert pre is not None, f"seed {seed}: healthy plane failed to order"
    assert not ingress_burn_pages(), \
        f"seed {seed}: burn alert fired on a healthy plane (false positive)"

    if faulted_plane is not None:
        # crypto-plane fault lands BEFORE the flood: the shed storm rides
        # hedged CPU-fallback verdicts end to end
        kind = ("wedge", "drop", "corrupt")[rng.integer(0, 2)]
        getattr(faulted_plane[1], kind)()

    # the flood: hot clients burst well past their per-client caps; half
    # the seeds flood VALID-shaped bad signatures (they must die in the
    # ingress auth batch, not in the pool)
    n_hot = rng.integer(8, 24)
    per_client = cap + rng.integer(3, 8)
    bad = rng.integer(0, 2) == 0
    burst = burst_writes(pool.trustee, n_hot, per_client, seed=seed,
                         bad_sigs=bad)
    for client, req in burst:
        for n in pool.names:
            ingress[n].submit(req.to_dict(), client)
    # honest steady client writes DURING the flood: its queue is its own,
    # so fairness (not luck) keeps it inside the SLO
    during = _ingress_order_and_time(
        pool, ingress, honest[1],
        len(_domain_txns(pool.nodes[pool.names[0]])) + 1,
        timeout=30.0, inbox_peaks=inbox_peaks)
    deadline = pre + (15.0 if faulted_plane is not None else 8.0)
    assert during is not None, \
        f"seed {seed}: honest client starved during flood (bad={bad})"
    assert during <= deadline, \
        f"seed {seed}: honest order took {during:.1f}s > {deadline:.1f}s"

    # explicit sheds, never silent: every over-cap burst write got a
    # LoadShed reply on every node
    expect_shed = n_hot * (per_client - cap)
    for n in pool.names:
        assert ingress[n].stats["shed"] >= expect_shed, \
            f"seed {seed}: {n} shed {ingress[n].stats['shed']} < " \
            f"{expect_shed}"
        sheds = [m for m, _ in pool.client_msgs[n]
                 if isinstance(m, LoadShed)]
        assert len(sheds) >= expect_shed, f"seed {seed}: missing replies"
        # bounded queues: depth never exceeded what the caps allow
        assert ingress[n].stats["queue_depth_max"] <= \
            (n_hot + 2) * cap + 2, f"seed {seed}: queue grew past caps"
    if bad:
        # the bad-signature flood died at the front door: auth rejects
        # recorded, and NOT ONE flood write reached the ledger
        assert any(ingress[n].stats["auth_fail"] > 0 for n in pool.names), \
            f"seed {seed}: bad-sig flood never hit the batched verifier"
        assert len(_domain_txns(pool.nodes[pool.names[0]])) == 3, \
            f"seed {seed}: a bad-signature write ordered"
    # sustain the flood (same hot clients, fresh writes) across several
    # snapshot intervals: the multi-window rule pages on a shed storm
    # that PERSISTS on both burn windows (a lone burst is a blip — that
    # it cannot page is pinned deterministically in test_telemetry), and
    # the breadth rule counts the capped-client storm against the budget
    # because MANY distinct clients are being refused, not one abuser
    for wave in range(6):
        for client, req in burst_writes(pool.trustee, n_hot, per_client,
                                        seed=seed * 131 + wave + 1,
                                        bad_sigs=bad):
            for n in pool.names:
                ingress[n].submit(req.to_dict(), client)
        pool.run(1.0)
    assert ingress_burn_pages(), \
        f"seed {seed}: sustained flood never fired the ingress burn " \
        f"alert (alerts: {[a.to_dict() for a in agg.alerts]})"
    # the pool never wedged: the raw client inbox stayed near-empty the
    # whole run (writes ride ingress, never the inbox)
    assert max(inbox_peaks) <= 10, \
        f"seed {seed}: client inbox grew to {max(inbox_peaks)}"
    if faulted_plane is not None:
        st = faulted_plane[0].supervisor_stats()
        assert st["fallback_batches"] >= 1, \
            f"seed {seed}: flood under fault never took the CPU fallback"
        assert st["max_stall_s"] <= st["max_budget_s"] + 0.3, \
            f"seed {seed}: shed storm stalled past the deadline budget"
    assert_safety(pool)


def run_client_flood_with_device_flap(seed: int) -> None:
    """client_flood composed with device_flap: the shared crypto plane is
    faulted before the flood, so every shed decision and every batched
    verdict rides the supervisor's hedged CPU fallback."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.supervisor import (CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    rng = SimRandom(seed * 75403 + 11)
    faulty = FaultyVerifier(CpuEd25519Verifier())
    sup = SupervisedVerifier(
        faulty, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=2,
                               cooldown=rng.float(0.5, 1.5)),
        budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                              warm_max=1.0, cold_max=1.0))
    run_client_flood_scenario(seed, faulted_plane=(sup, faulty))


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_client_flood_fuzz(bucket):
    for seed in range(bucket * 5, (bucket + 1) * 5):
        _run_with_artifacts(run_client_flood_scenario, seed)


def test_sim_client_flood_smoke():
    """One client_flood scenario always runs in the default suite."""
    _run_with_artifacts(run_client_flood_scenario, 2)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(2))
def test_sim_client_flood_device_flap_fuzz(bucket):
    for seed in range(bucket * 3, (bucket + 1) * 3):
        _run_with_artifacts(run_client_flood_with_device_flap, seed)


def test_sim_client_flood_device_flap_smoke():
    """One composed flood+crypto-fault scenario in the default suite."""
    _run_with_artifacts(run_client_flood_with_device_flap, 1)


LYING_READER_SEEDS = 20


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_lying_reader_fuzz(bucket):
    for seed in range(bucket * 5, (bucket + 1) * 5):
        _run_with_artifacts(run_lying_reader_scenario, seed)


def test_sim_lying_reader_smoke():
    """One lying_reader scenario always runs in the default suite."""
    _run_with_artifacts(run_lying_reader_scenario, 2)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_lying_reader_verkle_fuzz(bucket):
    for seed in range(bucket * 5, (bucket + 1) * 5):
        _run_with_artifacts(run_lying_reader_verkle_scenario, seed)


def test_sim_lying_reader_verkle_smoke():
    """Two verkle rungs always run in the default suite: seed 4 draws
    the spliced-multi-key rung (the aggregated-proof-specific forgery),
    seed 9 the stripped-proof escalation."""
    _run_with_artifacts(run_lying_reader_verkle_scenario, 4)
    _run_with_artifacts(run_lying_reader_verkle_scenario, 9)


def test_sim_lying_reader_stale_replay():
    """A liar replaying a captured pre-rotation reply (honest sig, old
    root) must be rejected by the freshness bound and failed over."""
    from plenum_tpu.execution.txn import GET_NYM, NYM
    from test_reads import LyingPlane, make_driver

    pool = Pool(seed=5, config=Config(**FAST))
    user = Ed25519Signer(seed=b"stale-user".ljust(32, b"\0")[:32])
    assert _order_and_time(pool, signed_nym(pool.trustee, user, 1), 2) \
        is not None

    # capture an honest reply at t0 through the liar-to-be
    liar = pool.names[0]
    node = pool.nodes[liar]
    captured = node.read_plane.answer(
        Request("cap", 1, {"type": GET_NYM, "dest": user.identifier}))

    pool.run(12.0)                      # age the captured anchor
    rotated = Ed25519Signer(seed=b"stale-user-2".ljust(32, b"\0")[:32])
    upd = Request(pool.trustee.identifier, 2,
                  {"type": NYM, "dest": user.identifier,
                   "verkey": rotated.verkey_b58})
    upd.signature = pool.trustee.sign_b58(upd.signing_bytes())
    assert _order_and_time(pool, upd, 3) is not None

    # replay keeps the asker echo so the client matches the reply to its
    # request; the result digest excludes those fields, so the binding
    # still verifies and rejection comes from the freshness bound alone
    node.read_plane = LyingPlane(
        node.read_plane,
        lambda result: dict(captured, identifier=result.get("identifier"),
                            reqId=result.get("reqId")))
    driver = make_driver(pool, client="stale", freshness_s=8.0)
    q = Request("stale", 9, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q, per_node_s=2.0,
                      order=[liar] + [n for n in pool.names if n != liar])
    assert res is not None
    assert res["data"]["verkey"] == rotated.verkey_b58
    assert driver.stats.failovers >= 1
    assert driver.stats.verify_failures >= 1


def run_lying_edge_scenario(seed: int, force_rung=None) -> None:
    """The `lying_edge` fuzz kind: the Proof CDN's trust claim
    (reads/edge.py — deny-but-never-forge) under seeded attack. A
    malicious KEYLESS edge cache serves poisoned cached envelopes,
    strips proofs, or refuses outright; the verifying client must
    convert every forgery into a rejected reply + ladder failover and
    every denial into escalation — the read always completes with the
    true value, within the ladder deadline, with ZERO forged
    acceptances across all seeds. Rungs:

    * ``forge_value``: a state-proof entry's value bytes are reversed
      inside the cached envelope;
    * ``forge_root``: the envelope cites a root the pool never signed,
      with the result digest rebound by a smart liar;
    * ``tamper_data``: the result data is swapped and the digest
      rebound — only proof verification stands;
    * ``strip``: the proof is removed -> NO_PROOF escalation (a deeper
      rung can still prove);
    * ``deny``: the edge refuses -> NACK, one timed-out rung.
    """
    import copy

    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.reads import READ_PROOF, result_digest
    from test_edge import attach_edge, make_edge_driver

    rng = SimRandom(seed * 9311 + 7)
    pool = _track(Pool(seed=seed, config=Config(**FAST)))
    edge = attach_edge(pool, name="liar-edge")
    user = Ed25519Signer(seed=(b"eliar%d" % seed).ljust(32, b"\0")[:32])
    assert _order_and_time(pool, signed_nym(pool.trustee, user, 1), 2) \
        is not None

    rejected: list = []
    driver = make_edge_driver(pool, edge, client="efuzz",
                              on_fail=rejected.append)
    # warm the cache HONESTLY first: the attack then mutates cached
    # bytes (a poisoned entry), not a mere forwarding proxy
    q0 = Request("efuzz", 50, {"type": GET_NYM, "dest": user.identifier})
    warm = driver.read(q0, per_node_s=2.0)
    assert warm is not None and driver.stats.edge_ok == 1, f"seed {seed}"

    def forge_value(result):
        env = result.get(READ_PROOF)
        if env and env.get("entries"):
            e = env["entries"][0]
            if e.get("value"):
                e["value"] = bytes(
                    reversed(bytes.fromhex(e["value"]))).hex()
        return result

    def forge_root(result):
        env = result.get(READ_PROOF)
        if env and env.get("root_hash"):
            env["root_hash"] = "ab" * 32
            env["result_digest"] = result_digest(result).hex()
        return result

    def tamper_data(result):
        if isinstance(result.get("data"), dict):
            result["data"] = dict(result["data"], verkey="EvilVerkey1111")
            env = result.get(READ_PROOF)
            if env:
                env["result_digest"] = result_digest(result).hex()
        return result

    def strip(result):
        result.pop(READ_PROOF, None)
        return result

    def deny(result):
        return None

    kinds = [("forge_value", forge_value), ("forge_root", forge_root),
             ("tamper_data", tamper_data), ("strip", strip),
             ("deny", deny)]
    kind, mutate = kinds[force_rung if force_rung is not None
                         else rng.integer(0, 4)]

    real_serve = edge.cache.serve

    def lying(request):
        res = real_serve(request)
        return mutate(copy.deepcopy(res)) if isinstance(res, dict) else res

    edge.cache.serve = lying

    q = Request("efuzz", 51, {"type": GET_NYM, "dest": user.identifier})
    t0 = pool.timer.get_current_time()
    res = driver.read(q, per_node_s=2.0)
    took = pool.timer.get_current_time() - t0
    deadline = 2.0 * (len(pool.names) + 1) + 1.0
    assert took <= deadline, \
        f"seed {seed}: {kind} read took {took:.1f}s > {deadline:.1f}s"
    s = driver.stats
    # the ONE invariant every rung shares: the lying edge never forges
    # an acceptance and never kills the read — a validator answers
    assert res is not None, f"seed {seed}: {kind} denied service for good"
    assert res["data"]["verkey"] == user.verkey_b58, \
        f"seed {seed}: {kind} FORGED an accepted read"
    assert s.edge_ok == 1 and s.fallbacks == 0, \
        f"seed {seed}: {kind} ({s.summary()})"
    if kind in ("forge_value", "forge_root", "tamper_data"):
        assert s.edge_verify_failures >= 1 and s.failovers >= 1, \
            f"seed {seed}: {kind} not rejected ({s.summary()})"
        assert rejected == [edge.name], f"seed {seed}"  # fleet was told
    elif kind == "strip":
        assert s.edge_escalations >= 1 and s.failovers >= 1, \
            f"seed {seed}: strip did not escalate ({s.summary()})"
        assert s.edge_verify_failures == 0, f"seed {seed}"
    else:                                   # deny
        assert s.timeouts >= 1 and s.failovers >= 1, \
            f"seed {seed}: deny did not fail over ({s.summary()})"
        assert s.edge_verify_failures == 0, f"seed {seed}"
    assert_safety(pool)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_lying_edge_fuzz(bucket):
    for seed in range(bucket * 5, (bucket + 1) * 5):
        _run_with_artifacts(run_lying_edge_scenario, seed)


def test_sim_lying_edge_smoke():
    """Two edge rungs always run in the default suite: the poisoned
    cached entry (forgery -> rejected + failover) and the denial rung
    (NACK -> timed-out rung + failover) — deny-but-never-forge in
    tier-1."""
    _run_with_artifacts(
        lambda s: run_lying_edge_scenario(s, force_rung=2), 2)
    _run_with_artifacts(
        lambda s: run_lying_edge_scenario(s, force_rung=4), 3)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_device_flap_fuzz(bucket):
    for seed in range(bucket * 5, (bucket + 1) * 5):
        _run_with_artifacts(run_device_flap_scenario, seed)


def test_sim_device_flap_smoke():
    """One device_flap scenario always runs in the default suite."""
    _run_with_artifacts(run_device_flap_scenario, 3)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_device_flap_pipeline_fuzz(bucket):
    for seed in range(bucket * 3, bucket * 3 + 3):
        _run_with_artifacts(run_device_flap_with_pipeline, seed)


def test_sim_device_flap_pipeline_smoke():
    """One pipelined device_flap scenario always runs in the default
    suite: breaker -> CPU fallback -> re-warm re-admits the pipeline."""
    _run_with_artifacts(run_device_flap_with_pipeline, 1)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_device_flap_multidevice_fuzz(bucket):
    for seed in range(bucket * 3, bucket * 3 + 3):
        _run_with_artifacts(run_device_flap_multidevice, seed)


def test_sim_device_flap_multidevice_smoke():
    """One per-device device_flap scenario always runs in the default
    suite: the seed-targeted chip's lane breaker opens ALONE, the other
    lanes keep dispatching, and the lane re-warms and rejoins."""
    _run_with_artifacts(run_device_flap_multidevice, 2)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_device_flap_commit_wave_fuzz(bucket):
    for seed in range(bucket * 3, bucket * 3 + 3):
        _run_with_artifacts(run_device_flap_with_commit_wave, seed)


def test_sim_device_flap_commit_wave_smoke():
    """One commit-wave device_flap scenario always runs in the default
    suite: the wedged cmt engine degrades that batch to host recommit,
    roots keep advancing, the ed lane stays isolated, and the healed
    engine re-admits fresh waves."""
    _run_with_artifacts(run_device_flap_with_commit_wave, 1)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_crypto_host_down_fuzz(bucket):
    for seed in range(bucket * 3, bucket * 3 + 3):
        _run_with_artifacts(run_crypto_host_down_scenario, seed)


def test_sim_crypto_host_down_smoke():
    """One crypto_host_down scenario always runs in the default suite:
    a rostered remote crypto host dies mid-consensus, only its lane's
    breaker opens, its queued waves steal back to local lanes (never
    double-verified), ordering holds the deadline budget, and the host
    re-warms and rejoins."""
    _run_with_artifacts(run_crypto_host_down_scenario, 2)


# 100 seeds, bucketed so failures show their seed range and xdist can split
@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(10))
def test_sim_view_change_fuzz(bucket):
    for seed in range(bucket * (N_SEEDS // 10),
                      (bucket + 1) * (N_SEEDS // 10)):
        _run_with_artifacts(run_scenario, seed)


def test_sim_fuzz_deep_window():
    """Existing scenario kinds under an AGGRESSIVELY deep pipeline:
    size-1 batches and a watermark-wide in-flight window keep many
    speculative uncommitted batches in flight straight through the fault,
    so revert-on-view-change and catchup re-staging run against a deep
    stack instead of the old 4-batch one. Seed 3 draws the primary
    blackout (partition of the primary), seed 4 the lossy network; plus
    one device_flap run with the crypto plane as the fault."""
    saved = dict(FAST)
    FAST.update(Max3PCBatchSize=1, Max3PCBatchesInFlight=300)
    try:
        _run_with_artifacts(run_scenario, 3)            # primary blackout
        _run_with_artifacts(run_scenario, 4)            # lossy network
        _run_with_artifacts(run_device_flap_scenario, 4)
    finally:
        FAST.clear()
        FAST.update(saved)


def test_sim_fuzz_smoke():
    """One scenario of each kind always runs in the default suite."""
    seen: set[int] = set()
    seed = 0
    while len(seen) < 6 and seed < 80:
        rng = SimRandom(seed * 7919 + 17)
        kind = rng.integer(0, 5)
        if kind not in seen:
            seen.add(kind)
            _run_with_artifacts(run_scenario, seed)
        seed += 1


def test_fuzz_failure_artifact_includes_all_rings(tmp_path):
    """The failure path itself: a failing rung must leave every node's
    flight-recorder ring on disk and name the artifact dir in the
    assertion (the acceptance shape for 'fuzz failures arrive with their
    last-seconds story')."""
    import glob
    import json
    import shutil

    def failing_scenario(seed):
        pool = _track(Pool(seed=seed, config=Config(**FAST)))
        user = Ed25519Signer(seed=b"artifact-user".ljust(32, b"\0")[:32])
        assert _order_and_time(pool, signed_nym(pool.trustee, user, 1), 2) \
            is not None
        raise AssertionError("synthetic rung failure")

    with pytest.raises(AssertionError) as exc:
        _run_with_artifacts(failing_scenario, 7)
    msg = str(exc.value)
    assert "flight-recorder rings of all nodes" in msg
    art_dir = msg.rsplit(": ", 1)[1].rstrip("]")
    try:
        dumps = sorted(glob.glob(art_dir + "/*-flight.json"))
        assert len(dumps) == 4, dumps          # one ring per node
        for path in dumps:
            with open(path) as fh:
                snap = json.load(fh)
            # the rings hold the pre-failure story: the ordered request's
            # span events are there
            stages = {e[1] for e in snap["events"]}
            assert "ordered" in stages and "reply" in stages, \
                (path, sorted(stages))
    finally:
        shutil.rmtree(art_dir, ignore_errors=True)


# --- scenario kind `membership_churn`: the POOL ITSELF is the fault ---------
# Live membership operations mid-load — node add (a fresh joiner catching
# up to join), node remove (including the current primary -> forced view
# change), BLS key rotation (stale-key commits rejected, then recovery),
# primary demotion — over the topology-aware WAN fabric (geo3/lossy_wan
# presets), composable with device_flap and client_flood. Runs as its own
# seed sweep (widening run_scenario's draw would remap historical seeds).

CHURN_NAMES = ["Alpha", "Beta", "Gamma", "Delta", "Eps"]


def _order_on(pool, req, expect_size: float, nodes: list[str],
              timeout: float = 30.0, to=None):
    """Submit to live nodes and run until every node in `nodes` reaches
    expect_size; -> sim seconds, or None on deadline miss."""
    t0 = pool.timer.get_current_time()
    live = [n for n in (to or pool.names) if n in pool.nodes]
    pool.submit(req, to=live)
    elapsed = 0.0
    while elapsed < timeout:
        pool.run(0.5)
        elapsed += 0.5
        if all(n in pool.nodes
               and len(_domain_txns(pool.nodes[n])) >= expect_size
               for n in nodes):
            return pool.timer.get_current_time() - t0
    return None


def run_membership_churn_scenario(seed: int, force_rung=None,
                                  faulted_plane=None) -> None:
    from plenum_tpu.crypto.bls import BlsCryptoSigner
    from plenum_tpu.network import make_topology
    from test_scale import signed_node_services

    rng = SimRandom(seed * 32452843 + 19)
    rung = rng.integer(0, 3) if force_rung is None else force_rung
    # the removed-primary rung ALWAYS runs under lossy_wan (the
    # acceptance profile); other rungs draw clean-vs-degraded WAN
    preset = "lossy_wan" if (rung == 2 or rng.integer(0, 1) == 0) \
        else "geo3"
    verifier = faulted_plane[0] if faulted_plane is not None else None
    # the join rung starts Eps demoted (it must catch up to join); every
    # OTHER rung runs all five as validators so a demotion/removal lands
    # at n=4, f=1 — removing a node from a 4-validator pool would leave
    # f=0, where ANY message loss is fatal and the rung stops measuring
    # churn and starts measuring luck
    pool = _track(Pool(names=CHURN_NAMES,
                       validator_names=CHURN_NAMES[:4] if rung == 0
                       else None,
                       seed=seed, config=Config(**FAST),
                       verifier=verifier))
    pool.net.set_topology(make_topology(preset, CHURN_NAMES))
    if faulted_plane is not None:
        sup, faulty = faulted_plane
        sup.set_clock(pool.timer.get_current_time)
        faulty.set_clock(pool.timer.get_current_time)

    users = [Ed25519Signer(seed=(b"mc%d-%d" % (seed, i))
                           .ljust(32, b"\0")[:32]) for i in range(4)]
    reqs = [signed_nym(pool.trustee, u, i + 1) for i, u in enumerate(users)]
    validators = CHURN_NAMES[:4] if rung == 0 else list(CHURN_NAMES)
    # healthy baseline write under the drawn WAN profile
    assert _order_on(pool, reqs[0], 2, validators) is not None, \
        f"seed {seed}: healthy churn pool failed to order ({preset})"

    if faulted_plane is not None:
        # the crypto plane faults BEFORE the churn event: every auth /
        # commit verdict through the churn rides the supervisor's
        # breaker + hedged CPU fallback
        getattr(faulted_plane[1],
                ("wedge", "drop", "corrupt")[rng.integer(0, 2)])()

    req_id = 100
    if rung == 0:
        # NODE ADD: Eps restarts with no memory, catches up AS A
        # NON-VALIDATOR (the joiner bus filter), is promoted, and the
        # 5-node pool orders everywhere
        pool.crash_node("Eps")
        assert _order_on(pool, reqs[1], 3, validators) is not None, \
            f"seed {seed}: pool stalled while joiner was away"
        pool.start_node("Eps")
        pool.net.connect_all()
        eps = pool.nodes["Eps"]
        assert len(_domain_txns(eps)) == 1          # fresh from genesis
        eps.start_catchup()
        elapsed = 0.0
        while elapsed < 40.0 and (eps.leecher.is_running
                                  or len(_domain_txns(eps)) < 3):
            pool.run(0.5)
            elapsed += 0.5
        assert len(_domain_txns(eps)) >= 3, \
            f"seed {seed}: joiner catchup never completed ({preset})"
        pool.submit(signed_node_services(pool.trustee, "Eps",
                                         ["VALIDATOR"], req_id),
                    to=validators)
        pool.run(8.0)
        assert "Eps" in pool.nodes["Alpha"].validators, \
            f"seed {seed}: promotion never committed"
        expect = len(_domain_txns(pool.nodes["Alpha"])) + 1
        took = _order_on(pool, reqs[2], expect, CHURN_NAMES, timeout=40.0)
        if took is None:
            sizes = {n: len(_domain_txns(pool.nodes[n]))
                     for n in CHURN_NAMES}
            raise AssertionError(
                f"seed {seed}: post-join pool failed to order: {sizes}")
    elif rung == 1:
        # NODE REMOVE (non-primary): demote AND crash a non-primary
        # validator — the surviving 4 (f=1) keep ordering
        primary = pool.nodes["Alpha"].master_replica.data.primary_name
        victim = [n for n in validators if n != primary][rng.integer(0, 3)]
        pool.submit(signed_node_services(pool.trustee, victim, [],
                                         req_id),
                    to=[n for n in CHURN_NAMES if n in pool.nodes])
        pool.run(8.0)
        survivors = [n for n in CHURN_NAMES if n != victim]
        assert victim not in pool.nodes["Alpha"].validators, \
            f"seed {seed}: demotion never committed"
        pool.crash_node(victim)
        expect = len(_domain_txns(pool.nodes["Alpha"])) + 1
        assert _order_on(pool, reqs[2], expect, survivors,
                         timeout=40.0) is not None, \
            f"seed {seed}: pool stalled after node removal ({preset})"
    elif rung == 2:
        # REMOVE THE PRIMARY (demotion mid-load) under lossy_wan: the
        # pool must complete a FORCED view change and order new writes
        # within the rung deadline
        primary = pool.nodes["Alpha"].master_replica.data.primary_name
        view0 = pool.nodes["Alpha"].master_replica.view_no
        pool.submit(signed_node_services(pool.trustee, primary, [],
                                         req_id),
                    to=validators)
        survivors = [n for n in validators if n != primary]
        expect = len(_domain_txns(pool.nodes["Alpha"])) + 1
        took = _order_on(pool, reqs[2], expect, survivors, timeout=50.0)
        assert took is not None, \
            f"seed {seed}: no ordering after primary demotion (lossy_wan)"
        for n in survivors:
            node = pool.nodes[n]
            assert primary not in node.validators, \
                f"seed {seed}: {n} kept the demoted primary"
            assert node.master_replica.view_no > view0, \
                f"seed {seed}: {n} never completed the forced view change"
    else:
        # BLS KEY ROTATION: ledger key rotates, the node's signer stays
        # stale (its commits must be rejected WITHOUT poisoning the
        # batch check), then the operator re-keys and the node rejoins
        # aggregates
        primary = pool.nodes["Alpha"].master_replica.data.primary_name
        victim = [n for n in validators if n != primary][rng.integer(0, 3)]
        old_pk = BlsCryptoSigner(
            seed=victim.encode().ljust(32, b"\0")[:32]).pk
        new_signer = BlsCryptoSigner(
            seed=(b"mc-rot%d-%s" % (seed, victim.encode()))
            .ljust(32, b"\0")[:32])
        req = Request(pool.trustee.identifier, req_id,
                      {"type": txn_lib.NODE, "dest": f"{victim}Dest",
                       "data": {"blskey": new_signer.pk,
                                "blskey_pop": new_signer.generate_pop()}})
        req.signature = pool.trustee.sign_b58(req.signing_bytes())
        pool.submit(req, to=validators)
        elapsed = 0.0      # NODE txns land on the POOL ledger: wait on
        while elapsed < 30.0:   # the registry, not the domain size
            pool.run(0.5)
            elapsed += 0.5
            if all(pool.nodes[n].pool_manager.bls_key_of(victim)
                   == new_signer.pk for n in validators):
                break
        else:
            raise AssertionError(
                f"seed {seed}: rotation txn never committed")
        # stale window: the pool keeps ordering, aggregates EXCLUDE the
        # stale signer, no view change storms
        expect = len(_domain_txns(pool.nodes["Alpha"])) + 1
        assert _order_on(pool, reqs[2], expect, validators,
                         timeout=40.0) is not None, \
            f"seed {seed}: pool stalled during stale-key window"
        for n in validators:
            node = pool.nodes[n]
            assert node.pool_manager.bls_key_of(victim) == new_signer.pk
            assert old_pk not in \
                node.replicas.master.bls._verifier._vk_cache, \
                f"seed {seed}: {n} kept the rotated-out key warm"
            if n != victim:
                recent = list(node.replicas.master.bls
                              ._recent_multi_sigs.values())
                assert recent and victim not in recent[-1].participants, \
                    f"seed {seed}: stale-key sig counted at {n}"
        # recovery: re-key, fresh aggregates include the victim again
        pool.nodes[victim].replicas.master.bls._signer = new_signer
        expect += 1
        assert _order_on(pool, reqs[3], expect, validators,
                         timeout=40.0) is not None, \
            f"seed {seed}: pool stalled after re-key"
        recent = list(pool.nodes["Alpha"].replicas.master.bls
                      ._recent_multi_sigs.values())
        assert any(victim in m.participants for m in recent[-2:]), \
            f"seed {seed}: re-keyed node never rejoined aggregates"

    if faulted_plane is not None:
        from plenum_tpu.parallel.supervisor import CLOSED
        sup, faulty = faulted_plane
        st = sup.supervisor_stats()
        assert st["fallback_batches"] >= 1, \
            f"seed {seed}: churn under crypto fault never took CPU fallback"
        faulty.heal()
        waited = 0.0
        while sup.breaker.state != CLOSED and waited < 30.0:
            pool.run(1.0)
            waited += 1.0
            sup.verify_batch([(b"mc-heal-%d-%f" % (seed, waited),
                               b"\0" * 64, b"\0" * 32)])
        assert sup.breaker.state == CLOSED, \
            f"seed {seed}: breaker never re-closed after churn+fault"
        assert sup.stats["verdict_forks"] == 0
    assert_safety(pool)


def run_membership_churn_with_device_flap(seed: int) -> None:
    """membership_churn composed with device_flap: the shared supervised
    crypto plane is faulted before the churn event, so the whole churn —
    catchup, promotion/demotion commits, the forced view change — rides
    hedged CPU-fallback verdicts, then the plane heals and re-admits."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.supervisor import (CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    rng = SimRandom(seed * 86028121 + 5)
    faulty = FaultyVerifier(CpuEd25519Verifier())
    sup = SupervisedVerifier(
        faulty, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=2,
                               cooldown=rng.float(0.5, 1.5)),
        budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                              warm_max=1.0, cold_max=1.0))
    run_membership_churn_scenario(seed, faulted_plane=(sup, faulty))


def run_membership_churn_with_client_flood(seed: int) -> None:
    """membership_churn composed with client_flood: hot clients burst
    through per-node ingress planes while the CURRENT PRIMARY is demoted
    — the forced view change completes, the honest steady client's write
    still orders, and every over-cap burst write is shed EXPLICITLY."""
    from plenum_tpu.client.sim_clients import burst_writes
    from plenum_tpu.common.node_messages import LoadShed
    from plenum_tpu.ingress import IngressPlane
    from plenum_tpu.network import make_topology
    from test_scale import signed_node_services

    rng = SimRandom(seed * 49979687 + 3)
    cap = rng.integer(2, 5)
    config = Config(**FAST, INGRESS_CLIENT_QUEUE_CAP=cap,
                    INGRESS_SLO_P95=0.3, INGRESS_CONTROL_INTERVAL=0.5)
    # five validators: demoting the primary leaves n=4 (f=1) — see the
    # base scenario's note on why removal at n=4 would measure luck
    pool = _track(Pool(names=CHURN_NAMES, seed=seed, config=config))
    pool.net.set_topology(make_topology("lossy_wan", pool.names))
    ingress = {n: IngressPlane(pool.nodes[n]) for n in pool.names}

    users = [Ed25519Signer(seed=(b"mcf%d-%d" % (seed, i))
                           .ljust(32, b"\0")[:32]) for i in range(2)]
    honest = [signed_nym(pool.trustee, u, i + 1)
              for i, u in enumerate(users)]
    pre = _ingress_order_and_time(pool, ingress, honest[0], 2,
                                  timeout=30.0)
    assert pre is not None, f"seed {seed}: healthy flood pool stalled"

    # flood + primary demotion land together
    n_hot = rng.integer(6, 16)
    per_client = cap + rng.integer(3, 6)
    burst = burst_writes(pool.trustee, n_hot, per_client, seed=seed)
    for client, req in burst:
        for n in pool.names:
            ingress[n].submit(req.to_dict(), client)
    primary = pool.nodes["Alpha"].master_replica.data.primary_name
    view0 = pool.nodes["Alpha"].master_replica.view_no
    pool.submit(signed_node_services(pool.trustee, primary, [], 400))
    during = _ingress_order_and_time(
        pool, ingress, honest[1],
        len(_domain_txns(pool.nodes[pool.names[0]])) + 1, timeout=60.0)
    assert during is not None, \
        f"seed {seed}: honest client starved during flood+demotion"
    survivors = [n for n in pool.names if n != primary]
    for n in survivors:
        assert pool.nodes[n].master_replica.view_no > view0, \
            f"seed {seed}: {n} never view-changed under flood"
        sheds = [m for m, _ in pool.client_msgs[n]
                 if isinstance(m, LoadShed)]
        assert len(sheds) >= n_hot * (per_client - cap), \
            f"seed {seed}: sheds silent at {n}"
    assert_safety(pool)


MEMBERSHIP_CHURN_SEEDS = 20


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_membership_churn_fuzz(bucket):
    for seed in range(bucket * 5, (bucket + 1) * 5):
        _run_with_artifacts(run_membership_churn_scenario, seed)


def test_sim_membership_churn_smoke():
    """Two rungs always run in the default suite: the acceptance rung —
    the CURRENT PRIMARY demoted under lossy_wan, forced view change
    completing within deadline — and the key-rotation rung (stale-key
    commits rejected, then recovery)."""
    _run_with_artifacts(
        lambda seed: run_membership_churn_scenario(seed, force_rung=2), 1)
    _run_with_artifacts(
        lambda seed: run_membership_churn_scenario(seed, force_rung=3), 2)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(2))
def test_sim_membership_churn_device_flap_fuzz(bucket):
    for seed in range(bucket * 3, (bucket + 1) * 3):
        _run_with_artifacts(run_membership_churn_with_device_flap, seed)


def test_sim_membership_churn_device_flap_smoke():
    _run_with_artifacts(run_membership_churn_with_device_flap, 2)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(2))
def test_sim_membership_churn_client_flood_fuzz(bucket):
    for seed in range(bucket * 2, (bucket + 1) * 2):
        _run_with_artifacts(run_membership_churn_with_client_flood, seed)


def test_sim_membership_churn_client_flood_smoke():
    _run_with_artifacts(run_membership_churn_with_client_flood, 1)


# --- scenario kind `cross_shard`: the SHARD BOUNDARY is under attack --------
# Over the 2-shard ShardedSimFabric (plenum_tpu/shards/): tamper rungs —
# a forged mapping proof, a wrong-shard answer, a stale map served after
# a resharding — must every one fail CLOSED at the composed cross-shard
# check; confinement rungs — a partition or a device_flap landing on ONE
# shard — must never stall the other shard's ordering or its verified
# cross-shard reads. Runs as its own seed sweep (the existing kinds keep
# their historical seeds).


def _shard_sizes(shard, names=None) -> set[int]:
    return {shard.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
            for n in (names or shard.names)}


def _fab_order_and_time(fab, shard, req, expect: int, names=None,
                        timeout: float = 25.0):
    """Route through the fabric and run until every node in `names` of
    `shard` reaches ledger size `expect`; -> sim seconds, or None."""
    t0 = fab.timer.get_current_time()
    assert fab.submit_write(req) == shard.shard_id
    elapsed = 0.0
    while elapsed < timeout:
        fab.run(0.5)
        elapsed += 0.5
        if _shard_sizes(shard, names) == {expect}:
            return fab.timer.get_current_time() - t0
    return None


def run_cross_shard_fuzz_scenario(seed: int, force_rung=None) -> None:
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.shards import (MappingLedger, ShardDescriptor,
                                   ShardReadGate, ShardedSimFabric)
    from plenum_tpu.shards.mapping import directory_bls_signers
    from test_shards import LyingGate, signed_write, user_on_shard

    rng = SimRandom(seed * 15485863 + 29)
    rung = rng.integer(0, 4) if force_rung is None else force_rung

    sup = faulty = None
    shard_verifiers = None
    flap_sid = rng.integer(0, 1)
    if rung == 4:
        # the crypto plane of ONE shard is the fault: that shard's four
        # nodes share a supervised faulty device, the other shard's
        # plane is untouched
        from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
        from plenum_tpu.parallel.faults import FaultyVerifier
        from plenum_tpu.parallel.supervisor import (CircuitBreaker,
                                                    DeadlineBudget,
                                                    SupervisedVerifier)
        faulty = FaultyVerifier(CpuEd25519Verifier())
        sup = SupervisedVerifier(
            faulty, fallback=CpuEd25519Verifier(),
            breaker=CircuitBreaker(fail_threshold=2,
                                   cooldown=rng.float(0.5, 1.5)),
            budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                                  warm_max=1.0, cold_max=1.0))
        shard_verifiers = {flap_sid: sup}

    fab = _track(ShardedSimFabric(n_shards=2, nodes_per_shard=4, seed=seed,
                                  config=Config(**FAST),
                                  shard_verifiers=shard_verifiers))
    if sup is not None:
        sup.set_clock(fab.timer.get_current_time)
        faulty.set_clock(fab.timer.get_current_time)

    # seed one owned write per shard; both shards order independently
    users = {sid: user_on_shard(fab, sid, b"xsf%d-" % seed)
             for sid in fab.shards}
    for req_id, (sid, u) in enumerate(sorted(users.items()), start=1):
        assert fab.submit_write(signed_write(fab, u, req_id)) == sid
    elapsed = 0.0
    while elapsed < 25.0 and any(_shard_sizes(s) != {2}
                                 for s in fab.shards.values()):
        fab.run(0.5)
        elapsed += 0.5
    for sid, shard in fab.shards.items():
        assert _shard_sizes(shard) == {2}, \
            f"seed {seed}: shard {sid} never ordered its seed write"

    victim_sid = rng.integer(0, 1)       # the shard the tamper targets
    victim = users[victim_sid]
    q = Request("xsf", 50, {"type": GET_NYM, "dest": victim.identifier})

    if rung == 0:
        # FORGED MAPPING PROOF: every node of the owning shard cites a
        # map signed by a non-directory committee — each ladder rung must
        # reject fail-closed; after the gate heals, the SAME driver
        # verifies again
        evil = MappingLedger(
            [ShardDescriptor.from_dict(d.to_dict())
             for d in fab.mapping.descriptors],
            directory_bls_signers([f"Ev{i}-{seed}" for i in range(4)]),
            now=fab.timer.get_current_time)

        def forge(result, key):
            result["shard_proof"] = evil.ownership_proof(key)
            return result

        fab.gates[victim_sid] = LyingGate(fab.gates[victim_sid], forge)
        driver = fab.read_driver()
        res = driver.read(q, per_node_s=1.0, step_s=0.1)
        s = driver.stats.summary()
        assert res is None and s["fallbacks"] == 1, \
            f"seed {seed}: forged map accepted ({s})"
        assert s["map_proof_failures"] >= 1 and \
            s["map_failure_reasons"].get("bad_map_multi_sig", 0) >= 1, \
            f"seed {seed}: wrong rejection reason ({s})"
        fab.gates[victim_sid] = fab.gates[victim_sid].inner
        res = driver.read(Request("xsf", 51, dict(q.operation)),
                          per_node_s=2.0, step_s=0.1)
        assert res is not None and \
            res["data"]["verkey"] == victim.verkey_b58, \
            f"seed {seed}: healed gate still rejected"
    elif rung == 1:
        # WRONG-SHARD ANSWER: a foreign-shard node serves a valid-looking
        # absence envelope against ITS root — the composed check rejects
        # it and the ladder fails over INTO the owning shard
        other_sid = 1 - victim_sid
        wrong = fab.shards[other_sid].names[rng.integer(0, 3)]
        driver = fab.read_driver()
        res = driver.read(q, per_node_s=2.0, step_s=0.1,
                          order=[wrong] + list(fab.shards[victim_sid].names))
        s = driver.stats.summary()
        assert res is not None and \
            res["data"]["verkey"] == victim.verkey_b58, \
            f"seed {seed}: wrong-shard ladder never recovered ({s})"
        assert s["verify_failures"] >= 1 and s["failovers"] >= 1 and \
            s["fallbacks"] == 0, f"seed {seed}: wrong-shard accepted ({s})"
    elif rung == 2:
        # STALE MAP AFTER RESHARDING: the owning shard's gate keeps
        # serving the epoch-0 map after the directory publishes epoch 1 —
        # a client whose view saw epoch 1 must fail closed, then verify
        # once the gate refreshes
        stale_ml = MappingLedger(
            [ShardDescriptor.from_dict(d.to_dict())
             for d in fab.mapping.descriptors],
            fab.directory, now=fab.timer.get_current_time)
        fab.gates[victim_sid] = ShardReadGate(stale_ml)
        fab.mapping.reshard([ShardDescriptor.from_dict(d.to_dict())
                             for d in fab.mapping.descriptors])
        driver = fab.read_driver()           # view is at epoch 1
        res = driver.read(q, per_node_s=1.0, step_s=0.1)
        s = driver.stats.summary()
        assert res is None and s["fallbacks"] == 1, \
            f"seed {seed}: stale map accepted ({s})"
        assert s["map_failure_reasons"].get("stale_map", 0) >= 1, \
            f"seed {seed}: wrong stale rejection ({s})"
        fab.gates[victim_sid] = ShardReadGate(fab.mapping)
        res = driver.read(Request("xsf", 52, dict(q.operation)),
                          per_node_s=2.0, step_s=0.1)
        assert res is not None, f"seed {seed}: refreshed gate rejected"
    elif rung == 3:
        # PARTITION CONFINED TO ONE SHARD: blackout the victim shard's
        # primary on ITS OWN SimNetwork; the other shard must keep
        # ordering within its healthy latency AND keep answering verified
        # cross-shard reads while the victim is mid-view-change; the
        # victim's survivors then view-change and recover on their own
        other_sid = 1 - victim_sid
        vshard, oshard = fab.shards[victim_sid], fab.shards[other_sid]
        primary = vshard.nodes[vshard.names[0]] \
            .master_replica.data.primary_name
        vshard.net.add_rule(Discard(), match_dst(primary))
        vshard.net.add_rule(Discard(), match_frm(primary))
        survivors = [n for n in vshard.names if n != primary]
        # a write pending on the victim shard across its view change
        pend = user_on_shard(fab, victim_sid, b"pend%d-" % seed)
        fab.router.route(signed_write(fab, pend, 60), "xsf")
        # ...must not slow the OTHER shard below healthy ordering
        u2 = user_on_shard(fab, other_sid, b"live%d-" % seed, start=50)
        took = _fab_order_and_time(fab, oshard, signed_write(fab, u2, 61),
                                   3, timeout=10.0)
        assert took is not None, \
            f"seed {seed}: healthy shard stalled by foreign partition"
        driver = fab.read_driver()
        q2 = Request("xsf", 62, {"type": GET_NYM,
                                 "dest": users[other_sid].identifier})
        res = driver.read(q2, per_node_s=2.0, step_s=0.1)
        assert res is not None and driver.stats.summary()["fallbacks"] == 0, \
            f"seed {seed}: cross-shard read starved by foreign partition"
        fab.run(25.0)                        # victim view-changes and heals
        for n in survivors:
            assert vshard.nodes[n].master_replica.view_no >= 1, \
                f"seed {seed}: {n} stuck in view 0 behind the partition"
        assert _shard_sizes(vshard, survivors) == {3}, \
            f"seed {seed}: victim survivors lost the pending write"
    else:
        # DEVICE_FLAP CONFINED TO ONE SHARD: wedge/drop/corrupt the
        # faulted shard's shared device MID-TRAFFIC; that shard degrades
        # to hedged CPU fallback and keeps ordering, the OTHER shard's
        # plane never even notices; heal re-closes the breaker
        kind = ("wedge", "drop", "corrupt")[rng.integer(0, 2)]
        assert sup.stats["device_batches"] >= 1, \
            f"seed {seed}: seed traffic never hit the faulted shard device"
        getattr(faulty, kind)()
        fshard = fab.shards[flap_sid]
        oshard = fab.shards[1 - flap_sid]
        uf = user_on_shard(fab, flap_sid, b"flap%d-" % seed, start=100)
        uo = user_on_shard(fab, 1 - flap_sid, b"calm%d-" % seed, start=100)
        t_other = _fab_order_and_time(fab, oshard,
                                      signed_write(fab, uo, 70), 3,
                                      timeout=10.0)
        assert t_other is not None, \
            f"seed {seed}: un-faulted shard stalled by foreign {kind}"
        t_fault = _fab_order_and_time(fab, fshard,
                                      signed_write(fab, uf, 71), 3)
        assert t_fault is not None, \
            f"seed {seed}: faulted shard stopped ordering under {kind}"
        st = sup.supervisor_stats()
        assert st["fallback_batches"] >= 1, \
            f"seed {seed}: no CPU fallback under {kind}"
        assert st["max_stall_s"] <= st["max_budget_s"] + 0.3, \
            f"seed {seed}: stall past deadline budget"
        from plenum_tpu.parallel.supervisor import CLOSED
        faulty.heal()
        waited = 0.0
        while sup.breaker.state != CLOSED and waited < 30.0:
            fab.run(1.0)
            waited += 1.0
            sup.verify_batch([(b"xsf-heal-%d-%f" % (seed, waited),
                               b"\0" * 64, b"\0" * 32)])
        assert sup.breaker.state == CLOSED, \
            f"seed {seed}: shard breaker never re-closed after {kind}"
        assert sup.stats["verdict_forks"] == 0

    for shard in fab.shards.values():        # no fork inside any shard
        assert_safety(shard)


CROSS_SHARD_SEEDS = 20


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_cross_shard_fuzz(bucket):
    for seed in range(bucket * 5, (bucket + 1) * 5):
        _run_with_artifacts(run_cross_shard_fuzz_scenario, seed)


def test_sim_cross_shard_smoke():
    """Two rungs always run in the default suite: one tamper rung (the
    forged mapping proof, failing closed end to end) and one confinement
    rung (a partition landing on one shard leaving the other's ordering
    and cross-shard reads untouched)."""
    _run_with_artifacts(
        lambda s: run_cross_shard_fuzz_scenario(s, force_rung=0), 1)
    _run_with_artifacts(
        lambda s: run_cross_shard_fuzz_scenario(s, force_rung=3), 2)


# --- scenario kind `reshard`: the SHARD MAP ITSELF is in motion -------------
# Live split/merge (shards/reshard.py) and proof-carrying cross-shard
# writes (shards/cross_write.py) under fire: every admitted write across
# a migration must be ordered EXACTLY ONCE (no drop, no duplicate),
# every stale-epoch or partitioned cross-shard write must fail closed
# with ZERO half-commits, and a coordinator crash between prepare and
# commit must never lose atomicity. Composes with partition (rung 2),
# the ratchet race (rung 3), the 2PC fault matrix (rungs 4/5), and
# device_flap / client_flood via the run_reshard_with_* runners.


def _reshard_fabric(seed: int, shard_verifiers=None):
    from plenum_tpu.shards import ShardedSimFabric
    return _track(ShardedSimFabric(
        n_shards=2, nodes_per_shard=4, seed=seed, config=Config(**FAST),
        shard_verifiers=shard_verifiers))


def _drive_migration(fab, m, timeout: float = 90.0) -> None:
    elapsed = 0.0
    while elapsed < timeout and m.phase not in ("done", "aborted"):
        fab.run(0.5)
        elapsed += 0.5


def _owner_sid(fab, req) -> int:
    return fab.router.shard_of(req)


def _assert_exactly_once(fab, seed: int, writes) -> None:
    """Every admitted write is ordered exactly once at its CURRENT
    owner (post-migration map), and nowhere gains a duplicate."""
    from plenum_tpu.execution import txn as txn_lib
    from plenum_tpu.execution.txn import NYM
    ledger_dests: dict[int, list] = {}
    for sid, shard in fab.shards.items():
        node = next(iter(shard.nodes.values()))
        ledger = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
        # NYM creations only: 2PC records are ATTRIBs that legitimately
        # repeat their shard's anchor DID
        ledger_dests[sid] = [
            txn_lib.txn_data(ledger.get_by_seq_no(i)).get("dest")
            for i in range(2, ledger.size + 1)
            if txn_lib.txn_type_of(ledger.get_by_seq_no(i)) == NYM]
    for sid, dests in ledger_dests.items():
        dup = [d for d in set(dests) if dests.count(d) > 1]
        assert not dup, f"seed {seed}: duplicates on shard {sid}: {dup}"
    for user, req in writes:
        owner = _owner_sid(fab, req)
        assert owner is not None, f"seed {seed}: write lost from the map"
        assert user.identifier in ledger_dests[owner], \
            (f"seed {seed}: write {user.identifier[:8]} missing at its "
             f"owner {owner} ({ {s: len(d) for s, d in ledger_dests.items()} })")


def run_reshard_fuzz_scenario(seed: int, force_rung=None,
                              faulted_plane=None) -> None:
    from plenum_tpu.execution.txn import GET_NYM
    from test_shards import signed_write, user_on_shard

    rng = SimRandom(seed * 49979693 + 41)
    rung = rng.integer(0, 5) if force_rung is None else force_rung

    shard_verifiers = None
    if faulted_plane is not None:
        shard_verifiers = {0: faulted_plane[0]}   # the SOURCE shard's plane
    fab = _reshard_fabric(seed, shard_verifiers=shard_verifiers)
    if faulted_plane is not None:
        sup, faulty = faulted_plane
        sup.set_clock(fab.timer.get_current_time)
        faulty.set_clock(fab.timer.get_current_time)

    # zipfian-shaped seed load: most writes key into shard 0 (the hot
    # range a split relieves), a trickle into shard 1
    writes = []
    rid = 0
    n_hot = 4 + rng.integer(0, 2)
    for k in range(n_hot):
        u = user_on_shard(fab, 0, b"rs%d-" % seed, start=k * 17)
        rid += 1
        writes.append((u, signed_write(fab, u, rid)))
    u_cold = user_on_shard(fab, 1, b"rc%d-" % seed)
    rid += 1
    writes.append((u_cold, signed_write(fab, u_cold, rid)))
    for _u, req in writes:
        assert fab.submit_write(req) is not None
    elapsed = 0.0
    while elapsed < 30.0 and any(
            s.ordered_count() < 1 for s in fab.shards.values()):
        fab.run(0.5)
        elapsed += 0.5
    assert fab.shards[0].ordered_count() >= n_hot, \
        f"seed {seed}: hot shard never ordered its seed load"

    if faulted_plane is not None:
        # the source shard's crypto plane faults BEFORE the split: the
        # whole migration (copy replays, handoff) rides the supervisor's
        # breaker + hedged CPU fallback
        getattr(faulted_plane[1],
                ("wedge", "drop", "corrupt")[rng.integer(0, 2)])()

    if rung == 0:
        # HEALTHY SPLIT UNDER TRAFFIC: the hot range splits onto a new
        # sub-pool while writes keep flowing; exactly-once everywhere,
        # epoch ratchets, a stale-view reader refreshes instead of
        # erroring
        stale_driver = fab.read_driver()          # view predates the split
        m = fab.reshard.split(0)
        for k in range(3):                        # mid-migration traffic
            u = user_on_shard(fab, 0, b"rm%d-" % seed, start=k * 23)
            rid += 1
            req = signed_write(fab, u, rid)
            writes.append((u, req))
            assert fab.submit_write(req) is not None
        _drive_migration(fab, m)
        assert m.phase == "done", \
            f"seed {seed}: migration stuck: {m.to_dict()}"
        assert fab.mapping.epoch == 1 and len(fab.shards) == 3
        _assert_exactly_once(fab, seed, writes)
        moved = next((u for u, req in writes if _owner_sid(fab, req) == 2),
                     None)
        assert moved is not None, f"seed {seed}: split moved nothing"
        q = Request("rr", 900, {"type": GET_NYM, "dest": moved.identifier})
        res = stale_driver.read(q, per_node_s=1.5, step_s=0.1)
        s = stale_driver.stats.summary()
        assert res is not None and \
            res["data"]["verkey"] == moved.verkey_b58, \
            f"seed {seed}: stale-view read errored during healthy reshard {s}"
        assert s.get("map_retries", 0) == 1 and s["fallbacks"] == 0, s
    elif rung == 1:
        # LIVE MERGE: shard 1's whole range folds into shard 0 under
        # traffic; the source retires, its data verifies at the survivor
        m = fab.reshard.merge(1, 0)
        _drive_migration(fab, m)
        assert m.phase == "done", \
            f"seed {seed}: merge stuck: {m.to_dict()}"
        assert fab.mapping.epoch == 1 and sorted(fab.shards) == [0]
        _assert_exactly_once(fab, seed, writes)
        driver = fab.read_driver()
        q = Request("rr", 901, {"type": GET_NYM,
                                "dest": u_cold.identifier})
        res = driver.read(q, per_node_s=2.0, step_s=0.1)
        assert res is not None and \
            res["data"]["verkey"] == u_cold.verkey_b58, \
            f"seed {seed}: merged-away data unreadable at the survivor"
        assert not any(n.startswith("S1N") for n in fab.aggregator.latest)
    elif rung == 2:
        # RESHARD MID-PARTITION: the split target's primary is cut off
        # mid-copy — the migration must NOT ratchet while the copy
        # cannot complete (source keeps ownership, no write lost), then
        # complete after the heal + the target's own view change
        m = fab.reshard.split(0)
        tshard = fab.shards[m.target]
        primary = tshard.nodes[tshard.names[0]] \
            .master_replica.data.primary_name
        rules = [tshard.net.add_rule(Discard(), match_dst(primary)),
                 tshard.net.add_rule(Discard(), match_frm(primary))]
        fab.run(rng.float(3.0, 6.0))
        # the fail-closed coupling: the epoch ratchets IFF the copy
        # completed (the target's survivors may legitimately view-change
        # around their cut primary and finish early — but a ratchet with
        # the copy incomplete would be data loss)
        assert (fab.mapping.epoch == 0) == (m.phase == "copying"), \
            f"seed {seed}: ratchet/copy desync: epoch=" \
            f"{fab.mapping.epoch} phase={m.phase}"
        if m.phase == "copying":
            assert not m.pending or fab.mapping.epoch == 0
        # writes during the (possibly stalled) migration are never lost
        u = user_on_shard(fab, 0, b"rp%d-" % seed, start=31)
        rid += 1
        req = signed_write(fab, u, rid)
        writes.append((u, req))
        assert fab.submit_write(req) is not None
        for r in rules:
            tshard.net.remove_rule(r)
        _drive_migration(fab, m, timeout=120.0)
        assert m.phase == "done", \
            f"seed {seed}: migration never recovered from the partition " \
            f"({m.to_dict()})"
        _assert_exactly_once(fab, seed, writes)
    elif rung == 3:
        # STALE-EPOCH WRITES RACING THE RATCHET: a write landing at the
        # OLD owner inside the handoff window is forwarded and ordered
        # exactly once at the NEW owner; past the window it fails closed
        # (explicit NACK, ordered NOWHERE)
        m = fab.reshard.split(0)
        while m.phase == "copying":
            fab.run(0.5)
        assert m.phase == "handoff"
        stale_sink = fab.router.sinks[0]
        mover = user_on_shard(fab, 2, b"rw%d-" % seed)
        rid += 1
        req = signed_write(fab, mover, rid)
        writes.append((mover, req))
        before = fab.shards[2].ordered_count()
        stale_sink(req, "stale-client")
        elapsed = 0.0
        while elapsed < 30.0 and fab.shards[2].ordered_count() <= before:
            fab.run(0.5)
            elapsed += 0.5
        assert fab.shards[2].ordered_count() == before + 1, \
            f"seed {seed}: in-window stale write dropped"
        assert m.forwarded >= 1 and not fab.stale_nacks
        # run out the window (+ drain grace), then race again: fail closed
        fab.run(fab.config.RESHARD_HANDOFF_WINDOW * 3 + 5.0)
        late_u = user_on_shard(fab, 2, b"rw%d-" % seed, start=60)
        rid += 1
        late = signed_write(fab, late_u, rid)
        c0, c2 = fab.shards[0].ordered_count(), fab.shards[2].ordered_count()
        stale_sink(late, "stale-client")
        fab.run(5.0)
        assert fab.stale_nacks, f"seed {seed}: late stale write not NACKed"
        assert fab.shards[0].ordered_count() == c0 and \
            fab.shards[2].ordered_count() == c2, \
            f"seed {seed}: post-window stale write ordered somewhere"
        _assert_exactly_once(fab, seed, writes)
    elif rung == 4:
        # 2PC COORDINATOR CRASH between prepare and commit: the
        # participant's lock TTL resolves via the anchored decision read
        # (proven absence -> abort), ledger recovery orders the abort —
        # and a later transaction over the same dependency commits
        import json as _json
        from plenum_tpu.execution.txn import ATTRIB, NYM
        xsw = fab.cross_writes()
        home = user_on_shard(fab, 0, b"xh%d-" % seed, start=80)
        txid = xsw.begin(
            0, 1, {"type": NYM, "dest": home.identifier,
                   "verkey": home.verkey_b58},
            {"type": GET_NYM, "dest": u_cold.identifier},
            {"type": ATTRIB, "dest": u_cold.identifier,
             "raw": _json.dumps({"linked": home.identifier})})
        assert xsw.step(txid) == "prepared"
        crash_after_lock = rng.integer(0, 1) == 1
        if crash_after_lock:
            assert xsw.step(txid) == "locked"
        fab.run(25.0)                  # crash; TTLs expire
        rec = xsw.recover_from_ledger(0)
        assert txid in rec["aborted"], f"seed {seed}: {rec}"
        xsw.participant(1).service()
        assert xsw.participant(1).locks == {}, \
            f"seed {seed}: orphan lock survived the crash"
        records = xsw._scan_records(0)
        assert records[txid]["decision"]["decision"] == "abort"
        # atomicity: NEITHER half applied
        node0 = next(iter(fab.shards[0].nodes.values()))
        from plenum_tpu.execution import txn as txn_lib
        ledger0 = node0.c.db.get_ledger(DOMAIN_LEDGER_ID)
        assert not any(
            txn_lib.txn_data(ledger0.get_by_seq_no(i)).get("dest")
            == home.identifier for i in range(2, ledger0.size + 1)), \
            f"seed {seed}: half-commit at home after crash"
        # the dependency is free again: a retry commits cleanly
        home2 = user_on_shard(fab, 0, b"xh%d-" % seed, start=120)
        txid2 = xsw.begin(
            0, 1, {"type": NYM, "dest": home2.identifier,
                   "verkey": home2.verkey_b58},
            {"type": GET_NYM, "dest": u_cold.identifier})
        assert xsw.drive(txid2) == "committed", \
            f"seed {seed}: retry after crash-abort failed"
    else:
        # 2PC RACING THE RATCHET: a LIVE SPLIT of the coordinator's own
        # shard lands between lock and commit — the transaction must
        # abort fail-closed (epoch changed), with zero half-commits,
        # while the migration itself completes
        from plenum_tpu.execution.txn import NYM
        xsw = fab.cross_writes()
        xsw._anchor(0)                 # anchors ordered pre-migration
        xsw._anchor(1)
        home = user_on_shard(fab, 0, b"xr%d-" % seed, start=80)
        txid = xsw.begin(
            0, 1, {"type": NYM, "dest": home.identifier,
                   "verkey": home.verkey_b58},
            {"type": GET_NYM, "dest": u_cold.identifier})
        assert xsw.step(txid) == "prepared"
        assert xsw.step(txid) == "locked"
        m = fab.reshard.split(0)       # the map moves under the 2PC
        _drive_migration(fab, m)
        assert m.phase == "done" and fab.mapping.epoch == 1
        assert xsw.step(txid) == "aborted"
        assert xsw.txs[txid].abort_reason == "epoch_changed", \
            f"seed {seed}: {xsw.txs[txid].abort_reason}"
        assert xsw.participant(1).locks == {}
        node0 = next(iter(fab.shards[0].nodes.values()))
        from plenum_tpu.execution import txn as txn_lib
        ledger0 = node0.c.db.get_ledger(DOMAIN_LEDGER_ID)
        assert not any(
            txn_lib.txn_data(ledger0.get_by_seq_no(i)).get("dest")
            == home.identifier for i in range(2, ledger0.size + 1)), \
            f"seed {seed}: half-commit despite the epoch ratchet"
        _assert_exactly_once(fab, seed, writes)

    if faulted_plane is not None:
        sup, faulty = faulted_plane
        st = sup.supervisor_stats()
        assert st["fallback_batches"] >= 1, \
            f"seed {seed}: reshard under crypto fault never took fallback"
        assert sup.stats["verdict_forks"] == 0

    for shard in fab.shards.values():
        assert_safety(shard)


def run_reshard_with_device_flap(seed: int) -> None:
    """A live split while the SOURCE shard's crypto plane is faulted:
    the copy replays and the handoff ride hedged CPU fallback, and the
    migration still completes exactly-once."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.supervisor import (CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    rng = SimRandom(seed * 67867979 + 7)
    faulty = FaultyVerifier(CpuEd25519Verifier())
    sup = SupervisedVerifier(
        faulty, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=2,
                               cooldown=rng.float(0.5, 1.5)),
        budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                              warm_max=1.0, cold_max=1.0))
    # rung 0 (the live split): its mid-migration writes drive auth
    # through the faulted source plane, so the breaker + hedged CPU
    # fallback are actually exercised by the migration itself
    run_reshard_fuzz_scenario(seed, force_rung=0,
                              faulted_plane=(sup, faulty))


def run_reshard_with_client_flood(seed: int) -> None:
    """A live split while hot clients flood the front door: over-cap
    bursts shed EXPLICITLY, the honest client's write (owned by the
    migrating shard) survives the migration, and the reshard completes."""
    from plenum_tpu.client.sim_clients import burst_writes
    from plenum_tpu.common.node_messages import LoadShed
    from test_shards import signed_write, user_on_shard

    rng = SimRandom(seed * 37199 + 11)
    cap = rng.integer(2, 4)
    from plenum_tpu.shards import ShardedSimFabric
    fab = _track(ShardedSimFabric(
        n_shards=2, nodes_per_shard=4, seed=seed,
        config=Config(**FAST, INGRESS_CLIENT_QUEUE_CAP=cap)))
    entry = fab.shards[0].names[0]
    ing = fab.ingress_plane(entry, tick=False)

    honest = user_on_shard(fab, 0, b"fl%d-" % seed)
    req = signed_write(fab, honest, 1)
    fab.submit_write(req)
    elapsed = 0.0
    while elapsed < 20.0 and fab.shards[0].ordered_count() < 1:
        fab.run(0.5)
        elapsed += 0.5
    assert fab.shards[0].ordered_count() >= 1

    m = fab.reshard.split(0)
    n_hot = rng.integer(4, 8)
    per_client = cap + rng.integer(3, 5)
    for client, burst_req in burst_writes(fab.trustee, n_hot, per_client,
                                          seed=seed):
        ing.submit(burst_req.to_dict(), client)
    honest2 = user_on_shard(fab, 0, b"fh%d-" % seed, start=40)
    ing.submit(signed_write(fab, honest2, 2).to_dict(), "honest-2")
    for _ in range(240):
        ing.service()
        fab.run(0.5)
        if m.phase == "done":
            break
    assert m.phase == "done", \
        f"seed {seed}: reshard starved by the flood ({m.to_dict()})"
    sheds = [msg for msg, _ in fab.shards[0].client_msgs[entry]
             if isinstance(msg, LoadShed)]
    assert len(sheds) >= n_hot * (per_client - cap), \
        f"seed {seed}: over-cap burst not shed explicitly"
    owner = fab.router.shard_of(signed_write(fab, honest2, 2))
    node = next(iter(fab.shards[owner].nodes.values()))
    elapsed = 0.0
    while elapsed < 30.0 and node._executed_txn(
            signed_write(fab, honest2, 2)) is None:
        ing.service()
        fab.run(0.5)
        elapsed += 0.5
    assert node._executed_txn(signed_write(fab, honest2, 2)) is not None, \
        f"seed {seed}: honest write lost across flood + migration"
    for shard in fab.shards.values():
        assert_safety(shard)


RESHARD_SEEDS = 20


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_reshard_fuzz(bucket):
    for seed in range(bucket * 5, (bucket + 1) * 5):
        _run_with_artifacts(run_reshard_fuzz_scenario, seed)


def test_sim_reshard_smoke():
    """Two rungs always run in the default suite: the healthy split
    under traffic (exactly-once + the stale-view reader refreshing) and
    the ratchet race (in-window forward, post-window fail-closed NACK)."""
    _run_with_artifacts(
        lambda s: run_reshard_fuzz_scenario(s, force_rung=0), 1)
    _run_with_artifacts(
        lambda s: run_reshard_fuzz_scenario(s, force_rung=3), 2)


def test_sim_reshard_2pc_smoke():
    """The 2PC fault rungs always run: coordinator crash between
    prepare and commit (atomicity through recovery), and the live split
    racing an in-flight cross-shard write (fail-closed epoch abort)."""
    _run_with_artifacts(
        lambda s: run_reshard_fuzz_scenario(s, force_rung=4), 3)
    _run_with_artifacts(
        lambda s: run_reshard_fuzz_scenario(s, force_rung=5), 4)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(2))
def test_sim_reshard_device_flap_fuzz(bucket):
    for seed in range(bucket * 3, (bucket + 1) * 3):
        _run_with_artifacts(run_reshard_with_device_flap, seed)


def test_sim_reshard_device_flap_smoke():
    _run_with_artifacts(run_reshard_with_device_flap, 1)


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(2))
def test_sim_reshard_client_flood_fuzz(bucket):
    for seed in range(bucket * 2, (bucket + 1) * 2):
        _run_with_artifacts(run_reshard_with_client_flood, seed)


def test_sim_reshard_client_flood_smoke():
    _run_with_artifacts(run_reshard_with_client_flood, 1)


# --- membership_churn satellite: DIRECTORY-COMMITTEE key rotation -----------


def run_membership_churn_dir_rotation_scenario(seed: int) -> None:
    """Rotate one directory-committee signer MID-LOAD: the mapping root
    re-signs under the new committee, old-committee map proofs fail
    closed against the rotated trust root, and reads/writes keep
    flowing for clients holding the new root."""
    from plenum_tpu.crypto.bls import BlsCryptoSigner
    from plenum_tpu.execution.txn import GET_NYM
    from test_shards import signed_write, user_on_shard

    rng = SimRandom(seed * 23456789 + 13)
    fab = _reshard_fabric(seed)
    users = {sid: user_on_shard(fab, sid, b"dr%d-" % seed)
             for sid in fab.shards}
    for rid, (sid, u) in enumerate(sorted(users.items()), start=1):
        assert fab.submit_write(signed_write(fab, u, rid)) == sid
    elapsed = 0.0
    while elapsed < 25.0 and any(s.ordered_count() < 1
                                 for s in fab.shards.values()):
        fab.run(0.5)
        elapsed += 0.5

    victim_sid = rng.integer(0, 1)
    key = fab.mapping.shard_of(
        users[victim_sid].identifier.encode())     # sanity: map intact
    old_keys = dict(fab.mapping.directory_keys)
    old_proof = fab.mapping.ownership_proof(
        users[victim_sid].identifier.encode())
    stale_client = fab.read_driver()               # trusts the OLD root
    assert stale_client.checker.directory_keys == old_keys

    # rotate one signer mid-load; writes keep flowing around it
    victim_dir = sorted(fab.directory)[rng.integer(0, 3)]
    new_signer = BlsCryptoSigner(
        seed=(b"dirrot%d-%s" % (seed, victim_dir.encode()))
        .ljust(32, b"\0")[:32])
    fab.mapping.rotate_signer(victim_dir, new_signer)
    u_mid = user_on_shard(fab, 0, b"dm%d-" % seed, start=30)
    elapsed, target = 0.0, fab.shards[0].ordered_count() + 1
    assert fab.submit_write(signed_write(fab, u_mid, 50)) == 0
    while elapsed < 25.0 and fab.shards[0].ordered_count() < target:
        fab.run(0.5)
        elapsed += 0.5
    assert fab.shards[0].ordered_count() >= target, \
        f"seed {seed}: pool stalled across the directory rotation"

    from plenum_tpu.shards import verify_ownership
    new_keys = fab.mapping.directory_keys
    # the root RE-SIGNED: fresh proofs verify under the new committee
    fresh = fab.mapping.ownership_proof(users[victim_sid]
                                        .identifier.encode())
    got, why = verify_ownership(users[victim_sid].identifier.encode(),
                                fresh, new_keys,
                                now=fab.timer.get_current_time)
    assert why == "ok" and got.shard_id == key.shard_id, \
        f"seed {seed}: re-signed root does not verify ({why})"
    # OLD-committee proofs fail closed against the rotated trust root
    got, why = verify_ownership(users[victim_sid].identifier.encode(),
                                old_proof, new_keys,
                                now=fab.timer.get_current_time)
    assert got is None and why == "bad_map_multi_sig", \
        f"seed {seed}: old-committee proof accepted ({why})"
    # a client on the NEW root verifies reads end to end
    fresh_client = fab.read_driver()
    q = Request("dr", 60, {"type": GET_NYM,
                           "dest": users[victim_sid].identifier})
    res = fresh_client.read(q, per_node_s=2.0, step_s=0.1)
    assert res is not None and fresh_client.stats.summary()[
        "map_proof_failures"] == 0, \
        f"seed {seed}: rotated root broke healthy reads"
    # a client still pinning the OLD root rejects the new signature —
    # fail closed, never a silently-accepted downgrade
    q2 = Request("dr", 61, dict(q.operation))
    res = stale_client.read(q2, per_node_s=1.0, step_s=0.1)
    s = stale_client.stats.summary()
    assert res is None and \
        s["map_failure_reasons"].get("bad_map_multi_sig", 0) >= 1, \
        f"seed {seed}: old-root client accepted the rotated committee {s}"
    for shard in fab.shards.values():
        assert_safety(shard)


DIR_ROTATION_SEEDS = 8


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(2))
def test_sim_membership_churn_dir_rotation_fuzz(bucket):
    for seed in range(bucket * 4, (bucket + 1) * 4):
        _run_with_artifacts(run_membership_churn_dir_rotation_scenario,
                            seed)


def test_sim_membership_churn_dir_rotation_smoke():
    _run_with_artifacts(run_membership_churn_dir_rotation_scenario, 1)


# --- autopilot: the control plane under composed stress ----------------------
# The `autopilot` fuzz kind: telemetry -> actuation closed-loop
# (control/autopilot.py). Every scenario runs with ZERO test-driven
# actuation — the test injects load and faults, the autopilot alone
# splits, re-pins, scales and degrades — and every run must leave a
# control ledger that AUDITS CLEAN (tools/control_audit.py): the pinned
# no-flap property (no action/undo pair inside one cooldown window, no
# oscillating split/merge), every action evidenced, every undo citing
# its action.

def _autopilot_config(**over):
    cfg = dict(FAST)
    cfg.update(AUTOPILOT=True, AUTOPILOT_INTERVAL=0.5,
               AUTOPILOT_SUSTAIN=2, AUTOPILOT_RECOVER_SUSTAIN=3,
               AUTOPILOT_COOLDOWN=6.0, RESHARD_COOLDOWN=6.0,
               TELEMETRY_INTERVAL=0.5, SLO_BURN_FAST_WINDOW=2.0,
               SLO_BURN_SLOW_WINDOW=6.0)
    cfg.update(over)
    return Config(**cfg)


def _autopilot_audit(ap, seed: int) -> list[dict]:
    from plenum_tpu.tools.control_audit import audit_records
    recs = ap.ledger.to_dicts()
    problems = audit_records(recs)
    assert problems == [], \
        f"seed {seed}: control ledger failed its audit: {problems}"
    return recs


def _supervised_lanes(rng, n_lanes):
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.supervisor import (CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    faulties, sups = [], []
    for k in range(n_lanes):
        faulty = FaultyVerifier(CpuEd25519Verifier())
        sup = SupervisedVerifier(
            faulty, fallback=CpuEd25519Verifier(),
            breaker=CircuitBreaker(fail_threshold=2,
                                   cooldown=rng.float(0.5, 1.5)),
            budget=DeadlineBudget(base=rng.float(0.3, 0.6), min_s=0.2,
                                  warm_max=1.0, cold_max=1.0),
            label=f"lane{k}")
        faulties.append(faulty)
        sups.append(sup)
    return faulties, sups


def _junk(tag: bytes, seed: int, n: int = 3):
    return [(b"%s-%d-%d" % (tag, seed, i), b"\x01" * 63 + b"\x00",
             bytes([i % 250 + 1]) * 32) for i in range(n)]


def run_autopilot_split_scenario(seed: int) -> None:
    """Zipfian flood onto shard 0: the autopilot's SUSTAINED imbalance
    judgment must drive maybe_split on its own, the migration completes
    exactly-once, and the ledger shows ONE split (evidence + pre/post
    shard state) with no merge chasing it."""
    from plenum_tpu.shards import ShardedSimFabric
    from test_shards import signed_write, user_on_shard

    rng = SimRandom(seed * 93179 + 3)
    fab = _track(ShardedSimFabric(n_shards=2, nodes_per_shard=3,
                                  seed=seed, config=_autopilot_config()))
    ap = fab.autopilot
    assert ap is not None

    writes, rid = [], 0
    for k in range(10 + rng.integer(0, 4)):
        sid = 1 if k % 8 == 7 else 0           # ~90% keyed into shard 0
        u = user_on_shard(fab, sid, b"as%d-" % seed, start=k * 13)
        rid += 1
        req = signed_write(fab, u, rid)
        writes.append((u, req))
        assert fab.submit_write(req) is not None

    elapsed = 0.0
    while elapsed < 60.0 and not any(
            r.action == "split" for r in ap.ledger.records):
        fab.run(0.5)
        elapsed += 0.5
    splits = [r for r in ap.ledger.records if r.action == "split"]
    assert splits, \
        f"seed {seed}: sustained imbalance never actuated a split " \
        f"({ap.summary()})"
    m = fab.reshard.active or fab.reshard.history[-1]
    _drive_migration(fab, m, timeout=120.0)
    assert m.phase == "done", \
        f"seed {seed}: autopilot split never completed: {m.to_dict()}"
    assert len(fab.shards) == 3 and fab.mapping.epoch == 1
    rec = splits[0]
    assert rec.evidence.get("hot_shard") == 0 and \
        rec.evidence.get("index", 0) >= \
        fab.config.SHARD_IMBALANCE_THRESHOLD, rec.evidence
    assert rec.pre["shards"] == [0, 1] and rec.post["shards"] == [0, 1, 2]
    # no oscillation: one split, zero merges, audit-clean ledger
    fab.run(fab.config.AUTOPILOT_COOLDOWN + 3.0)
    assert len([r for r in ap.ledger.records
                if r.action == "split"]) == 1, \
        f"seed {seed}: the split chased its own transient"
    assert not [r for r in ap.ledger.records if r.action == "merge"], \
        f"seed {seed}: split/merge oscillation"
    _autopilot_audit(ap, seed)
    _assert_exactly_once(fab, seed, writes)
    for shard in fab.shards.values():
        assert_safety(shard)


def run_autopilot_repin_scenario(seed: int) -> None:
    """One chip of the shared multi-device ring flaps: the sustained
    open breaker re-pins the sick lane's shards to a healthy chip, a
    write ordered mid-sickness survives, and after the breaker holds
    closed through the recovery window (+cooldown) the pins RESTORE —
    the unpin citing its repin, never both inside one window."""
    from plenum_tpu.parallel.pipeline import MultiDeviceCryptoPipeline
    from plenum_tpu.parallel.supervisor import CLOSED
    from plenum_tpu.shards import ShardedSimFabric
    from test_shards import signed_write, user_on_shard

    rng = SimRandom(seed * 69623 + 29)
    faulties, sups = _supervised_lanes(rng, n_lanes=3)
    pipeline = MultiDeviceCryptoPipeline(
        ed_inners=sups, config=Config(**FAST), threaded=False)
    fab = _track(ShardedSimFabric(n_shards=2, nodes_per_shard=3,
                                  seed=seed, config=_autopilot_config(),
                                  pipeline=pipeline))
    ap = fab.autopilot
    for obj in (*sups, *faulties):
        obj.set_clock(fab.timer.get_current_time)

    sick = fab.lane_pins[0]
    assert sick is not None
    kind = ("wedge", "drop", "corrupt")[rng.integer(0, 2)]
    getattr(faulties[sick], kind)()
    elapsed = 0.0
    while elapsed < 40.0 and not any(
            r.action == "repin" for r in ap.ledger.records):
        pipeline.verifier(lane=sick).verify_batch(
            _junk(b"ap-sick%d" % int(elapsed * 2), seed))
        fab.run(0.5)
        elapsed += 0.5
    repins = [r for r in ap.ledger.records if r.action == "repin"]
    assert repins, \
        f"seed {seed}: sustained open breaker never re-pinned " \
        f"(breaker={sups[sick].breaker.state}, {ap.summary()})"
    target = fab.lane_pins[0]
    assert target != sick, f"seed {seed}: pin did not move off lane {sick}"
    assert repins[0].evidence.get("sick_lane") == sick

    # ordering continues on the re-pinned lane while the chip is dark
    u = user_on_shard(fab, 0, b"ar%d-" % seed)
    req = signed_write(fab, u, 1)
    assert fab.submit_write(req) is not None
    before = fab.shards[0].ordered_count()
    elapsed = 0.0
    while elapsed < 30.0 and fab.shards[0].ordered_count() <= before:
        fab.run(0.5)
        elapsed += 0.5
    assert fab.shards[0].ordered_count() > before, \
        f"seed {seed}: shard stopped ordering after the re-pin"

    # heal: probe traffic re-closes the breaker; the clear streak plus
    # the repin's cooldown stamp gate the restore
    faulties[sick].heal()
    elapsed = 0.0
    while elapsed < 60.0 and not any(
            r.action == "unpin" for r in ap.ledger.records):
        if sups[sick].breaker.state != CLOSED:
            pipeline.verifier(lane=sick).verify_batch(
                _junk(b"ap-heal%d" % int(elapsed * 2), seed))
        fab.run(0.5)
        elapsed += 0.5
    unpins = [r for r in ap.ledger.records if r.action == "unpin"]
    assert unpins, \
        f"seed {seed}: pins never restored after the re-warm " \
        f"({ap.summary()})"
    assert fab.lane_pins[0] == sick            # back on its own chip
    assert unpins[0].cites == repins[0].seq
    # hysteresis, not a flap: the undo landed OUTSIDE the cooldown
    assert unpins[0].t >= repins[0].cooldown_until
    _autopilot_audit(ap, seed)
    for shard in fab.shards.values():
        assert_safety(shard)


def run_autopilot_observer_scenario(seed: int) -> None:
    """Regional read burn: reads beyond the region's pooled capacity
    ledger SLO violations, the sustained burn spawns an observer, and
    after demand falls back (with measured headroom) the newest one
    retires — the retire citing its spawn."""
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.shards import ShardedSimFabric

    rng = SimRandom(seed * 50329 + 13)
    cap = 3.0 + rng.integer(0, 3)
    fab = _track(ShardedSimFabric(n_shards=2, nodes_per_shard=3,
                                  seed=seed, config=_autopilot_config()))
    fleet = fab.attach_observer_fleet(regions=("r0",), capacity=cap)
    ap = fab.autopilot
    q = Request("rdr", 1, {"type": GET_NYM,
                           "dest": fab.trustee.identifier}).to_dict()

    elapsed = 0.0
    while elapsed < 40.0 and fleet.count("r0") == 1:
        for _ in range(int(cap * 3) + 2):      # ~3x pooled capacity
            fleet.serve_read("r0", q)
        fab.run(0.5)
        elapsed += 0.5
    assert fleet.count("r0") == 2, \
        f"seed {seed}: read burn never spawned an observer " \
        f"({fleet.summary()}, {ap.summary()})"
    spawns = [r for r in ap.ledger.records
              if r.action == "observer_spawn"]
    assert spawns[0].subject == "r0" and spawns[0].evidence

    # demand falls to a trickle one observer holds with headroom
    elapsed = 0.0
    while elapsed < 60.0 and fleet.count("r0") == 2:
        fleet.serve_read("r0", q)
        fab.run(0.5)
        elapsed += 0.5
    assert fleet.count("r0") == 1, \
        f"seed {seed}: observer never retired after recovery " \
        f"({fleet.summary()}, {ap.summary()})"
    retires = [r for r in ap.ledger.records
               if r.action == "observer_retire"]
    assert retires[0].cites == spawns[0].seq
    assert retires[0].t >= spawns[0].cooldown_until
    assert fleet.stats["reads"] > 0 and fleet.stats["violations"] > 0
    _autopilot_audit(ap, seed)
    for shard in fab.shards.values():
        assert_safety(shard)


def run_autopilot_ladder_scenario(seed: int) -> None:
    """A front door's SLO ledger burns hot and STAYS hot: the ladder
    steps down (shed-harder clamps every ingress plane, then pool-wide
    read-only), holds at the floor, and steps back UP one level at a
    time on sustained recovery — recovers citing their degrades LIFO,
    and a catchup-parked read-only is never the autopilot's to clear."""
    from plenum_tpu.shards import ShardedSimFabric

    rng = SimRandom(seed * 104729 + 5)
    fab = _track(ShardedSimFabric(n_shards=2, nodes_per_shard=3,
                                  seed=seed, config=_autopilot_config()))
    ap = fab.autopilot
    entry = fab.shards[0].names[0]
    plane = fab.ingress_plane(entry, tick=False)
    base_wm = plane.shed_watermark
    tracker = fab.aggregator.tracker("ingress", "front-door")

    def feed(viol: int, n: int = 5) -> None:
        tracker.note(fab.timer.get_current_time(), viol, n)
        fab.run(0.5)

    burn = 3 + rng.integer(0, 2)
    elapsed = 0.0
    while elapsed < 60.0 and ap.level < 1:
        feed(burn)
        elapsed += 0.5
    assert ap.level >= 1, f"seed {seed}: ladder never degraded " \
                          f"({ap.summary()})"
    assert plane.shed_watermark == max(
        1, fab.config.INGRESS_HIGH_WATERMARK
        // fab.config.AUTOPILOT_SHED_FACTOR)
    while elapsed < 120.0 and ap.level < 2:
        feed(burn)
        elapsed += 0.5
    assert ap.level == 2, f"seed {seed}: ladder stuck below read-only " \
                          f"({ap.summary()})"
    assert all(n.read_only_degraded for n in fab.nodes.values())
    # held at the floor: more burn, no action past the ladder's end
    floor_actions = ap.counts["actions"]
    for _ in range(8):
        feed(burn)
    assert ap.counts["actions"] == floor_actions

    # recovery: clean intervals age the burn out of both windows
    while elapsed < 300.0 and ap.level > 0:
        feed(0)
        elapsed += 0.5
    assert ap.level == 0, f"seed {seed}: ladder never recovered " \
                          f"({ap.summary()})"
    assert not any(n.read_only_degraded for n in fab.nodes.values())
    assert plane.shed_watermark == base_wm
    recs = _autopilot_audit(ap, seed)
    degrades = [r for r in recs if r["action"] == "degrade"]
    recovers = [r for r in recs if r["action"] == "recover"]
    assert [r["subject"] for r in degrades] == ["shed_harder",
                                                "read_only"]
    assert [r["cites"] for r in recovers] == \
        [degrades[1]["seq"], degrades[0]["seq"]]
    for shard in fab.shards.values():
        assert_safety(shard)


def run_autopilot_composed_scenario(seed: int) -> None:
    """The acceptance run: zipfian client flood + a flapping chip lane
    + the live-split membership churn the autopilot itself drives, all
    at once, healed end-to-end with zero test-driven actuation. Pinned:
    the ledger audits clean (no action/undo inside a cooldown window),
    no split/merge oscillation, exactly-once ordering, no fork."""
    from plenum_tpu.parallel.pipeline import MultiDeviceCryptoPipeline
    from plenum_tpu.parallel.supervisor import CLOSED
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.shards import ShardedSimFabric
    from test_shards import signed_write, user_on_shard

    rng = SimRandom(seed * 122949823 + 19)
    faulties, sups = _supervised_lanes(rng, n_lanes=3)
    pipeline = MultiDeviceCryptoPipeline(
        ed_inners=sups, config=Config(**FAST), threaded=False)
    # generous SLO budgets: the composed run exercises split + re-pin +
    # observer scale; the ladder has its own dedicated scenario and
    # must not park the pool read-only mid-migration over sim timing
    fab = _track(ShardedSimFabric(
        n_shards=2, nodes_per_shard=3, seed=seed,
        config=_autopilot_config(BATCH_SLO_P95=30.0,
                                 INGRESS_SLO_P95=30.0),
        pipeline=pipeline))
    ap = fab.autopilot
    for obj in (*sups, *faulties):
        obj.set_clock(fab.timer.get_current_time)
    cap = 3.0 + rng.integer(0, 2)
    fleet = fab.attach_observer_fleet(regions=("r0",), capacity=cap)
    q = Request("rdr", 1, {"type": GET_NYM,
                           "dest": fab.trustee.identifier}).to_dict()

    # zipfian flood: ~90% of the writes key into shard 0
    writes, rid = [], 0
    for k in range(8 + rng.integer(0, 4)):
        sid = 1 if k % 8 == 7 else 0
        u = user_on_shard(fab, sid, b"ac%d-" % seed, start=k * 19)
        rid += 1
        req = signed_write(fab, u, rid)
        writes.append((u, req))
        assert fab.submit_write(req) is not None

    sick = fab.lane_pins[0]
    kind = ("wedge", "drop", "corrupt")[rng.integer(0, 2)]
    fab.run(rng.float(0.5, 1.5))
    getattr(faulties[sick], kind)()            # the chip flaps mid-flood
    heal_step = 24 + rng.integer(0, 8)
    for step in range(120):
        if step == heal_step:
            faulties[sick].heal()
        if step < heal_step:
            pipeline.verifier(lane=sick).verify_batch(
                _junk(b"cx%d" % step, seed))
        elif sups[sick].breaker.state != CLOSED:
            pipeline.verifier(lane=sick).verify_batch(
                _junk(b"ch%d" % step, seed))
        for _ in range(int(cap * 3) + 2 if step < 40 else 1):
            fleet.serve_read("r0", q)          # read burn, then trickle
        fab.run(0.5)
        if step > 80 and fab.reshard.active is None \
                and sups[sick].breaker.state == CLOSED \
                and not ap._repins:
            break
    if fab.reshard.active is not None:
        _drive_migration(fab, fab.reshard.active, timeout=120.0)

    recs = _autopilot_audit(ap, seed)          # the pinned no-flap gate
    splits = [r for r in recs if r["action"] == "split"]
    merges = [r for r in recs if r["action"] == "merge"]
    assert len(splits) <= 1 and not merges, \
        f"seed {seed}: split/merge oscillation under composed stress " \
        f"({[r['action'] for r in recs]})"
    assert splits, \
        f"seed {seed}: the hot-shard flood never split ({ap.summary()})"
    assert fab.reshard.history and \
        fab.reshard.history[-1].phase == "done", \
        f"seed {seed}: composed stress starved the migration"
    repins = [r for r in recs if r["action"] == "repin"]
    assert repins, \
        f"seed {seed}: the flapping chip never forced a re-pin " \
        f"({ap.summary()})"
    _assert_exactly_once(fab, seed, writes)
    for shard in fab.shards.values():
        assert_safety(shard)


AUTOPILOT_SEEDS = 12


@pytest.mark.slow
@pytest.mark.parametrize("bucket", range(4))
def test_sim_autopilot_fuzz(bucket):
    for seed in range(bucket * 3, (bucket + 1) * 3):
        _run_with_artifacts(run_autopilot_composed_scenario, seed)


def test_sim_autopilot_split_smoke():
    _run_with_artifacts(run_autopilot_split_scenario, 1)


def test_sim_autopilot_repin_smoke():
    _run_with_artifacts(run_autopilot_repin_scenario, 1)


def test_sim_autopilot_observer_smoke():
    _run_with_artifacts(run_autopilot_observer_scenario, 1)


def test_sim_autopilot_ladder_smoke():
    _run_with_artifacts(run_autopilot_ladder_scenario, 1)


def test_sim_autopilot_composed_smoke():
    _run_with_artifacts(run_autopilot_composed_scenario, 1)
