"""Aux subsystems: metrics collection, validator info, recorder/replay,
observer framework.

Reference test model: plenum/test/metrics, plenum/test/recorder,
plenum/test/observer (SURVEY.md §5 aux subsystems).
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.metrics import (KvMetricsCollector, MetricsCollector,
                                       MetricsName, NullMetricsCollector)
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.storage.kv_memory import KvMemory

from test_pool import Pool, make_genesis, signed_nym


# --- metrics --------------------------------------------------------------

def test_metrics_accumulator_folds():
    m = MetricsCollector()
    for v in (3.0, 1.0, 2.0):
        m.add_event("x", v)
    m.add_event("y")
    s = m.summary()
    assert s["x"] == {"count": 3, "sum": 6.0, "avg": 2.0, "min": 1.0,
                      "max": 3.0}
    assert s["y"]["count"] == 1
    with m.measure_time("t"):
        pass
    assert m.summary()["t"]["count"] == 1


def test_kv_metrics_flush_and_read_back():
    store = KvMemory()
    clock = [1000.0]
    m = KvMetricsCollector(store, now=lambda: clock[0])
    m.add_event("a", 5.0)
    m.add_event("a", 7.0)
    m.flush()
    clock[0] = 1010.0
    m.add_event("a", 1.0)
    m.flush()
    rows = m.read_rows()
    assert [(ts, name, d["count"], d["sum"]) for ts, name, d in rows] == [
        (1000.0, "a", 2, 12.0), (1010.0, "a", 1, 1.0)]
    assert m.summary() == {}            # flushed clean


def test_null_collector_is_inert():
    m = NullMetricsCollector()
    m.add_event("x", 1.0)
    with m.measure_time("y"):
        pass
    assert m.summary() == {}


def test_pool_populates_metrics_and_validator_info():
    pool = Pool()
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    user = Ed25519Signer(seed=b"aux-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(5.0)
    node = pool.nodes[pool.names[0]]
    assert pool.replies(pool.names[0])
    s = node.metrics.summary()
    assert s[MetricsName.CLIENT_MSGS]["count"] >= 1
    assert s[MetricsName.ORDERED_BATCH_SIZE]["count"] >= 1
    assert s[MetricsName.EXECUTE_BATCH_TIME]["count"] >= 1

    info = node.validator_info()
    assert info["name"] == pool.names[0]
    assert sorted(info["validators"]) == sorted(pool.names)
    assert info["f"] == 1
    assert info["view_no"] == 0
    assert not info["catchup_in_progress"]
    assert info["last_ordered_3pc"][1] >= 1
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    dom = info["ledgers"][DOMAIN_LEDGER_ID]
    assert dom["size"] == 2 and dom["uncommitted"] == 0
    # info snapshots from every node agree on the ordered state
    other = pool.nodes[pool.names[-1]].validator_info()
    assert other["ledgers"][DOMAIN_LEDGER_ID]["root"] == dom["root"]


# --- recorder / replay ----------------------------------------------------

def test_record_and_replay_reproduces_state():
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.node import Node, NodeBootstrap
    from plenum_tpu.node.recorder import Recorder, attach_recorder, replay

    pool = Pool()
    target = pool.names[0]
    store = KvMemory()
    recorder = Recorder(store, now=pool.timer.get_current_time)
    attach_recorder(pool.nodes[target], recorder)

    user = Ed25519Signer(seed=b"rec-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(5.0)
    user2 = Ed25519Signer(seed=b"rec-user2".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user2, 2))
    pool.run(5.0)
    live = pool.nodes[target]
    live_root = live.c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
    assert live.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 3

    # fresh node, same genesis, fed ONLY the recorded stream
    genesis, _ = make_genesis(pool.names)
    timer = MockTimer()
    components = NodeBootstrap(target, genesis_txns=genesis).build()
    from plenum_tpu.common.event_bus import ExternalBus
    bus = ExternalBus(send_handler=lambda msg, dst: None)   # sends -> sink
    node = Node(target, timer, bus, components, config=pool.config)
    n = replay(recorder.iter_records(), node, timer)
    assert n > 0
    ledger = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
    assert ledger.size == 3
    assert ledger.root_hash == live_root


# --- observer -------------------------------------------------------------

def _observer_components(names):
    from plenum_tpu.node import NodeBootstrap
    genesis, _ = make_genesis(names)
    return NodeBootstrap("Observer", genesis_txns=genesis).build()


def test_observer_follows_committed_batches():
    from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID,
                                                 BatchCommitted)
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.node.observer import NodeObserver

    pool = Pool()
    target = pool.names[0]
    node = pool.nodes[target]
    node.observable.add_observer("obs-client-1")
    assert node.observable.observer_ids == ["obs-client-1"]

    user = Ed25519Signer(seed=b"obs-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(5.0)

    pushes = [m for m, client in pool.client_msgs[target]
              if isinstance(m, BatchCommitted) and client == "obs-client-1"]
    assert pushes, "no BatchCommitted pushed to the registered observer"

    observer = NodeObserver(_observer_components(pool.names))
    for batch in pushes:
        assert observer.process_batch(batch)
        assert not observer.process_batch(batch)     # idempotent
    ledger = observer.c.db.get_ledger(DOMAIN_LEDGER_ID)
    live = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
    assert ledger.size == live.size == 2
    assert ledger.root_hash == live.root_hash


def test_observer_refuses_tampered_batch():
    from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID,
                                                 BatchCommitted)
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.node.observer import NodeObserver

    pool = Pool()
    target = pool.names[0]
    node = pool.nodes[target]
    node.observable.add_observer("obs")
    user = Ed25519Signer(seed=b"obs-user-2".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(5.0)
    batch = next(m for m, c in pool.client_msgs[target]
                 if isinstance(m, BatchCommitted))

    observer = NodeObserver(_observer_components(pool.names))
    import dataclasses
    bad = dataclasses.replace(batch, txn_root="00" * 32)
    assert not observer.process_batch(bad)
    # refusal reverted cleanly: the honest batch still applies
    assert observer.process_batch(batch)
    assert observer.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2


def test_observer_f_plus_1_data_quorum():
    """With f=1 an observer needs 2 content-identical pushes from DISTINCT
    validators before applying (ref quorums.py:38 observer_data): a lone
    Byzantine validator's fabricated-but-self-consistent batch is buffered
    forever, and its re-push replaces (not adds to) its earlier vote."""
    from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID,
                                                 BatchCommitted)
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.node.observer import NodeObserver

    pool = Pool()
    node = pool.nodes["Alpha"]
    node.observable.add_observer("obs")
    user = Ed25519Signer(seed=b"quorum-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(5.0)
    batch = next(m for m, c in pool.client_msgs["Alpha"]
                 if isinstance(m, BatchCommitted))

    import dataclasses
    # a SELF-CONSISTENT fabrication: drop the user NYM request entirely and
    # recompute nothing — roots won't match, but even a root-consistent
    # fake only ever gets the Byzantine node's single vote
    fake = dataclasses.replace(batch, requests=batch.requests[:0])

    observer = NodeObserver(_observer_components(pool.names), f=1)
    ledger = observer.c.db.get_ledger(DOMAIN_LEDGER_ID)
    base = ledger.size
    # Byzantine node pushes its fake — no quorum, nothing applied
    assert not observer.process_batch(fake, frm="Byz")
    assert not observer.process_batch(fake, frm="Byz")   # re-push: 1 vote
    assert ledger.size == base
    # one honest push: still below f+1
    assert not observer.process_batch(batch, frm="Beta")
    assert ledger.size == base
    # second honest push with IDENTICAL content -> quorum -> applied
    assert observer.process_batch(batch, frm="Gamma")
    assert ledger.size == base + 1
    # quorum state for the settled range was purged
    assert not observer._votes
    # late duplicate from a straggler is idempotently ignored
    assert not observer.process_batch(batch, frm="Delta")


# --- action requests ------------------------------------------------------

def test_validator_info_action_requires_privilege():
    from plenum_tpu.common.node_messages import Reject, Reply, RequestNack
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.action_manager import VALIDATOR_INFO_ACTION

    pool = Pool()
    # trustee invokes the action: executes locally, no consensus round
    req = Request(pool.trustee.identifier, 1,
                  {"type": VALIDATOR_INFO_ACTION})
    req.signature = pool.trustee.sign_b58(req.signing_bytes())
    pool.submit(req, to=["Alpha"])
    pool.run(2.0)
    replies = [m for m, _ in pool.client_msgs["Alpha"]
               if isinstance(m, Reply)
               and m.result.get("type") == VALIDATOR_INFO_ACTION]
    assert replies, "no validator-info reply"
    info = replies[0].result["data"]
    assert info["name"] == "Alpha" and info["f"] == 1
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    # the action itself wrote NO txn (local execution, no consensus)
    assert pool.nodes["Alpha"].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 1

    # an unprivileged (but registered and validly signed) identity is
    # refused by the authorization check, not the signature check
    nobody = Ed25519Signer(seed=b"action-nobody".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, nobody, 2))
    pool.run(3.0)
    req2 = Request(nobody.identifier, 1, {"type": VALIDATOR_INFO_ACTION})
    req2.signature = nobody.sign_b58(req2.signing_bytes())
    pool.submit(req2, to=["Alpha"])
    pool.run(2.0)
    # well-formed but refused -> REJECT (never NACK: the NACK/REJECT wire
    # split reserves NACK for malformed requests)
    rejects = [m for m, _ in pool.client_msgs["Alpha"]
               if isinstance(m, Reject) and "TRUSTEE" in m.reason]
    assert rejects


def test_observer_catches_up_across_a_gap():
    """An observer that missed pushes pulls the gap via GET_TXN-style
    fetches, then resumes applying pushed batches."""
    from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID,
                                                 BatchCommitted)
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.node.observer import NodeObserver

    pool = Pool()
    node = pool.nodes["Alpha"]
    node.observable.add_observer("obs")
    for i in range(3):
        user = Ed25519Signer(seed=(b"gap-u%d" % i).ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, user, i + 1))
        pool.run(3.0)
    pushes = [m for m, c in pool.client_msgs["Alpha"]
              if isinstance(m, BatchCommitted)]
    assert len(pushes) == 3

    observer = NodeObserver(_observer_components(pool.names))
    # the observer only sees the LAST push: gap -> refused
    assert not observer.process_batch(pushes[-1])

    live = node.c.db.get_ledger(DOMAIN_LEDGER_ID)

    def fetch(ledger_id, seq_no):
        ledger = node.c.db.get_ledger(ledger_id)
        return ledger.get_by_seq_no(seq_no) if seq_no <= ledger.size \
            else None

    # a LYING fetcher is detected by the batch-root check and everything
    # staged is discarded (nothing unverified ever commits)
    import copy

    def lying_fetch(ledger_id, seq_no):
        txn = copy.deepcopy(fetch(ledger_id, seq_no))
        txn["txn"]["data"]["dest"] = "FORGED"
        return txn

    assert not observer.catch_up(pushes[-1], lying_fetch)
    assert observer.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 1

    # the honest fetcher fills the gap and the push applies atomically
    assert observer.catch_up(pushes[-1], fetch)
    obs_ledger = observer.c.db.get_ledger(DOMAIN_LEDGER_ID)
    assert obs_ledger.size == live.size == 4
    assert obs_ledger.root_hash == live.root_hash


def test_perf_metrics_emitted_during_ordering():
    """The perf-debugging metrics of VERDICT r2 item 9 exist and carry
    real values after ordering traffic: per-phase 3PC timings on the
    master, plus depth gauges via the flush path, all visible in
    validator_info."""
    from plenum_tpu.common.metrics import MetricsName
    from plenum_tpu.crypto.ed25519 import Ed25519Signer

    pool = Pool()
    user = Ed25519Signer(seed=b"metrics-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(5.0)

    node = pool.nodes["Alpha"]
    summary = node.metrics.summary()
    for name in (MetricsName.PREPARE_PHASE_TIME,
                 MetricsName.COMMIT_PHASE_TIME,
                 MetricsName.ORDERING_TIME):
        assert name in summary, f"missing {name}: {sorted(summary)}"
        assert summary[name]["count"] >= 1
        assert summary[name]["avg"] >= 0.0
    # the per-batch invariant (order >= prepare) only holds across matched
    # sample sets; a straggler batch that prepared but never ordered would
    # skew averages, so gate on count equality
    if summary[MetricsName.ORDERING_TIME]["count"] == \
            summary[MetricsName.PREPARE_PHASE_TIME]["count"]:
        assert summary[MetricsName.ORDERING_TIME]["sum"] >= \
            summary[MetricsName.PREPARE_PHASE_TIME]["sum"]
    # depth gauges are sampled into the accumulators by the flush path
    # (flush() then clears, so sample manually to inspect)
    node.metrics.add_event(MetricsName.REQUEST_QUEUE_DEPTH, sum(
        len(q) for q in
        node.master_replica.ordering.request_queues.values()))
    info = node.validator_info()
    assert MetricsName.REQUEST_QUEUE_DEPTH in info["metrics"]
    assert MetricsName.ORDERING_TIME in info["metrics"]


# --- notifier events ------------------------------------------------------

def test_notifier_spike_detection_bounds():
    """Spike math follows the reference's historical-bounds model
    (notifier_plugin_manager.py:92-117): no emission until min_cnt history,
    none below the activity floor, emission outside bounds_coeff x avg."""
    from plenum_tpu.node.notifier import (NotifierEventManager, TOPIC_SPIKE)

    events = []
    n = NotifierEventManager(bounds_coeff=3.0, min_cnt=3,
                             min_activity_threshold=5.0)
    n.register_handler(lambda topic, msg: events.append((topic, msg)))
    # building history: never spikes
    for v in (10.0, 11.0, 9.0):
        assert not n.check_throughput(v, "N1", 0.0)
    # inside bounds
    assert not n.check_throughput(12.0, "N1", 1.0)
    # way outside bounds -> spike
    assert n.check_throughput(200.0, "N1", 2.0)
    assert events and events[-1][0] == TOPIC_SPIKE
    assert events[-1][1]["value"] == 200.0
    # below the noise floor nothing fires even if ratio is huge
    quiet = NotifierEventManager(bounds_coeff=3.0, min_cnt=2,
                                 min_activity_threshold=5.0)
    quiet.register_handler(lambda t, m: events.append((t, m)))
    for v in (0.1, 0.2, 0.1, 2.0):
        assert not quiet.check_throughput(v, "N1", 0.0)
    # a broken handler never breaks the send path
    n._handlers.insert(0, lambda t, m: 1 / 0)
    assert n.check_throughput(0.01, "N1", 3.0)


def test_notifier_view_change_event_from_pool():
    """A real view change emits TOPIC_VIEW_CHANGE through the node's
    notifier (ref: viewChange notification wiring)."""
    from plenum_tpu.config import Config
    from plenum_tpu.node.notifier import TOPIC_VIEW_CHANGE
    from plenum_tpu.network import Discard, match_dst, match_frm

    pool = Pool(config=Config(Max3PCBatchWait=0.05,
                              PRIMARY_HEALTH_CHECK_FREQ=0.5,
                              ORDERING_PROGRESS_TIMEOUT=2.0,
                              STATE_FRESHNESS_UPDATE_INTERVAL=3.0))
    events = {n: [] for n in pool.names}
    for name, node in pool.nodes.items():
        node.notifier.register_handler(
            lambda t, m, nm=name: events[nm].append((t, m)))
    for rule in (match_dst("Alpha"), match_frm("Alpha")):
        pool.net.add_rule(Discard(), rule)
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    user = Ed25519Signer(seed=b"notif-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1),
                to=[n for n in pool.names if n != "Alpha"])
    pool.run(20.0)
    for name in pool.names:
        if name == "Alpha":
            continue
        vc = [m for t, m in events[name] if t == TOPIC_VIEW_CHANGE]
        assert vc, f"{name} emitted no view-change notification"
        assert vc[-1]["view_no"] >= 1
