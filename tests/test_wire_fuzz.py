"""Wire-protocol robustness fuzz: malformed, truncated, and random ingress
must never crash a node — malformed node traffic is dropped, malformed
client traffic is NACKed.

Reference test model: the message-validation suites over
messages/fields.py + validateNodeMsg (SURVEY.md §4 message validation).
"""
from __future__ import annotations

import random

import pytest

from plenum_tpu.common.message_base import (MessageValidationError,
                                            message_from_dict)
from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID, PrePrepare
from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.crypto.ed25519 import Ed25519Signer

from test_pool import Pool, signed_nym

N_CASES = 300


def _mutate(rng: random.Random, d):
    """Randomly corrupt a wire dict."""
    d = dict(d)
    op = rng.randrange(5)
    keys = list(d)
    if op == 0 and keys:
        del d[rng.choice(keys)]
    elif op == 1 and keys:
        d[rng.choice(keys)] = rng.choice(
            [None, -1, 2**70, "x" * 50, [], {}, float("nan"), b"\xff"])
    elif op == 2:
        d["op"] = rng.choice(["", "NOPE", 42, None])
    elif op == 3 and keys:
        k = rng.choice(keys)
        d[str(k) + "_extra"] = d.pop(k)
    else:
        d[rng.choice(["view_no", "pp_seq_no", "inst_id"])] = rng.choice(
            [-(2**40), "str", [1, 2], None])
    return d


def test_message_from_dict_never_crashes_on_garbage():
    rng = random.Random(1234)
    base = PrePrepare(inst_id=0, view_no=0, pp_seq_no=1, pp_time=1.0,
                      req_idr=("d",), discarded=(), digest="x",
                      ledger_id=DOMAIN_LEDGER_ID, state_root="", txn_root="",
                      audit_txn_root="").to_dict()
    ok = 0
    for _ in range(N_CASES):
        d = _mutate(rng, base)
        try:
            message_from_dict(d)
            ok += 1
        except MessageValidationError:
            pass                     # the ONLY acceptable failure mode
    # some mutations still validate (extra-field tolerance etc.); most fail
    assert ok < N_CASES


def test_node_survives_garbage_node_traffic():
    rng = random.Random(99)
    pool = Pool()
    node = pool.nodes["Alpha"]
    base = PrePrepare(inst_id=0, view_no=0, pp_seq_no=1, pp_time=1.0,
                      req_idr=("d",), discarded=(), digest="x",
                      ledger_id=DOMAIN_LEDGER_ID, state_root="", txn_root="",
                      audit_txn_root="").to_dict()
    for i in range(N_CASES):
        d = _mutate(rng, base)
        try:
            wire = pack(d)
        except (TypeError, ValueError, OverflowError):
            continue    # not serializable (bytes keys, ints beyond uint64):
            # a real sender could not have produced these bytes either
        try:
            msg = message_from_dict(unpack(wire))
        except MessageValidationError:
            continue                 # the ONLY acceptable decode failure
        # decodable-but-weird messages reach the bus like real traffic
        node.node_bus.process_incoming(msg, rng.choice(pool.names[1:]))
        node.prod()
    # the storm (forged non-primary pre-prepares "from" every peer) gets
    # them all blacklisted — and the TTL must self-heal the isolation
    assert node.blacklister.blacklisted
    pool.timer.advance(130.0)            # past BLACKLIST_TTL
    user = Ed25519Signer(seed=b"fuzz-after".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(10.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}, sizes


def test_propagate_batch_roundtrips_and_rejects_garbage():
    """PropagateBatch (the per-tick propagate coalescing envelope) must
    survive pack/unpack/message_from_dict unchanged, and every mutation
    must fail ONLY with MessageValidationError."""
    from plenum_tpu.common.node_messages import Propagate, PropagateBatch

    body = Propagate(request={"identifier": "A", "reqId": 1,
                              "operation": {"type": "1"}},
                     sender_client="cli-7").to_dict()
    base = PropagateBatch(
        votes=(("d" * 64, "cli-1"), ("e" * 64, None)),
        bodies=(body,)).to_dict()
    # clean round trip through the real wire path
    decoded = message_from_dict(unpack(pack(base)))
    assert isinstance(decoded, PropagateBatch)
    assert decoded.to_dict() == base
    assert decoded.votes[1][1] is None

    rng = random.Random(4242)
    ok = 0
    for _ in range(N_CASES):
        d = _mutate(rng, base)
        try:
            wire = pack(d)
        except (TypeError, ValueError, OverflowError):
            continue
        try:
            message_from_dict(unpack(wire))
            ok += 1
        except MessageValidationError:
            pass                     # the ONLY acceptable failure mode
    assert ok < N_CASES


def test_broadcast_call_sites_pack_once():
    """Guard against per-peer pack() regressions on broadcast paths: the
    node-stack outbox and the client-stack send_many must serialize a
    message ONCE no matter how many recipients it fans out to."""
    import inspect

    from plenum_tpu.network import tcp_stack as ts

    # source-level: no pack( inside the per-peer fan-out loops
    src = inspect.getsource(ts.TcpStack._enqueue_send)
    loop_body = src.split("for peer in targets", 1)[1]
    assert "pack(" not in loop_body, \
        "TcpStack._enqueue_send re-packs per peer"
    prop_src = inspect.getsource(ts.ClientStack._send_packed)
    assert "pack(" not in prop_src, \
        "ClientStack._send_packed must take pre-packed bytes"

    # functional: ClientStack.send_many packs once for N live clients
    class _W:                                   # fake asyncio writer
        class _T:
            @staticmethod
            def get_write_buffer_size():
                return 0
        transport = _T()

        def __init__(self):
            self.wrote = []

        def write(self, data):
            self.wrote.append(data)

    stack = ts.ClientStack("N1", "127.0.0.1", 0, on_request=lambda m, f: None)
    for i in range(5):
        stack._conns[f"client-{i}"] = _W()
    calls = {"n": 0}
    real_pack = ts.pack

    def counting_pack(obj):
        calls["n"] += 1
        return real_pack(obj)

    ts.pack = counting_pack
    try:
        stack.send_many({"op": "REPLY", "result": {"x": 1}},
                        [f"client-{i}" for i in range(5)])
    finally:
        ts.pack = real_pack
    assert calls["n"] == 1, f"send_many packed {calls['n']}x for 5 clients"
    assert sum(len(w.wrote) for w in stack._conns.values()) == 5


def test_node_nacks_garbage_client_traffic():
    rng = random.Random(7)
    pool = Pool()
    node = pool.nodes["Alpha"]
    for i in range(100):
        junk = rng.choice([
            {}, {"op": "x"}, {"identifier": 1}, {"reqId": None},
            {"identifier": "A", "reqId": i, "operation": "notadict"},
            {"identifier": "A", "reqId": i, "operation": {"type": None}},
            {"identifier": None, "reqId": [], "operation": {}},
        ])
        node.handle_client_message(dict(junk), f"cli{i}")
        node.prod()
    from plenum_tpu.common.node_messages import RequestNack
    assert pool.replies("Alpha", RequestNack)
    # and the node still serves real traffic
    user = Ed25519Signer(seed=b"fuzz-client".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(5.0)
    assert pool.nodes["Alpha"].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2


# --- proof-bearing REPLY envelope (verified read plane) -------------------

def _proof_bearing_result():
    """One committed NYM + a proof-enveloped GET_NYM result, plus the
    verification context (pool keys, sim clock)."""
    from plenum_tpu.common.request import Request
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.tools.local_pool import pool_bls_keys

    pool = Pool(seed=31)
    user = Ed25519Signer(seed=b"wirefuzz-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(6.0)
    q = Request("wf", 1, {"type": GET_NYM, "dest": user.identifier})
    result = pool.nodes["Alpha"].read_plane.answer(q)
    return pool, q, result, pool_bls_keys(pool.names)


def _corrupt_tree(rng: random.Random, obj):
    """One random structural corruption somewhere in a nested dict/list:
    drop a key, retype a value, truncate/flip a hex string, or splice in
    garbage. Returns a deep-copied corrupted twin."""
    import copy
    obj = copy.deepcopy(obj)

    def nodes(o, path=()):
        yield o, path
        if isinstance(o, dict):
            for k, v in o.items():
                yield from nodes(v, path + (k,))
        elif isinstance(o, list):
            for i, v in enumerate(o):
                yield from nodes(v, path + (i,))

    def set_at(o, path, value):
        for p in path[:-1]:
            o = o[p]
        o[path[-1]] = value

    def del_at(o, path):
        for p in path[:-1]:
            o = o[p]
        del o[path[-1]]

    candidates = [(n, p) for n, p in nodes(obj) if p]
    node, path = candidates[rng.randrange(len(candidates))]
    op = rng.randrange(4)
    if op == 0:
        del_at(obj, path)
    elif op == 1:
        set_at(obj, path, rng.choice(
            [None, -1, 2 ** 70, "zz", [], {}, True, b"\xff" * 4]))
    elif op == 2 and isinstance(node, str) and len(node) > 2:
        cut = rng.randrange(1, len(node))
        set_at(obj, path, node[:cut])            # truncation
    elif isinstance(node, str) and node:
        i = rng.randrange(len(node))
        repl = "0" if node[i] != "0" else "f"
        set_at(obj, path, node[:i] + repl + node[i + 1:])  # flip
    else:
        set_at(obj, path, "garbage")
    return obj


def _proof_bearing_result_verkle():
    """The Verkle twin of _proof_bearing_result: a pool whose domain
    state rides the wide-commitment backend, so the GET_NYM reply
    carries a ``verkle`` envelope (aggregated opening, no per-entry
    proof field)."""
    from plenum_tpu.common.request import Request
    from plenum_tpu.config import Config
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.tools.local_pool import pool_bls_keys

    pool = Pool(seed=33, config=Config(Max3PCBatchWait=0.05,
                                       STATE_COMMITMENT="verkle"))
    user = Ed25519Signer(seed=b"wirefuzz-vk-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(6.0)
    q = Request("wf", 1, {"type": GET_NYM, "dest": user.identifier})
    result = pool.nodes["Alpha"].read_plane.answer(q)
    return pool, q, result, pool_bls_keys(pool.names)


def test_verkle_envelope_roundtrips_and_fails_closed():
    """Same contract reads/proofs.py pins for MPT, for the new kind: the
    verkle envelope survives the wire roundtrip verbatim and STILL
    verifies; ~300 random corruptions of the envelope (or the result it
    binds) each verify False — never raise, never True unless the
    corruption was an exact no-op."""
    from plenum_tpu.common.node_messages import Reply
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.reads import READ_PROOF, verify_read_proof

    pool, q, result, keys = _proof_bearing_result_verkle()
    now = pool.timer.get_current_time
    assert result[READ_PROOF]["kind"] == "verkle", \
        "verkle-backed pool served a non-verkle envelope"

    wire = unpack(pack(Reply(result=result).to_dict()))
    rt_result = wire["result"]
    ok, reason = verify_read_proof(GET_NYM, q.operation, rt_result, keys,
                                   freshness_s=1e12, now=now)
    assert ok, f"roundtrip broke verkle verification: {reason}"

    rng = random.Random(31337)
    verified = rejected = 0
    for _ in range(N_CASES):
        bad = _corrupt_tree(rng, rt_result)
        try:
            ok, reason = verify_read_proof(GET_NYM, q.operation, bad,
                                           keys, freshness_s=1e12,
                                           now=now)
        except Exception as e:           # pragma: no cover
            raise AssertionError(
                f"verify_read_proof raised {type(e).__name__} on "
                f"corrupted verkle envelope") from e
        if ok:
            assert bad.get(READ_PROOF) == rt_result.get(READ_PROOF) \
                and {k: v for k, v in bad.items()
                     if k not in ("identifier", "reqId")} \
                == {k: v for k, v in rt_result.items()
                    if k not in ("identifier", "reqId")}, \
                f"corrupted verkle envelope VERIFIED: {bad}"
            verified += 1
        else:
            rejected += 1
    assert rejected > N_CASES // 2       # most corruptions must reject


def test_read_proof_envelope_roundtrips_and_fails_closed():
    """The proof-bearing REPLY survives the wire roundtrip verbatim and
    STILL verifies; any corruption of the envelope (or of the result it
    binds) must verify False — never raise, and never verify unless the
    corruption was a no-op."""
    from plenum_tpu.common.node_messages import Reply
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.reads import READ_PROOF, verify_read_proof

    pool, q, result, keys = _proof_bearing_result()
    now = pool.timer.get_current_time

    # wire roundtrip: pack -> unpack -> still verifies
    wire = unpack(pack(Reply(result=result).to_dict()))
    rt_result = wire["result"]
    ok, reason = verify_read_proof(GET_NYM, q.operation, rt_result, keys,
                                   freshness_s=1e12, now=now)
    assert ok, f"roundtrip broke verification: {reason}"

    rng = random.Random(4242)
    verified = rejected = 0
    for _ in range(N_CASES):
        bad = _corrupt_tree(rng, rt_result)
        try:
            ok, reason = verify_read_proof(GET_NYM, q.operation, bad,
                                           keys, freshness_s=1e12,
                                           now=now)
        except Exception as e:           # pragma: no cover
            raise AssertionError(
                f"verify_read_proof raised {type(e).__name__} on "
                f"corrupted envelope") from e
        if ok:
            # only acceptable when the corruption didn't change anything
            # the verifier reads (e.g. a legacy state_proof field)
            assert bad.get(READ_PROOF) == rt_result.get(READ_PROOF) \
                and {k: v for k, v in bad.items()
                     if k not in ("identifier", "reqId")} \
                == {k: v for k, v in rt_result.items()
                    if k not in ("identifier", "reqId")}, \
                f"corrupted envelope VERIFIED: {bad}"
            verified += 1
        else:
            rejected += 1
    assert rejected > N_CASES // 2       # most corruptions must reject
