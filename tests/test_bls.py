"""BLS over BN254: pairing properties, sign/verify, aggregation, PoP,
random-linear-combination batch verification
(ref crypto/bls/indy_crypto/bls_crypto_indy_crypto.py behavior)."""
import pytest

from plenum_tpu.crypto import bls as bls_mod
from plenum_tpu.crypto import bn254 as c
from plenum_tpu.crypto.bls import (BlsCryptoSigner, BlsCryptoVerifier,
                                   BlsSignKey, aggregate_sigs,
                                   batch_verify_combined, g1_from_bytes,
                                   g1_to_bytes, verify, verify_multi_sig,
                                   verify_pop)
from plenum_tpu.crypto.multi_signature import (MultiSignature,
                                               MultiSignatureValue)
from plenum_tpu.utils.base58 import b58decode, b58encode

# Pure-Python pairings run ~10-200x the native multi-pairing; when the
# in-tree C++ toolchain is absent, the pairing-HEAVY property tests (many
# pairings per test) move out of tier-1 so the 870 s budget holds. With
# the native lib built they cost milliseconds and stay in tier-1.
pairing_heavy = pytest.mark.slow if c._NATIVE is None else (lambda f: f)


@pairing_heavy
def test_pairing_bilinearity():
    a, b = 31337, 271828
    e = c.pairing(c.G2_GEN, c.G1_GEN)
    lhs = c.pairing(c.g2_mul(c.G2_GEN, a), c.g1_mul(c.G1_GEN, b))
    assert lhs == c.f12_pow(e, a * b % c.R)
    assert e != c.F12_ONE


def test_group_orders():
    assert c.g1_mul(c.G1_GEN, c.R) is None
    assert c.g2_mul(c.G2_GEN, c.R) is None
    assert c.g2_in_subgroup(c.G2_GEN)


def test_hash_to_g1_deterministic_and_valid():
    p1 = c.hash_to_g1(b"state-root-1")
    p2 = c.hash_to_g1(b"state-root-1")
    p3 = c.hash_to_g1(b"state-root-2")
    assert p1 == p2 != p3
    assert c.g1_is_on_curve(p1) and c.g1_is_on_curve(p3)


def test_sign_verify_roundtrip():
    key = BlsSignKey(seed=b"\x01" * 32)
    sig = key.sign(b"message")
    assert verify(sig, b"message", key.verkey)
    assert not verify(sig, b"other", key.verkey)
    other = BlsSignKey(seed=b"\x02" * 32)
    assert not verify(sig, b"message", other.verkey)


def test_signing_is_deterministic():
    k1 = BlsSignKey(seed=b"\x07" * 32)
    k2 = BlsSignKey(seed=b"\x07" * 32)
    assert k1.verkey == k2.verkey
    assert k1.sign(b"m") == k2.sign(b"m")


@pairing_heavy
def test_multi_sig_aggregate_and_verify():
    keys = [BlsSignKey(seed=bytes([i]) * 32) for i in range(1, 5)]
    msg = b"the-state-root"
    agg = aggregate_sigs([k.sign(msg) for k in keys])
    vks = [k.verkey for k in keys]
    assert verify_multi_sig(agg, msg, vks)
    # missing participant -> fail
    assert not verify_multi_sig(agg, msg, vks[:3])
    # wrong message -> fail
    assert not verify_multi_sig(agg, b"x", vks)
    # aggregated sig is not a valid single sig for any one key
    assert not verify(agg, msg, vks[0])


def test_proof_of_possession():
    key = BlsSignKey(seed=b"\x09" * 32)
    pop = key.generate_pop()
    assert verify_pop(pop, key.verkey)
    other = BlsSignKey(seed=b"\x0a" * 32)
    assert not verify_pop(pop, other.verkey)
    # a message signature must not double as a PoP (domain separation)
    assert not verify_pop(key.sign(b58 := key.verkey.encode()), key.verkey)


def test_provider_seam():
    signer = BlsCryptoSigner(seed=b"\x11" * 32)
    verifier = BlsCryptoVerifier()
    sig = signer.sign(b"root")
    assert verifier.verify_sig(sig, b"root", signer.pk)
    signer2 = BlsCryptoSigner(seed=b"\x12" * 32)
    agg = verifier.create_multi_sig([sig, signer2.sign(b"root")])
    assert verifier.verify_multi_sig(agg, b"root", [signer.pk, signer2.pk])
    assert verifier.verify_key_proof_of_possession(signer.generate_pop(),
                                                   signer.pk)


def test_garbage_inputs_rejected_not_raised():
    key = BlsSignKey(seed=b"\x13" * 32)
    assert not verify("not-base58-!!!", b"m", key.verkey)
    assert not verify(key.sign(b"m"), b"m", "bogus-verkey")
    assert not verify_multi_sig(key.sign(b"m"), b"m", [])


def test_multi_signature_value_roundtrip():
    value = MultiSignatureValue(1, "sr", "psr", "tr", 1234.5)
    ms = MultiSignature("sig58", ("Alpha", "Beta"), value)
    assert MultiSignature.from_list(ms.to_list()) == ms
    assert b"state_root_hash" in value.as_single_value()


def test_duplicate_participant_multisig_rejected():
    """A single colluding node's signature aggregated with itself must NOT
    pass as a quorum multi-sig (rogue self-aggregation)."""
    from plenum_tpu.common.node_messages import PrePrepare
    from plenum_tpu.common.quorums import Quorums
    from plenum_tpu.consensus.bls_bft_replica import (BlsBftReplica,
                                                      BlsKeyRegister)
    from plenum_tpu.crypto.bls import (BlsCryptoSigner, BlsCryptoVerifier,
                                       aggregate_sigs)
    from plenum_tpu.crypto.multi_signature import (MultiSignature,
                                                   MultiSignatureValue)

    signer = BlsCryptoSigner(seed=b"X".ljust(32, b"\0"))
    register = BlsKeyRegister({"X": signer.pk, "Y": "no", "Z": "no", "W": "no"})
    replica = BlsBftReplica(node_name="Y", bls_signer=None,
                            bls_verifier=BlsCryptoVerifier(),
                            key_register=register, quorums=Quorums(4))
    value = MultiSignatureValue(1, "aa", "bb", "cc", 1.0)
    sig = signer.sign(value.as_single_value())
    forged = MultiSignature(signature=aggregate_sigs([sig, sig, sig]),
                            participants=("X", "X", "X"), value=value)
    pp = PrePrepare(inst_id=0, view_no=0, pp_seq_no=2, pp_time=1.0,
                    req_idr=(), discarded=(), digest="d", ledger_id=1,
                    state_root="aa", txn_root="cc", pool_state_root="bb",
                    audit_txn_root="", bls_multi_sig=tuple(forged.to_list()))
    assert replica.validate_pre_prepare(pp, "X") == \
        BlsBftReplica.PPR_BLS_MULTISIG_WRONG


# --- batched (random-linear-combination) verification ------------------------

@pairing_heavy
def test_batch_verify_one_forged_fails_combined_and_names_culprit():
    """The soundness satellite: ONE forged Commit signature in an n-sig
    batch must fail the combined check, and the per-signature fallback must
    name exactly the culprit."""
    keys = [BlsSignKey(seed=bytes([40 + i]) * 32) for i in range(6)]
    msg = b"batch-root-forged"
    items = [(k.sign(msg), msg, k.verkey) for k in keys]
    assert batch_verify_combined(items)
    forged = list(items)
    forged[3] = (keys[3].sign(b"a DIFFERENT value"), msg, keys[3].verkey)
    assert not batch_verify_combined(forged)
    verdicts = BlsCryptoVerifier().batch_verify(forged)
    assert verdicts == [True, True, True, False, True, True]


@pairing_heavy
def test_batch_coefficients_fresh_per_batch(monkeypatch):
    """No replayable combination: the random coefficients must be freshly
    derived on EVERY batch check (an adversary who learns one batch's
    coefficients must gain nothing against the next)."""
    drawn = []
    orig = bls_mod.batch_coefficients
    monkeypatch.setattr(bls_mod, "batch_coefficients",
                        lambda n: drawn.append(orig(n)) or drawn[-1])
    keys = [BlsSignKey(seed=bytes([50 + i]) * 32) for i in range(3)]
    msg = b"batch-root-fresh"
    items = [(k.sign(msg), msg, k.verkey) for k in keys]
    assert batch_verify_combined(items)
    assert batch_verify_combined(items)
    assert len(drawn) == 2 and drawn[0] != drawn[1], \
        "coefficients must differ between two checks of the SAME batch"
    assert all(len(set(cs)) == len(cs) and all(r > 0 for r in cs)
               for cs in drawn)


@pairing_heavy
def test_batch_verify_rejects_cancelling_pair():
    """Why RLC instead of plain aggregation: a signature pair doctored as
    (σ₁+δ, σ₂-δ) still aggregates to the honest sum — plain multi-sig
    verification accepts it — but neither signature is individually valid,
    and the fresh-coefficient combination must reject the pair."""
    k1, k2 = BlsSignKey(seed=b"\x61" * 32), BlsSignKey(seed=b"\x62" * 32)
    msg = b"batch-root-cancel"
    s1 = g1_from_bytes(b58decode(k1.sign(msg)))
    s2 = g1_from_bytes(b58decode(k2.sign(msg)))
    delta = c.g1_mul(c.G1_GEN, 987654321)
    t1 = b58encode(g1_to_bytes(c.g1_add(s1, delta)))
    t2 = b58encode(g1_to_bytes(c.g1_add(s2, c.g1_neg(delta))))
    # plain aggregation is blind to the doctoring...
    assert verify_multi_sig(aggregate_sigs([t1, t2]), msg,
                            [k1.verkey, k2.verkey])
    # ...the random-linear-combination check is not
    assert not batch_verify_combined([(t1, msg, k1.verkey),
                                      (t2, msg, k2.verkey)])
    verdicts = BlsCryptoVerifier().batch_verify([(t1, msg, k1.verkey),
                                                 (t2, msg, k2.verkey)])
    assert verdicts == [False, False]


@pairing_heavy
def test_batch_verify_distinct_messages_one_check():
    """Mixed-message batches still settle in ONE pairing_check of n+1
    pairings (one per distinct message + the combined-signature pair)."""
    keys = [BlsSignKey(seed=bytes([70 + i]) * 32) for i in range(4)]
    items = [(k.sign(b"msg-%d" % i), b"msg-%d" % i, k.verkey)
             for i, k in enumerate(keys)]
    before = dict(c.PAIRING_STATS)
    assert batch_verify_combined(items)
    assert c.PAIRING_STATS["checks"] - before["checks"] == 1
    assert c.PAIRING_STATS["pairings"] - before["pairings"] == len(items) + 1


def test_batch_verify_malformed_input_is_false_not_raise():
    key = BlsSignKey(seed=b"\x44" * 32)
    msg = b"batch-root-malformed"
    items = [(key.sign(msg), msg, key.verkey),
             ("not-base58-!!!", msg, key.verkey),
             (key.sign(msg), msg, "bogus-verkey")]
    verdicts = BlsCryptoVerifier().batch_verify(items)
    assert verdicts == [True, False, False]
    assert not batch_verify_combined(items)


@pairing_heavy
def test_order_time_bad_signer_evicted():
    """Deferred COMMIT verification: one combined pairing check on the happy
    path; on failure, the per-signature fallback isolates the liar, reports
    it, and still produces a quorum multi-sig from the honest remainder."""
    from plenum_tpu.common.node_messages import Commit, PrePrepare
    from plenum_tpu.common.quorums import Quorums
    from plenum_tpu.consensus.bls_bft_replica import (BlsBftReplica,
                                                      BlsKeyRegister)

    signers = {n: BlsCryptoSigner(seed=n.encode().ljust(32, b"\0"))
               for n in "ABCD"}
    register = BlsKeyRegister({n: s.pk for n, s in signers.items()})
    replica = BlsBftReplica(node_name="A", bls_signer=signers["A"],
                            bls_verifier=BlsCryptoVerifier(),
                            key_register=register, quorums=Quorums(4))
    reported = []
    replica.report_bad_signature = reported.append

    pp = PrePrepare(inst_id=0, view_no=0, pp_seq_no=1, pp_time=1.0,
                    req_idr=(), discarded=(), digest="d", ledger_id=1,
                    state_root="aa", txn_root="cc", pool_state_root="bb")
    value = replica._signed_value(pp).as_single_value()
    # D signs the WRONG value (equivocating or buggy)
    sigs = {n: signers[n].sign(value) for n in "ABC"}
    sigs["D"] = signers["D"].sign(b"something else entirely")
    for n, s in sigs.items():
        replica.process_commit(
            Commit(inst_id=0, view_no=0, pp_seq_no=1, bls_sig=s), n)

    ms = replica.process_order((0, 1), pp)
    assert ms is not None, "honest quorum should still yield a multi-sig"
    assert set(ms.participants) == {"A", "B", "C"}
    assert reported == ["D"]
    assert verify_multi_sig(ms.signature, value,
                            [signers[n].pk for n in "ABC"])


def test_order_time_all_honest_single_check():
    """Happy path: the whole COMMIT set settles in ONE combined pairing
    check of 2 pairings — amortized O(1) in pool size, the figure the
    bench's pairings_per_batch counter reports."""
    from plenum_tpu.common.node_messages import Commit, PrePrepare
    from plenum_tpu.common.quorums import Quorums
    from plenum_tpu.consensus.bls_bft_replica import (BlsBftReplica,
                                                      BlsKeyRegister)

    signers = {n: BlsCryptoSigner(seed=n.encode().ljust(32, b"\0"))
               for n in "ABCD"}
    register = BlsKeyRegister({n: s.pk for n, s in signers.items()})
    verifier = BlsCryptoVerifier()
    replica = BlsBftReplica(node_name="A", bls_signer=signers["A"],
                            bls_verifier=verifier,
                            key_register=register, quorums=Quorums(4))
    # roots distinct from every other test in this module: the process-wide
    # verdict cache would otherwise settle the batch without any pairing
    pp = PrePrepare(inst_id=0, view_no=0, pp_seq_no=1, pp_time=1.0,
                    req_idr=(), discarded=(), digest="d", ledger_id=1,
                    state_root="a-single", txn_root="c-single",
                    pool_state_root="b-single")
    value = replica._signed_value(pp).as_single_value()
    for n in "ABCD":
        replica.process_commit(
            Commit(inst_id=0, view_no=0, pp_seq_no=1,
                   bls_sig=signers[n].sign(value)), n)
    before = dict(c.PAIRING_STATS)
    ms = replica.process_order((0, 1), pp)
    assert ms is not None and len(ms.participants) == 4
    assert c.PAIRING_STATS["checks"] - before["checks"] == 1, \
        "expected ONE combined pairing check for the whole COMMIT set"
    assert c.PAIRING_STATS["pairings"] - before["pairings"] == 2, \
        "same-message batch must cost 2 pairings regardless of n"
