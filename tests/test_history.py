"""Fleet history plane: durable time-series ring, growth verdicts,
footprint accounting, and the perf-regression sentinel.

Covers observability/history.py (HistoryRecorder + GrowthWatch), the
node's resource-footprint gauges (Node.footprint() -> telemetry
"footprint" section -> aggregator growth trends), the history ring's
replay determinism (the telemetry twin of the tracer guard), the
correlate.py control-ledger + history-context merge, and
tools/perf_sentinel.py's variance-aware regression gating over the
repo's own BENCH_r*.json trajectory.
"""
import json
import os

from plenum_tpu.common.metrics import MetricsName
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.observability import (GROWTH_EXEMPT_GAUGES,
                                      FleetAggregator, GrowthWatch,
                                      HistoryRecorder, linear_slope)

from test_pool import Pool, signed_nym

FAST = dict(Max3PCBatchWait=0.05, TELEMETRY_INTERVAL=0.5)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- growth verdicts --------------------------------------------------------

def test_linear_slope_units_and_degenerate_inputs():
    assert linear_slope([(0.0, 0.0), (10.0, 50.0)]) == 5.0
    assert linear_slope([(0.0, 3.0)]) is None            # one point
    assert linear_slope([(2.0, 1.0), (2.0, 9.0)]) is None  # zero t-spread
    assert abs(linear_slope([(t, 7.0) for t in range(10)])) < 1e-12


def test_growthwatch_three_gates():
    """bounded / growing / insufficient, and the two quiet gates: a
    gauge below its absolute floor never pages, and a gauge breathing
    within a fraction of its level never pages."""
    w = GrowthWatch(window=60.0, min_points=4, floor=64.0, fraction=0.5)
    assert w.verdict("missing")["verdict"] == "insufficient"
    for i in range(3):
        w.note("young", float(i), 100.0 + i)
    assert w.verdict("young")["verdict"] == "insufficient"
    # a steep ramp that is still TINY (below floor) stays quiet
    for i in range(10):
        w.note("tiny", float(i), 2.0 * i)         # ends at 18 < 64
    assert w.verdict("tiny")["verdict"] == "bounded"
    # a large gauge breathing within its level stays quiet
    for i in range(10):
        w.note("breathing", float(i), 5000.0 + (i % 3))
    assert w.verdict("breathing")["verdict"] == "bounded"
    # a real leak: outruns both floor and fraction-of-mean
    for i in range(10):
        w.note("leak", float(i) * 6.0, 64.0 + 40.0 * i)
    v = w.verdict("leak")
    assert v["verdict"] == "growing" and v["slope_per_s"] > 0
    assert "kv_entries" in GROWTH_EXEMPT_GAUGES


def test_growthwatch_projects_over_observed_span_not_full_window():
    """Ten samples spanning 9 s must not be extrapolated over a 120 s
    window — a sawtooth phase at cold start would page on noise."""
    w = GrowthWatch(window=120.0, min_points=8, floor=64.0, fraction=0.5)
    for i in range(10):
        w.note("saw", float(i), 120.0 + (i % 5) * 8)
    v = w.verdict("saw")
    assert v["verdict"] == "bounded", v
    # projected reflects the 9 s span (slope ~1.9/s -> ~17), not 120 s
    assert v["projected"] < 64.0

def test_growthwatch_per_gauge_floors():
    w = GrowthWatch(window=60.0, min_points=4, floor=64.0,
                    floors={"ring": 4097.0})
    for i in range(10):
        w.note("ring", float(i) * 6.0, 100.0 + 300.0 * i)   # cold fill
        w.note("other", float(i) * 6.0, 100.0 + 300.0 * i)
    assert w.verdict("ring")["verdict"] == "bounded"     # below its cap
    assert w.verdict("other")["verdict"] == "growing"
    assert set(w.verdicts()) == {"ring", "other"}


# --- the history ring -------------------------------------------------------

def test_history_ring_bounds_and_slot_rotation(tmp_path):
    rec = HistoryRecorder(dir=str(tmp_path), max_slots=8)
    for i in range(20):
        rec.append({"t": float(i), "tps": i * 10})
    assert len(rec.rows) == 8 and rec.seq == 20
    files = sorted(tmp_path.glob("history-*.json"))
    assert len(files) == 8                       # rotating slot window
    assert not list(tmp_path.glob("*.tmp"))      # atomic: no torn leftovers
    newest = max(json.loads(f.read_text())["seq"] for f in files)
    assert newest == 19
    # every in-memory row carries schema version + seq
    assert all(r["v"] == 1 for r in rec.rows)


def test_history_spool_survives_midwrite_crash(tmp_path, monkeypatch):
    """A crash between tmp-write and rename must leave the previous
    slot content intact, and load() must skip torn files."""
    rec = HistoryRecorder(dir=str(tmp_path), max_slots=4)
    rec.append({"t": 0.0, "tps": 1})
    real_replace = os.replace

    def crashy(src, dst):
        raise OSError("disk gone mid-rename")
    monkeypatch.setattr(os, "replace", crashy)
    rec.append({"t": 1.0, "tps": 2})             # spool fails, no raise
    monkeypatch.setattr(os, "replace", real_replace)
    assert len(rec.rows) == 2                    # in-memory ring unharmed
    on_disk = json.loads((tmp_path / "history-0.json").read_text())
    assert on_disk["seq"] == 0                   # old row still whole
    # a torn file (half-written JSON) is skipped on load
    (tmp_path / "history-2.json").write_text('{"seq": 2, "t":')
    loaded = HistoryRecorder.load(str(tmp_path), max_slots=4)
    assert [r["seq"] for r in loaded.rows] == [0]
    assert loaded.seq == 1


def test_history_query_windowing_and_downsample():
    rec = HistoryRecorder(max_slots=256)
    for i in range(100):
        rec.append({"t": float(i), "tps": i})
    assert [r["t"] for r in rec.window(10.0, 12.0)] == [10.0, 11.0, 12.0]
    picked = rec.query(max_points=10)
    assert len(picked) == 10
    assert picked[0]["t"] == 0.0 and picked[-1]["t"] == 99.0
    assert [r["t"] for r in picked] == sorted(r["t"] for r in picked)
    assert rec.query(max_points=1) == [rec.rows[-1]]
    # byte-canonical serialization exists and is stable
    assert rec.history_bytes() == rec.history_bytes()


def _seeded_history_run():
    pool = Pool(seed=7, config=Config(**FAST))
    for node in pool.nodes.values():
        node.telemetry.wall_sums = False
    agg = FleetAggregator(config=pool.config)
    agg.attach_history(HistoryRecorder(max_slots=128))
    for node in pool.nodes.values():
        node.telemetry.add_sink(agg.ingest)
    u = Ed25519Signer(seed=b"hist-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u, 1))
    pool.run(8.0)
    return agg


def test_history_ring_replay_determinism():
    """The SAME seeded sim run twice produces a byte-identical history
    ring (wall_sums=False strips RSS + the process-wide verdict cache —
    the non-replayable gauges). The telemetry twin of the tracer's
    wall_durations guard, extended to the fleet row."""
    a, b = _seeded_history_run(), _seeded_history_run()
    assert a.history.history_bytes() == b.history.history_bytes()
    assert len(a.history.rows) > 5
    row = a.history.rows[-1]
    assert row["nodes"] == 4
    fp = row["footprint"]
    assert "process_rss_bytes" not in fp         # stripped for replay
    assert "bls_verdict_cache_entries" not in fp
    assert fp["kv_entries"] > 0


# --- footprint gauges -------------------------------------------------------

def test_node_footprint_gauges_and_metrics_flush():
    pool = Pool(config=Config(**FAST))
    u = Ed25519Signer(seed=b"fp-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u, 1))
    pool.run(8.0)
    alpha = pool.nodes["Alpha"]
    fp = alpha.footprint()
    for gauge in ("kv_entries", "kv_disk_bytes", "flight_ring_entries",
                  "stashed_entries", "request_state_entries",
                  "dedup_map_entries", "read_cache_entries",
                  "vc_vote_entries", "bls_sig_entries",
                  "bls_verdict_cache_entries"):
        assert isinstance(fp[gauge], int), gauge
    assert fp["kv_entries"] > 0
    # the flush-cadence sampler lands the gauges in the metrics
    # namespace (the sim pool's plain collector never flushes, so
    # drive the sampler directly)
    alpha._sample_footprint_gauges()
    summary = alpha.metrics.summary()
    assert MetricsName.FOOTPRINT_KV_ENTRIES in summary
    assert MetricsName.FOOTPRINT_FLIGHT_RING in summary
    # and the telemetry snapshot ships the footprint section
    snap = alpha.telemetry.ring[-1]
    state_fp = snap["state"]["footprint"]
    assert state_fp["kv_entries"] == pool.nodes["Alpha"].footprint()["kv_entries"]
    assert "process_rss_bytes" in state_fp       # wall_sums=True default


def test_aggregator_fleet_footprint_and_growth_in_summary():
    pool = Pool(config=Config(**FAST))
    agg = FleetAggregator(config=pool.config)
    for node in pool.nodes.values():
        node.telemetry.add_sink(agg.ingest)
    u = Ed25519Signer(seed=b"sum-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u, 1))
    pool.run(10.0)
    summary = agg.fleet_summary()
    fp = summary["footprint"]
    assert fp["kv_entries"] > 0
    verdicts = agg.growth_verdicts()
    assert set(verdicts) >= {"kv_entries", "flight_ring_entries"}
    # a healthy pool: no unbounded_growth alert fired
    assert not [a for a in agg.alerts if a.kind == "unbounded_growth"]


# --- correlate: control ledger + history context ----------------------------

def test_incident_timeline_merges_control_and_history():
    from plenum_tpu.observability.correlate import (format_incidents,
                                                    incident_timelines)
    hist = HistoryRecorder(max_slots=32)
    for i in range(10):
        hist.append({"t": float(i), "tps": 100 + i, "health_min": 1.0})
    alerts = [{"t": 9.5, "kind": "slo_burn.ordering", "subject": "pool",
               "severity": "page", "detail": {}}]
    control = [{"t": 9.8, "policy": "burn", "action": "rate_limit",
                "subject": "pool", "evidence": {}, "cites": []}]
    incidents = incident_timelines([], alerts=alerts, control=control)
    assert len(incidents) == 1
    kinds = incidents[0]["kinds"]
    assert kinds == {"alert.slo_burn.ordering": 1, "control.rate_limit": 1}
    # with a history ring attached, the incident carries walk-in context
    incidents = incident_timelines([], alerts=alerts, control=control,
                                   history=hist, history_n=3)
    ctx = incidents[0]["history"]
    assert [r["t"] for r in ctx] == [7.0, 8.0, 9.0]
    lines = format_incidents(incidents)
    assert any("walked in from:" in ln for ln in lines)


# --- perf sentinel ----------------------------------------------------------

def test_perf_sentinel_self_check():
    from plenum_tpu.tools import perf_sentinel
    assert perf_sentinel.self_check() == []
    assert perf_sentinel.main(["--check"]) == 0


def test_perf_sentinel_repo_trajectory_no_false_regressions():
    """Over the repo's own BENCH_r01..r05 history the sentinel must
    emit ZERO regression verdicts: the r01->r02 headline drop is an
    honesty switch (in-process -> TCP, different headline_config ->
    not_comparable) and the r04->r05 reads drop has no spread baseline
    (-> warn at most)."""
    from plenum_tpu.tools import perf_sentinel
    rep = perf_sentinel.report(bench_dir=REPO_ROOT)
    assert len(rep["rows"]) >= 5
    assert rep["regressions"] == [], rep["regressions"]
    assert any(v["verdict"] == "not_comparable"
               for v in rep["verdicts"] if v["config"] == "headline")
    # legacy rounds predate provenance tagging: the lint must say so
    assert any("jax_source" in p for p in rep["lint"])


def test_perf_sentinel_flags_synthetic_regression_and_gates_single_pass():
    from plenum_tpu.tools.perf_sentinel import verdicts
    base = {"label": "r1", "configs": {"tcp": {
        "value": 1000.0, "spread_frac": 0.1}}}
    cliff = {"label": "r2", "configs": {"tcp": {"value": 500.0}}}
    vs = verdicts([base, cliff])
    assert [v["verdict"] for v in vs] == ["regression"]
    # the same cliff off a single-pass (no spread) baseline caps at warn
    vs = verdicts([{"label": "r1", "configs": {"tcp": {"value": 1000.0}}},
                   cliff])
    assert [v["verdict"] for v in vs] == ["warn"]


def test_perf_sentinel_trajectory_append_roundtrip(tmp_path):
    from plenum_tpu.tools.perf_sentinel import append_trajectory, load_rows
    path = str(tmp_path / "BENCH_trajectory.jsonl")
    parsed = {"tcp_tps": 1234.0, "headline": 1234.0,
              "headline_config": "tcp", "jax_source": "none",
              "host_cores": 8}
    row = append_trajectory(parsed, path, label="run-x")
    assert row["configs"]["tcp"]["value"] == 1234.0
    rows = load_rows(bench_dir=str(tmp_path), trajectory=path)
    assert rows[-1]["label"] == "run-x"
    assert rows[-1]["jax_source"] == "none"
    from plenum_tpu.tools.perf_sentinel import lint_provenance
    assert lint_provenance([rows[-1]]) == []
