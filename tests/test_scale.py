"""25-node / f=8 pool (the largest BASELINE.json config) + mixed load.

Reference analog: the 25-node replay scenario — quorum math, propagate
fan-out, and BLS aggregation at n=25 are qualitatively different from the
4-node slice (f+1=9 protocol instances, 17-signature aggregates), so a
pool this wide must order writes and stay consistent end to end.
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID, Reply
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.execution.txn import GET_NYM

from test_pool import Pool, signed_nym

TWENTY_FIVE = [f"N{i:02d}" for i in range(25)]


@pytest.mark.slow
def test_twenty_five_node_pool_orders_and_agrees():
    pool = Pool(names=TWENTY_FIVE, config=Config(
        Max3PCBatchWait=0.05, STATE_FRESHNESS_UPDATE_INTERVAL=600.0))
    node = pool.nodes["N00"]
    assert node.f == 8
    assert len(node.replicas) == 9            # f+1 instances
    assert node.quorums.commit.value == 17    # n - f

    users = []
    for i in range(4):
        user = Ed25519Signer(seed=(b"25n-u%d" % i).ljust(32, b"\0"))
        users.append(user)
        pool.submit(signed_nym(pool.trustee, user, i + 1))
    pool.run(10.0)

    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {5}, sizes                # genesis NYM + 4 writes
    roots = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
             for n in pool.names}
    assert len(roots) == 1
    # the ordered batch carries a 17-of-25 BLS aggregate over the state root
    assert any(isinstance(m, Reply) for m, _ in pool.client_msgs["N00"])


@pytest.mark.slow
def test_mixed_read_write_load():
    """Writes and state-proof reads interleaved on the same pool (the
    BASELINE 'mixed load' config): reads answer locally from committed
    state while writes keep ordering."""
    pool = Pool()
    users = []
    for i in range(3):
        user = Ed25519Signer(seed=(b"mx-u%d" % i).ljust(32, b"\0"))
        users.append(user)
        pool.submit(signed_nym(pool.trustee, user, i + 1))
    pool.run(6.0)

    # interleave: reads for committed NYMs + more writes in the same cycles
    from plenum_tpu.common.request import Request
    for i, user in enumerate(users):
        q = Request(pool.trustee.identifier, 100 + i,
                    {"type": GET_NYM, "dest": user.identifier})
        q.signature = pool.trustee.sign_b58(q.signing_bytes())
        pool.submit(q, to=["Alpha"])
    for i in range(3, 6):
        user = Ed25519Signer(seed=(b"mx-u%d" % i).ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, user, i + 1))
    pool.run(6.0)

    replies = [m for m, _ in pool.client_msgs["Alpha"]
               if isinstance(m, Reply)]
    reads = [m for m in replies if m.result.get("type") == GET_NYM]
    assert len(reads) == 3
    for m in reads:
        assert m.result["data"] is not None
        assert "state_proof" in m.result
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {7}, sizes                # genesis + 6 writes


def signed_node_services(trustee, alias, services, req_id):
    """Trustee services-only NODE edit (promotion/demotion)."""
    from plenum_tpu.common.request import Request
    from plenum_tpu.execution.txn import NODE
    req = Request(trustee.identifier, req_id,
                  {"type": NODE, "dest": f"{alias}Dest",
                   "data": {"services": services}})
    req.signature = trustee.sign_b58(req.signing_bytes())
    return req


def test_replicas_grow_when_pool_crosses_f_boundary():
    """Promoting nodes 5..7 moves f from 1 to 2: every node grows a third
    protocol instance with a deterministic primary for the current view,
    and the next view change will select 3 primaries (ref adjustReplicas
    node.py:1260)."""
    seven = ["Alpha", "Beta", "Gamma", "Delta", "Eps", "Zeta", "Eta"]
    pool = Pool(names=seven, validator_names=seven[:4],
                config=Config(Max3PCBatchWait=0.05,
                              STATE_FRESHNESS_UPDATE_INTERVAL=600.0))
    alpha = pool.nodes["Alpha"]
    assert len(alpha.validators) == 4 and alpha.f == 1
    assert len(alpha.replicas) == 2

    for i, alias in enumerate(seven[4:]):
        pool.submit(signed_node_services(pool.trustee, alias,
                                         ["VALIDATOR"], 50 + i))
        pool.run(4.0)

    for name in seven[:4]:
        node = pool.nodes[name]
        assert len(node.validators) == 7, name
        assert node.f == 2 and node.quorums.commit.value == 5
        assert len(node.replicas) == 3, name
        # the NEW instance's rank assignment is deterministic, distinct
        # from the existing ranks, and identical across the pool (the
        # master keeps its view-scoped list until the next view change)
        prims = list(node.replicas[2].data.primaries)
        assert len(prims) == 3 and len(set(prims)) == 3
        assert prims == list(
            pool.nodes["Beta"].replicas[2].data.primaries)
        assert node.replicas[2].data.view_no == \
            node.replicas.master.data.view_no
        assert node.replicas.master.view_changer._instance_count == 3

    # ordering continues at the wider quorum (promoted nodes shadowed the
    # full 3PC history, so they participate from the right state)
    user = Ed25519Signer(seed=b"grown-pool-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 99))
    pool.run(6.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in seven}
    assert sizes == {2}, sizes


def test_bls_key_rotation_keeps_pool_live():
    """Rotating a validator's BLS key (owner NODE edit) must not storm
    view changes: the first PRE-PREPARE after the rotation batch embeds a
    multi-sig made under the OLD key, which validators verify against the
    key register AS OF the sig's pool state root (historic MPT read,
    ref BlsKeyRegisterPoolManager.get_key_by_name(pool_state_root))."""
    from plenum_tpu.crypto.bls import BlsCryptoSigner
    from plenum_tpu.common.request import Request
    from plenum_tpu.execution.txn import NODE

    pool = Pool(config=Config(Max3PCBatchWait=0.05,
                              STATE_FRESHNESS_UPDATE_INTERVAL=600.0))
    # traffic before the rotation so multi-sigs exist
    u0 = Ed25519Signer(seed=b"rot-u0".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u0, 1))
    pool.run(5.0)

    new_signer = BlsCryptoSigner(seed=b"gamma-rotated-key".ljust(32, b"\0")[:32])
    req = Request(pool.trustee.identifier, 40,
                  {"type": NODE, "dest": "GammaDest",
                   "data": {"blskey": new_signer.pk,
                            "blskey_pop": new_signer.generate_pop()}})
    req.signature = pool.trustee.sign_b58(req.signing_bytes())
    pool.submit(req)
    pool.run(5.0)
    # ledger-side rotation landed
    assert pool.nodes["Alpha"].pool_manager.bls_key_of("Gamma") == new_signer.pk
    # the operator restarts Gamma with the new key (simulated in place)
    pool.nodes["Gamma"].replicas.master.bls._signer = new_signer

    for i in range(2, 5):
        u = Ed25519Signer(seed=(b"rot-u%d" % i).ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, u, i))
        pool.run(4.0)

    # no BLS multi-sig suspicions anywhere, no view change, all ordered
    for name in pool.names:
        node = pool.nodes[name]
        assert node.master_replica.view_no == 0, name
        bad = [e for e in node.spylog if e[0] == "suspicion"
               and e[1][0] == 15]
        assert not bad, (name, bad)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {5}, sizes
    # Gamma's NEW key participates in fresh aggregates
    ms = pool.nodes["Alpha"].replicas.master.bls._recent_multi_sigs
    assert any("Gamma" in m.participants for m in ms.values())


def test_replicas_shrink_when_pool_demotes_below_f_boundary():
    """Demoting 3 of 7 validators moves f back from 2 to 1: instances
    shrink to 2 on every remaining node and the pool keeps ordering at
    the narrower quorum."""
    seven = ["Alpha", "Beta", "Gamma", "Delta", "Eps", "Zeta", "Eta"]
    pool = Pool(names=seven, config=Config(
        Max3PCBatchWait=0.05, STATE_FRESHNESS_UPDATE_INTERVAL=600.0))
    assert len(pool.nodes["Alpha"].replicas) == 3
    for i, alias in enumerate(seven[4:]):
        pool.submit(signed_node_services(pool.trustee, alias, [], 60 + i))
        pool.run(4.0)
    for name in seven[:4]:
        node = pool.nodes[name]
        assert len(node.validators) == 4, name
        assert node.f == 1
        assert len(node.replicas) == 2, name
        assert node.replicas.master.view_changer._instance_count == 2
    user = Ed25519Signer(seed=b"shrunk-pool-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 99))
    pool.run(6.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in seven[:4]}
    assert sizes == {2}, sizes
