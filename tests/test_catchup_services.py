"""Unit tests for catchup service hardening (round-2 advisor findings):

- ConsProofService must quorum the 3PC key itself (f+1 matching non-None
  votes, minimum quorumed key) — one Byzantine peer echoing the honest
  (size, root) must not pick the pool's 3PC position
  (ref cons_proof_service.py _get_last_txn_3PC_key).
- CatchupRepService must apply reps that overlap already-applied txns
  (trim the prefix), drop fully-stale reps, and keep the retry timer armed
  while running (ref catchup_rep_service.py applies seqNo > ledger size).
- SeederService must decline a CatchupReq it cannot prove to catchup_till
  rather than ship a rep that gets an honest lagging peer blacklisted.
"""
import pytest

from plenum_tpu.catchup.cons_proof import ConsProofService
from plenum_tpu.catchup.rep import CatchupRepService
from plenum_tpu.catchup.seeder import SeederService
from plenum_tpu.common.node_messages import (CatchupRep, CatchupReq,
                                             ConsistencyProof)
from plenum_tpu.common.quorums import Quorums
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.ledger.ledger import Ledger

LID = 1


class DbStub:
    def __init__(self, ledger):
        self._ledger = ledger

    def get_ledger(self, ledger_id):
        return self._ledger if ledger_id == LID else None


def make_txns(n):
    return [{"seq": i, "payload": f"txn-{i}"} for i in range(1, n + 1)]


def proof_msg(source: Ledger, from_size: int, view_no, pp_seq_no):
    return ConsistencyProof(
        ledger_id=LID, seq_no_start=0, seq_no_end=source.size,
        view_no=view_no, pp_seq_no=pp_seq_no,
        old_merkle_root="", new_merkle_root=source.root_hash.hex(),
        hashes=tuple(source.consistency_proof(from_size, source.size))
        if from_size > 0 else ())


# --- ConsProofService: 3PC key quorum ----------------------------------------

class ConsProofHarness:
    def __init__(self, n=4):
        self.ledger = Ledger()
        self.targets = []
        self.svc = ConsProofService(
            LID, DbStub(self.ledger), lambda: Quorums(n),
            send=lambda msg, dst: None,
            on_target=lambda lid, target: self.targets.append(target))
        self.svc.start()


def test_byzantine_3pc_key_not_adopted():
    """A single peer echoing the honest (size, root) with an absurd 3PC key
    must not have its key adopted — no f+1 quorum on it means None."""
    h = ConsProofHarness()
    source = Ledger(genesis_txns=make_txns(5))
    h.svc.process_consistency_proof(proof_msg(source, 0, 0, 5), "B")   # honest
    h.svc.process_consistency_proof(proof_msg(source, 0, 999, 999), "C")  # byz
    assert len(h.targets) == 1
    size, root, last_3pc = h.targets[0]
    assert size == 5 and root == source.root_hash.hex()
    assert last_3pc is None          # old code adopted (999, 999): last wins


def test_quorumed_3pc_key_adopted():
    h = ConsProofHarness()
    source = Ledger(genesis_txns=make_txns(5))
    h.svc.process_consistency_proof(proof_msg(source, 0, 0, 5), "B")
    h.svc.process_consistency_proof(proof_msg(source, 0, 0, 5), "C")
    assert h.targets == [(5, source.root_hash.hex(), (0, 5))]


def test_none_3pc_votes_filtered():
    """Proofs carrying view_no/pp_seq_no=None must not crash nor count."""
    h = ConsProofHarness()
    source = Ledger(genesis_txns=make_txns(3))
    h.svc.process_consistency_proof(proof_msg(source, 0, None, None), "B")
    h.svc.process_consistency_proof(proof_msg(source, 0, None, None), "C")
    assert len(h.targets) == 1
    assert h.targets[0][2] is None


def test_min_quorumed_3pc_key_wins():
    h = ConsProofHarness()
    key = (5, "ab" * 32)
    h.svc._last_3pc_votes[key] = {(2, 9): {"B", "C"}, (0, 5): {"D", "E"},
                                  (7, 1): {"F"}}      # (7,1) not quorumed
    assert h.svc._quorumed_3pc(key) == (0, 5)


# --- CatchupRepService: overlapping and stale reps ---------------------------

class RepHarness:
    def __init__(self, committed=2, target=6, retry_timeout=5.0):
        self.source = Ledger(genesis_txns=make_txns(target))
        self.ledger = Ledger(genesis_txns=make_txns(committed))
        self.timer = MockTimer()
        self.sent = []
        self.added = []
        self.completed = []
        self.svc = CatchupRepService(
            LID, DbStub(self.ledger),
            send=lambda msg, dst: self.sent.append((msg, dst)),
            timer=self.timer, peers_provider=lambda: ["A", "B"],
            on_txn_added=lambda lid, txn: self.added.append(txn),
            on_complete=lambda lid: self.completed.append(lid),
            retry_timeout=retry_timeout)
        self.svc.start(self.source.size, self.source.root_hash.hex())

    def rep(self, lo, hi, frm="A"):
        txns = {str(i): self.source.get_by_seq_no(i) for i in range(lo, hi + 1)}
        proof = () if hi == self.source.size else \
            tuple(self.source.consistency_proof(hi, self.source.size))
        self.svc.process_catchup_rep(
            CatchupRep(ledger_id=LID, txns=txns, cons_proof=proof), frm)


def test_overlapping_rep_applied_with_prefix_trim():
    """Chunks with different boundaries (honest timeout re-splits) overlap;
    the applied prefix is trimmed instead of wedging the catchup."""
    h = RepHarness(committed=2, target=6)
    h.rep(3, 4, frm="A")
    assert h.ledger.size == 4
    h.rep(4, 6, frm="B")         # overlaps seq 4, already applied
    assert h.ledger.size == 6
    assert h.ledger.root_hash == h.source.root_hash
    assert h.completed == [LID]
    assert "B" not in h.svc._blacklisted_peers


def test_fully_stale_rep_dropped_and_retry_stays_armed():
    """A rep covering only already-applied txns is dropped; because its range
    'covers' the request window the old code computed missing=[] and never
    rescheduled the retry — the service stalled forever."""
    h = RepHarness(committed=2, target=4)
    h.rep(1, 4, frm="A")         # covers everything incl. applied 1-2
    assert h.ledger.size == 4    # prefix trimmed, applied to target
    assert h.completed == [LID]

    # now the stall scenario proper: a rep that is pending but unusable
    h2 = RepHarness(committed=2, target=6)
    h2.rep(1, 2, frm="A")        # fully stale: nothing new
    assert h2.ledger.size == 2
    assert h2.svc.is_running
    before = len(h2.sent)
    h2.timer.advance(6.0)        # retry must still be armed
    assert len(h2.sent) > before, "retry timer was not rearmed"
    # and the retried requests let the catchup finish
    h2.rep(3, 6, frm="B")
    assert h2.completed == [LID]


def test_gap_rep_waits_without_apply():
    h = RepHarness(committed=2, target=6)
    h.rep(5, 6, frm="B")         # gap: 3-4 missing
    assert h.ledger.size == 2
    h.rep(3, 4, frm="A")
    assert h.ledger.size == 6
    assert h.completed == [LID]


def test_retry_rotates_peers():
    """A silently-declining peer (itself behind the target) must not be
    re-asked for the same chunk on every retry pass."""
    h = RepHarness(committed=2, target=3)     # single missing chunk
    first = {dst[0] for msg, dst in h.sent}
    for _ in range(3):
        before = len(h.sent)
        h.timer.advance(6.0)
        assert len(h.sent) > before
    asked = [dst[0] for msg, dst in h.sent]
    assert set(asked) == {"A", "B"}, f"assignment never rotated: {asked}"


# --- SeederService: decline unprovable ranges --------------------------------

def test_seeder_declines_when_behind_target():
    ledger = Ledger(genesis_txns=make_txns(4))
    sent = []
    seeder = SeederService(DbStub(ledger),
                           send=lambda msg, dst: sent.append((msg, dst)),
                           last_3pc=lambda: (0, 0))
    seeder.process_catchup_req(
        CatchupReq(ledger_id=LID, seq_no_start=1, seq_no_end=6,
                   catchup_till=6), "B")
    assert sent == []            # lagging peer declines instead of lying
    seeder.process_catchup_req(
        CatchupReq(ledger_id=LID, seq_no_start=1, seq_no_end=4,
                   catchup_till=4), "B")
    assert len(sent) == 1 and sorted(int(k) for k in sent[0][0].txns) == [1, 2, 3, 4]
