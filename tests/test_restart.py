"""Full-node crash-restart: durable storage, audit-ledger 3PC restore,
rejoin via catchup.

Reference behavior under test: node restart recovery — ledgers/states
reopen from disk (ledger.py:70-113), the node resumes at the audit ledger's
3PC position and primaries (node.py:1830,1875), and a node that missed
traffic while down catches up and keeps ordering (SURVEY.md §5
checkpoint/resume).
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.node_messages import AUDIT_LEDGER_ID, DOMAIN_LEDGER_ID
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer

from test_pool import Pool, signed_nym


def _file_pool(tmp_path, **kw):
    return Pool(config=Config(Max3PCBatchWait=0.05, kv_backend="native"),
                data_dir=str(tmp_path), **kw)


def _user(tag: bytes) -> Ed25519Signer:
    return Ed25519Signer(seed=tag.ljust(32, b"\0"))


def test_single_node_crash_restart_rejoins_and_orders(tmp_path):
    pool = _file_pool(tmp_path)
    victim = "Delta"          # not the master primary (Alpha)

    pool.submit(signed_nym(pool.trustee, _user(b"rs-u1"), 1))
    pool.run(5.0)
    assert pool.nodes[victim].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2

    # hard-stop mid-stream: no clean shutdown, then the pool moves on
    pool.crash_node(victim)
    pool.submit(signed_nym(pool.trustee, _user(b"rs-u2"), 2),
                to=[n for n in pool.names if n != victim])
    pool.run(5.0)
    survivors = [n for n in pool.names if n != victim]
    assert all(pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 3
               for n in survivors)

    # restart from disk: committed state is back without any traffic
    node = pool.start_node(victim)
    pool.net.connect_all()
    ledger = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
    assert ledger.size == 2               # durable recovery of what it saw
    # audit restore: resumed at its pre-crash 3PC position, not (0, 0)
    assert node.master_replica.last_ordered_3pc[1] >= 1
    assert ("restored_from_audit", node.master_replica.last_ordered_3pc) \
        in list(node.spylog)

    # it catches up the missed txn...
    node.start_catchup()
    pool.run(10.0)
    assert ledger.size == 3
    assert ledger.root_hash == pool.nodes["Alpha"].c.db.get_ledger(
        DOMAIN_LEDGER_ID).root_hash

    # ...and participates in ordering NEW traffic
    pool.submit(signed_nym(pool.trustee, _user(b"rs-u3"), 3))
    pool.run(5.0)
    assert ledger.size == 4
    assert all(pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 4
               for n in pool.names)


def test_whole_pool_restart_resumes_without_catchup(tmp_path):
    pool = _file_pool(tmp_path)
    pool.submit(signed_nym(pool.trustee, _user(b"wp-u1"), 1))
    pool.run(5.0)
    last_3pc = pool.nodes["Alpha"].master_replica.last_ordered_3pc
    assert last_3pc[1] >= 1
    root = pool.nodes["Alpha"].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash

    # power failure: every node hard-stops
    for name in list(pool.names):
        pool.crash_node(name)
    for name in pool.names:
        pool.start_node(name)
    pool.net.connect_all()

    for name in pool.names:
        node = pool.nodes[name]
        assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash == root
        assert node.master_replica.last_ordered_3pc == last_3pc
        audit = node.c.db.get_ledger(AUDIT_LEDGER_ID)
        assert audit.size >= 1

    # the pool keeps ordering from where it left off — no catchup needed
    pool.submit(signed_nym(pool.trustee, _user(b"wp-u2"), 2))
    pool.run(5.0)
    for name in pool.names:
        ledger = pool.nodes[name].c.db.get_ledger(DOMAIN_LEDGER_ID)
        assert ledger.size == 3
        assert pool.nodes[name].master_replica.last_ordered_3pc[1] == \
            last_3pc[1] + 1


def test_restart_discards_uncommitted_tail(tmp_path):
    from plenum_tpu.storage.kv_native import native_available
    if not native_available():
        pytest.skip("native kvstore engine unavailable")
    """A torn write in the ledger log must not poison recovery: the native
    KV engine drops the torn tail (CRC + truncation) and the node restarts
    from the last durable record."""
    import os

    pool = _file_pool(tmp_path)
    pool.submit(signed_nym(pool.trustee, _user(b"tt-u1"), 1))
    pool.run(5.0)
    victim = "Delta"
    size_before = pool.nodes[victim].c.db.get_ledger(DOMAIN_LEDGER_ID).size
    pool.crash_node(victim)

    # tear the tail of the domain ledger log (crash mid-write)
    log = os.path.join(str(tmp_path), victim, "domain_log", "kv.kvn")
    file_size = os.path.getsize(log)
    os.truncate(log, file_size - 3)

    node = pool.start_node(victim)
    pool.net.connect_all()
    ledger = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
    assert ledger.size == size_before - 1     # torn record dropped
    node.start_catchup()
    pool.run(10.0)
    assert ledger.size == size_before         # catchup refills it
    assert ledger.root_hash == pool.nodes["Alpha"].c.db.get_ledger(
        DOMAIN_LEDGER_ID).root_hash


def test_instance_change_votes_survive_restart(tmp_path):
    """A node crash during a marginal f+1 InstanceChange accumulation must
    not reset the count: persisted votes + fresh votes still complete the
    view change (ref instance_change_provider.py:34-69).

    Strictness: Gamma is down for good, so exactly n-f=3 nodes remain and
    ALL of them must join the view change for it to complete. Delta hears
    Alpha's vote, restarts, then hears Beta's — without persistence Delta
    would hold 1 vote < f+1 and the pool would stay in view 0 forever.
    """
    from plenum_tpu.common.internal_messages import VoteForViewChange
    from plenum_tpu.common.suspicion_codes import Suspicions

    pool = _file_pool(tmp_path)
    pool.crash_node("Gamma")

    # Alpha votes; the InstanceChange broadcast reaches Beta and Delta
    pool.nodes["Alpha"].master_replica.internal_bus.send(
        VoteForViewChange(suspicion_code=Suspicions.PRIMARY_DEGRADED.code))
    pool.run(2.0)
    assert all(pool.nodes[n].master_replica.view_no == 0
               for n in pool.nodes)          # 1 vote < f+1: nothing starts

    pool.crash_node("Delta")
    node = pool.start_node("Delta")
    pool.net.connect_all()

    # Beta's fresh vote is the second: every live node reaches f+1 only if
    # Delta still counts Alpha's persisted vote
    pool.nodes["Beta"].master_replica.internal_bus.send(
        VoteForViewChange(suspicion_code=Suspicions.PRIMARY_DEGRADED.code))
    pool.run(15.0)
    for name in ("Alpha", "Beta", "Delta"):
        assert pool.nodes[name].master_replica.view_no == 1, name
        # the view change COMPLETED (NewView accepted), not merely started —
        # a restarted node claiming an off-boundary stable checkpoint used
        # to deadlock NewViewBuilder.calc_checkpoint here
        assert not pool.nodes[name].master_replica.data.waiting_for_new_view

    # and the pool keeps ordering under the new primary
    pool.submit(signed_nym(pool.trustee, _user(b"ic-u2"), 2),
                to=list(pool.nodes))
    pool.run(10.0)
    for name in ("Alpha", "Beta", "Delta"):
        assert pool.nodes[name].c.db.get_ledger(
            DOMAIN_LEDGER_ID).size == 2, name


def test_instance_change_votes_expire_at_load(tmp_path):
    """TTL-on-load: a persisted vote older than INSTANCE_CHANGE_TIMEOUT in
    wall-clock terms is dropped when the node restarts, so stale grievances
    can't combine across epochs (ref instance_change_provider TTL)."""
    from plenum_tpu.common.node_messages import InstanceChange
    from plenum_tpu.consensus.view_change_trigger_service import \
        InstanceChangeVoteStore
    from plenum_tpu.execution.database_manager import NODE_STATUS_DB_LABEL

    pool = _file_pool(tmp_path)
    node = pool.nodes["Delta"]
    node.master_replica.vc_trigger.process_instance_change(
        InstanceChange(view_no=1, reason=0), "Alpha")

    # age the persisted vote past the TTL by rewriting its wall stamp
    kv = node.c.db.get_store(NODE_STATUS_DB_LABEL)
    store = InstanceChangeVoteStore(kv)
    import time as _time
    old = _time.time() - pool.config.INSTANCE_CHANGE_TIMEOUT - 10
    store.save_view(1, {"Alpha": old})

    pool.crash_node("Delta")
    node = pool.start_node("Delta")
    trigger = node.master_replica.vc_trigger
    assert trigger._votes.get(1, {}) == {}    # expired vote not reloaded
    assert store.load(pool.config.INSTANCE_CHANGE_TIMEOUT) == {}  # and purged


def test_backup_primary_resumes_last_sent_pp(tmp_path):
    """A restarting BACKUP primary resumes its 3PC numbering from the
    persisted last-sent PRE-PREPARE instead of re-issuing pp_seq_no 1
    (ref last_sent_pp_store_helper.py). The master restores from the audit
    ledger; backups have no audit trail — only this store."""
    pool = _file_pool(tmp_path)
    # view 0 primaries: inst 0 = Alpha (master), inst 1 = Beta
    beta = pool.nodes["Beta"]
    assert beta.replicas[1].data.is_primary

    for i in range(3):
        pool.submit(signed_nym(pool.trustee, _user(b"bp-u%d" % i), i + 1))
        pool.run(2.0)
    sent_before = beta.replicas[1].data.pp_seq_no
    assert sent_before >= 1          # the backup primary really sent PPs

    pool.crash_node("Beta")
    beta = pool.start_node("Beta")
    pool.net.connect_all()
    # restored, not reset: the next PP it sends will be sent_before + 1
    assert beta.replicas[1].data.pp_seq_no == sent_before
    assert ("restored_backup_pp", (1, sent_before)) in list(beta.spylog)

    # new traffic: the backup keeps ordering with fresh seq-nos on every
    # node's shadow instance — a duplicate/gap would stall inst 1
    pool.submit(signed_nym(pool.trustee, _user(b"bp-u9"), 9))
    pool.run(8.0)
    for name in pool.names:
        inst1 = pool.nodes[name].replicas[1]
        assert inst1.data.last_ordered_3pc[1] >= sent_before + 1, \
            (name, inst1.data.last_ordered_3pc, sent_before)


def test_restart_with_chunked_store(tmp_path):
    """Crash-restart over the chunked append-log backend: the restarted
    node recovers its ledgers from sealed+tail chunks and rejoins."""
    pool = Pool(config=Config(Max3PCBatchWait=0.05, kv_backend="chunked"),
                data_dir=str(tmp_path))
    users = [Ed25519Signer(seed=(b"ck%d" % i).ljust(32, b"\0"))
             for i in range(6)]
    for i, u in enumerate(users[:4]):
        pool.submit(signed_nym(pool.trustee, u, req_id=i + 1))
    pool.run(8.0)
    assert pool.nodes["Beta"].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 5
    pool.crash_node("Beta")
    pool.submit(signed_nym(pool.trustee, users[4], req_id=5),
                to=["Alpha", "Gamma", "Delta"])
    pool.run(5.0)
    node = pool.start_node("Beta")
    pool.net.connect_all()
    assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 5  # durable
    node.start_catchup()
    pool.run(10.0)
    assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 6
    pool.submit(signed_nym(pool.trustee, users[5], req_id=6))
    pool.run(8.0)
    sizes = {n: nd.c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n, nd in pool.nodes.items()}
    assert sizes == {n: 7 for n in pool.names}, sizes
