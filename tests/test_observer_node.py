"""Deployable observer: a follower process with its own transport that
subscribes to a LIVE TCP pool, applies pushed batches under an f+1 push
quorum, and — after being killed and restarted — catches up unaided by
pulling the gap over its own GET_TXN queries.

Reference behavior under test: plenum/server/observer/observer_node.py (a
self-contained follower with storage + transport + sync policy).
"""
from __future__ import annotations

import asyncio
import shutil
import signal
import subprocess
import sys
import tempfile
import time

import pytest

from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID, POOL_LEDGER_ID


@pytest.fixture
def tcp_pool_4():
    pytest.importorskip(
        "cryptography",
        reason="the TCP node stack's handshake needs the cryptography package")
    from plenum_tpu.tools.tcp_pool import REPO, setup_pool_dir, _wait_all_started
    import os

    names = [f"Node{i + 1}" for i in range(4)]
    tmp = tempfile.mkdtemp(prefix="plenum_obs_pool_")
    trustee_seed = b"obs-pool-trustee".ljust(32, b"\0")
    specs = setup_pool_dir(tmp, names, trustee_seed)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-m", "plenum_tpu.tools.start_node",
         "--name", name, "--base-dir", tmp, "--kv", "memory"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for name in names]
    try:
        _wait_all_started(procs, deadline_s=60.0)
        yield tmp, names, specs, trustee_seed
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _signed_nyms(trustee_seed, tags):
    from plenum_tpu.client.wallet import Wallet
    from plenum_tpu.execution.txn import NYM

    wallet = Wallet("obs-test")
    trustee = wallet.add_identifier(seed=trustee_seed)
    reqs = []
    for tag in tags:
        user = wallet.add_identifier(seed=tag.encode().ljust(32, b"\0")[:32])
        reqs.append(wallet.sign_request(
            {"type": NYM, "dest": user, "verkey": wallet.verkey_of(user)},
            identifier=trustee))
    return reqs


async def _drive(addrs, f, requests):
    from plenum_tpu.client.pipelined import PipelinedPoolClient
    client = PipelinedPoolClient(addrs, f)
    done, _ = await client.drive(requests, window=50, timeout=60.0)
    assert len(done) == len(requests)


async def _wait_for(predicate, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        await asyncio.sleep(0.2)
    return False


def test_observer_follows_live_pool_and_catches_up_after_restart(tcp_pool_4):
    from plenum_tpu.node.observer_node import ObserverNode
    from plenum_tpu.tools.genesis import load_genesis_files

    tmp, names, specs, trustee_seed = tcp_pool_4
    genesis = load_genesis_files(tmp)
    addrs = {name: ("127.0.0.1", spec[3])
             for name, spec in zip(names, specs)}
    obs_dir = tempfile.mkdtemp(prefix="plenum_obs_data_")

    async def scenario():
        # phase 1: observer follows live traffic via pushes
        stop = asyncio.Event()
        obs = ObserverNode("observer1", genesis, addrs, f=1,
                           data_dir=obs_dir, storage_backend="file")
        task = asyncio.create_task(obs.run(stop))
        await asyncio.sleep(1.0)                 # registrations land
        await _drive(addrs, 1, _signed_nyms(trustee_seed,
                                            [f"obs-a{i}" for i in range(5)]))
        ledger = obs.observer.c.db.get_ledger(DOMAIN_LEDGER_ID)
        assert await _wait_for(lambda: ledger.size >= 6), \
            f"observer never applied pushes (size={ledger.size})"
        live_root = ledger.root_hash

        # phase 2: kill the observer (no clean shutdown of its stores),
        # order traffic it never sees...
        stop.set()
        await task
        await _drive(addrs, 1, _signed_nyms(trustee_seed,
                                            [f"obs-b{i}" for i in range(5)]))

        # phase 3: restart from its own data dir; the first push after
        # restart carries roots binding the whole gap, which the observer
        # fills with its OWN GET_TXN pulls — no helper callback
        stop2 = asyncio.Event()
        obs2 = ObserverNode("observer1", genesis, addrs, f=1,
                            data_dir=obs_dir, storage_backend="file")
        ledger2 = obs2.observer.c.db.get_ledger(DOMAIN_LEDGER_ID)
        assert ledger2.size >= 6                 # durable recovery
        task2 = asyncio.create_task(obs2.run(stop2))
        await asyncio.sleep(1.0)
        await _drive(addrs, 1, _signed_nyms(trustee_seed, ["obs-c0"]))
        ok = await _wait_for(lambda: ledger2.size >= 12)
        stop2.set()
        await task2
        assert ok, f"observer did not catch up (size={ledger2.size})"
        assert ledger2.size == 12                # 1 genesis + 5 + 5 + 1
        assert ledger2.root_hash != live_root

    try:
        asyncio.run(scenario())
    finally:
        shutil.rmtree(obs_dir, ignore_errors=True)


def test_gap_vote_buffer_bounded_per_validator():
    """A Byzantine validator minting ever-new seq_no_start values must hold
    at most ONE gap-vote bucket per ledger; honest f+1 quorum still arms."""
    from unittest.mock import MagicMock

    from plenum_tpu.common.node_messages import BatchCommitted
    from plenum_tpu.node.observer_node import ObserverNode

    def mk(start):
        return BatchCommitted(requests=(), ledger_id=1, inst_id=0, view_no=0,
                              pp_seq_no=start, pp_time=0.0,
                              state_root="00" * 32, txn_root="00" * 32,
                              seq_no_start=start, seq_no_end=start)

    obs = ObserverNode.__new__(ObserverNode)
    obs._gap_votes = {}
    inner = MagicMock()
    inner.f = 1
    ledger = MagicMock()
    ledger.size = 0
    inner.c.db.get_ledger.return_value = ledger
    obs.observer = inner

    for start in range(100, 1100):
        obs._gap_quorum("Evil", mk(start))
    assert len(obs._gap_votes) == 1

    assert not obs._gap_quorum("A", mk(50))
    assert obs._gap_quorum("B", mk(50))


def _mk_batch(start, multi_sig=None):
    from plenum_tpu.common.node_messages import BatchCommitted
    return BatchCommitted(requests=(), ledger_id=1, inst_id=0, view_no=0,
                          pp_seq_no=start, pp_time=0.0,
                          state_root="00" * 32, txn_root="00" * 32,
                          seq_no_start=start, seq_no_end=start,
                          multi_sig=multi_sig)


def test_gap_quorum_ignores_multi_sig_variation():
    """Two validators pushing the SAME gapped batch with DIFFERENT
    multi-sig attachments (honest aggregation subsets differ) must still
    arm the f+1 gap-fill — the advisory sig is excluded from the content
    digest."""
    from unittest.mock import MagicMock

    from plenum_tpu.node.observer_node import ObserverNode

    obs = ObserverNode.__new__(ObserverNode)
    obs._gap_votes = {}
    inner = MagicMock()
    inner.f = 1
    ledger = MagicMock()
    ledger.size = 0
    inner.c.db.get_ledger.return_value = ledger
    obs.observer = inner

    ms_a = ("sigA", ["Node1", "Node2", "Node3"],
            [1, "aa" * 32, "bb" * 32, "cc" * 32, 1.0])
    ms_b = ("sigB", ["Node2", "Node3", "Node4"],
            [1, "aa" * 32, "bb" * 32, "cc" * 32, 1.0])
    assert not obs._gap_quorum("A", _mk_batch(50, multi_sig=ms_a))
    assert obs._gap_quorum("B", _mk_batch(50, multi_sig=ms_b))


def test_push_quorum_ignores_multi_sig_variation_in_node_observer():
    """Same property on the live-push path (NodeObserver.process_batch):
    content-identical batches with different multi-sigs converge; a
    batch with DIFFERENT CONTENT still does not."""
    import dataclasses

    from plenum_tpu.client.wallet import Wallet
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.node.bootstrap import NodeBootstrap
    from plenum_tpu.node.observer import NodeObserver
    from test_pool import make_genesis

    genesis, _trustee = make_genesis(["Alpha", "Beta", "Gamma", "Delta"])
    obs = NodeObserver(NodeBootstrap(
        "obsq", genesis_txns=genesis).build(), f=1)

    wallet = Wallet("w")
    trustee_id = wallet.add_identifier(
        seed=b"trustee-seed".ljust(32, b"\0"))
    user = wallet.add_identifier(seed=b"obs-quorum-user".ljust(32, b"\0"))
    req = wallet.sign_request(
        {"type": NYM, "dest": user, "verkey": wallet.verkey_of(user)},
        identifier=trustee_id)

    # derive the true post-batch roots on a TWIN replica: apply
    # uncommitted, read the roots, revert — the pushed batch must cite
    # roots the observer's own recomputation reproduces
    twin = NodeBootstrap("twin", genesis_txns=genesis).build()
    roots = twin.write_manager.apply_batch(1, [req], 1.0, 0, 1)[2]
    twin.write_manager.revert_last_batch(1)
    txn_root, state_root = roots["txn_root"], roots["state_root"]

    real = dataclasses.replace(
        _mk_batch(2), requests=(req.to_dict(),), ledger_id=1,
        pp_seq_no=1, pp_time=1.0, txn_root=txn_root,
        state_root=state_root)
    ms_a = ("sigA", ["Alpha", "Beta", "Gamma"],
            [1, state_root, "bb" * 32, txn_root, 1.0])
    ms_b = ("sigB", ["Beta", "Gamma", "Delta"],
            [1, state_root, "bb" * 32, txn_root, 1.0])
    assert not obs.process_batch(
        dataclasses.replace(real, multi_sig=ms_a), frm="Alpha")
    # different content from Beta must NOT complete Alpha's quorum
    assert not obs.process_batch(
        dataclasses.replace(real, pp_time=2.0, multi_sig=ms_a),
        frm="Beta")
    # same content, different multi-sig: quorum completes, batch applies
    assert obs.process_batch(
        dataclasses.replace(real, multi_sig=ms_b), frm="Gamma")
    assert obs.c.db.get_ledger(1).size == 2


def test_observer_node_genesis_bls_keys():
    from plenum_tpu.node.observer_node import ObserverNode
    from test_pool import make_genesis
    genesis, _ = make_genesis(["Alpha", "Beta"])
    keys = ObserverNode._genesis_bls_keys(genesis)
    assert set(keys) == {"Alpha", "Beta"}
    assert all(isinstance(v, str) and v for v in keys.values())
