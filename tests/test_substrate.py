"""Unit tests for the common substrate: quorums, timers, buses, stashing,
messages, request digests, serialization, KV stores."""
import pytest

from plenum_tpu.common.quorums import Quorums, faults
from plenum_tpu.common.timer import MockTimer, QueueTimer, RepeatingTimer
from plenum_tpu.common.event_bus import InternalBus, ExternalBus
from plenum_tpu.common.stashing import StashingRouter, StashReason, STASH, PROCESS, DISCARD
from plenum_tpu.common.message_base import (MessageValidationError,
                                            message_from_dict)
from plenum_tpu.common.node_messages import (PrePrepare, Prepare, Commit,
                                             Checkpoint, Propagate)
from plenum_tpu.common.request import Request
from plenum_tpu.common.serialization import pack, unpack, signing_serialize
from plenum_tpu.config import Config, load_config
from plenum_tpu.storage import init_kv_store
from plenum_tpu.storage.kv_file import KvFile


# --- quorums (ref quorums.py table) --------------------------------------

@pytest.mark.parametrize("n,f", [(4, 1), (7, 2), (10, 3), (13, 4), (25, 8)])
def test_faults(n, f):
    assert faults(n) == f


def test_quorum_table_n4():
    q = Quorums(4)
    assert q.propagate.value == 2
    assert q.prepare.value == 2
    assert q.commit.value == 3
    assert q.view_change.value == 3
    assert q.checkpoint.value == 2
    assert q.timestamp.value == 2
    assert q.bls_signatures.value == 3
    assert q.prepare.is_reached(2) and not q.prepare.is_reached(1)


# --- timers ---------------------------------------------------------------

def test_mock_timer_fires_in_order():
    timer = MockTimer()
    fired = []
    timer.schedule(5, lambda: fired.append("b"))
    timer.schedule(1, lambda: fired.append("a"))
    timer.schedule(10, lambda: fired.append("c"))
    timer.advance(6)
    assert fired == ["a", "b"]
    timer.advance(5)
    assert fired == ["a", "b", "c"]


def test_timer_cancel():
    timer = MockTimer()
    fired = []
    cb = lambda: fired.append(1)
    timer.schedule(1, cb)
    timer.cancel(cb)
    timer.advance(2)
    assert fired == []


def test_repeating_timer():
    timer = MockTimer()
    fired = []
    rt = RepeatingTimer(timer, 10, lambda: fired.append(timer.get_current_time()))
    timer.advance(35)
    assert fired == [10, 20, 30]
    rt.stop()
    timer.advance(20)
    assert fired == [10, 20, 30]


# --- buses ----------------------------------------------------------------

def test_internal_bus_dispatch_by_type():
    bus = InternalBus()
    got = []
    bus.subscribe(Checkpoint, lambda m: got.append(m))
    cp = Checkpoint(inst_id=0, view_no=0, seq_no_start=0, seq_no_end=100, digest="d")
    bus.send(cp)
    assert got == [cp]


def test_external_bus_connecteds():
    sent = []
    bus = ExternalBus(lambda msg, dst: sent.append((msg, dst)))
    events = []
    bus.subscribe(ExternalBus.Connected, lambda m, frm: events.append(("+", m.name)))
    bus.subscribe(ExternalBus.Disconnected, lambda m, frm: events.append(("-", m.name)))
    bus.update_connecteds({"B", "C"})
    bus.update_connecteds({"C", "D"})
    assert ("+", "B") in events and ("+", "D") in events and ("-", "B") in events
    bus.send("hello", "B")
    assert sent == [("hello", ["B"])]


# --- stashing router ------------------------------------------------------

def test_stashing_router_stash_and_replay():
    router = StashingRouter()
    state = {"ready": False}
    seen = []

    def handler(msg, frm):
        if not state["ready"]:
            return STASH(StashReason.CATCHING_UP)
        seen.append((msg, frm))
        return PROCESS

    router.subscribe(Checkpoint, handler)
    cp = Checkpoint(inst_id=0, view_no=0, seq_no_start=0, seq_no_end=10, digest="x")
    router.dispatch(cp, "NodeB")
    assert router.stash_size(StashReason.CATCHING_UP) == 1
    assert seen == []
    state["ready"] = True
    router.process_all_stashed(StashReason.CATCHING_UP)
    assert seen == [(cp, "NodeB")]
    assert router.stash_size() == 0


def test_stashing_router_discard():
    router = StashingRouter()
    router.subscribe(Checkpoint, lambda m, frm: (DISCARD, "bad"))
    cp = Checkpoint(inst_id=0, view_no=0, seq_no_start=0, seq_no_end=10, digest="x")
    router.dispatch(cp, "B")
    assert len(router.discarded) == 1


# --- messages -------------------------------------------------------------

def _pp(**kw):
    base = dict(inst_id=0, view_no=0, pp_seq_no=1, pp_time=1.0,
                req_idr=("d1", "d2"), discarded=(), digest="bd",
                ledger_id=1, state_root="sr", txn_root="tr")
    base.update(kw)
    return PrePrepare(**base)


def test_message_roundtrip():
    pp = _pp()
    d = pp.to_dict()
    assert d["op"] == "PREPREPARE"
    pp2 = message_from_dict(unpack(pack(d)))
    assert pp2 == pp


def test_message_rejects_bad_fields():
    d = _pp().to_dict()
    d["pp_seq_no"] = "nope"
    with pytest.raises(MessageValidationError):
        message_from_dict(d)
    d2 = _pp().to_dict()
    d2["evil_extra"] = 1
    with pytest.raises(MessageValidationError):
        message_from_dict(d2)
    d3 = _pp().to_dict()
    del d3["digest"]
    with pytest.raises(MessageValidationError):
        message_from_dict(d3)


def test_message_semantic_validation():
    with pytest.raises(MessageValidationError):
        PrePrepare.from_dict(_pp().to_dict() | {"pp_seq_no": 0})
    with pytest.raises(MessageValidationError):
        Checkpoint.from_dict(dict(op="CHECKPOINT", inst_id=0, view_no=0,
                                  seq_no_start=5, seq_no_end=1, digest="d"))


def test_unknown_op_rejected():
    with pytest.raises(MessageValidationError):
        message_from_dict({"op": "EVIL"})


# --- request digests (ref request.py:87,90) ------------------------------

def test_request_digests():
    op = {"type": "1", "dest": "abc", "verkey": "vk"}
    r1 = Request("idr1", 1, op, signature="sigA")
    r2 = Request("idr1", 1, op, signature="sigB")
    assert r1.payload_digest == r2.payload_digest       # signature excluded
    assert r1.digest != r2.digest                       # signature included
    r3 = Request.from_dict(r1.to_dict())
    assert r3.digest == r1.digest


def test_request_multi_signatures():
    r = Request("idr1", 1, {"type": "1"}, signatures={"idr1": "s1", "endr": "s2"})
    assert r.all_signatures() == {"idr1": "s1", "endr": "s2"}


# --- serialization --------------------------------------------------------

def test_pack_deterministic_map_order():
    assert pack({"b": 1, "a": 2}) == pack({"a": 2, "b": 1})
    assert unpack(pack({"a": [1, 2], "n": None})) == {"a": [1, 2], "n": None}


def test_signing_serialize_canonical():
    assert signing_serialize({"b": 1, "a": 2}) == b'{"a":2,"b":1}'


# --- config ---------------------------------------------------------------

def test_config_layering():
    cfg = load_config({"CHK_FREQ": 10}, {"CHK_FREQ": 5, "LOG_SIZE": 15}, None)
    assert cfg.CHK_FREQ == 5 and cfg.LOG_SIZE == 15
    assert cfg.Max3PCBatchSize == 1000
    cfg2 = cfg.replace(DELTA=0.5)
    assert cfg2.DELTA == 0.5 and cfg.DELTA == 0.1


# --- KV stores ------------------------------------------------------------

@pytest.mark.parametrize("backend", ["memory", "file"])
def test_kv_store(backend, tdir):
    kv = init_kv_store(backend, path=tdir)
    kv.put("a", b"1")
    kv.put(b"b", b"2")
    kv.put("a", b"1x")
    assert kv.get("a") == b"1x"
    assert kv.try_get("zz") is None
    kv.remove("b")
    assert not kv.has_key("b")
    kv.put(5, b"five")
    assert kv.get(5) == b"five"
    assert kv.size == 2
    kv.close()


def test_kv_int_key_ordering(tdir):
    kv = init_kv_store("memory")
    for i in [3, 1, 300, 2, 256]:
        kv.put(i, str(i).encode())
    keys = [int.from_bytes(k, "big") for k in kv.iterator(include_value=False)]
    assert keys == [1, 2, 3, 256, 300]
    # ranged iteration
    vals = [v for _, v in kv.iterator(start=2, end=256)]
    assert vals == [b"2", b"3", b"256"]


def test_kv_file_crash_resume(tdir):
    kv = KvFile(tdir, "t")
    for i in range(100):
        kv.put(i, b"v%d" % i)
    kv.remove(50)
    del kv._fh  # simulate crash without close/compact
    kv2 = KvFile(tdir, "t")
    assert kv2.size == 99
    assert kv2.get(99) == b"v99"
    assert kv2.try_get(50) is None
    kv2.close()


def test_kv_file_batch_ops(tdir):
    kv = KvFile(tdir, "t")
    kv.do_ops_in_batch([("put", "x", b"1"), ("put", "y", b"2"), ("remove", "x", b"")])
    assert kv.try_get("x") is None and kv.get("y") == b"2"
    kv.close()


# --- regression tests for review findings ---------------------------------

def test_kv_file_torn_tail_then_append_then_crash(tdir):
    """Torn record must be truncated on replay so later appends aren't
    misparsed by the next replay (review finding #1)."""
    import os, struct
    kv = KvFile(tdir, "t")
    kv.put("key0", b"val0")
    kv.close()
    p = os.path.join(tdir, "t.kvlog")
    with open(p, "ab") as fh:  # simulate a torn header+partial record
        fh.write(struct.pack(">BII", 0, 4, 4) + b"ke")
    kv2 = KvFile(tdir, "t")
    kv2.put("b", b"2")
    del kv2._fh  # crash again without close
    kv3 = KvFile(tdir, "t")
    assert kv3.get("key0") == b"val0"
    assert kv3.get("b") == b"2"
    assert kv3.size == 2
    kv3.close()


def test_bare_tuple_field_roundtrips():
    """bls_multi_sig (bare tuple annot) must survive msgpack list decoding
    (review finding #2)."""
    pp = _pp(bls_multi_sig=("sig", "pool", ("v1", "v2")))
    pp2 = message_from_dict(unpack(pack(pp.to_dict())))
    assert pp2.bls_multi_sig == ("sig", "pool", ("v1", "v2"))
    assert hash(pp2) is not None


def test_stash_overflow_recorded():
    router = StashingRouter(limit=2)
    router.subscribe(Checkpoint, lambda m, frm: STASH(StashReason.CATCHING_UP))
    cp = Checkpoint(inst_id=0, view_no=0, seq_no_start=0, seq_no_end=10, digest="x")
    for frm in "BCDE":
        router.dispatch(cp, frm)
    assert router.stash_size() == 2
    assert len(router.discarded) == 2
    assert "overflow" in router.discarded[0][2]


def test_config_unknown_key_raises():
    with pytest.raises(KeyError):
        load_config({"CHK_FRQ": 10})


# --- regression tests for second review round -----------------------------

def test_dict_field_messages_hashable():
    c = Commit(inst_id=0, view_no=0, pp_seq_no=1, bls_sigs={"0": "sig"})
    assert hash(c) == hash(Commit.from_dict(c.to_dict()))
    assert c in {c}


def test_dict_field_rejects_non_str_keys():
    d = Propagate(request={"identifier": "a"}, sender_client=None).to_dict()
    d["request"] = {1: "a", "b": 2}
    with pytest.raises(MessageValidationError):
        message_from_dict(d)


def test_negative_fields_rejected_everywhere():
    from plenum_tpu.common.node_messages import (InstanceChange, ViewChange,
                                                 LedgerStatus, CatchupReq)
    with pytest.raises(MessageValidationError):
        InstanceChange.from_dict({"op": "INSTANCE_CHANGE", "view_no": -3, "reason": 0})
    with pytest.raises(MessageValidationError):
        Checkpoint.from_dict({"op": "CHECKPOINT", "inst_id": -5, "view_no": 0,
                              "seq_no_start": 0, "seq_no_end": 1, "digest": "d"})
    with pytest.raises(MessageValidationError):
        LedgerStatus.from_dict({"op": "LEDGER_STATUS", "ledger_id": 1,
                                "txn_seq_no": -1, "merkle_root": "r"})
    with pytest.raises(MessageValidationError):
        CatchupReq.from_dict({"op": "CATCHUP_REQ", "ledger_id": 1,
                              "seq_no_start": 5, "seq_no_end": 2, "catchup_till": 9})


def test_pack_mixed_key_types_no_crash():
    assert unpack(pack({1: "a", "b": 2})) == {1: "a", "b": 2}


def test_kv_file_corrupt_op_byte_stops_replay(tdir):
    import os, struct
    kv = KvFile(tdir, "t")
    kv.put("a", b"1")
    kv.put("b", b"2")
    kv.close()
    p = os.path.join(tdir, "t.kvlog")
    data = open(p, "rb").read()
    # corrupt the op byte of the second record
    second_off = 9 + 1 + 1
    patched = bytearray(data)
    patched[second_off] = 7
    open(p, "wb").write(bytes(patched))
    kv2 = KvFile(tdir, "t")
    assert kv2.get("a") == b"1"       # prefix survives
    assert kv2.try_get("b") is None   # corrupt record dropped, not misread
    kv2.close()


def test_stashing_duplicate_subscribe_raises():
    router = StashingRouter()
    router.subscribe(Checkpoint, lambda m, frm: PROCESS)
    with pytest.raises(ValueError):
        router.subscribe(Checkpoint, lambda m, frm: PROCESS)


# --- chunked append-log store (ref chunked_file_store.py) ------------------

def test_kv_chunked_rotates_and_resumes(tdir):
    from plenum_tpu.storage.kv_chunked import KvChunked
    kv = KvChunked(tdir, "c", chunk_records=10)
    for i in range(35):
        kv.put(i, b"v%d" % i)
    kv.remove(7)
    assert kv.chunk_count == 4            # 36 records / 10 per chunk
    del kv._fh                            # crash, no close
    kv2 = KvChunked(tdir, "c", chunk_records=10)
    assert kv2.size == 34
    assert kv2.get(34) == b"v34"
    assert kv2.try_get(7) is None
    # appends continue in the live tail chunk, sealing at the boundary
    for i in range(35, 50):
        kv2.put(i, b"v%d" % i)
    assert kv2.chunk_count == 6
    kv2.close()
    kv3 = KvChunked(tdir, "c", chunk_records=10)
    assert kv3.size == 49
    kv3.close()


def test_kv_chunked_torn_tail_only_affects_last_chunk(tdir):
    import os, struct
    from plenum_tpu.storage.kv_chunked import KvChunked
    kv = KvChunked(tdir, "c", chunk_records=5)
    for i in range(12):
        kv.put(i, b"x%d" % i)
    kv.close()
    # tear the TAIL chunk: replay drops only the torn record
    with open(os.path.join(tdir, "c.000003.chunk"), "ab") as fh:
        fh.write(struct.pack(">BII", 0, 4, 4) + b"ke")
    kv2 = KvChunked(tdir, "c", chunk_records=5)
    assert kv2.size == 12
    kv2.put(99, b"after")
    kv2.close()
    kv3 = KvChunked(tdir, "c", chunk_records=5)
    assert kv3.get(99) == b"after" and kv3.size == 13
    kv3.close()
    # a SEALED chunk failing to parse is corruption and must be loud
    with open(os.path.join(tdir, "c.000001.chunk"), "r+b") as fh:
        fh.truncate(7)
    with pytest.raises(IOError):
        KvChunked(tdir, "c", chunk_records=5)


def test_kv_chunked_backs_a_ledger(tdir):
    """The chunked store slots in as a Ledger txn log unchanged."""
    from plenum_tpu.storage.kv_chunked import KvChunked
    from plenum_tpu.ledger.ledger import Ledger
    led = Ledger(txn_log=KvChunked(tdir, "txns", chunk_records=8))
    for i in range(20):
        led.append({"txn": {"type": "1", "data": {"i": i}},
                    "txnMetadata": {}, "ver": "1"})
    root = led.root_hash
    led.close()
    led2 = Ledger(txn_log=KvChunked(tdir, "txns", chunk_records=8))
    assert led2.size == 20
    assert led2.root_hash == root
    assert led2.get_by_seq_no(13)["txn"]["data"]["i"] == 12


def test_kv_chunked_drop_sealed_chunks(tdir):
    from plenum_tpu.storage.kv_chunked import KvChunked
    kv = KvChunked(tdir, "c", chunk_records=4)
    for i in range(20):
        kv.put(i, b"d%d" % i)
    assert kv.chunk_count == 5
    assert kv.drop_sealed_chunks_before(3) == 2
    assert kv.chunk_count == 3
    # live view unaffected; the tail chunk is never dropped
    assert kv.get(0) == b"d0"
    assert kv.drop_sealed_chunks_before(999) == 2   # all sealed, not tail
    kv.close()
