"""Self-healing crypto plane (parallel/supervisor.py + parallel/faults.py):
breaker lifecycle, re-warm before re-admission, flap hysteresis, adaptive
deadlines with hedged CPU fallback (no-fork invariant), backpressure, and
the per-request deadline budget of the service client — driven by the
deterministic fault injector on an injected clock, plus real-wall-clock
integration against a live CryptoPlaneServer."""
from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from plenum_tpu.crypto.ed25519 import (CpuEd25519Verifier, Ed25519Signer,
                                       make_verifier)
from plenum_tpu.parallel.faults import FaultPlan, FaultyVerifier
from plenum_tpu.parallel.supervisor import (CLOSED, HALF_OPEN, OPEN,
                                            CircuitBreaker, DeadlineBudget,
                                            SupervisedVerifier,
                                            find_supervisor, supervise)

_signer = Ed25519Signer(seed=b"supervisor-tests".ljust(32, b"\0"))


def _items(tag: bytes, n: int = 3, bad: int = -1):
    out = []
    for i in range(n):
        msg = tag + b"-%d" % i
        sig = _signer.sign(msg if i != bad else msg + b"!")
        out.append((msg, sig, _signer.verkey))
    return out


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _plane(fail_threshold=2, cooldown=1.0, **budget_kw):
    clock = _Clock()
    dev = FaultyVerifier(CpuEd25519Verifier(), now=clock)
    sup = SupervisedVerifier(
        dev, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=fail_threshold,
                               cooldown=cooldown, now=clock),
        budget=DeadlineBudget(base=0.3, min_s=0.2, warm_max=1.0,
                              cold_max=1.0, **budget_kw),
        now=clock)
    return clock, dev, sup


# --- breaker lifecycle ------------------------------------------------------


def test_closed_to_open_on_k_consecutive_deadline_misses():
    clock, dev, sup = _plane(fail_threshold=2)
    assert sup.verify_batch(_items(b"warm")).all()
    assert sup.breaker.state == CLOSED
    dev.wedge()
    for i in range(2):
        tok = sup.submit_batch(_items(b"wedged-%d" % i))
        assert tok.kind == "dev"
        clock.advance(2.0)                       # past the deadline budget
        verdicts = sup.collect_batch(tok, wait=False)
        assert verdicts is not None and verdicts.all()   # hedged, correct
    assert sup.breaker.state == OPEN
    assert sup.breaker.opens == 1
    assert sup.stats["deadline_misses"] == 2
    # open circuit: dispatch routes to CPU INSTANTLY (no device submit)
    before = dev.submits
    tok = sup.submit_batch(_items(b"instant"))
    assert tok.kind == "cpu" and dev.submits == before
    assert sup.collect_batch(tok).all()
    assert sup.stats["open_circuit_fallbacks"] >= 1


def test_device_errors_also_trip_the_breaker():
    clock, dev, sup = _plane(fail_threshold=3)
    dev.drop()                                  # connection refused
    for i in range(3):
        assert sup.verify_batch(_items(b"drop-%d" % i)).all()
    assert sup.breaker.state == OPEN
    assert sup.stats["device_errors"] == 3


def test_half_open_probe_rewarns_before_readmitting():
    clock, dev, sup = _plane(fail_threshold=1, cooldown=1.0)
    dev.corrupt()
    assert sup.verify_batch(_items(b"c")).all()          # error -> open
    assert sup.breaker.state == OPEN
    dev.heal()
    clock.advance(1.5)                                   # cooldown elapsed
    sup.submit_batch(_items(b"trigger"))                 # starts the probe
    assert sup.breaker.state in (HALF_OPEN, CLOSED)
    assert dev.rewarms == 1, "re-warm must precede the probe dispatch"
    sup.submit_batch(_items(b"poll"))                    # probe lands
    assert sup.breaker.state == CLOSED
    # the device is genuinely re-admitted
    tok = sup.submit_batch(_items(b"back"))
    assert tok.kind == "dev" and sup.collect_batch(tok).all()


def test_probe_verdict_must_be_correct_not_just_present():
    """A device that answers but answers WRONG (all-True garbage) must not
    be re-admitted: the probe carries a known-bad signature."""

    class _LyingVerifier(CpuEd25519Verifier):
        def verify_batch(self, items):
            return np.ones(len(items), dtype=bool)

    clock = _Clock()
    dev = FaultyVerifier(_LyingVerifier(), now=clock)
    sup = SupervisedVerifier(
        dev, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=1, cooldown=1.0, now=clock),
        budget=DeadlineBudget(base=0.3, min_s=0.2, warm_max=1.0,
                              cold_max=1.0),
        now=clock)
    dev.drop()
    sup.verify_batch(_items(b"x"))
    assert sup.breaker.state == OPEN
    dev.heal()
    clock.advance(1.5)
    sup.submit_batch(_items(b"t1"))          # probe starts
    sup.submit_batch(_items(b"t2"))          # probe lands: [True, True] != expected
    assert sup.breaker.state == OPEN, "lying device must stay quarantined"
    assert sup.stats["probe_failures"] >= 1


def test_flap_hysteresis_doubles_cooldown_and_decays():
    clock, dev, sup = _plane(fail_threshold=1, cooldown=1.0)
    base = sup.breaker.cooldown

    def flap_once():
        dev.wedge()
        tok = sup.submit_batch(_items(b"f%f" % clock.t))
        clock.advance(2.0)
        sup.collect_batch(tok, wait=False)            # miss -> open
        assert sup.breaker.state == OPEN
        dev.heal()
        clock.advance(sup.breaker.cooldown + 0.1)
        sup.submit_batch(_items(b"p%f" % clock.t))    # probe starts
        sup.submit_batch(_items(b"q%f" % clock.t))    # probe lands -> close
        assert sup.breaker.state == CLOSED

    flap_once()
    after_one = sup.breaker.cooldown          # first open: base cooldown
    flap_once()
    after_two = sup.breaker.cooldown
    flap_once()
    after_three = sup.breaker.cooldown
    # every RE-open (an open before the decay window passed) doubles the
    # probe cooldown: a flapping relay faces exponentially rarer probes,
    # not a thrash loop
    assert after_one == base
    assert after_two == base * 2
    assert after_three == base * 4
    # hysteresis decay: a long run of healthy traffic restores the base
    for i in range(sup.breaker.reset_after + 1):
        assert sup.verify_batch(_items(b"ok-%d" % i, n=1)).all()
    assert sup.breaker.cooldown == base


def test_failed_probe_reopens_with_longer_cooldown():
    clock, dev, sup = _plane(fail_threshold=1, cooldown=1.0)
    dev.wedge()
    tok = sup.submit_batch(_items(b"w"))
    clock.advance(2.0)
    sup.collect_batch(tok, wait=False)
    assert sup.breaker.state == OPEN
    clock.advance(1.5)                       # still wedged: probe will hang
    sup.submit_batch(_items(b"t"))           # probe starts (lost in wedge)
    assert sup.breaker.state == HALF_OPEN
    clock.advance(2.0)                       # probe deadline passes
    sup.submit_batch(_items(b"u"))           # reopen, cooldown doubled
    assert sup.breaker.state == OPEN
    assert sup.breaker.cooldown == 2.0
    assert sup.stats["probe_failures"] == 1


# --- hedged dispatch + no-fork invariant ------------------------------------


def test_hedged_race_verdicts_identical_per_item():
    """Device delayed past its budget: the CPU hedge answers; when the
    device verdict finally lands it is reaped and compared — identical
    per item (including the known-bad one), zero forks."""
    clock, dev, sup = _plane(fail_threshold=5)
    items = _items(b"hedge", n=5, bad=2)
    expected = [True, True, False, True, True]
    dev.delay(3.0)                            # longer than any budget
    tok = sup.submit_batch(items)
    assert sup.collect_batch(tok, wait=False) is None   # still in flight
    clock.advance(1.5)                        # past deadline
    verdicts = sup.collect_batch(tok, wait=False)
    assert list(verdicts) == expected         # CPU hedge verdict, correct
    assert sup.stats["hedge_wins"] == 1
    # the late device verdict lands; the reaper must compare and agree
    clock.advance(5.0)
    dev.heal()
    sup.submit_batch(_items(b"reap"))         # drives the zombie reaper
    assert sup.stats["late_landings"] == 1
    assert sup.stats["verdict_forks"] == 0


def test_blocking_collect_hedges_at_deadline_real_clock():
    """Wall-clock: a blocking collect on a wedged device returns the CPU
    verdict within the deadline budget — measured, not slept-and-hoped."""
    dev = FaultyVerifier(CpuEd25519Verifier())
    sup = SupervisedVerifier(
        dev, fallback=CpuEd25519Verifier(),
        budget=DeadlineBudget(base=0.4, min_s=0.3, warm_max=0.5,
                              cold_max=0.5))
    items = _items(b"block", n=4, bad=1)
    dev.wedge()
    t0 = time.monotonic()
    verdicts = sup.verify_batch(items)
    elapsed = time.monotonic() - t0
    assert list(verdicts) == [True, False, True, True]
    assert elapsed < 2.0, f"stall {elapsed:.2f}s exceeded the budget"
    assert sup.stats["hedge_wins"] == 1
    assert sup.stats["max_stall_s"] <= sup.stats["max_budget_s"] + 0.5


# --- backpressure -----------------------------------------------------------


def test_backpressure_watermark_routes_to_cpu():
    clock, dev, sup = _plane()
    sup.max_outstanding_bytes = 400
    dev.delay(10.0)                           # keep dispatches in flight
    big = _items(b"x" * 100, n=3)             # ~300+ bytes over watermark
    t1 = sup.submit_batch(big)
    assert t1.kind == "dev"
    t2 = sup.submit_batch(big)
    assert t2.kind == "cpu", "past the watermark new batches go straight to CPU"
    assert sup.stats["backpressure_fallbacks"] == 1
    assert sup.collect_batch(t2).all()


# --- deadline budget --------------------------------------------------------


def test_deadline_budget_cold_then_warm_ceiling():
    b = DeadlineBudget(base=1.0, per_item_initial=0.5, margin=2.0,
                       min_s=0.5, warm_max=10.0, cold_max=300.0)
    # cold: a first dispatch may sit behind a multi-minute compile
    assert b.budget(1000) == 300.0
    b.record(1000, 2.0)                       # first success: warmed
    assert b.budget(1000) <= 10.0
    # p99 of observed per-item cost now drives the estimate
    assert b.per_item_p99() == pytest.approx(0.002)
    assert b.budget(100) == pytest.approx(1.0 + 100 * 0.002 * 2.0)


def test_deadline_budget_scales_with_batch_size():
    b = DeadlineBudget(base=0.5, margin=4.0, min_s=0.25, warm_max=30.0)
    for _ in range(10):
        b.record(100, 0.5)                    # 5 ms/item observed
    assert b.budget(10) < b.budget(1000)
    assert b.budget(1000) == pytest.approx(0.5 + 1000 * 0.005 * 4.0)


# --- fault injector determinism ---------------------------------------------


def test_fault_plan_is_pure_function_of_seed():
    for seed in (0, 1, 7, 12345):
        a, b = FaultPlan.from_seed(seed), FaultPlan.from_seed(seed)
        assert a.windows == b.windows
    assert FaultPlan.from_seed(1).windows != FaultPlan.from_seed(2).windows


def test_fault_plan_drives_modes_by_clock():
    plan = FaultPlan([(1.0, 2.0, "wedge"), (3.0, 4.0, "drop")])
    clock = _Clock()
    dev = FaultyVerifier(CpuEd25519Verifier(), plan=plan, now=clock)
    assert dev.mode() == "ok"
    clock.t = 1.5
    assert dev.mode() == "wedge"
    clock.t = 2.5
    assert dev.mode() == "ok"
    clock.t = 3.5
    with pytest.raises(ConnectionError):
        dev.submit_batch(_items(b"planned"))


def test_wedge_loses_inflight_tokens_even_after_heal():
    clock = _Clock()
    dev = FaultyVerifier(CpuEd25519Verifier(), now=clock)
    tok = dev.submit_batch(_items(b"inflight"))
    dev.wedge()
    dev.heal()
    # the reply died with the wedge; it must never resolve
    assert dev.collect_batch(tok, wait=False) is None
    with pytest.raises(ConnectionError):
        dev.collect_batch(tok, wait=True)


# --- factory + wiring -------------------------------------------------------


def test_make_verifier_wraps_device_backends():
    jax = pytest.importorskip("jax")
    del jax
    v = make_verifier("jax", min_batch=8)
    assert isinstance(v, SupervisedVerifier)
    assert type(v._device).__name__ == "JaxEd25519Verifier"
    assert find_supervisor(v) is v
    # bare escape hatch
    v2 = make_verifier("jax", min_batch=8, supervised=False)
    assert not isinstance(v2, SupervisedVerifier)
    # cpu stays bare: there is nothing to supervise
    assert not isinstance(make_verifier("cpu"), SupervisedVerifier)


def test_supervisor_delegates_device_attributes():
    _, dev, sup = _plane()
    dev.extra_attribute = 42
    assert sup.extra_attribute == 42
    with pytest.raises(AttributeError):
        sup._not_proxied


# --- service-client deadline + live-server integration ----------------------


class _WedgeableCpu(CpuEd25519Verifier):
    """Inner verifier whose verify can be held wedged from the test."""

    def __init__(self):
        super().__init__()
        self.hold = threading.Event()

    def verify_batch(self, items):
        deadline = time.monotonic() + 30.0
        while self.hold.is_set() and time.monotonic() < deadline:
            time.sleep(0.01)
        return super().verify_batch(items)


@pytest.fixture
def live_service(tmp_path):
    import asyncio

    from plenum_tpu.parallel.crypto_service import CryptoPlaneServer
    inner = _WedgeableCpu()
    sock = str(tmp_path / "crypto.sock")
    server = CryptoPlaneServer(inner, socket_path=sock)
    started = threading.Event()

    async def run():
        await server.start()
        started.set()
        while not server._stop.is_set():
            await asyncio.sleep(0.02)
        await server.stop()

    t = threading.Thread(
        target=lambda: asyncio.new_event_loop().run_until_complete(run()),
        daemon=True)
    t.start()
    assert started.wait(5.0)
    yield server, inner, sock
    server._stop.set()
    t.join(timeout=5.0)


def test_service_client_wedge_costs_one_bounded_miss(live_service):
    """The satellite fix for the flat request_timeout=300: a wedged relay
    costs ONE per-request deadline budget (a few seconds warm), measured
    on the wall clock — not a 5-minute stall per batch."""
    from plenum_tpu.parallel.crypto_service import ServiceEd25519Verifier
    server, inner, sock = live_service
    client = ServiceEd25519Verifier(socket_path=sock, request_timeout=60.0,
                                    warm_timeout=5.0)
    assert client.verify_batch(_items(b"warmup")).all()   # warms the budget
    inner.hold.set()
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="deadline budget"):
        client.verify_batch(_items(b"wedged"))
    elapsed = time.monotonic() - t0
    inner.hold.clear()
    # warm budget: base 2s + small per-item term, nowhere near 60 or 300
    assert elapsed < 10.0, f"wedge cost {elapsed:.1f}s — deadline not applied"
    assert elapsed > 0.5
    client.close()


def test_supervised_service_client_survives_wedge_and_recovers(live_service):
    """End to end on the wall clock: supervise(service client) keeps
    returning correct verdicts through a server-side wedge (hedged CPU),
    opens the breaker, then re-admits the plane after heal + probe."""
    from plenum_tpu.parallel.crypto_service import ServiceEd25519Verifier
    server, inner, sock = live_service
    sup = SupervisedVerifier(
        ServiceEd25519Verifier(socket_path=sock, request_timeout=60.0,
                               warm_timeout=5.0),
        fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=2, cooldown=0.3),
        budget=DeadlineBudget(base=0.4, min_s=0.3, warm_max=0.6,
                              cold_max=0.6))
    assert sup.verify_batch(_items(b"pre", bad=0)).tolist() == \
        [False, True, True]
    inner.hold.set()
    t0 = time.monotonic()
    for i in range(3):                        # misses open the breaker
        assert sup.verify_batch(_items(b"mid-%d" % i, bad=1)).tolist() == \
            [True, False, True]
    worst = time.monotonic() - t0
    assert sup.breaker.state == OPEN
    assert worst < 6.0, f"3 wedged batches took {worst:.1f}s"
    # open circuit: instant CPU, no network wait at all
    t0 = time.monotonic()
    assert sup.verify_batch(_items(b"open")).all()
    assert time.monotonic() - t0 < 0.2
    # heal: probe + re-warm (reconnect) re-admits the plane
    inner.hold.clear()
    time.sleep(0.4)                           # cooldown elapses
    deadline = time.monotonic() + 10.0
    while sup.breaker.state != CLOSED and time.monotonic() < deadline:
        sup.verify_batch(_items(b"drive-%f" % time.monotonic(), n=1))
        time.sleep(0.05)
    assert sup.breaker.state == CLOSED
    tok = sup.submit_batch(_items(b"readmitted"))
    assert tok.kind == "dev"
    assert sup.collect_batch(tok).all()
    assert sup.stats["verdict_forks"] == 0
    sup.close()
