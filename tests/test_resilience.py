"""WAN-degraded operation hardening: jittered backoff, RTT-adaptive
catchup pacing, the catchup progress watchdog, read-only degradation,
membership-aware bus filtering, and key-rotation key-table eviction.

The deterministic A/B here is the acceptance shape for the hardening:
the SAME seed, the SAME fault (catchup replies dropped for a window),
one arm on the legacy flat 5 s retry timer and one on the RTT-adaptive
backoff — flat misses the recovery deadline the adaptive path makes.
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.backoff import ExponentialBackoff, RttEstimator
from plenum_tpu.common.node_messages import (CatchupRep, CatchupReq,
                                             DOMAIN_LEDGER_ID, LedgerStatus)
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.network import Discard, match_dst, match_frm
from plenum_tpu.network.sim_network import match_type

from test_pool import Pool, signed_nym

QUIET = dict(Max3PCBatchWait=0.05,
             STATE_FRESHNESS_UPDATE_INTERVAL=600.0,
             STUCK_BEHIND_CHECK_FREQ=600.0,
             PerfCheckFreq=600.0)


# --- backoff / RTT primitives ----------------------------------------------


def test_exponential_backoff_bounds_growth_and_jitter():
    b = ExponentialBackoff(base=0.1, cap=1.0, jitter=0.5, salt="x")
    seq = [b.next() for _ in range(8)]
    for k, d in enumerate(seq):
        raw = min(0.1 * 2 ** k, 1.0)
        assert 0.5 * raw - 1e-9 <= d <= raw + 1e-9, (k, d)
    # truncation: late attempts hover at the cap band, not beyond
    assert seq[-1] <= 1.0


def test_backoff_desynchronizes_across_salts_and_replays_per_salt():
    a = ExponentialBackoff(base=0.1, cap=1.0, salt="Alpha->Beta")
    c = ExponentialBackoff(base=0.1, cap=1.0, salt="Gamma->Beta")
    seq_a = [a.next() for _ in range(8)]
    seq_c = [c.next() for _ in range(8)]
    assert seq_a != seq_c                     # no stampede lockstep
    replay = ExponentialBackoff(base=0.1, cap=1.0, salt="Alpha->Beta")
    assert [replay.next() for _ in range(8)] == seq_a
    # reset returns to the floor but keeps the jitter stream advancing
    a.reset()
    assert a.next() <= 0.1 + 1e-9


def test_tcp_dial_backoff_is_the_jittered_one():
    """The reconnect-stampede fix: two dialers' retry schedules differ,
    both bounded by RETRY_MIN doubling to RETRY_MAX."""
    from plenum_tpu.network import tcp_stack
    a = tcp_stack._retry_backoff("Alpha", "Beta")
    g = tcp_stack._retry_backoff("Gamma", "Beta")
    seq_a = [a.next() for _ in range(6)]
    seq_g = [g.next() for _ in range(6)]
    assert seq_a != seq_g
    for k, d in enumerate(seq_a):
        raw = min(tcp_stack.RETRY_MIN * 2 ** k, tcp_stack.RETRY_MAX)
        assert (1 - tcp_stack.RETRY_JITTER) * raw - 1e-9 <= d <= raw + 1e-9


def test_rtt_estimator_rfc6298_shape():
    r = RttEstimator()
    # no samples: fallback wins, clamped
    assert r.timeout(floor=0.1, cap=10.0, fallback=5.0) == 5.0
    assert r.timeout(floor=6.0, cap=10.0, fallback=5.0) == 6.0
    r.note(0.2)
    assert r.srtt == 0.2 and r.rttvar == 0.1
    # srtt + 4*rttvar = 0.6
    assert abs(r.timeout(floor=0.0, cap=10.0) - 0.6) < 1e-9
    for _ in range(50):
        r.note(0.2)                           # stable link: variance decays
    assert r.timeout(floor=0.0, cap=10.0) < 0.3
    r.note(-1.0)                              # clock skew: ignored
    assert r.samples == 51


# --- deterministic flat-vs-adaptive catchup A/B -----------------------------


def _catchup_ab_arm(adaptive: bool, seed: int = 31, heal_at: float = 1.0):
    """One arm: Delta partitioned while 2 txns order, healed, then its
    catchup runs with every CatchupRep TO Delta dropped for the first
    `heal_at` seconds — a lossy-WAN blip eating one request/reply
    exchange. -> sim seconds from catchup start to Delta fully synced
    (None if not synced within 25 s)."""
    config = Config(**QUIET, CATCHUP_ADAPTIVE_TIMEOUTS=adaptive,
                    CATCHUP_WATCHDOG_INTERVAL=600.0)
    pool = Pool(seed=seed, config=config)
    pool.net.set_latency(0.02, 0.1)
    users = [Ed25519Signer(seed=(b"ab-%d" % i).ljust(32, b"\0")[:32])
             for i in range(2)]
    part = [pool.net.add_rule(Discard(), match_dst("Delta")),
            pool.net.add_rule(Discard(), match_frm("Delta"))]
    others = [n for n in pool.names if n != "Delta"]
    for i, u in enumerate(users):
        pool.submit(signed_nym(pool.trustee, u, i + 1), to=others)
    pool.run(6.0)
    sizes = {len_of(pool, n) for n in others}
    assert sizes == {3}, sizes               # genesis + 2, Delta at 1
    for rule in part:
        pool.net.remove_rule(rule)
    drop = pool.net.add_rule(Discard(), match_dst("Delta"),
                             match_type(CatchupRep))
    delta = pool.nodes["Delta"]
    t0 = pool.timer.get_current_time()
    delta.start_catchup()
    healed = False
    elapsed = 0.0
    while elapsed < 25.0:
        pool.run(0.25)
        elapsed += 0.25
        if not healed and elapsed >= heal_at:
            pool.net.remove_rule(drop)
            healed = True
        if len_of(pool, "Delta") >= 3 and not delta.leecher.is_running:
            return pool.timer.get_current_time() - t0
    return None


def len_of(pool, name):
    from test_sim_fuzz import _domain_txns
    return len(_domain_txns(pool.nodes[name]))


def test_catchup_adaptive_beats_flat_timeout_deterministically():
    """Same seed, same fault (one catchup request/reply exchange eaten
    by the lossy link): the RTT-adaptive retry re-asks within a few
    measured round trips and completes; the flat 5 s timer sits out its
    full period first — at the recovery DEADLINE between them, flat has
    stalled where adaptive completed. THE acceptance A/B."""
    adaptive = _catchup_ab_arm(adaptive=True)
    flat = _catchup_ab_arm(adaptive=False)
    assert adaptive is not None, "adaptive arm never completed"
    assert flat is not None, "flat arm never completed (even eventually)"
    deadline = 4.0          # > heal + several RTTs, < the flat 5 s timer
    assert adaptive < deadline, (adaptive, flat)
    assert flat > deadline, (adaptive, flat)
    assert adaptive + 1.0 < flat, (adaptive, flat)


# --- catchup progress watchdog + provider switching ------------------------


def test_catchup_watchdog_kicks_then_restarts_a_stalled_round():
    config = Config(**QUIET, CATCHUP_WATCHDOG_INTERVAL=2.0,
                    CATCHUP_WATCHDOG_RESTART_KICKS=3)
    pool = Pool(seed=37, config=config)
    pool.net.set_latency(0.02, 0.1)
    users = [Ed25519Signer(seed=(b"wd-%d" % i).ljust(32, b"\0")[:32])
             for i in range(2)]
    part = [pool.net.add_rule(Discard(), match_dst("Delta")),
            pool.net.add_rule(Discard(), match_frm("Delta"))]
    others = [n for n in pool.names if n != "Delta"]
    for i, u in enumerate(users):
        pool.submit(signed_nym(pool.trustee, u, i + 1), to=others)
    pool.run(6.0)
    for rule in part:
        pool.net.remove_rule(rule)
    drop = pool.net.add_rule(Discard(), match_dst("Delta"),
                             match_type(CatchupRep))
    delta = pool.nodes["Delta"]
    delta.start_catchup()
    pool.run(9.0)            # several watchdog intervals, reps all dropped
    kicks = [e for e in delta.spylog if e[0] == "catchup_watchdog_kick"]
    assert kicks, "watchdog never fired on a frozen catchup"
    assert delta.leecher.is_running          # restarted, not wedged
    pool.net.remove_rule(drop)
    pool.run(10.0)
    assert len_of(pool, "Delta") >= 3
    assert not delta.leecher.is_running
    # stall accounting reached the metrics plane
    summary = delta.metrics.summary()
    from plenum_tpu.common.metrics import MetricsName
    assert summary.get(MetricsName.CATCHUP_WATCHDOG_KICKS, {}).get("count")
    assert summary.get(MetricsName.CATCHUP_DURATION, {}).get("count")
    # the all-peers stall sidelined providers at least once
    switches = delta.leecher.round_stats()["provider_switches"]
    assert switches >= 1, delta.leecher.round_stats()


# --- graceful degradation: read-only instead of wedging ---------------------


def test_diverged_catchup_degrades_to_read_only_serving():
    config = Config(**QUIET, CATCHUP_MAX_DIVERGED_ROUNDS=2)
    pool = Pool(seed=41, config=config)
    user = Ed25519Signer(seed=b"deg-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(6.0)
    assert len_of(pool, "Delta") == 2

    delta = pool.nodes["Delta"]
    # simulate a catchup round that ended in divergence, twice (the rep
    # service sets .diverged when every provider's chunk conflicts with
    # the f+1-agreed target — fabricating that end-to-end needs >f
    # correlated amnesia, outside the sim's fault model, so the node
    # seam is driven directly)
    delta.start_catchup()                    # pauses ordering
    delta.leecher.stop()
    lid = delta.leecher._order[0]
    delta.leecher.leechers[lid].rep.diverged = True
    delta._on_catchup_complete(None)         # diverged round 1: retry
    assert not delta.read_only_degraded
    delta._on_catchup_complete(None)         # diverged round 2: degrade
    assert delta.read_only_degraded
    assert any(e[0] == "degraded_read_only" for e in delta.spylog)

    # degraded = no new catchup rounds, no ordering participation...
    delta.start_catchup()
    assert not delta.leecher.is_running
    user2 = Ed25519Signer(seed=b"deg-user2".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user2, 2))
    pool.run(8.0)
    survivors = [n for n in pool.names if n != "Delta"]
    assert {len_of(pool, n) for n in survivors} == {3}
    assert len_of(pool, "Delta") == 2        # parked, not participating

    # ...but verified reads still serve at the LAST ANCHORED root
    from plenum_tpu.common.request import Request
    from plenum_tpu.execution.txn import GET_NYM
    from plenum_tpu.reads import READ_PROOF
    res = delta.read_plane.answer(
        Request("ro-cli", 9, {"type": GET_NYM, "dest": user.identifier}))
    assert res["data"]["verkey"] == user.verkey_b58
    env = res.get(READ_PROOF)
    assert env is not None and env.get("multi_signature"), \
        "degraded node stopped serving anchored proofs"
    info = delta.validator_info()
    assert info["read_only_degraded"] is True


# --- membership-aware bus filter (catchup-to-join) --------------------------


def test_known_non_validator_is_served_catchup_to_join():
    """A pool-ledger-known but demoted node that restarts from genesis
    can catch up from the validators (the joiner filter admits its
    LedgerStatus/CatchupReq), while its replies/votes stay filtered."""
    names = ["Alpha", "Beta", "Gamma", "Delta", "Eps"]
    pool = Pool(names=names, validator_names=names[:4],
                config=Config(**QUIET))
    user = Ed25519Signer(seed=b"join-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(6.0)
    assert len_of(pool, "Alpha") == 2

    # Eps restarts with NO memory (fresh from genesis, still demoted)
    pool.crash_node("Eps")
    pool.start_node("Eps")
    pool.net.connect_all()
    eps = pool.nodes["Eps"]
    assert len_of(pool, "Eps") == 1
    assert "Eps" not in pool.nodes["Alpha"].validators

    # the joiner filter: queries pass, replies/votes do not
    alpha = pool.nodes["Alpha"]
    req = CatchupReq(ledger_id=DOMAIN_LEDGER_ID, seq_no_start=1,
                     seq_no_end=2, catchup_till=2)
    assert alpha._accept_joiner_msg(req, "Eps")
    assert alpha._accept_joiner_msg(
        LedgerStatus(ledger_id=DOMAIN_LEDGER_ID, txn_seq_no=1,
                     merkle_root="00", view_no=None, pp_seq_no=None), "Eps")
    assert not alpha._accept_joiner_msg(
        LedgerStatus(ledger_id=DOMAIN_LEDGER_ID, txn_seq_no=1,
                     merkle_root="00", view_no=None, pp_seq_no=None,
                     is_reply=True), "Eps")
    assert not alpha._accept_joiner_msg(
        CatchupRep(ledger_id=DOMAIN_LEDGER_ID, txns={}, cons_proof=()),
        "Eps")
    assert not alpha._accept_joiner_msg(req, "NotInLedger")

    eps.start_catchup()
    pool.run(15.0)
    assert len_of(pool, "Eps") == 2, "joiner was not served catchup"
    assert not eps.leecher.is_running


# --- key rotation: stale-key commits + key-table eviction -------------------


def test_rotated_out_bls_key_is_excluded_without_poisoning_quorum():
    """A validator whose ledger BLS key rotated but whose process still
    signs with the OLD key: its commits fail the batch check and are
    culprit-named (PR 2 path) — they never count toward the multi-sig
    quorum and never poison the batch for honest signers; the pool keeps
    ordering and, after the operator re-keys, the node rejoins
    aggregates. The rotated-out key is also evicted from every node's
    BLS key table."""
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.bls import BlsCryptoSigner
    from plenum_tpu.execution.txn import NODE

    pool = Pool(seed=53, config=Config(**QUIET))
    u0 = Ed25519Signer(seed=b"rot2-u0".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u0, 1))
    pool.run(5.0)

    old_pk = BlsCryptoSigner(seed=b"Gamma".ljust(32, b"\0")[:32]).pk
    # the old key is warm in the verifiers' key tables
    assert any(old_pk in node.replicas.master.bls._verifier._vk_cache
               for node in pool.nodes.values())

    new_signer = BlsCryptoSigner(seed=b"gamma-rot2".ljust(32, b"\0")[:32])
    req = Request(pool.trustee.identifier, 10,
                  {"type": NODE, "dest": "GammaDest",
                   "data": {"blskey": new_signer.pk,
                            "blskey_pop": new_signer.generate_pop()}})
    req.signature = pool.trustee.sign_b58(req.signing_bytes())
    pool.submit(req)
    pool.run(5.0)
    for name, node in pool.nodes.items():
        assert node.pool_manager.bls_key_of("Gamma") == new_signer.pk
        # eviction: the dead key left the key table on every node
        assert old_pk not in node.replicas.master.bls._verifier._vk_cache, \
            name
        ms = node.metrics.summary()
        from plenum_tpu.common.metrics import MetricsName
        assert ms.get(MetricsName.MEMBERSHIP_KEY_ROTATIONS, {}).get("sum")

    # Gamma's signer is STALE: its commits carry old-key signatures
    for i in range(2, 5):
        u = Ed25519Signer(seed=(b"rot2-u%d" % i).ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, u, i))
        pool.run(4.0)
    sizes = {len_of(pool, n) for n in pool.names}
    assert sizes == {5}, sizes               # pool stayed live throughout
    for name in pool.names:
        node = pool.nodes[name]
        assert node.master_replica.view_no == 0, name   # no VC storm
        if name == "Gamma":
            continue
        bls = node.replicas.master.bls
        # stale-key commits were culprit-named, and the post-rotation
        # aggregates exclude Gamma rather than dying
        assert any("Gamma" in bad for bad in bls._known_bad.values()), name
        post = [m for m in bls._recent_multi_sigs.values()]
        assert post and all("Gamma" not in m.participants
                            for m in post[-2:]), name

    # operator re-keys Gamma: recovery — fresh aggregates include it
    pool.nodes["Gamma"].replicas.master.bls._signer = new_signer
    for i in range(5, 8):
        u = Ed25519Signer(seed=(b"rot2-u%d" % i).ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, u, i))
        pool.run(4.0)
    assert {len_of(pool, n) for n in pool.names} == {8}
    ms = pool.nodes["Alpha"].replicas.master.bls._recent_multi_sigs
    assert any("Gamma" in m.participants for m in list(ms.values())[-2:])


def test_crypto_plane_key_eviction_seams():
    """evict_key drops exactly the named key from each key table: the
    CPU verifier's parsed-key cache, the device verifier's staged
    quarter-point rows, the BLS decoded-G2 table — and the pipeline
    forwards to its inners."""
    from plenum_tpu.crypto.bls import BlsCryptoSigner, BlsCryptoVerifier
    from plenum_tpu.crypto.ed25519 import (CpuEd25519Verifier,
                                           JaxEd25519Verifier)
    from plenum_tpu.parallel.pipeline import CryptoPipeline

    signer = Ed25519Signer(seed=b"evict-me".ljust(32, b"\0"))
    vk = signer.verkey
    cpu = CpuEd25519Verifier()
    # the parsed-key cache only fills on the cryptography-backed path
    # (this container runs the pure-Python fallback) — seed it directly:
    # eviction semantics are what's under test, not the backend
    if hasattr(cpu, "_pk_cache"):
        cpu._pk_cache[vk] = object()
        cpu.evict_key(vk)
        assert vk not in cpu._pk_cache

    dev = JaxEd25519Verifier()
    dev._neg_a_limbs(vk)
    assert vk in dev._pt_cache
    dev.evict_key(vk)
    assert vk not in dev._pt_cache

    bls_pk = BlsCryptoSigner(seed=b"evict-bls".ljust(32, b"\0")[:32]).pk
    bls = BlsCryptoVerifier()
    bls._pk(bls_pk)
    assert bls_pk in bls._vk_cache
    bls.evict_key(bls_pk)
    assert bls_pk not in bls._vk_cache

    pipe = CryptoPipeline(ed_inner=cpu, bls_inner=bls)
    cpu._pk_cache[vk] = object()
    bls._pk(bls_pk)
    pipe.evict_key(vk)
    pipe.evict_key(bls_pk)
    assert vk not in cpu._pk_cache and bls_pk not in bls._vk_cache


# --- metrics_report: view_change / catchup / membership sections ------------


def test_metrics_report_churn_sections():
    from plenum_tpu.tools.metrics_report import derive_summary

    def fold(count=1, total=0.0, samples=None, last=None, mn=None, mx=None):
        return {"count": count, "sum": total, "mean":
                (total / count) if count else None, "min": mn, "max": mx,
                "last": last, "flushes": 1,
                **({"samples": samples} if samples else {})}

    folds = {
        "view_change.duration": fold(3, 6.0, samples=[1.0, 2.0, 3.0]),
        "consensus.vc_detect_to_vote": fold(3, 1.5),
        "catchup.duration": fold(2, 9.0, samples=[4.0, 5.0]),
        "catchup.rounds": fold(2, 7.0, samples=[3.0, 4.0]),
        "catchup.provider_switches": fold(1, 2.0),
        "catchup.watchdog_kicks": fold(4, 4.0),
        "catchup.degraded": fold(1, 1.0, mx=1.0),
        "membership.pool_changes": fold(5, 5.0),
        "membership.validators": fold(5, 23.0, last=5.0, mn=4.0, mx=5.0),
        "membership.key_rotations": fold(2, 2.0),
    }
    out = derive_summary(folds, span_s=100.0)
    vc = out["view_change"]
    assert vc["episodes"] == 3
    assert vc["duration_s_p50"] == 2.0 and vc["duration_s_p95"] == 3.0
    assert vc["detect_to_vote_s"] == 0.5
    cu = out["catchup"]
    assert cu["completed"] == 2 and cu["duration_s_p95"] == 5.0
    assert cu["provider_switches"] == 2 and cu["watchdog_kicks"] == 4
    assert cu["read_only_degraded"] is True
    mem = out["membership"]
    assert mem == {"pool_changes": 5, "validators_last": 5,
                   "validators_min": 4, "validators_max": 5,
                   "key_rotations": 2}


# --- churn soak: bounded growth under churn ---------------------------------


def test_churn_soak_smoke_bounded_and_converged():
    """Fast tier-1 slice of the 10-minute churn soak: two churn waves
    over lossy_wan, every bounded-growth cap respected, pool converged —
    and the history plane wired in: growth verdicts on every footprint
    gauge with zero unbounded_growth alerts, and a queryable,
    downsampled history ring covering the run."""
    from plenum_tpu.tools.churn_soak import run_churn_soak
    out = run_churn_soak(seconds=40.0, seed=3)
    assert out["bounds_ok"], (out["violations"], out["growth_unexpected"])
    assert out["converged"], out["ledger_sizes"]
    assert out["waves"] >= 2 and "demote" in out["events"][0]
    # every footprint gauge got a growth verdict, none alerted
    for gauge in ("stashed_entries", "flight_ring_entries",
                  "bls_sig_entries", "kv_entries"):
        assert gauge in out["growth_verdicts"], out["growth_verdicts"]
    assert out["growth_alerts"] == []
    # the ring recorded one row per pool interval, downsampled on query
    assert out["history_seq"] >= out["waves"]
    assert 0 < len(out["history_tail"]) <= 12
    assert out["history_tail"][0]["seq"] < out["history_tail"][-1]["seq"]


def test_churn_soak_injected_leak_pages_once_naming_gauge():
    """The detector self-test: an injected unbounded gauge (leak_rate)
    raises EXACTLY ONE edge-triggered unbounded_growth page naming the
    gauge, while every real structure stays quiet."""
    from plenum_tpu.tools.churn_soak import run_churn_soak
    out = run_churn_soak(seconds=40.0, seed=3, leak_rate=8.0)
    assert out["bounds_ok"], (out["violations"], out["growth_unexpected"])
    pages = out["growth_alerts"]
    assert len(pages) == 1, pages
    assert pages[0]["subject"] == "leaky_stash"
    assert pages[0]["detail"]["gauge"] == "leaky_stash"
    assert out["growth_verdicts"]["leaky_stash"]["verdict"] == "growing"


@pytest.mark.slow
@pytest.mark.soak
def test_churn_soak_ten_minutes():
    """The full bounded-growth soak: 10 SIMULATED minutes of sustained
    writes + one churn event per 20 s wave (demote/promote, BLS
    rotation, primary demotion) over lossy_wan. Fails on the first
    bound violation or unbounded_growth page, so a leak names its
    structure and its wave; the history ring must hold a queryable,
    downsampled record of the whole run."""
    from plenum_tpu.tools.churn_soak import run_churn_soak
    out = run_churn_soak(seconds=600.0, seed=11)
    assert out["bounds_ok"], (out["violations"], out["growth_unexpected"])
    assert out["converged"], out["ledger_sizes"]
    assert out["growth_alerts"] == []
    # 600 sim-seconds at 1 s telemetry intervals: the ring saw the whole
    # run (seq counts every row) while holding at most HISTORY_MAX_SLOTS
    assert out["history_seq"] >= 500
    assert out["history_rows"] <= 512
    tail = out["history_tail"]
    assert 0 < len(tail) <= 12 and tail[0]["seq"] < tail[-1]["seq"]
