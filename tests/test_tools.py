"""Ops-surface tests: wallet, keygen, genesis files, and a real 4-process
pool started via the start_node script, written to and read from with the
PoolClient over TCP.

Reference test model: the scripts/ + client e2e flow (SURVEY.md §2 tools,
client wallet).
"""
from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- wallet ---------------------------------------------------------------

def test_wallet_sign_and_roundtrip(tmp_path):
    from plenum_tpu.client import Wallet
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.utils.base58 import b58decode

    w = Wallet("w1")
    did = w.add_identifier(seed=b"wallet-seed-0001".ljust(32, b"\0"))
    assert w.default_id == did
    req = w.sign_request({"type": NYM, "dest": "X", "verkey": "Y"})
    assert req.identifier == did and req.signature
    ok = CpuEd25519Verifier().verify(
        req.signing_bytes(), b58decode(req.signature),
        b58decode(w.verkey_of(did)))
    assert ok

    # persistence: same keys come back
    path = str(tmp_path / "wallet.bin")
    w.save(path)
    assert oct(os.stat(path).st_mode & 0o777) == "0o600"
    w2 = Wallet.load(path)
    assert w2.identifiers() == [did] and w2.default_id == did
    assert w2.verkey_of(did) == w.verkey_of(did)


# --- keygen + genesis -----------------------------------------------------

def test_keygen_and_genesis_files(tmp_path):
    from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID,
                                                 POOL_LEDGER_ID)
    from plenum_tpu.crypto.bls import verify_pop
    from plenum_tpu.tools import genesis as gen
    from plenum_tpu.tools import keygen

    base = str(tmp_path)
    for i, name in enumerate(("Alpha", "Beta")):
        keys = keygen.generate_keys(
            name, seed=(b"kg%d" % i).ljust(32, b"\0"))
        keygen.save_keys(keys, base)
        loaded = keygen.load_keys(base, name)
        assert loaded == keys
        assert verify_pop(keys["bls_pop"], keys["bls_pk"])

    out = gen.build_genesis_files(
        base, [("Alpha", "127.0.0.1", 9701, 9702),
               ("Beta", "127.0.0.1", 9703, 9704)],
        trustee_seed=b"t".ljust(32, b"\0"))
    assert os.path.exists(out["pool_genesis"])
    loaded = gen.load_genesis_files(base)
    assert len(loaded[POOL_LEDGER_ID]) == 2
    assert len(loaded[DOMAIN_LEDGER_ID]) == 1
    data = loaded[POOL_LEDGER_ID][0]["txn"]["data"]["data"]
    assert data["alias"] == "Alpha" and data["node_port"] == 9701


# --- 4 OS processes over real sockets -------------------------------------


@pytest.mark.slow
def test_four_process_pool_orders_nym(tmp_path):
    from plenum_tpu.client import PoolClient, Wallet
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.tools.tcp_pool import setup_pool_dir

    base = str(tmp_path)
    names = ["Node1", "Node2", "Node3", "Node4"]
    trustee_seed = b"proc-trustee".ljust(32, b"\0")
    specs = setup_pool_dir(base, names, trustee_seed)

    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    procs = []
    try:
        for name in names:
            cmd = [sys.executable, "-m", "plenum_tpu.tools.start_node",
                   "--name", name, "--base-dir", base, "--kv", "memory"]
            if name == "Node1":
                cmd.append("--record")     # exercised by the replay below
            procs.append(subprocess.Popen(
                cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT))
        # wait for every process to report "started"
        for p in procs:
            line = p.stdout.readline()
            assert b"started" in line, line

        wallet = Wallet("cli")
        trustee_did = wallet.add_identifier(seed=trustee_seed)
        user_did = wallet.add_identifier(seed=b"proc-user".ljust(32, b"\0"))
        req = wallet.sign_request(
            {"type": NYM, "dest": user_did,
             "verkey": wallet.verkey_of(user_did)}, identifier=trustee_did)

        async def run():
            client = PoolClient(
                {name: ("127.0.0.1", spec[3])
                 for name, spec in zip(names, specs)}, f=1)
            try:
                return await client.submit(req, timeout=30.0)
            finally:
                await client.close()

        reply = asyncio.run(run())
        assert reply["op"] == "REPLY", reply
        txn = reply["result"]
        assert txn["txn"]["data"]["dest"] == user_did
        assert txn["txnMetadata"]["seqNo"] == 2

        # offline replay of the recorded node reproduces its ledger state
        # (STACK_COMPANION story: record in production, debug offline)
        procs[0].send_signal(signal.SIGTERM)
        procs[0].wait(timeout=5)
        out = subprocess.run(
            [sys.executable, "-m", "plenum_tpu.tools.replay",
             "--name", "Node1", "--base-dir", base],
            env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        replayed = json.loads(out.stdout.strip().splitlines()[-1])
        from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
        dom = replayed["ledgers"][str(DOMAIN_LEDGER_ID)] \
            if str(DOMAIN_LEDGER_ID) in replayed["ledgers"] \
            else replayed["ledgers"][DOMAIN_LEDGER_ID]
        assert dom["size"] == 2            # genesis NYM + the ordered one
        assert replayed["last_ordered_3pc"][1] >= 1
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.slow
def test_tcp_pool_bench_orders_load():
    """The real-transport benchmark drives a 4-process TCP pool end to end:
    every request reaches an f+1 REPLY quorum over the wire."""
    from plenum_tpu.tools.tcp_pool import run_tcp_pool
    stats = run_tcp_pool(n_nodes=4, n_txns=30, timeout=90.0)
    assert stats["txns_ordered"] == 30, stats
    assert stats["tps"] > 0
    assert stats["p50_latency_ms"] is not None


def test_pipelined_client_survives_dead_node_and_reuse():
    """The pipelined client must tolerate an unreachable node (quorum
    covers it) and be reusable across drive() calls with a clean slate."""
    from plenum_tpu.client import PipelinedPoolClient
    from plenum_tpu.common.request import Request
    from plenum_tpu.common.serialization import pack, unpack

    async def main():
        async def serve(reader, writer):
            try:
                while True:
                    hdr = await reader.readexactly(4)
                    frame = await reader.readexactly(
                        int.from_bytes(hdr, "big"))
                    req = unpack(frame)
                    reply = pack({"op": "REPLY", "result": {"txn": {
                        "metadata": {"from": req["identifier"],
                                     "reqId": req["reqId"]}}}})
                    writer.write(len(reply).to_bytes(4, "big") + reply)
                    await writer.drain()
            except (asyncio.IncompleteReadError, OSError):
                return

        servers = [await asyncio.start_server(serve, "127.0.0.1", 0)
                   for _ in range(3)]
        addrs = {f"N{i}": ("127.0.0.1", s.sockets[0].getsockname()[1])
                 for i, s in enumerate(servers)}
        addrs["Ndead"] = ("127.0.0.1", 1)      # nothing listens there

        client = PipelinedPoolClient(addrs, f=1)
        reqs = [Request("idr", i, {"type": "1"}) for i in range(5)]
        done, _ = await client.drive(reqs, window=3, timeout=10.0)
        assert len(done) == 5

        # reuse: a smaller second batch must NOT be satisfied by stale state
        reqs2 = [Request("idr", 100 + i, {"type": "1"}) for i in range(2)]
        done2, _ = await client.drive(reqs2, window=2, timeout=10.0)
        assert set(done2) == {("idr", 100), ("idr", 101)}
        for s in servers:
            s.close()

    asyncio.run(main())


def test_metrics_report_reads_flushed_history(tmp_path):
    """tools.metrics_report turns a node's flushed metrics store into
    per-metric folds and a derived summary (ref scripts/process_logs)."""
    from plenum_tpu.common.metrics import KvMetricsCollector, MetricsName
    from plenum_tpu.storage.kv_file import KvFile
    from plenum_tpu.tools.metrics_report import main as report_main, report_node

    mdir = tmp_path / "Node1" / "metrics"
    clock = [1000.0]
    m = KvMetricsCollector(KvFile(str(mdir)), now=lambda: clock[0])
    for tick in range(3):
        for _ in range(10):
            m.add_event(MetricsName.ORDERED_BATCH_SIZE, 5)
        m.add_event(MetricsName.PREPARE_PHASE_TIME, 0.040)
        m.add_event(MetricsName.CLIENT_INBOX_DEPTH, tick)  # gauge: last wins
        m.flush()
        clock[0] += 10.0

    folds, summary = report_node(str(mdir), last_s=None)
    assert folds["node.ordered_batch_size"]["count"] == 30
    assert summary["txns_ordered"] == 150
    assert summary["window_s"] == 20.0            # 3 flushes, 10 s apart
    assert summary["tps"] == 7.5                  # 150 txns / 20 s
    assert summary["prepare_phase_ms"] == 40.0
    assert summary["client_inbox_depth_max"] == 2

    # the trailing-window filter drops the first flush
    _, tail = report_node(str(mdir), last_s=10.0)
    assert tail["txns_ordered"] == 100

    # CLI over the whole base dir, machine-readable
    import contextlib
    import io
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = report_main([str(tmp_path), "--json"])
    assert rc == 0
    out = json.loads(buf.getvalue())
    assert out["Node1"]["summary"]["txns_ordered"] == 150


def test_metrics_report_commit_stage_percentiles(tmp_path):
    """Commit-path stage timers flush bounded RAW samples so the report
    can print honest p50/p95 per stage (not just fold means), plus the
    pairings-per-batch and plane-dispatch counters that previously never
    reached the report."""
    from plenum_tpu.common.metrics import KvMetricsCollector, MetricsName
    from plenum_tpu.storage.kv_file import KvFile
    from plenum_tpu.tools.metrics_report import report_node

    mdir = tmp_path / "Node1" / "metrics"
    m = KvMetricsCollector(KvFile(str(mdir)), now=lambda: 1000.0)
    for i in range(100):
        m.add_event(MetricsName.COMMIT_BLS_VERIFY_TIME, 0.001 * (i + 1))
        m.add_event(MetricsName.COMMIT_DURABLE_TIME, 0.002)
        m.add_event(MetricsName.BLS_PAIRINGS_PER_BATCH, 2)
    m.add_event(MetricsName.SIG_BATCH_SIZE, 512)
    m.add_event(MetricsName.SIG_PLANE_DISPATCHES, 7)   # cumulative gauge
    m.add_event(MetricsName.BLS_PAIRING_CHECKS, 100)
    m.add_event(MetricsName.BLS_PAIRINGS, 200)
    m.flush()

    folds, summary = report_node(str(mdir), last_s=None)
    assert summary["bls_verify_ms_p50"] == pytest.approx(51.0, abs=2.0)
    assert summary["bls_verify_ms_p95"] == pytest.approx(96.0, abs=2.0)
    assert summary["durable_ms_p50"] == pytest.approx(2.0, abs=0.1)
    assert summary["pairings_per_batch"] == 2.0
    assert summary["pairing_checks_total"] == 100
    assert summary["pairings_total"] == 200
    assert summary["plane_dispatches"] == 7
    assert summary["sig_batch_size_mean"] == 512.0


def test_distinct_signers_config_orders_owner_writes():
    """config1b: n distinct client keys on the authN hot path — every
    ATTRIB owner-signed by its own DID (authorization: owner-or-trustee),
    so the figure reflects diverse-client traffic, not one amortized
    trustee key."""
    from plenum_tpu.tools.bench_configs import config1b_distinct_signers
    r = config1b_distinct_signers(n_txns=40, timeout=60.0)
    assert r.get("txns_ordered") == 40, r
    assert r["distinct_signers"] == 40


def test_replay_reproduces_span_sequence():
    """Record/replay x tracing determinism guard: replaying a recorded
    node under the mock clock reproduces a BYTE-IDENTICAL span sequence.
    Span timestamps come only from the injectable timer and payloads only
    from message content (wall_durations=False strips the perf_counter
    stage durations, the one legitimately non-deterministic field), so
    any divergence here means a span site leaked wall state into the
    trace — the property the flight-recorder postmortems rely on."""
    from plenum_tpu.common.event_bus import ExternalBus
    from plenum_tpu.common.timer import MockTimer
    from plenum_tpu.common.tracing import Tracer, span_sequence
    from plenum_tpu.config import Config
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.network import SimNetwork, SimRandom
    from plenum_tpu.node import Node, NodeBootstrap
    from plenum_tpu.node.recorder import Recorder, attach_recorder, replay
    from plenum_tpu.storage.kv_memory import KvMemory
    from test_pool import NODES, make_genesis, signed_nym

    genesis, trustee = make_genesis(NODES)
    timer = MockTimer()
    net = SimNetwork(timer, SimRandom(11))
    config = Config(Max3PCBatchWait=0.05)
    recorder = Recorder(KvMemory(), now=timer.get_current_time)
    nodes = {}
    for name in NODES:
        bus = net.create_peer(name)
        components = NodeBootstrap(name, genesis_txns=genesis).build()
        tracer = Tracer(name, timer.get_current_time,
                        wall_durations=False) if name == "Alpha" else None
        nodes[name] = Node(name, timer, bus, components, config=config,
                           tracer=tracer)
        if name == "Alpha":
            # before connect_all: the Connected events must be recorded
            attach_recorder(nodes[name], recorder)
    net.connect_all()

    user = Ed25519Signer(seed=b"replay-span-user".ljust(32, b"\0"))
    req = signed_nym(trustee, user, 1)
    for name in NODES:
        nodes[name].handle_client_message(req.to_dict(), "cli")
    for _ in range(100):
        for node in nodes.values():
            node.prod()
        timer.advance(0.05)
    live = span_sequence(nodes["Alpha"].tracer.snapshot())
    assert b'"ordered"' in live and b'"reply"' in live

    # fresh Alpha from the same genesis; feed the recorded stream back
    first_ts = next(ts for ts, *_ in recorder.iter_records())
    timer2 = MockTimer(start=first_ts)
    bus2 = ExternalBus(send_handler=lambda msg, dst: None)
    components2 = NodeBootstrap("Alpha", genesis_txns=genesis).build()
    tracer2 = Tracer("Alpha", timer2.get_current_time,
                     wall_durations=False)
    node2 = Node("Alpha", timer2, bus2, components2, config=config,
                 tracer=tracer2)
    replay(recorder.iter_records(), node2, timer2)
    assert span_sequence(tracer2.snapshot()) == live


def test_log_analyzer_unit(tmp_path):
    """Analyzer halves: error clustering over text, per-view timeline
    over structured events (ref scripts/process_logs redesign)."""
    import json as _json
    from plenum_tpu.tools.log_analyzer import analyze_node
    d = tmp_path / "NodeX"
    d.mkdir()
    (d / "node.log").write_text(
        "2026-01-01 WARNING stack undecodable message from Node2\n"
        "2026-01-01 WARNING stack undecodable message from Node3\n"
        "2026-01-01 ERROR svc handler failed for PrePrepare 17 from Node4\n"
        "plain info noise that must be ignored\n"
        "2026-01-01 ERROR svc handler failed for PrePrepare 99 from Node4\n")
    rows = [
        {"t": 10.0, "event": "restored_from_audit", "data": [0, 0]},
        {"t": 11.0, "event": "suspicion", "data": [21, "Node1"]},
        {"t": 12.5, "event": "vc_stall_phases",
         "data": {"detect": 11.0, "vote": 12.5, "start": 12.56,
                  "new_view": 12.58, "order": 12.9}},
        {"t": 13.0, "event": "view_change_complete", "data": 1},
        {"t": 14.0, "event": "catchup_started", "data": None},
    ]
    with open(d / "events.jsonl", "w") as fh:
        for r in rows:
            fh.write(_json.dumps(r) + "\n")
        fh.write('{"t": 15.0, "event": "torn')   # torn tail: tolerated
    rep = analyze_node(str(d))
    assert rep["event_counts"]["suspicion"] == 1
    # two clusters: the repeated undecodable (x2) and the failed handler
    # (x2, seq-no digits normalized into one template)
    levels = {(c["level"], c["count"]) for c in rep["error_clusters"]}
    assert levels == {("WARNING", 2), ("ERROR", 2)}
    views = rep["views"]
    assert [v["view_no"] for v in views] == [0, 1]
    assert views[0]["vc_stall"]["total_s"] == 1.9
    assert views[0]["vc_stall"]["phases"]["order"] == 1.9
    assert views[1]["events"] == {"catchup_started": 1}


def test_durable_spylog_survives_torn_tail(tmp_path):
    """Crash mid-write tears a line; the restarted log starts on a fresh
    line and the analyzer skips ONLY the torn line (review findings)."""
    from plenum_tpu.tools.log_analyzer import read_events
    from plenum_tpu.tools.start_node import _DurableSpylog
    p = str(tmp_path / "events.jsonl")
    log = _DurableSpylog(p, now=lambda: 1.0)
    log.append(("view_change_complete", 1))
    log._fh.close()
    with open(p, "a") as fh:
        fh.write('{"t": 2.0, "event": "torn')      # crash mid-write
    log2 = _DurableSpylog(p, now=lambda: 3.0)      # restart
    log2.append(("catchup_started", None))
    log2._fh.close()
    rows = read_events(p)
    assert [r["event"] for r in rows] == ["view_change_complete",
                                          "catchup_started"]


def test_start_node_chunked_backend_is_durable(tmp_path):
    """--kv chunked must build a node on KvChunked ledgers (review
    finding: it silently fell back to in-memory storage)."""
    pytest.importorskip(
        "cryptography",
        reason="build_node stands up the TCP stack, which needs cryptography")
    from plenum_tpu.storage.kv_chunked import KvChunked
    from plenum_tpu.tools.start_node import build_node
    from plenum_tpu.tools.tcp_pool import setup_pool_dir
    base = str(tmp_path)
    setup_pool_dir(base, ["N1", "N2", "N3", "N4"], b"t" * 32)
    prodable, node, _reg = build_node("N1", base, kv="chunked")
    lid = 1
    log = node.c.db.get_ledger(lid)._log
    assert isinstance(log, KvChunked), type(log)
    node.c.db.close()


@pytest.mark.slow
def test_config18_autopilot_heals_zipfian_flood_hands_off():
    """The ISSUE 18 acceptance bench: config12's zipfian hot-range
    flood with AUTOPILOT=True and ZERO test-driven actuation — the
    autopilot must split the hot shard on its own cadence and the run
    must recover to >= 0.8x pre-flood TPS with a clean control-ledger
    audit."""
    from plenum_tpu.tools.bench_configs import config18_autopilot
    out = config18_autopilot()
    assert "error" not in out, out
    assert out["test_driven_actuations"] == 0
    assert out["recovery_ratio"] >= 0.8, out
    assert out["audit_problems"] == [], out
    assert out["split_evidence"]["hot_shard"] == 0
    assert out["migration"]["phase"] == "done", out
