"""Proof CDN edge tier (reads/edge.py, docs/edge.md).

Covers the keyless EdgeCache (content addressing, anchor-advance
invalidation at an f+1 push vote, stale-while-revalidate under the
freshness bound, negative caching, proofless pass-through), the SimEdge
push/serve surfaces riding the observer ingress router unchanged, the
edge-first client ladder rung (served / escalated / rejected), and the
aggregator + autopilot absorbed-capacity seam (note_edge /
edge_hit_rate / the observer-spawn hold).
"""
from __future__ import annotations

import copy

from plenum_tpu.common.metrics import MetricsCollector
from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID, Reply,
                                             RequestNack)
from plenum_tpu.common.request import Request
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.execution.txn import GET_NYM, GET_TXN
from plenum_tpu.reads import (EDGE_CANNOT_SERVE, READ_PROOF, EdgeCache,
                              SimEdge, SimReadDriver)

from test_pool import Pool, signed_nym
from test_reads import FOREVER, pool_bls_keys

EDGE_FRESH = 1e9        # an edge bound that never triggers in sim time


def attach_edge(pool, name="edge1", freshness_s=EDGE_FRESH, f=1):
    """SimEdge over the pool: origin round-robins the validators' own
    read planes, pushes registered over the observer client plane."""
    rr = {"i": 0}

    def origin(request):
        v = pool.names[rr["i"] % len(pool.names)]
        rr["i"] += 1
        return pool.nodes[v].read_plane.answer(request)

    edge = SimEdge(name, origin, now=pool.timer.get_current_time,
                   freshness_s=freshness_s, f=f)
    edge.register(lambda v, msg: pool.nodes[v]
                  .handle_client_message(msg, edge.client_id),
                  pool.names)
    pool.run(0.5)
    return edge


def make_edge_driver(pool, edge, client="edrv", freshness_s=FOREVER,
                     on_fail=None):
    """Three-tier driver: the edge rung first, validators as failover."""
    def submit(name, req):
        if name == edge.name:
            edge.handle_client_message(req.to_dict(), client)
        else:
            pool.nodes[name].handle_client_message(req.to_dict(), client)

    def collect(name):
        if name == edge.name:
            out = [m.result for m, _ in edge.sent if isinstance(m, Reply)]
            edge.sent.clear()
            return out
        msgs = pool.client_msgs[name]
        out = [m.result for m, c in msgs
               if isinstance(m, Reply) and c == client]
        pool.client_msgs[name] = [
            (m, c) for m, c in msgs
            if not (isinstance(m, Reply) and c == client)]
        return out

    return SimReadDriver(submit, collect, pool.run, pool.names,
                         pool_bls_keys(pool), freshness_s=freshness_s,
                         now=pool.timer.get_current_time,
                         edge_names=[edge.name],
                         on_edge_verify_failure=on_fail)


def _edge_pool(freshness_s=EDGE_FRESH):
    from test_ingress import run_routed
    pool = Pool()
    edge = attach_edge(pool, freshness_s=freshness_s)
    user = Ed25519Signer(seed=b"edge-reads-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    run_routed(pool, [edge], 6.0)
    return pool, edge, user


# --- edge-served verified reads -------------------------------------------

def test_edge_cold_miss_then_warm_hit_verifies():
    """First read misses (one origin fetch = one pool read), second is a
    pure cache hit — and BOTH verify client-side against the real BLS
    anchor: the keyless cache added no trust surface."""
    pool, edge, user = _edge_pool()
    driver = make_edge_driver(pool, edge)
    for req_id in (10, 11):
        q = Request("edrv", req_id, {"type": GET_NYM,
                                     "dest": user.identifier})
        res = driver.read(q)
        assert res is not None
        assert res["data"]["verkey"] == user.verkey_b58
        assert res[READ_PROOF]["kind"] == "state"
    s = driver.stats
    assert s.edge_ok == 2 and s.single_reply_ok == 2
    assert s.failovers == 0 and s.fallbacks == 0
    cs = edge.cache.stats
    assert cs == {**cs, "hits": 1, "misses": 1, "origin_fetches": 1}
    assert cs["bytes_served"] > 0


def test_tampered_edge_fails_over_never_forges():
    """A lying edge (forged verkey in cached bytes) is REJECTED by the
    client's verify gate and the ladder falls over to a validator — the
    read still completes with the true value (deny-but-never-forge)."""
    pool, edge, user = _edge_pool()
    real_serve = edge.cache.serve

    def lying(request):
        res = real_serve(request)
        if isinstance(res, dict) and isinstance(res.get("data"), dict):
            bad = copy.deepcopy(res)
            bad["data"]["verkey"] = "4" * 43
            return bad
        return res

    edge.cache.serve = lying
    rejected = []
    driver = make_edge_driver(pool, edge, on_fail=rejected.append)
    q = Request("edrv", 20, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q)
    assert res is not None
    assert res["data"]["verkey"] == user.verkey_b58
    s = driver.stats
    assert s.edge_ok == 0 and s.single_reply_ok == 1
    assert s.edge_verify_failures == 1 and s.verify_failures == 1
    assert s.failovers >= 1 and s.fallbacks == 0
    assert rejected == [edge.name]


def test_edge_nacks_writes_and_ladder_survives():
    """A write through the edge rung gets the explicit serving NACK (a
    keyless cache cannot order anything); the client ladder treats the
    non-REPLY as one failover, exactly like a down rung."""
    pool, edge, user = _edge_pool()
    write = signed_nym(
        pool.trustee,
        Ed25519Signer(seed=b"edge-write-user".ljust(32, b"\0")[:32]),
        req_id=2)
    out = edge.serve(write.to_dict())
    assert isinstance(out, RequestNack)
    assert out.reason == EDGE_CANNOT_SERVE
    assert edge.cache.stats["origin_fetches"] == 1  # origin refused it


def test_negative_absence_result_cached():
    """An absence proof (GET_TXN beyond the signed tree) caches exactly
    like a positive result: the second read is a negative cache hit and
    still verifies client-side."""
    pool, edge, user = _edge_pool()
    driver = make_edge_driver(pool, edge)
    for req_id in (30, 31):
        q = Request("edrv", req_id, {"type": GET_TXN, "data": 99})
        res = driver.read(q)
        assert res is not None
        assert res.get("data") is None
        assert res[READ_PROOF]["kind"] == "merkle"
    s = driver.stats
    assert s.edge_ok == 2
    cs = edge.cache.stats
    assert cs["negative_hits"] == 1 and cs["hits"] == 1


def test_anchor_advance_invalidates_then_revalidates():
    """A committed write advances the anchor; the BatchCommitted push
    fan-out marks superseded entries stale. The next read serves the
    still-inside-bound stale copy AND refreshes from origin in the same
    call (stale-while-revalidate); the read after that is a fresh hit
    under the new root."""
    from test_ingress import run_routed
    pool, edge, user = _edge_pool()
    driver = make_edge_driver(pool, edge)
    q = Request("edrv", 40, {"type": GET_NYM, "dest": user.identifier})
    assert driver.read(q) is not None          # cold: cached at root R1
    # a write on the SAME ledger advances the domain anchor to R2
    other = Ed25519Signer(seed=b"edge-advance-usr".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, other, req_id=3))
    run_routed(pool, [edge], 6.0)
    cs = edge.cache.stats
    assert cs["invalidations"] >= 1, "push fan-out never invalidated"
    q2 = Request("edrv", 41, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q2)
    assert res is not None and res["data"]["verkey"] == user.verkey_b58
    cs = edge.cache.stats
    assert cs["stale_served"] == 1 and cs["revalidations"] == 1
    q3 = Request("edrv", 42, {"type": GET_NYM, "dest": user.identifier})
    assert driver.read(q3) is not None
    cs = edge.cache.stats
    assert cs["stale_served"] == 1, "revalidation did not refresh"
    assert driver.stats.edge_ok == 3 and driver.stats.fallbacks == 0


def test_stale_beyond_bound_is_a_miss():
    """A superseded entry OUTSIDE the freshness bound is never served
    stale (the client would reject it as a lie): it drops and the read
    pays one origin refetch instead."""
    from test_ingress import run_routed
    pool, edge, user = _edge_pool(freshness_s=5.0)
    driver = make_edge_driver(pool, edge)
    q = Request("edrv", 50, {"type": GET_NYM, "dest": user.identifier})
    assert driver.read(q) is not None
    other = Ed25519Signer(seed=b"edge-too-old-usr".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, other, req_id=4))
    run_routed(pool, [edge], 6.0)              # invalidate + age past 5 s
    misses_before = edge.cache.stats["misses"]
    q2 = Request("edrv", 51, {"type": GET_NYM, "dest": user.identifier})
    assert driver.read(q2) is not None
    cs = edge.cache.stats
    assert cs["misses"] == misses_before + 1
    assert cs["stale_served"] == 0 and cs["revalidations"] == 0


# --- the push vote (unit) -------------------------------------------------

def test_push_quorum_gates_advisory_adoption():
    """One pusher (<= f) can NEVER move the advisory anchor — f
    Byzantine validators cannot even churn the cache; f+1 distinct
    pushers adopt, and a later quorum on an OLDER timestamp is
    refused (the advisory clock never moves backwards)."""
    cache = EdgeCache(lambda request: None, f=1, now=lambda: 100.0)
    assert not cache.on_push(1, "aa", 50.0, "V1")
    assert not cache.on_push(1, "aa", 50.0, "V1")   # replays don't count
    assert cache.on_push(1, "aa", 50.0, "V2")        # f+1 distinct: adopt
    assert cache._advisory[1] == ("aa", 50.0)
    assert not cache.on_push(1, "bb", 10.0, "V1")
    assert not cache.on_push(1, "bb", 10.0, "V2")    # older ts: refused
    assert cache._advisory[1] == ("aa", 50.0)
    assert cache.on_push(1, "cc", 60.0, "V1") is False
    assert cache.on_push(1, "cc", 60.0, "V3")        # newer: adopt
    assert cache._advisory[1] == ("cc", 60.0)


def test_poisoned_push_degrades_never_forges():
    """A quorum-backed but BOGUS root hint only flips entries to the
    revalidation path — every read still returns origin-anchored bytes
    that verify client-side (hint poisoning is DoS, not forgery)."""
    pool, edge, user = _edge_pool()
    driver = make_edge_driver(pool, edge)
    q = Request("edrv", 60, {"type": GET_NYM, "dest": user.identifier})
    assert driver.read(q) is not None
    # 2 = f+1 colluding pushers agree on a fabricated far-future root
    far = pool.timer.get_current_time() + 1e6
    assert edge.cache.on_push(DOMAIN_LEDGER_ID, "f" * 64, far, "V1") \
        is False
    assert edge.cache.on_push(DOMAIN_LEDGER_ID, "f" * 64, far, "V2")
    q2 = Request("edrv", 61, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q2)
    assert res is not None and res["data"]["verkey"] == user.verkey_b58
    assert driver.stats.verify_failures == 0
    assert edge.cache.stats["revalidations"] >= 1


# --- aggregator + autopilot seam ------------------------------------------

def test_aggregator_edge_hit_rate_window():
    from plenum_tpu.config import Config
    from plenum_tpu.observability import FleetAggregator
    agg = FleetAggregator(config=Config(SLO_BURN_SLOW_WINDOW=20.0))
    assert agg.edge_hit_rate("r0") is None
    agg.note_edge("r0", hits=50, served=100, edges=2, bytes_served=1000,
                  now=1.0)
    agg.note_edge("r0", hits=100, served=100, edges=2, bytes_served=1000,
                  now=2.0)
    assert abs(agg.edge_hit_rate("r0") - 0.75) < 1e-9
    # old windows age out of the slow-window fold
    agg.note_edge("r0", hits=100, served=100, edges=2, bytes_served=0,
                  now=50.0)
    assert abs(agg.edge_hit_rate("r0") - 1.0) < 1e-9
    ed = agg.edge
    assert ed["regions"]["r0"]["served"] == 300
    assert ed["bytes"] == 2000


def test_autopilot_edge_absorb_holds_observer_spawn():
    """With sustained read burn, the observer policy SPAWNS — unless the
    region's edges already absorb the reads (hit-rate at the configured
    bar), in which case it HOLDS with the rate as ledger evidence."""
    from plenum_tpu.config import Config
    from plenum_tpu.control.autopilot import Autopilot
    from plenum_tpu.observability import FleetAggregator

    class _Fleet:
        def __init__(self):
            self.regions = {"r0": [object()]}
            self.spawned = []
            self._last_served = {}
            self.capacity = 64.0

        def count(self, region):
            return len(self.regions[region]) + len(self.spawned)

        def spawn(self, region):
            self.spawned.append(region)
            return f"{region}-obs{len(self.spawned) + 1}"

        def scale_in_safe(self, region):
            return False

    class _Fabric:
        config = Config(AUTOPILOT=True)
        metrics = MetricsCollector()
        fabric_tracer = None

        def __init__(self):
            self.aggregator = FleetAggregator(config=self.config)
            self.observers = _Fleet()

    fab = _Fabric()
    ap = Autopilot(fab)
    agg = fab.aggregator
    agg._streaks[("slo_burn.reads", "r0")] = ap._sustain  # sustained burn
    agg.note_edge("r0", hits=99, served=100, edges=3, now=1.0)
    ap._policy_observers(1.0)
    assert fab.observers.spawned == [], "spawned despite edge absorption"
    holds = [r for r in ap.ledger.records if r.action == "hold"]
    assert holds and holds[-1].evidence.get("edge_absorbing") is True
    assert abs(holds[-1].evidence["edge_hit_rate"] - 0.99) < 1e-9
    # the edges stop absorbing: the SAME sustained burn now spawns, and
    # the (sub-bar) hit rate still rides the action's evidence
    agg.note_edge("r0", hits=0, served=900, edges=3, now=2.0)
    ap._policy_observers(2.0)
    assert fab.observers.spawned == ["r0"]
    spawn = [r for r in ap.ledger.records
             if r.action == "observer_spawn"][-1]
    assert spawn.evidence["edge_hit_rate"] < 0.95
