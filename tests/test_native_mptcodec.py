"""In-tree C++ MPT node codec (native/mptcodec.cpp): SHA3-256 and
flat-node RLP, differential-tested against hashlib and the pure-Python
twin. Deliberately NOT wired into the trie hot path: measured through
ctypes at single-node granularity it is ~2x slower than Python rlp +
hashlib sha3 (docs/performance.md "Future directions") — the native
win requires a batch-granularity API. The differential surface keeps
the codec honest until then."""
from __future__ import annotations

import hashlib
import random

import pytest

from plenum_tpu.state import native_codec as nc
from plenum_tpu.state import rlp

pytestmark = pytest.mark.skipif(not nc.available(),
                                reason="native toolchain unavailable")


def test_sha3_matches_hashlib_across_padding_boundaries():
    rng = random.Random(3)
    # rate = 136 bytes for SHA3-256: cover both sides of every boundary
    for n in (0, 1, 55, 56, 135, 136, 137, 271, 272, 273, 4096):
        data = bytes(rng.randrange(256) for _ in range(n))
        assert nc.sha3_native(data) == hashlib.sha3_256(data).digest(), n


def test_flat_node_encode_hash_matches_python_twin():
    rng = random.Random(7)
    for trial in range(300):
        n_items = rng.choice([2, 17])
        node = []
        for _ in range(n_items):
            kind = rng.random()
            if kind < 0.3:
                node.append(b"")
            elif kind < 0.5:
                node.append(bytes([rng.randrange(256)]))  # 1-byte RLP case
            elif kind < 0.8:
                node.append(bytes(rng.randrange(256) for _ in range(32)))
            else:
                node.append(bytes(rng.randrange(256)
                                  for _ in range(rng.randrange(2, 90))))
        enc_py = rlp.encode(node)
        got = nc.encode_hash_flat(node)
        assert got is not None
        assert got[0] == enc_py, (trial, node)
        assert got[1] == hashlib.sha3_256(enc_py).digest()


def test_nested_children_defer_to_python():
    assert nc.encode_hash_flat([b"ab", [b"x", b"y"]]) is None


def test_long_item_prefix_encoding():
    # >55-byte items exercise the multi-byte length prefix
    node = [bytes(200), bytes(56), b"\x7f"]
    got = nc.encode_hash_flat(node)
    assert got is not None and got[0] == rlp.encode(node)


def test_batch_encode_hash_with_backrefs():
    """mptc_encode_hash_batch resolves child refs in-call: <32B children
    splice raw, >=32B children hash-ref — differentially checked against
    a pure-Python post-order resolution."""
    rng = random.Random(11)
    for trial in range(100):
        # build a random 3-node chain: leafish -> mid -> top, plus one
        # clean inline child spliced raw into mid
        leaf = [bytes([0x20 | rng.randrange(16)]),
                bytes(rng.randrange(256)
                      for _ in range(rng.choice([1, 8, 40])))]
        inline_child = [b"\x31", b"v"]
        prepared = [
            [(-1, leaf[0]), (-1, leaf[1])],
            [(-1, b"\x00\x12"), (0, b""), (-2, rlp.encode(inline_child))],
            [(-1, b"\x16"), (1, b"")],
        ]
        got = nc.encode_hash_many(prepared)
        assert got is not None
        # python twin: resolve bottom-up
        enc0 = rlp.encode(leaf)
        ref0 = leaf if len(enc0) < 32 \
            else hashlib.sha3_256(enc0).digest()
        mid = [b"\x00\x12", ref0, inline_child]
        enc1 = rlp.encode(mid)
        ref1 = mid if len(enc1) < 32 else hashlib.sha3_256(enc1).digest()
        top = [b"\x16", ref1]
        enc2 = rlp.encode(top)
        for i, enc in enumerate((enc0, enc1, enc2)):
            assert got[i][0] == enc, (trial, i)
            assert got[i][1] == hashlib.sha3_256(enc).digest()


def test_trie_native_and_python_resolution_agree(monkeypatch):
    """The deferred trie produces IDENTICAL roots/values/proofs whether
    the dirty set resolves through the native batch call or the
    pure-Python twin — across random set/remove batches."""
    from plenum_tpu.state.pruning_state import PruningState

    rng = random.Random(23)
    ops = []
    live = {}
    for _ in range(400):
        k = bytes(rng.randrange(256) for _ in range(rng.choice([3, 8, 20])))
        if live and rng.random() < 0.25:
            k = rng.choice(list(live))
            ops.append(("del", k, None))
            live.pop(k)
        else:
            v = bytes(rng.randrange(1, 256)
                      for _ in range(rng.randrange(1, 120)))
            ops.append(("set", k, v))
            live[k] = v

    def run(native_on):
        st = PruningState()
        roots = []
        for i, (op, k, v) in enumerate(ops):
            if op == "set":
                st.set(k, v)
            else:
                st.remove(k)
            if i % 37 == 0:             # commit-batch boundaries
                roots.append(st.head_hash)
        st.commit()
        roots.append(st.committed_head_hash)
        return st, roots

    with monkeypatch.context() as m:
        m.setattr(nc, "available", lambda: False)
        st_py, roots_py = run(False)
    st_nat, roots_nat = run(True)
    assert roots_py == roots_nat
    for k, v in live.items():
        assert st_nat.get(k, committed=True) == v
        proof = st_nat.generate_state_proof(k)
        assert PruningState.verify_state_proof(
            st_nat.committed_head_hash, k, v, proof)
    gone = [k for op, k, _ in ops if op == "del" and k not in live]
    for k in gone[:10]:
        assert st_nat.get(k, committed=True) is None
