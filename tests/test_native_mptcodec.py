"""In-tree C++ MPT node codec (native/mptcodec.cpp): SHA3-256 and
flat-node RLP, differential-tested against hashlib and the pure-Python
twin. Deliberately NOT wired into the trie hot path: measured through
ctypes at single-node granularity it is ~2x slower than Python rlp +
hashlib sha3 (docs/performance.md "Future directions") — the native
win requires a batch-granularity API. The differential surface keeps
the codec honest until then."""
from __future__ import annotations

import hashlib
import random

import pytest

from plenum_tpu.state import native_codec as nc
from plenum_tpu.state import rlp

pytestmark = pytest.mark.skipif(not nc.available(),
                                reason="native toolchain unavailable")


def test_sha3_matches_hashlib_across_padding_boundaries():
    rng = random.Random(3)
    # rate = 136 bytes for SHA3-256: cover both sides of every boundary
    for n in (0, 1, 55, 56, 135, 136, 137, 271, 272, 273, 4096):
        data = bytes(rng.randrange(256) for _ in range(n))
        assert nc.sha3_native(data) == hashlib.sha3_256(data).digest(), n


def test_flat_node_encode_hash_matches_python_twin():
    rng = random.Random(7)
    for trial in range(300):
        n_items = rng.choice([2, 17])
        node = []
        for _ in range(n_items):
            kind = rng.random()
            if kind < 0.3:
                node.append(b"")
            elif kind < 0.5:
                node.append(bytes([rng.randrange(256)]))  # 1-byte RLP case
            elif kind < 0.8:
                node.append(bytes(rng.randrange(256) for _ in range(32)))
            else:
                node.append(bytes(rng.randrange(256)
                                  for _ in range(rng.randrange(2, 90))))
        enc_py = rlp.encode(node)
        got = nc.encode_hash_flat(node)
        assert got is not None
        assert got[0] == enc_py, (trial, node)
        assert got[1] == hashlib.sha3_256(enc_py).digest()


def test_nested_children_defer_to_python():
    assert nc.encode_hash_flat([b"ab", [b"x", b"y"]]) is None


def test_long_item_prefix_encoding():
    # >55-byte items exercise the multi-byte length prefix
    node = [bytes(200), bytes(56), b"\x7f"]
    got = nc.encode_hash_flat(node)
    assert got is not None and got[0] == rlp.encode(node)
