"""Proof-carrying cross-shard writes (shards/cross_write.py): the
fail-closed 2PC — happy path, every abort row of the matrix
(docs/sharding.md "Cross-shard writes"), and crash recovery from
durable state alone. The invariant under test everywhere: NO
half-commits — the home write and the remote write land together or
not at all."""
from __future__ import annotations

import json

import pytest

from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
from plenum_tpu.common.request import Request
from plenum_tpu.execution import txn as txn_lib
from plenum_tpu.execution.txn import ATTRIB, GET_NYM, NYM

from test_shards import make_fabric, signed_write, user_on_shard


def _fab_with_dep():
    """2-shard fabric with a dependency DID ordered on shard 1."""
    fab = make_fabric()
    dep = user_on_shard(fab, 1, b"xwdep")
    fab.submit_write(signed_write(fab, dep, 1))
    fab.run(8.0)
    assert fab.shards[1].domain_sizes() == {2}
    return fab, dep


def _nym_applied(fab, sid, did) -> bool:
    node = next(iter(fab.shards[sid].nodes.values()))
    ledger = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
    return any(
        txn_lib.txn_data(ledger.get_by_seq_no(i)).get("dest") == did
        and txn_lib.txn_type_of(ledger.get_by_seq_no(i)) == NYM
        for i in range(2, ledger.size + 1))


def _attrib_applied(fab, sid, did) -> bool:
    node = next(iter(fab.shards[sid].nodes.values()))
    ledger = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
    for i in range(2, ledger.size + 1):
        txn = ledger.get_by_seq_no(i)
        if txn_lib.txn_type_of(txn) != ATTRIB:
            continue
        data = txn_lib.txn_data(txn)
        if data.get("dest") == did and "linked" in (data.get("raw") or ""):
            return True
    return False


def _begin(fab, xsw, dep, tag: bytes, start: int = 0):
    home = user_on_shard(fab, 0, tag, start=start)
    txid = xsw.begin(
        0, 1,
        {"type": NYM, "dest": home.identifier, "verkey": home.verkey_b58},
        {"type": GET_NYM, "dest": dep.identifier},
        {"type": ATTRIB, "dest": dep.identifier,
         "raw": json.dumps({"linked": home.identifier})})
    return home, txid


def test_cross_write_commits_atomically():
    """Happy path: witness read -> ordered prepare carrying BOTH proofs
    -> lock -> ANCHORED ack -> commit; both halves applied."""
    fab, dep = _fab_with_dep()
    xsw = fab.cross_writes()
    home, txid = _begin(fab, xsw, dep, b"xwh")
    assert xsw.drive(txid) == "committed"
    assert _nym_applied(fab, 0, home.identifier)
    assert _attrib_applied(fab, 1, dep.identifier)
    assert xsw.participant(1).locks == {}        # released on commit
    # the ordered prepare record LITERALLY carries the witness: the
    # remote read proof + the mapping ownership proof, auditable from
    # the coordinator shard's ledger alone
    recs = xsw._scan_records(0)
    prep = recs[txid]["prepare"]
    result = prep["witness"]["result"]
    assert "read_proof" in result and "shard_proof" in result
    assert prep["intent"]["epoch"] == 0
    assert recs[txid]["decision"]["decision"] == "commit"


def test_cross_write_aborts_on_epoch_change():
    """The map ratchets between lock and commit: the ownership its
    witness was judged under is superseded — abort, nothing applied."""
    from plenum_tpu.shards import ShardDescriptor

    fab, dep = _fab_with_dep()
    xsw = fab.cross_writes()
    home, txid = _begin(fab, xsw, dep, b"xwe", start=10)
    assert xsw.step(txid) == "prepared"
    assert xsw.step(txid) == "locked"
    fab.mapping.reshard([ShardDescriptor.from_dict(d.to_dict())
                         for d in fab.mapping.descriptors])
    assert xsw.step(txid) == "aborted"
    assert xsw.txs[txid].abort_reason == "epoch_changed"
    assert not _nym_applied(fab, 0, home.identifier)
    assert not _attrib_applied(fab, 1, dep.identifier)
    assert xsw.participant(1).locks == {}        # lock released
    recs = xsw._scan_records(0)
    assert recs[txid]["decision"]["decision"] == "abort"


def test_cross_write_aborts_on_remote_partition():
    """The remote shard cannot order the lock (its primary is cut off):
    the prepare times out and the coordinator aborts — fail closed,
    never an indefinite wait, never a half-commit."""
    from plenum_tpu.network import Discard, match_dst, match_frm

    fab, dep = _fab_with_dep()
    xsw = fab.cross_writes()
    xsw._anchor(1)                 # anchor DID ordered BEFORE the fault
    rshard = fab.shards[1]
    primary = rshard.nodes[rshard.names[0]].master_replica.data.primary_name
    rshard.net.add_rule(Discard(), match_dst(primary))
    rshard.net.add_rule(Discard(), match_frm(primary))
    home, txid = _begin(fab, xsw, dep, b"xwp", start=20)
    assert xsw.step(txid) == "prepared"
    state = xsw.step(txid)
    assert state == "aborted", xsw.txs[txid].abort_reason
    assert not _nym_applied(fab, 0, home.identifier)
    assert not _attrib_applied(fab, 1, dep.identifier)


def test_cross_write_refuses_forged_witness():
    """A witness whose envelope does not verify against the
    participant's OWN trust roots is refused at prepare."""
    fab, dep = _fab_with_dep()
    xsw = fab.cross_writes()
    home, txid = _begin(fab, xsw, dep, b"xwf", start=30)
    assert xsw.step(txid) == "prepared"
    tx = xsw.txs[txid]
    forged = json.loads(json.dumps(tx.witness))
    forged["result"]["data"]["verkey"] = "FORGED"
    ok, why = xsw.participant(1).handle_prepare(txid, tx.intent, forged)
    assert not ok and why.startswith("bad_witness")
    assert xsw.participant(1).locks == {}


def test_cross_write_coordinator_crash_recovers_abort():
    """Crash between lock and commit: the participant's lock TTL
    expires and resolves via a verified read of the decision record (a
    proven ABSENCE -> abort); ledger recovery orders the abort decision.
    Neither half applies."""
    fab, dep = _fab_with_dep()
    xsw = fab.cross_writes()
    home, txid = _begin(fab, xsw, dep, b"xwc", start=40)
    assert xsw.step(txid) == "prepared"
    assert xsw.step(txid) == "locked"
    # ...coordinator crashes here: no further steps. Time passes.
    fab.run(25.0)                  # past XSW_PREPARE_TTL
    rec = xsw.recover_from_ledger(0)
    assert txid in rec["aborted"]
    xsw.participant(1).service()   # lock TTL expired: resolve + abort
    assert xsw.participant(1).locks == {}
    assert xsw.participant(1).stats["resolved_aborts"] == 1
    assert not _nym_applied(fab, 0, home.identifier)
    assert not _attrib_applied(fab, 1, dep.identifier)


def test_cross_write_crash_after_decision_completes():
    """Crash AFTER the commit decision ordered but before the home
    write / remote notify: recovery replays the home write from the
    durable intent, and the participant resolves its lock to a PROVEN
    commit and applies — atomicity holds through the crash."""
    fab, dep = _fab_with_dep()
    xsw = fab.cross_writes()
    home, txid = _begin(fab, xsw, dep, b"xwd", start=50)
    assert xsw.step(txid) == "prepared"
    assert xsw.step(txid) == "locked"
    # the decision orders; the crash lands before anything else
    xsw._order_record(0, txid, "decision", {"decision": "commit"})
    rec = xsw.recover_from_ledger(0)
    assert txid in rec["completed"]
    assert _nym_applied(fab, 0, home.identifier)
    fab.run(25.0)                  # past the lock TTL
    xsw.participant(1).service()
    assert xsw.participant(1).locks == {}
    assert _attrib_applied(fab, 1, dep.identifier)


def test_cross_write_conflicting_lock_refused():
    """Two transactions against the same remote dependency: the second
    prepare is refused while the first holds the lock, and admitted
    after it releases."""
    fab, dep = _fab_with_dep()
    xsw = fab.cross_writes()
    h1, tx1 = _begin(fab, xsw, dep, b"xwl1", start=60)
    assert xsw.step(tx1) == "prepared"
    assert xsw.step(tx1) == "locked"
    h2, tx2 = _begin(fab, xsw, dep, b"xwl2", start=70)
    assert xsw.step(tx2) == "prepared"
    assert xsw.step(tx2) == "aborted"
    assert xsw.txs[tx2].abort_reason == "prepare_refused:locked"
    assert xsw.step(tx1) == "committed"          # the holder commits
    assert _nym_applied(fab, 0, h1.identifier)
    assert not _nym_applied(fab, 0, h2.identifier)
