"""Device SHA-256 kernel vs hashlib ground truth."""
import hashlib
import os
import random

import numpy as np
import pytest

import jax.numpy as jnp

from plenum_tpu.ops.sha256 import (sha256_words, sha256_batch, hash_interior,
                                   merkle_reduce_pow2, pad_to_words,
                                   n_blocks_for, digests_to_bytes,
                                   bytes_to_digests)


def ref_hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def test_empty_and_abc():
    assert sha256_batch([b""]) == [ref_hash(b"")]
    assert sha256_batch([b"abc"]) == [ref_hash(b"abc")]


def test_random_lengths_match_hashlib():
    rng = random.Random(42)
    msgs = [rng.randbytes(rng.randint(0, 300)) for _ in range(64)]
    assert sha256_batch(msgs) == [ref_hash(m) for m in msgs]


def test_prefix_applied():
    msgs = [b"leafdata", b"x" * 100]
    assert sha256_batch(msgs, prefix=b"\x00") == [ref_hash(b"\x00" + m) for m in msgs]


def test_block_boundary_lengths():
    # 55/56/63/64/119/120 bytes straddle padding edges
    for n in [55, 56, 63, 64, 119, 120, 128]:
        m = bytes(range(256))[:n] * 1
        assert sha256_batch([m]) == [ref_hash(m)], f"len {n}"


def test_n_blocks_for():
    assert n_blocks_for(0) == 1
    assert n_blocks_for(55) == 1
    assert n_blocks_for(56) == 2   # padding needs 9 bytes
    assert n_blocks_for(119) == 2
    assert n_blocks_for(120) == 3


def test_hash_interior_matches_rfc6962_shape():
    rng = random.Random(7)
    lefts = [rng.randbytes(32) for _ in range(17)]
    rights = [rng.randbytes(32) for _ in range(17)]
    out = hash_interior(jnp.asarray(bytes_to_digests(lefts)),
                        jnp.asarray(bytes_to_digests(rights)))
    expect = [ref_hash(b"\x01" + l + r) for l, r in zip(lefts, rights)]
    assert digests_to_bytes(out) == expect


def test_merkle_reduce_pow2_vs_host():
    rng = random.Random(9)
    leaves = [rng.randbytes(32) for _ in range(16)]

    def host_root(hs):
        if len(hs) == 1:
            return hs[0]
        nxt = [ref_hash(b"\x01" + hs[i] + hs[i + 1]) for i in range(0, len(hs), 2)]
        return host_root(nxt)

    root = merkle_reduce_pow2(jnp.asarray(bytes_to_digests(leaves)))
    assert digests_to_bytes(root[None])[0] == host_root(leaves)


def test_digest_bytes_roundtrip():
    rng = random.Random(1)
    hs = [rng.randbytes(32) for _ in range(5)]
    assert digests_to_bytes(jnp.asarray(bytes_to_digests(hs))) == hs
