"""Cross-process crypto plane (parallel/crypto_service.py): one device
owner, many clients; coalescing, verdict cache, and the OS-process pool
topology it exists for."""
from __future__ import annotations

import asyncio
import os
import threading

import numpy as np
import pytest


def _make_items(n, signers=4, tag=b""):
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    sgs = [Ed25519Signer((b"svc%d" % i).ljust(32, b"\0")) for i in range(signers)]
    out = []
    for i in range(n):
        s = sgs[i % signers]
        msg = tag + b"payload-%d" % i
        out.append((msg, s.sign(msg), s.verkey))
    return out


@pytest.fixture
def service(tmp_path):
    """A live server on a CPU verifier + a factory for connected clients."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.crypto_service import (CryptoPlaneServer,
                                                    ServiceEd25519Verifier)
    sock = str(tmp_path / "crypto.sock")
    server = CryptoPlaneServer(CpuEd25519Verifier(), socket_path=sock)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def run():
        await server.start()
        started.set()
        while not server._stop.is_set():
            await asyncio.sleep(0.05)
        await server.stop()

    t = threading.Thread(target=lambda: loop.run_until_complete(run()),
                         daemon=True)
    t.start()
    assert started.wait(5.0)
    clients = []

    def connect():
        c = ServiceEd25519Verifier(socket_path=sock)
        clients.append(c)
        return c

    yield server, connect
    for c in clients:
        c.close()
    server._stop.set()
    t.join(timeout=5.0)


def test_verdicts_match_direct_verification(service):
    server, connect = service
    ver = connect()
    items = _make_items(12)
    # corrupt two: flipped sig byte, wrong key
    items[3] = (items[3][0], items[3][1][:32] + bytes(32), items[3][2])
    items[7] = (items[7][0], items[7][1], items[0][2])
    out = ver.verify_batch(items)
    expected = np.ones(12, dtype=bool)
    expected[3] = expected[7] = False
    assert (out == expected).all()


def test_cache_dedupes_across_clients(service):
    server, connect = service
    a, b = connect(), connect()
    items = _make_items(20, tag=b"dedup")
    assert a.verify_batch(items).all()
    dispatched_before = server.stats["dispatched_items"]
    assert b.verify_batch(items).all()          # same content, other client
    # nothing new dispatched: B rode A's cached verdicts
    assert server.stats["dispatched_items"] == dispatched_before
    assert server.stats["cache_hits"] >= 20


def test_pipelined_submit_collect(service):
    _, connect = service
    ver = connect()
    t1 = ver.submit_batch(_make_items(5, tag=b"one"))
    t2 = ver.submit_batch(_make_items(5, tag=b"two"))
    # out-of-order collection: replies are matched by id
    assert ver.collect_batch(t2, wait=True).all()
    assert ver.collect_batch(t1, wait=True).all()


def test_malformed_items_are_false_not_fatal(service):
    _, connect = service
    ver = connect()
    good = _make_items(2)
    bad = [(b"msg", b"short-sig", b"short-key"), good[0], (b"", b"", b"")]
    out = ver.verify_batch(bad)
    assert list(out) == [False, True, False]


def test_connect_fails_fast_without_server(tmp_path):
    from plenum_tpu.parallel.crypto_service import ServiceEd25519Verifier
    with pytest.raises(OSError):
        ServiceEd25519Verifier(socket_path=str(tmp_path / "nope.sock"))


def test_tcp_pool_over_crypto_service():
    """The topology this exists for: a 4-process pool whose nodes all
    verify through ONE crypto-plane process (backend service:cpu), with
    the verdict cache collapsing per-node re-verification."""
    pytest.importorskip(
        "cryptography",
        reason="the TCP node stack's handshake needs the cryptography package")
    from plenum_tpu.tools.tcp_pool import run_tcp_pool
    r = run_tcp_pool(n_nodes=4, n_txns=60, backend="service:cpu",
                     timeout=90.0)
    assert r["txns_ordered"] == 60, r
    stats = r.get("crypto_service")
    assert stats, "service stats missing from the bench result"
    # 4 nodes x 60 requests: without the cache the plane would dispatch
    # ~4x the unique signatures; with it, roughly one dispatch per unique
    # signature (trustee + 60 users, plus handshake traffic)
    assert stats["cache_hits"] > 0
    assert stats["dispatched_items"] < stats["items"]


def test_cache_poisoning_by_field_shift_rejected(service):
    """(msg, sig+vk[:1], vk[1:]) must NOT share a cache digest with the
    honest (msg, sig, vk): every field is length-prefixed. An attacker
    pre-submitting the shifted triple (malformed -> False) must not make
    the plane reject the honest signature afterwards."""
    _, connect = service
    attacker, honest = connect(), connect()
    (msg, sig, vk) = _make_items(1, tag=b"poison")[0]
    shifted = (msg, sig + vk[:1], vk[1:])
    assert not attacker.verify_batch([shifted]).any()   # cached False
    assert honest.verify_batch([(msg, sig, vk)]).all()  # unaffected


def test_backend_failure_is_loud_and_worker_survives(tmp_path):
    """An inner-verifier exception (device tunnel dropping) must surface
    as an error to waiting clients — never a silent all-False verdict or
    a dead worker thread that wedges every node."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.crypto_service import (CryptoPlaneServer,
                                                    ServiceEd25519Verifier)

    class FlakyVerifier(CpuEd25519Verifier):
        def __init__(self):
            super().__init__()
            self.fail_next = True

        def verify_batch(self, items):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("device tunnel dropped")
            return super().verify_batch(items)

    sock = str(tmp_path / "crypto.sock")
    server = CryptoPlaneServer(FlakyVerifier(), socket_path=sock)
    loop_ready = threading.Event()

    def runner():
        async def run():
            await server.start()
            loop_ready.set()
            while not server._stop.is_set():
                await asyncio.sleep(0.05)
        asyncio.new_event_loop().run_until_complete(run())

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    assert loop_ready.wait(5.0)
    ver = ServiceEd25519Verifier(socket_path=sock)
    items = _make_items(3, tag=b"flaky")
    with pytest.raises(RuntimeError, match="device tunnel dropped"):
        ver.verify_batch(items)
    # the worker survived: the next dispatch succeeds
    assert ver.verify_batch(items).all()
    # the swallow was NAMED, not silent: the audit counters recorded it
    assert server.stats.get("submit_errors", 0) + \
        server.stats.get("collect_errors", 0) >= 1
    ver.close()
    server._stop.set()
    t.join(timeout=5.0)


def test_supervised_inner_degrades_server_to_cpu_not_errors(tmp_path):
    """The production server topology: its inner device verifier rides
    the plane supervisor, so a wedged device yields CPU-hedged VERDICTS
    to every client — not error replies — and the stats op exposes the
    breaker state over the socket."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.crypto_service import (CryptoPlaneServer,
                                                    ServiceEd25519Verifier)
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.supervisor import (CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    device = FaultyVerifier(CpuEd25519Verifier())
    inner = SupervisedVerifier(
        device, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=2, cooldown=30.0),
        budget=DeadlineBudget(base=0.2, min_s=0.15, warm_max=0.3,
                              cold_max=0.3))
    sock = str(tmp_path / "crypto.sock")
    server = CryptoPlaneServer(inner, socket_path=sock)
    started = threading.Event()

    def runner():
        async def run():
            await server.start()
            started.set()
            while not server._stop.is_set():
                await asyncio.sleep(0.02)
        asyncio.new_event_loop().run_until_complete(run())

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    assert started.wait(5.0)
    try:
        ver = ServiceEd25519Verifier(socket_path=sock)
        good = _make_items(3, tag=b"sup-ok")
        assert ver.verify_batch(good).all()
        device.wedge()
        mixed = _make_items(4, tag=b"sup-wedge")
        mixed[1] = (mixed[1][0], mixed[1][1][:32] + bytes(32), mixed[1][2])
        # verdicts, not errors: the server hedged on its CPU fallback
        out = ver.verify_batch(mixed)
        assert list(out) == [True, False, True, True]
        stats = ver.stats()
        assert stats["plane"]["hedge_wins"] >= 1
        assert stats["plane"]["verdict_forks"] == 0
        ver.close()
    finally:
        server._stop.set()
        t.join(timeout=5.0)


def test_bls_checks_ride_the_plane_and_dedupe(service):
    """The per-batch BLS aggregate check is the other identical-on-every-
    node pairing; routed through the plane it runs once per host."""
    from plenum_tpu.crypto import bls as bls_mod
    from plenum_tpu.crypto.bls import BlsCryptoSigner, aggregate_sigs
    from plenum_tpu.parallel.crypto_service import ServiceBlsVerifier

    server, connect = service
    signers = [BlsCryptoSigner(seed=b"svcbls%d" % i + bytes(25))
               for i in range(3)]
    message = b"state-root-over-the-plane"
    agg = aggregate_sigs([s.sign(message) for s in signers])
    vks = [s.pk for s in signers]

    a = ServiceBlsVerifier(socket_path=connect().socket_path)
    bls_mod._BLS_VERDICTS.clear()
    assert a.verify_multi_sig(agg, message, vks)
    pairings_after_first = server.stats.get("bls_pairings", 0)
    assert pairings_after_first >= 1

    # the REAL cross-process claim: a separate OS process (fresh local
    # cache) asking the same check costs the server a lookup, not a
    # pairing — and different verkey order must not change the verdict
    import base64
    import pickle
    import subprocess
    import sys
    blob = base64.b64encode(pickle.dumps(
        (a._client.socket_path, agg, message, list(reversed(vks))))).decode()
    code = (
        "import base64, pickle, sys\n"
        "sock, agg, msg, vks = pickle.loads(base64.b64decode('" + blob + "'))\n"
        "from plenum_tpu.parallel.crypto_service import ServiceBlsVerifier\n"
        "v = ServiceBlsVerifier(socket_path=sock)\n"
        "assert v.verify_multi_sig(agg, msg, vks)\n"
        "print('XPROC-OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60,
                         env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert "XPROC-OK" in out.stdout, out.stderr[-500:]
    assert server.stats.get("bls_pairings", 0) == pairings_after_first

    # wrong participant set still fails closed
    assert not a.verify_multi_sig(agg, message, vks[:2])
    assert not a.verify_multi_sig(agg, b"other", vks)
    a.close()


def test_bls_single_flight_survives_cancellation(tmp_path):
    """A client disconnect cancels its _process task mid-pairing; the
    single-flight future must still resolve (and the key must be popped)
    so later identical checks don't await a dead future forever."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.crypto_service import CryptoPlaneServer
    server = CryptoPlaneServer(CpuEd25519Verifier(),
                               socket_path=str(tmp_path / "c.sock"))
    in_pairing = threading.Event()
    release = threading.Event()

    def slow_verify(sig, msg, vks):
        in_pairing.set()
        assert release.wait(5.0)
        return True

    server._bls.verify_multi_sig = slow_verify
    msg = b"cancel-regression-%d" % os.getpid()   # dodge the global cache

    async def scenario():
        loop = asyncio.get_running_loop()
        first = asyncio.ensure_future(
            server._bls_check(loop, "sig", msg, ["vk1", "vk2"]))
        while not in_pairing.is_set():
            await asyncio.sleep(0.01)
        first.cancel()                 # the disconnecting client
        with pytest.raises(asyncio.CancelledError):
            await first
        # identical check from a co-hosted node: joins the in-flight
        # pairing and must resolve once it completes
        second = asyncio.ensure_future(
            server._bls_check(loop, "sig", msg, ["vk1", "vk2"]))
        await asyncio.sleep(0.05)
        release.set()
        return await asyncio.wait_for(second, timeout=5.0)

    assert asyncio.run(scenario()) is True
    assert server._bls_pending == {}


# --- double-buffered worker (round 5) -------------------------------------

class _SlowAsyncVerifier:
    """Inner verifier with REAL async token semantics: submit returns
    immediately, the 'device' resolves each token ~30 ms later in a
    background thread — enough for the worker to stage the next wave."""

    def __init__(self):
        from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
        self._cpu = CpuEd25519Verifier()
        self.submitted = []

    def submit_batch(self, items):
        import time
        self.submitted.append([i[0] for i in items])
        return {"t": time.monotonic() + 0.03,
                "verdicts": self._cpu.verify_batch(items)}

    def collect_batch(self, token, wait=True):
        import time
        while time.monotonic() < token["t"]:
            if not wait:
                return None
            time.sleep(0.002)
        return token["verdicts"]

    def verify_batch(self, items):
        return self.collect_batch(self.submit_batch(items), wait=True)


def test_worker_overlaps_waves_and_dedupes_across_them(tmp_path):
    """Wave k+1 must dispatch while wave k is still in flight (overlap),
    and content already computing in wave k must NOT be re-dispatched by a
    later wave — the job rides the in-flight wave."""
    from plenum_tpu.parallel.crypto_service import (CryptoPlaneServer,
                                                    ServiceEd25519Verifier)
    sock = str(tmp_path / "crypto.sock")
    inner = _SlowAsyncVerifier()
    server = CryptoPlaneServer(inner, socket_path=sock)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def run():
        await server.start()
        started.set()
        while not server._stop.is_set():
            await asyncio.sleep(0.02)
        await server.stop()

    t = threading.Thread(target=lambda: loop.run_until_complete(run()),
                         daemon=True)
    t.start()
    assert started.wait(5.0)
    try:
        c1 = ServiceEd25519Verifier(socket_path=sock)
        c2 = ServiceEd25519Verifier(socket_path=sock)
        a = _make_items(6, tag=b"waveA-")
        b = _make_items(6, tag=b"waveB-")
        # wave 1: client 1 ships A; then while it is in flight, client 2
        # ships B (new content -> second wave overlapped) AND A again
        # (must attach to wave 1, not re-dispatch)
        t1 = c1.submit_batch(a)
        import time
        time.sleep(0.005)                  # let the worker pick up wave 1
        t2 = c2.submit_batch(b)
        t3 = c2.submit_batch(a)
        ok1 = c1.collect_batch(t1)
        ok2 = c2.collect_batch(t2)
        ok3 = c2.collect_batch(t3)
        assert ok1.all() and ok2.all() and ok3.all()
        # A's messages were dispatched exactly once across all waves
        flat = [m for batch in inner.submitted for m in batch]
        assert len(flat) == len(set(flat)), "re-dispatched content"
        assert server.stats.get("overlapped", 0) >= 1, server.stats
        c1.close(); c2.close()
    finally:
        server._stop.set()
        t.join(timeout=5.0)


def test_submit_failure_with_cross_wave_dependency_is_loud(tmp_path):
    """Regression (round-5 review): wave 1 in flight, a job referencing
    wave-1 content plus new content attaches to wave 2; wave 2's submit
    raises. The job must get an ERROR reply (not hang) and the worker
    thread must survive to serve later requests."""
    from plenum_tpu.parallel.crypto_service import (CryptoPlaneServer,
                                                    ServiceEd25519Verifier)

    class _FlakySubmit(_SlowAsyncVerifier):
        def __init__(self):
            super().__init__()
            self.fail_next = False

        def submit_batch(self, items):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("tunnel dropped")
            return super().submit_batch(items)

    sock = str(tmp_path / "crypto.sock")
    inner = _FlakySubmit()
    server = CryptoPlaneServer(inner, socket_path=sock)
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def run():
        await server.start()
        started.set()
        while not server._stop.is_set():
            await asyncio.sleep(0.02)
        await server.stop()

    t = threading.Thread(target=lambda: loop.run_until_complete(run()),
                         daemon=True)
    t.start()
    assert started.wait(5.0)
    try:
        c = ServiceEd25519Verifier(socket_path=sock)
        a = _make_items(4, tag=b"dep-a-")
        b = _make_items(4, tag=b"dep-b-")
        t1 = c.submit_batch(a)
        import time
        time.sleep(0.005)            # wave 1 (a) now in flight
        inner.fail_next = True
        t2 = c.submit_batch(a + b)   # depends on wave 1 AND the failing wave
        assert c.collect_batch(t1).all()
        with pytest.raises(RuntimeError):
            c.collect_batch(t2)      # loud error, not a hang
        # worker alive: a fresh request still round-trips
        t3 = c.submit_batch(_make_items(3, tag=b"dep-c-"))
        assert c.collect_batch(t3).all()
        assert server._worker.is_alive()
        c.close()
    finally:
        server._stop.set()
        t.join(timeout=5.0)


# --- federated wave frames + prewarm/pin negotiation -------------------------

def test_federated_wave_frames_dispatch_verbatim(service):
    """`"wave": 1` frames bypass the server's dedup/coalescing: a padded
    bucket of IDENTICAL items dispatches at full width (the federated
    lane's pinned-shape guarantee crosses the wire), while verdicts stay
    correct and still land in the shared digest cache."""
    from plenum_tpu.parallel.crypto_service import FederatedEd25519Client
    server, connect = service
    fed = FederatedEd25519Client(socket_path=connect().socket_path)
    pad = _make_items(1, tag=b"pad")[0]
    before = server.stats["dispatched_items"]
    out = fed.collect_batch(fed.submit_batch([pad] * 16), wait=True)
    assert out.shape == (16,) and out.all()
    assert server.stats["dispatched_items"] - before == 16, \
        "server deduped a wave frame — the dispatched shape shrank"
    assert server.stats.get("wave_frames", 0) >= 1
    # mixed real verdicts round-trip the raw path too
    items = _make_items(6, tag=b"wavemix")
    items[2] = (items[2][0], items[2][1][:32] + bytes(32), items[2][2])
    got = fed.collect_batch(fed.submit_batch(items), wait=True)
    assert list(got) == [True, True, False, True, True, True]
    fed.close()


def test_federated_prewarm_pin_negotiation(service):
    """The prewarm RPC compiles each pad bucket server-side (one
    verbatim all-pad wave per bucket) and answers whether the remote
    inner is device-backed; pin marks warmup over."""
    from plenum_tpu.parallel.crypto_service import FederatedEd25519Client
    server, connect = service
    fed = FederatedEd25519Client(socket_path=connect().socket_path)
    reply = fed.prewarm([8, 16])
    assert reply["warmed"] == [8, 16]
    assert reply["bucketed"] is False       # CPU inner: don't pad for it
    assert server.stats.get("prewarms") == 1
    assert fed.pin()["pinned"] is True
    assert server.stats.get("pinned") == 1
    fed.close()


def test_federated_pipeline_rides_remote_lane(service):
    """End-to-end: a FederatedCryptoPipeline with one REAL remote lane
    over the service socket — prewarm negotiation turns padding off for
    the CPU-backed host, unhinted waves land on the remote, verdicts
    are correct, and no item is double-verified."""
    from plenum_tpu.config import Config
    from plenum_tpu.crypto.ed25519 import JaxEd25519Verifier
    from plenum_tpu.parallel.crypto_service import FederatedEd25519Client
    from plenum_tpu.parallel.federation import FederatedCryptoPipeline
    from plenum_tpu.parallel.supervisor import supervise
    server, connect = service
    sock = connect().socket_path
    class FakeDev(JaxEd25519Verifier):
        def __init__(self):
            super().__init__(min_batch=1)

        def submit_batch(self, items):
            return np.ones(len(items), dtype=bool)

        def collect_batch(self, token, wait=True):
            return token

    fed = supervise(FederatedEd25519Client(socket_path=sock),
                    label="remote0")
    pipe = FederatedCryptoPipeline(
        ed_inners=[FakeDev()],
        remote_inners=[fed], hosts=[sock],
        config=Config(PIPELINE_MIN_BUCKET=16, PIPELINE_MAX_BUCKET=64,
                      PIPELINE_FLUSH_WAIT=0.0),
        threaded=False)
    pipe.prewarm([16])
    assert pipe.lanes[1].bucketed is False  # negotiated: CPU host
    pipe.pin()
    n = 0
    toks = []
    for i in range(8):
        items = _make_items(4, tag=b"fed%d-" % i)
        toks.append(pipe.submit_verify(items))
        n += 4
    for t in toks:
        out = pipe.collect_verify(t, wait=True)
        assert out is not None and out.all()
    assert pipe.lanes[1].stats["dispatches"] >= 1, \
        "the remote lane never carried a wave"
    assert pipe.stats["dispatched_items"] == n
    assert pipe.stats["unpinned_shapes"] == 0
    assert pipe.federation_state()["remote_lanes"] == 1
    assert pipe.federation_state()["ship_ms_p95"] > 0.0
    pipe.close()
