"""Service-level consensus tests: real Replica services, SimNetwork transport,
MockTimer — no sockets, no node (ref plenum/test/consensus/conftest.py seam)."""
import pytest

from plenum_tpu.common.internal_messages import VoteForViewChange
from plenum_tpu.common.node_messages import (Commit, Ordered, PrePrepare,
                                             Prepare, DOMAIN_LEDGER_ID)
from plenum_tpu.common.internal_messages import ReqKey
from plenum_tpu.common.request import Request
from plenum_tpu.common.suspicion_codes import Suspicions
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.config import Config
from plenum_tpu.consensus.batch_executor import SimBatchExecutor
from plenum_tpu.consensus.replica import Replica
from plenum_tpu.network import (Deliver, Discard, SimNetwork, SimRandom,
                                match_frm, match_type)

NODES = ["Alpha", "Beta", "Gamma", "Delta"]


class PoolSim:
    """In-process pool of one replica per node over a seeded SimNetwork."""

    def __init__(self, names=NODES, seed=42, config=None, with_bls=False):
        self.names = list(names)
        self.timer = MockTimer()
        self.net = SimNetwork(self.timer, SimRandom(seed))
        self.config = config or Config()
        self.requests: dict[str, Request] = {}
        self.replicas: dict[str, Replica] = {}
        self.ordered: dict[str, list[Ordered]] = {n: [] for n in self.names}
        self.executors: dict[str, SimBatchExecutor] = {}

        bls_parts = {}
        if with_bls:
            from plenum_tpu.crypto.bls import BlsCryptoSigner, BlsCryptoVerifier
            from plenum_tpu.consensus.bls_bft_replica import (BlsBftReplica,
                                                              BlsKeyRegister)
            signers = {n: BlsCryptoSigner(seed=n.encode().ljust(32, b"\0"))
                       for n in self.names}
            register = BlsKeyRegister({n: s.pk for n, s in signers.items()})
            for n in self.names:
                bls_parts[n] = BlsBftReplica(
                    node_name=n, bls_signer=signers[n],
                    bls_verifier=BlsCryptoVerifier(), key_register=register)

        for name in self.names:
            bus = self.net.create_peer(name)
            executor = SimBatchExecutor()
            self.executors[name] = executor
            replica = Replica(node_name=name, inst_id=0,
                              validators=self.names, timer=self.timer,
                              network=bus, executor=executor,
                              bls=bls_parts.get(name), config=self.config,
                              get_request=self.requests.get)
            replica.internal_bus.subscribe(
                Ordered, lambda m, n=name: self.ordered[n].append(m))
            self.replicas[name] = replica
        self.net.connect_all()

    def finalize_request(self, req: Request, to=None):
        """Make a request available on (a subset of) nodes, as the propagate
        quorum would."""
        self.requests[req.digest] = req
        for name in (to or self.names):
            self.replicas[name].internal_bus.send(ReqKey(req.digest))

    def run(self, seconds=5.0, step=0.25):
        elapsed = 0.0
        while elapsed < seconds:
            for r in self.replicas.values():
                r.service()
            self.timer.advance(step)
            elapsed += step

    def primary_name(self):
        return self.replicas[self.names[0]].data.primaries[0]


def make_request(i: int) -> Request:
    return Request(identifier=f"client{i % 3}", req_id=1000 + i,
                   operation={"type": "1", "dest": f"did{i}"},
                   signature="sig")


def test_happy_path_orders_batch_on_all_nodes():
    pool = PoolSim()
    req = make_request(0)
    pool.finalize_request(req)
    pool.run(3.0)
    for name in NODES:
        assert len(pool.ordered[name]) == 1, f"{name} did not order"
        o = pool.ordered[name][0]
        assert o.req_idr == (req.digest,)
        assert o.pp_seq_no == 1
    # Deterministic executor: every master applied identical state.
    roots = {pool.ordered[n][0].state_root for n in NODES}
    assert len(roots) == 1


def test_multiple_batches_stay_in_order():
    pool = PoolSim()
    for i in range(5):
        pool.finalize_request(make_request(i))
        pool.run(1.5)
    seqs = [o.pp_seq_no for o in pool.ordered["Beta"]]
    assert seqs == sorted(seqs)
    assert seqs[-1] >= 2
    # All nodes converge to the same ordered log.
    logs = {n: tuple((o.pp_seq_no, o.state_root) for o in pool.ordered[n])
            for n in NODES}
    assert len(set(logs.values())) == 1


def test_batching_coalesces_requests():
    pool = PoolSim()
    reqs = [make_request(i) for i in range(10)]
    for r in reqs:
        pool.requests[r.digest] = r
    # Deliver all ReqKeys before any service cycle: one batch expected.
    for r in reqs:
        for name in NODES:
            pool.replicas[name].internal_bus.send(ReqKey(r.digest))
    pool.run(3.0)
    assert len(pool.ordered["Alpha"]) == 1
    assert len(pool.ordered["Alpha"][0].req_idr) == 10


def test_checkpoint_stabilizes_and_garbage_collects():
    pool = PoolSim(config=Config(CHK_FREQ=2, LOG_SIZE=6))
    for i in range(4):
        pool.finalize_request(make_request(i))
        pool.run(1.5)
    for name in NODES:
        data = pool.replicas[name].data
        assert data.stable_checkpoint >= 2, f"{name} at {data.stable_checkpoint}"
        assert data.low_watermark == data.stable_checkpoint
        ordering = pool.replicas[name].ordering
        assert all(k[1] > data.stable_checkpoint - 1
                   for k in ordering.prePrepares), "GC left stale entries"


def test_non_primary_preprepare_is_rejected():
    pool = PoolSim()
    suspicions = []
    pool.replicas["Beta"].internal_bus.subscribe(
        type(pool.replicas["Beta"]).__mro__ and
        __import__("plenum_tpu.common.internal_messages",
                   fromlist=["RaisedSuspicion"]).RaisedSuspicion,
        lambda m: suspicions.append(m))
    fake = PrePrepare(inst_id=0, view_no=0, pp_seq_no=1, pp_time=0.0,
                      req_idr=(), discarded=(), digest="bogus",
                      ledger_id=DOMAIN_LEDGER_ID, state_root="x", txn_root="y")
    # Gamma (not the primary) injects a PRE-PREPARE directly into Beta.
    pool.replicas["Beta"].network.process_incoming(fake, "Gamma")
    assert any(s.code == Suspicions.PPR_FRM_NON_PRIMARY.code for s in suspicions)
    assert len(pool.ordered["Beta"]) == 0


def test_view_change_replaces_dead_primary():
    pool = PoolSim()
    pool.finalize_request(make_request(0))
    pool.run(3.0)
    assert all(len(pool.ordered[n]) == 1 for n in NODES)
    old_primary = pool.primary_name()
    assert old_primary == "Alpha"

    # Kill the primary's outbound traffic, then vote (as the monitor would).
    pool.net.add_rule(Discard(), match_frm("Alpha"))
    for name in ["Beta", "Gamma", "Delta"]:
        pool.replicas[name].internal_bus.send(
            VoteForViewChange(Suspicions.PRIMARY_DEGRADED.code))
    pool.run(5.0)

    for name in ["Beta", "Gamma", "Delta"]:
        data = pool.replicas[name].data
        assert data.view_no == 1, f"{name} stuck at view {data.view_no}"
        assert not data.waiting_for_new_view
        assert data.primaries[0] == "Beta"

    # The new primary keeps ordering where the old one left off.
    req = make_request(99)
    pool.finalize_request(req, to=["Beta", "Gamma", "Delta"])
    pool.run(4.0)
    for name in ["Beta", "Gamma", "Delta"]:
        last = pool.ordered[name][-1]
        assert last.req_idr == (req.digest,)
        assert last.view_no == 1
        assert last.pp_seq_no == 2


def test_view_change_reorders_prepared_batch():
    """A batch prepared before the view change must be re-ordered in the new
    view with its original digest (ref calc_batches + re-ordering)."""
    pool = PoolSim()
    req = make_request(0)
    # Block COMMITs so the batch prepares but never orders.
    rule = pool.net.add_rule(Discard(), match_type(Commit))
    pool.finalize_request(req)
    pool.run(3.0)
    assert all(len(pool.ordered[n]) == 0 for n in NODES)
    prepared = [n for n in NODES if pool.replicas[n].data.prepared]
    assert len(prepared) >= 3

    pool.net.remove_rule(rule)
    for name in NODES:
        pool.replicas[name].internal_bus.send(
            VoteForViewChange(Suspicions.PRIMARY_DEGRADED.code))
    pool.run(6.0)

    for name in NODES:
        data = pool.replicas[name].data
        assert data.view_no == 1
        assert not data.waiting_for_new_view
    # The batch ordered in view 1 carrying the view-0 payload.
    for name in NODES:
        assert len(pool.ordered[name]) == 1, f"{name}: {pool.ordered[name]}"
        o = pool.ordered[name][0]
        assert o.req_idr == (req.digest,)
        assert o.view_no == 1
        assert o.original_view_no == 0


def test_out_of_order_commit_quorums_order_sequentially():
    pool = PoolSim(seed=7)
    # Make batch 1's traffic slow so batch 2 completes its quorum first.
    slow = pool.net.add_rule(Deliver(2.0, 2.5), match_type((Prepare, Commit)))
    pool.finalize_request(make_request(0))
    pool.run(0.5)
    pool.net.remove_rule(slow)
    pool.finalize_request(make_request(1))
    pool.run(6.0)
    for name in NODES:
        seqs = [o.pp_seq_no for o in pool.ordered[name]]
        assert seqs == [1, 2], f"{name}: {seqs}"


def test_bls_multi_sig_survives_one_bad_signer():
    """Regression: a batch orders at quorum n-f COMMITs, so with one
    Byzantine signer among the first arrivals the honest aggregate falls
    short at order time; the late honest COMMIT (stale for 3PC — its key is
    already ordered) must still reach the BLS retry, or one bad signer
    suppresses multi-sigs on most of the pool forever."""
    pool = PoolSim(with_bls=True)

    class EvilSigner:
        def __init__(self, inner):
            self._inner = inner
        def sign(self, message):
            return self._inner.sign(b"EVIL " + message)

    evil = pool.names[-1]
    pool.replicas[evil].bls._signer = EvilSigner(
        pool.replicas[evil].bls._signer)
    req = make_request(0)
    pool.finalize_request(req)
    pool.run(5.0)
    assert all(len(pool.ordered[n]) == 1 for n in NODES)
    o = pool.ordered["Alpha"][0]
    for name in NODES:
        ms = pool.replicas[name].bls._recent_multi_sigs.get(o.state_root)
        assert ms is not None, f"{name} never formed a multi-sig"
        assert evil not in ms.participants
        assert len(ms.participants) == 3


def test_bls_multi_sig_collected_on_order():
    pool = PoolSim(with_bls=True)
    req = make_request(0)
    pool.finalize_request(req)
    pool.run(3.0)
    assert all(len(pool.ordered[n]) == 1 for n in NODES)
    # After ordering, each node aggregated a multi-sig over the state root.
    o = pool.ordered["Alpha"][0]
    for name in NODES:
        bls = pool.replicas[name].bls
        ms = bls._recent_multi_sigs.get(o.state_root)
        assert ms is not None, f"{name} has no multi-sig"
        assert len(ms.participants) >= 3
    # Second batch embeds the first batch's multi-sig in its PRE-PREPARE.
    pool.finalize_request(make_request(1))
    pool.run(3.0)
    pp = pool.replicas["Beta"].ordering.prePrepares[(0, 2)]
    assert pp.bls_multi_sig is not None
