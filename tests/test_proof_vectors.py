"""Golden-vector pin for the state-commitment proof formats.

tools/proof_vectors.py writes canonical (keys -> root -> proof ->
verify) fixtures for BOTH backends into tests/vectors/; this tier-1
test regenerates them in-process and verifies the checked-in bytes with
the current verifiers. A verifier-side encoding drift (transcript
order, domain separator, leaf-scalar preimage, RLP/msgpack layout)
breaks HERE instead of silently invalidating every proof a deployed
client already holds — the exact discipline the wire-format tests apply
to messages.
"""
from __future__ import annotations

import json

import pytest

from plenum_tpu.tools import proof_vectors as pv


@pytest.fixture(scope="module")
def checked_in():
    with open(pv.VECTORS_PATH) as fh:
        return json.load(fh)


def test_vectors_match_and_verify(checked_in):
    problems = pv.check_vectors(checked_in)
    assert not problems, "\n".join(problems)


def test_vectors_cover_both_backends(checked_in):
    assert set(checked_in["backends"]) == {"mpt", "verkle"}
    for backend, vec in checked_in["backends"].items():
        for field in ("root", "single_proof", "absence_proof",
                      "page_proof"):
            assert vec.get(field), f"{backend}.{field} empty"


def test_tampered_vector_fails_closed(checked_in):
    """A flipped byte anywhere in a checked-in proof must verify False —
    the vectors double as a canonical tamper fixture for client code."""
    from plenum_tpu.state.commitment import PruningState, VerkleState
    for backend, cls in (("mpt", PruningState), ("verkle", VerkleState)):
        vec = checked_in["backends"][backend]
        root = bytes.fromhex(vec["root"])
        proof = bytearray(bytes.fromhex(vec["single_proof"]))
        proof[len(proof) // 2] ^= 0x01
        assert not cls.verify_state_proof(
            root, pv.FIXTURE_KEYS[0], pv.FIXTURE_VALUES[0], bytes(proof))
        # and against a different root the honest proof fails too
        bad_root = bytes(32)
        assert not cls.verify_state_proof(
            bad_root, pv.FIXTURE_KEYS[0], pv.FIXTURE_VALUES[0],
            bytes.fromhex(vec["single_proof"]))
