"""Golden-vector pin for the state-commitment proof formats.

tools/proof_vectors.py writes canonical (keys -> root -> proof ->
verify) fixtures for BOTH backends into tests/vectors/; this tier-1
test regenerates them in-process and verifies the checked-in bytes with
the current verifiers. A verifier-side encoding drift (transcript
order, domain separator, leaf-scalar preimage, RLP/msgpack layout)
breaks HERE instead of silently invalidating every proof a deployed
client already holds — the exact discipline the wire-format tests apply
to messages.
"""
from __future__ import annotations

import json

import pytest

from plenum_tpu.tools import proof_vectors as pv


@pytest.fixture(scope="module")
def checked_in():
    with open(pv.VECTORS_PATH) as fh:
        return json.load(fh)


def test_vectors_match_and_verify(checked_in):
    problems = pv.check_vectors(checked_in)
    assert not problems, "\n".join(problems)


def test_vectors_cover_both_backends(checked_in):
    assert set(checked_in["backends"]) == {"mpt", "verkle"}
    for backend, vec in checked_in["backends"].items():
        for field in ("root", "single_proof", "absence_proof",
                      "page_proof"):
            assert vec.get(field), f"{backend}.{field} empty"


def test_recommit_roots_fused_matches_host(checked_in):
    """The commit-wave drift pin: the fused recommit root must equal the
    host-resolved root AND the checked-in vector for both commitment
    backends — a staging or kernel change that forks the state root
    breaks here in tier-1, never silently on a running pool."""
    for backend in ("mpt", "verkle"):
        rec = pv.recommit_roots(backend)
        assert rec["fused"] == rec["host"], \
            f"{backend}: fused recommit root drifted from host"
        assert rec["host"] == \
            checked_in["backends"][backend]["recommit_root"], \
            f"{backend}: recommit root drifted from the checked-in vector"


def test_ledger_recommit_root_fused_matches_host(checked_in):
    rec = pv.ledger_recommit_roots()
    assert rec["fused"] == rec["host"]
    assert rec["host"] == checked_in["ledger_recommit_root"]


def test_tampered_vector_fails_closed(checked_in):
    """A flipped byte anywhere in a checked-in proof must verify False —
    the vectors double as a canonical tamper fixture for client code."""
    from plenum_tpu.state.commitment import PruningState, VerkleState
    for backend, cls in (("mpt", PruningState), ("verkle", VerkleState)):
        vec = checked_in["backends"][backend]
        root = bytes.fromhex(vec["root"])
        proof = bytearray(bytes.fromhex(vec["single_proof"]))
        proof[len(proof) // 2] ^= 0x01
        assert not cls.verify_state_proof(
            root, pv.FIXTURE_KEYS[0], pv.FIXTURE_VALUES[0], bytes(proof))
        # and against a different root the honest proof fails too
        bad_root = bytes(32)
        assert not cls.verify_state_proof(
            bad_root, pv.FIXTURE_KEYS[0], pv.FIXTURE_VALUES[0],
            bytes.fromhex(vec["single_proof"]))
