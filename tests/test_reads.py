"""Verified read plane: single-reply state-proof reads (docs/reads.md).

Covers the server ReadPlane (envelopes, anchoring, cache), the shared
verification path (MultiSignature.verify + verify_read_proof soundness
against tampering), the client ladder (SimReadDriver fanout/failover),
the read-reply quorum-key fix in PoolClient, and the GET_TXN ledgerId
NACK.
"""
from __future__ import annotations

import copy

import pytest

from plenum_tpu.client.client import PoolClient
from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID, Reply,
                                             RequestNack)
from plenum_tpu.common.request import Request
from plenum_tpu.crypto.bls import BlsCryptoSigner
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.crypto.multi_signature import (MultiSignature,
                                               MultiSignatureValue)
from plenum_tpu.execution.txn import (GET_ATTR, GET_NYM, GET_TXN, ATTRIB,
                                      NYM)
from plenum_tpu.reads import (READ_PROOF, SimReadDriver, result_digest,
                              verify_read_proof)

from test_pool import Pool, signed_nym

FOREVER = 1e12          # freshness bound that never triggers


def pool_bls_keys(pool) -> dict:
    # the canonical name-seeded derivation (matches test_pool genesis)
    from plenum_tpu.tools.local_pool import pool_bls_keys as derive
    return derive(pool.names)


def make_driver(pool, client="drv", freshness_s=FOREVER):
    def submit(name, req):
        pool.nodes[name].handle_client_message(req.to_dict(), client)

    def collect(name):
        msgs = pool.client_msgs[name]
        out = [m.result for m, c in msgs
               if isinstance(m, Reply) and c == client]
        pool.client_msgs[name] = [
            (m, c) for m, c in msgs
            if not (isinstance(m, Reply) and c == client)]
        return out

    return SimReadDriver(submit, collect, pool.run, pool.names,
                         pool_bls_keys(pool), freshness_s=freshness_s,
                         now=pool.timer.get_current_time)


@pytest.fixture(scope="module")
def rpool():
    pool = Pool()
    user = Ed25519Signer(seed=b"reads-user-1".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(6.0)
    # one ATTRIB so GET_ATTR has something to prove
    import json
    req = Request(pool.trustee.identifier, 2,
                  {"type": ATTRIB, "dest": user.identifier,
                   "raw": json.dumps({"endpoint": "https://x"})})
    req.signature = pool.trustee.sign_b58(req.signing_bytes())
    pool.submit(req)
    pool.run(6.0)
    pool.user = user
    return pool


# --- single verified reply, per proof kind -------------------------------

def test_get_nym_single_reply_verifies(rpool):
    driver = make_driver(rpool)
    q = Request("anyone", 100, {"type": GET_NYM,
                                "dest": rpool.user.identifier})
    res = driver.read(q)
    assert res is not None
    assert res["data"]["verkey"] == rpool.user.verkey_b58
    assert res[READ_PROOF]["kind"] == "state"
    s = driver.stats
    assert s.single_reply_ok == 1 and s.failovers == 0 and s.fallbacks == 0
    # THE fanout claim: one request out, one reply in
    assert s.msgs_sent == 1 and s.replies_seen == 1


def test_get_nym_absence_proof_verifies(rpool):
    driver = make_driver(rpool)
    q = Request("anyone", 101, {"type": GET_NYM, "dest": "NoSuchDid999"})
    res = driver.read(q)
    assert res is not None and res["data"] is None
    assert driver.stats.single_reply_ok == 1


def test_get_attr_single_reply_verifies(rpool):
    driver = make_driver(rpool)
    q = Request("anyone", 102, {"type": GET_ATTR,
                                "dest": rpool.user.identifier,
                                "attr_name": "endpoint"})
    res = driver.read(q)
    assert res is not None
    assert res["meta"]["kind"] == "raw"
    assert driver.stats.single_reply_ok == 1


def test_get_txn_merkle_anchored_to_signed_root(rpool):
    driver = make_driver(rpool)
    q = Request("anyone", 103, {"type": GET_TXN,
                                "ledgerId": DOMAIN_LEDGER_ID, "data": 2})
    res = driver.read(q)
    assert res is not None
    env = res[READ_PROOF]
    assert env["kind"] == "merkle"
    # anchored to the multi-sig's txn root, not the legacy current root
    assert env["txn_root"] == env["multi_signature"][2][3]
    assert driver.stats.single_reply_ok == 1


def test_get_txn_beyond_signed_tree_absence(rpool):
    driver = make_driver(rpool)
    q = Request("anyone", 104, {"type": GET_TXN,
                                "ledgerId": DOMAIN_LEDGER_ID,
                                "data": 999})
    res = driver.read(q)
    assert res is not None and res["data"] is None
    assert driver.stats.single_reply_ok == 1


def test_get_txn_invalid_ledger_id_nacked(rpool):
    """Satellite: an invalid ledgerId must NACK, not silently coerce to
    DOMAIN (which would answer a different question than asked)."""
    node = rpool.nodes["Alpha"]
    q = Request("anyone", 105, {"type": GET_TXN, "ledgerId": 99,
                                "data": 1})
    node.handle_client_message(q.to_dict(), "nack-cli")
    rpool.run(0.5)
    nacks = [m for m, c in rpool.client_msgs["Alpha"]
             if isinstance(m, RequestNack) and c == "nack-cli"]
    assert nacks and "ledgerId" in nacks[-1].reason


# --- tamper suite: every forgery must fail CLOSED ------------------------

def _verified_result(rpool, req_id=120):
    node = rpool.nodes["Alpha"]
    q = Request("anyone", req_id, {"type": GET_NYM,
                                   "dest": rpool.user.identifier})
    res = node.read_plane.answer(q)
    keys = pool_bls_keys(rpool)
    ok, reason = verify_read_proof(
        GET_NYM, q.operation, res, keys, freshness_s=FOREVER,
        now=rpool.timer.get_current_time)
    assert ok, reason
    return q, res, keys


def _reverify(rpool, q, res, keys):
    return verify_read_proof(GET_NYM, q.operation, res, keys,
                             freshness_s=FOREVER,
                             now=rpool.timer.get_current_time)


def test_tampered_value_rejected(rpool):
    q, res, keys = _verified_result(rpool)
    bad = copy.deepcopy(res)
    ent = bad[READ_PROOF]["entries"][0]
    ent["value"] = bytes(reversed(bytes.fromhex(ent["value"]))).hex()
    ok, reason = _reverify(rpool, q, bad, keys)
    assert not ok and reason in ("bad_state_proof", "data_mismatch")


def test_tampered_data_rejected(rpool):
    q, res, keys = _verified_result(rpool)
    bad = copy.deepcopy(res)
    bad["data"] = dict(bad["data"], verkey="FakeVerkey111111111111")
    ok, reason = _reverify(rpool, q, bad, keys)
    assert not ok and reason == "result_digest_mismatch"


def test_unsigned_root_rejected(rpool):
    q, res, keys = _verified_result(rpool)
    bad = copy.deepcopy(res)
    bad[READ_PROOF]["root_hash"] = "ab" * 32
    bad[READ_PROOF]["result_digest"] = result_digest(bad).hex()
    ok, reason = _reverify(rpool, q, bad, keys)
    assert not ok and reason == "unsigned_root"


def test_tampered_multi_sig_participants_rejected(rpool):
    q, res, keys = _verified_result(rpool)
    bad = copy.deepcopy(res)
    ms = bad[READ_PROOF]["multi_signature"]
    # claim a participant set the aggregate was not built from
    ms[1] = list(ms[1])[:-1] + ["Alpha"] \
        if ms[1][-1] != "Alpha" else list(ms[1])[:-1] + ["Beta"]
    bad[READ_PROOF]["result_digest"] = result_digest(bad).hex()
    ok, reason = _reverify(rpool, q, bad, keys)
    assert not ok


def test_spliced_proof_from_other_result_rejected(rpool):
    """An honest envelope spliced onto a different (honest) result must
    fail the result-digest binding."""
    q1, res1, keys = _verified_result(rpool, req_id=121)
    node = rpool.nodes["Alpha"]
    q2 = Request("anyone", 122, {"type": GET_NYM, "dest": "NoSuchDid999"})
    res2 = node.read_plane.answer(q2)
    spliced = copy.deepcopy(res2)
    spliced[READ_PROOF] = copy.deepcopy(res1[READ_PROOF])
    ok, reason = verify_read_proof(
        GET_NYM, q2.operation, spliced, keys, freshness_s=FOREVER,
        now=rpool.timer.get_current_time)
    assert not ok and reason == "result_digest_mismatch"


def test_freshness_bound_rejects_old_anchor(rpool):
    q, res, keys = _verified_result(rpool)
    ok, reason = verify_read_proof(
        GET_NYM, q.operation, res, keys, freshness_s=5.0,
        now=lambda: rpool.timer.get_current_time() + 3600.0)
    assert not ok and reason == "stale"


def _anchor_ts(res) -> float:
    return MultiSignature.from_list(
        list(res[READ_PROOF]["multi_signature"])).value.timestamp


def test_freshness_exactly_at_bound_passes(rpool):
    """The bound is inclusive (`abs(skew) > freshness_s` rejects): an
    anchor EXACTLY freshness_s old still verifies — the edge tier's
    stale-while-revalidate window leans on this edge."""
    q, res, keys = _verified_result(rpool, req_id=130)
    ts = _anchor_ts(res)
    ok, reason = verify_read_proof(
        GET_NYM, q.operation, res, keys, freshness_s=5.0,
        now=lambda: ts + 5.0)
    assert ok, reason


def test_freshness_just_past_bound_rejects(rpool):
    q, res, keys = _verified_result(rpool, req_id=131)
    ts = _anchor_ts(res)
    ok, reason = verify_read_proof(
        GET_NYM, q.operation, res, keys, freshness_s=5.0,
        now=lambda: ts + 5.0001)
    assert not ok and reason == "stale"


def test_freshness_rejects_future_anchor_clock_skew(rpool):
    """abs() makes the window symmetric: an anchor from the FUTURE
    (skewed or lying clock) beyond the bound fails exactly like an old
    one; inside the bound the skew is tolerated."""
    q, res, keys = _verified_result(rpool, req_id=132)
    ts = _anchor_ts(res)
    ok, _ = verify_read_proof(
        GET_NYM, q.operation, res, keys, freshness_s=5.0,
        now=lambda: ts - 5.0)
    assert ok                     # skew inside the bound: tolerated
    ok, reason = verify_read_proof(
        GET_NYM, q.operation, res, keys, freshness_s=5.0,
        now=lambda: ts - 5.0001)
    assert not ok and reason == "stale"


# --- cache + invalidation -------------------------------------------------

def test_result_cache_hits_and_commit_invalidation():
    pool = Pool(seed=77)
    user = Ed25519Signer(seed=b"cache-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(6.0)
    node = pool.nodes["Alpha"]
    plane = node.read_plane

    q1 = Request("r1", 1, {"type": GET_NYM, "dest": user.identifier})
    q2 = Request("r2", 9, {"type": GET_NYM, "dest": user.identifier})
    r1 = plane.answer(q1)
    hits_before = plane.stats["cache_hits"]
    r2 = plane.answer(q2)            # same question, different asker
    assert plane.stats["cache_hits"] == hits_before + 1
    # per-request echo differs, content identical
    assert (r1["identifier"], r1["reqId"]) == ("r1", 1)
    assert (r2["identifier"], r2["reqId"]) == ("r2", 9)
    assert result_digest(r1) == result_digest(r2)

    # rotate the DID's verkey -> batch commit must invalidate the cache
    rotated = Ed25519Signer(seed=b"cache-user-2".ljust(32, b"\0")[:32])
    upd = Request(pool.trustee.identifier, 2,
                  {"type": NYM, "dest": user.identifier,
                   "verkey": rotated.verkey_b58})
    upd.signature = pool.trustee.sign_b58(upd.signing_bytes())
    anchors_before = plane.stats["anchor_updates"]
    pool.submit(upd)
    pool.run(6.0)
    assert plane.stats["anchor_updates"] > anchors_before
    r3 = plane.answer(Request("r3", 1, {"type": GET_NYM,
                                        "dest": user.identifier}))
    assert r3["data"]["verkey"] == rotated.verkey_b58
    ok, reason = verify_read_proof(
        GET_NYM, {"type": GET_NYM, "dest": user.identifier}, r3,
        pool_bls_keys(pool), freshness_s=FOREVER,
        now=pool.timer.get_current_time)
    assert ok, reason


# --- MultiSignature.verify (satellite) -----------------------------------

def _ms_fixture():
    names = ["A", "B", "C", "D"]
    signers = {n: BlsCryptoSigner(seed=f"ms-{n}".encode().ljust(32, b"\0"))
               for n in names}
    keys = {n: s.pk for n, s in signers.items()}
    value = MultiSignatureValue(ledger_id=1, state_root_hash="aa" * 32,
                                pool_state_root_hash="bb" * 32,
                                txn_root_hash="cc" * 32, timestamp=42.0)
    participants = ("A", "B", "C")
    from plenum_tpu.crypto import bls as bls_lib
    agg = bls_lib.aggregate_sigs(
        [signers[n].sign(value.as_single_value()) for n in participants])
    return keys, MultiSignature(signature=agg, participants=participants,
                                value=value)


def test_multi_signature_verify_ok():
    keys, ms = _ms_fixture()
    assert ms.verify(keys)
    assert ms.verify(keys.get, n=4)         # callable lookup needs n


def test_multi_signature_verify_wrong_participant_set():
    keys, ms = _ms_fixture()
    lying = MultiSignature(ms.signature, ("A", "B", "D"), ms.value)
    assert not lying.verify(keys)
    unknown = MultiSignature(ms.signature, ("A", "B", "Zz"), ms.value)
    assert not unknown.verify(keys)
    dup = MultiSignature(ms.signature, ("A", "A", "B"), ms.value)
    assert not dup.verify(keys)


def test_multi_signature_verify_tampered_value():
    keys, ms = _ms_fixture()
    tampered = MultiSignature(
        ms.signature, ms.participants,
        ms.value._replace(timestamp=ms.value.timestamp + 1))
    assert not tampered.verify(keys)
    wrong_root = MultiSignature(
        ms.signature, ms.participants,
        ms.value._replace(state_root_hash="dd" * 32))
    assert not wrong_root.verify(keys)


def test_multi_signature_verify_sub_quorum_and_garbage():
    keys, ms = _ms_fixture()
    # 2 of 4 < n - f = 3
    from plenum_tpu.crypto import bls as bls_lib
    short = MultiSignature(ms.signature, ("A", "B"), ms.value)
    assert not short.verify(keys)
    garbage = MultiSignature("!!not-base58!!", ms.participants, ms.value)
    assert not garbage.verify(keys)
    # callable lookup without a pool size must refuse, not guess
    assert not ms.verify(keys.get)


# --- PoolClient read-reply quorum key (satellite) ------------------------

def test_vote_key_separates_diverging_read_replies():
    """Regression: read replies (no txn metadata) from nodes returning
    DIFFERENT data must land in DIFFERENT f+1 buckets."""
    honest = {"op": "REPLY",
              "result": {"type": GET_NYM, "dest": "D", "identifier": "c",
                         "reqId": 1, "data": {"verkey": "VK1"}}}
    lying = copy.deepcopy(honest)
    lying["result"]["data"] = {"verkey": "EVIL"}
    assert PoolClient._vote_key(honest) != PoolClient._vote_key(lying)
    # identical content from another node (even a different asker echo /
    # a different honest multi-sig participant subset) -> same bucket
    twin = copy.deepcopy(honest)
    twin["result"]["reqId"] = 1
    twin["result"][READ_PROOF] = {"kind": "state", "anything": 1}
    assert PoolClient._vote_key(honest) == PoolClient._vote_key(twin)
    # write replies keep voting by txn identity
    w1 = {"op": "REPLY", "result": {
        "txn": {"metadata": {"digest": "d1", "from": "c", "reqId": 1}},
        "txnMetadata": {"seqNo": 7}}}
    w2 = copy.deepcopy(w1)
    assert PoolClient._vote_key(w1) == PoolClient._vote_key(w2)
    w2["result"]["txnMetadata"]["seqNo"] = 8
    assert PoolClient._vote_key(w1) != PoolClient._vote_key(w2)
    nack = {"op": "REQNACK", "reason": "no"}
    assert PoolClient._vote_key(nack) == ("REQNACK", "no")


# --- failover + A/B fanout ------------------------------------------------

class LyingPlane:
    """Wraps a node's ReadPlane, corrupting every dict result."""

    def __init__(self, inner, mutate):
        self._inner = inner
        self._mutate = mutate

    def answer_batch(self, requests):
        out = []
        for o in self._inner.answer_batch(requests):
            if isinstance(o, dict):
                o = self._mutate(copy.deepcopy(o))
            out.append(o)
        return out

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _forge_value(result):
    env = result.get(READ_PROOF)
    if env and env.get("entries"):
        e = env["entries"][0]
        if e.get("value"):
            e["value"] = bytes(reversed(bytes.fromhex(e["value"]))).hex()
    return result


def test_failover_to_honest_node(rpool):
    liar = rpool.names[0]
    node = rpool.nodes[liar]
    real = node.read_plane
    node.read_plane = LyingPlane(real, _forge_value)
    try:
        driver = make_driver(rpool, client="fo")
        q = Request("anyone", 130, {"type": GET_NYM,
                                    "dest": rpool.user.identifier})
        res = driver.read(q, order=list(rpool.names))   # liar first
        assert res is not None
        assert res["data"]["verkey"] == rpool.user.verkey_b58
        s = driver.stats
        assert s.failovers == 1 and s.verify_failures == 1
        assert s.single_reply_ok == 1 and s.fallbacks == 0
    finally:
        node.read_plane = real


def test_fanout_ab_single_reply_vs_broadcast(rpool):
    """The acceptance A/B: a verified read is 1 request + 1 reply; the
    legacy path pays n requests + n replies for the same answer."""
    n = len(rpool.names)
    driver = make_driver(rpool, client="ab")
    for i in range(10):
        q = Request("ab", 200 + i, {"type": GET_NYM,
                                    "dest": rpool.user.identifier})
        assert driver.read(q) is not None
    s = driver.stats.summary()
    assert s["fanout"] == 2.0            # 1 tx + 1 rx per read
    # legacy broadcast: same 10 reads cost n tx + n rx each
    legacy_msgs = 0
    for i in range(10):
        q = Request("ab-legacy", 300 + i,
                    {"type": GET_NYM, "dest": rpool.user.identifier})
        rpool.submit(q, client="ab-legacy")
        legacy_msgs += n
    rpool.run(1.0)
    replies = [m for name in rpool.names
               for m, c in rpool.client_msgs[name]
               if isinstance(m, Reply) and c == "ab-legacy"]
    legacy_fanout = (legacy_msgs + len(replies)) / 10
    assert legacy_fanout >= 2 * n        # n requests + n replies per read
    assert s["fanout"] * n <= legacy_fanout


def test_read_plane_metrics_flow():
    """Proof-gen timers + cache gauges reach the flushed metrics rows."""
    import tempfile
    from plenum_tpu.common.metrics import MetricsName
    pool = Pool(seed=91)
    user = Ed25519Signer(seed=b"metrics-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(6.0)
    node = pool.nodes["Alpha"]
    for i in range(3):
        node.handle_client_message(
            Request("m", i + 1, {"type": GET_NYM,
                                 "dest": user.identifier}).to_dict(), "m")
    pool.run(0.5)
    accs = node.metrics.accumulators
    assert accs[MetricsName.READ_QUERIES].total >= 3
    assert MetricsName.READ_PROOF_GEN_TIME in accs
    node._sample_crypto_gauges()
    assert accs[MetricsName.READ_CACHE_HITS].max >= 1


# --- VerifyingReadClient over real sockets -------------------------------

def test_verifying_read_client_tcp_ladder(rpool):
    """The asyncio client end to end: framed wire, single-node sends,
    verify, failover past a lying server to an honest one."""
    import asyncio

    from plenum_tpu.common.serialization import pack, unpack
    from plenum_tpu.reads.client import VerifyingReadClient, ladder_order

    node = rpool.nodes["Alpha"]
    q = Request("tcpc", 900, {"type": GET_NYM,
                              "dest": rpool.user.identifier})
    honest_core = node.read_plane.answer(q)
    keys = pool_bls_keys(rpool)

    def personalize(core, req_dict):
        out = copy.deepcopy(core)
        out["identifier"] = req_dict.get("identifier")
        out["reqId"] = req_dict.get("reqId")
        return out

    async def serve(reader, writer, lie):
        try:
            while True:
                hdr = await reader.readexactly(4)
                frame = await reader.readexactly(
                    int.from_bytes(hdr, "big"))
                req_dict = unpack(frame)
                result = personalize(honest_core, req_dict)
                if lie:
                    result["data"] = dict(result["data"],
                                          verkey="EvilVerkey1111")
                    # smart liar: re-bind the digest so rejection comes
                    # from the proof chain, not the cheap digest check
                    from plenum_tpu.reads import result_digest
                    result[READ_PROOF] = dict(
                        result[READ_PROOF],
                        result_digest=result_digest(result).hex())
                data = pack({"op": "REPLY", "result": result})
                writer.write(len(data).to_bytes(4, "big") + data)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass

    async def main():
        srv_a = await asyncio.start_server(
            lambda r, w: serve(r, w, True), "127.0.0.1", 0)
        srv_b = await asyncio.start_server(
            lambda r, w: serve(r, w, True), "127.0.0.1", 0)
        ports = [s.sockets[0].getsockname()[1] for s in (srv_a, srv_b)]
        addrs = {"NodeA": ("127.0.0.1", ports[0]),
                 "NodeB": ("127.0.0.1", ports[1])}
        # whatever rung the ladder visits LAST becomes the honest one;
        # every earlier rung lies -> the read must fail over to the end
        order = ladder_order(list(addrs), q)
        lies = {order[0]: True, order[1]: False}
        honest_srv = await asyncio.start_server(
            lambda r, w: serve(r, w, False), "127.0.0.1", 0)
        # rebuild: liar on rung 0, honest on rung 1
        addrs[order[1]] = ("127.0.0.1",
                           honest_srv.sockets[0].getsockname()[1])
        client = VerifyingReadClient(
            addrs, f=0, bls_keys=keys, freshness_s=FOREVER,
            now=rpool.timer.get_current_time)
        try:
            msg = await client.submit_read(q, per_node_timeout=3.0)
        finally:
            await client.close()
            for s in (srv_a, srv_b, honest_srv):
                s.close()
        return msg, client.stats

    msg, stats = asyncio.run(main())
    assert msg["op"] == "REPLY"
    assert msg["result"]["data"]["verkey"] == rpool.user.verkey_b58
    assert stats.single_reply_ok == 1
    assert stats.failovers == 1 and stats.verify_failures == 1
    assert stats.fallbacks == 0


# --- review-fix regressions ----------------------------------------------

def test_forged_absence_tree_size_rejected(rpool):
    """The multi-sig signs no tree size: a liar claiming a SMALLER tree
    (to 'prove' a committed txn absent) must fail the last-leaf binding."""
    node = rpool.nodes["Alpha"]
    keys = pool_bls_keys(rpool)
    # honest absence envelope (seq 999 beyond the signed tree)
    honest = node.read_plane.answer(
        Request("abs", 1, {"type": GET_TXN, "ledgerId": DOMAIN_LEDGER_ID,
                           "data": 999}))
    env = honest[READ_PROOF]
    assert env.get("last_leaf"), "absence envelope must bind the size"
    # the lie: txn 2 exists, but claim tree_size=1 so 2 > size -> absent
    forged = copy.deepcopy(honest)
    forged["seqNo"] = 2
    fenv = forged[READ_PROOF]
    fenv["seq_no"] = 2
    fenv["tree_size"] = 1
    fenv["result_digest"] = result_digest(forged).hex()
    op = {"type": GET_TXN, "ledgerId": DOMAIN_LEDGER_ID, "data": 2}
    ok, reason = verify_read_proof(GET_TXN, op, forged, keys,
                                   freshness_s=FOREVER,
                                   now=rpool.timer.get_current_time)
    assert not ok and reason == "unbound_tree_size"
    # stripping the binding entirely must also fail closed
    stripped = copy.deepcopy(forged)
    stripped[READ_PROOF].pop("last_leaf")
    ok, _ = verify_read_proof(GET_TXN, op, stripped, keys,
                              freshness_s=FOREVER,
                              now=rpool.timer.get_current_time)
    assert not ok
    # tree_size=0 claim needs the empty-tree root, which the signed
    # root of a populated ledger is not
    zero = copy.deepcopy(forged)
    zero[READ_PROOF]["tree_size"] = 0
    zero[READ_PROOF]["result_digest"] = result_digest(zero).hex()
    ok, reason = verify_read_proof(GET_TXN, op, zero, keys,
                                   freshness_s=FOREVER,
                                   now=rpool.timer.get_current_time)
    assert not ok and reason == "unbound_tree_size"


def test_vote_key_ignores_legacy_multi_sig_variation():
    """Honest nodes embed whichever n-f COMMIT-sig subset they
    aggregated into the legacy state_proof field; identical read data
    must still pool into ONE f+1 bucket."""
    a = {"op": "REPLY", "result": {
        "type": GET_NYM, "dest": "D", "data": {"verkey": "VK"},
        "state_proof": {"root_hash": "aa", "proof_nodes": "bb",
                        "multi_signature": ["sig1", ["A", "B", "C"],
                                            [1, "r", "p", "t", 1.0]]}}}
    b = copy.deepcopy(a)
    b["result"]["state_proof"]["multi_signature"] = \
        ["sig2", ["B", "C", "D"], [1, "r", "p", "t", 1.0]]
    assert PoolClient._vote_key(a) == PoolClient._vote_key(b)
    # honest nodes answering at DIFFERENT commit points cite different
    # current roots in the advisory proof fields — still one bucket
    # (proofs are unsigned-by-this-quorum attachments, data is the vote)
    c = copy.deepcopy(a)
    c["result"]["state_proof"]["root_hash"] = "ee"
    c["result"]["merkle_proof"] = {"rootHash": "ff", "treeSize": 9}
    assert PoolClient._vote_key(a) == PoolClient._vote_key(c)
    # diverging DATA is real divergence
    d = copy.deepcopy(a)
    d["result"]["data"] = {"verkey": "OTHER"}
    assert PoolClient._vote_key(a) != PoolClient._vote_key(d)


def test_cache_invalidated_on_commit_even_without_anchor_advance():
    """When multi-sig aggregation lags a commit, the commit alone must
    flush the ledger's cache — otherwise the unchanged anchor key keeps
    serving pre-commit data."""
    pool = Pool(seed=55)
    user = Ed25519Signer(seed=b"lagging-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(6.0)
    plane = pool.nodes["Alpha"].read_plane
    plane.answer(Request("c", 1, {"type": GET_NYM,
                                  "dest": user.identifier}))
    assert plane._cache.get(DOMAIN_LEDGER_ID)
    # a commit whose multi-sig hasn't landed (state root not in the BLS
    # store): anchor stays put, cache must still flush
    anchors = dict(plane._anchors)
    plane.on_batch_committed(DOMAIN_LEDGER_ID, "ff" * 32, "ee" * 32)
    assert not plane._cache.get(DOMAIN_LEDGER_ID)
    assert plane._anchors == anchors


def test_forged_derived_metadata_rejected(rpool):
    """A smart liar re-binding the result digest after forging seqNo/
    txnTime/dest (fields a client consumes but that aren't the data
    blob) must still fail the proven-projection check."""
    for field, value in (("seqNo", 999999), ("txnTime", 1.0),
                         ("dest", "SomeOtherDid")):
        q, res, keys = _verified_result(rpool, req_id=140)
        bad = copy.deepcopy(res)
        bad[field] = value
        bad[READ_PROOF]["result_digest"] = result_digest(bad).hex()
        ok, reason = _reverify(rpool, q, bad, keys)
        assert not ok, f"forged {field} verified"
        assert reason == "data_mismatch", (field, reason)


def test_get_txn_default_ledger_gets_proof(rpool):
    """GET_TXN with ledgerId OMITTED defaults to DOMAIN (like the
    handler) and must still ship a verifiable envelope — not silently
    degrade every default-ledger read to the broadcast path."""
    driver = make_driver(rpool, client="dflt")
    q = Request("anyone", 150, {"type": GET_TXN, "data": 2})
    res = driver.read(q)
    assert res is not None, "default-ledger GET_TXN fell back"
    assert res[READ_PROOF]["kind"] == "merkle"
    assert driver.stats.single_reply_ok == 1
    assert driver.stats.fallbacks == 0


# --- observer-served verified reads (ingress/observer_reads.py) -----------

def _observer_pool(anchor_lag_max=None):
    """Fresh pool + registered observer + one committed NYM, with pushes
    routed. -> (pool, observer, user)."""
    from test_ingress import attach_observer, run_routed
    pool = Pool()
    obs = attach_observer(pool, anchor_lag_max=anchor_lag_max)
    user = Ed25519Signer(seed=b"obs-reads-user".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    run_routed(pool, [obs], 6.0)
    assert obs.batches_applied >= 1
    return pool, obs, user


def make_observer_driver(pool, obs, client="odrv", freshness_s=FOREVER):
    """Two-tier driver: the observer rung first, validators as failover."""
    def submit(name, req):
        if name == obs.name:
            obs.handle_client_message(req.to_dict(), client)
        else:
            pool.nodes[name].handle_client_message(req.to_dict(), client)

    def collect(name):
        if name == obs.name:
            out = [m.result for m, _ in obs.sent if isinstance(m, Reply)]
            obs.sent.clear()
            return out
        msgs = pool.client_msgs[name]
        out = [m.result for m, c in msgs
               if isinstance(m, Reply) and c == client]
        pool.client_msgs[name] = [
            (m, c) for m, c in msgs
            if not (isinstance(m, Reply) and c == client)]
        return out

    return SimReadDriver(submit, collect, pool.run, pool.names,
                         pool_bls_keys(pool), freshness_s=freshness_s,
                         now=pool.timer.get_current_time,
                         observer_names=[obs.name])


def test_observer_served_read_verifies_client_side():
    """An observer's reply carries a real proof at a VERIFIED BLS anchor;
    the client verifies it exactly like a validator's — consensus is
    never touched (fanout 1 request + 1 reply, all to the observer)."""
    pool, obs, user = _observer_pool()
    driver = make_observer_driver(pool, obs)
    q = Request("odrv", 10, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q)
    assert res is not None
    assert res["data"]["verkey"] == user.verkey_b58
    assert res[READ_PROOF]["kind"] == "state"
    s = driver.stats
    assert s.observer_ok == 1 and s.single_reply_ok == 1
    assert s.msgs_sent == 1 and s.replies_seen == 1
    assert s.failovers == 0 and s.fallbacks == 0


def test_tampered_observer_envelope_fails_over_to_validator():
    """A lying/compromised observer forging proven values must fail
    CLOSED at the client and fail over to a validator rung."""
    pool, obs, user = _observer_pool()
    obs.gate.read_plane = LyingPlane(obs.gate.read_plane, _forge_value)
    driver = make_observer_driver(pool, obs)
    q = Request("odrv", 11, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q)
    assert res is not None
    assert res["data"]["verkey"] == user.verkey_b58
    s = driver.stats
    assert s.verify_failures == 1 and s.failovers == 1
    assert s.observer_ok == 0 and s.single_reply_ok == 1
    assert s.fallbacks == 0


def test_stale_observer_replay_fails_over_to_validator():
    """An observer replaying a captured pre-rotation reply (honest sig,
    old anchor) is rejected by the client's freshness bound and the read
    fails over to a validator, which serves the ROTATED truth."""
    from test_ingress import run_routed
    pool, obs, user = _observer_pool()
    captured = obs.gate.answer_batch(
        [Request("cap", 1, {"type": GET_NYM,
                            "dest": user.identifier})])[0]
    assert READ_PROOF in captured

    pool.run(12.0)                      # age the captured anchor
    rotated = Ed25519Signer(seed=b"obs-rotated".ljust(32, b"\0")[:32])
    upd = Request(pool.trustee.identifier, 2,
                  {"type": NYM, "dest": user.identifier,
                   "verkey": rotated.verkey_b58})
    upd.signature = pool.trustee.sign_b58(upd.signing_bytes())
    pool.submit(upd)
    run_routed(pool, [obs], 6.0)

    obs.gate.read_plane = LyingPlane(
        obs.gate.read_plane,
        lambda result: dict(captured, identifier=result.get("identifier"),
                            reqId=result.get("reqId")))
    driver = make_observer_driver(pool, obs, freshness_s=8.0)
    q = Request("odrv", 12, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q)
    assert res is not None
    assert res["data"]["verkey"] == rotated.verkey_b58
    assert driver.stats.verify_failures >= 1
    assert driver.stats.failovers >= 1
    assert driver.stats.observer_ok == 0


def test_observer_anchor_advances_with_traffic():
    """Each committed batch's pushed multi-sig advances the observer's
    serving anchor (verified, then adopted) — reads after a write see
    the NEW state under the NEW anchor."""
    from test_ingress import run_routed
    pool, obs, user = _observer_pool()
    anchors_before = obs.gate.read_plane.stats["anchor_updates"]
    user2 = Ed25519Signer(seed=b"obs-reads-u2".ljust(32, b"\0")[:32])
    pool.submit(signed_nym(pool.trustee, user2, req_id=2))
    run_routed(pool, [obs], 6.0)
    assert obs.gate.read_plane.stats["anchor_updates"] > anchors_before
    driver = make_observer_driver(pool, obs)
    q = Request("odrv", 13, {"type": GET_NYM, "dest": user2.identifier})
    res = driver.read(q)
    assert res is not None and res["data"]["verkey"] == user2.verkey_b58
    assert driver.stats.observer_ok == 1
