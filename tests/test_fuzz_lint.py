"""tools/fuzz_lint.py: every registered sim-fuzz kind must keep an
always-on (non-slow) smoke rung in tier-1 — a kind living only in the
slow sweep is silent coverage loss."""
from __future__ import annotations

import textwrap

from plenum_tpu.tools.fuzz_lint import run_lint


def test_fuzz_suite_smoke_coverage():
    """The real suite: every scenario runner (base kinds AND the
    run_*_with_* compositions) is referenced by a non-slow test."""
    out = run_lint()
    assert out["check"] == "ok", out["problems"]
    assert out["scenarios"] >= 10        # the kinds this repo has grown
    assert out["smoke_covered"] == out["scenarios"]
    # the reshard kind introduced with live split/merge is registered
    assert "run_reshard_fuzz_scenario" in out["kinds"]
    # the Proof-CDN kind (a lying edge cache can deny, never forge)
    assert "run_lying_edge_scenario" in out["kinds"]


def test_fuzz_lint_catches_sweep_only_kind(tmp_path):
    """A scenario with ONLY a slow sweep must fail the lint; adding a
    smoke rung clears it."""
    bad = tmp_path / "bad_fuzz.py"
    bad.write_text(textwrap.dedent("""
        import pytest

        def run_orphan_scenario(seed):
            pass

        @pytest.mark.slow
        def test_orphan_fuzz():
            run_orphan_scenario(1)
    """))
    out = run_lint(str(bad))
    assert out["check"] == "FAIL"
    assert any("run_orphan_scenario" in p for p in out["problems"])

    good = tmp_path / "good_fuzz.py"
    good.write_text(textwrap.dedent("""
        import pytest

        def run_orphan_scenario(seed):
            pass

        @pytest.mark.slow
        def test_orphan_fuzz():
            run_orphan_scenario(1)

        def test_orphan_smoke():
            run_orphan_scenario(2)
    """))
    out = run_lint(str(good))
    assert out["check"] == "ok", out["problems"]


def test_fuzz_lint_smoke_via_lambda_counts(tmp_path):
    """The suite's idiom wraps scenarios in lambdas (force_rung pinning);
    the AST walk must see through them."""
    f = tmp_path / "lambda_fuzz.py"
    f.write_text(textwrap.dedent("""
        def run_thing_scenario(seed, force_rung=None):
            pass

        def _run_with_artifacts(fn, seed):
            fn(seed)

        def test_thing_smoke():
            _run_with_artifacts(
                lambda s: run_thing_scenario(s, force_rung=0), 1)
    """))
    out = run_lint(str(f))
    assert out["check"] == "ok", out["problems"]


def test_fuzz_lint_naming_drift_fails(tmp_path):
    """If the suite's naming convention drifts so discovery finds
    nothing, the lint fails loudly instead of vacuously passing."""
    f = tmp_path / "empty_fuzz.py"
    f.write_text("def helper():\n    pass\n")
    out = run_lint(str(f))
    assert out["check"] == "FAIL"
