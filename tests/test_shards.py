"""Horizontal state sharding (docs/sharding.md): the mapping ledger's
ownership proofs, the ShardRouter behind the ingress seam, the N-shard
sim fabric, composed cross-shard read verification (fail closed on every
tamper), and the shard-aware failover ladder.

The tier-1 CI smoke is `test_two_shard_smoke`: boot a 2-shard fabric,
route one write per shard, round-trip one verified cross-shard read.
"""
from __future__ import annotations

import copy

import pytest

from plenum_tpu.common.request import Request
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.execution.txn import GET_NYM, NYM
from plenum_tpu.shards import (MappingLedger, ShardDescriptor,
                               ShardReadGate, ShardedSimFabric,
                               equal_ranges, routing_key, verify_ownership)
from plenum_tpu.shards.mapping import directory_bls_signers

NOW = lambda: 1000.0


def make_map(n_shards=2, epoch=0):
    dirs = directory_bls_signers(["Dir1", "Dir2", "Dir3", "Dir4"])
    descs = [ShardDescriptor(i, lo, hi,
                             [f"S{i}N{j}" for j in range(1, 5)],
                             {f"S{i}N{j}": f"pk{i}{j}"
                              for j in range(1, 5)}, epoch=epoch)
             for i, (lo, hi) in enumerate(equal_ranges(n_shards))]
    return MappingLedger(descs, dirs, now=NOW)


def make_fabric(**kw):
    kw.setdefault("config", Config(Max3PCBatchWait=0.05))
    return ShardedSimFabric(n_shards=2, nodes_per_shard=4, seed=3, **kw)


def signed_write(fab, user, req_id):
    req = Request(fab.trustee.identifier, req_id,
                  {"type": NYM, "dest": user.identifier,
                   "verkey": user.verkey_b58})
    req.signature = fab.trustee.sign_b58(req.signing_bytes())
    return req


def user_on_shard(fab, sid, tag=b"u", start=0):
    """Deterministic search for a user whose DID the given shard owns."""
    for i in range(start, start + 400):
        u = Ed25519Signer(seed=(tag + b"%d" % i).ljust(32, b"\0")[:32])
        probe = Request(fab.trustee.identifier, 1,
                        {"type": NYM, "dest": u.identifier})
        if fab.router.shard_of(probe) == sid:
            return u
    raise AssertionError(f"no user found for shard {sid}")


# --- mapping ledger ---------------------------------------------------------

def test_equal_ranges_partition_the_keyspace():
    for n in (1, 2, 3, 4, 7):
        ranges = equal_ranges(n)
        assert ranges[0][0] == "0" * 64 and ranges[-1][1] is None
        for (_, hi), (lo, _) in zip(ranges, ranges[1:]):
            assert hi == lo                       # contiguous, no gaps
        ml = make_map(n) if n == 2 else None
    # every key owned by EXACTLY one shard
    ml = make_map(4)
    for i in range(50):
        key = (b"cover%d" % i)
        owners = [d.shard_id for d in ml.descriptors if d.owns(key)]
        assert len(owners) == 1, (key, owners)


def test_ownership_proof_roundtrip_and_tamper_fail_closed():
    ml = make_map(2)
    key = routing_key({"dest": "SomeDid123"})
    want = ml.shard_of(key).shard_id
    proof = ml.ownership_proof(key)
    keys = ml.directory_keys

    desc, why = verify_ownership(key, proof, keys, now=NOW)
    assert why == "ok" and desc.shard_id == want

    cases = []

    def tampered(mutate):
        p = copy.deepcopy(proof)
        mutate(p)
        return verify_ownership(key, p, keys, now=NOW)

    # forged descriptor content (keys, nodes, range) breaks inclusion
    for field, value in (("bls_keys", {"Evil": "pk"}),
                         ("nodes", ["Evil1", "Evil2"]),
                         ("lo", "0" * 64)):
        desc2, why2 = tampered(
            lambda p, f=field, v=value: p["descriptor"].__setitem__(f, v))
        cases.append((field, desc2, why2))
    for field, got, why2 in cases:
        assert got is None, field
        assert why2 in ("bad_map_inclusion", "wrong_shard"), (field, why2)
    # spliced audit path / index
    assert tampered(lambda p: p.__setitem__("index", 1 - p["index"]))[1] \
        == "bad_map_inclusion"
    assert tampered(lambda p: p["audit_path"].__setitem__(
        0, p["audit_path"][0][::-1])) \
        [1] in ("bad_map_inclusion", "malformed_map_proof")
    # the OTHER shard's (honestly signed) descriptor: valid map row,
    # wrong owner -> wrong_shard, never ok
    other = next(d for d in ml.descriptors if d.shard_id != want)
    assert tampered(lambda p: p.__setitem__(
        "descriptor", other.to_dict()))[1] == "wrong_shard"
    # a whole fake map signed by NON-directory keys
    evil = make_map(2)
    evil_signers = directory_bls_signers(["Evil1", "Evil2", "Evil3",
                                          "Evil4"])
    evil = MappingLedger(
        [ShardDescriptor.from_dict(d.to_dict()) for d in ml.descriptors],
        evil_signers, now=NOW)
    got, why2 = verify_ownership(key, evil.ownership_proof(key), keys,
                                 now=NOW)
    assert got is None and why2 == "bad_map_multi_sig"
    # freshness + malformed
    assert verify_ownership(key, proof, keys, now=lambda: 1e9)[1] \
        == "stale_map_sig"
    assert verify_ownership(key, None, keys, now=NOW)[1] == "no_map_proof"
    assert verify_ownership(key, {"descriptor": 3}, keys, now=NOW)[1] \
        == "malformed_map_proof"


def test_reshard_ratchets_epoch_and_stales_old_proofs():
    ml = make_map(2)
    key = routing_key({"dest": "EpochDid"})
    old = ml.ownership_proof(key)
    ml.reshard([ShardDescriptor.from_dict(d.to_dict())
                for d in ml.descriptors])
    assert ml.epoch == 1
    # an epoch-0 proof verifies only for clients that never saw epoch 1
    assert verify_ownership(key, old, ml.directory_keys, min_epoch=0,
                            now=NOW)[1] == "ok"
    assert verify_ownership(key, old, ml.directory_keys, min_epoch=1,
                            now=NOW)[1] == "stale_map"
    fresh = ml.ownership_proof(key)
    assert verify_ownership(key, fresh, ml.directory_keys, min_epoch=1,
                            now=NOW)[1] == "ok"


# --- the 2-shard fabric (tier-1 CI smoke) -----------------------------------

def test_two_shard_smoke():
    """Boot a 2-shard fabric, route ONE write per shard, round-trip one
    verified cross-shard read — the always-on acceptance slice."""
    fab = make_fabric()
    users = {sid: user_on_shard(fab, sid, b"smoke") for sid in fab.shards}
    for req_id, (sid, u) in enumerate(sorted(users.items()), start=1):
        assert fab.submit_write(signed_write(fab, u, req_id)) == sid
    fab.run(8.0)
    # each write ordered ONLY on its owning shard, consistently
    for sid, shard in fab.shards.items():
        assert shard.domain_sizes() == {2}, \
            (sid, shard.domain_sizes())
    assert fab.router.summary()["per_shard"] == {0: 1, 1: 1}

    # cross-shard read: shard 1's user, composed verification
    driver = fab.read_driver()
    u1 = users[1]
    q = Request("reader", 7, {"type": GET_NYM, "dest": u1.identifier})
    res = driver.read(q, per_node_s=2.0, step_s=0.1)
    assert res is not None and res["data"]["verkey"] == u1.verkey_b58
    s = driver.stats.summary()
    assert s["single_reply_ok"] == 1 and s["fallbacks"] == 0
    assert s["cross_reads"] == 1 and s["map_proof_failures"] == 0
    # the ladder asked ONLY the owning shard
    assert s["msgs_sent"] == 1 and s["fanout"] == 2.0


def test_router_unroutable_surfaces():
    fab = make_fabric()
    u = user_on_shard(fab, 1, b"hole")
    # a map with a hole: drop shard 1's descriptor and republish
    fab.mapping.descriptors = [d for d in fab.mapping.descriptors
                               if d.shard_id == 0]
    fab.mapping.publish()
    nacked = []
    fab.router.on_unroutable = lambda req, frm, why: nacked.append(why)
    assert fab.submit_write(signed_write(fab, u, 1)) is None
    assert nacked and fab.router.stats["unroutable"] == 1


def test_ingress_front_door_routes_across_shards():
    """Admission + ONE batched auth at the entry node, then the verified
    write fans to the OWNING shard's submit_preverified — the PR 7
    ingress seam composed with the router."""
    fab = make_fabric()
    entry = fab.shards[0].names[0]               # front door on shard 0
    ing = fab.ingress_plane(entry, tick=False)
    u = user_on_shard(fab, 1, b"ing")            # write owned by shard 1
    req = signed_write(fab, u, 1)
    ing.submit(req.to_dict(), "cli-ing")
    for _ in range(60):
        ing.service()
        fab.run(0.2)
        if fab.shards[1].ordered_count() >= 1:
            break
    assert fab.shards[1].domain_sizes() == {2}   # ordered where it belongs
    assert fab.shards[0].domain_sizes() == {1}   # entry shard untouched
    assert ing.stats["auth_batches"] == 1        # auth paid once, up front
    assert fab.ingress_router.summary()["per_shard"][1] == 1


def test_shared_pipeline_amortizes_across_shards():
    fab = make_fabric(share_pipeline=True)
    assert fab.pipeline is not None
    for sid in fab.shards:
        u = user_on_shard(fab, sid, b"pipe")
        fab.submit_write(signed_write(fab, u, sid + 1))
    deadline = 0.0
    while deadline < 20.0 and any(s.domain_sizes() != {2}
                                  for s in fab.shards.values()):
        fab.run(0.5)
        fab.pipeline.flush()
        deadline += 0.5
    for shard in fab.shards.values():
        assert shard.domain_sizes() == {2}
    # every shard's auth rode the ONE shared ring
    assert fab.pipeline.stats["dispatches"] >= 1
    for shard in fab.shards.values():
        for node in shard.nodes.values():
            assert node.c.pipeline is fab.pipeline


# --- cross-shard tamper + failover ------------------------------------------

class LyingGate:
    """Wraps a ShardReadGate with a forged decoration."""

    def __init__(self, inner, mutate):
        self.inner = inner
        self.mutate = mutate

    def decorate(self, result, key):
        return self.mutate(self.inner.decorate(result, key), key)


def _fabric_with_data():
    fab = make_fabric()
    users = {sid: user_on_shard(fab, sid, b"liar") for sid in fab.shards}
    for req_id, (sid, u) in enumerate(sorted(users.items()), start=1):
        fab.submit_write(signed_write(fab, u, req_id))
    fab.run(8.0)
    for shard in fab.shards.values():
        assert shard.domain_sizes() == {2}
    return fab, users


def test_forged_mapping_proof_fails_over_within_shard():
    fab, users = _fabric_with_data()
    evil = MappingLedger(
        [ShardDescriptor.from_dict(d.to_dict())
         for d in fab.mapping.descriptors],
        directory_bls_signers(["Ev1", "Ev2", "Ev3", "Ev4"]), now=NOW)

    def forge(result, key):
        result["shard_proof"] = evil.ownership_proof(key)
        return result

    # EVERY node of the owning shard serves the forged map: the ladder
    # must reject each rung fail-closed and end in the bounded fallback,
    # never accept
    fab.gates[1] = LyingGate(fab.gates[1], forge)
    driver = fab.read_driver()
    q = Request("r", 9, {"type": GET_NYM, "dest": users[1].identifier})
    res = driver.read(q, per_node_s=1.0, step_s=0.1)
    s = driver.stats.summary()
    assert res is None and s["fallbacks"] == 1
    assert s["map_proof_failures"] == 4          # one per shard rung
    assert s["map_failure_reasons"] == {"bad_map_multi_sig": 4}
    # heal the gate: the same driver verifies again
    fab.gates[1] = fab.gates[1].inner
    res = driver.read(Request("r", 10, {"type": GET_NYM,
                                        "dest": users[1].identifier}),
                      per_node_s=2.0, step_s=0.1)
    assert res is not None


def test_wrong_shard_answer_rejected():
    """A shard-0 node answering a shard-1 key serves a VALID-looking
    envelope (absence against ITS root) — the composed check kills it:
    the honest map proof names shard 1's keys, the envelope is signed by
    shard 0's."""
    fab, users = _fabric_with_data()
    driver = fab.read_driver()
    wrong = fab.shards[0].names[0]
    right = fab.shards[1].names
    q = Request("r", 11, {"type": GET_NYM, "dest": users[1].identifier})
    res = driver.read(q, per_node_s=2.0, step_s=0.1,
                      order=[wrong] + list(right))
    assert res is not None and \
        res["data"]["verkey"] == users[1].verkey_b58
    s = driver.stats.summary()
    assert s["verify_failures"] >= 1 and s["failovers"] >= 1
    assert s["fallbacks"] == 0


def test_stale_map_after_reshard_fails_closed():
    fab, users = _fabric_with_data()
    # shard 1's gate keeps serving the pre-reshard (epoch-0) map
    stale_ml = MappingLedger(
        [ShardDescriptor.from_dict(d.to_dict())
         for d in fab.mapping.descriptors],
        fab.directory, now=fab.timer.get_current_time)
    fab.gates[1] = ShardReadGate(stale_ml)
    fab.mapping.reshard([ShardDescriptor.from_dict(d.to_dict())
                         for d in fab.mapping.descriptors])
    driver = fab.read_driver()                   # view sees epoch 1
    q = Request("r", 12, {"type": GET_NYM, "dest": users[1].identifier})
    res = driver.read(q, per_node_s=1.0, step_s=0.1)
    s = driver.stats.summary()
    assert res is None and s["fallbacks"] == 1
    assert s["map_failure_reasons"].get("stale_map", 0) >= 1
    # the gate refreshes to the post-reshard map: reads verify again
    fab.gates[1] = ShardReadGate(fab.mapping)
    res = driver.read(Request("r", 13, {"type": GET_NYM,
                                        "dest": users[1].identifier}),
                      per_node_s=2.0, step_s=0.1)
    assert res is not None


def test_shard_aware_failover_stays_in_owning_shard():
    """The satellite regression: the ladder with a shard resolver fails
    over WITHIN the owning shard (first rung partitioned -> second rung
    of the SAME shard answers) and never consults a foreign shard —
    while a flat mis-configured client aimed at the wrong shard would
    accept that shard's VERIFIED absence as an answer."""
    from plenum_tpu.reads import SimReadDriver
    from plenum_tpu.tools.local_pool import pool_bls_keys

    fab, users = _fabric_with_data()
    q = Request("r", 14, {"type": GET_NYM, "dest": users[1].identifier})

    # the wrong-shard hazard the shard ladder exists to prevent: a flat
    # driver configured with ONLY shard 0's keys verifies shard 0's
    # absence proof for a key shard 1 holds — a lie that checks out
    from plenum_tpu.common.node_messages import Reply

    flat_names = fab.shards[0].names

    def flat_collect(n):
        msgs = fab.shards[0].client_msgs[n]
        out = [dict(m.result) for m, c in msgs
               if c == "flat" and isinstance(m, Reply)]
        fab.shards[0].client_msgs[n] = [(m, c) for m, c in msgs
                                        if c != "flat"]
        return out

    flat = SimReadDriver(
        lambda n, r: fab.shards[0].nodes[n].handle_client_message(
            r.to_dict(), "flat"),
        flat_collect,
        fab.run, flat_names, pool_bls_keys(flat_names), freshness_s=1e12,
        now=fab.timer.get_current_time)
    res = flat.read(q, per_node_s=2.0, step_s=0.1)
    assert res is not None and res.get("data") is None   # "verified" lie

    # the shard-aware ladder: kill the first ladder rung of the owning
    # shard (drops client messages, the sim twin of a partitioned node);
    # the read fails over to ANOTHER shard-1 node
    driver = fab.read_driver()
    view_nodes = driver.shard_resolver(q)
    assert set(view_nodes) == set(fab.shards[1].names)
    from plenum_tpu.reads.client import ladder_order
    first = ladder_order([n for n in view_nodes], q)[0]
    fab.shards[1].nodes[first].handle_client_message = \
        lambda *a, **kw: None
    res = driver.read(q, per_node_s=1.0, step_s=0.1)
    s = driver.stats.summary()
    assert res is not None and res["data"]["verkey"] == \
        users[1].verkey_b58
    assert s["failovers"] >= 1 and s["fallbacks"] == 0
    # every message went to the owning shard (1 timeout rung + 1 answer)
    assert s["msgs_sent"] <= len(view_nodes)


def test_unreachable_owning_shard_fails_closed():
    """A client that can only dial its HOME shard, asked for a key a
    FOREIGN shard owns: the empty shard ladder must fail closed — never
    escalate to a home-shard broadcast whose f+1 nodes would happily
    agree on absence against the wrong root."""
    import asyncio

    from plenum_tpu.reads import SimReadDriver
    from plenum_tpu.reads.client import VerifyingReadClient

    q = Request("r", 1, {"type": GET_NYM, "dest": "ForeignDid"})
    resolver = lambda req: ["S1N1", "S1N2", "S1N3", "S1N4"]

    client = VerifyingReadClient({"S0N1": ("h", 1), "S0N2": ("h", 2)}, 0,
                                 {}, shard_resolver=resolver)
    with pytest.raises(TimeoutError):
        asyncio.run(client.submit_read(q, per_node_timeout=0.01))
    assert client.stats.fallbacks == 1 and client.stats.msgs_sent == 0

    driver = SimReadDriver(
        lambda n, r: pytest.fail("submitted to a foreign shard"),
        lambda n: [], lambda s: None, ["S0N1", "S0N2"], {},
        shard_resolver=resolver)
    assert driver.read(q, per_node_s=0.01) is None
    s = driver.stats.summary()
    assert s["fallbacks"] == 1 and s["msgs_sent"] == 0


# --- elastic resharding (shards/reshard.py) ---------------------------------

def _seed_shard0(fab, n=5, tag=b"el"):
    """Order n writes owned by shard 0 (some land in the upper half of
    its range — the slice a midpoint split moves)."""
    users = []
    rid = 0
    for k in range(n):
        u = user_on_shard(fab, 0, tag, start=k * 13)
        rid += 1
        users.append(u)
        assert fab.submit_write(signed_write(fab, u, rid)) == 0
    fab.run(10.0)
    assert fab.shards[0].domain_sizes() == {n + 1}
    return users


def test_live_split_migrates_range_under_traffic():
    fab = make_fabric()
    users = _seed_shard0(fab)
    m = fab.reshard.split(0)
    assert sorted(fab.shards) == [0, 1, 2] and m.phase == "copying"
    # traffic DURING the migration keeps routing through the live map
    during = [user_on_shard(fab, 0, b"mid", start=k * 29) for k in range(3)]
    for i, u in enumerate(during):
        fab.submit_write(signed_write(fab, u, 100 + i))
    for _ in range(120):
        fab.run(0.5)
        if m.phase == "done":
            break
    assert m.phase == "done", m.to_dict()
    assert fab.mapping.epoch == 1                 # the ledger transaction
    assert fab.shards[2].ordered_count() >= 1     # the range moved
    # EVERY write (pre-split, mid-split) verifies at its current owner
    driver = fab.read_driver()
    for i, u in enumerate(users + during):
        q = Request("r", 500 + i, {"type": GET_NYM, "dest": u.identifier})
        res = driver.read(q, per_node_s=2.0, step_s=0.1)
        assert res is not None and \
            res["data"]["verkey"] == u.verkey_b58, \
            (u.identifier, driver.stats.summary())
    s = driver.stats.summary()
    assert s["fallbacks"] == 0 and s["map_proof_failures"] == 0
    # no duplicate: each moved DID ordered EXACTLY once at the target
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    from plenum_tpu.execution import txn as txn_lib
    node = next(iter(fab.shards[2].nodes.values()))
    ledger = node.c.db.get_ledger(DOMAIN_LEDGER_ID)
    dests = [txn_lib.txn_data(ledger.get_by_seq_no(i)).get("dest")
             for i in range(2, ledger.size + 1)]
    assert len(dests) == len(set(dests)), f"duplicated writes: {dests}"


def test_live_merge_retires_source():
    fab = make_fabric()
    u0 = user_on_shard(fab, 0, b"mg0")
    u1 = user_on_shard(fab, 1, b"mg1")
    for rid, u in ((1, u0), (2, u1)):
        fab.submit_write(signed_write(fab, u, rid))
    fab.run(10.0)
    m = fab.reshard.merge(1, 0)
    for _ in range(120):
        fab.run(0.5)
        if m.phase == "done":
            break
    assert m.phase == "done", m.to_dict()
    assert fab.mapping.epoch == 1
    assert sorted(fab.shards) == [0] and 1 in fab.retired
    # the merged-away shard's data verifies from the surviving shard
    driver = fab.read_driver()
    for i, u in enumerate((u0, u1)):
        q = Request("r", 600 + i, {"type": GET_NYM, "dest": u.identifier})
        res = driver.read(q, per_node_s=2.0, step_s=0.1)
        assert res is not None and res["data"]["verkey"] == u.verkey_b58
    # post-merge writes for the moved range route to the survivor
    u2 = user_on_shard(fab, 0, b"mg2", start=50)
    assert fab.submit_write(signed_write(fab, u2, 3)) == 0
    # the aggregator forgot the retired nodes (gone, not 0.0-health)
    assert not any(n.startswith("S1N") for n in fab.aggregator.latest)


def test_stale_route_forwarded_in_window_then_nacked():
    """The dual-ownership handoff contract: a write landing at the OLD
    owner after the ratchet is forwarded (ordered exactly once at the
    new owner) inside the window, and NACKed fail-closed after it."""
    fab = make_fabric()
    _seed_shard0(fab, n=3)
    m = fab.reshard.split(0)
    while m.phase == "copying":
        fab.run(0.5)
    assert m.phase == "handoff"
    stale_sink = fab.router.sinks[0]          # a stale router's decision
    mover = user_on_shard(fab, 2, b"race")    # key the new map gives to 2
    req = signed_write(fab, mover, 300)
    before = fab.shards[2].ordered_count()
    stale_sink(req, "stale-client")
    for _ in range(40):
        fab.run(0.5)
        if fab.shards[2].ordered_count() > before:
            break
    assert fab.shards[2].ordered_count() == before + 1, \
        "forwarded write not ordered at the new owner"
    assert m.forwarded == 1 and not fab.stale_nacks
    # drain the window; past it the same stale route fails closed
    for _ in range(240):
        fab.run(0.5)
        done = fab.reshard.history and \
            fab.timer.get_current_time() > (m.drain_until or 1e18)
        if done:
            break
    late = signed_write(fab, user_on_shard(fab, 2, b"race", start=40), 301)
    count2 = fab.shards[2].ordered_count()
    count0 = fab.shards[0].ordered_count()
    stale_sink(late, "stale-client")
    fab.run(5.0)
    assert fab.stale_nacks, "late stale write was not NACKed"
    assert fab.shards[2].ordered_count() == count2
    assert fab.shards[0].ordered_count() == count0, \
        "late stale write ordered at the OLD owner (double ownership)"


def test_read_ladder_refreshes_on_reshard():
    """Satellite: a client whose map view predates the reshard must not
    error — the ladder refreshes the view and retries once against the
    new owner."""
    fab = make_fabric()
    users = _seed_shard0(fab)
    driver = fab.read_driver()                # view at epoch 0
    m = fab.reshard.split(0)
    for _ in range(120):
        fab.run(0.5)
        if m.phase == "done":
            break
    assert m.phase == "done"
    moved = next(u for u in users
                 if fab.router.shard_of(
                     Request("p", 1, {"type": GET_NYM,
                                      "dest": u.identifier})) == 2)
    q = Request("r", 700, {"type": GET_NYM, "dest": moved.identifier})
    res = driver.read(q, per_node_s=1.0, step_s=0.1)
    s = driver.stats.summary()
    assert res is not None and res["data"]["verkey"] == moved.verkey_b58, s
    assert s["map_retries"] == 1 and s["fallbacks"] == 0, s


def test_maybe_split_consumes_imbalance_signal():
    """The PR 11 aggregator's hot-shard flag is the split trigger."""
    fab = make_fabric()
    # synthetic skewed telemetry: shard 0 orders 50x shard 1's rate
    for i in range(30):
        t = float(i)
        for name, sid, rate in (("S0N1", 0, 50), ("S1N1", 1, 1)):
            fab.aggregator.ingest({
                "v": 1, "node": name, "seq": i, "t": t,
                "tags": {"shard": sid}, "counters": {}, "sampled": {},
                "state": {"node": {"ordered_total": i * rate}}})
    index, hot = fab.aggregator.load_imbalance()
    assert hot == 0 and index >= fab.config.SHARD_IMBALANCE_THRESHOLD
    m = fab.reshard.maybe_split()
    assert m is not None and m.source == 0
    assert fab.reshard.maybe_split() is None    # one migration at a time


def test_front_door_fast_nacks_dead_shard():
    """Satellite: a write whose owning shard scores 0.0 health (every
    member silent past the staleness bound) is refused immediately with
    a retryable LoadShed instead of timing out against a dead pool."""
    from plenum_tpu.common.node_messages import LoadShed

    fab = make_fabric()
    entry = fab.shards[0].names[0]
    ing = fab.ingress_plane(entry, tick=False)
    # shard 1 went dark: its members' last snapshots are far behind the
    # fleet clock the (live) shard-0 members keep advancing
    for name in fab.shards[1].names:
        fab.aggregator.ingest({"v": 1, "node": name, "seq": 0, "t": 0.0,
                               "tags": {"shard": 1}, "counters": {},
                               "sampled": {}, "state": {}})
    for i, name in enumerate(fab.shards[0].names):
        fab.aggregator.ingest({"v": 1, "node": name, "seq": 9, "t": 100.0,
                               "tags": {"shard": 0}, "counters": {},
                               "sampled": {}, "state": {}})
    assert fab.aggregator.shard_health()[1] == 0.0
    u = user_on_shard(fab, 1, b"dead")
    ing.submit(signed_write(fab, u, 1).to_dict(), "cli-x")
    for _ in range(30):
        ing.service()
        fab.run(0.2)
        sheds = [msg for msg, _ in fab.shards[0].client_msgs[entry]
                 if isinstance(msg, LoadShed)]
        if sheds:
            break
    assert sheds and "unavailable" in sheds[0].reason
    assert sheds[0].retry_after > 0          # the RETRYABLE hint
    assert fab.ingress_router.stats["fast_nacked"] == 1
    assert fab.shards[1].ordered_count() == 0


def test_directory_signer_rotation_stales_old_committee():
    """Satellite: rotating one directory signer re-signs the map root;
    proofs minted under the old committee fail closed against the
    rotated trust root."""
    from plenum_tpu.crypto.bls import BlsCryptoSigner

    ml = make_map(2)
    key = routing_key({"dest": "RotDid"})
    old_proof = ml.ownership_proof(key)
    old_keys = dict(ml.directory_keys)
    new_signer = BlsCryptoSigner(seed=b"rotated-dir-1".ljust(32, b"\0"))
    ml.rotate_signer("Dir1", new_signer)
    new_keys = ml.directory_keys
    assert new_keys != old_keys
    # freshly minted proof verifies against the NEW trust root
    fresh = ml.ownership_proof(key)
    assert verify_ownership(key, fresh, new_keys, now=NOW)[1] == "ok"
    # the OLD committee's proof fails closed against the new root
    assert verify_ownership(key, old_proof, new_keys, now=NOW)[1] \
        == "bad_map_multi_sig"
    # and the new proof fails against a verifier still on the old root
    assert verify_ownership(key, fresh, old_keys, now=NOW)[1] \
        == "bad_map_multi_sig"
    with pytest.raises(KeyError):
        ml.rotate_signer("NotADir", new_signer)


# --- observability ----------------------------------------------------------

def _folds_from(collector):
    out = {}
    for name, a in collector.accumulators.items():
        f = {"count": a.count, "sum": a.total, "min": a.min, "max": a.max,
             "mean": a.total / a.count if a.count else None,
             "last": a.total / a.count if a.count else None, "flushes": 1}
        if a.samples:
            f["samples"] = list(a.samples)
        out[name] = f
    return out


def test_metrics_report_shards_section():
    from plenum_tpu.tools.metrics_report import derive_summary

    fab, users = _fabric_with_data()
    driver = fab.read_driver()
    q = Request("r", 15, {"type": GET_NYM, "dest": users[1].identifier})
    assert driver.read(q, per_node_s=2.0, step_s=0.1) is not None
    fab.ordered_counts()
    summary = derive_summary(_folds_from(fab.metrics), span_s=10.0)
    sh = summary["shards"]
    assert sh["routed"] == 2 and sh["unroutable"] == 0
    assert sh["cross_shard_reads"] == 1 and sh["cross_shard_reads_ok"] == 1
    assert sh["map_proof_failures"] == 0
    assert sh["ordered_per_shard_mean"] == 1.0
    assert sh["cross_verify_ms_p50"] is not None


def test_trace_report_attributes_shards():
    from plenum_tpu.tools.trace_report import assemble, summarize

    fab = make_fabric(tracing=True)
    users = {sid: user_on_shard(fab, sid, b"tr") for sid in fab.shards}
    for req_id, (sid, u) in enumerate(sorted(users.items()), start=1):
        fab.submit_write(signed_write(fab, u, req_id))
    fab.run(8.0)
    driver = fab.read_driver()
    q = Request("r", 16, {"type": GET_NYM, "dest": users[1].identifier})
    assert driver.read(q, per_node_s=2.0, step_s=0.1) is not None
    report = assemble(fab.tracer_snapshots())
    sh = report["shards"]
    assert set(sh["nodes_by_shard"]) == {"0", "1"}
    assert sorted(sh["nodes_by_shard"]["0"]) == fab.shards[0].names
    assert sh["route_decisions"] == 2
    assert sh["routes_per_shard"] == {"0": 1, "1": 1}
    assert sh["cross_shard_reads"] == 1 and sh["cross_shard_ok"] == 1
    assert "cross_shard" in report["attribution"]
    assert summarize(report)["shards"] == sh
