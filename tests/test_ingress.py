"""Ingress plane: admission control, fair queueing, batched auth, and
observer read fan-out (docs/ingress.md).

The smoke test at the top is the CI acceptance shape: construct the
whole plane on a 4-node sim pool and round-trip one admitted write and
one observer-verified read. The rest pins each mechanism: per-client
caps, watermark hysteresis + explicit LoadShed replies, weighted-fair
dequeue, one-dispatch auth batching through the ReqAuthenticator seam,
the AIMD admission controller, verification-gated observer anchors, and
the anchor-lag escalation.
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID, BatchCommitted,
                                             LoadShed, Reply, RequestNack)
from plenum_tpu.common.request import Request
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.execution.txn import GET_NYM
from plenum_tpu.ingress import (SHED_CLIENT_CAP, SHED_OVERLOAD,
                                IngressController, IngressPlane, SimObserver)

from test_pool import Pool, signed_nym

FAST = Config(Max3PCBatchWait=0.05, STATE_FRESHNESS_UPDATE_INTERVAL=600.0)


def attach_ingress(pool, names=None, config=None):
    """One IngressPlane per node, ticking on the pool's MockTimer."""
    return {n: IngressPlane(pool.nodes[n], config=config)
            for n in (names or pool.names)}


def attach_observer(pool, name="obs1", anchor_lag_max=None, f=1):
    """In-process observer registered with every validator. Attach
    BEFORE ordering traffic: pushes only cover live batches."""
    from plenum_tpu.tools.local_pool import pool_bls_keys
    obs = SimObserver(name, pool.genesis, pool.names,
                      pool_bls_keys(pool.names),
                      now=pool.timer.get_current_time, f=f,
                      anchor_lag_max=anchor_lag_max)
    obs.register(lambda v, msg: pool.nodes[v].handle_client_message(
        msg, obs.client_id))
    pool.run(0.5)                       # registrations land
    return obs


def route_pushes(pool, observers):
    """Move BatchCommitted pushes from validator client outboxes into
    the observers (the sim twin of the TCP push connection)."""
    by_id = {o.client_id: o for o in observers}
    for v in pool.names:
        keep = []
        for m, c in pool.client_msgs[v]:
            obs = by_id.get(c)
            if obs is not None:
                if isinstance(m, BatchCommitted):
                    obs.deliver_push(m, v)
            else:
                keep.append((m, c))
        pool.client_msgs[v] = keep


def run_routed(pool, observers, seconds=1.0, step=0.1):
    elapsed = 0.0
    while elapsed < seconds:
        pool.run(step, step=step)
        route_pushes(pool, observers)
        elapsed += step


def shed_replies(pool, node_name, client=None):
    return [m for m, c in pool.client_msgs[node_name]
            if isinstance(m, LoadShed) and (client is None or c == client)]


# --- the CI smoke: whole plane, one write + one observer-verified read ---

def test_ingress_smoke_write_and_observer_read():
    from plenum_tpu.reads import SimReadDriver
    from plenum_tpu.tools.local_pool import pool_bls_keys

    pool = Pool(config=FAST)
    obs = attach_observer(pool)
    ingress = attach_ingress(pool)

    user = Ed25519Signer(seed=b"ing-smoke-user".ljust(32, b"\0"))
    req = signed_nym(pool.trustee, user, req_id=1)
    for n in pool.names:
        ingress[n].submit(req.to_dict(), "cli1")
    run_routed(pool, [obs], 6.0)

    # the write round-tripped: ordered everywhere + client REPLY
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}, sizes
    assert any(isinstance(m, Reply) for m, c in pool.client_msgs["Alpha"]
               if c == "cli1")
    assert ingress["Alpha"].stats["admitted"] == 1
    assert ingress["Alpha"].stats["auth_batches"] >= 1
    # the write NEVER touched the node's raw client inbox
    assert all(len(pool.nodes[n]._client_inbox) == 0 for n in pool.names)

    # the observer replicated the batch and serves a VERIFIED read
    assert obs.batches_applied >= 1
    assert obs.gate.stats["ms_adopted"] >= 1

    def submit(name, q):
        if name == obs.name:
            obs.handle_client_message(q.to_dict(), "rdr")
        else:
            pool.nodes[name].handle_client_message(q.to_dict(), "rdr")

    def collect(name):
        if name == obs.name:
            out = [m.result for m, _ in obs.sent if isinstance(m, Reply)]
            obs.sent.clear()
            return out
        out = [m.result for m, c in pool.client_msgs[name]
               if isinstance(m, Reply) and c == "rdr"]
        pool.client_msgs[name] = [
            (m, c) for m, c in pool.client_msgs[name]
            if not (isinstance(m, Reply) and c == "rdr")]
        return out

    driver = SimReadDriver(submit, collect, pool.run, pool.names,
                           pool_bls_keys(pool.names), freshness_s=1e12,
                           now=pool.timer.get_current_time,
                           observer_names=[obs.name])
    q = Request("rdr", 10, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q)
    assert res is not None and res["data"]["verkey"] == user.verkey_b58
    s = driver.stats
    assert s.observer_ok == 1 and s.single_reply_ok == 1
    assert s.failovers == 0 and s.fallbacks == 0
    # fanout 2 and the pool was never touched by the read
    assert s.msgs_sent == 1 and s.replies_seen == 1


# --- admission control ----------------------------------------------------

def test_per_client_cap_sheds_hot_client_only():
    pool = Pool(config=FAST)
    cfg = FAST.replace(INGRESS_CLIENT_QUEUE_CAP=4, INGRESS_CONTROLLER=False)
    # tick=False: the queue must be observable BEFORE a service drains it
    ing = IngressPlane(pool.nodes["Alpha"], config=cfg, tick=False)

    hot_reqs = [signed_nym(pool.trustee,
                           Ed25519Signer(seed=(b"hot%d" % i).ljust(32, b"\0")),
                           req_id=100 + i) for i in range(10)]
    for r in hot_reqs:
        ing.submit(r.to_dict(), "hot")
    steady = signed_nym(pool.trustee,
                        Ed25519Signer(seed=b"steady".ljust(32, b"\0")), 200)
    ing.submit(steady.to_dict(), "steady")

    assert ing.stats["shed_client_cap"] == 6       # 10 - cap(4)
    assert ing.stats["admitted"] == 5              # 4 hot + 1 steady
    sheds = shed_replies(pool, "Alpha", "hot")
    assert len(sheds) == 6
    assert all(m.reason == SHED_CLIENT_CAP for m in sheds)
    assert not shed_replies(pool, "Alpha", "steady")


def test_global_watermark_hysteresis_and_recovery():
    pool = Pool(config=FAST)
    cfg = FAST.replace(INGRESS_HIGH_WATERMARK=8, INGRESS_LOW_WATERMARK=2,
                       INGRESS_CLIENT_QUEUE_CAP=4, INGRESS_ADMIT_MAX=4,
                       INGRESS_ADMIT_MIN=4, INGRESS_CONTROLLER=False)
    ing = IngressPlane(pool.nodes["Alpha"], config=cfg, tick=False)
    reqs = [signed_nym(pool.trustee,
                       Ed25519Signer(seed=(b"wm%02d" % i).ljust(32, b"\0")),
                       300 + i) for i in range(20)]
    # 20 distinct clients, 1 req each: per-client caps never bind, the
    # GLOBAL watermark does — admit 8, shed the rest, latch engaged
    for i, r in enumerate(reqs[:12]):
        ing.submit(r.to_dict(), f"c{i}")
    assert ing.queue_depth == 8
    assert ing.stats["shed_overload"] == 4
    assert all(m.reason == SHED_OVERLOAD
               for m in shed_replies(pool, "Alpha"))
    # latched: still shedding even though depth < high watermark
    ing.service()                       # drains 4 -> depth 4 > low mark
    ing.submit(reqs[12].to_dict(), "c12")
    assert ing.stats["shed_overload"] == 5
    # drain below the low mark -> latch clears, admission resumes
    ing.service()
    assert ing.queue_depth <= 2
    ing.submit(reqs[13].to_dict(), "c13")
    assert ing.stats["shed_overload"] == 5
    assert ing.queue_depth >= 1
    pool.run(2.0)


def test_fair_dequeue_splits_budget_across_clients():
    pool = Pool(config=FAST)
    cfg = FAST.replace(INGRESS_CLIENT_QUEUE_CAP=32, INGRESS_ADMIT_MAX=6,
                       INGRESS_ADMIT_MIN=6, INGRESS_CONTROLLER=False,
                       INGRESS_HIGH_WATERMARK=1000)
    node = pool.nodes["Alpha"]
    ing = IngressPlane(node, config=cfg, tick=False)
    admitted = []
    node.submit_preverified = lambda req, frm: admitted.append(frm)

    # hog floods 20, two mice bring 2 each; a 6-budget drain must take
    # from EVERY active client, not FIFO-reward the hog
    for i in range(20):
        ing.submit(signed_nym(pool.trustee, Ed25519Signer(
            seed=(b"hog%02d" % i).ljust(32, b"\0")), 400 + i).to_dict(),
            "hog")
    for c in ("mouse1", "mouse2"):
        for i in range(2):
            ing.submit(signed_nym(pool.trustee, Ed25519Signer(
                seed=(c.encode() + b"%d" % i).ljust(32, b"\0")),
                500 + i).to_dict(), c)
    ing.service()
    assert len(admitted) == 6
    assert admitted.count("mouse1") == 2 and admitted.count("mouse2") == 2
    assert admitted.count("hog") == 2    # fair share, not the whole budget

    # weights: a weight-3 client gets 3 slots per rotation pass
    ing.set_weight("hog", 3)
    admitted.clear()
    ing.service()
    assert admitted.count("hog") >= 3


def test_bad_signature_flood_dies_at_ingress():
    from plenum_tpu.client.sim_clients import burst_writes
    pool = Pool(config=FAST)
    ing = IngressPlane(pool.nodes["Alpha"], config=FAST, tick=False)
    burst = burst_writes(pool.trustee, n_clients=5, per_client=3,
                         bad_sigs=True)
    for client, req in burst:
        ing.submit(req.to_dict(), client)
    ing.service()
    assert ing.stats["auth_fail"] == 15
    nacks = [m for m, _ in pool.client_msgs["Alpha"]
             if isinstance(m, RequestNack)]
    assert len(nacks) == 15
    assert all("signature" in m.reason for m in nacks)
    pool.run(2.0)
    # nothing reached the pool: no propagates, nothing ordered
    assert pool.nodes["Alpha"].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 1
    assert len(pool.nodes["Alpha"]._client_inbox) == 0


def test_auth_batch_amortizes_one_dispatch_per_tick():
    """Many clients' writes admitted in one tick ride ONE submit_batch
    dispatch — the measured auth batch size the bench line publishes."""
    pool = Pool(config=FAST)
    cfg = FAST.replace(INGRESS_ADMIT_MAX=64, INGRESS_ADMIT_MIN=64,
                       INGRESS_CONTROLLER=False)
    ing = IngressPlane(pool.nodes["Alpha"], config=cfg, tick=False)
    for i in range(24):
        ing.submit(signed_nym(pool.trustee, Ed25519Signer(
            seed=(b"amort%02d" % i).ljust(32, b"\0")), 600 + i).to_dict(),
            f"c{i}")
    ing.service()
    assert ing.stats["auth_batches"] == 1
    assert ing.stats["auth_items"] == 24
    assert ing.summary()["auth_batch_mean"] == 24.0


def test_duplicate_digest_settles_both_copies_one_verify():
    pool = Pool(config=FAST)
    ing = IngressPlane(pool.nodes["Alpha"], config=FAST, tick=False)
    node = pool.nodes["Alpha"]
    settled = []
    node.submit_preverified = lambda req, frm: settled.append(frm)
    req = signed_nym(pool.trustee,
                     Ed25519Signer(seed=b"dup-user".ljust(32, b"\0")), 700)
    ing.submit(req.to_dict(), "a")
    ing.submit(req.to_dict(), "b")
    ing.service()
    assert ing.stats["auth_items"] == 1          # ONE device verify
    assert sorted(settled) == ["a", "b"]         # both copies settled


# --- the admission controller --------------------------------------------

def test_ingress_controller_aimd_policy():
    timer = MockTimer()
    cfg = Config(INGRESS_ADMIT_MIN=16, INGRESS_ADMIT_MAX=256,
                 INGRESS_HIGH_WATERMARK=1024, INGRESS_LOW_WATERMARK=64,
                 INGRESS_SLO_P95=0.1, INGRESS_CONTROL_INTERVAL=1.0)
    ctl = IngressController(cfg, timer)
    start_admit = ctl.admit_max

    def interval(wait):
        for _ in range(20):
            ctl.note_admitted(wait)
        timer.advance(1.1)
        ctl.note_admitted(wait)

    # over SLO with drain headroom: admit budget grows first
    interval(0.5)
    assert ctl.last_decision["verdict"] == "grow:drain"
    assert ctl.admit_max == start_admit * 2
    # keep violating until the budget caps, then the watermark shrinks
    guard = 0
    while ctl.admit_max < cfg.INGRESS_ADMIT_MAX and guard < 10:
        interval(0.5)
        guard += 1
    interval(0.5)
    assert ctl.last_decision["verdict"] == "shrink:watermark"
    assert ctl.shed_watermark < cfg.INGRESS_HIGH_WATERMARK
    shrunk = ctl.shed_watermark
    # floor: repeated violation can never shed everything
    for _ in range(30):
        interval(0.5)
    assert ctl.shed_watermark >= cfg.INGRESS_HIGH_WATERMARK // 8
    # headroom: watermark recovers additively, budget decays
    interval(0.01)
    assert ctl.last_decision["verdict"] == "recover:headroom"
    assert ctl.shed_watermark > ctl._watermark_floor or \
        ctl.shed_watermark > shrunk - 1
    guard = 0
    while (ctl.shed_watermark < cfg.INGRESS_HIGH_WATERMARK
           or ctl.admit_max > start_admit) and guard < 200:
        interval(0.01)
        guard += 1
    assert ctl.shed_watermark == cfg.INGRESS_HIGH_WATERMARK
    assert ctl.admit_max == start_admit
    # no samples -> no decision (idle front door holds the knobs)
    before = ctl.decisions
    timer.advance(5.0)
    ctl.tick()
    assert ctl.decisions == before


def test_controller_steers_live_plane_under_flood():
    """Queue waits over the SLO must move the live plane's effective
    watermark/budget (decisions ride sample arrivals on the MockTimer)."""
    pool = Pool(config=FAST)
    cfg = FAST.replace(INGRESS_SLO_P95=0.05, INGRESS_CONTROL_INTERVAL=0.2,
                       INGRESS_ADMIT_MAX=8, INGRESS_ADMIT_MIN=2,
                       INGRESS_CLIENT_QUEUE_CAP=64,
                       INGRESS_HIGH_WATERMARK=4096,
                       INGRESS_TICK_INTERVAL=0.5)
    ing = IngressPlane(pool.nodes["Alpha"], config=cfg)
    for i in range(64):
        ing.submit(signed_nym(pool.trustee, Ed25519Signer(
            seed=(b"ctl%03d" % i).ljust(32, b"\0")), 800 + i).to_dict(),
            f"c{i % 8}")
    pool.run(5.0)
    assert ing.controller is not None
    assert ing.controller.decisions >= 1
    # a 0.5s tick draining 8/turn over 64 queued FAR exceeds the 50ms
    # SLO: the budget must have grown off its default
    assert ing.controller.admit_max > 2


# --- wire + tracing + report ----------------------------------------------

def test_loadshed_wire_roundtrip():
    from plenum_tpu.common.message_base import message_from_dict
    from plenum_tpu.common.serialization import pack, unpack
    m = LoadShed(identifier="cli", req_id=7, reason=SHED_OVERLOAD,
                 retry_after=0.5)
    got = message_from_dict(unpack(pack(m.to_dict())))
    assert got == m
    with pytest.raises(Exception):
        LoadShed.from_dict({"op": "LOAD_SHED", "identifier": "x",
                            "req_id": 1, "reason": "r",
                            "retry_after": -1.0})


def test_ingress_spans_reach_tracer_and_waterfall():
    from plenum_tpu.common import tracing
    from plenum_tpu.tools.trace_report import assemble

    pool = Pool(config=FAST)
    cfg = FAST.replace(INGRESS_CLIENT_QUEUE_CAP=1, INGRESS_CONTROLLER=False)
    ingress = attach_ingress(pool, config=cfg)
    user = Ed25519Signer(seed=b"span-user".ljust(32, b"\0"))
    req = signed_nym(pool.trustee, user, req_id=1)
    shed_me = signed_nym(pool.trustee, Ed25519Signer(
        seed=b"span-shed".ljust(32, b"\0")), 2)
    for n in pool.names:
        ingress[n].submit(req.to_dict(), "cli1")
        ingress[n].submit(shed_me.to_dict(), "cli1")   # over the cap: shed
    pool.run(6.0)

    ring = list(pool.nodes["Alpha"].tracer.ring)
    stages = {e[1] for e in ring}
    assert {tracing.ING_ADMIT, tracing.ING_SHED, tracing.ING_AUTH,
            tracing.ING_VERDICT} <= stages
    shed = [e for e in ring if e[1] == tracing.ING_SHED]
    assert shed[0][2] == shed_me.digest
    assert shed[0][3]["reason"] == SHED_CLIENT_CAP

    # the assembled waterfall attributes the front door as a stage
    report = assemble([pool.nodes[n].tracer.snapshot() for n in pool.names])
    wf = report["requests"][req.digest]["Alpha"]
    assert "front_door" in wf["stages"]
    assert "front_door" in report["attribution"]


def test_metrics_report_ingress_section():
    from plenum_tpu.common.metrics import KvMetricsCollector
    from plenum_tpu.storage.kv_memory import KvMemory
    from plenum_tpu.tools.metrics_report import derive_summary, fold_rows

    pool = Pool(config=FAST)
    node = pool.nodes["Alpha"]
    kv = KvMemory()
    collector = KvMetricsCollector(kv, now=pool.timer.get_current_time)
    cfg = FAST.replace(INGRESS_CLIENT_QUEUE_CAP=2, INGRESS_CONTROLLER=True,
                       INGRESS_CONTROL_INTERVAL=0.1)
    ing = IngressPlane(node, config=cfg, metrics=collector, tick=False)
    for i in range(6):
        ing.submit(signed_nym(pool.trustee, Ed25519Signer(
            seed=(b"mr%02d" % i).ljust(32, b"\0")), 900 + i).to_dict(),
            f"c{i % 2}")                 # 2 clients, cap 2 -> sheds
    pool.timer.advance(0.2)
    ing.service()
    collector.flush()
    folds = fold_rows(collector.read_rows())
    summary = derive_summary(folds, span_s=10.0)
    ing_section = summary["ingress"]
    assert ing_section["admitted"] == 4
    assert ing_section["shed"] == 2
    assert ing_section["auth_batches"] == 1
    assert ing_section["auth_batch_mean"] == 4.0
    assert "queue_wait_ms_p95" in ing_section
    assert "controller" in ing_section


# --- observer read fan-out ------------------------------------------------

def test_observer_rejects_forged_multi_sig_anchor():
    """A Byzantine pusher can stall an observer's anchor but never move
    it: a tampered multi-sig fails MultiSignature.verify and is never
    adopted, so reads stay proofless instead of lying."""
    pool = Pool(config=FAST)
    obs = attach_observer(pool, f=1)
    user = Ed25519Signer(seed=b"forge-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(6.0)

    pushes = [(m, v) for v in pool.names for m, c in pool.client_msgs[v]
              if c == obs.client_id and isinstance(m, BatchCommitted)]
    assert len(pushes) >= 2
    import dataclasses
    for m, v in pushes:
        if m.multi_sig:
            forged = list(m.multi_sig)
            forged[1] = list(forged[1])[:-1]     # drop a participant
            m = dataclasses.replace(m, multi_sig=tuple(forged))
        obs.deliver_push(m, v)
    assert obs.batches_applied >= 1              # quorum still applies
    assert obs.gate.stats["ms_adopted"] == 0
    assert obs.gate.stats["ms_rejected"] >= 1
    # served read carries NO proof (never a forged anchor)
    q = Request("rdr", 5, {"type": GET_NYM, "dest": user.identifier})
    out = obs.gate.answer_batch([q])[0]
    from plenum_tpu.reads import READ_PROOF
    assert isinstance(out, dict) and READ_PROOF not in out


def test_observer_push_quorum_tolerates_multi_sig_variation():
    """Honest validators attach DIFFERENT (all-valid) aggregations to the
    same batch; the f+1 content quorum must still converge."""
    pool = Pool(config=FAST)
    obs = attach_observer(pool, f=1)
    user = Ed25519Signer(seed=b"msvar-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(6.0)
    pushes = [(m, v) for v in pool.names for m, c in pool.client_msgs[v]
              if c == obs.client_id and isinstance(m, BatchCommitted)]
    assert len(pushes) >= 2
    import dataclasses
    delivered = 0
    for i, (m, v) in enumerate(pushes[:2]):
        if m.multi_sig:
            # rotate the participant list: same sig, different list ORDER
            # (a legitimately different aggregation shape)
            ms = list(m.multi_sig)
            ms[1] = list(ms[1])[i:] + list(ms[1])[:i]
            m = dataclasses.replace(m, multi_sig=tuple(ms))
        delivered += 1
        obs.deliver_push(m, v)
    assert delivered == 2
    assert obs.batches_applied == 1              # 2 votes = f+1 quorum


def test_observer_anchor_lag_escalates_to_validator():
    """An observer whose anchor aged past the lag bound serves PROOFLESS;
    the two-tier driver escalates to a validator and the read still
    verifies — stale proofs are never served."""
    from plenum_tpu.reads import READ_PROOF, SimReadDriver
    from plenum_tpu.tools.local_pool import pool_bls_keys

    pool = Pool(config=FAST)
    obs = attach_observer(pool, anchor_lag_max=5.0)
    user = Ed25519Signer(seed=b"lag-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    run_routed(pool, [obs], 6.0)
    assert obs.gate.stats["ms_adopted"] >= 1

    # age the anchor past the bound with NO new pushes
    pool.timer.advance(60.0)

    def submit(name, q):
        if name == obs.name:
            obs.handle_client_message(q.to_dict(), "rdr")
        else:
            pool.nodes[name].handle_client_message(q.to_dict(), "rdr")

    def collect(name):
        if name == obs.name:
            out = [m.result for m, _ in obs.sent if isinstance(m, Reply)]
            obs.sent.clear()
            return out
        out = [m.result for m, c in pool.client_msgs[name]
               if isinstance(m, Reply) and c == "rdr"]
        pool.client_msgs[name] = [
            (m, c) for m, c in pool.client_msgs[name]
            if not (isinstance(m, Reply) and c == "rdr")]
        return out

    driver = SimReadDriver(submit, collect, pool.run, pool.names,
                           pool_bls_keys(pool.names), freshness_s=1e12,
                           now=pool.timer.get_current_time,
                           observer_names=[obs.name])
    q = Request("rdr", 9, {"type": GET_NYM, "dest": user.identifier})
    res = driver.read(q)
    assert res is not None and res["data"]["verkey"] == user.verkey_b58
    assert READ_PROOF in res                     # proven BY THE VALIDATOR
    s = driver.stats
    assert s.observer_escalations == 1 and s.observer_ok == 0
    assert s.failovers == 1 and s.fallbacks == 0
    assert obs.gate.stats["stale_suppressed"] == 1


# --- the full 10k bench config, shrunk (slow) -----------------------------

@pytest.mark.slow
def test_bench_config7_ingress_end_to_end():
    """The acceptance bench config end to end at reduced scale: batched
    auth measured >> 1, observer-served verified reads, and the overload
    A/B (bounded+shedding vs unbounded inbox)."""
    from plenum_tpu.tools.bench_configs import config7_ingress_10k
    out = config7_ingress_10k(n_clients=10_000, n_ops=300,
                              burst_clients=40, burst_per_client=6,
                              timeout=120.0)
    assert "error" not in out, out
    assert out["reads_served"] > 0
    assert out["observer_served"] == out["reads_served"]
    assert out["writes_ordered"] == out["writes_submitted"]
    assert out["auth_batch_mean"] is not None
    ab = out["overload_ab"]
    assert ab["ingress"]["bounded"]
    assert ab["ingress"]["shed"] > 0
    assert ab["no_ingress"]["inbox_depth_after_burst"] == ab["no_ingress"]["burst"]
    assert ab["ingress"]["queue_depth_peak"] <= ab["ingress"]["watermark"]
