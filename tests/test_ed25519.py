"""Ed25519 kernel + provider tests: field/point unit checks, RFC 8032 vectors,
random signatures from the C library, adversarial inputs."""
import hashlib
import os
import random

import numpy as np
import pytest

import jax.numpy as jnp

from plenum_tpu.ops import ed25519 as ops
from plenum_tpu.crypto.ed25519 import (Ed25519Signer, CpuEd25519Verifier,
                                       JaxEd25519Verifier, make_verifier)
from plenum_tpu.utils.base58 import b58encode, b58decode


# --- field arithmetic vs python ints --------------------------------------

def _rand_fe(rng):
    return rng.randrange(ops.P)


def test_limb_roundtrip():
    rng = random.Random(0)
    for _ in range(20):
        x = _rand_fe(rng)
        assert ops.limbs_to_int(ops.int_to_limbs(x)) == x


@pytest.mark.parametrize("op,pyop", [
    ("add", lambda a, b: (a + b) % ops.P),
    ("sub", lambda a, b: (a - b) % ops.P),
    ("mul", lambda a, b: (a * b) % ops.P),
])
def test_field_ops_match_bigint(op, pyop):
    rng = random.Random(1)
    fn = {"add": ops.f_add, "sub": ops.f_sub, "mul": ops.f_mul}[op]
    xs = [_rand_fe(rng) for _ in range(8)] + [0, 1, ops.P - 1, ops.P - 19]
    ys = [_rand_fe(rng) for _ in range(8)] + [ops.P - 1, 0, ops.P - 1, 19]
    a = jnp.asarray(np.stack([ops.int_to_limbs(x) for x in xs]))
    b = jnp.asarray(np.stack([ops.int_to_limbs(y) for y in ys]))
    # outputs are in CARRIED form (congruent mod p, limbs possibly signed);
    # canonicalize before comparing against the bigint reference
    out = ops.f_canon(fn(a, b))
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert ops.limbs_to_int(np.asarray(out)[i]) == pyop(x, y), (op, i)


def test_f_canon():
    # a value deliberately left ≥ p
    x = ops.P + 12345
    l = jnp.asarray(ops.int_to_limbs(x % (1 << 260))[None, :])
    c = np.asarray(ops.f_canon(l))[0]
    assert ops.limbs_to_int(c) == 12345
    assert all(0 <= v <= ops.MASK for v in c)


# --- point ops vs python reference ----------------------------------------

def _py_edwards_add(p1, p2):
    x1, y1 = p1
    x2, y2 = p2
    den = ops.D * x1 * x2 * y1 * y2 % ops.P
    x3 = (x1 * y2 + x2 * y1) * pow(1 + den, ops.P - 2, ops.P) % ops.P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - den, ops.P - 2, ops.P) % ops.P
    return (x3, y3)


def _to_affine(pt):
    x, y, z, _ = (ops.limbs_to_int(np.asarray(c)[0]) for c in pt)
    zi = pow(z, ops.P - 2, ops.P)
    return (x * zi % ops.P, y * zi % ops.P)


def _dev_pt(affine):
    return tuple(jnp.asarray(v) for v in ops.points_to_limbs([affine]))


def test_pt_add_and_double_match_reference():
    B = (ops.BX, ops.BY)
    b_dev = _dev_pt(B)
    two_b = ops.pt_double(b_dev)
    assert _to_affine(two_b) == _py_edwards_add(B, B)
    three_b = ops.pt_add(two_b, b_dev)
    assert _to_affine(three_b) == _py_edwards_add(_py_edwards_add(B, B), B)
    # unified add used as doubling agrees with dedicated double
    assert _to_affine(ops.pt_add(b_dev, b_dev)) == _to_affine(ops.pt_double(b_dev))


def test_pt_add_identity():
    B = (ops.BX, ops.BY)
    b_dev = _dev_pt(B)
    o = _dev_pt((0, 1))
    assert _to_affine(ops.pt_add(b_dev, o)) == B
    assert _to_affine(ops.pt_add(o, b_dev)) == B


# --- RFC 8032 test vectors ------------------------------------------------

RFC8032_VECTORS = [
    # (secret_seed_hex, public_hex, message_hex, signature_hex) — §7.1
    ("9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
     "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
     "",
     "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
     "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"),
    ("4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
     "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
     "72",
     "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
     "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"),
    ("c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
     "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
     "af82",
     "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
     "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"),
]


@pytest.mark.parametrize("case", range(len(RFC8032_VECTORS)))
def test_rfc8032_vectors(case):
    seed_h, pub_h, msg_h, sig_h = RFC8032_VECTORS[case]
    msg = bytes.fromhex(msg_h)
    sig = bytes.fromhex(sig_h)
    vk = bytes.fromhex(pub_h)
    # signer reproduces the vector
    s = Ed25519Signer(bytes.fromhex(seed_h))
    assert s.verkey == vk
    assert s.sign(msg) == sig
    # both verifier backends accept
    for backend in ("cpu", "jax"):
        v = make_verifier(backend)
        assert v.verify(msg, sig, vk), backend
        assert not v.verify(msg + b"x", sig, vk), backend
        bad = bytearray(sig); bad[0] ^= 1
        assert not v.verify(msg, bytes(bad), vk), backend


# --- random batch vs C library -------------------------------------------

def test_jax_batch_matches_cpu_on_mixed_batch():
    rng = random.Random(3)
    signers = [Ed25519Signer(bytes([i]) * 32) for i in range(4)]
    items = []
    expect = []
    for i in range(37):
        s = signers[i % 4]
        msg = rng.randbytes(rng.randint(0, 100))
        sig = s.sign(msg)
        good = rng.random() < 0.7
        if not good:
            kind = rng.randrange(4)
            if kind == 0:
                b = bytearray(sig); b[rng.randrange(64)] ^= 0xFF; sig = bytes(b)
            elif kind == 1:
                msg = msg + b"!"
            elif kind == 2:
                sig = sig[:32] + (ops.L + 5).to_bytes(32, "little")  # S >= L
            else:
                sig = b"\xff" * 64  # garbage R
        items.append((msg, sig, s.verkey))
        expect.append(good)
    cpu = CpuEd25519Verifier().verify_batch(items)
    dev = JaxEd25519Verifier().verify_batch(items)
    assert list(cpu) == expect
    assert list(dev) == expect


def test_malformed_inputs_never_raise():
    v = JaxEd25519Verifier()
    items = [(b"m", b"short", b"\x00" * 32),
             (b"m", b"\x00" * 64, b"bad"),
             (b"m", b"\x00" * 64, b"\x00" * 32),
             (b"", b"\xff" * 64, b"\xff" * 32)]
    out = v.verify_batch(items)
    assert not out.any()
    assert CpuEd25519Verifier().verify_batch(items).any() == False


def test_verkey_cache_hits():
    s = Ed25519Signer(b"\x07" * 32)
    v = JaxEd25519Verifier()
    msgs = [b"m%d" % i for i in range(8)]
    items = [(m, s.sign(m), s.verkey) for m in msgs]
    assert v.verify_batch(items).all()
    # the compressed dispatch ships raw key bytes and decompresses on
    # device: the host-side point cache is never populated on the hot path
    assert len(v._pt_cache) == 0

    class _Limb(JaxEd25519Verifier):
        _compressed_dispatch = False

    lv = _Limb()
    assert lv.verify_batch(items).all()
    assert len(lv._pt_cache) == 1      # limb path still caches per verkey


# --- base58 ---------------------------------------------------------------

def test_base58_roundtrip():
    rng = random.Random(5)
    for _ in range(20):
        data = rng.randbytes(rng.randint(0, 40))
        assert b58decode(b58encode(data)) == data
    assert b58encode(b"\x00\x00a") .startswith("11")
    with pytest.raises(ValueError):
        b58decode("0OIl")


# --- backend-agreement regression tests (review round 3) ------------------

def test_backends_agree_on_noncanonical_encodings():
    """Non-canonical point encodings (y >= p) must be rejected by BOTH
    backends — a backend verdict split would fork the pool."""
    bad_vk = (ops.P + 1).to_bytes(32, "little")
    sig = bad_vk + (0).to_bytes(32, "little")
    for backend in ("cpu", "jax"):
        v = make_verifier(backend)
        assert not v.verify(b"msg", sig, bad_vk), backend
    # canonical-but-valid still passes both
    s = Ed25519Signer(b"\x09" * 32)
    m = b"agree"
    for backend in ("cpu", "jax"):
        assert make_verifier(backend).verify(m, s.sign(m), s.verkey), backend


def test_non_bytes_items_return_false_not_raise():
    for backend in ("cpu", "jax"):
        v = make_verifier(backend)
        out = v.verify_batch([("str-msg", "s" * 64, b"\x00" * 32),
                              (b"m", None, b"\x00" * 32)])
        assert not out.any(), backend


def test_pt_cache_bounded():
    v = JaxEd25519Verifier(cache_size=4)
    for i in range(10):
        v._decompress_cached(i.to_bytes(32, "little"))
    assert len(v._pt_cache) == 4


# --- coalescing crypto plane (co-hosted nodes, one dispatch) --------------

def test_coalescing_verifier_merges_batches():
    from plenum_tpu.crypto.ed25519 import _PLANE_VERDICTS, CoalescingVerifier
    _PLANE_VERDICTS.clear()   # flush() asserts below depend on a cold cache
    inner = JaxEd25519Verifier(min_batch=8)
    plane = CoalescingVerifier(inner)
    signers = [Ed25519Signer(bytes([i + 1]) * 32) for i in range(3)]
    batches, expects = [], []
    for k, s in enumerate(signers):   # three "nodes" stage batches
        items, expect = [], []
        for i in range(2 + k):
            m = b"node%d-msg%d" % (k, i)
            good = (i + k) % 3 != 0
            sig = s.sign(m) if good else b"\x01" * 64
            items.append((m, sig, s.verkey))
            expect.append(good)
        batches.append(plane.submit_batch(items))
        expects.append(expect)
    # nothing dispatched yet; a flush sends ONE combined dispatch
    assert plane._in_flight is None
    assert plane.flush()
    for tok, expect in zip(batches, expects):
        got = plane.collect_batch(tok, wait=True)
        assert list(got) == expect
    # collect without explicit flush also works (self-dispatching)
    tok = plane.submit_batch([(b"x", signers[0].sign(b"x"), signers[0].verkey)])
    assert list(plane.collect_batch(tok, wait=True)) == [True]


def test_coalescing_verifier_staged_while_in_flight():
    from plenum_tpu.crypto.ed25519 import CoalescingVerifier
    plane = CoalescingVerifier(JaxEd25519Verifier(min_batch=4))
    s = Ed25519Signer(b"\x21" * 32)
    t1 = plane.submit_batch([(b"a", s.sign(b"a"), s.verkey)])
    plane.flush()
    # second submitter stages while the first dispatch is in flight
    t2 = plane.submit_batch([(b"b", s.sign(b"b"), s.verkey)])
    assert list(plane.collect_batch(t1, wait=True)) == [True]
    assert list(plane.collect_batch(t2, wait=True)) == [True]


# --- compressed dispatch: device-side key decompression (round 5) ---------

def test_decompress_kernel_matches_host():
    """Device decompression must agree with the host `decompress` twin on
    valid keys (producing the same -A quarter points as ext_quarters) and
    on every adversarial encoding class."""
    keys = [Ed25519Signer(bytes([i + 40]) * 32).verkey for i in range(3)]
    bad = [
        (ops.P + 1).to_bytes(32, "little"),          # y >= p (non-canonical)
        (ops.P - 1).to_bytes(32, "little"),          # y = p-1: off curve?
        bytes(32),                                   # y = 0
        (1 | (1 << 255)).to_bytes(32, "little"),     # y = 1 -> x = 0, sign=1
        (2).to_bytes(32, "little"),                  # y = 2
    ]
    all_keys = keys + bad
    k_u8 = np.frombuffer(b"".join(all_keys), np.uint8).reshape(-1, 32)
    import jax.numpy as jnp
    (qx, qy, qz, qt), valid = ops.decompress_kernel(jnp.asarray(k_u8))
    valid = np.asarray(valid)
    for i, kb in enumerate(all_keys):
        host = ops.decompress(kb)
        assert valid[i] == (host is not None), (i, kb.hex())
        if host is None:
            continue
        neg = ((ops.P - host[0]) % ops.P, host[1])
        want = ops.ext_quarters(neg)                 # [4, 4, NLIMB]
        for q in range(4):
            got = [np.asarray(c)[q, i] for c in (qx, qy, qz, qt)]
            x, y, z, t = (ops.limbs_to_int(np.asarray(ops.f_canon(
                jnp.asarray(g[None, :])))[0]) for g in got)
            zi = pow(z, ops.P - 2, ops.P)
            wx = ops.limbs_to_int(want[q, 0])
            wy = ops.limbs_to_int(want[q, 1])
            wz = ops.limbs_to_int(want[q, 2])
            wzi = pow(wz, ops.P - 2, ops.P)
            assert x * zi % ops.P == wx * wzi % ops.P, (i, q)
            assert y * zi % ops.P == wy * wzi % ops.P, (i, q)
            # extended-coordinate invariant: T = X*Y/Z
            assert t % ops.P == x * y % ops.P * zi % ops.P, (i, q)


def test_bytes_and_limb_dispatch_agree():
    """The compressed byte dispatch and the limb-staged dispatch are the
    same verifier semantics — run both on a mixed batch and compare."""
    rng = random.Random(11)
    signers = [Ed25519Signer(bytes([i + 60]) * 32) for i in range(3)]
    items = []
    for i in range(19):
        s = signers[i % 3]
        msg = rng.randbytes(20)
        sig = s.sign(msg)
        if i % 4 == 0:
            b = bytearray(sig); b[1] ^= 0x55; sig = bytes(b)
        if i % 7 == 0:
            sig = sig[:32] + (ops.L + i).to_bytes(32, "little")  # S >= L
        items.append((msg, sig, s.verkey))
    items.append((b"m", b"\x01" * 64, bytes(32)))      # y=0 verkey
    items.append((b"m", b"\x01" * 64, (ops.P + 2).to_bytes(32, "little")))

    class _Limb(JaxEd25519Verifier):
        _compressed_dispatch = False

    got_b = JaxEd25519Verifier().verify_batch(items)
    got_l = _Limb().verify_batch(items)
    cpu = CpuEd25519Verifier().verify_batch(items)
    assert list(got_b) == list(got_l) == list(cpu)
