"""End-to-end 4-node pool: signed NYM writes over SimNetwork through the full
stack — client authN (real Ed25519), propagate quorum, 3PC, BLS multi-sig,
ledger+state+audit commit, REPLY with Merkle/state proofs.

This is SURVEY.md §7's "minimum end-to-end slice" — the equivalent of the
reference's sdk_send_random_and_check over txnPoolNodeSet
(plenum/test/conftest.py:695, helper.py:1034).
"""
import pytest

from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID, POOL_LEDGER_ID,
                                             Reply, RequestAck, RequestNack)
from plenum_tpu.common.request import Request
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.config import Config
from plenum_tpu.crypto.bls import BlsCryptoSigner
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.execution import txn as txn_lib
from plenum_tpu.execution.txn import NODE, NYM, TRUSTEE
from plenum_tpu.network import SimNetwork, SimRandom
from plenum_tpu.node import Node, NodeBootstrap
from plenum_tpu.state.pruning_state import PruningState

NODES = ["Alpha", "Beta", "Gamma", "Delta"]


def make_genesis(names, validator_names=None):
    """Pool NODE txns (with real BLS verkeys) + a trustee NYM.
    validator_names: subset with services=[VALIDATOR]; the rest start as
    known-but-demoted nodes (services=[]) awaiting promotion."""
    trustee = Ed25519Signer(seed=b"trustee-seed".ljust(32, b"\0"))
    pool_txns = []
    for i, name in enumerate(names):
        bls_pk = BlsCryptoSigner(seed=name.encode().ljust(32, b"\0")[:32]).pk
        services = ["VALIDATOR"] if (validator_names is None
                                     or name in validator_names) else []
        txn = txn_lib.new_txn(NODE, {
            "dest": f"{name}Dest",
            "data": {"alias": name, "services": services,
                     "blskey": bls_pk,
                     "node_ip": "127.0.0.1", "node_port": 9700 + 2 * i,
                     "client_ip": "127.0.0.1", "client_port": 9701 + 2 * i}})
        # genesis nodes are steward-owned by the trustee so owner-only
        # edits (key rotation) are exercisable in tests
        txn["txn"].setdefault("metadata", {})["from"] = trustee.identifier
        txn_lib.set_seq_no(txn, i + 1)
        pool_txns.append(txn)
    nym = txn_lib.new_txn(NYM, {"dest": trustee.identifier,
                                "verkey": trustee.verkey_b58,
                                "role": TRUSTEE})
    txn_lib.set_seq_no(nym, 1)
    return {POOL_LEDGER_ID: pool_txns, DOMAIN_LEDGER_ID: [nym]}, trustee


class Pool:
    def __init__(self, names=NODES, seed=42, config=None, data_dir=None,
                 validator_names=None, verifier=None, tracing=True,
                 pipeline=None):
        self.names = list(names)
        self.timer = MockTimer()
        self.net = SimNetwork(self.timer, SimRandom(seed))
        self.config = config or Config(Max3PCBatchWait=0.05)
        self.verifier = verifier          # shared crypto plane (co-hosted)
        self.pipeline = pipeline          # shared fused crypto pipeline
        self.data_dir = data_dir          # per-node durable storage root
        self.tracing = tracing            # flight recorders on every node
        self.genesis, self.trustee = make_genesis(self.names, validator_names)
        self.client_msgs: dict[str, list] = {n: [] for n in self.names}
        self.nodes: dict[str, Node] = {}
        for name in self.names:
            self.start_node(name)
        self.net.connect_all()
        # conftest dumps every registered pool's flight-recorder rings
        # into the test report when the test fails
        try:
            from conftest import register_pool_for_flight_dump
            register_pool_for_flight_dump(self)
        except ImportError:
            pass

    def _node_data_dir(self, name):
        import os
        return os.path.join(self.data_dir, name) if self.data_dir else None

    def start_node(self, name: str) -> Node:
        """(Re)build a node from genesis + its durable dir and attach it
        to the fabric; used both at pool build and for restart tests."""
        bus = self.net.create_peer(name)
        components = NodeBootstrap(
            name, genesis_txns=self.genesis,
            data_dir=self._node_data_dir(name),
            crypto_backend=self.config.crypto_backend,
            storage_backend=self.config.kv_backend,
            verifier=self.verifier,
            pipeline=self.pipeline,
            state_commitment=self.config.STATE_COMMITMENT,
            state_commitment_per_ledger=(
                self.config.STATE_COMMITMENT_PER_LEDGER),
            verkle_width=self.config.VERKLE_WIDTH).build()
        from plenum_tpu.common.tracing import Tracer
        tracer = Tracer(name, self.timer.get_current_time,
                        clock_domain="shared") if self.tracing else None
        self.nodes[name] = Node(
            name, self.timer, bus, components,
            client_send=lambda msg, client, n=name:
                self.client_msgs[n].append((msg, client)),
            config=self.config, tracer=tracer)
        return self.nodes[name]

    def crash_node(self, name: str) -> None:
        """Hard-stop: drop the node object with NO clean shutdown (no
        close, no compaction) — the durable files are left exactly as the
        last flushed write; the dropped handles leak until GC, as in a
        real crash."""
        self.nodes.pop(name)
        self.net.remove_peer(name)

    def run(self, seconds=5.0, step=0.1):
        elapsed = 0.0
        while elapsed < seconds:
            for node in self.nodes.values():
                node.prod()
            self.timer.advance(step)
            elapsed += step

    def submit(self, request: Request, client="cli1", to=None):
        for name in (to or self.names):
            self.nodes[name].handle_client_message(request.to_dict(), client)

    def replies(self, node_name: str, msg_type=Reply):
        return [m for m, _ in self.client_msgs[node_name]
                if isinstance(m, msg_type)]


def signed_nym(trustee: Ed25519Signer, dest_signer: Ed25519Signer,
               req_id: int) -> Request:
    req = Request(trustee.identifier, req_id,
                  {"type": NYM, "dest": dest_signer.identifier,
                   "verkey": dest_signer.verkey_b58})
    req.signature = trustee.sign_b58(req.signing_bytes())
    return req


@pytest.fixture(scope="module")
def pool():
    return Pool()


def test_nym_write_end_to_end(pool):
    user = Ed25519Signer(seed=b"user-1".ljust(32, b"\0"))
    req = signed_nym(pool.trustee, user, req_id=1)
    pool.submit(req)
    pool.run(6.0)

    # every node ordered + committed the txn with identical roots
    sizes = {n: pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert all(s == 2 for s in sizes.values()), sizes    # genesis + our txn
    roots = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
             for n in pool.names}
    assert len(roots) == 1
    state_roots = {pool.nodes[n].c.db.get_state(DOMAIN_LEDGER_ID)
                   .committed_head_hash for n in pool.names}
    assert len(state_roots) == 1

    # f+1 consistent replies reached the client
    replies = [r for n in pool.names for r in pool.replies(n)]
    assert len(replies) >= pool.nodes["Alpha"].f + 1
    seq_nos = {r.result["txnMetadata"]["seqNo"] for r in replies}
    assert seq_nos == {2}
    # acks were sent before ordering
    acks = [r for n in pool.names for r in pool.replies(n, RequestAck)]
    assert len(acks) == len(pool.names)


def test_bad_signature_rejected(pool):
    user = Ed25519Signer(seed=b"user-2".ljust(32, b"\0"))
    req = signed_nym(pool.trustee, user, req_id=2)
    req.signature = pool.trustee.sign_b58(b"something else entirely")
    before = {n: len(pool.replies(n, RequestNack)) for n in pool.names}
    pool.submit(req)
    pool.run(2.0)
    nacks = [r for n in pool.names for r in pool.replies(n, RequestNack)
             ][sum(before.values()):]
    assert len(nacks) == len(pool.names)
    assert all("signature" in m.reason for m in nacks)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}      # nothing new ordered


def test_unauthorized_write_gets_rejected(pool):
    """A DID with no role cannot create other DIDs -> Reject after ordering."""
    user = Ed25519Signer(seed=b"user-1".ljust(32, b"\0"))
    other = Ed25519Signer(seed=b"user-3".ljust(32, b"\0"))
    req = Request(user.identifier, 3,
                  {"type": NYM, "dest": other.identifier,
                   "verkey": other.verkey_b58})
    req.signature = user.sign_b58(req.signing_bytes())
    pool.submit(req)
    pool.run(6.0)
    from plenum_tpu.common.node_messages import Reject
    rejects = [r for n in pool.names for r in pool.replies(n, Reject)]
    assert rejects, "dynamic-validation rejection should Reject to the client"


def test_get_nym_with_proof_and_multisig(pool):
    user = Ed25519Signer(seed=b"user-1".ljust(32, b"\0"))
    q = Request("anyone", 10, {"type": "105", "dest": user.identifier})
    node = pool.nodes["Alpha"]
    node.handle_client_message(q.to_dict(), "cli-q")
    pool.run(1.0)
    replies = [m for m, c in pool.client_msgs["Alpha"]
               if isinstance(m, Reply) and c == "cli-q"]
    assert replies
    res = replies[-1].result
    assert res["data"]["verkey"] == user.verkey_b58
    sp = res["state_proof"]
    value = node.c.db.get_state(DOMAIN_LEDGER_ID).get(
        user.identifier.encode(), committed=True)
    assert PruningState.verify_state_proof(
        bytes.fromhex(sp["root_hash"]), user.identifier.encode(), value,
        bytes.fromhex(sp["proof_nodes"]))
    # BLS multi-sig over a recent state root is attached once batches ordered
    assert "multi_signature" in sp


def test_audit_ledger_tracks_batches(pool):
    audit = pool.nodes["Alpha"].c.db.get_ledger(3)
    if audit.size == 0:      # self-sufficiency when run standalone
        user = Ed25519Signer(seed=b"user-audit".ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, user, req_id=99))
        pool.run(6.0)
    assert audit.size >= 1
    from plenum_tpu.execution.handlers import audit as audit_lib
    view_no, pp_seq_no, primaries = audit_lib.last_audited_view(audit)
    assert view_no == 0 and pp_seq_no >= 1
    assert primaries == pool.nodes["Alpha"].master_replica.data.primaries


@pytest.mark.slow
def test_pool_jax_backend_end_to_end():
    """The full 4-node pool with crypto_backend=jax: every client signature
    is verified by the device kernel (one fixed-shape dispatch per prod
    cycle) and every ledger uses the jax-backed tree hasher. Slow: the
    kernel compiles once for the pool's dispatch bucket."""
    pool = Pool(config=Config(Max3PCBatchWait=0.05, crypto_backend="jax"))
    verifier = pool.nodes["Alpha"].c.authenticator.core_authenticator.verifier
    # device backends come supervised from the factory (breaker + hedged
    # CPU fallback); the device underneath is the jax kernel verifier
    from plenum_tpu.parallel.supervisor import SupervisedVerifier
    assert isinstance(verifier, SupervisedVerifier)
    assert type(verifier._device).__name__ == "JaxEd25519Verifier"
    user = Ed25519Signer(seed=b"jax-pool-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(10.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}, sizes
    roots = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
             for n in pool.names}
    assert len(roots) == 1
    assert pool.replies("Alpha")

    # a bad signature is rejected by the SAME device path
    bad = signed_nym(pool.trustee, Ed25519Signer(
        seed=b"jax-bad-user".ljust(32, b"\0")), 2)
    bad.signature = bad.signature[:-2] + "11"
    pool.submit(bad)
    pool.run(8.0)     # > MAX_AUTH_POLLS prods so the pipelined collect blocks
    from plenum_tpu.common.node_messages import RequestNack
    assert pool.replies("Alpha", RequestNack)


def test_pool_sharded_crypto_plane_end_to_end():
    """REAL node traffic through the multi-chip plane: a 4-node pool shares
    one CoalescingVerifier whose device program is ShardedCryptoPlane over
    the suite's 8 virtual CPU devices (2x4 'inst'x'sig' mesh) — the same
    SPMD program dryrun_multichip compiles, now fed by client authN instead
    of synthetic batches (SURVEY.md §2.3 distributed-comm row)."""
    from plenum_tpu.crypto.ed25519 import CoalescingVerifier
    from plenum_tpu.parallel.crypto_plane import make_sharded_verifier

    sharded = make_sharded_verifier(min_batch=8)
    shared = CoalescingVerifier(sharded)
    pool = Pool(config=Config(Max3PCBatchWait=0.05,
                              crypto_backend="jax-sharded"),
                verifier=shared)
    # every node's authenticator feeds the ONE shared plane
    for n in pool.names:
        assert pool.nodes[n].c.authenticator.core_authenticator.verifier \
            is shared

    user = Ed25519Signer(seed=b"sharded-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1))
    pool.run(10.0)
    assert sharded.dispatches >= 1, "no traffic reached the sharded plane"
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}, sizes
    roots = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
             for n in pool.names}
    assert len(roots) == 1
    assert pool.replies("Alpha")

    # a WELL-FORMED wrong signature must be refused by the device verdict
    # itself (a mangled-encoding sig would be host-rejected before
    # dispatch and prove nothing about the plane)
    imposter = Ed25519Signer(seed=b"sharded-imposter".ljust(32, b"\0"))
    bad = signed_nym(pool.trustee, Ed25519Signer(
        seed=b"sharded-bad".ljust(32, b"\0")), 2)
    bad.signature = imposter.sign_b58(bad.signing_bytes())
    before = sharded.dispatches
    pool.submit(bad)
    pool.run(8.0)
    from plenum_tpu.common.node_messages import RequestNack
    assert pool.replies("Alpha", RequestNack)
    assert sharded.dispatches > before


def test_endorsed_multi_sig_request_orders():
    """A request carrying MULTIPLE signatures (author + endorser) passes
    only if every signer verifies (ref authenticate_multi:84), and a bad
    endorser signature nacks the whole request."""
    pool = Pool(seed=77)
    author = Ed25519Signer(seed=b"ms-author".ljust(32, b"\0"))
    # register the author (no role) so its verkey resolves from state
    pool.submit(signed_nym(pool.trustee, author, 1))
    pool.run(5.0)

    user = Ed25519Signer(seed=b"ms-target".ljust(32, b"\0"))
    req = Request(author.identifier, 2,
                  {"type": NYM, "dest": user.identifier,
                   "verkey": user.verkey_b58},
                  endorser=pool.trustee.identifier)
    payload = req.signing_bytes()
    req.signatures = {author.identifier: author.sign_b58(payload),
                      pool.trustee.identifier: pool.trustee.sign_b58(payload)}
    pool.submit(req)
    pool.run(5.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {3}, sizes

    # same shape but the endorser's signature is broken -> NACK, no txn
    req2 = Request(author.identifier, 3,
                   {"type": NYM, "dest": "X" + user.identifier[1:],
                    "verkey": user.verkey_b58},
                   endorser=pool.trustee.identifier)
    payload2 = req2.signing_bytes()
    sigs = {author.identifier: author.sign_b58(payload2),
            pool.trustee.identifier: pool.trustee.sign_b58(b"wrong")}
    req2.signatures = sigs
    pool.submit(req2, to=["Alpha"])
    pool.run(5.0)
    nacks = pool.replies("Alpha", RequestNack)
    assert any(m.req_id == 3 for m in nacks)
    assert pool.nodes["Alpha"].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 3


def test_named_endorser_without_signature_is_nacked():
    """Naming a trustee as endorser WITHOUT their signature must fail
    authentication — otherwise anyone could borrow the trustee's role."""
    pool = Pool(seed=78)
    author = Ed25519Signer(seed=b"imp-author".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, author, 1))
    pool.run(5.0)

    user = Ed25519Signer(seed=b"imp-target".ljust(32, b"\0"))
    req = Request(author.identifier, 2,
                  {"type": NYM, "dest": user.identifier,
                   "verkey": user.verkey_b58},
                  endorser=pool.trustee.identifier)   # named, NOT signing
    req.signature = author.sign_b58(req.signing_bytes())
    pool.submit(req, to=["Alpha"])
    pool.run(5.0)
    assert any(m.req_id == 2 for m in pool.replies("Alpha", RequestNack))
    assert pool.nodes["Alpha"].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2


class DeferredVerifier:
    """Ed25519Verifier test double: verdicts computed at submit (C library)
    but withheld from collect until release() — makes the async device
    pipeline's in-flight window controllable from a test."""

    def __init__(self):
        from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
        self._inner = CpuEd25519Verifier()
        self.released = False
        self.submits = []               # item batches, for dispatch counting

    def submit_batch(self, items):
        self.submits.append(list(items))
        return self._inner.verify_batch(items)

    def collect_batch(self, token, wait=True):
        if not (self.released or wait):
            return None
        return token

    def verify_batch(self, items):
        return self.submit_batch(items)


def test_client_copy_parks_on_inflight_propagate_dispatch():
    """A client request arriving while a peer's PROPAGATE of the same bytes
    is already being verified must NOT start a second device dispatch: it
    parks on the digest and settles on the in-flight verdict."""
    pool = Pool()
    beta = pool.nodes["Beta"]
    deferred = DeferredVerifier()
    beta.c.authenticator.core_authenticator.verifier = deferred

    user = Ed25519Signer(seed=b"parked-user".ljust(32, b"\0"))
    req = signed_nym(pool.trustee, user, req_id=77)

    # Alpha sees the request first and propagates; Beta's propagate-path
    # dispatch goes in flight and stays there (verdict withheld)
    pool.submit(req, to=["Alpha"])
    pool.run(2.0)           # < MAX_AUTH_POLLS prods: Beta must not block
    assert len(deferred.submits) == 1
    assert req.digest in beta._authing

    # now the client's own copy reaches Beta: parked, not re-dispatched
    pool.submit(req, to=["Beta"], client="cli-beta")
    pool.run(1.0)
    assert len(deferred.submits) == 1, "client copy must not re-dispatch"
    assert any(kind == "client" for kind, *_ in beta._authing[req.digest])

    # release the verdict: parked client gets ACKed, request orders
    deferred.released = True
    pool.run(6.0)
    assert len(deferred.submits) == 1
    assert any(isinstance(m, RequestAck) and c == "cli-beta"
               for m, c in pool.client_msgs["Beta"])
    assert any(isinstance(m, Reply) for m, _ in pool.client_msgs["Beta"])
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}
