"""Digest-gossip dissemination: digest-only votes, the body-fetch fallback
through MessageReq (with a lying responder in the loop), legacy full-body
wire compat, and the bytes-on-wire reduction itself.

Covers the ISSUE acceptance points: a node that reaches the f+1 propagate
quorum on digest votes alone must pull the body from a voter and finalize;
one bad/timeout reply must not wedge it; an old node's full-body PROPAGATE
must still be accepted and counted.
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.message_base import message_from_dict
from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID, MessageRep,
                                             Propagate, PropagateBatch,
                                             Reply)
from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.network.sim_network import (Discard, Mutate, match_dst,
                                            match_type)

from test_pool import Pool, signed_nym


def _mk_request(pool, req_id):
    user = Ed25519Signer(seed=(b"dg-user%d" % req_id).ljust(32, b"\0")[:32])
    return signed_nym(pool.trustee, user, req_id)


def test_single_submit_orders_via_digest_gossip():
    """The client submits to ONE node only: whoever that is, the pool must
    still finalize and order — through the designated disseminator's body
    broadcast or the digest-vote fetch path."""
    pool = Pool(seed=101)
    req = _mk_request(pool, 1)
    pool.submit(req, to=["Alpha"])
    pool.run(8.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}, sizes
    assert pool.replies("Alpha", Reply)


def test_digest_votes_only_vote_never_forwards_without_body():
    """f+1 digest votes with NO body must not finalize/forward; the node
    arms the fetch loop instead (ordering may never cite an absent body)."""
    pool = Pool(seed=102)
    delta = pool.nodes["Delta"]
    req = _mk_request(pool, 2)
    for frm in ("Alpha", "Beta", "Gamma"):
        delta.node_bus.process_incoming(
            Propagate(digest=req.digest, sender_client="cli1"), frm)
    delta.prod()
    state = delta.propagator.requests.get(req.digest)
    assert state is not None
    assert len(state.propagates) == 3          # >= f+1: quorum of votes
    assert state.request is None
    assert not state.finalised and not state.forwarded
    assert req.digest in delta._body_fetches    # fetch loop armed


def test_fetch_fallback_reaches_quorum_then_pulls_body():
    """Delta reaches the f+1 propagate quorum on digest votes alone, and
    the first fetch candidate (Alpha, sorted first) does NOT hold the
    body: the loop must survive the unanswered MessageReq and pull the
    body from the next voter (Gamma, the only holder)."""
    from plenum_tpu.network.sim_network import match_frm
    pool = Pool(seed=103)
    delta = pool.nodes["Delta"]
    # keep the body pinned to Gamma: none of its propagates leave it
    pool.net.add_rule(Discard(), match_type((Propagate, PropagateBatch)),
                      match_frm("Gamma"))
    req = _mk_request(pool, 3)
    pool.submit(req, to=["Gamma"])
    pool.run(2.0)
    assert pool.nodes["Gamma"].propagator.requests.has_body(req.digest)
    assert delta.propagator.requests.get(req.digest) is None

    # quorum of digest votes: Alpha (bodyless) sorts before Gamma (holder)
    for frm in ("Alpha", "Gamma"):
        delta.node_bus.process_incoming(
            Propagate(digest=req.digest, sender_client="cli1"), frm)
    delta.prod()
    assert req.digest in delta._body_fetches
    pool.run(6.0)   # try 1 -> Alpha (no body, times out), try 2 -> Gamma
    state = delta.propagator.requests.get(req.digest)
    assert state is not None and state.request is not None
    assert state.request.digest == req.digest
    assert state.finalised and state.forwarded
    assert req.digest not in delta._body_fetches    # loop stood down


def test_fetch_survives_lying_responder():
    """The first MessageRep body is swapped for a DIFFERENT (validly
    signed) request: it cannot hash to the fetched digest, so the fetch
    loop must retry and still land the real body."""
    pool = Pool(seed=104)
    delta = pool.nodes["Delta"]
    decoy = _mk_request(pool, 98)
    lied = {"n": 0}

    def corrupt_first_rep(msg):
        if isinstance(msg, MessageRep) and msg.msg_type == "PROPAGATE" \
                and lied["n"] == 0:
            lied["n"] += 1
            return MessageRep(msg_type=msg.msg_type, params=msg.params,
                              msg=Propagate(request=decoy.to_dict(),
                                            sender_client=None).to_dict())
        return msg

    from plenum_tpu.network.sim_network import match_frm
    # the body lives only on Gamma; Delta learns of it via digest votes
    pool.net.add_rule(Discard(), match_type((Propagate, PropagateBatch)),
                      match_frm("Gamma"))
    mutate = pool.net.add_rule(Mutate(corrupt_first_rep),
                               match_type(MessageRep), match_dst("Delta"))
    req = _mk_request(pool, 4)
    pool.submit(req, to=["Gamma"])
    pool.run(2.0)
    for frm in ("Beta", "Gamma"):
        delta.node_bus.process_incoming(
            Propagate(digest=req.digest, sender_client="cli1"), frm)
    delta.prod()
    pool.run(12.0)   # Beta times out, Gamma's first reply lies -> retry
    assert lied["n"] == 1, "the mutation never fired"
    state = delta.propagator.requests.get(req.digest)
    assert state is not None and state.request is not None
    assert state.request.digest == req.digest
    assert state.finalised
    pool.net.remove_rule(mutate)


def test_legacy_full_body_propagate_still_counts():
    """Wire compat: an old node's PROPAGATE (full body, no digest field)
    decodes, authenticates, and counts as a body-carrying vote."""
    pool = Pool(seed=105)
    alpha = pool.nodes["Alpha"]
    req = _mk_request(pool, 5)
    legacy_wire = pack({"op": "PROPAGATE", "request": req.to_dict(),
                        "sender_client": "cli-old"})
    msg = message_from_dict(unpack(legacy_wire))
    assert isinstance(msg, Propagate) and msg.digest == ""
    alpha.node_bus.process_incoming(msg, "Beta")
    for _ in range(3):
        alpha.prod()
    state = alpha.propagator.requests.get(req.digest)
    assert state is not None and state.request is not None
    assert "Beta" in state.propagates
    # and the node relayed its own vote (body or digest, per designation)
    assert "Alpha" in state.propagates


def test_mismatched_body_digest_is_dropped():
    """A body that does not hash to the claimed digest is a lie — dropped,
    never counted."""
    pool = Pool(seed=106)
    alpha = pool.nodes["Alpha"]
    req = _mk_request(pool, 6)
    other = _mk_request(pool, 7)
    alpha.node_bus.process_incoming(
        Propagate(request=req.to_dict(), digest=other.digest,
                  sender_client=None), "Beta")
    for _ in range(3):
        alpha.prod()
    assert alpha.propagator.requests.get(req.digest) is None
    state = alpha.propagator.requests.get(other.digest)
    assert state is None or state.request is None


def test_designated_disseminator_is_deterministic():
    pool = Pool(seed=107)
    req = _mk_request(pool, 8)
    flags = [pool.nodes[n].propagator.is_disseminator(req.digest)
             for n in pool.names]
    assert sum(flags) == 1      # exactly one body broadcaster per digest


def test_digest_gossip_off_restores_full_body_flooding():
    from plenum_tpu.config import Config
    pool = Pool(seed=108, config=Config(Max3PCBatchWait=0.05,
                                        DIGEST_GOSSIP=False))
    req = _mk_request(pool, 9)
    pool.submit(req)
    pool.run(6.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}, sizes
    # every node's own vote carried the body (sampled via Alpha's state
    # having a body from whichever peer's propagate landed first)
    tx = pool.net.tx_msgs
    assert "PROPAGATE" in tx or "PROPAGATE_BATCH" in tx


def test_propagate_bytes_drop_vs_full_body():
    """The measured point of the whole change: same load, digest-gossip
    on vs off, propagate bytes on the wire must drop >= 2x."""
    from plenum_tpu.config import Config

    def run_one(gossip: bool) -> int:
        pool = Pool(seed=109, config=Config(Max3PCBatchWait=0.05,
                                            DIGEST_GOSSIP=gossip))
        for i in range(5):
            pool.submit(_mk_request(pool, 10 + i))
        pool.run(8.0)
        sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
                 for n in pool.names}
        assert sizes == {6}, (gossip, sizes)    # 1 genesis NYM + 5 writes
        tx = pool.net.tx_msgs
        return sum(c[1] for op, c in tx.items()
                   if op in ("PROPAGATE", "PROPAGATE_BATCH"))

    flood = run_one(False)
    gossip = run_one(True)
    assert gossip * 2 <= flood, (gossip, flood)
