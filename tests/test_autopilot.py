"""Autopilot control plane (docs/robustness.md "Autopilot"): sustained
judgment streaks on the aggregator, the reshard manager's idempotent
entry guard, the control ledger + its audit lint, the degradation
ladder's actuation, and the AUTOPILOT=False identity pin.

The live closed-loop scenarios (split under zipfian flood, lane re-pin
around a sick chip, observer scale-out, the composed stress run) live
in test_sim_fuzz.py as the `autopilot` fuzz kind.
"""
from __future__ import annotations

import pytest

from plenum_tpu.config import Config
from plenum_tpu.control import (CONTROL_LEDGER_ID, ControlLedger, LADDER,
                                REVERT_OF, make_autopilot)
from plenum_tpu.observability import FleetAggregator
from plenum_tpu.shards import ShardedSimFabric
from plenum_tpu.tools.control_audit import audit_records, replay


def _snap(node, seq, t, shard=None, ordered=0, slo=None, devices=None):
    state = {"node": {"ordered_total": ordered, "view_no": 0,
                      "vc_in_progress": False, "catchup_running": False,
                      "read_only_degraded": False, "validators": 4,
                      "anchor_age": 1.0}}
    if slo is not None:
        state["ingress"] = {"queue_depth": 0, "shedding": False,
                            "slo": slo}
    if devices is not None:
        state["pipeline"] = {
            "devices": devices,
            "breakers_open": sum(1 for d in devices
                                 if d.get("breaker") != "closed")}
    return {"v": 1, "node": node, "seq": seq, "t": t,
            **({"tags": {"shard": shard}} if shard is not None else {}),
            "counters": {}, "sampled": {}, "state": state}


def _agg(**over):
    cfg = dict(SLO_BURN_FAST_WINDOW=5.0, SLO_BURN_SLOW_WINDOW=20.0,
               TELEMETRY_INTERVAL=1.0)
    cfg.update(over)
    return FleetAggregator(config=Config(**cfg))


# --- sustained judgment streaks ----------------------------------------------

def test_sustained_counts_consecutive_burn_intervals():
    """`sustained(kind, N)` = N consecutive pool-interval judgments
    over threshold; one clean interval resets the streak, and recovery
    builds the `sustained_clear` streak the undo policies gate on."""
    agg = _agg()
    for i in range(30):
        agg.ingest(_snap("N1", i, float(i), slo=[4, 5]))
    assert agg.sustained("slo_burn.ingress", 3, subject="N1")
    assert agg.sustained("slo_burn.ingress", 3)           # any-subject
    assert agg.sustained_subjects("slo_burn.ingress", 3) == ["N1"]
    assert not agg.sustained("slo_burn.batch", 1)
    assert not agg.sustained_clear("slo_burn.ingress", 1, subject="N1")
    # recovery: clean intervals age the burn out of both windows, the
    # active streak zeroes, and the clear streak accumulates
    for i in range(30, 70):
        agg.ingest(_snap("N1", i, float(i), slo=[0, 5]))
    assert not agg.sustained("slo_burn.ingress", 1, subject="N1")
    assert agg.sustained_clear("slo_burn.ingress", 5, subject="N1")
    assert agg.sustained_clear("slo_burn.ingress", 5)     # every subject
    # a kind never noted is vacuously clear — the recover path must not
    # deadlock on signals that never existed
    assert agg.sustained_clear("slo_burn.reads", 99)


def test_sustained_streak_resets_on_a_single_clean_interval():
    agg = _agg()
    for i in range(12):
        agg.ingest(_snap("N1", i, float(i), slo=[5, 5]))
    assert agg.sustained("slo_burn.ingress", 3, subject="N1")
    streak = agg._streaks[("slo_burn.ingress", "N1")]
    # one interval under threshold: consecutive means CONSECUTIVE
    for i in range(12, 40):
        agg.ingest(_snap("N1", i, float(i), slo=[0, 5]))
        if not agg.sustained("slo_burn.ingress", 1, subject="N1"):
            break
    assert agg._streaks[("slo_burn.ingress", "N1")] == 0
    assert streak >= 3


def test_lane_breaker_judgments_feed_pipeline_streaks():
    agg = _agg()
    sick = [{"lane": 0, "breaker": "closed", "occupancy": 0},
            {"lane": 2, "breaker": "open", "occupancy": 3}]
    for i in range(4):
        agg.ingest(_snap("N1", i, float(i), devices=sick))
    assert agg.lane_breakers() == {0: False, 2: True}
    assert agg.sustained("pipeline.lane", 3, subject="2")
    assert not agg.sustained("pipeline.lane", 1, subject="0")
    healed = [{"lane": 0, "breaker": "closed", "occupancy": 0},
              {"lane": 2, "breaker": "closed", "occupancy": 0}]
    for i in range(4, 10):
        agg.ingest(_snap("N1", i, float(i), devices=healed))
    assert agg.sustained_clear("pipeline.lane", 4, subject="2")


def test_cold_shard_names_the_underloaded_merge_candidate():
    agg = _agg()
    for i in range(30):
        agg.ingest(_snap("A", i, float(i), shard=0, ordered=i * 40))
        agg.ingest(_snap("B", i, float(i), shard=1, ordered=i))
    rates = agg.ordered_rates()
    assert agg.cold_shard(rates) == 1
    # balanced rates: nobody is cold; an idle pool is balanced, not
    # under-loaded (mean 0 -> None)
    assert agg.cold_shard({0: 10.0, 1: 9.0}) is None
    assert agg.cold_shard({0: 0.0, 1: 0.0}) is None
    assert agg.cold_shard({0: 5.0}) is None
    # under-load is never judged while a shard is HOT (merge must not
    # fight split): the skew above flags shard 0 hot, so the underload
    # streak stayed zero all along
    assert agg.sustained("shard.imbalance", 3)
    assert not agg.sustained("shard.underload", 1)


# --- the reshard manager's idempotent entry guard ----------------------------

def test_maybe_split_is_idempotent_while_busy_and_cooling():
    fab = ShardedSimFabric(
        n_shards=2, nodes_per_shard=3, seed=7,
        config=Config(Max3PCBatchWait=0.05, RESHARD_COOLDOWN=5.0))
    rm = fab.reshard
    assert rm.can_start() and not rm.busy
    m = rm.split(0)
    # second caller during the in-flight migration: clean no-op, not
    # the double-entry assert
    assert rm.busy and not rm.can_start()
    assert rm.maybe_split() is None
    elapsed = 0.0
    while elapsed < 90.0 and m.phase not in ("done", "aborted"):
        fab.run(0.5)
        elapsed += 0.5
    assert m.phase == "done", m.to_dict()
    # done stamps the cooldown: still a no-op until it expires
    now = fab.timer.get_current_time()
    assert rm.cooldown_until > now
    assert not rm.can_start() and rm.maybe_split() is None
    fab.run(rm.cooldown_until - now + 1.0)
    assert rm.can_start()
    assert rm.summary()["cooldown_until"] == round(rm.cooldown_until, 3)


# --- control ledger + audit --------------------------------------------------

def test_control_ledger_orders_records_and_audits_clean():
    clock = [5.0]
    ledger = ControlLedger(now=lambda: clock[0])
    a = ledger.append(policy="lane", action="repin", subject="shard0",
                      evidence={"sick_lane": 1}, pre={"lane": 1},
                      post={"lane": 0}, cooldown_until=15.0)
    clock[0] = 20.0
    b = ledger.append(policy="lane", action="unpin", subject="shard0",
                      evidence={"healed_lane": 1}, pre={"lane": 0},
                      post={"lane": 1}, cooldown_until=30.0, cites=a.seq)
    assert (a.seq, b.seq) == (1, 2) and len(ledger) == 2
    dicts = ledger.to_dicts()
    assert all(d["ledger_id"] == CONTROL_LEDGER_ID for d in dicts)
    assert audit_records(dicts) == []
    assert replay(dicts)["pins"] == {}      # the unpin undid the repin


def test_audit_catches_uncited_undo_and_cooldown_flap():
    clock = [5.0]
    ledger = ControlLedger(now=lambda: clock[0])
    ledger.append(policy="lane", action="repin", subject="shard0",
                  evidence={"sick_lane": 1}, pre={"lane": 1},
                  post={"lane": 0}, cooldown_until=15.0)
    clock[0] = 8.0                           # INSIDE the cooldown window
    ledger.append(policy="lane", action="unpin", subject="shard0",
                  evidence={"healed_lane": 1}, pre={}, post={},
                  cooldown_until=18.0)       # and citing nothing
    problems = audit_records(ledger.to_dicts())
    assert any("cites no earlier record" in p for p in problems)
    assert any("fired inside cooldown" in p for p in problems)
    # every undo action has a forward action to cite
    assert set(REVERT_OF.values()) == {"repin", "observer_spawn",
                                       "degrade"}


def test_control_audit_self_check_is_green():
    """`control_audit --check` is the tier-1 self-test gate (the
    fleet_console --check pattern): a synthetic good ledger lints
    clean and one corrupted variant per lint rule is caught."""
    from plenum_tpu.tools import control_audit
    assert control_audit.main(["--check"]) == 0


# --- the degradation ladder actuates and steps back up -----------------------

def _enabled_fabric(**over):
    cfg = dict(Max3PCBatchWait=0.05, AUTOPILOT=True,
               AUTOPILOT_INTERVAL=0.5, AUTOPILOT_SUSTAIN=2,
               AUTOPILOT_RECOVER_SUSTAIN=2, AUTOPILOT_COOLDOWN=3.0,
               RESHARD_COOLDOWN=3.0, TELEMETRY_INTERVAL=0.5)
    cfg.update(over)
    return ShardedSimFabric(n_shards=2, nodes_per_shard=3, seed=11,
                            config=Config(**cfg))


def test_ladder_degrades_and_recovers_with_cited_undos():
    """Force the sustained-burn judgment directly and watch the ladder
    walk down (shed-harder, then read-only) and back up one level at a
    time — every step a ledger record, every recover citing its
    degrade, never two steps inside one cooldown window."""
    fab = _enabled_fabric()
    ap = fab.autopilot
    agg = fab.aggregator
    entry = fab.shards[0].names[0]
    plane = fab.ingress_plane(entry, tick=False)
    base_wm = plane.shed_watermark

    def tick(burning: bool):
        agg.now += ap._interval
        key = ("slo_burn.ingress", "front")
        if burning:
            agg._streaks[key] = agg._streaks.get(key, 0) + 1
            agg._clear_streaks[key] = 0
        else:
            agg._clear_streaks[key] = agg._clear_streaks.get(key, 0) + 1
            agg._streaks[key] = 0
        ap.service()

    def drive(burning, until, limit=40):
        for _ in range(limit):
            if until():
                return
            tick(burning)
        raise AssertionError(f"ladder stuck at {ap.summary()}")

    drive(True, lambda: ap.level == 1)
    assert plane.shed_watermark == max(
        1, fab.config.INGRESS_HIGH_WATERMARK // ap._shed_factor)
    assert plane.shed_watermark < base_wm
    drive(True, lambda: ap.level == 2)
    assert all(n.read_only_degraded for n in fab.nodes.values())
    # held at the ladder's floor: more burn adds holds, never actions
    floor_actions = ap.counts["actions"]
    for _ in range(6):
        tick(True)
    assert ap.level == 2 and ap.counts["actions"] == floor_actions
    # recovery: one level at a time, each recover citing its degrade
    drive(False, lambda: ap.level == 1)
    assert not any(n.read_only_degraded for n in fab.nodes.values())
    drive(False, lambda: ap.level == 0)
    assert plane.shed_watermark == base_wm
    recs = ap.ledger.to_dicts()
    degrades = [r for r in recs if r["action"] == "degrade"]
    recovers = [r for r in recs if r["action"] == "recover"]
    assert [r["subject"] for r in degrades] == ["shed_harder",
                                                "read_only"]
    assert [r["subject"] for r in recovers] == ["read_only",
                                                "shed_harder"]
    assert [r["cites"] for r in recovers] == \
        [degrades[1]["seq"], degrades[0]["seq"]]          # LIFO undo
    assert audit_records(recs) == []
    assert replay(recs)["level"] == 0
    assert ap.summary()["state"] == LADDER[0]


def test_ladder_never_undegrades_a_catchup_diverged_node():
    fab = _enabled_fabric()
    node = next(iter(fab.nodes.values()))
    node._degrade_read_only()                # catchup divergence, not us
    assert node.read_only_degraded
    assert not node.set_read_only(True, reason="autopilot")
    assert not node.set_read_only(False, reason="autopilot")
    assert node.read_only_degraded           # autopilot never clears it


# --- AUTOPILOT=False is identity ---------------------------------------------

def test_autopilot_off_is_todays_behavior_exactly():
    fab = ShardedSimFabric(n_shards=2, nodes_per_shard=3, seed=3,
                           config=Config(Max3PCBatchWait=0.05))
    assert fab.autopilot is None
    assert make_autopilot(fab) is None
    fab.run(3.0)
    assert fab.aggregator.autopilot is None
    assert not any(name.startswith("autopilot.")
                   for name in fab.metrics.accumulators)
    assert "autopilot" not in fab.summary()


def test_autopilot_on_decides_on_aggregator_intervals():
    fab = _enabled_fabric()
    ap = fab.autopilot
    assert ap is not None
    fab.run(3.0)
    assert ap.counts["decisions"] >= 2
    assert ap.counts["actions"] == 0         # healthy pool: no actuation
    assert fab.aggregator.autopilot == ap.summary()
    assert "autopilot.decisions" in fab.metrics.accumulators
    # the cadence rides the FLEET clock: with no snapshot arrivals the
    # autopilot does not keep ticking (deterministic replay) — at most
    # one boundary fire when the clock sits exactly on the next mark
    ap.service()
    before = ap.counts["decisions"]
    for _ in range(10):
        ap.service()
    assert ap.counts["decisions"] == before
