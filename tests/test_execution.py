"""Execution-layer tests: handlers, write manager lifecycle, audit ledger.

Mirrors the reference's handler/batch-handler unit tests
(plenum/test/req_handler tests, audit_ledger/) at the same seams.
"""
import pytest

from plenum_tpu.common.node_messages import (AUDIT_LEDGER_ID,
                                             CONFIG_LEDGER_ID,
                                             DOMAIN_LEDGER_ID, POOL_LEDGER_ID)
from plenum_tpu.common.request import Request
from plenum_tpu.execution import (DatabaseManager, LedgerBatchExecutor,
                                  ReadRequestManager, ThreePcBatch,
                                  WriteRequestManager)
from plenum_tpu.execution.database_manager import SEQ_NO_DB_LABEL, TS_STORE_LABEL
from plenum_tpu.execution.exceptions import (InvalidClientRequest,
                                             UnauthorizedClientRequest)
from plenum_tpu.execution.handlers import (GetNymHandler,
                                           GetTxnAuthorAgreementAmlHandler,
                                           GetTxnAuthorAgreementHandler,
                                           GetTxnHandler, NodeHandler,
                                           NymHandler,
                                           TxnAuthorAgreementAmlHandler,
                                           TxnAuthorAgreementHandler)
from plenum_tpu.execution.handlers import audit as audit_lib
from plenum_tpu.execution.handlers.taa import taa_digest
from plenum_tpu.execution.txn import (NYM, STEWARD, TRUSTEE,
                                      TXN_AUTHOR_AGREEMENT,
                                      TXN_AUTHOR_AGREEMENT_AML)
from plenum_tpu.ledger.ledger import Ledger
from plenum_tpu.state.pruning_state import PruningState
from plenum_tpu.storage.kv_memory import KvMemory
from plenum_tpu.storage.state_ts_store import StateTsStore


TRUSTEE_DID = "trusteeTrusteeTrustee1"
STEWARD_DID = "stewardStewardSteward1"
USER_DID = "userUserUserUserUser11"


def make_db():
    db = DatabaseManager()
    for lid in (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID,
                AUDIT_LEDGER_ID):
        state = None if lid == AUDIT_LEDGER_ID else PruningState()
        db.register_ledger(lid, Ledger(), state)
    db.register_store(TS_STORE_LABEL, StateTsStore(KvMemory()))
    db.register_store(SEQ_NO_DB_LABEL, KvMemory())
    return db


@pytest.fixture
def db():
    return make_db()


def make_managers(db):
    wm = WriteRequestManager(db)
    nym = NymHandler(db)
    wm.register_handler(nym)
    wm.register_handler(NodeHandler(db, nym))
    wm.register_handler(TxnAuthorAgreementHandler(db, nym))
    wm.register_handler(TxnAuthorAgreementAmlHandler(db, nym))
    rm = ReadRequestManager()
    rm.register_handler(GetNymHandler(db))
    rm.register_handler(GetTxnHandler(db))
    rm.register_handler(GetTxnAuthorAgreementHandler(db))
    rm.register_handler(GetTxnAuthorAgreementAmlHandler(db))
    return wm, rm


def nym_req(author, dest, role=None, verkey="vk", req_id=1, taa=None):
    op = {"type": NYM, "dest": dest, "verkey": verkey}
    if role is not None:
        op["role"] = role
    return Request(author, req_id, op, signature="sig", taa_acceptance=taa)


def bootstrap_trustee(wm, pp=1):
    """First NYM into empty state is allowed (pool bootstrap)."""
    req = nym_req(TRUSTEE_DID, TRUSTEE_DID, role=TRUSTEE)
    valid, rejected, roots = wm.apply_batch(DOMAIN_LEDGER_ID, [req],
                                            pp_time=1000.0, view_no=0,
                                            pp_seq_no=pp)
    assert len(valid) == 1 and not rejected
    return roots


class TestNymHandler:
    def test_bootstrap_then_permissioned(self, db):
        wm, _ = make_managers(db)
        bootstrap_trustee(wm)
        # trustee can create
        ok, rej, _ = wm.apply_batch(DOMAIN_LEDGER_ID,
                                    [nym_req(TRUSTEE_DID, USER_DID, req_id=2)],
                                    1001.0, 0, 2)
        assert len(ok) == 1 and not rej
        # a plain user cannot create another DID
        ok, rej, _ = wm.apply_batch(DOMAIN_LEDGER_ID,
                                    [nym_req(USER_DID, "otherDid111", req_id=3)],
                                    1002.0, 0, 3)
        assert not ok and len(rej) == 1

    def test_owner_can_rotate_key_but_not_role(self, db):
        wm, _ = make_managers(db)
        bootstrap_trustee(wm)
        wm.apply_batch(DOMAIN_LEDGER_ID,
                       [nym_req(TRUSTEE_DID, USER_DID, req_id=2)], 1001.0, 0, 2)
        ok, rej, _ = wm.apply_batch(
            DOMAIN_LEDGER_ID,
            [nym_req(USER_DID, USER_DID, verkey="newvk", req_id=3)],
            1002.0, 0, 3)
        assert len(ok) == 1
        ok, rej, _ = wm.apply_batch(
            DOMAIN_LEDGER_ID,
            [nym_req(USER_DID, USER_DID, role=TRUSTEE, req_id=4)],
            1003.0, 0, 4)
        assert len(rej) == 1

    def test_static_validation(self, db):
        wm, _ = make_managers(db)
        with pytest.raises(InvalidClientRequest):
            wm.static_validation(Request("a", 1, {"type": NYM}))
        with pytest.raises(InvalidClientRequest):
            wm.static_validation(
                Request("a", 1, {"type": NYM, "dest": "d", "role": "99"}))


class TestWriteLifecycle:
    def test_apply_commit_updates_seq_no_and_ts(self, db):
        wm, _ = make_managers(db)
        roots = bootstrap_trustee(wm)
        batch = ThreePcBatch(DOMAIN_LEDGER_ID, 0, 1, 1000.0, ("x",),
                             bytes.fromhex(roots["state_root"]),
                             bytes.fromhex(roots["txn_root"]),
                             bytes.fromhex(roots["audit_txn_root"]))
        committed = wm.commit_batch(batch)
        assert len(committed) == 1
        ledger = db.get_ledger(DOMAIN_LEDGER_ID)
        assert ledger.size == 1
        assert db.get_ledger(AUDIT_LEDGER_ID).size == 1
        assert db.get_store(TS_STORE_LABEL).get(
            DOMAIN_LEDGER_ID, 1000) is not None

    def test_revert_is_exact_inverse(self, db):
        wm, _ = make_managers(db)
        state = db.get_state(DOMAIN_LEDGER_ID)
        root0 = state.head_hash
        ledger = db.get_ledger(DOMAIN_LEDGER_ID)
        bootstrap_trustee(wm)
        assert state.head_hash != root0
        wm.revert_last_batch(DOMAIN_LEDGER_ID)
        assert state.head_hash == root0
        assert ledger.uncommitted_size == 0
        assert db.get_ledger(AUDIT_LEDGER_ID).uncommitted_txns == []

    def test_multi_batch_revert_interleaved(self, db):
        wm, _ = make_managers(db)
        bootstrap_trustee(wm, pp=1)
        state = db.get_state(DOMAIN_LEDGER_ID)
        mid_root = state.head_hash
        wm.apply_batch(DOMAIN_LEDGER_ID,
                       [nym_req(TRUSTEE_DID, USER_DID, req_id=2)], 1001.0, 0, 2)
        assert state.head_hash != mid_root
        wm.revert_last_batch(DOMAIN_LEDGER_ID)
        assert state.head_hash == mid_root
        assert wm.uncommitted_batch_count == 1


class TestAuditLedger:
    def test_audit_snapshot_and_backrefs(self, db):
        wm, _ = make_managers(db)
        for i in range(3):
            r = bootstrap_trustee(wm, pp=i + 1) if i == 0 else \
                wm.apply_batch(DOMAIN_LEDGER_ID,
                               [nym_req(TRUSTEE_DID, f"did{i}xxxxxxxxxxxxxxxx",
                                        req_id=10 + i)],
                               1000.0 + i, 0, i + 1)[2]
            wm.commit_batch(ThreePcBatch(
                DOMAIN_LEDGER_ID, 0, i + 1, 1000.0 + i, (),
                bytes.fromhex(r["state_root"]), b"", b""))
        audit = db.get_ledger(AUDIT_LEDGER_ID)
        assert audit.size == 3
        last = audit_lib.last_audit_txn(audit)
        view_no, pp_seq_no, _ = audit_lib.last_audited_view(audit)
        assert (view_no, pp_seq_no) == (0, 3)
        # domain root is stored literally; pool root is a back-reference
        domain_root = audit_lib.resolve_ledger_root(audit, last, DOMAIN_LEDGER_ID)
        assert domain_root == db.get_ledger(DOMAIN_LEDGER_ID).root_hash.hex()
        pool_root = audit_lib.resolve_ledger_root(audit, last, POOL_LEDGER_ID)
        assert pool_root == db.get_ledger(POOL_LEDGER_ID).root_hash.hex()


class TestTaa:
    def _setup_taa(self, wm):
        roots1 = bootstrap_trustee(wm)
        taa = Request(TRUSTEE_DID, 5,
                      {"type": TXN_AUTHOR_AGREEMENT, "version": "1",
                       "text": "agree", "ratification_ts": 900},
                      signature="s")
        aml = Request(TRUSTEE_DID, 6,
                      {"type": TXN_AUTHOR_AGREEMENT_AML, "version": "1",
                       "aml": {"click": "desc"}}, signature="s")
        ok, rej, roots2 = wm.apply_batch(CONFIG_LEDGER_ID, [aml, taa],
                                         1001.0, 0, 2)
        assert len(ok) == 2, rej
        wm.commit_batch(ThreePcBatch(
            DOMAIN_LEDGER_ID, 0, 1, 1000.0, (),
            bytes.fromhex(roots1["state_root"]), b"", b""))
        wm.commit_batch(ThreePcBatch(
            CONFIG_LEDGER_ID, 0, 2, 1001.0, (),
            bytes.fromhex(roots2["state_root"]), b"", b""))

    def test_domain_write_requires_acceptance(self, db):
        wm, rm = make_managers(db)
        self._setup_taa(wm)
        ok, rej, _ = wm.apply_batch(
            DOMAIN_LEDGER_ID, [nym_req(TRUSTEE_DID, USER_DID, req_id=7)],
            1002.0, 0, 3)
        assert len(rej) == 1 and "agreement" in rej[0][1]
        acceptance = {"taaDigest": taa_digest("agree", "1"),
                      "mechanism": "click", "time": 1002}
        ok, rej, _ = wm.apply_batch(
            DOMAIN_LEDGER_ID,
            [nym_req(TRUSTEE_DID, USER_DID, req_id=8, taa=acceptance)],
            1003.0, 0, 4)
        assert len(ok) == 1, rej
        # read it back
        res = rm.get_result(Request("x", 9, {"type": "6"}))
        assert res["data"]["version"] == "1"

    def test_historic_taa_read_at_timestamp(self, db):
        """State-as-of-time-T: after TAA v1 (t=1001) and a v2 update
        (t=2000), GET_TAA at timestamp 1500 must return v1, at 2500 v2,
        and before any config batch None (ref
        get_txn_author_agreement_handler.py:46 + state_ts_store.py:38)."""
        wm, rm = make_managers(db)
        self._setup_taa(wm)
        taa2 = Request(TRUSTEE_DID, 7,
                       {"type": TXN_AUTHOR_AGREEMENT, "version": "2",
                        "text": "agree harder", "ratification_ts": 1900},
                       signature="s")
        ok, rej, roots = wm.apply_batch(CONFIG_LEDGER_ID, [taa2],
                                        2000.0, 0, 3)
        assert len(ok) == 1, rej
        wm.commit_batch(ThreePcBatch(
            CONFIG_LEDGER_ID, 0, 3, 2000.0, (),
            bytes.fromhex(roots["state_root"]), b"", b""))
        q = lambda ts: rm.get_result(
            Request("x", 9, {"type": "6", "timestamp": ts}))["data"]
        assert q(1500)["version"] == "1"
        assert q(2500)["version"] == "2"
        assert q(2000)["version"] == "2"    # equal-or-prev: equal hits
        assert q(500) is None               # before any config batch
        # latest (no timestamp) still reads the committed head
        res = rm.get_result(Request("x", 10, {"type": "6"}))
        assert res["data"]["version"] == "2"
        # AML as of time T rides the same root resolution
        aml = rm.get_result(
            Request("x", 11, {"type": "7", "timestamp": 1500}))["data"]
        assert aml is not None and aml["version"] == "1"
        assert rm.get_result(
            Request("x", 12, {"type": "7", "timestamp": 500}))["data"] is None

    def test_bad_mechanism_rejected(self, db):
        wm, _ = make_managers(db)
        self._setup_taa(wm)
        acceptance = {"taaDigest": taa_digest("agree", "1"),
                      "mechanism": "wave", "time": 1002}
        ok, rej, _ = wm.apply_batch(
            DOMAIN_LEDGER_ID,
            [nym_req(TRUSTEE_DID, USER_DID, req_id=8, taa=acceptance)],
            1003.0, 0, 3)
        assert len(rej) == 1 and "mechanism" in rej[0][1]


class TestReads:
    def test_get_nym_with_state_proof(self, db):
        wm, rm = make_managers(db)
        roots = bootstrap_trustee(wm)
        wm.commit_batch(ThreePcBatch(
            DOMAIN_LEDGER_ID, 0, 1, 1000.0, (),
            bytes.fromhex(roots["state_root"]), b"", b""))
        res = rm.get_result(Request("x", 1, {"type": "105",
                                             "dest": TRUSTEE_DID}))
        assert res["data"]["verkey"] == "vk"
        sp = res["state_proof"]
        state = db.get_state(DOMAIN_LEDGER_ID)
        from plenum_tpu.state.pruning_state import PruningState as PS
        from plenum_tpu.common.serialization import pack
        value = state.get(TRUSTEE_DID.encode(), committed=True)
        assert PS.verify_state_proof(bytes.fromhex(sp["root_hash"]),
                                     TRUSTEE_DID.encode(), value,
                                     bytes.fromhex(sp["proof_nodes"]))

    def test_get_txn_merkle_proof(self, db):
        wm, rm = make_managers(db)
        roots = bootstrap_trustee(wm)
        wm.commit_batch(ThreePcBatch(
            DOMAIN_LEDGER_ID, 0, 1, 1000.0, (),
            bytes.fromhex(roots["state_root"]), b"", b""))
        res = rm.get_result(Request("x", 1, {"type": "3", "data": 1,
                                             "ledgerId": DOMAIN_LEDGER_ID}))
        assert res["data"] is not None
        assert res["merkle_proof"] is not None


class TestExecutorSeam:
    def test_applied_batch_roots(self, db):
        wm, _ = make_managers(db)
        ex = LedgerBatchExecutor(wm)
        req = nym_req(TRUSTEE_DID, TRUSTEE_DID, role=TRUSTEE)
        applied = ex.apply_batch(DOMAIN_LEDGER_ID, [req], 1000.0, 0, 1)
        assert applied.valid_digests == (req.digest,)
        assert applied.state_root
        assert applied.txn_root
        assert applied.audit_txn_root
        assert ex.ledger_id_for(req) == DOMAIN_LEDGER_ID
        ex.revert_last_batch(DOMAIN_LEDGER_ID)
        assert wm.uncommitted_batch_count == 0


class TestAttribHandler:
    """ATTRIB write + GET_ATTR read (BASELINE config 2's second write type;
    indy-node semantics at the plenum layer — see handlers/attrib.py)."""

    def _managers(self, db):
        from plenum_tpu.execution.handlers.attrib import (
            ATTRIB_STORE_LABEL, AttribHandler, GetAttrHandler)
        wm, rm = make_managers(db)
        db.register_store(ATTRIB_STORE_LABEL, KvMemory())
        wm.register_handler(AttribHandler(db))
        rm.register_handler(GetAttrHandler(db))
        return wm, rm

    def _attrib_req(self, author, dest, raw=None, req_id=10, **extra):
        from plenum_tpu.execution.txn import ATTRIB
        op = {"type": ATTRIB, "dest": dest}
        if raw is not None:
            op["raw"] = raw
        op.update(extra)
        return Request(author, req_id, op, signature="sig")

    def test_owner_sets_attr_and_reads_it_back_with_proof(self, db):
        import json
        from plenum_tpu.execution.txn import GET_ATTR
        wm, rm = self._managers(db)
        bootstrap_trustee(wm)
        wm.apply_batch(DOMAIN_LEDGER_ID,
                       [nym_req(TRUSTEE_DID, USER_DID, req_id=2)],
                       pp_time=1001.0, view_no=0, pp_seq_no=2)
        req = self._attrib_req(USER_DID, USER_DID,
                               raw=json.dumps({"endpoint": "127.0.0.1:99"}))
        valid, rejected, _ = wm.apply_batch(DOMAIN_LEDGER_ID, [req],
                                            pp_time=1002.0, view_no=0,
                                            pp_seq_no=3)
        assert len(valid) == 1 and not rejected
        for seq in (1, 2, 3):
            wm.commit_batch(ThreePcBatch(
                ledger_id=DOMAIN_LEDGER_ID, view_no=0, pp_seq_no=seq,
                pp_time=1002.0, valid_digests=(req.digest,) if seq == 3
                else (),
                state_root=b"", txn_root=b"", audit_txn_root=b""))

        q = Request(USER_DID, 11, {"type": GET_ATTR, "dest": USER_DID,
                                   "attr_name": "endpoint"})
        result = rm.get_result(q)
        assert json.loads(result["data"]) == {"endpoint": "127.0.0.1:99"}
        assert result["meta"]["kind"] == "raw"
        assert result["state_proof"]["proof_nodes"]

    def test_stranger_cannot_set_attr(self, db):
        import json
        wm, _ = self._managers(db)
        bootstrap_trustee(wm)
        for did, rid in ((USER_DID, 2), (STEWARD_DID, 3)):
            wm.apply_batch(DOMAIN_LEDGER_ID,
                           [nym_req(TRUSTEE_DID, did, req_id=rid)],
                           pp_time=1001.0, view_no=0, pp_seq_no=rid)
        req = self._attrib_req(STEWARD_DID, USER_DID,
                               raw=json.dumps({"x": 1}))
        valid, rejected, _ = wm.apply_batch(DOMAIN_LEDGER_ID, [req],
                                            pp_time=1002.0, view_no=0,
                                            pp_seq_no=4)
        assert not valid and len(rejected) == 1

    def test_attr_on_unknown_did_rejected(self, db):
        import json
        wm, _ = self._managers(db)
        bootstrap_trustee(wm)
        req = self._attrib_req(TRUSTEE_DID, "ghostGhostGhostGhost11",
                               raw=json.dumps({"x": 1}))
        valid, rejected, _ = wm.apply_batch(DOMAIN_LEDGER_ID, [req],
                                            pp_time=1002.0, view_no=0,
                                            pp_seq_no=2)
        assert not valid and len(rejected) == 1

    def test_exactly_one_of_raw_enc_hash(self, db):
        import json
        wm, _ = self._managers(db)
        with pytest.raises(InvalidClientRequest):
            wm.static_validation(self._attrib_req(USER_DID, USER_DID))
        with pytest.raises(InvalidClientRequest):
            wm.static_validation(self._attrib_req(
                USER_DID, USER_DID, raw=json.dumps({"a": 1}), enc="blob"))
        with pytest.raises(InvalidClientRequest):
            wm.static_validation(self._attrib_req(
                USER_DID, USER_DID, raw=json.dumps({"a": 1, "b": 2})))
        wm.static_validation(self._attrib_req(USER_DID, USER_DID,
                                              enc="ciphertextblob", req_id=1))


class TestTxnVersionDispatch:
    """Version-keyed handler selection (ref txn_version_controller.py:1,
    write_request_manager.py:113): a v2-format handler serves payloads
    carrying ver="2"; unversioned payloads keep flowing through the
    default handler — no flag day."""

    def _wm_with_v2(self, db):
        from plenum_tpu.common.serialization import unpack as _unpack
        from plenum_tpu.execution.handlers.nym import (NymHandler,
                                                       nym_state_key)

        class NymV2Handler(NymHandler):
            """v2 payload: requires a 'diddoc' field and records it."""

            def static_validation(self, request):
                super().static_validation(request)
                self._require(isinstance(request.operation.get("diddoc"),
                                         str), request,
                              "NYM v2 needs a diddoc")

            def gen_txn(self, request):
                txn = super().gen_txn(request)
                txn["txn"]["data"]["diddoc"] = request.operation["diddoc"]
                return txn

        wm, _ = make_managers(db)
        wm.register_handler(NymV2Handler(db), version="2")
        return wm, nym_state_key, _unpack

    def test_both_versions_apply_through_their_handlers(self, db):
        wm, nym_state_key, _unpack = self._wm_with_v2(db)
        bootstrap_trustee(wm)
        # v1 (no ver field): default handler, no diddoc requirement
        ok, rej, _ = wm.apply_batch(
            DOMAIN_LEDGER_ID, [nym_req(TRUSTEE_DID, USER_DID, req_id=2)],
            1001.0, 0, 2)
        assert len(ok) == 1 and not rej
        # v2 payload without the new field: NACKed by the v2 handler's
        # static validation (the client-intake seam); the v1 payload above
        # sailed through because it routed to the default handler
        op = {"type": NYM, "dest": "v2dest1111", "verkey": "vk", "ver": "2"}
        bad = Request(TRUSTEE_DID, 3, op, signature="sig")
        with pytest.raises(InvalidClientRequest, match="diddoc"):
            wm.static_validation(bad)
        # well-formed v2 payload: applied by the v2 handler, txn stamped
        op = dict(op, diddoc="doc-123")
        good = Request(TRUSTEE_DID, 4, op, signature="sig")
        ok, rej, _ = wm.apply_batch(DOMAIN_LEDGER_ID, [good], 1003.0, 0, 3)
        assert len(ok) == 1 and not rej, rej
        from plenum_tpu.execution import txn as txn_lib
        raw = db.get_state(DOMAIN_LEDGER_ID).get(
            nym_state_key("v2dest1111"), committed=False)
        assert _unpack(raw)["verkey"] == "vk"

    def test_version_stamp_survives_committed_replay(self, db):
        """A v2-minted txn re-applied via the catchup path must dispatch
        to the v2 handler again (the txn carries its format version)."""
        wm, nym_state_key, _unpack = self._wm_with_v2(db)
        bootstrap_trustee(wm)
        op = {"type": NYM, "dest": "v2dest2222", "verkey": "vk",
              "ver": "2", "diddoc": "doc-xyz"}
        req = Request(TRUSTEE_DID, 5, op, signature="sig")
        ok, _, roots = wm.apply_batch(DOMAIN_LEDGER_ID, [req], 1004.0, 0, 2)
        assert len(ok) == 1
        batch = ThreePcBatch(
            DOMAIN_LEDGER_ID, 0, 2, 1004.0, (req.digest,),
            bytes.fromhex(roots["state_root"]),
            bytes.fromhex(roots["txn_root"]),
            bytes.fromhex(roots["audit_txn_root"]))
        wm.commit_batch(ThreePcBatch(
            DOMAIN_LEDGER_ID, 0, 1, 1000.0, (),
            db.get_state(DOMAIN_LEDGER_ID).head_hash,
            b"", b""))
        committed = wm.commit_batch(batch)
        # payload-level stamp (ref get_payload_txn_version); the envelope
        # "ver" stays "1" — it is the txn FORMAT version, not the payload's
        assert committed and committed[0]["txn"].get("ver") == "2"
        assert committed[0].get("ver") == "1"
        # replay into a FRESH db through apply_committed_txn
        db2 = make_db()
        wm2 = self._wm_with_v2(db2)[0]
        for txn in committed:
            wm2.apply_committed_txn(DOMAIN_LEDGER_ID, dict(txn))
        raw = db2.get_state(DOMAIN_LEDGER_ID).get(
            nym_state_key("v2dest2222"), committed=True)
        assert _unpack(raw)["verkey"] == "vk"
