"""Tracing plane: span events, flight recorder, waterfall assembly,
NullTracer disabled-cost budget, and the metrics satellites this PR
shipped with it (nearest-rank percentile fix, deterministic reservoir
sampling).
"""
from __future__ import annotations

import json
import time

import pytest

from plenum_tpu.common.metrics import SAMPLE_CAP, Accumulator, percentile
from plenum_tpu.common.node_messages import Reply
from plenum_tpu.common.tracing import NULL_TRACER, Tracer, span_sequence
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.tools.trace_report import (assemble, attribution_summary,
                                           summarize)

from test_pool import Pool, signed_nym


# --- metrics satellites -----------------------------------------------------

def test_percentile_nearest_rank_pins():
    """Nearest-rank: rank = ceil(q*n); the old int(q*n) sat one rank high
    for every integral q*n (p50 of 4 values returned the 3rd)."""
    assert percentile([1, 2, 3, 4], 0.5) == 2
    assert percentile([1, 2, 3, 4], 0.25) == 1
    assert percentile([1, 2, 3, 4], 0.75) == 3
    assert percentile([1, 2, 3, 4], 1.0) == 4
    assert percentile([1, 2, 3, 4], 0.0) == 1
    assert percentile([7], 0.95) == 7
    assert percentile(list(range(1, 101)), 0.5) == 50
    assert percentile(list(range(1, 101)), 0.95) == 95
    assert percentile(list(range(1, 101)), 1.0) == 100
    assert percentile([3, 1, 2], 0.5) == 2          # unsorted input
    assert percentile([], 0.5) is None


def test_accumulator_reservoir_is_deterministic_and_unbiased():
    """Samples are a seeded reservoir over the WHOLE interval: the same
    add() sequence reproduces the same set (replay-stable), and events
    past the first SAMPLE_CAP are represented — the old first-N sampling
    kept zero of them, over-weighting cold-start costs in every p95."""
    stream = [float(v) for v in range(SAMPLE_CAP * 4)]
    a1 = Accumulator(keep_samples=True, seed=7)
    a2 = Accumulator(keep_samples=True, seed=7)
    for v in stream:
        a1.add(v)
        a2.add(v)
    assert a1.samples == a2.samples
    assert len(a1.samples) == SAMPLE_CAP
    tail = sum(1 for v in a1.samples if v >= SAMPLE_CAP)
    # uniform reservoir over 4x CAP events: ~75% expected from the tail;
    # first-N sampling would have exactly 0
    assert tail > SAMPLE_CAP // 2, tail
    a3 = Accumulator(keep_samples=True, seed=8)
    for v in stream:
        a3.add(v)
    assert a3.samples != a1.samples                 # seeds decorrelate
    # fold stats unaffected by sampling
    assert a1.count == len(stream) and a1.max == stream[-1]


# --- NullTracer disabled-cost budget ----------------------------------------

def test_null_tracer_disabled_cost_microbench():
    """The acceptance budget: tracing disabled must cost <=2% TPS. Every
    hot-path site is `if tracer.enabled: tracer.emit(...)` with
    NullTracer.enabled a class attribute — measure that exact pattern and
    assert the per-request total (~12 guarded sites fire per ordered txn)
    stays under 2% of a 1 ms/txn budget (the 4-node sim spends 3-5 ms of
    CPU per txn; 1 ms is a conservative floor, so passing here passes the
    bench A/B with margin)."""
    tracer = NULL_TRACER
    assert tracer.enabled is False
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        if tracer.enabled:
            tracer.emit("stage", "key", None)
    per_site = (time.perf_counter() - t0) / n
    sites_per_txn = 12
    budget = 0.02 * 0.001       # 2% of 1 ms
    assert per_site * sites_per_txn < budget, \
        f"{per_site * 1e9:.0f} ns/site x {sites_per_txn} sites " \
        f"exceeds {budget * 1e6:.0f} us/txn"


# --- flight recorder mechanics ----------------------------------------------

def test_flight_recorder_ring_bounds_and_auto_dump(tmp_path):
    clock = {"t": 0.0}
    tr = Tracer("N1", lambda: clock["t"], ring_size=8,
                dump_dir=str(tmp_path), min_dump_interval=5.0)
    for i in range(20):
        tr.emit("stage", f"k{i}")
    assert len(tr.ring) == 8                        # bounded
    tr.anomaly("suspicion", {"code": 1})            # auto-dump fires
    tr.anomaly("suspicion", {"code": 2})            # debounced away
    dumps = sorted(tmp_path.glob("N1-flight-*.json"))
    assert len(dumps) == 1
    clock["t"] = 10.0
    tr.anomaly("suspicion", {"code": 3})            # past the debounce
    assert len(sorted(tmp_path.glob("N1-flight-*.json"))) == 2
    snap = json.loads(dumps[0].read_text())
    assert snap["node"] == "N1"
    assert len(snap["events"]) == 8
    assert snap["events"][-1][1] == "anomaly.suspicion"
    assert snap["anomalies"] == 1                   # at dump time


def test_breaker_transitions_reach_flight_recorder():
    """CircuitBreaker.on_transition (the hook the node installs) lands
    every state change in the ring as an anomaly."""
    from plenum_tpu.parallel.supervisor import CircuitBreaker
    tr = Tracer("N", lambda: 0.0)
    br = CircuitBreaker(fail_threshold=2, cooldown=1.0, now=lambda: 0.0)
    br.on_transition = lambda old, new: tr.anomaly(
        "breaker", {"from": old, "to": new})
    br.record_failure()
    br.record_failure()                             # -> open
    br.to_half_open()
    br.close()
    hops = [(e[3]["from"], e[3]["to"]) for e in tr.ring
            if e[1] == "anomaly.breaker"]
    assert hops == [("closed", "open"), ("open", "half_open"),
                    ("half_open", "closed")]


# --- end-to-end: 4-node sim waterfall ---------------------------------------

def _order_one_traced(pool, req):
    """Submit and run until a Reply lands; -> (t_submit, t_reply) sim
    times measured the way a client would."""
    t0 = pool.timer.get_current_time()
    pool.submit(req)
    for _ in range(1000):
        for node in pool.nodes.values():
            node.prod()
        if any(isinstance(m, Reply)
               for m, _ in pool.client_msgs[pool.names[0]]):
            return t0, pool.timer.get_current_time()
        pool.timer.advance(0.01)
    raise AssertionError("request never ordered")


def test_sim_waterfall_stage_sum_matches_e2e():
    """The tentpole acceptance shape on the deterministic sim: every node
    produces a full per-request waterfall, stage sums telescope to within
    10% of the measured end-to-end latency, and pool-level attribution
    reports p50/p95 for each stage including cross-node network time."""
    pool = Pool()
    user = Ed25519Signer(seed=b"waterfall-user".ljust(32, b"\0"))
    req = signed_nym(pool.trustee, user, 1)
    t_submit, t_reply = _order_one_traced(pool, req)
    e2e = t_reply - t_submit
    assert e2e > 0
    pool.run(3.0)       # let the slower replicas finish their own commits

    report = assemble([pool.nodes[n].tracer.snapshot()
                       for n in pool.names])
    assert req.digest in report["requests"]
    per_node = report["requests"][req.digest]
    assert set(per_node) == set(pool.names)         # every node's view
    for node_name, wf in per_node.items():
        for stage in ("crypto", "propagate", "queue", "ordering",
                      "durable", "reply"):
            assert stage in wf["stages"], (node_name, wf["stages"])
        # stages telescope: their sum IS the node's ingress->reply span
        assert wf["total"] == pytest.approx(wf["end"] - wf["start"],
                                            abs=1e-9), node_name
    # the node whose client reply defined the measured e2e: stage sum
    # within 10% (+1 prod step of measurement granularity)
    wf = per_node[pool.names[0]]
    assert abs(wf["total"] - e2e) <= 0.1 * e2e + 0.011, (wf["total"], e2e)
    att = attribution_summary(report)
    for stage in ("network", "crypto", "propagate", "queue", "ordering",
                  "durable", "reply", "apply_wall", "durable_wall"):
        assert stage in att, sorted(att)
        assert att[stage]["p50_ms"] >= 0
        assert att[stage]["p95_ms"] >= att[stage]["p50_ms"]
    # the compact bench-line summary rides the same report
    summary = summarize(report)
    assert summary["requests_traced"] == 1
    # a clamped out-of-order stage (a replica can admit the pre-prepare
    # before its own propagate quorum) may shave the ratio slightly
    assert summary["stage_sum_ratio_p50"] == pytest.approx(1.0, abs=0.02)


def test_anomalies_recorded_across_view_change():
    """A primary blackout's story lands in the flight recorder: VC start
    + completion anomalies on the survivors, and the assembled report's
    anomaly timeline carries them in order."""
    from plenum_tpu.config import Config
    pool = Pool(config=Config(Max3PCBatchWait=0.05,
                              PRIMARY_HEALTH_CHECK_FREQ=0.5,
                              ORDERING_PROGRESS_TIMEOUT=2.0,
                              STATE_FRESHNESS_UPDATE_INTERVAL=3.0,
                              VIEW_CHANGE_TIMEOUT=8.0,
                              NEW_VIEW_TIMEOUT=4.0))
    from plenum_tpu.network import Discard, match_dst, match_frm
    primary = pool.nodes["Alpha"].master_replica.data.primary_name
    pool.net.add_rule(Discard(), match_dst(primary))
    pool.net.add_rule(Discard(), match_frm(primary))
    user = Ed25519Signer(seed=b"vc-anomaly-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 1),
                to=[n for n in pool.names if n != primary])
    pool.run(25.0)
    survivors = [n for n in pool.names if n != primary]
    assert all(pool.nodes[n].master_replica.view_no >= 1
               for n in survivors)
    report = assemble([pool.nodes[n].tracer.snapshot()
                       for n in survivors])
    kinds = [k for (_t, _n, k, _d) in report["anomalies"]]
    assert "view_change_start" in kinds
    assert "view_change_complete" in kinds
    # completion never precedes the first start in the aligned timeline
    assert kinds.index("view_change_start") \
        < kinds.index("view_change_complete")


# --- tooling smoke (the tier-1 CI satellite) --------------------------------

def test_trace_report_check_smoke(capsys):
    """`trace_report --check` assembles a synthetic two-node fixture with
    skewed wall anchors and asserts alignment + waterfall invariants —
    the tier-1 smoke for the assembly path."""
    from plenum_tpu.tools.trace_report import main
    assert main(["--check"]) == 0
    out = json.loads(capsys.readouterr().out.strip())
    assert out["check"] == "ok"
    assert not out["problems"]


def test_log_analyzer_ingests_flight_dumps(tmp_path):
    """log_analyzer merges flight-recorder anomaly timelines (wall-
    aligned, deduplicated across a dump series) into its per-view
    report next to the spylog-sourced events."""
    from plenum_tpu.tools.log_analyzer import analyze_node
    node_dir = tmp_path / "Node1"
    node_dir.mkdir()
    rows = [{"t": 100.0, "event": "suspicion", "data": [13, "Beta"]},
            {"t": 101.0, "event": "view_change_complete", "data": 1},
            {"t": 102.0, "event": "executed", "data": [1, 1]}]
    (node_dir / "events.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows))
    dump = {"node": "Node1", "clock_domain": "wall", "mono_anchor": 0.0,
            "wall_anchor": 100.0, "dumped_at": 3.0, "anomalies": 2,
            "events": [
                [0.2, "pp_sent", "b" * 8, {"seq": 1, "reqs": []}],
                [0.5, "anomaly.breaker", "",
                 {"from": "closed", "to": "open"}],
                [2.5, "anomaly.catchup", "", None]]}
    (node_dir / "Node1-flight-0.json").write_text(json.dumps(dump))
    # a second overlapping dump (auto-dump cascade) must not double-count
    (node_dir / "Node1-flight-1.json").write_text(json.dumps(dump))

    rep = analyze_node(str(node_dir))
    assert rep["flight_anomalies"] == 2
    assert rep["event_counts"]["flight.breaker"] == 1
    assert rep["event_counts"]["flight.catchup"] == 1
    # wall-aligned: breaker (100.5) falls in the view-0 segment, catchup
    # (102.5) after the view change -> view-1 segment
    assert rep["views"][0]["events"].get("flight.breaker") == 1
    assert rep["views"][1]["events"].get("flight.catchup") == 1


def test_waterfall_out_of_order_points_stay_disjoint():
    """A replica can admit the PRE-PREPARE before its OWN propagate
    quorum completes; the waterfall must not re-count the overlap into
    the ordering stage — stage sums always telescope to the observed
    first->last span (regression: overlapping stages inflated totals
    past end-start and poisoned the 10% acceptance ratio)."""
    req, batch = "r" * 8, "b" * 8
    dump = {"node": "N", "clock_domain": "shared", "mono_anchor": 0.0,
            "wall_anchor": None, "dumped_at": 20.0, "anomalies": 0,
            "events": [
                [1.0, "ingress", req, None],
                [2.0, "auth", req, {"ok": True}],
                # pp arrives at t=3, BEFORE the local quorum at t=5
                [3.0, "pp_recv", batch, {"seq": 1, "reqs": [req]}],
                [5.0, "propagate_quorum", req, {"votes": 2}],
                [10.0, "ordered", batch, {"seq": 1}],
                [11.0, "durable", "", {"seqs": [1]}],
                [13.0, "reply", req, {"seq": 1}]]}
    report = assemble([dump])
    wf = report["requests"][req]["N"]
    assert wf["total"] == pytest.approx(wf["end"] - wf["start"], abs=1e-9)
    assert wf["total"] == pytest.approx(12.0, abs=1e-9)   # 13 - 1
    assert wf["stages"]["queue"] == 0.0                   # clamped
    # ordering starts where the covered prefix ends (t=5), not at pp t=3
    assert wf["stages"]["ordering"] == pytest.approx(5.0, abs=1e-9)


def test_span_sequence_canonical():
    tr = Tracer("N", lambda: 1.5)
    tr.emit("ingress", "d1", {"frm": "cli"})
    a = span_sequence(tr.snapshot())
    b = span_sequence(tr.snapshot())
    assert a == b and b"ingress" in a
    assert span_sequence(None) == b""
