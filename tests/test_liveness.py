"""Liveness tests: the pool recovers from dead, stalled, and malicious
primaries WITHOUT any manual vote injection.

Mirrors the reference's primary-disconnect / freshness / suspicion scenarios
(plenum/server/consensus/monitoring/, ordering_service.py:1991,
node.py:2854-2944) over SimNetwork fault injection.
"""
import pytest

from plenum_tpu.common.internal_messages import RaisedSuspicion
from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID, Propagate
from plenum_tpu.common.suspicion_codes import Suspicions
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.network import Discard, match_dst, match_frm

from test_pool import Pool, signed_nym

FAST = dict(Max3PCBatchWait=0.05,
            PRIMARY_HEALTH_CHECK_FREQ=0.5,
            ORDERING_PROGRESS_TIMEOUT=2.0,
            STATE_FRESHNESS_UPDATE_INTERVAL=3.0)


def fast_pool(seed=13, **overrides):
    return Pool(seed=seed, config=Config(**{**FAST, **overrides}))


def cut_off(pool, name):
    return [pool.net.add_rule(Discard(), match_dst(name)),
            pool.net.add_rule(Discard(), match_frm(name))]


def healthy(pool, victim):
    return [n for n in pool.names if n != victim]


def test_dead_primary_triggers_view_change():
    """Cut off the view-0 primary with client traffic pending: the ordering-
    progress watchdog votes, f+1 InstanceChanges start a view change, and the
    pool orders under the new primary — no manual vote injection."""
    pool = fast_pool(seed=13)
    primary = pool.nodes["Alpha"].master_replica.data.primary_name
    assert primary == "Alpha"
    cut_off(pool, primary)

    user = Ed25519Signer(seed=b"dead-primary-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1),
                to=healthy(pool, primary))
    pool.run(20.0)

    for n in healthy(pool, primary):
        node = pool.nodes[n]
        assert node.master_replica.view_no >= 1, \
            f"{n} never left view 0"
        assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2, \
            f"{n} did not order the pending request after the view change"
    roots = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
             for n in healthy(pool, primary)}
    assert len(roots) == 1


def test_quiescent_dead_primary_detected_via_freshness():
    """No client traffic at all: freshness silence alone must out the dead
    primary (ref STATE_SIGS_ARE_NOT_UPDATED / freshness batches)."""
    pool = fast_pool(seed=17)
    cut_off(pool, "Alpha")
    pool.run(15.0)
    for n in healthy(pool, "Alpha"):
        assert pool.nodes[n].master_replica.view_no >= 1, \
            f"{n} never detected the silent dead primary"

    # and the pool still works
    user = Ed25519Signer(seed=b"quiescent-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1),
                to=healthy(pool, "Alpha"))
    pool.run(8.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in healthy(pool, "Alpha")}
    assert sizes == {2}


def test_primary_disconnect_votes_within_disconnect_timeout():
    """CONNECTION LOSS to the primary triggers the view-change vote within
    PRIMARY_DISCONNECT_TIMEOUT — seconds — without waiting out the (here
    deliberately enormous) ordering-stall and freshness windows (ref
    primary_connection_monitor_service.py + ToleratePrimaryDisconnection)."""
    pool = fast_pool(seed=19,
                     PRIMARY_DISCONNECT_TIMEOUT=2.0,
                     ORDERING_PROGRESS_TIMEOUT=300.0,
                     STATE_FRESHNESS_UPDATE_INTERVAL=300.0)
    primary = pool.nodes["Alpha"].master_replica.data.primary_name
    assert primary == "Alpha"
    # crash_node drops the peer from the fabric -> Disconnected events on
    # every survivor (cut_off would only drop messages, not the connection)
    pool.crash_node(primary)

    user = Ed25519Signer(seed=b"disc-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1),
                to=healthy(pool, primary))
    pool.run(10.0)      # << 300s: only the disconnect path can have fired

    for n in healthy(pool, primary):
        node = pool.nodes[n]
        assert node.master_replica.view_no >= 1, \
            f"{n} never voted on primary disconnect"
        assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2, \
            f"{n} did not order after the fast view change"


def test_wedged_backup_instance_removed_then_restored():
    """A backup instance whose primary stops ordering is detected (queued
    work, no 3PC progress), voted out by an f+1 BackupInstanceFaulty
    quorum on every node, and re-created fresh by the next view change
    (ref backup_instance_faulty_processor.py + node.py:2580-2596)."""
    pool = fast_pool(seed=23,
                     BACKUP_INSTANCE_FAULTY_CHECK_FREQ=0.5,
                     BACKUP_INSTANCE_FAULTY_TIMEOUT=2.0)
    # wedge instance 1 pool-wide by muting its primary's ordering service
    backup_primary = None
    for node in pool.nodes.values():
        r1 = node.replicas[1]
        if r1.is_primary:
            backup_primary = node.name
            r1.ordering.service = lambda: None
    assert backup_primary is not None

    user = Ed25519Signer(seed=b"wedge-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(10.0)

    for name, node in pool.nodes.items():
        assert 1 not in node.replicas, \
            f"{name} never removed the wedged backup instance"
        assert ("backup_instance_removed", 1) in node.spylog
        # master kept ordering throughout
        assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2

    # a view change (here: master primary goes quiet with work pending)
    # re-creates the removed backup fresh
    cut_off(pool, "Alpha")
    user2 = Ed25519Signer(seed=b"wedge-user-2".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user2, req_id=2),
                to=healthy(pool, "Alpha"))
    pool.run(20.0)
    for n in healthy(pool, "Alpha"):
        node = pool.nodes[n]
        assert node.master_replica.view_no >= 1
        assert 1 in node.replicas, f"{n} did not restore the backup"
        assert node.replicas[1].view_no == node.master_replica.view_no


def test_malicious_primary_wrong_state_root():
    """The primary lies about the state root: validators' re-apply catches it
    (PPR_STATE_WRONG), the suspicion becomes a view-change vote, and the pool
    re-orders the batch honestly under the next primary."""
    pool = fast_pool(seed=19)
    alpha = pool.nodes["Alpha"]
    orig_apply = alpha.master_replica.ordering._apply

    def corrupt(ledger_id, reqs, pp_time, view_no, pp_seq_no):
        applied = orig_apply(ledger_id, reqs, pp_time, view_no, pp_seq_no)
        return applied._replace(state_root="00" * 32)

    alpha.master_replica.ordering._apply = corrupt

    user = Ed25519Signer(seed=b"malicious-primary".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(20.0)

    suspicions = [e for n in pool.names for e in pool.nodes[n].spylog
                  if e[0] == "suspicion"
                  and e[1][0] == Suspicions.PPR_STATE_WRONG.code]
    assert suspicions, "no validator caught the wrong state root"
    for n in pool.names:
        node = pool.nodes[n]
        assert node.master_replica.view_no >= 1, f"{n} never left view 0"
        assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2, \
            f"{n} did not order the request after the view change"
    roots = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
             for n in pool.names}
    assert len(roots) == 1, "pool diverged after malicious primary"


def test_freshness_batches_keep_signatures_fresh():
    """An idle pool still orders empty freshness batches on state-bearing
    ledgers so BLS state signatures stay fresh (ref :1991)."""
    pool = fast_pool(seed=23)
    pool.run(10.0)
    for n in pool.names:
        node = pool.nodes[n]
        assert node.master_replica.last_ordered_3pc[1] >= 2, \
            f"{n} ordered no freshness batches while idle"
        # freshness batches are empty: no ledger growth
        assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 1
    # the pool is still perfectly writable afterwards
    user = Ed25519Signer(seed=b"fresh-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(6.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}


def test_suspicion_routing_blacklist_and_primary_fault():
    """Unit probe of the suspicion router: peer misbehavior blacklists (and
    ingress drops the peer's traffic); primary-authored faults become votes."""
    pool = fast_pool(seed=29)
    beta = pool.nodes["Beta"]

    # unambiguous peer misbehavior -> blacklist + ingress drop
    beta._on_suspicion(RaisedSuspicion(
        inst_id=0, code=Suspicions.PPR_FRM_NON_PRIMARY.code,
        reason="pre-prepare from non-primary", sender="Gamma"))
    assert beta.blacklister.is_blacklisted("Gamma")
    before = len(beta._propagate_inbox)
    beta.node_bus.process_incoming(
        Propagate(request={"x": 1}, sender_client=None), "Gamma")
    assert len(beta._propagate_inbox) == before, \
        "blacklisted peer's traffic reached the node"

    # primary-authored fault -> view-change vote recorded, no blacklist
    primary = beta.master_replica.data.primary_name
    beta._on_suspicion(RaisedSuspicion(
        inst_id=0, code=Suspicions.PPR_STATE_WRONG.code,
        reason="root mismatch", sender=primary))
    assert not beta.blacklister.is_blacklisted(primary)
    votes = beta.master_replica.vc_trigger._votes
    assert any("Beta" in voters for voters in votes.values()), \
        f"no vote recorded: {votes}"


def test_degraded_master_voted_out_by_monitor():
    """The RBFT monitor compares master vs backup instance throughput: stall
    the master instance's 3PC traffic while backups keep ordering, and the
    DELTA ratio check must vote the master out (ref monitor.py:425-492)."""
    from plenum_tpu.common.node_messages import Commit, PrePrepare, Prepare
    # The watchdog timeout is long enough that the MONITOR fires first (its
    # EMA warms up in ~5s) but still live: after the view change the new
    # primary's first batch may be lost to the still-active stall rule, and
    # recovering THAT is the ordering-progress watchdog's job.
    pool = fast_pool(seed=37,
                     ORDERING_PROGRESS_TIMEOUT=8.0,
                     STATE_FRESHNESS_UPDATE_INTERVAL=600.0,
                     PerfCheckFreq=1.0,
                     throughput_first_ts_window=2.0)
    rule = pool.net.add_rule(
        Discard(),
        lambda m, f, d: isinstance(m, (PrePrepare, Prepare, Commit))
        and getattr(m, "inst_id", None) == 0)

    for i in range(12):
        user = Ed25519Signer(seed=f"deg{i}".encode().ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, user, req_id=i + 1))
        pool.run(0.5)
    pool.run(8.0)

    degraded = [n for n in pool.names
                if any(e[0] == "master_degraded" for e in pool.nodes[n].spylog)]
    assert degraded, "no node's monitor flagged the degraded master"
    for n in pool.names:
        assert pool.nodes[n].master_replica.view_no >= 1, \
            f"{n}: degraded master never voted out"

    # with the stall lifted the pool orders the backlog (possibly one more
    # watchdog-driven view change later, if the new primary's first batch
    # was sent while the stall rule still held)
    pool.net.remove_rule(rule)
    pool.run(25.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {13}, sizes


def test_own_node_never_blacklisted():
    pool = fast_pool(seed=31)
    beta = pool.nodes["Beta"]
    beta._on_suspicion(RaisedSuspicion(
        inst_id=0, code=Suspicions.PPR_FRM_NON_PRIMARY.code,
        reason="", sender="Beta"))
    assert not beta.blacklister.is_blacklisted("Beta")


def test_equivocating_primary_detected():
    """A primary sending TWO different PRE-PREPAREs for the same (view,
    seq) is detected (DUPLICATE_PPR_SENT): honest nodes keep the first,
    suspect the primary, and the pool's ledgers never diverge."""
    from plenum_tpu.common.node_messages import PrePrepare

    pool = fast_pool(seed=23)
    user = Ed25519Signer(seed=b"equivocate".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    # advance just past the batch cut so the PP is IN FLIGHT, not ordered
    # (a duplicate of an already-ordered seq is discarded as stale, which
    # is correct but not what this test is about)
    for _ in range(4):
        pool.run(0.06)
        if pool.nodes["Beta"].master_replica.ordering.prePrepares:
            break

    beta = pool.nodes["Beta"]
    honest = beta.master_replica.ordering.prePrepares
    assert honest, "no pre-prepare in flight yet"
    (view_no, pp_seq_no), pp = next(iter(honest.items()))
    twin = PrePrepare(**{**{f: getattr(pp, f) for f in pp.__dataclass_fields__},
                         "digest": "ff" * 16})
    for victim in ("Beta", "Gamma"):
        pool.nodes[victim].master_replica.ordering.process_preprepare(
            twin, "Alpha")
    pool.run(5.0)

    suspicions = [e for n in ("Beta", "Gamma")
                  for e in pool.nodes[n].spylog
                  if e[0] == "suspicion"
                  and e[1][0] == Suspicions.DUPLICATE_PPR_SENT.code]
    assert suspicions, "equivocation not suspected"
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}
    roots = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
             for n in pool.names}
    assert len(roots) == 1, "pool diverged under equivocation"


def test_lost_quorum_connectivity_resyncs_on_reconnect():
    """A node that HAD consensus connectivity and then drops below the
    weak quorum (ref inconsistency_watchers.py:5 fires a restart there)
    marks itself inconsistent and catches up as soon as enough peers are
    back — and the pool orders again afterwards."""
    pool = Pool(seed=31)
    user = Ed25519Signer(seed=b"nw-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(5.0)
    for n in pool.nodes.values():
        assert n.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2

    # 4-node pool, f=1: weak quorum is 2 connected peers. Crashing Beta
    # and Gamma leaves Alpha/Delta with ONE peer each -> watcher fires.
    pool.crash_node("Beta")
    pool.crash_node("Gamma")
    pool.run(1.0)
    for n in ("Alpha", "Delta"):
        events = [e for e, _ in pool.nodes[n].spylog]
        assert "lost_quorum_connectivity" in events, n
        assert pool.nodes[n]._needs_resync, n

    # peers return (fresh from genesis, as after a restart): the survivors
    # must resync via catchup, not keep trusting their own liveness view
    pool.start_node("Beta")
    pool.start_node("Gamma")
    pool.net.connect_all()
    # a restarting node catches up at boot (what tools/start_node does);
    # the point under test is that the SURVIVORS resync too
    pool.nodes["Beta"].start_catchup()
    pool.nodes["Gamma"].start_catchup()
    pool.run(10.0)
    for n in ("Alpha", "Delta"):
        events = [e for e, _ in pool.nodes[n].spylog]
        assert "resync_after_partition" in events, n
        assert not pool.nodes[n]._needs_resync, n

    # liveness proof: the healed pool orders a new request everywhere
    user2 = Ed25519Signer(seed=b"nw-user2".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user2, req_id=2))
    pool.run(10.0)
    for name, node in pool.nodes.items():
        assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 3, name


def test_vc_stall_phases_are_recorded():
    """The view-change stall decomposition (VERDICT r4 item 5) stamps
    detect -> vote -> start -> new_view -> order and emits phase metrics;
    the detect->vote wait must track PRIMARY_DISCONNECT_TIMEOUT."""
    pool = fast_pool(seed=23,
                     PRIMARY_DISCONNECT_TIMEOUT=2.0,
                     ORDERING_PROGRESS_TIMEOUT=300.0,
                     STATE_FRESHNESS_UPDATE_INTERVAL=300.0)
    primary = pool.nodes["Alpha"].master_replica.data.primary_name
    pool.crash_node(primary)
    user = Ed25519Signer(seed=b"vcphase".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1),
                to=healthy(pool, primary))
    pool.run(15.0)
    n = pool.nodes[healthy(pool, primary)[0]]
    phases = [p for e, p in n.spylog if e == "vc_stall_phases"]
    assert phases, "no completed stall episode recorded"
    ts = phases[0]
    assert set(ts) >= {"detect", "vote", "start", "new_view", "order"}, ts
    assert ts["detect"] <= ts["vote"] <= ts["start"] \
        <= ts["new_view"] <= ts["order"]
    # detection wait ~= the configured tolerance (MockTimer steps 0.1s)
    assert 1.9 <= ts["vote"] - ts["detect"] <= 2.7, ts


def test_straggler_recheck_avoids_spurious_catchup():
    """An ordinary view change must NOT trigger the straggler catchup:
    ViewChange/NewView chatter for my+1 is excluded from evidence, and
    the deferred callback re-verifies the lag at fire time."""
    pool = fast_pool(seed=41)
    primary = pool.nodes["Alpha"].master_replica.data.primary_name
    cut_off(pool, primary)
    user = Ed25519Signer(seed=b"recheck".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1),
                to=healthy(pool, primary))
    pool.run(20.0)
    for n in healthy(pool, primary):
        node = pool.nodes[n]
        assert node.master_replica.view_no >= 1
        # the single-step view change produced no straggler resync
        assert not [e for e in node.spylog if e[0] == "straggler_resync"], n
        assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2, n


def test_stuck_behind_resync_rejoins_mid_view():
    """A node isolated while the pool orders PAST it (same view, below
    CHK_FREQ) must detect the commit quorum ahead of its stagnant
    position and resync without any view change."""
    pool = fast_pool(seed=43,
                     STUCK_BEHIND_CHECK_FREQ=1.0,
                     ORDERING_PROGRESS_TIMEOUT=300.0,
                     STATE_FRESHNESS_UPDATE_INTERVAL=300.0,
                     PRIMARY_DISCONNECT_TIMEOUT=300.0)
    primary = pool.nodes["Alpha"].master_replica.data.primary_name
    assert primary == "Alpha"
    victim = "Delta"
    rules = cut_off(pool, victim)
    users = [Ed25519Signer(seed=(b"sb%d" % i).ljust(32, b"\0"))
             for i in range(3)]
    for i, u in enumerate(users):
        pool.submit(signed_nym(pool.trustee, u, req_id=i + 1),
                    to=healthy(pool, victim))
        pool.run(2.0)
    # pool ordered 3 txns without the victim
    assert pool.nodes["Alpha"].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 4
    assert pool.nodes[victim].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 1
    for r in rules:
        pool.net.remove_rule(r)
    # heal: new traffic flows; the victim sees commits ahead of its
    # stagnant position and resyncs WITHOUT a view change
    u4 = Ed25519Signer(seed=b"sb-late".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u4, req_id=9))
    pool.run(15.0)
    node = pool.nodes[victim]
    assert [e for e in node.spylog if e[0] == "stuck_behind_resync"], \
        "victim never detected the quorum ahead of it"
    assert node.master_replica.view_no == 0      # no view change happened
    assert node.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 5
