"""Catchup tests: a cut-off node syncs ledgers+state and rejoins consensus.

Mirrors the reference's node_catchup/ scenarios (SURVEY.md §3.4) using
SimNetwork Discard rules as the fault injection (delayers analog).
"""
import pytest

from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID, Reply
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.network import Discard, match_dst, match_frm

from test_pool import Pool, signed_nym


@pytest.fixture
def pool():
    return Pool(seed=7)


def cut_off(pool, name):
    r1 = pool.net.add_rule(Discard(), match_dst(name))
    r2 = pool.net.add_rule(Discard(), match_frm(name))
    return r1, r2


def test_lagging_node_catches_up(pool):
    victim = "Delta"
    rules = cut_off(pool, victim)

    users = [Ed25519Signer(seed=f"cu{i}".encode().ljust(32, b"\0"))
             for i in range(6)]
    for i, u in enumerate(users):
        pool.submit(signed_nym(pool.trustee, u, req_id=i + 1),
                    to=[n for n in pool.names if n != victim])
    pool.run(8.0)

    healthy = [n for n in pool.names if n != victim]
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in healthy}
    assert sizes == {7}, sizes                   # genesis + 6
    assert pool.nodes[victim].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 1

    for r in rules:
        pool.net.remove_rule(r)
    pool.nodes[victim].start_catchup()
    pool.run(10.0)

    v = pool.nodes[victim]
    ref = pool.nodes["Alpha"]
    assert v.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 7
    assert v.c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash == \
        ref.c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
    assert v.c.db.get_state(DOMAIN_LEDGER_ID).committed_head_hash == \
        ref.c.db.get_state(DOMAIN_LEDGER_ID).committed_head_hash
    assert v.c.db.get_ledger(3).size == ref.c.db.get_ledger(3).size
    assert v.master_replica.last_ordered_3pc == \
        ref.master_replica.last_ordered_3pc

    # the recovered node participates in new ordering
    u = Ed25519Signer(seed=b"after-catchup".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u, req_id=50))
    pool.run(6.0)
    assert v.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 8
    assert v.c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash == \
        ref.c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash


def test_catchup_noop_when_current(pool):
    """A current node's catchup finishes via the equal-status quorum."""
    node = pool.nodes["Beta"]
    node.start_catchup()
    pool.run(3.0)
    assert not node.leecher.is_running
    assert ("catchup_complete", (0, 0)) in node.spylog


def test_seeder_serves_ranges(pool):
    """Direct seeder probe: a CatchupReq returns verifiable txns."""
    from plenum_tpu.common.node_messages import CatchupReq, CatchupRep
    users = [Ed25519Signer(seed=f"sr{i}".encode().ljust(32, b"\0"))
             for i in range(3)]
    for i, u in enumerate(users):
        pool.submit(signed_nym(pool.trustee, u, req_id=i + 1))
    pool.run(6.0)
    sent = []
    alpha = pool.nodes["Alpha"]
    alpha.seeder._send = lambda msg, dst: sent.append((msg, dst))
    alpha.seeder.process_catchup_req(
        CatchupReq(ledger_id=DOMAIN_LEDGER_ID, seq_no_start=1, seq_no_end=4,
                   catchup_till=4), "Beta")
    assert len(sent) == 1
    rep, dst = sent[0]
    assert isinstance(rep, CatchupRep) and dst == "Beta"
    assert sorted(int(k) for k in rep.txns) == [1, 2, 3, 4]
