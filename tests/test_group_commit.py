"""Group-committed durable writes: the per-3PC-batch atomic KV batch.

Covers the storage primitive (the _BATCH record in kv_file/kv_chunked:
torn tail drops the WHOLE batch, never a prefix), the execution layer's
commit footprint (one appended record frame per store per commit, no
interleaved single puts), crash-replay between commit-quorum and durable
flush, and multi-batch coalescing under one DatabaseManager.group_commit
scope.
"""
import os
import struct

import pytest

from plenum_tpu.common.node_messages import (AUDIT_LEDGER_ID,
                                             CONFIG_LEDGER_ID,
                                             DOMAIN_LEDGER_ID, POOL_LEDGER_ID)
from plenum_tpu.common.request import Request
from plenum_tpu.execution import (DatabaseManager, ThreePcBatch,
                                  WriteRequestManager)
from plenum_tpu.execution import txn as txn_lib
from plenum_tpu.execution.database_manager import (SEQ_NO_DB_LABEL,
                                                   TS_STORE_LABEL)
from plenum_tpu.execution.handlers import NodeHandler, NymHandler
from plenum_tpu.execution.txn import NYM, TRUSTEE
from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
from plenum_tpu.ledger.hash_store import HashStore
from plenum_tpu.ledger.ledger import Ledger
from plenum_tpu.state.pruning_state import PruningState
from plenum_tpu.storage.kv_chunked import KvChunked
from plenum_tpu.storage.kv_file import KvFile, _HDR
from plenum_tpu.storage.state_ts_store import StateTsStore

TRUSTEE_DID = "trusteeTrusteeTrustee1"


def count_frames(path: str) -> int:
    """Top-level record frames in a kvlog (a _BATCH counts as ONE)."""
    with open(path, "rb") as fh:
        data = fh.read()
    frames, off = 0, 0
    while off + _HDR.size <= len(data):
        _op, klen, vlen = _HDR.unpack_from(data, off)
        off += _HDR.size + klen + vlen
        frames += 1
    assert off == len(data), "trailing garbage in log"
    return frames


# --- storage level -----------------------------------------------------------

@pytest.mark.parametrize("factory", [
    lambda d: KvFile(d),
    lambda d: KvChunked(d, chunk_records=100),
], ids=["kv_file", "kv_chunked"])
def test_torn_batch_drops_whole_batch(tmp_path, factory):
    """Crash mid-flush (simulated by truncating the tail at EVERY byte
    boundary of the batch record): replay yields all-or-nothing, never a
    half-written batch."""
    d = str(tmp_path / "kv")
    kv = factory(d)
    kv.put(b"pre", b"kept")
    log = [f for f in os.listdir(d)][0]
    path = os.path.join(d, log)
    size_before = os.path.getsize(path)
    with kv.write_batch():
        for i in range(4):
            kv.put(b"k%d" % i, b"v%d" % i * 7)
    size_after = os.path.getsize(path)
    kv._fh.close()          # abandon WITHOUT close(): close compacts
    kv._fh = None
    import shutil
    for cut in range(size_before, size_after):
        trial = str(tmp_path / f"cut{cut}")
        shutil.copytree(d, trial)
        with open(os.path.join(trial, log), "r+b") as fh:
            fh.truncate(cut)
        re = factory(trial)
        got = dict(re.iterator())
        assert got == {b"pre": b"kept"}, \
            f"cut at {cut}: partial batch survived: {got}"
        re._fh.close()
        re._fh = None
    # untouched log replays the full batch
    re = factory(d)
    assert re.size == 5
    re._fh.close()
    re._fh = None


def test_batch_survives_replay_and_compaction(tmp_path):
    d = str(tmp_path / "kv")
    kv = KvFile(d)
    with kv.write_batch():
        kv.put(b"a", b"1")
        kv.remove(b"a")
        kv.put(b"b", b"2")
        assert kv.try_get(b"b") == b"2"     # read-your-writes in scope
    kv.close()                              # compacts to plain records
    re = KvFile(d)
    assert dict(re.iterator()) == {b"b": b"2"}
    re.close()


def test_nested_write_batch_joins_outer(tmp_path):
    d = str(tmp_path / "kv")
    kv = KvFile(d)
    with kv.write_batch():
        kv.put(b"x", b"1")
        with kv.write_batch():              # joins: still ONE frame
            kv.put(b"y", b"2")
        kv.put(b"z", b"3")
    assert count_frames(os.path.join(d, "kv.kvlog")) == 1
    kv._fh.close()
    kv._fh = None
    re = KvFile(d)
    assert re.size == 3
    re._fh.close()
    re._fh = None


# --- execution level ---------------------------------------------------------

def make_durable_db(path, kv_factory) -> DatabaseManager:
    """File-backed DatabaseManager mirroring bootstrap's store layout."""
    db = DatabaseManager()
    for lid, label in ((AUDIT_LEDGER_ID, "audit"), (POOL_LEDGER_ID, "pool"),
                       (CONFIG_LEDGER_ID, "config"),
                       (DOMAIN_LEDGER_ID, "domain")):
        tree = CompactMerkleTree(
            hash_store=HashStore(kv_factory(os.path.join(path,
                                                         label + "_hashes"))))
        ledger = Ledger(tree, kv_factory(os.path.join(path, label + "_log")))
        state = None if lid == AUDIT_LEDGER_ID else \
            PruningState(kv_factory(os.path.join(path, label + "_state")))
        db.register_ledger(lid, ledger, state)
    db.register_store(TS_STORE_LABEL,
                      StateTsStore(kv_factory(os.path.join(path, "ts"))))
    db.register_store(SEQ_NO_DB_LABEL,
                      kv_factory(os.path.join(path, "seq_no_db")))
    return db


def make_wm(db) -> WriteRequestManager:
    wm = WriteRequestManager(db)
    nym = NymHandler(db)
    wm.register_handler(nym)
    wm.register_handler(NodeHandler(db, nym))
    return wm


def commit_nym_batch(wm, dests, pp_seq_no, pp_time):
    reqs = []
    for i, dest in enumerate(dests):
        op = {"type": NYM, "dest": dest, "verkey": "vk%d" % i}
        if dest == TRUSTEE_DID:
            op["role"] = TRUSTEE            # pool bootstrap
        reqs.append(Request(TRUSTEE_DID, 100 + pp_seq_no * 10 + i, op,
                            signature="sig"))
    valid, rejected, roots = wm.apply_batch(
        DOMAIN_LEDGER_ID, reqs, pp_time, 0, pp_seq_no)
    assert len(valid) == len(dests) and not rejected
    batch = ThreePcBatch(DOMAIN_LEDGER_ID, 0, pp_seq_no, pp_time,
                         tuple(r.digest for r in valid),
                         bytes.fromhex(roots["state_root"]),
                         bytes.fromhex(roots["txn_root"]),
                         bytes.fromhex(roots["audit_txn_root"]))
    return wm.commit_batch(batch)


@pytest.mark.parametrize("kv_factory", [
    lambda d: KvFile(d),
    lambda d: KvChunked(d, chunk_records=1000),
], ids=["kv_file", "kv_chunked"])
def test_commit_is_one_frame_per_store(tmp_path, kv_factory):
    """The acceptance shape: a commit's durable writes per store collapse
    to ONE appended record frame (the atomic batch), not interleaved
    single puts."""
    d = str(tmp_path / "node")
    db = make_durable_db(d, kv_factory)
    wm = make_wm(db)
    commit_nym_batch(wm, [TRUSTEE_DID], 1, 1000.0)      # bootstrap trustee
    logs = {label: os.path.join(d, label, os.listdir(os.path.join(d, label))[0])
            for label in ("domain_log", "audit_log", "seq_no_db", "ts",
                          "domain_hashes", "audit_hashes")}
    before = {k: count_frames(p) for k, p in logs.items()}
    commit_nym_batch(wm, ["userA1", "userB2", "userC3"], 2, 1001.0)
    grew = {k: count_frames(p) - before[k] for k, p in logs.items()}
    # commit_batch runs under ONE group scope: every store that took >1 row
    # appended exactly one batch frame; single-row stores appended one
    # plain record
    for k, delta in grew.items():
        assert delta <= 1, f"{k}: {delta} frames for one committed batch"
    assert grew["domain_log"] == 1 and grew["seq_no_db"] == 1
    assert grew["audit_log"] == 1 and grew["ts"] == 1


def test_multi_batch_group_commit_single_frame(tmp_path):
    """Several ready batches committed inside one outer group_commit scope
    (the node's drain loop) coalesce into ONE frame per store."""
    d = str(tmp_path / "node")
    db = make_durable_db(d, lambda p: KvFile(p))
    wm = make_wm(db)
    commit_nym_batch(wm, [TRUSTEE_DID], 1, 1000.0)
    # stage two batches, then commit both under one scope
    batches = []
    for pp_seq_no, dests in ((2, ["uA", "uB"]), (3, ["uC", "uD"])):
        reqs = [Request(TRUSTEE_DID, 200 + pp_seq_no * 10 + i,
                        {"type": NYM, "dest": dest, "verkey": "v"},
                        signature="sig")
                for i, dest in enumerate(dests)]
        valid, rejected, roots = wm.apply_batch(
            DOMAIN_LEDGER_ID, reqs, 1000.0 + pp_seq_no, 0, pp_seq_no)
        assert len(valid) == 2 and not rejected
        batches.append(ThreePcBatch(
            DOMAIN_LEDGER_ID, 0, pp_seq_no, 1000.0 + pp_seq_no,
            tuple(r.digest for r in valid),
            bytes.fromhex(roots["state_root"]),
            bytes.fromhex(roots["txn_root"]),
            bytes.fromhex(roots["audit_txn_root"])))
    domain_log = os.path.join(d, "domain_log", "kv.kvlog")
    before = count_frames(domain_log)
    with db.group_commit():
        for b in batches:
            wm.commit_batch(b)
    assert count_frames(domain_log) - before == 1, \
        "two batches under one scope must flush as one frame"
    assert db.get_ledger(DOMAIN_LEDGER_ID).size == 5


def test_crash_between_quorum_and_flush_replays_cleanly(tmp_path):
    """The satellite's crash case: process dies after commit-quorum but
    mid durable flush. Simulated by truncating the committed batch's tail
    record at an arbitrary interior byte on EVERY store: replay must show
    NO half-written audit/seq-no/ledger rows — each store holds the whole
    batch or none of it."""
    import shutil
    d = str(tmp_path / "node")
    db = make_durable_db(d, lambda p: KvFile(p))
    wm = make_wm(db)
    commit_nym_batch(wm, [TRUSTEE_DID], 1, 1000.0)
    sizes_before = {}
    for label in ("domain_log", "audit_log", "seq_no_db"):
        sizes_before[label] = os.path.getsize(
            os.path.join(d, label, "kv.kvlog"))
    committed = commit_nym_batch(wm, ["uX1", "uX2", "uX3"], 2, 1001.0)
    digests = [txn_lib.txn_payload_digest(t) for t in committed]
    assert all(digests)
    ledger_size = db.get_ledger(DOMAIN_LEDGER_ID).size
    audit_size = db.get_ledger(AUDIT_LEDGER_ID).size

    # crash: abandon without close (close would compact), then tear the
    # tail of each store's log a few bytes into the batch record
    crash = str(tmp_path / "crash")
    shutil.copytree(d, crash)
    for label in ("domain_log", "audit_log", "seq_no_db"):
        p = os.path.join(crash, label, "kv.kvlog")
        with open(p, "r+b") as fh:
            fh.truncate(sizes_before[label] + 7)    # mid batch record
    re_db = make_durable_db(crash, lambda p: KvFile(p))
    assert re_db.get_ledger(DOMAIN_LEDGER_ID).size == ledger_size - 3, \
        "torn ledger batch must vanish whole"
    assert re_db.get_ledger(AUDIT_LEDGER_ID).size == audit_size - 1
    seq_no = re_db.get_store(SEQ_NO_DB_LABEL)
    assert all(seq_no.try_get(dg.encode()) is None for dg in digests), \
        "half-written seq-no rows survived the torn batch"

    # the UNTORN copy replays the full batch — nothing was lost by the
    # batch framing itself
    re_db2 = make_durable_db(d, lambda p: KvFile(p))
    assert re_db2.get_ledger(DOMAIN_LEDGER_ID).size == ledger_size
    seq_no2 = re_db2.get_store(SEQ_NO_DB_LABEL)
    assert all(seq_no2.try_get(dg.encode()) is not None for dg in digests)
