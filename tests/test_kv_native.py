"""Native C++ KV engine: differential-tested against the in-memory model,
plus durability, torn-tail, and compaction behavior.

Reference test model: storage tests for the LevelDB/RocksDB backends.
"""
from __future__ import annotations

import os
import random

import pytest

from plenum_tpu.storage.kv_memory import KvMemory
from plenum_tpu.storage.kv_native import KvNative, native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native toolchain unavailable")


def test_differential_vs_memory_model(tmp_path):
    rng = random.Random(7)
    kv = KvNative(str(tmp_path))
    model = KvMemory()
    keys = [b"k%03d" % i for i in range(50)]
    for _ in range(2000):
        op = rng.randrange(3)
        k = rng.choice(keys)
        if op == 0:
            v = rng.randbytes(rng.randrange(0, 200))
            kv.put(k, v)
            model.put(k, v)
        elif op == 1:
            kv.remove(k)
            model.remove(k)
        else:
            try:
                expect = model.get(k)
            except KeyError:
                with pytest.raises(KeyError):
                    kv.get(k)
            else:
                assert kv.get(k) == expect
    assert list(kv.iterator()) == list(model.iterator())
    assert kv.size == model.size

    # ranged iteration agrees too (inclusive end, KvMemory semantics)
    assert list(kv.iterator(start=b"k010", end=b"k020")) == \
        list(model.iterator(start=b"k010", end=b"k020"))

    # durability: reopen sees the same content
    kv.close()
    kv2 = KvNative(str(tmp_path))
    assert list(kv2.iterator()) == list(model.iterator())
    kv2.close()


def test_torn_tail_drops_only_last_record(tmp_path):
    kv = KvNative(str(tmp_path))
    for i in range(10):
        kv.put(b"key%d" % i, b"value%d" % i)
    # close WITHOUT compaction path interfering: garbage ratio is 0 here
    kv.close()
    path = os.path.join(str(tmp_path), "kv.kvn")
    os.truncate(path, os.path.getsize(path) - 4)
    kv2 = KvNative(str(tmp_path))
    assert kv2.size == 9                 # only the torn record lost
    assert kv2.get(b"key8") == b"value8"
    with pytest.raises(KeyError):
        kv2.get(b"key9")
    # the truncated tail was cut at a record boundary: appends work
    kv2.put(b"key9", b"value9b")
    kv2.close()
    kv3 = KvNative(str(tmp_path))
    assert kv3.get(b"key9") == b"value9b"
    kv3.close()


def test_corrupt_record_detected_by_crc(tmp_path):
    kv = KvNative(str(tmp_path))
    kv.put(b"aa", b"11")
    kv.put(b"bb", b"22")
    kv.close()
    path = os.path.join(str(tmp_path), "kv.kvn")
    data = bytearray(open(path, "rb").read())
    data[-1] ^= 0xFF                     # flip a bit in the LAST record
    open(path, "wb").write(bytes(data))
    kv2 = KvNative(str(tmp_path))
    assert kv2.size == 1                 # corrupt record (and after) dropped
    assert kv2.get(b"aa") == b"11"
    kv2.close()


def test_compaction_shrinks_file_and_preserves_content(tmp_path):
    kv = KvNative(str(tmp_path))
    for round_ in range(20):
        for i in range(20):
            kv.put(b"k%d" % i, b"v%d-%d" % (i, round_))
    path = os.path.join(str(tmp_path), "kv.kvn")
    before = os.path.getsize(path)
    assert kv.garbage_ratio > 0.8
    kv.compact()
    after = os.path.getsize(path)
    assert after < before / 5
    assert kv.size == 20
    assert kv.get(b"k7") == b"v7-19"
    # still writable after compaction
    kv.put(b"new", b"x")
    kv.close()
    kv2 = KvNative(str(tmp_path))
    assert kv2.get(b"new") == b"x" and kv2.size == 21
    kv2.close()
