"""MessageReq/MessageRep recovery: a node that misses a single message
recovers it from peers and keeps ordering WITHOUT a full catchup.

Mirrors the reference's message_req_processor.py:13 scenarios over SimNetwork
Discard rules.
"""
import pytest

from plenum_tpu.common.node_messages import (DOMAIN_LEDGER_ID, MessageRep,
                                             MessageReq, PrePrepare, Propagate)
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.network import Discard

from test_pool import Pool, signed_nym

FAST = dict(Max3PCBatchWait=0.05,
            PRIMARY_HEALTH_CHECK_FREQ=0.5,
            ORDERING_PROGRESS_TIMEOUT=30.0,       # recovery must NOT need it
            STATE_FRESHNESS_UPDATE_INTERVAL=600.0)


def fast_pool(seed, **overrides):
    return Pool(seed=seed, config=Config(**{**FAST, **overrides}))


def no_catchup(node):
    return not any(e[0] == "catchup_started" for e in node.spylog)


def test_dropped_propagates_recovered():
    """Delta never receives any PROPAGATE for a request; the pre-prepare
    referencing it triggers RequestPropagates -> MessageReq(PROPAGATE) and
    Delta orders without catchup (VERDICT: 'a dropped propagate can wedge a
    replica until full catchup')."""
    pool = fast_pool(seed=41)
    rule = pool.net.add_rule(
        Discard(), lambda m, f, d: isinstance(m, Propagate) and d == "Delta")

    user = Ed25519Signer(seed=b"mr-user-1".ljust(32, b"\0"))
    # submit to the other three only: Delta can learn of the request ONLY
    # through recovery
    pool.submit(signed_nym(pool.trustee, user, req_id=1),
                to=["Alpha", "Beta", "Gamma"])
    pool.run(10.0)

    delta = pool.nodes["Delta"]
    assert delta.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2, \
        "Delta did not recover the dropped PROPAGATE"
    assert delta.c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash == \
        pool.nodes["Alpha"].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
    assert no_catchup(delta), "recovery went through catchup, not MessageReq"
    pool.net.remove_rule(rule)


def test_dropped_preprepare_recovered():
    """Delta loses the PRE-PREPARE but sees the PREPARE quorum: it re-requests
    the pre-prepare, validates it against the prepare-certified digest, and
    orders without catchup."""
    pool = fast_pool(seed=43)
    rule = pool.net.add_rule(
        Discard(), lambda m, f, d: isinstance(m, PrePrepare) and d == "Delta"
        and getattr(m, "inst_id", None) == 0)

    user = Ed25519Signer(seed=b"mr-user-2".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(10.0)

    delta = pool.nodes["Delta"]
    assert delta.c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2, \
        "Delta did not recover the dropped PRE-PREPARE"
    assert delta.master_replica.last_ordered_3pc[1] >= 1
    assert no_catchup(delta)
    pool.net.remove_rule(rule)


def test_forged_preprepare_rejected():
    """A lying MessageRep responder cannot inject a pre-prepare: without f+1
    matching PREPARE votes for its digest it is ignored."""
    pool = fast_pool(seed=47)
    delta = pool.nodes["Delta"]
    forged = PrePrepare(
        inst_id=0, view_no=0, pp_seq_no=1, pp_time=0.0,
        req_idr=(), discarded=(), digest="ff" * 32,
        ledger_id=DOMAIN_LEDGER_ID, state_root="aa" * 32, txn_root="bb" * 32)
    delta.node_bus.process_incoming(
        MessageRep(msg_type="PREPREPARE",
                   params={"inst_id": 0, "view_no": 0, "pp_seq_no": 1},
                   msg=forged.to_dict()), "Gamma")
    pool.run(2.0)
    assert (0, 1) not in delta.master_replica.ordering.prePrepares
    assert delta.master_replica.last_ordered_3pc == (0, 0)


def test_message_req_served_from_stores():
    """Direct probe: peers serve PROPAGATE and PREPREPARE from their stores."""
    pool = fast_pool(seed=53)
    user = Ed25519Signer(seed=b"mr-user-3".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, req_id=1))
    pool.run(6.0)

    alpha = pool.nodes["Alpha"]
    served = []
    alpha.node_bus._send_handler = lambda msg, dst: served.append((msg, dst))

    # the request executed, so the propagate store is freed — but the
    # pre-prepare log still serves
    alpha.message_req.process_message_req(
        MessageReq(msg_type="PREPREPARE",
                   params={"inst_id": 0, "view_no": 0, "pp_seq_no": 1}),
        "Delta")
    assert len(served) == 1
    rep, dst = served[0]
    assert isinstance(rep, MessageRep) and dst == ["Delta"]
    assert rep.msg["pp_seq_no"] == 1

    # unknown keys are silently not served
    alpha.message_req.process_message_req(
        MessageReq(msg_type="PREPREPARE",
                   params={"inst_id": 0, "view_no": 0, "pp_seq_no": 99}),
        "Delta")
    assert len(served) == 1


def test_throttle_dedups_requests():
    pool = fast_pool(seed=59)
    alpha = pool.nodes["Alpha"]
    sent = []
    alpha.node_bus._send_handler = lambda msg, dst: sent.append(msg)
    for _ in range(5):
        alpha.message_req.request("PROPAGATE", {"digest": "abc"})
    assert len(sent) == 1, "identical requests not throttled"
    pool.timer.advance(5.0)
    alpha.message_req.request("PROPAGATE", {"digest": "abc"})
    assert len(sent) == 2, "throttle never expires"
