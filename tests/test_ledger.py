"""Merkle tree / ledger tests: RFC-6962 known-answer vectors, property tests
against a naive reference tree, proofs, recovery, uncommitted staging."""
import hashlib

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package")
from hypothesis import given, settings, strategies as st

from plenum_tpu.ledger.tree_hasher import TreeHasher, make_tree_hasher
from plenum_tpu.ledger.hash_store import HashStore
from plenum_tpu.ledger.compact_merkle_tree import CompactMerkleTree
from plenum_tpu.ledger.merkle_verifier import MerkleVerifier
from plenum_tpu.ledger.ledger import Ledger
from plenum_tpu.storage.kv_file import KvFile
from plenum_tpu.storage.kv_memory import KvMemory


H = TreeHasher()


def naive_mth(leaves):
    """Straight RFC 6962 §2.1 recursion, the independent reference."""
    n = len(leaves)
    if n == 0:
        return hashlib.sha256(b"").digest()
    if n == 1:
        return hashlib.sha256(b"\x00" + leaves[0]).digest()
    k = 1
    while k * 2 < n:
        k *= 2
    return hashlib.sha256(b"\x01" + naive_mth(leaves[:k]) + naive_mth(leaves[k:])).digest()


# --- RFC 6962 known-answer tests (vectors from the RFC's example tree) ----

def test_empty_tree_root():
    t = CompactMerkleTree()
    assert t.root_hash == hashlib.sha256(b"").digest()
    assert t.root_hash.hex().startswith("e3b0c442")


def test_single_leaf():
    t = CompactMerkleTree()
    t.append(b"")
    # RFC 6962: MTH({d(0)}) = SHA-256(00 ||) = 6e34...
    assert t.root_hash.hex().startswith("6e340b9c")


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 6, 7, 8, 9, 15, 16, 17, 33, 100])
def test_root_matches_naive(n):
    leaves = [bytes([i]) * (i % 7 + 1) for i in range(n)]
    t = CompactMerkleTree()
    for l in leaves:
        t.append(l)
    assert t.root_hash == naive_mth(leaves)


def test_batch_extend_equals_sequential():
    leaves = [b"txn%d" % i for i in range(57)]
    t1 = CompactMerkleTree()
    for l in leaves:
        t1.append(l)
    t2 = CompactMerkleTree()
    t2.extend_batch(leaves[:13])
    t2.extend_batch(leaves[13:40])
    t2.extend_batch(leaves[40:])
    assert t1.root_hash == t2.root_hash
    assert t1.tree_size == t2.tree_size == 57


@settings(max_examples=30, deadline=None)
@given(st.lists(st.binary(min_size=0, max_size=40), min_size=1, max_size=64),
       st.data())
def test_inclusion_proofs_property(leaves, data):
    t = CompactMerkleTree()
    t.extend_batch(leaves)
    v = MerkleVerifier()
    root = t.root_hash
    m = data.draw(st.integers(min_value=0, max_value=len(leaves) - 1))
    path = t.inclusion_proof(m)
    assert v.verify_inclusion(leaves[m], m, len(leaves), path, root)
    # tampered leaf must fail
    assert not v.verify_inclusion(leaves[m] + b"x", m, len(leaves), path, root)
    # wrong index must fail (unless hash-collision-equivalent position)
    if len(leaves) > 1:
        wrong = (m + 1) % len(leaves)
        assert not v.verify_inclusion(leaves[m], wrong, len(leaves), path, root) or \
            leaves[wrong] == leaves[m]


@settings(max_examples=30, deadline=None)
@given(st.lists(st.binary(min_size=0, max_size=20), min_size=1, max_size=64),
       st.data())
def test_consistency_proofs_property(leaves, data):
    t = CompactMerkleTree()
    v = MerkleVerifier()
    m = data.draw(st.integers(min_value=1, max_value=len(leaves)))
    t.extend_batch(leaves[:m])
    old_root = t.root_hash
    t.extend_batch(leaves[m:])
    new_root = t.root_hash
    proof = t.consistency_proof(m, len(leaves))
    assert v.verify_consistency(m, len(leaves), old_root, new_root, proof)
    if m < len(leaves):
        assert not v.verify_consistency(m, len(leaves), old_root,
                                        hashlib.sha256(b"evil").digest(), proof)


def test_inclusion_proof_historic_size():
    leaves = [b"L%d" % i for i in range(20)]
    t = CompactMerkleTree()
    t.extend_batch(leaves)
    v = MerkleVerifier()
    # proof of leaf 3 in the historic size-10 tree
    t10 = CompactMerkleTree()
    t10.extend_batch(leaves[:10])
    path = t.inclusion_proof(3, 10)
    assert v.verify_inclusion(leaves[3], 3, 10, path, t10.root_hash)


def test_tree_recovery_from_hash_store():
    store = HashStore(KvMemory())
    t = CompactMerkleTree(hash_store=store)
    leaves = [b"x%d" % i for i in range(37)]
    t.extend_batch(leaves)
    root = t.root_hash
    t2 = CompactMerkleTree.recover(TreeHasher(), store)
    assert t2.tree_size == 37
    assert t2.root_hash == root
    t2.append(b"more")
    assert t2.tree_size == 38


def test_jax_tree_hasher_matches_cpu():
    leaves = [b"leaf%d" % i for i in range(32)]
    cpu, dev = make_tree_hasher("cpu"), make_tree_hasher("jax")
    assert dev.hash_leaves(leaves) == cpu.hash_leaves(leaves)
    pairs = [(hashlib.sha256(b"%d" % i).digest(),
              hashlib.sha256(b"r%d" % i).digest()) for i in range(17)]
    assert dev.hash_children_batch(pairs) == cpu.hash_children_batch(pairs)
    t1, t2 = CompactMerkleTree(cpu), CompactMerkleTree(dev)
    t1.extend_batch(leaves)
    t2.extend_batch(leaves)
    assert t1.root_hash == t2.root_hash


# --- Ledger ---------------------------------------------------------------

def _txn(i):
    return {"txn": {"type": "1", "data": {"i": i}},
            "txnMetadata": {"seqNo": i + 1}}


def test_ledger_append_and_read(tdir):
    l = Ledger()
    infos = l.append_batch([_txn(i) for i in range(10)])
    assert l.size == 10
    assert infos[0]["seqNo"] == 1 and infos[9]["seqNo"] == 10
    assert l.get_by_seq_no(5)["txnMetadata"]["seqNo"] == 5
    v = MerkleVerifier()
    from plenum_tpu.ledger.ledger import txn_to_leaf
    info = l.merkle_info(5)
    assert v.verify_inclusion(txn_to_leaf(l.get_by_seq_no(5)), 4, 10,
                              [bytes.fromhex(h) for h in info["auditPath"]],
                              bytes.fromhex(info["rootHash"]))


def test_ledger_genesis():
    genesis = [_txn(0), _txn(1)]
    l = Ledger(genesis_txns=genesis)
    assert l.size == 2


def test_ledger_uncommitted_staging():
    l = Ledger(genesis_txns=[_txn(0)])
    committed_root = l.root_hash
    root1, size1 = l.append_txns_to_uncommitted([_txn(1), _txn(2)])
    assert size1 == 3 and root1 != committed_root
    assert l.root_hash == committed_root          # committed untouched
    root2, size2 = l.append_txns_to_uncommitted([_txn(3)])
    assert size2 == 4
    # revert last batch
    l.discard_txns(1)
    assert l.uncommitted_size == 3
    assert l.uncommitted_root_hash == root1
    # commit the rest
    txns, infos = l.commit_txns(2)
    assert l.size == 3 and l.root_hash == root1
    assert [i["seqNo"] for i in infos] == [2, 3]


def test_ledger_uncommitted_root_matches_direct_append():
    l1 = Ledger(genesis_txns=[_txn(0)])
    l1.append_txns_to_uncommitted([_txn(i) for i in range(1, 8)])
    l2 = Ledger(genesis_txns=[_txn(0)])
    l2.append_batch([_txn(i) for i in range(1, 8)])
    assert l1.uncommitted_root_hash == l2.root_hash


def test_ledger_durable_recovery(tdir):
    log = KvFile(tdir + "/log", "txns")
    store = HashStore(KvFile(tdir + "/hs", "hashes"))
    l = Ledger(CompactMerkleTree(hash_store=store), log)
    l.append_batch([_txn(i) for i in range(25)])
    root = l.root_hash
    l.close()
    log2 = KvFile(tdir + "/log", "txns")
    store2 = HashStore(KvFile(tdir + "/hs", "hashes"))
    l2 = Ledger(CompactMerkleTree.recover(TreeHasher(), store2), log2)
    assert l2.size == 25 and l2.root_hash == root
    l2.close()


def test_ledger_recovery_hash_store_lagging(tdir):
    """Txn log ahead of hash store (crash between log write and tree write):
    replay the tail."""
    log = KvFile(tdir + "/log", "txns")
    l = Ledger(CompactMerkleTree(hash_store=HashStore(KvMemory())), log)
    l.append_batch([_txn(i) for i in range(10)])
    root = l.root_hash
    l._log.close()
    # reopen with EMPTY (memory) hash store: full rebuild path
    log2 = KvFile(tdir + "/log", "txns")
    l2 = Ledger(CompactMerkleTree(hash_store=HashStore(KvMemory())), log2)
    assert l2.size == 10 and l2.root_hash == root


def test_fresh_tree_over_persisted_store_recovers(tdir):
    """Review finding: Ledger must recover even when handed a non-recovered
    tree over a persisted hash store."""
    log = KvFile(tdir + "/log", "txns")
    store_kv = KvFile(tdir + "/hs", "hashes")
    l = Ledger(CompactMerkleTree(hash_store=HashStore(store_kv)), log)
    l.append_batch([_txn(i) for i in range(5)])
    root = l.root_hash
    l.close()
    # reopen with a FRESH tree (not CompactMerkleTree.recover)
    l2 = Ledger(CompactMerkleTree(hash_store=HashStore(KvFile(tdir + "/hs", "hashes"))),
                KvFile(tdir + "/log", "txns"))
    assert l2.size == 5
    assert l2.root_hash == root
    assert l2.merkle_info(1)["seqNo"] == 1
    l2.close()


def test_proof_range_errors_are_value_errors():
    t = CompactMerkleTree()
    t.append(b"x")
    with pytest.raises(ValueError):
        t.inclusion_proof(5)
    with pytest.raises(ValueError):
        t.consistency_proof(0, 1)
    l = Ledger()
    with pytest.raises(ValueError):
        l.commit_txns(3)
    with pytest.raises(ValueError):
        l.discard_txns(1)
