"""Live fleet telemetry plane (plenum_tpu/observability/).

Covers the PR 11 acceptance gates: snapshot-stream determinism on the
seeded timer (PR 5's tracing-determinism guard pattern), multi-window
burn-rate alerting (a client flood MUST fire the ingress SLO alert; an
idle pool must fire NONE), device_flap degrading + recovering the
crypto health score, the zipfian hot-shard load-imbalance flag, the
disabled path collapsing to one attribute check (microbench-pinned),
the metrics lint (every MetricsName in the snapshot schema or
exempted), pool-wide percentile merging in metrics_report, and the
fleet_console --check self-test.
"""
from __future__ import annotations

import json
import time

import pytest

from plenum_tpu.common.metrics import MetricsCollector, MetricsName
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.observability import (NULL_TELEMETRY, FleetAggregator,
                                      TelemetryEmitter, make_telemetry,
                                      snapshot_bytes)

from test_pool import Pool, signed_nym

FAST_BURN = dict(Max3PCBatchWait=0.05,
                 SLO_BURN_FAST_WINDOW=3.0,
                 SLO_BURN_SLOW_WINDOW=10.0,
                 TELEMETRY_INTERVAL=0.5)


def _wire_aggregator(pool, config=None):
    agg = FleetAggregator(config=config or pool.config)
    for node in pool.nodes.values():
        assert node.telemetry.enabled
        node.telemetry.add_sink(agg.ingest)
    return agg


# --- disabled path ----------------------------------------------------------

def test_null_telemetry_disabled_cost_microbench():
    """TELEMETRY=False must collapse the plane to one attribute check
    per call site (the NullTracer acceptance pattern): no timer, no
    snapshot work, and the guarded-check pattern itself within 2% of a
    1 ms/txn budget at a generous 4 sites per txn."""
    telemetry = NULL_TELEMETRY
    assert telemetry.enabled is False
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        if telemetry.enabled:
            telemetry.tick()
    per_site = (time.perf_counter() - t0) / n
    assert per_site * 4 < 0.02 * 0.001, \
        f"{per_site * 1e9:.0f} ns/site exceeds the disabled budget"


def test_disabled_node_gets_null_telemetry_and_no_timer():
    timer = MockTimer()
    made = make_telemetry("N", MetricsCollector(), timer.get_current_time,
                          config=Config(TELEMETRY=False), timer=timer)
    assert made is NULL_TELEMETRY
    assert timer.size == 0                      # no snapshot timer registered
    pool = Pool(config=Config(Max3PCBatchWait=0.05, TELEMETRY=False))
    assert all(node.telemetry is NULL_TELEMETRY
               for node in pool.nodes.values())


# --- snapshot mechanics -----------------------------------------------------

def test_emitter_counter_deltas_and_flush_rebase():
    timer = MockTimer()
    metrics = MetricsCollector()
    em = TelemetryEmitter("N", metrics, timer.get_current_time,
                          config=Config())
    metrics.add_event("node.propagates", 1)
    metrics.add_event("node.propagates", 1)
    s1 = em.snapshot()
    assert s1["counters"]["node.propagates"][0] == 2
    metrics.add_event("node.propagates", 1)
    s2 = em.snapshot()
    assert s2["counters"]["node.propagates"][0] == 1     # delta, not total
    # a collector flush (KvMetricsCollector) drops accumulators; the
    # next interval's fold IS the delta — no negative or double counts
    metrics.flush()
    for _ in range(5):
        metrics.add_event("node.propagates", 1)
    s3 = em.snapshot()
    assert s3["counters"]["node.propagates"][0] == 5
    assert [s["seq"] for s in (s1, s2, s3)] == [0, 1, 2]


def test_spool_is_bounded_and_atomic(tmp_path):
    timer = MockTimer()
    metrics = MetricsCollector()
    em = TelemetryEmitter("N1", metrics, timer.get_current_time,
                          config=Config(TELEMETRY_SPOOL_MAX=4),
                          spool_dir=str(tmp_path))
    for i in range(10):
        metrics.add_event("node.propagates", 1)
        timer.advance(1.0)
        em.tick()
    files = sorted(tmp_path.glob("N1-telemetry-*.json"))
    assert len(files) == 4                      # rotating window, bounded
    snaps = [json.loads(f.read_text()) for f in files]
    assert max(s["seq"] for s in snaps) == 9    # newest snapshot present
    assert not list(tmp_path.glob("*.tmp"))     # atomic: no torn leftovers


def test_snapshot_stream_determinism():
    """PR 5's guard pattern for the telemetry plane: the SAME seeded sim
    workload run twice produces byte-identical snapshot streams
    (wall_sums=False strips the perf_counter-derived sums/percentiles,
    the one legitimately non-deterministic field — the tracer's
    wall_durations twin)."""
    def run_once():
        pool = Pool(seed=7, config=Config(**FAST_BURN))
        for node in pool.nodes.values():
            node.telemetry.wall_sums = False
        u = Ed25519Signer(seed=b"det-user".ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, u, 1))
        pool.run(8.0)
        return b"|".join(snapshot_bytes(s)
                         for s in pool.nodes["Alpha"].telemetry.ring)
    a, b = run_once(), run_once()
    assert a == b and len(a) > 100


def test_telemetry_ships_over_sim_network_wire():
    """The best-effort TELEMETRY wire message: every node ships its
    snapshots to Beta (TELEMETRY_SHIP_TO — the production wiring, full
    wire pack/unpack roundtrip), whose attached FleetAggregator
    composes the whole pool's view."""
    pool = Pool(config=Config(**FAST_BURN, TELEMETRY_SHIP_TO="Beta"))
    beta = pool.nodes["Beta"]
    agg = FleetAggregator(config=pool.config)
    beta.fleet_aggregator = agg                  # wire-ingest only
    pool.run(5.0)
    # every OTHER node's snapshots arrived across the wire
    assert set(agg.latest) == {"Alpha", "Gamma", "Delta"}
    assert agg.latest["Alpha"]["state"]["node"]["validators"] == 4
    # Beta ships nowhere (it hosts the aggregator); attach adds its own
    # stream through the in-process sink seam
    assert pool.nodes["Alpha"].telemetry.ship is not None
    assert beta.telemetry.ship is None
    beta.attach_fleet_aggregator(agg)
    pool.run(2.0)
    assert set(agg.latest) == set(pool.names)


# --- burn-rate alerting -----------------------------------------------------

def test_burn_tracker_multi_window_rule():
    from plenum_tpu.observability import BurnRateTracker
    tr = BurnRateTracker(budget=0.05, threshold=2.0,
                         fast_window=3.0, slow_window=10.0)
    # below MIN_SAMPLES nothing can page, however bad the fraction
    tr.note(0.0, 5, 5)
    assert not tr.alerting(0.0)
    for i in range(1, 12):
        tr.note(float(i), 4, 5)
    assert tr.alerting(11.0)                     # both windows burning
    # recovery: fast window clears first, the alert rule follows it
    for i in range(12, 20):
        tr.note(float(i), 0, 5)
    assert not tr.alerting(19.0)


def test_idle_pool_fires_zero_alerts():
    """Zero false positives: an idle 4-node pool with the full telemetry
    plane on raises NO alerts across a long quiet stretch."""
    pool = Pool(config=Config(**FAST_BURN))
    agg = _wire_aggregator(pool)
    u = Ed25519Signer(seed=b"idle-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u, 1))
    pool.run(30.0)
    assert agg.snapshots > 100
    assert agg.alerts == [], \
        f"idle pool alerted: {[a.to_dict() for a in agg.alerts]}"
    assert all(agg.node_health(n) == 1.0 for n in pool.names)


def test_client_flood_fires_ingress_burn_alert():
    """A sustained flood through the front door must fire the ingress
    SLO burn-rate alert (sheds + over-SLO queue waits burn the error
    budget on both windows) — and the alert lands in the node's
    flight-recorder ring as a structured anomaly."""
    from plenum_tpu.client.sim_clients import burst_writes
    from plenum_tpu.ingress import IngressPlane
    config = Config(**FAST_BURN, INGRESS_CLIENT_QUEUE_CAP=4,
                    INGRESS_SLO_P95=0.2)
    pool = Pool(config=config)
    alpha = pool.nodes["Alpha"]
    agg = FleetAggregator(config=config, tracer=alpha.tracer,
                          metrics=alpha.metrics)
    for node in pool.nodes.values():
        node.telemetry.add_sink(agg.ingest)
    ingress = {n: IngressPlane(pool.nodes[n]) for n in pool.names}
    pool.run(3.0)                                # healthy datum
    assert not [a for a in agg.alerts if a.kind == "slo_burn.ingress"]
    # repeated hot-client bursts: well past the per-client caps, every
    # wave shedding the surplus, sustained across both burn windows
    for wave in range(10):
        for client, req in burst_writes(pool.trustee, 8, 10,
                                        seed=wave + 1):
            for n in pool.names:
                ingress[n].submit(req.to_dict(), client)
        pool.run(1.5)
    fired = [a for a in agg.alerts
             if a.kind == "slo_burn.ingress" and a.severity == "page"]
    assert fired, f"flood never fired: {[a.to_dict() for a in agg.alerts]}"
    assert fired[0].detail["fast"] >= config.SLO_BURN_THRESHOLD
    # structured alert reached the flight-recorder ring
    kinds = [e[1] for e in alpha.tracer.ring]
    assert any(k == "anomaly.alert.slo_burn.ingress" for k in kinds)
    # and the alert-volume counter reached metrics
    assert alpha.metrics.accumulators[MetricsName.TELEMETRY_ALERTS].count >= 1


def test_silent_node_goes_stale_not_frozen_at_healthy():
    """A crashed/partitioned node must read as DOWN: once its last
    snapshot ages past TELEMETRY_STALE_AFTER (vs the fleet clock), its
    health drops to 0.0, the sweep raises the health alert, and its
    ordered-rate contribution decays — never frozen-at-last-healthy."""
    pool = Pool(config=Config(**FAST_BURN, TELEMETRY_STALE_AFTER=3.0))
    agg = _wire_aggregator(pool)
    u = Ed25519Signer(seed=b"stale-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u, 1))
    pool.run(5.0)
    assert agg.node_health("Delta") == 1.0
    # Delta goes dark; the rest of the pool keeps snapshotting
    pool.nodes["Delta"].telemetry.stop()
    pool.run(10.0)
    assert agg.node_stale("Delta")
    assert agg.node_health("Delta") == 0.0
    stale_alerts = [a for a in agg.alerts
                    if a.kind == "health.node" and a.subject == "Delta"
                    and a.severity == "warn"]
    assert stale_alerts and stale_alerts[0].detail.get("stale_s", 0) > 3.0
    # the live members are untouched
    assert all(agg.node_health(n) == 1.0
               for n in ("Alpha", "Beta", "Gamma"))


def test_single_abusive_client_does_not_page_pool_slo():
    """The breadth rule: ONE client hammering past its per-client cap is
    the fairness mechanism working, not pool overload — its sheds must
    not burn the pool's ingress error budget (no false page), while the
    same volume spread over many clients does (pinned by the flood
    test)."""
    from plenum_tpu.client.sim_clients import burst_writes
    from plenum_tpu.ingress import IngressPlane
    config = Config(**FAST_BURN, INGRESS_CLIENT_QUEUE_CAP=4,
                    INGRESS_SLO_P95=0.2)
    pool = Pool(config=config)
    agg = _wire_aggregator(pool, config=config)
    ingress = {n: IngressPlane(pool.nodes[n]) for n in pool.names}
    pool.run(3.0)
    # one client, same aggregate volume as the flood's waves
    for wave in range(10):
        for client, req in burst_writes(pool.trustee, 1, 80,
                                        seed=wave + 1):
            for n in pool.names:
                ingress[n].submit(req.to_dict(), client)
        pool.run(1.5)
    assert ingress[pool.names[0]].stats["shed_client_cap"] > 0
    pages = [a for a in agg.alerts
             if a.kind == "slo_burn.ingress" and a.severity == "page"]
    assert pages == [], \
        f"one capped client paged the pool: {[a.to_dict() for a in pages]}"


def test_device_flap_degrades_crypto_health_and_recovers():
    """The acceptance arc: a wedged crypto plane opens the breaker ->
    the node's health score degrades; the plane heals and the breaker
    re-closes -> health recovers to 1.0."""
    from plenum_tpu.crypto.ed25519 import CpuEd25519Verifier
    from plenum_tpu.parallel.faults import FaultyVerifier
    from plenum_tpu.parallel.supervisor import (CircuitBreaker,
                                                DeadlineBudget,
                                                SupervisedVerifier)
    faulty = FaultyVerifier(CpuEd25519Verifier())
    sup = SupervisedVerifier(
        faulty, fallback=CpuEd25519Verifier(),
        breaker=CircuitBreaker(fail_threshold=2, cooldown=1.0),
        budget=DeadlineBudget(base=0.4, min_s=0.2, warm_max=1.0,
                              cold_max=1.0))
    pool = Pool(config=Config(**FAST_BURN), verifier=sup)
    sup.set_clock(pool.timer.get_current_time)
    faulty.set_clock(pool.timer.get_current_time)
    agg = _wire_aggregator(pool)

    u1 = Ed25519Signer(seed=b"flap-user1".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u1, 1))
    pool.run(5.0)
    assert agg.node_health("Alpha") == 1.0

    faulty.wedge()                               # the fault lands
    u2 = Ed25519Signer(seed=b"flap-user2".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u2, 2))
    pool.run(8.0)
    assert sup.breaker.state != "closed"
    degraded = agg.node_health("Alpha")
    assert degraded is not None and degraded <= 0.5, \
        f"breaker {sup.breaker.state} but health {degraded}"

    faulty.heal()                                # recovery
    u3 = Ed25519Signer(seed=b"flap-user3".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u3, 3))
    pool.run(20.0)
    assert sup.breaker.state == "closed"
    assert agg.node_health("Alpha") == 1.0


# --- sharded fabric: imbalance + health exposure ----------------------------

def test_zipfian_hot_shard_flags_imbalance():
    """A 90:10 hot-key skew onto shard 0 must push the load-imbalance
    index past the threshold and name shard 0 hot — the per-shard load
    signal elastic resharding will consume — and surface per-shard
    health through the router summary and `shards` metrics."""
    from plenum_tpu.common.request import Request
    from plenum_tpu.execution.txn import NYM
    from plenum_tpu.shards import ShardedSimFabric
    fab = ShardedSimFabric(
        n_shards=2, nodes_per_shard=3, seed=5,
        config=Config(Max3PCBatchWait=0.05, TELEMETRY_INTERVAL=0.5,
                      STATE_FRESHNESS_UPDATE_INTERVAL=600.0))
    by_shard: dict[int, list] = {0: [], 1: []}
    i = 0
    while min(len(v) for v in by_shard.values()) < 40 and i < 400:
        i += 1
        user = Ed25519Signer(seed=(b"zh%08d" % i).ljust(32, b"\0")[:32])
        req = Request(fab.trustee.identifier, i,
                      {"type": NYM, "dest": user.identifier,
                       "verkey": user.verkey_b58})
        req.signature = fab.trustee.sign_b58(req.signing_bytes())
        sid = fab.router.shard_of(req)
        if sid in by_shard:
            by_shard[sid].append(req)
    for j in range(40):
        fab.submit_write(by_shard[0][j] if j % 10 else by_shard[1][j // 10])
        if j % 8 == 7:
            fab.run(1.0)
    fab.run(10.0)
    fab.ordered_counts()
    index, hot = fab.aggregator.load_imbalance()
    assert hot == 0 and index is not None and index >= 1.5, \
        f"hot shard not flagged: index={index} hot={hot} " \
        f"rates={fab.aggregator.ordered_rates()}"
    assert any(a.kind == "shard.imbalance" for a in fab.aggregator.alerts)
    # satellite: per-shard health is visible at the routing layer and in
    # the shards metrics section (signal only — no routing change)
    summary = fab.router.summary()
    assert summary["shard_health"] == {0: 1.0, 1: 1.0}
    assert summary["degraded_shards"] == []
    health_acc = fab.metrics.accumulators.get(MetricsName.SHARD_HEALTH)
    imb_acc = fab.metrics.accumulators.get(MetricsName.SHARD_IMBALANCE)
    assert health_acc is not None and health_acc.count >= 2
    assert imb_acc is not None and imb_acc.max >= 1.5
    # the read ladder exposes the same health signal
    driver = fab.read_driver()
    assert driver.shard_health() == {0: 1.0, 1: 1.0}
    # and the fabric summary carries the full fleet columns
    s = fab.summary()
    assert s["hot_shard"] == 0 and s["load_imbalance"] == index


# --- metrics_report pool merge (satellite) ----------------------------------

def test_pool_percentiles_merge_reservoirs_not_average():
    """Pool p50/p95 must come from the UNION of the nodes' sampled
    reservoirs. With two nodes at 1 ms and 100 ms, averaging per-node
    p50s would invent a ~50 ms figure no request ever saw; the merged
    p95 must sit at the slow node's value."""
    from plenum_tpu.common.metrics import percentile
    from plenum_tpu.tools.metrics_report import (merge_node_folds,
                                                 pool_summary)
    name = "commit_path.durable_time"
    ordered = {"count": 10, "sum": 150.0, "min": 10, "max": 20,
               "mean": 15.0, "first_ts": 0.0, "last_ts": 10.0,
               "flushes": 1}
    per_node = {
        "A": {name: {"count": 100, "sum": 0.1, "min": 0.001, "max": 0.001,
                     "mean": 0.001, "first_ts": 0.0, "last_ts": 10.0,
                     "flushes": 1, "samples": [0.001] * 100},
              "node.ordered_batch_size": dict(ordered)},
        "B": {name: {"count": 100, "sum": 10.0, "min": 0.1, "max": 0.1,
                     "mean": 0.1, "first_ts": 0.0, "last_ts": 10.0,
                     "flushes": 1, "samples": [0.1] * 100},
              "node.ordered_batch_size": dict(ordered)},
    }
    merged = merge_node_folds(per_node)
    samples = merged[name]["samples"]
    assert len(samples) == 200
    p50 = percentile(samples, 0.5)
    p95 = percentile(samples, 0.95)
    assert p50 in (0.001, 0.1)                   # a real observed value
    assert p95 == pytest.approx(0.1)             # the slow node dominates
    avg_of_p50s = (0.001 + 0.1) / 2
    assert abs(p50 - avg_of_p50s) > 0.04         # NOT the averaged figure
    assert merged[name]["count"] == 200
    assert merged[name]["min"] == 0.001 and merged[name]["max"] == 0.1
    summary = pool_summary(per_node)
    assert summary["nodes"] == 2
    assert summary["durable_ms_p95"] == pytest.approx(100.0)
    # the ordered stream is REPLICATED on every node: the pool figure
    # must be de-replicated, not the nodes' sum (2x reality)
    assert summary["txns_ordered"] == 150
    assert summary["tps"] == pytest.approx(15.0)


# --- lint + console self-tests (tier-1 gates) -------------------------------

def test_metrics_lint_is_clean_and_catches_gaps(monkeypatch):
    from plenum_tpu.tools.metrics_lint import run_lint
    out = run_lint()
    assert out["check"] == "ok", out["problems"]
    assert out["covered"] + out["exempted"] == out["metrics"]
    # a counter added without schema coverage must FAIL the lint
    monkeypatch.setattr(MetricsName, "BOGUS_NEW", "bogus.new_counter",
                        raising=False)
    out2 = run_lint()
    assert out2["check"] == "FAIL"
    assert any("bogus.new_counter" in p for p in out2["problems"])


def test_fleet_console_check_smoke(capsys):
    """`fleet_console --check` is the tier-1 self-test gate (the
    trace_report --check pattern): synthetic healthy / overload /
    crypto-fault / hot-shard streams through the REAL aggregator."""
    from plenum_tpu.tools import fleet_console
    assert fleet_console.main(["--check"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["check"] == "ok"


def test_fleet_console_reads_spool_dir(tmp_path):
    """End-to-end over the on-disk seam: a pool spools snapshots, the
    console builds the fleet view from the files alone."""
    pool = Pool(config=Config(**FAST_BURN))
    for n, node in pool.nodes.items():
        node.telemetry.spool_dir = str(tmp_path / n / "telemetry")
    u = Ed25519Signer(seed=b"spool-user".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, u, 1))
    pool.run(6.0)
    from plenum_tpu.tools.fleet_console import build_view, render
    agg, incidents = build_view([str(tmp_path)], config=pool.config)
    assert set(agg.latest) == set(pool.names)
    assert all(agg.node_health(n) == 1.0 for n in pool.names)
    assert agg.alerts == []
    text = render(agg, incidents)
    assert "Alpha" in text and "alerts: 0 active" in text


def test_aggregator_alert_lands_in_flight_ring_and_incidents():
    """Structured alerts mirror into an attached tracer ring and merge
    into the cross-node incident timeline."""
    from plenum_tpu.common.tracing import Tracer
    from plenum_tpu.observability import incident_timelines
    clock = {"t": 0.0}
    tracer = Tracer("agg", lambda: clock["t"])
    agg = FleetAggregator(config=Config(SLO_BURN_FAST_WINDOW=3.0,
                                        SLO_BURN_SLOW_WINDOW=10.0),
                          tracer=tracer)
    for i in range(15):
        clock["t"] = float(i)
        agg.ingest({"v": 1, "node": "N1", "seq": i, "t": float(i),
                    "counters": {}, "sampled": {},
                    "state": {"ingress": {"slo": [5, 5]},
                              "node": {"ordered_total": 0}}})
    assert any(a.kind == "slo_burn.ingress" for a in agg.alerts)
    assert any(e[1] == "anomaly.alert.slo_burn.ingress"
               for e in tracer.ring)
    incidents = incident_timelines([tracer.snapshot()], alerts=agg.alerts)
    assert incidents and any("alert.slo_burn.ingress" in inc["kinds"]
                             for inc in incidents)
