"""SimNetwork determinism and fault-injection semantics
(ref plenum/test/simulation/test_sim_network.py behavior)."""
from plenum_tpu.common.node_messages import Checkpoint, Propagate
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.network import (Deliver, Discard, SimNetwork, SimRandom, Stash,
                                match_dst, match_frm, match_type)


def _mk_pool(n=3, seed=7):
    timer = MockTimer()
    net = SimNetwork(timer, SimRandom(seed))
    inboxes = {}
    for i in range(n):
        name = f"N{i}"
        bus = net.create_peer(name)
        inboxes[name] = []
        bus.subscribe(Checkpoint, lambda m, frm, box=inboxes[name]: box.append((m, frm)))
        bus.subscribe(Propagate, lambda m, frm, box=inboxes[name]: box.append((m, frm)))
    net.connect_all()
    return timer, net, inboxes


def _chk(end=10):
    return Checkpoint(inst_id=0, view_no=0, seq_no_start=0, seq_no_end=end,
                      digest="d" * 8)


def test_broadcast_reaches_all_other_peers():
    timer, net, inboxes = _mk_pool()
    net._peers["N0"].send(_chk())
    timer.run_to_completion()
    assert len(inboxes["N1"]) == 1 and len(inboxes["N2"]) == 1
    assert inboxes["N0"] == []
    msg, frm = inboxes["N1"][0]
    assert frm == "N0" and msg.seq_no_end == 10
    # Wire round-trip produced a fresh object, not the sender's instance.
    assert isinstance(msg, Checkpoint)


def test_unicast_and_selector_rules():
    timer, net, inboxes = _mk_pool()
    net.add_rule(Discard(), match_frm("N0"), match_dst("N1"),
                 match_type(Checkpoint))
    net._peers["N0"].send(_chk(), dst=["N1", "N2"])
    timer.run_to_completion()
    assert inboxes["N1"] == []           # discarded
    assert len(inboxes["N2"]) == 1       # delivered


def test_stash_rule_replays_on_removal():
    timer, net, inboxes = _mk_pool()
    rule = net.add_rule(Stash(), match_type(Checkpoint))
    net._peers["N0"].send(_chk())
    timer.run_to_completion()
    assert inboxes["N1"] == [] and inboxes["N2"] == []
    net.remove_rule(rule)
    timer.run_to_completion()
    assert len(inboxes["N1"]) == 1 and len(inboxes["N2"]) == 1


def test_deliver_rule_controls_delay():
    timer, net, inboxes = _mk_pool()
    net.add_rule(Deliver(5.0, 5.0), match_type(Checkpoint))
    net._peers["N0"].send(_chk())
    timer.advance(4.9)
    assert inboxes["N1"] == []
    timer.advance(0.2)
    assert len(inboxes["N1"]) == 1


def test_determinism_same_seed_same_trace():
    traces = []
    for _ in range(2):
        timer, net, inboxes = _mk_pool(n=4, seed=123)
        net.add_rule(Discard(0.5), match_type(Checkpoint))
        for k in range(20):
            net._peers["N0"].send(_chk(end=k))
        timer.run_to_completion()
        traces.append([m.seq_no_end for (m, _) in inboxes["N1"]])
    assert traces[0] == traces[1]


def test_connected_events():
    timer = MockTimer()
    net = SimNetwork(timer)
    seen = []
    b0 = net.create_peer("N0")
    b0.subscribe(type(b0).Connected, lambda m, frm: seen.append(m.name))
    net.create_peer("N1")
    net.connect_all()
    assert seen == ["N1"]
    assert b0.connecteds == {"N1"}


def test_multihost_api_single_process():
    """init_multihost + global_mesh + shard_host_batch drive the sharded
    crypto plane on the (virtual, 8-device) single-process job — the same
    call sequence a multi-host deployment uses."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from plenum_tpu.parallel.multihost import (global_mesh, init_multihost,
                                               shard_host_batch)

    init_multihost()                       # single-process: no coordinator
    mesh = global_mesh(8)
    assert mesh.devices.size == 8 and mesh.axis_names == ("inst", "sig")

    arr = np.arange(8 * 4, dtype=np.int64).reshape(8, 4)
    garr = shard_host_batch(mesh, arr, P(("inst", "sig"), None))
    assert garr.shape == (8, 4)
    np.testing.assert_array_equal(np.asarray(garr), arr)
