"""SimNetwork determinism and fault-injection semantics
(ref plenum/test/simulation/test_sim_network.py behavior)."""
from plenum_tpu.common.node_messages import Checkpoint, Propagate
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.network import (Deliver, Discard, SimNetwork, SimRandom, Stash,
                                match_dst, match_frm, match_type)


def _mk_pool(n=3, seed=7):
    timer = MockTimer()
    net = SimNetwork(timer, SimRandom(seed))
    inboxes = {}
    for i in range(n):
        name = f"N{i}"
        bus = net.create_peer(name)
        inboxes[name] = []
        bus.subscribe(Checkpoint, lambda m, frm, box=inboxes[name]: box.append((m, frm)))
        bus.subscribe(Propagate, lambda m, frm, box=inboxes[name]: box.append((m, frm)))
    net.connect_all()
    return timer, net, inboxes


def _chk(end=10):
    return Checkpoint(inst_id=0, view_no=0, seq_no_start=0, seq_no_end=end,
                      digest="d" * 8)


def test_broadcast_reaches_all_other_peers():
    timer, net, inboxes = _mk_pool()
    net._peers["N0"].send(_chk())
    timer.run_to_completion()
    assert len(inboxes["N1"]) == 1 and len(inboxes["N2"]) == 1
    assert inboxes["N0"] == []
    msg, frm = inboxes["N1"][0]
    assert frm == "N0" and msg.seq_no_end == 10
    # Wire round-trip produced a fresh object, not the sender's instance.
    assert isinstance(msg, Checkpoint)


def test_unicast_and_selector_rules():
    timer, net, inboxes = _mk_pool()
    net.add_rule(Discard(), match_frm("N0"), match_dst("N1"),
                 match_type(Checkpoint))
    net._peers["N0"].send(_chk(), dst=["N1", "N2"])
    timer.run_to_completion()
    assert inboxes["N1"] == []           # discarded
    assert len(inboxes["N2"]) == 1       # delivered


def test_stash_rule_replays_on_removal():
    timer, net, inboxes = _mk_pool()
    rule = net.add_rule(Stash(), match_type(Checkpoint))
    net._peers["N0"].send(_chk())
    timer.run_to_completion()
    assert inboxes["N1"] == [] and inboxes["N2"] == []
    net.remove_rule(rule)
    timer.run_to_completion()
    assert len(inboxes["N1"]) == 1 and len(inboxes["N2"]) == 1


def test_deliver_rule_controls_delay():
    timer, net, inboxes = _mk_pool()
    net.add_rule(Deliver(5.0, 5.0), match_type(Checkpoint))
    net._peers["N0"].send(_chk())
    timer.advance(4.9)
    assert inboxes["N1"] == []
    timer.advance(0.2)
    assert len(inboxes["N1"]) == 1


def test_determinism_same_seed_same_trace():
    traces = []
    for _ in range(2):
        timer, net, inboxes = _mk_pool(n=4, seed=123)
        net.add_rule(Discard(0.5), match_type(Checkpoint))
        for k in range(20):
            net._peers["N0"].send(_chk(end=k))
        timer.run_to_completion()
        traces.append([m.seq_no_end for (m, _) in inboxes["N1"]])
    assert traces[0] == traces[1]


def test_connected_events():
    timer = MockTimer()
    net = SimNetwork(timer)
    seen = []
    b0 = net.create_peer("N0")
    b0.subscribe(type(b0).Connected, lambda m, frm: seen.append(m.name))
    net.create_peer("N1")
    net.connect_all()
    assert seen == ["N1"]
    assert b0.connecteds == {"N1"}


def test_multihost_api_single_process():
    """init_multihost + global_mesh + shard_host_batch drive the sharded
    crypto plane on the (virtual, 8-device) single-process job — the same
    call sequence a multi-host deployment uses."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from plenum_tpu.parallel.multihost import (global_mesh, init_multihost,
                                               shard_host_batch)

    init_multihost()                       # single-process: no coordinator
    mesh = global_mesh(8)
    assert mesh.devices.size == 8 and mesh.axis_names == ("inst", "sig")

    arr = np.arange(8 * 4, dtype=np.int64).reshape(8, 4)
    garr = shard_host_batch(mesh, arr, P(("inst", "sig"), None))
    assert garr.shape == (8, 4)
    np.testing.assert_array_equal(np.asarray(garr), arr)


# --- topology-aware fault model ---------------------------------------------
# LinkProfile/Topology/make_topology: per-link latency+jitter+loss+bandwidth,
# all drawn through the fabric's SimRandom so profiled runs stay replayable.

from plenum_tpu.network import LinkProfile, Topology, make_topology


def _timed_pool(n=4, seed=7, topology=None):
    timer = MockTimer()
    net = SimNetwork(timer, SimRandom(seed), topology=topology)
    arrivals = {}
    for i in range(n):
        name = f"N{i}"
        bus = net.create_peer(name)
        arrivals[name] = []
        bus.subscribe(Checkpoint,
                      lambda m, frm, box=arrivals[name], t=timer:
                      box.append((t.get_current_time(), m, frm)))
    net.connect_all()
    return timer, net, arrivals


def test_topology_regions_shape_latency():
    """geo3: same-region delivery is millisecond-scale, cross-region pays
    the inter-region propagation delay."""
    topo = make_topology("geo3", ["N0", "N1", "N2", "N3"])
    # round-robin assignment: N0->geo0, N1->geo1, N2->geo2, N3->geo0
    assert topo.region_of("N0") == topo.region_of("N3") == "geo0"
    assert topo.region_of("N1") == "geo1"
    timer, net, arrivals = _timed_pool(topology=topo)
    net._peers["N0"].send(_chk(), dst=["N3"])       # intra-region
    net._peers["N0"].send(_chk(), dst=["N1"])       # cross-region
    timer.run_to_completion()
    t_intra = arrivals["N3"][0][0]
    t_inter = arrivals["N1"][0][0]
    assert t_intra < 0.01, t_intra
    assert t_inter >= 0.04, t_inter                 # >= base inter delay


def test_lossy_wan_drops_are_counted_and_seeded():
    """lossy_wan drops a seeded fraction cross-region and counts every
    loss; the same seed reproduces the identical loss pattern."""
    traces = []
    for _ in range(2):
        topo = make_topology("lossy_wan", ["N0", "N1"], n_regions=2)
        timer, net, arrivals = _timed_pool(n=2, seed=99, topology=topo)
        for k in range(200):
            net._peers["N0"].send(_chk(end=k), dst=["N1"])
        timer.run_to_completion()
        got = [m.seq_no_end for (_, m, _) in arrivals["N1"]]
        assert net.lost_count > 0
        assert len(got) + net.lost_count == 200
        traces.append((net.lost_count, sorted(got)))
    assert traces[0] == traces[1]


def test_bandwidth_cap_spreads_bursts():
    """A burst over a thin link serializes: the last frame's arrival
    reflects queueing behind the burst, not one flat propagation delay."""
    thin = LinkProfile(base_delay=0.01, jitter=0.0, loss=0.0,
                      bandwidth=10_000.0)          # 10 kB/s
    topo = Topology(["a", "b"], links={("a", "b"): thin,
                                       ("b", "a"): thin})
    topo.assign("N0", "a")
    topo.assign("N1", "b")
    timer, net, arrivals = _timed_pool(n=2, topology=topo)
    for k in range(20):
        net._peers["N0"].send(_chk(end=k), dst=["N1"])
    timer.run_to_completion()
    times = [t for (t, _, _) in arrivals["N1"]]
    assert len(times) == 20
    size = net.tx_msgs["CHECKPOINT"][1] / 20        # bytes per message
    expect_last = 0.01 + 20 * size / 10_000.0
    assert max(times) >= expect_last * 0.9
    # and the spread is real: first arrival well before the last
    assert min(times) < max(times) / 2


def test_explicit_rules_override_topology():
    """Scenario faults compose ON TOP of the WAN profile: a Deliver rule
    still pins its own delay, a Discard still kills the message."""
    topo = make_topology("geo3", [f"N{i}" for i in range(4)])
    timer, net, arrivals = _timed_pool(topology=topo)
    net.add_rule(Deliver(5.0, 5.0), match_dst("N1"))
    net.add_rule(Discard(), match_dst("N2"))
    net._peers["N0"].send(_chk())
    timer.run_to_completion()
    assert arrivals["N1"][0][0] >= 5.0
    assert arrivals["N2"] == []


def test_topology_assigns_churned_peers_deterministically():
    """A peer created after construction (membership churn: a joiner) is
    auto-assigned round-robin — same join order, same placement."""
    topo = make_topology("geo3", ["N0", "N1", "N2"])
    first = topo.region_of("Joiner")
    topo2 = make_topology("geo3", ["N0", "N1", "N2"])
    assert topo2.region_of("Joiner") == first
