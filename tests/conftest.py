"""Test configuration.

Per the build contract: tests run JAX on CPU with 8 virtual devices so
multi-chip sharding is exercised without TPU hardware.

NOTE: the env-var route (JAX_PLATFORMS=cpu) does NOT work in this image — the
axon TPU plugin overrides it at registration time and jax.devices() still
returns the tunneled TPU. jax.config.update is the only knob that sticks, and
it must run before the first backend query.
"""
import os

# Keep the env vars too for subprocesses that re-exec python.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5) has no such option; the XLA_FLAGS
    # host-platform device count set above provides the 8 devices
    pass
# NOTE: x64 deliberately NOT enabled — the kernels are int32 (radix-13
# limbs) and production runs with default dtypes; tests must match.

# NOTE: the persistent compile cache is configured by plenum_tpu.ops
# (~/.cache/plenum_tpu/jax) — kernels cache across runs automatically.

import pytest  # noqa: E402


@pytest.fixture
def tdir(tmp_path):
    return str(tmp_path)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running e2e tests (process pools, fuzzing)")
