"""Test configuration.

Per the build contract: tests run JAX on CPU with 8 virtual devices so
multi-chip sharding is exercised without TPU hardware.

NOTE: the env-var route (JAX_PLATFORMS=cpu) does NOT work in this image — the
axon TPU plugin overrides it at registration time and jax.devices() still
returns the tunneled TPU. jax.config.update is the only knob that sticks, and
it must run before the first backend query.
"""
import os

# Keep the env vars too for subprocesses that re-exec python.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5) has no such option; the XLA_FLAGS
    # host-platform device count set above provides the 8 devices
    pass
# NOTE: x64 deliberately NOT enabled — the kernels are int32 (radix-13
# limbs) and production runs with default dtypes; tests must match.

# NOTE: the persistent compile cache is configured by plenum_tpu.ops
# (~/.cache/plenum_tpu/jax) — kernels cache across runs automatically.

import json  # noqa: E402
import weakref  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def tdir(tmp_path):
    return str(tmp_path)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running e2e tests (process pools, fuzzing)")
    config.addinivalue_line(
        "markers", "soak: minutes-scale bounded-growth soaks "
                   "(tools/churn_soak.py; always also marked slow)")


# --- flight-recorder dump on failure ----------------------------------------
# Sim pools (test_pool.Pool) register here at construction; when a test
# fails, every still-alive registered pool's per-node flight-recorder ring
# (common/tracing.py) is appended to the test report, so a red test
# arrives with its last-seconds span/anomaly story instead of just an
# assertion message. Weak references: pools die with their tests, and a
# stale pool from an earlier (passed) test drops out as soon as it is
# collected.
FLIGHT_POOLS: "weakref.WeakSet" = weakref.WeakSet()


def register_pool_for_flight_dump(pool) -> None:
    FLIGHT_POOLS.add(pool)


def flight_ring_lines(max_events: int = 40) -> list[str]:
    """Render every registered pool's rings (newest events last)."""
    lines: list[str] = []
    for pool in list(FLIGHT_POOLS):
        for name, node in sorted(getattr(pool, "nodes", {}).items()):
            tracer = getattr(node, "tracer", None)
            if tracer is None or not getattr(tracer, "enabled", False):
                continue
            snap = tracer.snapshot()
            events = snap["events"][-max_events:]
            lines.append(f"--- {name}: {len(snap['events'])} ring events "
                         f"({snap['anomalies']} anomalies), last "
                         f"{len(events)} ---")
            lines.extend(json.dumps(ev, default=repr) for ev in events)
    return lines


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed:
        try:
            lines = flight_ring_lines()
        except Exception:
            lines = []
        if lines:
            rep.sections.append(("flight recorder", "\n".join(lines)))
