"""Test configuration.

Per the build contract: tests run JAX on CPU with 8 virtual devices so
multi-chip sharding is exercised without TPU hardware. Env must be set before
jax is imported anywhere.
"""
import os

# Force-overwrite: the environment presets JAX_PLATFORMS=axon (the TPU tunnel);
# tests must run on the 8-device virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import pytest  # noqa: E402


@pytest.fixture
def tdir(tmp_path):
    return str(tmp_path)
