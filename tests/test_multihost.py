"""Multi-host plane with REAL multiple processes (VERDICT r4 item 6).

Two OS processes join one jax.distributed job over the localhost
coordinator (gloo collectives on the CPU backend), each contributing 4
virtual devices to one 8-device global mesh, and run a shard_map program
using the crypto plane's collective pattern (all_gather of per-shard
reductions + psum of counts) through `global_mesh` + `shard_host_batch`.
Anchor: SURVEY §2.3 distributed-comm row; the single-host plane's SPMD
program (parallel/crypto_plane.py) runs over exactly this mesh/sharding
machinery on a multi-host deployment.
"""
import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]
from plenum_tpu.parallel.multihost import (init_multihost, global_mesh,
                                           shard_host_batch)
init_multihost(coordinator="127.0.0.1:" + port,
               num_processes=2, process_id=rank)
assert jax.process_count() == 2
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
mesh = global_mesh()                       # spans both processes' devices
assert mesh.devices.size == 8, mesh.devices.shape

# each "host" stages its local half of a [8, 16] batch (values encode the
# global row index so misplacement is detectable)
local = np.arange(4 * 16, dtype=np.float32).reshape(4, 16) + rank * 64
garr = shard_host_batch(mesh, local, P(("inst", "sig"), None))

from plenum_tpu.parallel.crypto_plane import _shard_map as shard_map

def step(x):
    # the plane's collective pattern: per-shard reduction, all_gather of
    # the partials (every device sees all of them), psum of a count
    part = jnp.sum(x)
    parts = jax.lax.all_gather(part, ("inst", "sig"))
    n = jax.lax.psum(jnp.asarray(1, jnp.int32), ("inst", "sig"))
    return parts, n

f = jax.jit(shard_map(step, mesh=mesh,
                      in_specs=(P(("inst", "sig"), None),),
                      out_specs=(P(None), P()),
                      check_vma=False))
parts, n = f(garr)
want = np.arange(128, dtype=np.float32).reshape(8, 16).sum(axis=1)
assert np.allclose(np.asarray(parts), want), np.asarray(parts)
assert int(n) == 8
print("RANK_OK", rank, flush=True)
"""


def test_two_process_distributed_mesh(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        cwd=str(tmp_path)) for r in range(2)]
    outs = []
    for r, p in enumerate(procs):
        out, _ = p.communicate(timeout=180)
        outs.append(out.decode())
        if "Multiprocess computations aren't implemented" in outs[-1]:
            for q in procs:
                q.kill()
            pytest.skip("this jax build has no cross-process CPU "
                        "collectives (gloo backend missing)")
        assert p.returncode == 0, f"rank{r} failed:\n{outs[-1]}"
    assert "RANK_OK 0" in outs[0]
    assert "RANK_OK 1" in outs[1]
