"""7-node / f=2 pool (a BASELINE.json config): 3 RBFT instances, ordering
under load, and recovery from TWO simultaneous node failures including the
primary. The TCP variant proves the asyncio stack's O(n^2) mesh (42
directed connections) holds up beyond 4 nodes.
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.network import Discard, match_dst, match_frm

from test_pool import Pool, signed_nym

SEVEN = ["Alpha", "Beta", "Gamma", "Delta", "Epsilon", "Zeta", "Eta"]


def test_seven_node_pool_orders_and_survives_f_failures():
    pool = Pool(names=SEVEN, config=Config(
        Max3PCBatchWait=0.05, PRIMARY_HEALTH_CHECK_FREQ=0.5,
        ORDERING_PROGRESS_TIMEOUT=2.0,
        STATE_FRESHNESS_UPDATE_INTERVAL=3.0))
    node = pool.nodes["Alpha"]
    assert node.f == 2
    assert len(node.replicas) == 3            # f+1 instances

    for i in range(5):
        user = Ed25519Signer(seed=(b"7n-u%d" % i).ljust(32, b"\0"))
        pool.submit(signed_nym(pool.trustee, user, i + 1))
    pool.run(8.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {6}, sizes

    # cut off the master primary AND one other node (exactly f=2 faults)
    primary = node.master_replica.data.primary_name
    other = next(n for n in pool.names if n != primary)
    for victim in (primary, other):
        pool.net.add_rule(Discard(), match_dst(victim))
        pool.net.add_rule(Discard(), match_frm(victim))
    survivors = [n for n in pool.names if n not in (primary, other)]

    user = Ed25519Signer(seed=b"7n-after-vc".ljust(32, b"\0"))
    pool.submit(signed_nym(pool.trustee, user, 10), to=survivors)
    # with exactly n-f=5 live nodes every view change needs ALL survivors
    # timely, so convergence can take several rounds — give it room
    pool.run(60.0)
    for n in survivors:
        assert pool.nodes[n].master_replica.view_no >= 1, \
            f"{n} never left view 0"
        assert pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 7, n
    roots = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).root_hash
             for n in survivors}
    assert len(roots) == 1


@pytest.mark.slow
def test_seven_node_pool_over_real_tcp():
    """The asyncio TCP stack at 7 nodes / f=2: 42 directed encrypted
    connections, 7 OS processes, real NYM load ordered pool-wide
    (VERDICT r2: no scale datum existed for the TCP stack beyond 4)."""
    pytest.importorskip(
        "cryptography",
        reason="the TCP node stack's handshake needs the cryptography package")
    from plenum_tpu.tools.tcp_pool import run_tcp_pool

    stats = run_tcp_pool(n_nodes=7, n_txns=60, timeout=120.0)
    assert stats["txns_ordered"] == 60, stats
    assert stats["tps"] > 1.0
    assert stats["p50_latency_ms"] < 30_000
