"""Closed-loop batch controller + deep-pipeline ordering tests.

Controller determinism: every sample the controller sees is stamped on the
injectable timer and every decision is a pure function of those samples —
these tests drive MockTimer and assert exact knob movements (no wall-clock
reads anywhere in the control path). The pipeline tests use the PoolSim
service harness from test_consensus.
"""
import pytest

from plenum_tpu.common.internal_messages import ViewChangeStarted
from plenum_tpu.common.node_messages import Checkpoint, Commit
from plenum_tpu.common.timer import MockTimer
from plenum_tpu.common import tracing
from plenum_tpu.config import Config
from plenum_tpu.consensus.batch_controller import (BatchController,
                                                   make_controller)
from plenum_tpu.network import Discard, Stash, match_type

from test_consensus import NODES, PoolSim, make_request


def make_ctl(timer=None, **overrides) -> BatchController:
    cfg = Config(**overrides)
    return BatchController(cfg, timer or MockTimer())


# --- controller policy (pure, deterministic) -------------------------------


def test_idle_tick_holds_every_knob():
    ctl = make_ctl()
    before = (ctl.batch_size, ctl.batch_wait, ctl.depth,
              ctl.group_commit_max)
    ctl.tick()
    assert (ctl.batch_size, ctl.batch_wait, ctl.depth,
            ctl.group_commit_max) == before
    assert ctl.decisions == 0


def test_queueing_dominated_shrinks_wait_and_full_batches():
    """SLO violated with queue wait the largest stage: requests spend
    their latency WAITING — the wait shrinks multiplicatively, and the
    batch size too when batches are being cut full."""
    ctl = make_ctl(Max3PCBatchWait=0.1, BATCH_SLO_P95=0.2)
    for _ in range(20):
        ctl.note_batch_cut(queue_wait=0.5, n_reqs=ctl.batch_size)  # full
        ctl.note_ordered(0.01)
    size0, wait0 = ctl.batch_size, ctl.batch_wait
    ctl.tick()
    assert ctl.last_decision["verdict"] == "shrink:queueing"
    assert ctl.batch_wait == pytest.approx(wait0 * 0.5)
    assert ctl.batch_size < size0
    # repeated pressure floors at the configured bounds, never below
    for _ in range(40):
        for _ in range(4):
            ctl.note_batch_cut(0.5, ctl.batch_size)
            ctl.note_ordered(0.01)
        ctl.tick()
    assert ctl.batch_wait == pytest.approx(Config().BATCH_WAIT_MIN)
    assert ctl.batch_size == Config().BATCH_SIZE_MIN


def test_fixed_cost_dominated_grows_wait_and_coalescing():
    """SLO violated, batches underfull, 3PC span dominant: per-batch fixed
    costs are being paid on near-empty batches — the wait GROWS so more
    requests coalesce per batch (sim25's shape: tiny batches, n-squared
    vote flood per batch)."""
    ctl = make_ctl(Max3PCBatchWait=0.05, BATCH_SLO_P95=0.2,
                   GROUP_COMMIT_MAX_BATCHES=32)
    assert ctl.group_commit_max == 8      # starts below the cap (room to act)
    for _ in range(20):
        ctl.note_batch_cut(queue_wait=0.01, n_reqs=30)   # 3% full
        ctl.note_ordered(0.5)                            # costly 3PC
    wait0, coal0 = ctl.batch_wait, ctl.group_commit_max
    ctl.tick()
    assert ctl.last_decision["verdict"] == "grow:fixed-cost"
    assert ctl.batch_wait == pytest.approx(wait0 * 1.5)
    assert ctl.group_commit_max == coal0 + 4
    # and it caps at BATCH_WAIT_MAX under sustained pressure
    for _ in range(40):
        for _ in range(4):
            ctl.note_batch_cut(0.01, 30)
            ctl.note_ordered(0.5)
        ctl.tick()
    assert ctl.batch_wait == pytest.approx(Config().BATCH_WAIT_MAX)


def test_saturated_full_batches_shrink_depth():
    """SLO violated with FULL batches and service-side spans dominant:
    genuinely too much in flight — the speculative window backs off."""
    ctl = make_ctl(BATCH_SLO_P95=0.2)
    depth0 = ctl.depth
    for _ in range(20):
        ctl.note_batch_cut(queue_wait=0.01, n_reqs=ctl.batch_size)
        ctl.note_ordered(0.5)
    ctl.tick()
    assert ctl.last_decision["verdict"] == "shrink:depth"
    assert ctl.depth == int(depth0 * 0.7)
    # floors at the legacy window of 4, never a dead pipeline
    for _ in range(40):
        for _ in range(4):
            ctl.note_batch_cut(0.01, ctl.batch_size)
            ctl.note_ordered(0.5)
        ctl.tick()
    assert ctl.depth == 4


def test_headroom_deepens_and_decays_grown_wait():
    ctl = make_ctl(Max3PCBatchWait=0.05, BATCH_SLO_P95=0.5,
                   Max3PCBatchesInFlight=64)
    ctl.depth = 10
    ctl.batch_wait = 0.4                   # left high by a past episode
    ctl.group_commit_max = 20              # ditto
    for _ in range(10):
        ctl.note_batch_cut(queue_wait=0.001, n_reqs=ctl.batch_size)
        ctl.note_ordered(0.005)
    size0 = ctl.batch_size
    ctl.tick()
    assert ctl.last_decision["verdict"] == "grow:headroom"
    assert ctl.depth == 11                 # additive increase
    assert ctl.batch_size == size0         # already at the config cap
    assert ctl.batch_wait == pytest.approx(0.4 * 0.9)
    assert ctl.group_commit_max == 19      # decays toward its start value


def test_load_shift_moves_knobs_in_expected_direction():
    """The acceptance shape: a deterministic load shift on the injectable
    timer moves the chosen knobs the expected way — light load grows the
    window, a queue-wait storm shrinks wait/size, and recovery grows the
    window again."""
    timer = MockTimer()
    cfg = Config(Max3PCBatchWait=0.05, BATCH_SLO_P95=0.2,
                 BATCH_CONTROL_INTERVAL=0.5)
    ctl = BatchController(cfg, timer)
    ctl.depth = 8

    def feed(n, wait, fill, span):
        for _ in range(n):
            ctl.note_batch_cut(wait, fill)
            ctl.note_ordered(span)
        timer.advance(0.5)
        ctl.note_ordered(span)    # first sample past the deadline decides

    feed(10, wait=0.001, fill=ctl.batch_size, span=0.01)   # light
    assert ctl.depth == 9
    depth_light = ctl.depth
    size_light = ctl.batch_size
    for _ in range(3):                                     # overload
        feed(10, wait=0.6, fill=ctl.batch_size, span=0.01)
    assert ctl.batch_wait < 0.05 and ctl.batch_size < size_light
    feed(10, wait=0.001, fill=ctl.batch_size, span=0.01)   # recovery
    assert ctl.depth == depth_light + 1
    assert ctl.decisions == 5


def test_decisions_ride_the_tracer():
    timer = MockTimer()
    tracer = tracing.Tracer("N", timer.get_current_time)
    ctl = BatchController(Config(BATCH_SLO_P95=0.2), timer, tracer=tracer)
    ctl.note_batch_cut(0.5, ctl.batch_size)
    ctl.note_ordered(0.01)
    ctl.tick()
    events = [e for e in tracer.ring if e[1] == tracing.CONTROLLER]
    assert len(events) == 1
    assert events[0][3]["verdict"] == "shrink:queueing"
    assert events[0][3]["slo_ms"] == 200.0


def test_make_controller_config_gate():
    assert make_controller(Config(BATCH_CONTROLLER=False), MockTimer()) is None
    assert make_controller(Config(), MockTimer()) is not None


# --- satellite regression: the leftover-queue wait clock -------------------


def test_partial_batch_wait_clock_survives_inflight_backpressure():
    """Regression: send_3pc_batch used to re-arm the per-ledger wait clock
    on every prod tick that left a leftover queue — so while the in-flight
    gate held fresh cuts back, a queued partial batch's Max3PCBatchWait
    restarted every tick, and after the gate opened it still waited one
    FULL extra period. The enqueue stamp now rides the queue entry itself:
    once capacity frees, a request that has already waited out the bound
    is cut on the next service pass."""
    pool = PoolSim(config=Config(Max3PCBatchWait=1.0,
                                 Max3PCBatchesInFlight=1,
                                 BATCH_CONTROLLER=False))
    pool.net.set_latency(0.001, 0.01)     # keep delivery ≪ the batch wait
    primary = pool.primary_name()
    ordering = pool.replicas[primary].ordering
    # batch 1 occupies the whole in-flight window (commits stashed)
    rule = pool.net.add_rule(Stash(), match_type(Commit))
    pool.finalize_request(make_request(0))
    pool.run(1.5)
    assert pool.replicas[primary].data.pp_seq_no == 1
    assert not pool.ordered[primary]
    # a second request arrives and waits OUT its full bound behind the gate
    pool.finalize_request(make_request(1))
    pool.run(2.0)
    assert pool.replicas[primary].data.pp_seq_no == 1   # gate held
    # heal: stashed commits deliver, batch 1 orders, the gate opens —
    # the overdue partial batch must cut on the next service pass, NOT
    # after another full Max3PCBatchWait
    pool.net.remove_rule(rule)
    pool.run(0.5, step=0.25)
    assert pool.replicas[primary].data.pp_seq_no == 2, \
        "overdue partial batch waited a fresh full period after the " \
        "in-flight gate opened (wait clock was re-armed)"


# --- deep pipeline ---------------------------------------------------------


def test_deep_window_pins_at_high_watermark_and_resumes():
    """Speculative cuts run to the high watermark and STOP (the protocol
    bound); once checkpoints stabilize and the window slides, the backlog
    drains. LOG_SIZE=4 with CHK_FREQ=2 so the boundary is cheap to hit."""
    pool = PoolSim(config=Config(Max3PCBatchSize=1, Max3PCBatchWait=0.0,
                                 CHK_FREQ=2, LOG_SIZE=4,
                                 BATCH_CONTROLLER=False,
                                 Max3PCBatchesInFlight=300))
    primary = pool.primary_name()
    # hold checkpoint traffic: the watermark window cannot slide
    rule = pool.net.add_rule(Stash(), match_type(Checkpoint))
    for i in range(10):
        pool.finalize_request(make_request(i))
    pool.run(5.0)
    data = pool.replicas[primary].data
    assert data.pp_seq_no == data.high_watermark == 4, \
        f"primary ran past the watermark window: {data.pp_seq_no}"
    assert sum(len(q) for q in
               pool.replicas[primary].ordering.request_queues.values()) == 6
    # heal: checkpoints stabilize, the window slides, the backlog drains
    pool.net.remove_rule(rule)
    pool.run(8.0)
    for name in NODES:
        assert [o.pp_seq_no for o in pool.ordered[name]] == list(range(1, 11))


def _slow_commit_cut_depth(depth: int) -> tuple[int, int]:
    """-> (pp_seq_no cut, batches ordered) at a fixed sim time, with every
    COMMIT delayed 1.0 s and a steady request trickle."""
    pool = PoolSim(config=Config(Max3PCBatchSize=1, Max3PCBatchWait=0.0,
                                 BATCH_CONTROLLER=False,
                                 Max3PCBatchesInFlight=depth))
    pool.net.set_latency(0.001, 0.002)
    from plenum_tpu.network import Deliver
    pool.net.add_rule(Deliver(1.0, 1.0), match_type(Commit))
    primary = pool.primary_name()
    for i in range(30):
        pool.finalize_request(make_request(i))
        pool.run(0.05, step=0.05)
    pool.run(0.5, step=0.05)
    return (pool.replicas[primary].data.pp_seq_no,
            len(pool.ordered[primary]))


def test_deep_window_decouples_cuts_from_slow_commits():
    """The tentpole's core claim, deterministically: with COMMITs slowed to
    1 s, the legacy 4-deep window stalls every fresh cut behind the oldest
    uncommitted batch, while the deep window keeps cutting speculative
    batches — same pool, same trickle, same sim clock."""
    deep_cut, deep_ordered = _slow_commit_cut_depth(64)
    legacy_cut, legacy_ordered = _slow_commit_cut_depth(4)
    assert legacy_cut <= legacy_ordered + 4     # the old hard ceiling
    assert deep_cut >= legacy_cut * 2, \
        f"deep window cut only {deep_cut} vs legacy {legacy_cut}"
    assert deep_ordered >= legacy_ordered


def test_view_change_reverts_deep_speculative_stack_in_reverse():
    """N>4 speculative uncommitted applies revert in EXACT reverse apply
    order on a view change (the deep-pipeline extension of the reference's
    _revert contract)."""
    n_batches = 7
    pool = PoolSim(config=Config(Max3PCBatchSize=1, Max3PCBatchWait=0.0,
                                 Max3PCBatchesInFlight=300))
    primary = pool.primary_name()
    executor = pool.executors[primary]
    rule = pool.net.add_rule(Discard(), match_type(Commit))
    for i in range(n_batches):
        pool.finalize_request(make_request(i))
    pool.run(3.0)
    applied = list(executor.applied)
    assert len(applied) == n_batches > 4
    reverted = []
    original = executor.revert_last_batch

    def spying_revert(ledger_id):
        reverted.append(executor.applied[-1])
        original(ledger_id)

    executor.revert_last_batch = spying_revert
    pool.replicas[primary].ordering.process_view_change_started(
        ViewChangeStarted(view_no=1))
    assert reverted == list(reversed(applied))
    assert executor.applied == []
    pool.net.remove_rule(rule)
