"""NetworkInconsistencyWatcher unit tests (ref inconsistency_watchers.py:5):
the callback fires exactly on the strong-connectivity-then-lost-weak edge.
"""
from plenum_tpu.node.inconsistency_watcher import NetworkInconsistencyWatcher


def _watcher(n=4):
    fired = []
    w = NetworkInconsistencyWatcher(lambda: fired.append(1))
    w.set_nodes([f"N{i}" for i in range(n)])
    return w, fired


def test_fires_after_strong_then_below_weak():
    w, fired = _watcher(4)            # f=1: strong=3 peers, weak=2
    for p in ("N1", "N2", "N3"):
        w.connect(p)                  # strong connectivity reached
    w.disconnect("N1")
    assert not fired                  # 2 left: still >= weak
    w.disconnect("N2")
    assert len(fired) == 1            # 1 left: below weak -> fire


def test_never_fires_without_reaching_strong_first():
    w, fired = _watcher(4)
    w.connect("N1")
    w.connect("N2")                   # weak yes, strong never
    w.disconnect("N1")
    w.disconnect("N2")
    assert not fired


def test_one_shot_until_strong_again():
    w, fired = _watcher(4)
    for p in ("N1", "N2", "N3"):
        w.connect(p)
    for p in ("N1", "N2", "N3"):
        w.disconnect(p)
    assert len(fired) == 1            # no repeat fire on further drops
    w.connect("N1")
    w.disconnect("N1")
    assert len(fired) == 1            # weak alone does not re-arm
    for p in ("N1", "N2", "N3"):
        w.connect(p)                  # strong re-arms
    w.disconnect("N1")
    w.disconnect("N2")
    assert len(fired) == 2


def test_no_fire_before_membership_known():
    fired = []
    w = NetworkInconsistencyWatcher(lambda: fired.append(1))
    w.connect("N1")
    w.disconnect("N1")                # Quorums(0) must not trip anything
    assert not fired


def test_membership_growth_rescales_thresholds():
    w, fired = _watcher(4)
    for p in ("N1", "N2", "N3"):
        w.connect(p)
    w.set_nodes([f"N{i}" for i in range(7)])   # f=2: weak=3 peers
    w.disconnect("N1")                # 2 connected < weak(3) -> fire
    assert len(fired) == 1


def test_bus_events_drive_the_watcher():
    from plenum_tpu.common.event_bus import ExternalBus
    bus = ExternalBus(lambda msg, dst: None)
    fired = []
    w = NetworkInconsistencyWatcher(lambda: fired.append(1), network=bus)
    w.set_nodes(["A", "B", "C", "D"])
    bus.update_connecteds({"B", "C", "D"})
    bus.update_connecteds(set())
    assert len(fired) == 1
