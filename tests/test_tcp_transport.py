"""Real-socket transport tests: handshake, allowlist, batching, reconnect,
and a full 4-node pool ordering a NYM over localhost TCP.

Reference test model: stp_zmq tests (connect/auth) + the pool e2e NYM flow
(SURVEY.md §4). Everything runs in one asyncio loop — real sockets, no OS
process per node.
"""
from __future__ import annotations

import asyncio
import hashlib
import time

import pytest

pytest.importorskip(
    "cryptography",
    reason="the TCP node stack's handshake needs the cryptography package")

from plenum_tpu.common.node_messages import InstanceChange
from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.network.tcp_stack import ClientStack, NodeRegistry, TcpStack


def _seed(name: str) -> bytes:
    return hashlib.sha256(b"tcp-test-" + name.encode()).digest()


def _vk(seed: bytes) -> bytes:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey)
    sk = Ed25519PrivateKey.from_private_bytes(seed)
    return sk.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw)


async def _make_pair(names=("Alpha", "Beta")):
    reg = NodeRegistry()
    stacks = {}
    for n in names:
        stacks[n] = TcpStack(n, "127.0.0.1", 0, reg, _seed(n))
        port = await stacks[n].bind()
        reg.set(n, "127.0.0.1", port, stacks[n].verkey)
    for n in names:
        await stacks[n].start()
    return reg, stacks


async def _wait(cond, timeout=5.0, interval=0.01):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


def test_handshake_and_message_roundtrip():
    async def main():
        reg, stacks = await _make_pair()
        a, b = stacks["Alpha"], stacks["Beta"]
        assert await _wait(lambda: a.connected == {"Beta"}
                           and b.connected == {"Alpha"})

        got = []
        b.bus.subscribe(InstanceChange,
                        lambda msg, frm: got.append((msg, frm)))
        a.bus.send(InstanceChange(view_no=3, reason=0), "Beta")
        assert await _wait(lambda: b.drain() + len(got) and got)
        msg, frm = got[0]
        assert isinstance(msg, InstanceChange) and msg.view_no == 3
        assert frm == "Alpha"

        # and the reverse direction (acceptor -> dialer)
        got_a = []
        a.bus.subscribe(InstanceChange,
                        lambda msg, frm: got_a.append((msg, frm)))
        b.bus.send(InstanceChange(view_no=7, reason=0), "Alpha")
        assert await _wait(lambda: a.drain() + len(got_a) and got_a)
        assert got_a[0][0].view_no == 7 and got_a[0][1] == "Beta"

        # Connected events reached the bus subscribers
        assert a.bus.connecteds == {"Beta"}
        await a.stop()
        await b.stop()

    asyncio.run(main())


def test_allowlist_rejects_unknown_verkey():
    async def main():
        reg, stacks = await _make_pair()
        a, b = stacks["Alpha"], stacks["Beta"]
        assert await _wait(lambda: a.connected == {"Beta"})

        # an impostor dialing Beta with a key not in the registry: the
        # acceptor must refuse (ZAP allowlist, zstack.py:322)
        evil_reg = NodeRegistry()
        evil_reg.set("Beta", "127.0.0.1", b.port, b.verkey)
        evil = TcpStack("AAAevil", "127.0.0.1", 0, evil_reg,
                        _seed("not-in-registry"))
        await evil.bind()
        evil.maintain_connections()
        await asyncio.sleep(0.5)
        assert evil.connected == set()
        assert b.stats["rejected"] >= 1
        assert b.connected == {"Alpha"}      # honest session unaffected
        await evil.stop()
        await a.stop()
        await b.stop()

    asyncio.run(main())


def test_outbox_batching_one_frame_per_flush():
    async def main():
        reg, stacks = await _make_pair()
        a, b = stacks["Alpha"], stacks["Beta"]
        assert await _wait(lambda: a.connected == {"Beta"})
        base = a.stats["sent_frames"]
        got = []
        b.bus.subscribe(InstanceChange, lambda m, f: got.append(m))
        for v in range(50):
            a.bus.send(InstanceChange(view_no=v, reason=0), "Beta")
        assert await _wait(lambda: (b.drain(), len(got))[1] >= 50)
        # 50 messages coalesced into one encrypted frame (batched.py:20)
        assert a.stats["sent_frames"] == base + 1
        assert [m.view_no for m in got] == list(range(50))
        await a.stop()
        await b.stop()

    asyncio.run(main())


def test_queued_outbox_flushes_after_reconnect():
    async def main():
        reg = NodeRegistry()
        a = TcpStack("Alpha", "127.0.0.1", 0, reg, _seed("Alpha"))
        await a.bind()
        reg.set("Alpha", "127.0.0.1", a.port, a.verkey)
        # Beta is registered but not yet listening: messages queue
        beta_seed = _seed("Beta")
        reg.set("Beta", "127.0.0.1", 1, _vk(beta_seed))  # dead port
        await a.start()
        a.bus.send(InstanceChange(view_no=9, reason=0), "Beta")
        await asyncio.sleep(0.3)
        assert a.connected == set()

        # now Beta comes up on a real port; update registry; dialer retries
        b = TcpStack("Beta", "127.0.0.1", 0, reg, beta_seed)
        port = await b.bind()
        reg.set("Beta", "127.0.0.1", port, b.verkey)
        await b.start()
        got = []
        b.bus.subscribe(InstanceChange, lambda m, f: got.append(m))
        assert await _wait(lambda: (b.drain(), len(got))[1] >= 1, timeout=8.0)
        assert got[0].view_no == 9           # queued message survived
        await a.stop()
        await b.stop()

    asyncio.run(main())


def test_session_supersede_on_peer_restart():
    async def main():
        reg, stacks = await _make_pair()
        a, b = stacks["Alpha"], stacks["Beta"]
        assert await _wait(lambda: a.connected == {"Beta"})
        b_port = b.port
        await b.stop()
        assert await _wait(lambda: a.connected == set(), timeout=5.0)

        # Beta restarts on the SAME port with the same identity
        b2 = TcpStack("Beta", "127.0.0.1", b_port, reg, _seed("Beta"))
        await b2.start()
        assert await _wait(lambda: a.connected == {"Beta"}
                           and b2.connected == {"Alpha"}, timeout=8.0)
        got = []
        b2.bus.subscribe(InstanceChange, lambda m, f: got.append(m))
        a.bus.send(InstanceChange(view_no=4, reason=0), "Beta")
        assert await _wait(lambda: (b2.drain(), len(got))[1] >= 1)
        await a.stop()
        await b2.stop()

    asyncio.run(main())


# --- full pool over real sockets -----------------------------------------

def _build_tcp_pool(n_nodes=4):
    """Nodes + TCP stacks + client stacks in one loop; returns the parts."""
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    from plenum_tpu.common.timer import QueueTimer
    from plenum_tpu.config import Config
    from plenum_tpu.node import Node, NodeBootstrap
    from plenum_tpu.node.looper import Looper, Prodable
    from plenum_tpu.tools.local_pool import build_genesis

    names = [f"Node{i + 1}" for i in range(n_nodes)]
    genesis, trustee = build_genesis(names)
    reg = NodeRegistry()
    config = Config(Max3PCBatchWait=0.005,
                    STATE_FRESHNESS_UPDATE_INTERVAL=600.0)
    looper = Looper(prod_interval=0.002)
    nodes, node_stacks, client_stacks = {}, {}, {}

    async def setup():
        for name in names:
            stack = TcpStack(name, "127.0.0.1", 0, reg, _seed(name))
            await stack.bind()
            reg.set(name, "127.0.0.1", stack.port, stack.verkey)
            node_stacks[name] = stack
        for name in names:
            components = NodeBootstrap(name, genesis_txns=genesis).build()
            timer = QueueTimer(time.perf_counter)
            cstack = ClientStack(name, "127.0.0.1", 0, on_request=None)
            node = Node(name, timer, node_stacks[name].bus, components,
                        client_send=cstack.send, config=config)
            cstack._on_request = node.handle_client_message
            nodes[name] = node
            client_stacks[name] = cstack
            looper.add(Prodable(node, node_stacks[name], cstack, timer))

    return names, reg, looper, nodes, client_stacks, setup, trustee


@pytest.mark.slow
def test_pool_orders_nym_over_tcp():
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM

    (names, reg, looper, nodes, client_stacks,
     setup, trustee) = _build_tcp_pool()

    async def main():
        await setup()
        async with looper:
            # all nodes fully meshed
            ok = await looper.run_until(
                lambda: all(len(n.node_bus.connecteds) == 3
                            for n in nodes.values()), timeout=10.0)
            assert ok, "pool never meshed over TCP"

            # a real TCP client submits a signed NYM to every node
            user = Ed25519Signer(seed=b"tcp-pool-user".ljust(32, b"\0"))
            req = Request(trustee.identifier, 1,
                          {"type": NYM, "dest": user.identifier,
                           "verkey": user.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())
            replies = []

            async def submit(name):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", client_stacks[name].port)
                data = pack(req.to_dict())
                writer.write(len(data).to_bytes(4, "big") + data)
                await writer.drain()
                try:
                    while True:
                        hdr = await asyncio.wait_for(
                            reader.readexactly(4), timeout=15.0)
                        frame = await reader.readexactly(
                            int.from_bytes(hdr, "big"))
                        msg = unpack(frame)
                        replies.append(msg)
                        if msg.get("op") == "REPLY":
                            break
                except (asyncio.TimeoutError, asyncio.IncompleteReadError):
                    pass
                writer.close()

            await asyncio.gather(*(submit(n) for n in names))
            assert any(m.get("op") == "REPLY" for m in replies), replies

            sizes = {nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
                     for n in names}
            assert sizes == {2}, sizes       # genesis NYM + the new one

    asyncio.run(main())


@pytest.mark.slow
def test_primary_crash_recovers_within_disconnect_timeout():
    """Kill the primary (stop prodding + close its sockets): survivors see
    the TCP disconnect, vote PRIMARY_DISCONNECTED after
    PRIMARY_DISCONNECT_TIMEOUT, complete a view change, and order a
    pending NYM — with the stall/freshness watchdogs configured far too
    slow (600s) to be the cause (ref primary_connection_monitor_service)."""
    from plenum_tpu.common.node_messages import DOMAIN_LEDGER_ID
    from plenum_tpu.common.request import Request
    from plenum_tpu.crypto.ed25519 import Ed25519Signer
    from plenum_tpu.execution.txn import NYM

    (names, reg, looper, nodes, client_stacks,
     setup, trustee) = _build_tcp_pool()

    async def main():
        await setup()
        # only the disconnect fast path may fire inside this test's window
        for node in nodes.values():
            node.config.PRIMARY_DISCONNECT_TIMEOUT = 2.0
            node.config.ORDERING_PROGRESS_TIMEOUT = 600.0
            node.config.STATE_FRESHNESS_UPDATE_INTERVAL = 600.0
        async with looper:
            ok = await looper.run_until(
                lambda: all(len(n.node_bus.connecteds) == 3
                            for n in nodes.values()), timeout=10.0)
            assert ok, "pool never meshed over TCP"

            primary = nodes[names[0]].master_replica.data.primary_name
            survivors = [n for n in names if n != primary]
            victim = next(p for p in looper._prodables
                          if p.node is nodes[primary])
            victim.prod = lambda: 0          # the process is "dead"
            await victim.stop()              # sockets close underneath peers

            user = Ed25519Signer(seed=b"tcp-crash-user".ljust(32, b"\0"))
            req = Request(trustee.identifier, 1,
                          {"type": NYM, "dest": user.identifier,
                           "verkey": user.verkey_b58})
            req.signature = trustee.sign_b58(req.signing_bytes())

            async def submit(name):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", client_stacks[name].port)
                data = pack(req.to_dict())
                writer.write(len(data).to_bytes(4, "big") + data)
                await writer.drain()
                writer.close()

            await asyncio.gather(*(submit(n) for n in survivors))
            t0 = time.perf_counter()
            ok = await looper.run_until(
                lambda: all(
                    nodes[n].master_replica.view_no >= 1
                    and nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size == 2
                    for n in survivors),
                timeout=25.0)
            elapsed = time.perf_counter() - t0
            for n in survivors:
                assert nodes[n].master_replica.view_no >= 1, \
                    f"{n} never left view 0 (after {elapsed:.1f}s)"
                assert nodes[n].c.db.get_ledger(
                    DOMAIN_LEDGER_ID).size == 2, f"{n} did not order"
            # sanity: recovery rode the 2s disconnect vote, not the 600s
            # watchdogs (generous bound for slow CI)
            assert elapsed < 25.0

    asyncio.run(main())


def test_client_connection_flood_is_bounded():
    """Client-stack connection budget (ref plenum/config.py:285-292):
    a connection flood is capped at max_connections with the overflow
    rejected; sweeping reclaims slots from idle connections so live
    clients still get served after the flood."""
    import time as _time

    async def scenario():
        stack = ClientStack("srv", "127.0.0.1", 0, on_request=None,
                            max_connections=8, idle_timeout=0.5)
        seen = []
        stack._on_request = lambda msg, cid: seen.append((msg, cid))
        port = await stack.bind()

        # flood: 30 connections, each sending one frame to prove liveness
        floods = []
        for i in range(30):
            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                from plenum_tpu.common.serialization import pack
                payload = pack({"op": "NOOP", "i": i})
                w.write(len(payload).to_bytes(4, "big") + payload)
                await w.drain()
                floods.append((r, w))
            except OSError:
                pass
        await asyncio.sleep(0.3)
        assert len(stack._conns) <= 8            # bounded, not 30
        assert stack.rejected_connections >= 20

        # flood connections go idle; a NEW client connects after the
        # idle window and must be admitted via the sweep
        await asyncio.sleep(0.6)
        r2, w2 = await asyncio.open_connection("127.0.0.1", port)
        from plenum_tpu.common.serialization import pack as _pack
        payload = _pack({"op": "LIVE"})
        w2.write(len(payload).to_bytes(4, "big") + payload)
        await w2.drain()
        await asyncio.sleep(0.3)
        stack.drain()
        assert any(m.get("op") == "LIVE" for m, _ in seen)
        assert len(stack._conns) <= 8

        for _, w in floods:
            try:
                w.close()
            except Exception:
                pass
        w2.close()
        await stack.stop()

    asyncio.run(scenario())
