"""Plugin system: a demo plugin adds a new write txn type + read query and
the pool orders it end-to-end through real consensus.

Reference test model: plenum/test/plugin (the AUCTION/BANK demo plugins
exercised through a looper pool).
"""
from __future__ import annotations

import pytest

from plenum_tpu.common.node_messages import CONFIG_LEDGER_ID, DOMAIN_LEDGER_ID
from plenum_tpu.common.request import Request
from plenum_tpu.config import Config
from plenum_tpu.crypto.ed25519 import Ed25519Signer
from plenum_tpu.execution.handlers.base import (ReadRequestHandler,
                                                WriteRequestHandler)
from plenum_tpu.execution import txn as txn_lib
from plenum_tpu import plugins as plugin_lib

from test_pool import Pool, signed_nym

BUY = "9001"          # demo plugin txn type (like the reference's AUCTION)
GET_BAL = "9002"


class BuyHandler(WriteRequestHandler):
    """Accumulates per-DID balances in domain state."""

    def __init__(self, db):
        super().__init__(db, BUY, DOMAIN_LEDGER_ID)

    def static_validation(self, request):
        self._require(isinstance(request.operation.get("amount"), int)
                      and request.operation["amount"] > 0,
                      request, "amount must be a positive int")

    def gen_txn(self, request):
        return txn_lib.new_txn(BUY, {"amount": request.operation["amount"]},
                               request=request)

    def update_state(self, txn, is_committed):
        frm = txn_lib.txn_author(txn)
        amount = txn_lib.txn_data(txn)["amount"]
        key = f"buy:{frm}".encode()
        prev = self.state.get(key, committed=False)
        total = (int(prev.decode()) if prev else 0) + amount
        self.state.set(key, str(total).encode())


class GetBalanceHandler(ReadRequestHandler):
    def __init__(self, db):
        super().__init__(db, GET_BAL, DOMAIN_LEDGER_ID)

    def get_result(self, request):
        dest = request.operation.get("dest")
        raw = self.state.get(f"buy:{dest}".encode(), committed=True)
        return {"type": GET_BAL, "dest": dest,
                "balance": int(raw.decode()) if raw else 0}


class DemoPlugin:
    name = "demo-buy"

    def __init__(self):
        self.inited_nodes = []

    def get_write_handlers(self, db):
        return [BuyHandler(db)]

    def get_read_handlers(self, db):
        return [GetBalanceHandler(db)]

    def init(self, node):
        self.inited_nodes.append(node.name)


def test_plugin_txn_ordered_through_pool():
    plugin = DemoPlugin()
    plugin_lib.register_plugin(plugin)
    try:
        pool = Pool(config=Config(Max3PCBatchWait=0.05))
    finally:
        plugin_lib.unregister_plugin(plugin)

    assert sorted(plugin.inited_nodes) == sorted(pool.names)
    trustee = pool.trustee
    req = Request(trustee.identifier, 1, {"type": BUY, "amount": 5})
    req.signature = trustee.sign_b58(req.signing_bytes())
    pool.submit(req)
    pool.run(5.0)
    sizes = {pool.nodes[n].c.db.get_ledger(DOMAIN_LEDGER_ID).size
             for n in pool.names}
    assert sizes == {2}, sizes

    # second BUY accumulates
    req2 = Request(trustee.identifier, 2, {"type": BUY, "amount": 7})
    req2.signature = trustee.sign_b58(req2.signing_bytes())
    pool.submit(req2)
    pool.run(5.0)

    # the plugin's read handler answers from committed state
    q = Request(trustee.identifier, 3, {"type": GET_BAL,
                                        "dest": trustee.identifier})
    q.signature = trustee.sign_b58(q.signing_bytes())
    pool.submit(q, to=["Alpha"])
    pool.run(2.0)
    replies = pool.replies("Alpha")
    balances = [m.result.get("balance") for m in replies
                if m.result.get("type") == GET_BAL]
    assert balances and balances[-1] == 12

    # invalid amount is nacked by the plugin's static validation
    from plenum_tpu.common.node_messages import RequestNack
    bad = Request(trustee.identifier, 4, {"type": BUY, "amount": -1})
    bad.signature = trustee.sign_b58(bad.signing_bytes())
    pool.submit(bad, to=["Alpha"])
    pool.run(2.0)
    nacks = pool.replies("Alpha", RequestNack)
    assert any("amount" in m.reason for m in nacks)


def test_load_plugin_by_module_path():
    # plugins can be dotted module paths (PLUGIN_ROOT-style loading)
    mod = plugin_lib.load_plugin("plenum_tpu.plugins")
    assert mod in plugin_lib.registered_plugins()
    plugin_lib.unregister_plugin(mod)
    assert mod not in plugin_lib.registered_plugins()
