"""Differential tests: the C++ BN254 library vs the pure-Python twin.

The native library (plenum_tpu/native/bn254.cpp) carries the 3PC BLS hot
path; the Python implementation (crypto/bn254.py) is the authoritative
reference. Every exported operation is checked against it on random inputs —
the correctness bar SURVEY.md §7 sets for native pairing code.
"""
import ctypes
import random

import pytest

from plenum_tpu.crypto import bn254 as c
from plenum_tpu.crypto.bn254 import _dec_g1, _dec_g2, _enc_g1, _enc_g2
from plenum_tpu.native import bn254_lib, have_native_bn254

pytestmark = pytest.mark.skipif(not have_native_bn254(),
                                reason="native toolchain unavailable")

rng = random.Random(0xB254)


def py_g1_mul(a, k):
    out = None
    while k:
        if k & 1:
            out = c.g1_add(out, a)
        a = c.g1_add(a, a)
        k >>= 1
    return out


def py_g2_mul(a, k):
    out = None
    while k:
        if k & 1:
            out = c.g2_add(out, a)
        a = c.g2_add(a, a)
        k >>= 1
    return out


def f12_to_bytes(f):
    (a, b, d), (e, g, h) = f
    vals = [a[0], a[1], b[0], b[1], d[0], d[1],
            e[0], e[1], g[0], g[1], h[0], h[1]]
    return b"".join(x.to_bytes(32, "big") for x in vals)


def f12_from_bytes(raw):
    v = [int.from_bytes(raw[i * 32:(i + 1) * 32], "big") for i in range(12)]
    return (((v[0], v[1]), (v[2], v[3]), (v[4], v[5])),
            ((v[6], v[7]), (v[8], v[9]), (v[10], v[11])))


def test_g1_mul_differential():
    for _ in range(5):
        k = rng.randrange(1, c.R)
        assert c.g1_mul(c.G1_GEN, k) == py_g1_mul(c.G1_GEN, k)


def test_g2_mul_differential():
    for _ in range(2):
        k = rng.randrange(1, c.R)
        assert c.g2_mul(c.G2_GEN, k) == py_g2_mul(c.G2_GEN, k)


def test_g1_g2_add_differential():
    a = c.g1_mul(c.G1_GEN, 7)
    b = c.g1_mul(c.G1_GEN, 11)
    buf = ctypes.create_string_buffer(64)
    assert bn254_lib.pc_g1_add(_enc_g1(a), _enc_g1(b), buf) == 0
    assert _dec_g1(buf.raw) == c.g1_add(a, b)
    qa = c.g2_mul(c.G2_GEN, 7)
    qb = c.g2_mul(c.G2_GEN, 11)
    buf2 = ctypes.create_string_buffer(128)
    assert bn254_lib.pc_g2_add(_enc_g2(qa), _enc_g2(qb), buf2) == 0
    assert _dec_g2(buf2.raw) == c.g2_add(qa, qb)


def test_miller_loop_differential():
    p1 = c.g1_mul(c.G1_GEN, 123)
    q2 = c.g2_mul(c.G2_GEN, 45)
    buf = ctypes.create_string_buffer(384)
    assert bn254_lib.pc_miller(_enc_g2(q2), _enc_g1(p1), buf) == 0
    assert f12_from_bytes(buf.raw) == c.miller_loop(q2, p1)


def test_final_exp_differential():
    m = c.miller_loop(c.g2_mul(c.G2_GEN, 9), c.g1_mul(c.G1_GEN, 31))
    buf = ctypes.create_string_buffer(384)
    assert bn254_lib.pc_final_exp(f12_to_bytes(m), buf) == 0
    assert f12_from_bytes(buf.raw) == c.final_exponentiation(m)


def test_pairing_check_bilinearity_random():
    for _ in range(3):
        a = rng.randrange(1, c.R)
        b = rng.randrange(1, c.R)
        ok = c.pairing_check([
            (c.g2_mul(c.G2_GEN, a), c.g1_mul(c.G1_GEN, b)),
            (c.g2_mul(c.G2_GEN, a * b % c.R), c.g1_neg(c.G1_GEN))])
        assert ok


def test_pairing_check_rejects_wrong():
    p1 = c.g1_mul(c.G1_GEN, 31337)
    assert not c.pairing_check([(c.G2_GEN, c.g1_neg(p1)),
                                (c.g2_mul(c.G2_GEN, 2), c.G1_GEN)])


def test_native_agrees_with_python_backend():
    """The exact same pairing_check answer with and without the native lib."""
    p1 = c.g1_mul(c.G1_GEN, 777)
    q2 = c.g2_mul(c.G2_GEN, 777)
    pairs = [(c.G2_GEN, c.g1_neg(p1)), (q2, c.G1_GEN)]
    native = c.pairing_check(pairs)
    python = c.multi_pairing(pairs) == c.F12_ONE
    assert native == python == True      # noqa: E712


def test_subgroup_check_differential():
    assert c.g2_in_subgroup(c.G2_GEN)
    assert c.g2_in_subgroup(c.g2_mul(c.G2_GEN, 12345))


def test_infinity_handling():
    assert c.g1_mul(c.G1_GEN, c.R) is None
    assert c.g2_mul(c.G2_GEN, c.R) is None
    assert c.pairing_check([(c.G2_GEN, None), (None, c.G1_GEN)])
