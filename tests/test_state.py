"""MPT state tests: RLP codec, trie vs dict model (property-based), known
Ethereum root vectors, proofs, commit/revert."""
import hashlib

import pytest

pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package")
from hypothesis import given, settings, strategies as st

from plenum_tpu.state import rlp
from plenum_tpu.state.trie import (Trie, BLANK_ROOT, bytes_to_nibbles,
                                   hex_prefix_encode, hex_prefix_decode)
from plenum_tpu.state.pruning_state import PruningState
from plenum_tpu.storage.kv_memory import KvMemory


# --- RLP ------------------------------------------------------------------

@pytest.mark.parametrize("item,expected", [
    (b"", b"\x80"),
    (b"\x00", b"\x00"),
    (b"\x7f", b"\x7f"),
    (b"\x80", b"\x81\x80"),
    (b"dog", b"\x83dog"),
    ([], b"\xc0"),
    ([b"cat", b"dog"], b"\xc8\x83cat\x83dog"),
    (b"a" * 55, b"\xb7" + b"a" * 55),
    (b"a" * 56, b"\xb8\x38" + b"a" * 56),
])
def test_rlp_known_vectors(item, expected):
    assert rlp.encode(item) == expected
    assert rlp.decode(expected) == (item if not isinstance(item, list) else item)


@settings(max_examples=50, deadline=None)
@given(st.recursive(st.binary(max_size=70),
                    lambda s: st.lists(s, max_size=6), max_leaves=20))
def test_rlp_roundtrip(item):
    assert rlp.decode(rlp.encode(item)) == item


def test_rlp_rejects_noncanonical():
    with pytest.raises(rlp.RlpError):
        rlp.decode(b"\x81\x05")        # single byte <0x80 must be bare
    with pytest.raises(rlp.RlpError):
        rlp.decode(b"\x83do")          # truncated
    with pytest.raises(rlp.RlpError):
        rlp.decode(b"\x83dogX")        # trailing


# --- hex-prefix -----------------------------------------------------------

@pytest.mark.parametrize("nibbles,leaf", [
    ([], False), ([], True), ([1], False), ([1], True),
    ([1, 2], False), ([1, 2, 3], True), (list(range(16)), True),
])
def test_hex_prefix_roundtrip(nibbles, leaf):
    assert hex_prefix_decode(hex_prefix_encode(nibbles, leaf)) == (nibbles, leaf)


# --- trie vs dict model ---------------------------------------------------

def test_empty_root_is_blank():
    t = Trie()
    assert t.root_hash == BLANK_ROOT
    assert t.root_hash == hashlib.sha3_256(rlp.encode(b"")).digest()


def test_ethereum_style_known_root():
    """Single key/value — root must be sha3(rlp([hp(path,leaf), value]))."""
    t = Trie()
    t.set(b"k", b"value")
    expected = hashlib.sha3_256(rlp.encode(
        [hex_prefix_encode(bytes_to_nibbles(b"k"), True), b"value"])).digest()
    assert t.root_hash == expected


@settings(max_examples=40, deadline=None)
@given(st.dictionaries(st.binary(min_size=0, max_size=8),
                       st.binary(min_size=1, max_size=16), max_size=40))
def test_trie_matches_dict(model):
    t = Trie()
    for k, v in model.items():
        t.set(k, v)
    for k, v in model.items():
        assert t.get(k) == v
    assert t.get(b"\xff" * 9) is None
    assert t.to_dict() == model


@settings(max_examples=30, deadline=None)
@given(st.dictionaries(st.binary(min_size=0, max_size=6),
                       st.binary(min_size=1, max_size=8), min_size=1, max_size=30),
       st.data())
def test_trie_insertion_order_independent(model, data):
    keys = list(model)
    perm = data.draw(st.permutations(keys))
    t1, t2 = Trie(), Trie()
    for k in keys:
        t1.set(k, model[k])
    for k in perm:
        t2.set(k, model[k])
    assert t1.root_hash == t2.root_hash


@settings(max_examples=30, deadline=None)
@given(st.dictionaries(st.binary(min_size=0, max_size=6),
                       st.binary(min_size=1, max_size=8), min_size=2, max_size=30),
       st.data())
def test_trie_remove(model, data):
    t = Trie()
    for k, v in model.items():
        t.set(k, v)
    victims = data.draw(st.lists(st.sampled_from(list(model)), unique=True,
                                 min_size=1, max_size=len(model)))
    for k in victims:
        assert t.remove(k)
        assert not t.remove(k)     # second remove is a no-op
    remaining = {k: v for k, v in model.items() if k not in victims}
    assert t.to_dict() == remaining
    # root equals a trie built from scratch with remaining keys
    t2 = Trie()
    for k, v in remaining.items():
        t2.set(k, v)
    assert t.root_hash == t2.root_hash


def test_trie_update_value():
    t = Trie()
    t.set(b"abc", b"1")
    r1 = t.root_hash
    t.set(b"abc", b"2")
    assert t.get(b"abc") == b"2"
    t.set(b"abc", b"1")
    assert t.root_hash == r1


# --- proofs ---------------------------------------------------------------

@settings(max_examples=25, deadline=None)
@given(st.dictionaries(st.binary(min_size=1, max_size=6),
                       st.binary(min_size=1, max_size=40), min_size=1, max_size=25),
       st.data())
def test_state_proofs(model, data):
    t = Trie()
    for k, v in model.items():
        t.set(k, v)
    root = t.root_hash
    key = data.draw(st.sampled_from(list(model)))
    proof = t.produce_proof(key)
    present, value = Trie.verify_proof(root, key, proof)
    assert present and value == model[key]
    # absence proof for a key not in the model
    absent = b"\xfe" * 7
    proof2 = t.produce_proof(absent)
    present2, _ = Trie.verify_proof(root, absent, proof2)
    assert not present2


def test_proof_tampering_fails():
    t = Trie()
    for i in range(20):
        t.set(b"key%d" % i, b"val%d" % i)
    root = t.root_hash
    proof = t.produce_proof(b"key7")
    assert PruningState.verify_state_proof(root, b"key7", b"val7", proof)
    assert not PruningState.verify_state_proof(root, b"key7", b"valX", proof)
    assert not PruningState.verify_state_proof(root, b"key7", None, proof)
    # proof against a different root fails cleanly
    t.set(b"more", b"x")
    assert not PruningState.verify_state_proof(t.root_hash, b"key7", b"val7", [])


# --- PruningState commit/revert -------------------------------------------

def test_state_commit_revert_cycle():
    s = PruningState()
    s.set(b"a", b"1")
    s.commit()
    committed = s.committed_head_hash
    # stage uncommitted writes (3PC apply)
    s.set(b"b", b"2")
    s.set(b"a", b"1x")
    assert s.get(b"a", committed=False) == b"1x"
    assert s.get(b"a", committed=True) == b"1"
    assert s.get(b"b", committed=True) is None
    # revert (view change / reject)
    s.revert_to_head()
    assert s.head_hash == committed
    assert s.get(b"b", committed=False) is None
    # re-apply and commit
    s.set(b"b", b"2")
    s.commit()
    assert s.get(b"b", committed=True) == b"2"


def test_state_commit_explicit_root():
    """Commit an intermediate root (batch-by-batch commit of staged writes).

    With pipelined 3PC batches, later batches are applied on top of the one
    being committed — committing an earlier root must NOT rewind the
    uncommitted head (that would drop the in-flight writes)."""
    s = PruningState()
    s.set(b"x", b"1")
    r1 = s.head_hash
    s.set(b"y", b"2")
    r2 = s.head_hash
    s.commit(r1)
    assert s.committed_head_hash == r1
    assert s.get(b"y", committed=True) is None
    # head keeps the in-flight batch applied on top
    assert s.head_hash == r2
    assert s.get(b"y", committed=False) == b"2"
    # committing the head root later promotes it
    s.commit(r2)
    assert s.get(b"y", committed=True) == b"2"


def test_state_durable_reopen(tdir):
    from plenum_tpu.storage.kv_file import KvFile
    db = KvFile(tdir, "state")
    s = PruningState(db)
    s.set(b"k1", b"v1")
    s.set(b"k2", b"v2")
    s.commit()
    root = s.committed_head_hash
    s.set(b"k3", b"uncommitted")
    s.close()
    db2 = KvFile(tdir, "state")
    s2 = PruningState(db2)
    assert s2.committed_head_hash == root
    assert s2.get(b"k1") == b"v1"
    assert s2.get(b"k3", committed=False) is None   # uncommitted lost on crash
    s2.close()


def test_historic_reads():
    s = PruningState()
    s.set(b"k", b"old")
    s.commit()
    r_old = s.committed_head_hash
    s.set(b"k", b"new")
    s.commit()
    assert s.get(b"k") == b"new"
    assert s.get_for_root(b"k", r_old) == b"old"
