"""Autopilot control plane: fleet telemetry closed-loop to actuation.

``Autopilot`` (autopilot.py) rides the FleetAggregator's once-per-
interval cadence and actuates — live shard split/merge, pipeline lane
re-placement, observer fan-out, orchestrated degradation — with every
decision an ordered transaction on the reserved ``CONTROL_LEDGER_ID``.
``tools/control_audit.py`` replays and lints that ledger.
"""
from .autopilot import (Autopilot, CONTROL_LEDGER_ID, ControlLedger,
                        ControlRecord, LADDER, REVERT_OF, make_autopilot)

__all__ = ["Autopilot", "ControlLedger", "ControlRecord",
           "CONTROL_LEDGER_ID", "LADDER", "REVERT_OF", "make_autopilot"]
