"""Autopilot: the control plane that closes telemetry -> actuation.

Every control signal and every actuator already exists on the fabric —
the aggregator's health scores, SLO burn pages and imbalance index;
``ReshardManager.maybe_split``; pipeline lane placement and breakers;
read-only degradation — but until now a human or a test had to connect
them. The :class:`Autopilot` rides the :class:`FleetAggregator`'s
once-per-interval cadence and *actuates* instead of alerting, through
four policies, each with cooldown + flap hysteresis (the circuit-breaker
pattern lifted to fleet scale):

1. **Live shard split/merge.** A SUSTAINED imbalance flag (N consecutive
   pool-interval judgments, ``aggregator.sustained``) drives
   ``ReshardManager.maybe_split``; a sustained under-load judgment (only
   ever noted while NO shard is hot) merges the cold shard into its
   range-adjacent neighbor. One pool-wide reshard cooldown — layered on
   the manager's own ``cooldown_until`` guard — means a reshard can
   never chase its own transient.
2. **Pipeline lane re-placement.** A chip whose lane breaker stays open
   across the sustain window gets its pinned shards re-pinned to the
   least-backlogged healthy lane (``healthy_lane``); after the breaker
   stays CLOSED for the (longer) recovery window and the cooldown has
   expired, the pins restore. Re-pinning changes only FUTURE
   submissions — the ring itself never reshuffles in-flight waves.
3. **Observer fan-out.** Regional read-latency burn (the observer
   fleet's ``("reads", region)`` trackers, the same multi-window
   burn-rate rule as every other SLO) spawns observers up to a bound;
   sustained-clear burn plus measured demand headroom retires them.
4. **Orchestrated degradation.** When SLO burn persists for twice the
   sustain window DESPITE policies 1–3, the pool steps down a
   documented ladder — level 1: every front door's shed watermark
   clamps harder; level 2: pool-wide read-only — and steps back up one
   level at a time on sustained recovery. A catchup-diverged node's
   read-only is never touched (``Node.set_read_only`` refuses).

Every decision is an ordered transaction on the reserved
``CONTROL_LEDGER_ID``: action, attributed evidence snapshot, pre/post
state, cooldown stamp, and — for every undo — the seq of the action it
reverts. The autopilot's history is replayable and auditable
(tools/control_audit.py), never an operator mutation. All timing rides
the injectable timer, and decisions fire only when snapshot arrivals
advance the aggregator's fleet clock past the next interval mark — so a
recorded run replays byte-identically.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from plenum_tpu.common.metrics import MetricsName

# Reserved ledger id for control transactions — outside VALID_LEDGER_IDS
# like MAPPING_LEDGER_ID (100): the control history is fabric-scoped
# bookkeeping with ledger DISCIPLINE (ordered, append-only, auditable),
# not a consensus ledger nodes replicate.
CONTROL_LEDGER_ID = 101

# forward action -> the undo that must cite it (the audit contract)
REVERT_OF = {"unpin": "repin",
             "observer_retire": "observer_spawn",
             "recover": "degrade"}

# the documented degradation ladder, in descending order of service
LADDER = ("normal", "shed_harder", "read_only")


@dataclass
class ControlRecord:
    """One ordered control transaction."""
    seq: int
    t: float
    policy: str                  # "reshard" | "lane" | "observer" | "ladder"
    action: str                  # "split"/"merge"/"repin"/"unpin"/...
    subject: str
    evidence: dict = field(default_factory=dict)
    pre: dict = field(default_factory=dict)
    post: dict = field(default_factory=dict)
    cooldown_until: float = 0.0
    cites: Optional[int] = None  # seq of the action an undo reverts

    def to_dict(self) -> dict:
        return {"ledger_id": CONTROL_LEDGER_ID, "seq": self.seq,
                "t": round(self.t, 6), "policy": self.policy,
                "action": self.action, "subject": self.subject,
                "evidence": self.evidence, "pre": self.pre,
                "post": self.post,
                "cooldown_until": round(self.cooldown_until, 6),
                "cites": self.cites}


class ControlLedger:
    """Ordered, append-only record of every autopilot decision."""

    def __init__(self, now: Callable[[], float]):
        self.now = now
        self.records: list[ControlRecord] = []

    def append(self, **kw) -> ControlRecord:
        rec = ControlRecord(seq=len(self.records) + 1,
                            t=kw.pop("t", None) or self.now(), **kw)
        self.records.append(rec)
        return rec

    def to_dicts(self) -> list[dict]:
        return [r.to_dict() for r in self.records]

    def __len__(self) -> int:
        return len(self.records)


class Autopilot:
    """Drive with ``service()`` from the fabric's prod loop."""

    SLO_KINDS = ("slo_burn.ingress", "slo_burn.batch", "slo_burn.reads")

    def __init__(self, fabric):
        self.fabric = fabric
        self.config = fabric.config
        self.agg = fabric.aggregator
        self.ledger = ControlLedger(now=lambda: self.agg.now)
        cfg = self.config
        self._interval = getattr(cfg, "AUTOPILOT_INTERVAL", 1.0)
        self._sustain = getattr(cfg, "AUTOPILOT_SUSTAIN", 3)
        self._recover = getattr(cfg, "AUTOPILOT_RECOVER_SUSTAIN", 5)
        self._cooldown = getattr(cfg, "AUTOPILOT_COOLDOWN", 30.0)
        self._min_shards = getattr(cfg, "AUTOPILOT_MIN_SHARDS", 2)
        self._obs_min = getattr(cfg, "AUTOPILOT_OBSERVER_MIN", 1)
        self._obs_max = getattr(cfg, "AUTOPILOT_OBSERVER_MAX", 4)
        self._edge_absorb = getattr(cfg, "AUTOPILOT_EDGE_ABSORB", 0.95)
        self._shed_factor = getattr(cfg, "AUTOPILOT_SHED_FACTOR", 4)
        self._next_eval = 0.0
        # (policy, subject) -> timestamp before which the policy may not
        # touch the subject again (INCLUDING undoing itself: an
        # action/undo pair can never fit inside one cooldown window)
        self._cooldowns: dict[tuple[str, str], float] = {}
        # one hold record per blocked episode, not one per tick
        self._held: dict[tuple[str, str, str], float] = {}
        # sid -> {"prev": lane, "sick": lane, "seq": n} while re-pinned
        self._repins: dict[int, dict] = {}
        # region -> stack of observer_spawn seqs awaiting retire-cite
        self._spawns: dict[str, list[int]] = {}
        self.level = 0
        self._ladder_seqs: list[int] = []
        self.counts = {"decisions": 0, "actions": 0, "reverts": 0,
                       "holds": 0}

    # --- cadence -----------------------------------------------------------

    def service(self) -> None:
        """Evaluate once per AUTOPILOT_INTERVAL of the AGGREGATOR's
        fleet clock — it only advances on snapshot arrivals, so every
        decision fires on an aggregator-interval boundary and a
        replayed snapshot stream reproduces the decision stream."""
        t = self.agg.now
        if t < self._next_eval:
            return
        self._next_eval = t + self._interval
        self.counts["decisions"] += 1
        self.fabric.metrics.add_event(MetricsName.AUTOPILOT_DECISIONS)
        self._policy_lanes(t)
        self._policy_reshard(t)
        self._policy_observers(t)
        self._policy_ladder(t)
        self.agg.autopilot = self.summary()

    # --- bookkeeping helpers ------------------------------------------------

    def _cooled(self, policy: str, subject: str, t: float) -> bool:
        return t >= self._cooldowns.get((policy, subject), 0.0)

    def _stamp(self, policy: str, subject: str, until: float) -> None:
        self._cooldowns[(policy, subject)] = until

    def _record(self, t: float, policy: str, action: str, subject: str,
                evidence: dict, pre: dict, post: dict,
                cooldown_until: float = 0.0,
                cites: Optional[int] = None) -> int:
        rec = self.ledger.append(
            t=t, policy=policy, action=action, subject=subject,
            evidence=evidence, pre=pre, post=post,
            cooldown_until=cooldown_until, cites=cites)
        metrics = self.fabric.metrics
        if action == "hold":
            self.counts["holds"] += 1
            metrics.add_event(MetricsName.AUTOPILOT_HOLDS)
        elif action in REVERT_OF:
            self.counts["reverts"] += 1
            metrics.add_event(MetricsName.AUTOPILOT_REVERTS)
        else:
            self.counts["actions"] += 1
            metrics.add_event(MetricsName.AUTOPILOT_ACTIONS)
        tracer = self.fabric.fabric_tracer
        if tracer is not None and tracer.enabled:
            tracer.anomaly(f"autopilot.{action}", rec.to_dict())
        return rec.seq

    def _hold(self, t: float, policy: str, wanted: str, subject: str,
              evidence: dict, cd_subject: Optional[str] = None) -> None:
        """Record that a sustained signal wanted `wanted` but cooldown/
        busy state blocked it — once per blocked episode (one record per
        distinct cooldown stamp, not one per tick; a fresh action
        re-stamps, opening a new episode)."""
        until = self._cooldowns.get((policy, cd_subject or subject), 0.0)
        key = (policy, subject, wanted)
        if self._held.get(key) == until:
            return
        self._held[key] = until
        self._record(t, policy, "hold", subject,
                     {"wanted": wanted, "blocked_until": round(until, 3),
                      **evidence}, pre={}, post={})

    def _shard_state(self) -> dict:
        return {"shards": sorted(self.fabric.shards),
                "epoch": self.fabric.mapping.epoch}

    # --- policy 1: live shard split / merge ---------------------------------

    def _policy_reshard(self, t: float) -> None:
        rm = self.fabric.reshard
        if self.agg.sustained("shard.imbalance", self._sustain):
            index, hot = self.agg.load_imbalance()
            if hot is None:
                return
            subject = f"shard{hot}"
            if not self._cooled("reshard", "pool", t) or not rm.can_start():
                self._hold(t, "reshard", "split", subject,
                           {"index": index, "busy": rm.busy},
                           cd_subject="pool")
                return
            pre = self._shard_state()
            mig = rm.maybe_split()
            if mig is None:
                return          # thin load sample / hot shard vanished
            cd = t + self._cooldown
            self._stamp("reshard", "pool", cd)
            self._record(
                t, "reshard", "split", subject,
                {"index": index, "hot_shard": hot,
                 "streak": self.agg._streaks.get(
                     ("shard.imbalance", "pool"), 0)},
                pre=pre, post=self._shard_state(), cooldown_until=cd)
            return
        if not self.agg.sustained("shard.underload", self._sustain):
            return
        rates = self.agg.ordered_rates()
        cold = self.agg.cold_shard(rates)
        if cold is None or cold not in self.fabric.shards \
                or len(self.fabric.shards) <= self._min_shards:
            return
        subject = f"shard{cold}"
        if not self._cooled("reshard", "pool", t) or not rm.can_start():
            self._hold(t, "reshard", "merge", subject, {"busy": rm.busy},
                       cd_subject="pool")
            return
        partner = self._adjacent_shard(cold)
        if partner is None:
            return
        pre = self._shard_state()
        rm.merge(cold, partner)
        cd = t + self._cooldown
        self._stamp("reshard", "pool", cd)
        self._record(
            t, "reshard", "merge", subject,
            {"cold_shard": cold, "into": partner,
             "rates": {str(k): round(v, 2) for k, v in sorted(
                 rates.items())}},
            pre=pre, post=self._shard_state(), cooldown_until=cd)

    def _adjacent_shard(self, sid: int) -> Optional[int]:
        """The live shard whose key range abuts `sid`'s (merge targets
        must be range-adjacent or the mapping ratchet can't fold them)."""
        from plenum_tpu.shards import mapping as mapping_lib
        mine = None
        for d in self.fabric.mapping.descriptors:
            if d.shard_id == sid:
                mine = d
        if mine is None:
            return None
        for d in sorted(self.fabric.mapping.descriptors,
                        key=lambda d: d.lo):
            if d.shard_id == sid or d.shard_id not in self.fabric.shards:
                continue
            if mapping_lib.ranges_adjacent(mine, d) or \
                    mapping_lib.ranges_adjacent(d, mine):
                return d.shard_id
        return None

    # --- policy 2: pipeline lane re-placement -------------------------------

    def _policy_lanes(self, t: float) -> None:
        pipe = self.fabric.pipeline
        lanes = getattr(pipe, "lanes", None)
        if pipe is None or lanes is None:
            return
        for lane in lanes:
            subject = str(lane.idx)
            if not self.agg.sustained("pipeline.lane", self._sustain,
                                      subject=subject):
                continue
            pinned = [sid for sid, l in sorted(
                self.fabric.lane_pins.items())
                if l == lane.idx and sid in self.fabric.shards
                and sid not in self._repins]
            if not pinned:
                continue
            if not self._cooled("lane", subject, t):
                self._hold(t, "lane", "repin", subject,
                           {"breaker": lane.breaker_state()})
                continue
            target = pipe.healthy_lane(exclude=(lane.idx,))
            if target is None:
                continue        # nowhere healthier to go
            cd = t + self._cooldown
            self._stamp("lane", subject, cd)
            for sid in pinned:
                prev = self.fabric.repin_shard_lane(sid, target)
                seq = self._record(
                    t, "lane", "repin", f"shard{sid}",
                    {"sick_lane": lane.idx,
                     "breaker": lane.breaker_state()},
                    pre={"lane": prev}, post={"lane": target},
                    cooldown_until=cd)
                self._repins[sid] = {"prev": prev, "sick": lane.idx,
                                     "seq": seq}
        # restore pins after a stable re-warm: the sick lane's breaker
        # held CLOSED for the (longer) recovery window AND the cooldown
        # stamped at re-pin time has expired — never both sides of a
        # flap inside one window
        for sid, info in sorted(self._repins.items()):
            subject = str(info["sick"])
            if not self.agg.sustained_clear("pipeline.lane", self._recover,
                                            subject=subject):
                continue
            if not self._cooled("lane", subject, t):
                continue
            if sid not in self.fabric.shards:
                del self._repins[sid]
                continue
            cur = self.fabric.lane_pins.get(sid)
            self.fabric.repin_shard_lane(sid, info["prev"])
            cd = t + self._cooldown
            self._stamp("lane", subject, cd)
            self._record(
                t, "lane", "unpin", f"shard{sid}",
                {"healed_lane": info["sick"],
                 "clear_streak": self.agg._clear_streaks.get(
                     ("pipeline.lane", subject), 0)},
                pre={"lane": cur}, post={"lane": info["prev"]},
                cooldown_until=cd, cites=info["seq"])
            del self._repins[sid]

    # --- policy 3: observer fan-out per region ------------------------------

    def _policy_observers(self, t: float) -> None:
        fleet = getattr(self.fabric, "observers", None)
        if fleet is None:
            return
        for region in sorted(fleet.regions):
            n = fleet.count(region)
            if self.agg.sustained("slo_burn.reads", self._sustain,
                                  subject=region):
                burn = self.agg.burn.get(("reads", region))
                evidence = {"region": region, "observers": n,
                            **(burn.summary(t) if burn else {})}
                # the Proof-CDN signal (aggregator.note_edge): when the
                # region's edges already absorb nearly every verified
                # read, more observer capacity can't move the burn —
                # hold with the hit-rate as evidence instead of
                # spawning. No edge fleet -> no signal -> policy as
                # before (the observer fuzz pins that identity).
                rate_fn = getattr(self.agg, "edge_hit_rate", None)
                rate = rate_fn(region) if callable(rate_fn) else None
                if rate is not None:
                    evidence["edge_hit_rate"] = round(rate, 4)
                    if rate >= self._edge_absorb:
                        self._hold(t, "observer", "observer_spawn",
                                   region,
                                   {**evidence, "edge_absorbing": True})
                        continue
                if n >= self._obs_max:
                    # capacity exhausted: the ladder's cue, not ours
                    self._hold(t, "observer", "observer_spawn", region,
                               {**evidence, "at_max": True})
                    continue
                if not self._cooled("observer", region, t):
                    self._hold(t, "observer", "observer_spawn", region,
                               evidence)
                    continue
                name = fleet.spawn(region)
                cd = t + self._cooldown
                self._stamp("observer", region, cd)
                seq = self._record(
                    t, "observer", "observer_spawn", region, evidence,
                    pre={"observers": n}, post={"observers": n + 1,
                                                "spawned": name},
                    cooldown_until=cd)
                self._spawns.setdefault(region, []).append(seq)
            elif (self._spawns.get(region)
                  and n > self._obs_min
                  and self.agg.sustained_clear("slo_burn.reads",
                                               self._recover,
                                               subject=region)
                  and fleet.scale_in_safe(region)
                  and self._cooled("observer", region, t)):
                name = fleet.retire(region)
                if name is None:
                    continue
                cd = t + self._cooldown
                self._stamp("observer", region, cd)
                self._record(
                    t, "observer", "observer_retire", region,
                    {"region": region,
                     "demand": fleet._last_served.get(region, 0),
                     "capacity": fleet.capacity * (n - 1)},
                    pre={"observers": n},
                    post={"observers": n - 1, "retired": name},
                    cooldown_until=cd,
                    cites=self._spawns[region].pop())

    # --- policy 4: the degradation ladder -----------------------------------

    def _burning(self) -> list[list]:
        """(kind, subject) pairs whose burn judgment has been ACTIVE for
        2x the sustain window — burn that persisted despite policies
        1-3 having had a full window to act."""
        out = []
        for kind in self.SLO_KINDS:
            for s in self.agg.sustained_subjects(kind, 2 * self._sustain):
                out.append([kind, s])
        return out

    def _policy_ladder(self, t: float) -> None:
        burning = self._burning()
        if burning and self.level < len(LADDER) - 1:
            if not self._cooled("ladder", "pool", t):
                self._hold(t, "ladder", "degrade", "pool",
                           {"burning": burning})
                return
            pre = {"level": self.level, "state": LADDER[self.level]}
            self.level += 1
            self._apply_level()
            cd = t + self._cooldown
            self._stamp("ladder", "pool", cd)
            seq = self._record(
                t, "ladder", "degrade", LADDER[self.level],
                {"burning": burning},
                pre=pre, post={"level": self.level,
                               "state": LADDER[self.level]},
                cooldown_until=cd)
            self._ladder_seqs.append(seq)
        elif (self.level > 0 and not burning
              and all(self.agg.sustained_clear(kind, self._recover)
                      for kind in self.SLO_KINDS)
              and self._cooled("ladder", "pool", t)):
            pre = {"level": self.level, "state": LADDER[self.level]}
            left = LADDER[self.level]
            self.level -= 1
            self._apply_level()
            cd = t + self._cooldown
            self._stamp("ladder", "pool", cd)
            self._record(
                t, "ladder", "recover", left,
                {"clear_for": self._recover},
                pre=pre, post={"level": self.level,
                               "state": LADDER[self.level]},
                cooldown_until=cd,
                cites=self._ladder_seqs.pop())

    def _apply_level(self) -> None:
        """Make the fabric match self.level. Idempotent — applying the
        same level twice is a no-op at every actuator."""
        shed = self.level >= 1
        for plane in getattr(self.fabric, "ingress_planes", []):
            if shed:
                base = self.config.INGRESS_HIGH_WATERMARK
                plane.force_shed_watermark(
                    max(1, base // self._shed_factor))
            else:
                plane.force_shed_watermark(None)
        read_only = self.level >= 2
        for node in self.fabric.nodes.values():
            node.set_read_only(read_only, reason="autopilot")

    # --- reporting ----------------------------------------------------------

    def summary(self) -> dict:
        return {"level": self.level, "state": LADDER[self.level],
                "records": len(self.ledger),
                "repins": {sid: info["sick"] for sid, info in
                           sorted(self._repins.items())},
                **self.counts}


def make_autopilot(fabric) -> Optional[Autopilot]:
    """Config-gated construction seam: ``AUTOPILOT=False`` (the
    default) returns None and the fabric pays one ``is None`` check per
    prod — today's behavior exactly, identity-pinned by test."""
    if not getattr(fabric.config, "AUTOPILOT", False):
        return None
    return Autopilot(fabric)
