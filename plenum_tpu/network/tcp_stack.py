"""Real network transport: authenticated-encrypted TCP behind the
ExternalBus seam.

Reference behavior being replaced: stp_zmq/zstack.py:52 (ROUTER/DEALER
sockets with CurveZMQ encryption), zstack.py:322 (ZAP allowlist
authenticator), zstack.py:520 (per-cycle receive quotas),
stp_zmq/kit_zstack.py:28 (maintain-connections retry loop) and
plenum/common/batched.py:20 (per-peer outbox coalescing into one wire
frame per flush).

Redesign, not a port: instead of ZMQ + CurveCP this is asyncio TCP with an
explicit Noise-style handshake built from the primitives already in the
image's `cryptography` package:

  dialer  -> acceptor : magic || eph_A                      (32B X25519)
  acceptor-> dialer   : eph_B || vk_B || sig_B("resp"||eph_A||eph_B)
  dialer  -> acceptor : vk_A || sig_A("init"||eph_A||eph_B)

Both sides sign the ephemeral transcript with their long-lived Ed25519 node
key (the same key the pool ledger registers), so peer identity = ledger
identity and the allowlist is exactly the node registry — the reference
reuses its CurveZMQ keys the same way. Session keys are
HKDF(X25519(eph, eph'), salt=transcript) split per direction; frames are
length-prefixed ChaCha20-Poly1305 with a counter nonce (replay-safe: a
counter never repeats under a session key, and sessions never resume).

Wire frames carry a msgpack LIST of message dicts — the outbox batching the
reference does in common/batched.py — so one TCP segment typically carries a
whole prod cycle's traffic to a peer.

Dialer rule: for each pair the lexicographically SMALLER name dials; the
other side accepts. The dialer owns the retry loop (kit_zstack semantics).
"""
from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Callable, Optional

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey, Ed25519PublicKey)
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey)
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    _HAVE_CRYPTOGRAPHY = True
except Exception:  # pragma: no cover — gated again in TcpStack.__init__
    _HAVE_CRYPTOGRAPHY = False

from plenum_tpu.common.backoff import ExponentialBackoff
from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.message_base import MessageBase, message_from_dict
from plenum_tpu.common.serialization import pack, unpack

logger = logging.getLogger(__name__)

MAGIC = b"PTPU\x01\x00\x00\x00"
MAX_FRAME = 8 * 1024 * 1024          # reference caps ZMQ frames similarly
OUTBOX_CAP = 10_000                  # queued msgs per disconnected peer
WRITE_HWM = 8 * 1024 * 1024          # drop a peer that stops reading (ZMQ HWM)
# dialer backoff (kit_zstack retries). RETRY_MAX bounds how long a
# transient drop stays down: it must sit BELOW the pool's
# PRIMARY_DISCONNECT_TIMEOUT (config.py) or a blip at max backoff could
# outlast the tolerance on every peer at once and force a needless view
# change. A down peer being redialed every second by n-1 nodes is noise.
# The doubling is JITTERED per (dialer, peer) — see _retry_backoff: the
# bare min->max doubling is the same deterministic sequence on every
# node, so a pool-wide restart had n-1 dialers arriving at each
# recovering acceptor in synchronized waves (a reconnect stampede, worst
# exactly when the pool is weakest).
RETRY_MIN, RETRY_MAX = 0.1, 1.0
RETRY_JITTER = 0.5


def _retry_backoff(dialer: str, peer: str) -> ExponentialBackoff:
    """Dial-loop retry schedule: truncated doubling with deterministic
    seeded jitter, decorrelated per (dialer, peer) pair so simultaneous
    losers spread their retries instead of stampeding in lockstep."""
    return ExponentialBackoff(base=RETRY_MIN, cap=RETRY_MAX,
                              jitter=RETRY_JITTER,
                              salt=f"dial/{dialer}->{peer}")


class HandshakeError(Exception):
    pass


class NodeRegistry:
    """name -> (host, port, ed25519 verkey bytes); the transport allowlist.

    Mutable on purpose: pool-ledger NODE txns update membership at runtime
    (ref pool_manager reconnect semantics)."""

    def __init__(self, entries: Optional[dict] = None):
        self._entries: dict[str, tuple[str, int, bytes]] = dict(entries or {})

    def set(self, name: str, host: str, port: int, verkey: bytes) -> None:
        self._entries[name] = (host, port, bytes(verkey))

    def remove(self, name: str) -> None:
        self._entries.pop(name, None)

    def get(self, name: str):
        return self._entries.get(name)

    def name_by_verkey(self, verkey: bytes) -> Optional[str]:
        for name, (_, _, vk) in self._entries.items():
            if vk == verkey:
                return name
        return None

    def names(self) -> list[str]:
        return list(self._entries)


def _derive_keys(eph_priv: X25519PrivateKey, eph_peer_pub: bytes,
                 transcript: bytes) -> tuple[bytes, bytes]:
    """-> (dialer->acceptor key, acceptor->dialer key)."""
    shared = eph_priv.exchange(X25519PublicKey.from_public_bytes(eph_peer_pub))
    okm = HKDF(algorithm=hashes.SHA256(), length=64, salt=transcript,
               info=b"plenum-tpu transport v1").derive(shared)
    return okm[:32], okm[32:]


class _Session:
    """One established, authenticated, encrypted peer connection."""

    def __init__(self, peer: str, writer: asyncio.StreamWriter,
                 send_key: bytes, recv_key: bytes):
        self.peer = peer
        self.writer = writer
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_ctr = 0
        self._recv_ctr = 0

    def encrypt_frame(self, plaintext: bytes) -> bytes:
        nonce = b"\x00" * 4 + self._send_ctr.to_bytes(8, "little")
        self._send_ctr += 1
        ct = self._send_aead.encrypt(nonce, plaintext, None)
        return len(ct).to_bytes(4, "big") + ct

    def decrypt(self, ciphertext: bytes) -> bytes:
        nonce = b"\x00" * 4 + self._recv_ctr.to_bytes(8, "little")
        self._recv_ctr += 1
        return self._recv_aead.decrypt(nonce, ciphertext, None)


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    data = await reader.readexactly(n)
    return data


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    hdr = await _read_exact(reader, 4)
    length = int.from_bytes(hdr, "big")
    if length > MAX_FRAME:
        raise HandshakeError(f"frame too large: {length}")
    return await _read_exact(reader, length)


class TcpStack:
    """Node-to-node transport; owns an ExternalBus facing the Node.

    Lifecycle: construct -> (optionally bind() to learn the real port)
    -> start() -> ... -> stop(). All I/O runs on one asyncio loop; the
    owning Looper calls drain() each prod cycle to hand queued inbound
    messages to the bus (per-cycle quota, like zstack.py:520).
    """

    def __init__(self, name: str, host: str, port: int,
                 registry: NodeRegistry, seed: bytes,
                 max_inbound_per_drain: int = 1000):
        if not _HAVE_CRYPTOGRAPHY:
            # the handshake needs X25519 + ChaCha20-Poly1305; unlike the
            # request-signing seam there is no pure-Python fallback here
            raise ImportError(
                "the `cryptography` package is required for the TCP node "
                "stack (sim fabric and client stack run without it)")
        self.name = name
        self.host, self.port = host, port
        self.registry = registry
        self._sk = Ed25519PrivateKey.from_private_bytes(seed)
        self.verkey = self._sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        self.bus = ExternalBus(self._enqueue_send)
        self._sessions: dict[str, _Session] = {}
        self._outboxes: dict[str, list[bytes]] = {}
        self._inbound: deque[tuple[Any, str]] = deque()
        self._server: Optional[asyncio.AbstractServer] = None
        self._dial_tasks: dict[str, asyncio.Task] = {}
        self._reader_tasks: set[asyncio.Task] = set()
        self._flush_scheduled = False
        self._quota = max_inbound_per_drain
        self._stopped = False
        # dropped_frames/dropped_sessions: silent-loss accounting — outbox
        # trimming and HWM disconnects previously discarded traffic with no
        # trace (surfaced via tools.metrics_report through the node's
        # metrics store). tx/rx maps: per-message-type [count, bytes] so
        # wire-cost claims (digest-gossip) are measured, not asserted.
        self.stats = {"sent_frames": 0, "recv_frames": 0, "rejected": 0,
                      "dropped_frames": 0, "dropped_sessions": 0,
                      "tx_msgs": {}, "rx_msgs": {}}

    @staticmethod
    def _count_msg(table: dict, op: str, nbytes: int, n: int = 1) -> None:
        row = table.get(op)
        if row is None:
            row = table[op] = [0, 0]
        row[0] += n
        row[1] += nbytes * n

    # --- lifecycle -------------------------------------------------------

    async def bind(self) -> int:
        """Start the listener; returns the actual port (use port=0 to let
        the OS pick — the tests and the local-pool runner do)."""
        if self._server is None:
            self._server = await asyncio.start_server(
                self._on_accept, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def start(self) -> None:
        await self.bind()
        self.maintain_connections()

    def maintain_connections(self) -> None:
        """(Re)start dial loops for every registry peer we should dial."""
        for peer in self.registry.names():
            if peer == self.name or not self._is_dialer(peer):
                continue
            task = self._dial_tasks.get(peer)
            if task is None or task.done():
                self._dial_tasks[peer] = asyncio.get_running_loop(
                ).create_task(self._dial_loop(peer))

    async def stop(self) -> None:
        self._stopped = True
        for task in list(self._dial_tasks.values()):
            task.cancel()
        for task in list(self._reader_tasks):
            task.cancel()
        for sess in list(self._sessions.values()):
            try:
                sess.writer.close()
            except Exception:
                pass
        self._sessions.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def _is_dialer(self, peer: str) -> bool:
        return self.name < peer

    # --- outgoing --------------------------------------------------------

    def _enqueue_send(self, msg: Any, dst) -> None:
        # pack ONCE per message, even for a broadcast — the per-peer loop
        # below only appends the shared bytes (guarded by the wire-fuzz
        # pack-once test; a per-peer pack() here is the n^2 serde tax the
        # reference pays in its per-remote serialization)
        if isinstance(msg, MessageBase):
            d = msg.to_dict()
            data = pack(d)
            op = d.get("op", type(msg).__name__)
        else:
            data = pack(msg)
            op = msg.get("op", "?") if isinstance(msg, dict) else "?"
        targets = dst if dst is not None else [
            p for p in self.registry.names() if p != self.name]
        self._count_msg(self.stats["tx_msgs"], op, len(data), len(targets))
        for peer in targets:
            box = self._outboxes.setdefault(peer, [])
            box.append(data)
            if len(box) > OUTBOX_CAP:          # quota: drop oldest
                trimmed = len(box) - OUTBOX_CAP
                del box[:trimmed]
                self.stats["dropped_frames"] += trimmed
                logger.warning(
                    "outbox to %s over cap: dropped %d oldest queued "
                    "messages (%d total dropped)", peer, trimmed,
                    self.stats["dropped_frames"])
        self._schedule_flush()

    def _schedule_flush(self) -> None:
        if self._flush_scheduled or self._stopped:
            return
        self._flush_scheduled = True
        try:
            asyncio.get_running_loop().call_soon(self._flush)
        except RuntimeError:
            self._flush_scheduled = False      # no loop yet; flushed on start

    def _flush(self) -> None:
        """Coalesce each peer's queued messages into ONE encrypted frame
        (common/batched.py flushOutBoxes equivalent)."""
        self._flush_scheduled = False
        for peer, box in self._outboxes.items():
            sess = self._sessions.get(peer)
            if sess is None or not box:
                continue                       # keep queued until connected
            frame_payload = pack(box)
            n_msgs = len(box)
            box.clear()
            try:
                # backpressure: a peer that stopped reading is dead to us —
                # unbounded transport buffering would OOM the node (the
                # reference's ZMQ high-water mark drops slow peers the same
                # way; the dialer's retry loop gives it a fresh start)
                if sess.writer.transport.get_write_buffer_size() > WRITE_HWM:
                    raise ConnectionError("peer write buffer over HWM")
                sess.writer.write(sess.encrypt_frame(frame_payload))
                self.stats["sent_frames"] += 1
            except Exception:
                # the cleared box's messages die with the session — count
                # them; silent loss here cost a debugging session once
                self.stats["dropped_sessions"] += 1
                self.stats["dropped_frames"] += n_msgs
                logger.warning(
                    "dropping session to %s (write failed or over HWM); "
                    "%d queued messages lost", peer, n_msgs)
                self._drop_session(peer)

    # --- incoming --------------------------------------------------------

    def drain(self) -> int:
        """Deliver up to the per-cycle quota of inbound messages to the bus."""
        n = 0
        while self._inbound and n < self._quota:
            msg, frm = self._inbound.popleft()
            n += 1
            try:
                self.bus.process_incoming(msg, frm)
            except Exception:
                logger.exception("handler failed for %s from %s",
                                 type(msg).__name__, frm)
        return n

    @property
    def connected(self) -> set[str]:
        return set(self._sessions)

    # --- handshake: dialer side -----------------------------------------

    async def _dial_loop(self, peer: str) -> None:
        backoff = _retry_backoff(self.name, peer)
        while not self._stopped:
            if peer in self._sessions:
                await asyncio.sleep(RETRY_MAX)
                continue
            entry = self.registry.get(peer)
            if entry is None:
                return
            host, port, expect_vk = entry
            writer = None
            try:
                reader, writer = await asyncio.open_connection(host, port)
                # a wedged acceptor must not hang the dial loop forever:
                # same 5s budget the acceptor gives us
                sess = await asyncio.wait_for(
                    self._handshake_dialer(peer, expect_vk, reader, writer),
                    timeout=5.0)
                self._install_session(peer, sess, reader)
                backoff.reset()
            except (OSError, HandshakeError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError):
                if writer is not None:       # failed handshake: free the fd
                    try:
                        writer.close()
                    except Exception:
                        pass
                await asyncio.sleep(backoff.next())

    async def _handshake_dialer(self, peer: str, expect_vk: bytes,
                                reader, writer) -> _Session:
        eph = X25519PrivateKey.generate()
        eph_pub = eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        writer.write(MAGIC + eph_pub)
        await writer.drain()
        resp = await _read_exact(reader, 32 + 32 + 64)
        eph_b, vk_b, sig_b = resp[:32], resp[32:64], resp[64:]
        if vk_b != expect_vk:
            raise HandshakeError(f"{peer}: unexpected verkey")
        transcript = eph_pub + eph_b
        try:
            Ed25519PublicKey.from_public_bytes(vk_b).verify(
                sig_b, b"resp" + transcript)
        except InvalidSignature:
            raise HandshakeError(f"{peer}: bad responder signature")
        sig_a = self._sk.sign(b"init" + transcript)
        writer.write(self.verkey + sig_a)
        await writer.drain()
        k_d2a, k_a2d = _derive_keys(eph, eph_b, transcript)
        return _Session(peer, writer, send_key=k_d2a, recv_key=k_a2d)

    # --- handshake: acceptor side ---------------------------------------

    async def _on_accept(self, reader, writer) -> None:
        try:
            sess = await asyncio.wait_for(
                self._handshake_acceptor(reader, writer), timeout=5.0)
        except Exception:
            self.stats["rejected"] += 1
            writer.close()
            return
        self._install_session(sess.peer, sess, reader)

    async def _handshake_acceptor(self, reader, writer) -> _Session:
        hello = await _read_exact(reader, len(MAGIC) + 32)
        if hello[:len(MAGIC)] != MAGIC:
            raise HandshakeError("bad magic")
        eph_a = hello[len(MAGIC):]
        eph = X25519PrivateKey.generate()
        eph_pub = eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        transcript = eph_a + eph_pub
        sig_b = self._sk.sign(b"resp" + transcript)
        writer.write(eph_pub + self.verkey + sig_b)
        await writer.drain()
        fin = await _read_exact(reader, 32 + 64)
        vk_a, sig_a = fin[:32], fin[32:]
        peer = self.registry.name_by_verkey(vk_a)
        if peer is None:                       # ZAP allowlist: unknown key
            raise HandshakeError("verkey not in registry")
        try:
            Ed25519PublicKey.from_public_bytes(vk_a).verify(
                sig_a, b"init" + transcript)
        except InvalidSignature:
            raise HandshakeError(f"{peer}: bad initiator signature")
        k_d2a, k_a2d = _derive_keys(eph, eph_a, transcript)
        return _Session(peer, writer, send_key=k_a2d, recv_key=k_d2a)

    # --- session plumbing -----------------------------------------------

    def _install_session(self, peer: str, sess: _Session, reader) -> None:
        old = self._sessions.get(peer)
        if old is not None:
            # restarted peer: the new connection supersedes the old one
            try:
                old.writer.close()
            except Exception:
                pass
        self._sessions[peer] = sess
        task = asyncio.get_running_loop().create_task(
            self._read_loop(peer, sess, reader))
        self._reader_tasks.add(task)
        task.add_done_callback(self._reader_tasks.discard)
        self.bus.update_connecteds(self.connected)
        self._schedule_flush()                 # release queued outbox

    def _drop_session(self, peer: str) -> None:
        sess = self._sessions.pop(peer, None)
        if sess is not None:
            try:
                sess.writer.close()
            except Exception:
                pass
            self.bus.update_connecteds(self.connected)

    async def _read_loop(self, peer: str, sess: _Session, reader) -> None:
        try:
            while not self._stopped:
                ct = await _read_frame(reader)
                payload = sess.decrypt(ct)
                self.stats["recv_frames"] += 1
                # frame payload = packed list of per-message packed dicts
                # (messages are serialized once at enqueue, even for
                # broadcasts, then batched per peer at flush)
                for raw in unpack(payload):
                    try:
                        d = unpack(raw)
                        msg = message_from_dict(d)
                    except Exception:
                        logger.warning("undecodable message from %s", peer)
                        continue
                    self._count_msg(
                        self.stats["rx_msgs"],
                        d.get("op", "?") if isinstance(d, dict) else "?",
                        len(raw))
                    self._inbound.append((msg, peer))
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                asyncio.CancelledError, Exception):
            pass
        finally:
            if self._sessions.get(peer) is sess:
                self._drop_session(peer)


class ClientStack:
    """Client-facing listener.

    Plaintext length-prefixed msgpack frames: client requests are themselves
    Ed25519-signed at the request layer (client_authn), which is what
    authenticates them — transport encryption for clients is TLS-termination
    territory, out of scope the same way the reference leaves client CurveZMQ
    keys unauthenticated (any client key is accepted, zstack.py:322).

    on_request(msg_dict, client_id) is wired to Node.handle_client_message;
    send(msg, client_id) is the Node's client_send callback.

    Connection budget (ref plenum/config.py:285-292 MAX_CONNECTED_CLIENTS_NUM
    + client-stack restart): at most `max_connections` concurrent client
    sockets. The reference restarts the whole ZMQ stack to shed dead
    connections because ZMQ cannot enumerate them; an asyncio listener can,
    so a full stack first sweeps connections idle past `idle_timeout`
    (activity = any frame in OR any push/reply out) and only rejects the
    new connection if every slot is genuinely live — validator traffic is
    untouched either way (separate node stack).
    """

    INBOUND_CAP = 10_000          # queued requests across all clients

    def __init__(self, name: str, host: str, port: int,
                 on_request: Callable[[dict, str], None],
                 max_inbound_per_drain: int = 500,
                 max_connections: int = 400,
                 idle_timeout: float = 300.0):
        self.name = name
        self.host, self.port = host, port
        self._on_request = on_request
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: dict[str, asyncio.StreamWriter] = {}
        self._next_id = 0
        self._inbound: deque[tuple[dict, str]] = deque()
        self._quota = max_inbound_per_drain
        self.max_connections = max_connections
        self.idle_timeout = idle_timeout
        self._last_activity: dict[str, float] = {}
        self.rejected_connections = 0

    async def bind(self) -> int:
        if self._server is None:
            self._server = await asyncio.start_server(
                self._on_accept, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        for w in self._conns.values():
            try:
                w.close()
            except Exception:
                pass
        self._conns.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def drain(self) -> int:
        """Per-cycle quota, like the node stack (ref zstack.py:520) — one
        fast client must not stall a whole prod cycle."""
        n = 0
        while self._inbound and n < self._quota:
            msg, cid = self._inbound.popleft()
            n += 1
            try:
                self._on_request(msg, cid)
            except Exception:
                logger.exception("client request failed")
        return n

    def send(self, msg: Any, client_id: str) -> None:
        if self._conns.get(client_id) is None:
            return                             # client gone; reply dropped
        self._send_packed(
            pack(msg.to_dict() if isinstance(msg, MessageBase) else msg),
            client_id)

    def send_many(self, msg: Any, client_ids) -> None:
        """Broadcast to several clients packing the message ONCE (mirror of
        the node stack's pack-once broadcast): the observer push previously
        re-serialized the same BatchCommitted per registered observer."""
        data = None
        for cid in client_ids:
            if self._conns.get(cid) is None:
                continue
            if data is None:
                data = pack(msg.to_dict()
                            if isinstance(msg, MessageBase) else msg)
            self._send_packed(data, cid)

    def _send_packed(self, data: bytes, client_id: str) -> None:
        writer = self._conns.get(client_id)
        if writer is None:
            return
        try:
            if writer.transport.get_write_buffer_size() > WRITE_HWM:
                raise ConnectionError("client write buffer over HWM")
            writer.write(len(data).to_bytes(4, "big") + data)
            self._last_activity[client_id] = time.monotonic()
        except Exception:
            self._drop_client(client_id)

    def _drop_client(self, client_id: str) -> None:
        writer = self._conns.pop(client_id, None)
        self._last_activity.pop(client_id, None)
        if writer is not None:
            try:
                writer.close()
            except Exception:
                pass

    def _sweep_idle(self) -> int:
        """Close connections with no traffic in either direction for
        idle_timeout; returns number closed."""
        now = time.monotonic()
        stale = [cid for cid, ts in self._last_activity.items()
                 if now - ts > self.idle_timeout]
        for cid in stale:
            self._drop_client(cid)
        return len(stale)

    async def _on_accept(self, reader, writer) -> None:
        if len(self._conns) >= self.max_connections:
            self._sweep_idle()
        if len(self._conns) >= self.max_connections:
            # every slot is live within the idle window: shed the newcomer
            # (bounded memory/FDs beat fairness here, as in the reference's
            # MAX_CONNECTED_CLIENTS_NUM)
            self.rejected_connections += 1
            try:
                writer.close()
            except Exception:
                pass
            return
        cid = f"client-{self._next_id}"
        self._next_id += 1
        self._conns[cid] = writer
        self._last_activity[cid] = time.monotonic()
        try:
            while True:
                frame = await _read_frame(reader)
                msg = unpack(frame)
                self._last_activity[cid] = time.monotonic()
                if isinstance(msg, dict) and \
                        len(self._inbound) < self.INBOUND_CAP:
                    self._inbound.append((msg, cid))
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                Exception):
            pass
        finally:
            self._drop_client(cid)
