"""Seeded deterministic randomness for protocol simulation.

Reference behavior: plenum/test/simulation/sim_random.py — every random choice
in a simulated pool flows through one seeded source so a failing fuzz run can
be replayed exactly from its seed (SURVEY.md §4 item 3).
"""
from __future__ import annotations

import random
from typing import Any, Sequence


class SimRandom:
    def __init__(self, seed: int = 42):
        self._seed = seed
        self._rng = random.Random(seed)

    @property
    def seed(self) -> int:
        return self._seed

    def integer(self, low: int, high: int) -> int:
        """Uniform integer in [low, high]."""
        return self._rng.randint(low, high)

    def float(self, low: float, high: float) -> float:
        return self._rng.uniform(low, high)

    def string(self, length: int, alphabet: str = "abcdefghijklmnopqrstuvwxyz") -> str:
        return "".join(self._rng.choice(alphabet) for _ in range(length))

    def choice(self, *args: Any) -> Any:
        return self._rng.choice(args if len(args) > 1 else args[0])

    def sample(self, population: Sequence, k: int) -> list:
        return self._rng.sample(list(population), k)

    def shuffle(self, items: Sequence) -> list:
        out = list(items)
        self._rng.shuffle(out)
        return out
