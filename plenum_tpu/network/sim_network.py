"""Deterministic in-process network fabric for multi-node simulation.

Reference behavior: plenum/test/simulation/sim_network.py:98 — peers are
ExternalBus instances wired through a rule chain; each rule can Discard (with
probability), Stash, or Deliver (with random delay) messages matched by
predicate. All delays go through the TimerService, all randomness through
SimRandom, so a whole pool run is replayable from a seed. Messages make a
round trip through the real wire serializer so schema bugs surface in sims.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, NamedTuple, Optional, Union

from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.message_base import MessageBase, message_from_dict
from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.common.timer import TimerService

from .sim_random import SimRandom


class Discard(NamedTuple):
    probability: float = 1.0


class Deliver(NamedTuple):
    min_delay: float = 0.0
    max_delay: float = 0.0


class Stash(NamedTuple):
    pass


class Mutate(NamedTuple):
    """Byzantine fault injection: transform the message before delivery
    (the transform returns the replacement message, or None to drop).
    Mutated traffic still pays the normal wire roundtrip, so a mutation
    that breaks the message SCHEMA surfaces as a parse reject at the
    receiver — exactly like a real byzantine peer's frame would."""
    transform: Callable[[Any], Any]
    probability: float = 1.0


Action = Union[Discard, Deliver, Stash, Mutate]
Selector = Callable[[Any, str, str], bool]   # (msg, frm, dst) -> bool


class Rule(NamedTuple):
    action: Action
    selectors: tuple


def match_frm(frm: Union[str, Iterable[str]]) -> Selector:
    names = {frm} if isinstance(frm, str) else set(frm)
    return lambda _msg, f, _dst: f in names


def match_dst(dst: Union[str, Iterable[str]]) -> Selector:
    names = {dst} if isinstance(dst, str) else set(dst)
    return lambda _msg, _frm, d: d in names


def match_type(t: Union[type, Iterable[type]]) -> Selector:
    types = t if isinstance(t, type) else tuple(t)
    return lambda msg, _frm, _dst: isinstance(msg, types)


class SimNetwork:
    """Full-mesh fabric: every peer's ExternalBus sends into the rule chain;
    surviving messages are scheduled for delivery on the shared timer."""

    def __init__(self, timer: TimerService, random: Optional[SimRandom] = None,
                 wire_roundtrip: bool = True):
        self._timer = timer
        self._random = random or SimRandom()
        self._wire_roundtrip = wire_roundtrip
        self._peers: dict[str, ExternalBus] = {}
        self._rules: list[Rule] = []
        self._stashed: list[tuple[Any, str, str]] = []
        self.min_latency = 0.01
        self.max_latency = 0.5
        self.sent_count = 0
        self.delivered_count = 0
        # per-message-type [count, bytes] over every scheduled delivery —
        # the sim twin of TcpStack.stats["tx_msgs"], so wire-cost claims
        # (digest-gossip) are measurable on the deterministic fabric too
        self.tx_msgs: dict[str, list] = {}

    def bytes_summary(self) -> dict:
        total = sum(c[1] for c in self.tx_msgs.values())
        return {"total_bytes": total,
                "by_type": {op: {"count": c[0], "bytes": c[1]}
                            for op, c in sorted(self.tx_msgs.items())}}

    # --- peers -----------------------------------------------------------

    def create_peer(self, name: str,
                    send_handler: Optional[Callable] = None) -> ExternalBus:
        if name in self._peers:
            raise ValueError(f"peer {name!r} already exists")
        handler = send_handler or (lambda msg, dst, frm=name: self._send(frm, msg, dst))
        bus = ExternalBus(handler)
        self._peers[name] = bus
        return bus

    def remove_peer(self, name: str) -> None:
        self._peers.pop(name, None)
        self._refresh_connecteds()

    @property
    def peer_names(self) -> list[str]:
        return list(self._peers)

    def connect_all(self) -> None:
        self._refresh_connecteds()

    def _refresh_connecteds(self) -> None:
        all_names = set(self._peers)
        for name, bus in self._peers.items():
            bus.update_connecteds(all_names - {name})

    # --- rules -----------------------------------------------------------

    def add_rule(self, action: Action, *selectors: Selector) -> Rule:
        rule = Rule(action=action, selectors=selectors)
        self._rules.append(rule)
        return rule

    def remove_rule(self, rule: Rule) -> None:
        if rule in self._rules:
            self._rules.remove(rule)
            self._replay_stashed()

    def set_latency(self, min_value: float, max_value: float) -> None:
        self.min_latency = min_value
        self.max_latency = max_value

    def _replay_stashed(self) -> None:
        stashed, self._stashed = self._stashed, []
        for msg, frm, dst in stashed:
            self._route(msg, frm, dst)

    # --- transmission ----------------------------------------------------

    def _send(self, frm: str, msg: Any, dst) -> None:
        if dst is None:
            targets = [n for n in self._peers if n != frm]
        elif isinstance(dst, str):
            # a bare name must address ONE peer — iterating a string
            # would silently split it into characters and drop the send
            targets = [dst]
        else:
            targets = [d for d in dst]
        # pack-once broadcast: one wire serialization shared by every
        # target of this send (a real transport packs a broadcast frame
        # once too). Keyed by object identity, and the cache value PINS
        # the message object: a Mutate rule's per-destination replacement
        # may be garbage-collected as soon as its _route returns, and a
        # later replacement allocated at the recycled address would
        # otherwise hit the dead entry and deliver the previous
        # mutation's bytes. Holding the reference (and re-checking `is`)
        # makes identity-keying sound for the send's lifetime.
        pack_cache: dict[int, tuple[Any, dict, bytes]] = {}
        for d in targets:
            self.sent_count += 1
            self._route(msg, frm, d, pack_cache)

    def _route(self, msg: Any, frm: str, dst: str,
               pack_cache: Optional[dict] = None) -> None:
        # Last-added rule wins, like a filter stack.
        for rule in reversed(self._rules):
            if not all(sel(msg, frm, dst) for sel in rule.selectors):
                continue
            if isinstance(rule.action, Discard):
                if self._random.float(0.0, 1.0) <= rule.action.probability:
                    return
                continue
            if isinstance(rule.action, Stash):
                self._stashed.append((msg, frm, dst))
                return
            if isinstance(rule.action, Mutate):
                if self._random.float(0.0, 1.0) <= rule.action.probability:
                    msg = rule.action.transform(msg)
                    if msg is None:
                        return
                continue        # mutated message keeps flowing down the chain
            if isinstance(rule.action, Deliver):
                delay = self._random.float(rule.action.min_delay, rule.action.max_delay)
                self._schedule(delay, msg, frm, dst, pack_cache)
                return
        delay = self._random.float(self.min_latency, self.max_latency)
        self._schedule(delay, msg, frm, dst, pack_cache)

    def _schedule(self, delay: float, msg: Any, frm: str, dst: str,
                  pack_cache: Optional[dict] = None) -> None:
        if self._wire_roundtrip and isinstance(msg, MessageBase):
            # Serialize now (sender's view), deserialize at delivery — exactly
            # what a real wire does, so schema violations fail loudly in sims.
            cached = pack_cache.get(id(msg)) if pack_cache is not None else None
            if cached is None or cached[0] is not msg:
                d = msg.to_dict()
                cached = (msg, d, pack(d))
                if pack_cache is not None:
                    pack_cache[id(msg)] = cached
            _, d, data = cached
            row = self.tx_msgs.setdefault(d.get("op", "?"), [0, 0])
            row[0] += 1
            row[1] += len(data)
            deliver = lambda: self._deliver_wire(data, frm, dst)
        else:
            deliver = lambda: self._deliver(msg, frm, dst)
        self._timer.schedule(delay, deliver)

    def _deliver_wire(self, data: bytes, frm: str, dst: str) -> None:
        self._deliver(message_from_dict(unpack(data)), frm, dst)

    def _deliver(self, msg: Any, frm: str, dst: str) -> None:
        bus = self._peers.get(dst)
        if bus is None:
            return
        self.delivered_count += 1
        bus.process_incoming(msg, frm)
