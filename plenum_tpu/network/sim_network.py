"""Deterministic in-process network fabric for multi-node simulation.

Reference behavior: plenum/test/simulation/sim_network.py:98 — peers are
ExternalBus instances wired through a rule chain; each rule can Discard (with
probability), Stash, or Deliver (with random delay) messages matched by
predicate. All delays go through the TimerService, all randomness through
SimRandom, so a whole pool run is replayable from a seed. Messages make a
round trip through the real wire serializer so schema bugs surface in sims.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, NamedTuple, Optional, Union

from plenum_tpu.common.event_bus import ExternalBus
from plenum_tpu.common.message_base import MessageBase, message_from_dict
from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.common.timer import TimerService

from .sim_random import SimRandom


class Discard(NamedTuple):
    probability: float = 1.0


class Deliver(NamedTuple):
    min_delay: float = 0.0
    max_delay: float = 0.0


class Stash(NamedTuple):
    pass


class Mutate(NamedTuple):
    """Byzantine fault injection: transform the message before delivery
    (the transform returns the replacement message, or None to drop).
    Mutated traffic still pays the normal wire roundtrip, so a mutation
    that breaks the message SCHEMA surfaces as a parse reject at the
    receiver — exactly like a real byzantine peer's frame would."""
    transform: Callable[[Any], Any]
    probability: float = 1.0


Action = Union[Discard, Deliver, Stash, Mutate]
Selector = Callable[[Any, str, str], bool]   # (msg, frm, dst) -> bool


class LinkProfile(NamedTuple):
    """One directed link's WAN character. All fields are sim seconds /
    probabilities / bytes-per-second; every random draw they imply goes
    through the fabric's SimRandom, so a profiled run replays from its
    seed exactly like a flat one."""
    base_delay: float = 0.01     # one-way propagation latency
    jitter: float = 0.0          # uniform extra delay in [0, jitter]
    loss: float = 0.0            # per-message drop probability
    bandwidth: float = 0.0       # serialization cap (bytes/s); 0 = infinite


class Topology:
    """Named regions + per-(region, region) directed LinkProfiles.

    Asymmetry is first-class: the (frm_region, dst_region) key is
    directed, so an asymmetric route (fat down-link, thin up-link) is two
    entries. Lookup order: exact directed pair -> ("*", dst) -> (frm, "*")
    -> default. Peers created after construction (membership churn) are
    auto-assigned round-robin over the region list so a joining node gets
    a deterministic placement."""

    def __init__(self, regions: Iterable[str],
                 links: Optional[dict] = None,
                 default: Optional[LinkProfile] = None):
        self.regions = list(regions) or ["region0"]
        self.links: dict[tuple[str, str], LinkProfile] = dict(links or {})
        self.default = default or LinkProfile()
        self._assignment: dict[str, str] = {}
        self._auto_idx = 0

    def assign(self, name: str, region: Optional[str] = None) -> str:
        if region is None:
            region = self.regions[self._auto_idx % len(self.regions)]
            self._auto_idx += 1
        self._assignment[name] = region
        return region

    def assign_round_robin(self, names: Iterable[str]) -> None:
        for name in names:
            self.assign(name)

    def region_of(self, name: str) -> str:
        got = self._assignment.get(name)
        if got is None:
            got = self.assign(name)
        return got

    def set_link(self, frm_region: str, dst_region: str,
                 profile: LinkProfile) -> None:
        self.links[(frm_region, dst_region)] = profile

    def profile(self, frm: str, dst: str) -> LinkProfile:
        a, b = self.region_of(frm), self.region_of(dst)
        for key in ((a, b), ("*", b), (a, "*")):
            got = self.links.get(key)
            if got is not None:
                return got
        return self.default


def make_topology(preset: str, names: Iterable[str],
                  n_regions: int = 3) -> Topology:
    """Region presets for bench/fuzz configs.

    - ``lan``: one region, sub-millisecond, lossless, effectively
      unbounded bandwidth — the flat fabric restated as a profile.
    - ``geo3``: `n_regions` geo regions; fast clean intra-region links,
      40-90 ms inter-region propagation with mild jitter and a 100 Mbit/s
      serialization cap.
    - ``lossy_wan``: geo3 degraded — inter-region links lose 3% of
      messages, jitter widens to 80 ms, bandwidth drops to 20 Mbit/s.
      This is the profile the churn/view-change hardening is judged
      under (a view change that only completes on a clean LAN is not a
      view change).
    """
    names = list(names)
    if preset == "lan":
        topo = Topology(["lan"], default=LinkProfile(
            base_delay=0.0002, jitter=0.0003, loss=0.0, bandwidth=125e6))
        topo.assign_round_robin(names)
        return topo
    if preset not in ("geo3", "lossy_wan"):
        raise ValueError(f"unknown topology preset {preset!r}")
    regions = [f"geo{i}" for i in range(max(2, n_regions))]
    intra = LinkProfile(base_delay=0.001, jitter=0.002, loss=0.0,
                        bandwidth=125e6)
    if preset == "geo3":
        inter = LinkProfile(base_delay=0.04, jitter=0.03, loss=0.0,
                            bandwidth=12.5e6)
    else:
        inter = LinkProfile(base_delay=0.06, jitter=0.08, loss=0.03,
                            bandwidth=2.5e6)
    links = {}
    for i, a in enumerate(regions):
        for j, b in enumerate(regions):
            if i == j:
                links[(a, b)] = intra
            else:
                # deterministic mild asymmetry: the "far" direction pays
                # ~25% more propagation (uplink-shaped routes)
                stretch = 1.0 + 0.25 * ((i + j) % 2 if i < j else 0)
                links[(a, b)] = inter._replace(
                    base_delay=inter.base_delay * stretch)
    topo = Topology(regions, links=links, default=intra)
    topo.assign_round_robin(names)
    return topo


class Rule(NamedTuple):
    action: Action
    selectors: tuple


def match_frm(frm: Union[str, Iterable[str]]) -> Selector:
    names = {frm} if isinstance(frm, str) else set(frm)
    return lambda _msg, f, _dst: f in names


def match_dst(dst: Union[str, Iterable[str]]) -> Selector:
    names = {dst} if isinstance(dst, str) else set(dst)
    return lambda _msg, _frm, d: d in names


def match_type(t: Union[type, Iterable[type]]) -> Selector:
    types = t if isinstance(t, type) else tuple(t)
    return lambda msg, _frm, _dst: isinstance(msg, types)


class SimNetwork:
    """Full-mesh fabric: every peer's ExternalBus sends into the rule chain;
    surviving messages are scheduled for delivery on the shared timer."""

    def __init__(self, timer: TimerService, random: Optional[SimRandom] = None,
                 wire_roundtrip: bool = True,
                 topology: Optional[Topology] = None):
        self._timer = timer
        self._random = random or SimRandom()
        self._wire_roundtrip = wire_roundtrip
        self._peers: dict[str, ExternalBus] = {}
        self._rules: list[Rule] = []
        self._stashed: list[tuple[Any, str, str]] = []
        self.min_latency = 0.01
        self.max_latency = 0.5
        # topology-aware fault model: when set, the default delivery path
        # derives per-message delay/loss/serialization from the directed
        # (frm, dst) LinkProfile instead of the flat uniform latency.
        # Explicit Deliver/Discard rules still win (last-added-rule-first),
        # so targeted scenario faults compose ON TOP of the WAN character.
        self._topology = topology
        # directed link -> sim time the link's serializer is busy until
        # (bandwidth cap: frames queue behind each other, a burst pays
        # its own transmission time, not just propagation)
        self._link_busy: dict[tuple[str, str], float] = {}
        self.sent_count = 0
        self.delivered_count = 0
        self.lost_count = 0          # topology-loss drops (rule Discards
        #                              are scenario faults, counted apart)
        # per-message-type [count, bytes] over every scheduled delivery —
        # the sim twin of TcpStack.stats["tx_msgs"], so wire-cost claims
        # (digest-gossip) are measurable on the deterministic fabric too
        self.tx_msgs: dict[str, list] = {}

    def bytes_summary(self) -> dict:
        total = sum(c[1] for c in self.tx_msgs.values())
        return {"total_bytes": total,
                "by_type": {op: {"count": c[0], "bytes": c[1]}
                            for op, c in sorted(self.tx_msgs.items())}}

    # --- peers -----------------------------------------------------------

    def create_peer(self, name: str,
                    send_handler: Optional[Callable] = None) -> ExternalBus:
        if name in self._peers:
            raise ValueError(f"peer {name!r} already exists")
        handler = send_handler or (lambda msg, dst, frm=name: self._send(frm, msg, dst))
        bus = ExternalBus(handler)
        self._peers[name] = bus
        return bus

    def remove_peer(self, name: str) -> None:
        self._peers.pop(name, None)
        self._refresh_connecteds()

    @property
    def peer_names(self) -> list[str]:
        return list(self._peers)

    def connect_all(self) -> None:
        self._refresh_connecteds()

    def _refresh_connecteds(self) -> None:
        all_names = set(self._peers)
        for name, bus in self._peers.items():
            bus.update_connecteds(all_names - {name})

    # --- rules -----------------------------------------------------------

    def add_rule(self, action: Action, *selectors: Selector) -> Rule:
        rule = Rule(action=action, selectors=selectors)
        self._rules.append(rule)
        return rule

    def remove_rule(self, rule: Rule) -> None:
        if rule in self._rules:
            self._rules.remove(rule)
            self._replay_stashed()

    def set_latency(self, min_value: float, max_value: float) -> None:
        self.min_latency = min_value
        self.max_latency = max_value

    def set_topology(self, topology: Optional[Topology]) -> None:
        self._topology = topology
        self._link_busy.clear()

    @property
    def topology(self) -> Optional[Topology]:
        return self._topology

    def _replay_stashed(self) -> None:
        stashed, self._stashed = self._stashed, []
        for msg, frm, dst in stashed:
            self._route(msg, frm, dst)

    # --- transmission ----------------------------------------------------

    def _send(self, frm: str, msg: Any, dst) -> None:
        if dst is None:
            targets = [n for n in self._peers if n != frm]
        elif isinstance(dst, str):
            # a bare name must address ONE peer — iterating a string
            # would silently split it into characters and drop the send
            targets = [dst]
        else:
            targets = [d for d in dst]
        # pack-once broadcast: one wire serialization shared by every
        # target of this send (a real transport packs a broadcast frame
        # once too). Keyed by object identity, and the cache value PINS
        # the message object: a Mutate rule's per-destination replacement
        # may be garbage-collected as soon as its _route returns, and a
        # later replacement allocated at the recycled address would
        # otherwise hit the dead entry and deliver the previous
        # mutation's bytes. Holding the reference (and re-checking `is`)
        # makes identity-keying sound for the send's lifetime.
        pack_cache: dict[int, tuple[Any, dict, bytes]] = {}
        for d in targets:
            self.sent_count += 1
            self._route(msg, frm, d, pack_cache)

    def _route(self, msg: Any, frm: str, dst: str,
               pack_cache: Optional[dict] = None) -> None:
        # Last-added rule wins, like a filter stack.
        for rule in reversed(self._rules):
            if not all(sel(msg, frm, dst) for sel in rule.selectors):
                continue
            if isinstance(rule.action, Discard):
                if self._random.float(0.0, 1.0) <= rule.action.probability:
                    return
                continue
            if isinstance(rule.action, Stash):
                self._stashed.append((msg, frm, dst))
                return
            if isinstance(rule.action, Mutate):
                if self._random.float(0.0, 1.0) <= rule.action.probability:
                    msg = rule.action.transform(msg)
                    if msg is None:
                        return
                continue        # mutated message keeps flowing down the chain
            if isinstance(rule.action, Deliver):
                delay = self._random.float(rule.action.min_delay, rule.action.max_delay)
                self._schedule(delay, msg, frm, dst, pack_cache)
                return
        topo = self._topology
        if topo is not None:
            prof = topo.profile(frm, dst)
            if prof.loss and self._random.float(0.0, 1.0) <= prof.loss:
                self.lost_count += 1
                return
            delay = prof.base_delay
            if prof.jitter:
                delay += self._random.float(0.0, prof.jitter)
            self._schedule(delay, msg, frm, dst, pack_cache, profile=prof)
            return
        delay = self._random.float(self.min_latency, self.max_latency)
        self._schedule(delay, msg, frm, dst, pack_cache)

    def _tx_time(self, profile: LinkProfile, frm: str, dst: str,
                 nbytes: int) -> float:
        """Serialization + queueing on the directed link's bandwidth cap:
        a frame starts transmitting when the link frees up, so a burst
        spreads out instead of all arriving one propagation delay later."""
        if not profile.bandwidth or nbytes <= 0:
            return 0.0
        ser = nbytes / profile.bandwidth
        now = self._timer.get_current_time()
        start = max(now, self._link_busy.get((frm, dst), now))
        self._link_busy[(frm, dst)] = start + ser
        return (start - now) + ser

    def _schedule(self, delay: float, msg: Any, frm: str, dst: str,
                  pack_cache: Optional[dict] = None,
                  profile: Optional[LinkProfile] = None) -> None:
        if self._wire_roundtrip and isinstance(msg, MessageBase):
            # Serialize now (sender's view), deserialize at delivery — exactly
            # what a real wire does, so schema violations fail loudly in sims.
            cached = pack_cache.get(id(msg)) if pack_cache is not None else None
            if cached is None or cached[0] is not msg:
                d = msg.to_dict()
                cached = (msg, d, pack(d))
                if pack_cache is not None:
                    pack_cache[id(msg)] = cached
            _, d, data = cached
            row = self.tx_msgs.setdefault(d.get("op", "?"), [0, 0])
            row[0] += 1
            row[1] += len(data)
            if profile is not None:
                delay += self._tx_time(profile, frm, dst, len(data))
            deliver = lambda: self._deliver_wire(data, frm, dst)
        else:
            deliver = lambda: self._deliver(msg, frm, dst)
        self._timer.schedule(delay, deliver)

    def _deliver_wire(self, data: bytes, frm: str, dst: str) -> None:
        self._deliver(message_from_dict(unpack(data)), frm, dst)

    def _deliver(self, msg: Any, frm: str, dst: str) -> None:
        bus = self._peers.get(dst)
        if bus is None:
            return
        self.delivered_count += 1
        bus.process_incoming(msg, frm)
