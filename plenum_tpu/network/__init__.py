from .sim_random import SimRandom
from .sim_network import SimNetwork, Discard, Deliver, Stash, Mutate, Rule
from .sim_network import match_frm, match_dst, match_type
from .sim_network import LinkProfile, Topology, make_topology

__all__ = ["SimRandom", "SimNetwork", "Discard", "Deliver", "Stash",
           "Mutate", "Rule",
           "match_frm", "match_dst", "match_type",
           "LinkProfile", "Topology", "make_topology"]
