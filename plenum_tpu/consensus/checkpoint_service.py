"""Checkpointing: periodic stabilization points that garbage-collect the 3PC
log and bound how far any node can run ahead.

Reference behavior: plenum/server/consensus/checkpoint_service.py:29 — every
CHK_FREQ ordered batches the replica emits a Checkpoint keyed by the audit
ledger root (:147-166); a quorum of n-f-1 matching checkpoints stabilizes it
(_mark_checkpoint_stable :177), advancing the watermark window [h, h+LOG_SIZE]
(set_watermarks :216); a checkpoint quorum the node cannot reach from its own
ordered log triggers catchup (_start_catchup_if_needed :107).
"""
from __future__ import annotations

from typing import Callable, Optional

from plenum_tpu.common.event_bus import ExternalBus, InternalBus
from plenum_tpu.common.internal_messages import (CheckpointStabilized,
                                                 NeedMasterCatchup)
from plenum_tpu.common.node_messages import Checkpoint, Ordered
from plenum_tpu.config import Config

from .consensus_shared_data import ConsensusSharedData


class CheckpointService:
    def __init__(self,
                 data: ConsensusSharedData,
                 bus: InternalBus,
                 network: ExternalBus,
                 config: Optional[Config] = None,
                 checkpoint_digest_provider: Optional[Callable[[int], str]] = None):
        self._data = data
        self._bus = bus
        self._network = network
        self._config = config or Config()
        # Digest of the stabilizable state at a pp_seq_no — the node wires this
        # to the audit ledger's uncommitted root; standalone tests use a stub.
        self._digest_for = checkpoint_digest_provider or (lambda seq: f"chk-{seq}")
        self._data.log_size = self._config.LOG_SIZE
        # (seq_no_end, digest) -> set of voting node names
        self._received: dict[tuple[int, str], set[str]] = {}
        self._own: dict[int, Checkpoint] = {}

        bus.subscribe(Ordered, self.process_ordered)
        self._network_unsub = network.subscribe(Checkpoint,
                                                self.process_checkpoint)

    def stop(self) -> None:
        """Detach from the shared network bus (replica removal)."""
        self._network_unsub()

    @property
    def _chk_freq(self) -> int:
        return self._config.CHK_FREQ

    # --- producing checkpoints -------------------------------------------

    def process_ordered(self, ordered: Ordered) -> None:
        if ordered.inst_id != self._data.inst_id:
            return
        seq_no = ordered.pp_seq_no
        if seq_no % self._chk_freq != 0:
            return
        self._create_checkpoint(seq_no)

    def _create_checkpoint(self, seq_no: int) -> None:
        msg = Checkpoint(inst_id=self._data.inst_id,
                         view_no=self._data.view_no,
                         seq_no_start=self._data.stable_checkpoint + 1,
                         seq_no_end=seq_no,
                         digest=self._digest_for(seq_no))
        self._own[seq_no] = msg
        self._data.checkpoints.append(msg)
        self._network.send(msg)
        self._try_stabilize(seq_no, msg.digest)

    # --- receiving checkpoints -------------------------------------------

    def process_checkpoint(self, msg: Checkpoint, sender: str) -> None:
        if msg.inst_id != self._data.inst_id:
            return
        if msg.seq_no_end <= self._data.stable_checkpoint:
            return
        key = (msg.seq_no_end, msg.digest)
        self._received.setdefault(key, set()).add(sender)
        self._try_stabilize(msg.seq_no_end, msg.digest)
        self._check_if_lagging(msg.seq_no_end, msg.digest)

    def _votes(self, seq_no: int, digest: str) -> int:
        votes = len(self._received.get((seq_no, digest), ()))
        if seq_no in self._own and self._own[seq_no].digest == digest:
            votes += 1
        return votes

    def _try_stabilize(self, seq_no: int, digest: str) -> None:
        if seq_no <= self._data.stable_checkpoint:
            return
        if seq_no not in self._own:
            return                      # can't stabilize what we haven't reached
        if self._own[seq_no].digest != digest:
            return
        if not self._data.quorums.checkpoint.is_reached(self._votes(seq_no, digest)):
            return
        self._mark_stable(seq_no)

    def _mark_stable(self, seq_no: int) -> None:
        self._data.stable_checkpoint = seq_no
        self._data.low_watermark = seq_no
        # Keep the newly-stable checkpoint itself: view changes cite it.
        self._data.checkpoints = [c for c in self._data.checkpoints
                                  if c.seq_no_end >= seq_no]
        self._own = {k: v for k, v in self._own.items() if k > seq_no}
        self._received = {k: v for k, v in self._received.items() if k[0] > seq_no}
        # Prune in-flight batch records below the watermark.
        self._data.preprepared = [b for b in self._data.preprepared
                                  if b.pp_seq_no > seq_no]
        self._data.prepared = [b for b in self._data.prepared
                               if b.pp_seq_no > seq_no]
        self._bus.send(CheckpointStabilized(
            inst_id=self._data.inst_id,
            last_stable_3pc=(self._data.view_no, seq_no)))

    # --- lag detection (ref :107) ----------------------------------------

    def _check_if_lagging(self, seq_no: int, digest: str) -> None:
        votes = len(self._received.get((seq_no, digest), set()))
        if not self._data.quorums.checkpoint.is_reached(votes):
            return
        # A full quorum agrees on a checkpoint we haven't produced ourselves
        # and that is beyond our watermark window: we fell behind.
        lagging = (seq_no not in self._own
                   and seq_no > self._data.last_ordered_3pc[1] + self._chk_freq)
        if lagging and self._data.is_master:
            self._mark_stable_remote(seq_no)
            self._bus.send(NeedMasterCatchup())

    def _mark_stable_remote(self, seq_no: int) -> None:
        """Adopt a remote quorum checkpoint so stashed traffic can unblock
        after catchup."""
        self._data.stable_checkpoint = seq_no
        self._data.low_watermark = seq_no

    # --- view change hooks ------------------------------------------------

    def process_new_view_accepted(self, checkpoint: tuple) -> None:
        """Reset to the checkpoint selected by NewView (ref :304)."""
        view, start, end, digest = checkpoint
        if end > self._data.stable_checkpoint:
            self._data.stable_checkpoint = end
            self._data.low_watermark = end
        self._own = {k: v for k, v in self._own.items() if k > end}
        self._received = {k: v for k, v in self._received.items() if k[0] > end}
        # The adopted checkpoint STAYS in the list: the next view change must
        # have a selectable candidate every node holds, or NewViewBuilder can
        # never reach its strong quorum again and every later view change
        # deadlocks (the same reason every node starts with the virtual
        # checkpoint at seq 0).
        self._data.checkpoints = \
            [Checkpoint(inst_id=self._data.inst_id, view_no=view,
                        seq_no_start=start, seq_no_end=end, digest=digest)] + \
            [c for c in self._data.checkpoints if c.seq_no_end > end]
