"""BLS wiring into the 3PC flow: sign state roots at COMMIT, aggregate at
order time, embed the previous batch's multi-sig into the next PRE-PREPARE.

Reference behavior: plenum/bls/bls_bft_replica_plenum.py:21 —
update_pre_prepare :80 / validate_pre_prepare :43 / update_commit :99
(_sign_state :227) / validate_commit :55 / process_commit :144 /
process_order :154 (_calculate_all_multi_sigs :261) — and plenum/bls/
bls_store.py (root-hash → multi-sig KV used by state-proof reads).
"""
from __future__ import annotations

from typing import Callable, Optional

from plenum_tpu.common.node_messages import Commit, PrePrepare
from plenum_tpu.common.quorums import Quorums
from plenum_tpu.common.serialization import json_dumps, json_loads
from plenum_tpu.crypto.bls import BlsCryptoSigner, BlsCryptoVerifier
from plenum_tpu.crypto.multi_signature import (MultiSignature,
                                               MultiSignatureValue)
from plenum_tpu.storage.kv_store import KeyValueStorage


class BlsKeyRegister:
    """node name → BLS verkey, sourced from the pool ledger NODE txns
    (ref plenum/bls/bls_key_register_pool_manager.py). Injectable for tests."""

    def __init__(self, keys: Optional[dict[str, str]] = None):
        self._keys: dict[str, str] = dict(keys or {})

    def get_key_by_name(self, node_name: str) -> Optional[str]:
        return self._keys.get(node_name)

    def set_key(self, node_name: str, verkey: Optional[str]) -> None:
        if verkey is None:
            self._keys.pop(node_name, None)
        else:
            self._keys[node_name] = verkey

    def known_nodes(self) -> list[str]:
        return list(self._keys)


class BlsStore:
    """Persistent root-hash → MultiSignature map consulted by state-proof
    reads (ref plenum/bls/bls_store.py)."""

    def __init__(self, kv: KeyValueStorage):
        self._kv = kv

    def put(self, multi_sig: MultiSignature) -> None:
        self._kv.put(multi_sig.value.state_root_hash.encode(),
                     json_dumps(multi_sig.to_list()).encode())

    def get(self, state_root_hash: str) -> Optional[MultiSignature]:
        data = self._kv.try_get(state_root_hash.encode())
        if data is None:
            return None
        return MultiSignature.from_list(json_loads(data))


class BlsBftReplica:
    PPR_NO_BLS_MULTISIG = 0      # benign: previous batch had no quorum yet
    PPR_BLS_MULTISIG_WRONG = 1
    CM_BLS_SIG_WRONG = 2

    def __init__(self,
                 node_name: str,
                 bls_signer: Optional[BlsCryptoSigner],
                 bls_verifier: BlsCryptoVerifier,
                 key_register: BlsKeyRegister,
                 bls_store: Optional[BlsStore] = None,
                 quorums: Optional[Quorums] = None):
        self._node_name = node_name
        self._signer = bls_signer
        self._verifier = bls_verifier
        self._register = key_register
        self._store = bls_store
        self._quorums = quorums or Quorums(4)
        # (view_no, pp_seq_no) -> {node_name: sig}
        self._sigs: dict[tuple[int, int], dict[str, str]] = {}
        # state_root -> MultiSignature for recently ordered batches
        self._recent_multi_sigs: dict[str, MultiSignature] = {}

    def set_quorums(self, quorums: Quorums) -> None:
        self._quorums = quorums

    # --- signed payload ---------------------------------------------------

    @staticmethod
    def _signed_value(pre_prepare: PrePrepare) -> MultiSignatureValue:
        return MultiSignatureValue(
            ledger_id=pre_prepare.ledger_id,
            state_root_hash=pre_prepare.state_root,
            pool_state_root_hash=pre_prepare.pool_state_root,
            txn_root_hash=pre_prepare.txn_root,
            timestamp=pre_prepare.pp_time)

    # --- PRE-PREPARE ------------------------------------------------------

    def update_pre_prepare(self, params: dict, state_root: str) -> dict:
        """Attach the previous batch's aggregated multi-sig (by state root)."""
        ms = self._recent_multi_sigs.get(state_root)
        if ms is not None:
            params["bls_multi_sig"] = tuple(ms.to_list())
        return params

    def validate_pre_prepare(self, pre_prepare: PrePrepare, sender: str) -> Optional[int]:
        if pre_prepare.bls_multi_sig is None:
            return None
        try:
            ms = MultiSignature.from_list(list(pre_prepare.bls_multi_sig))
        except (ValueError, TypeError, IndexError, KeyError):
            return self.PPR_BLS_MULTISIG_WRONG
        # Participants must be DISTINCT registered validators: aggregation is
        # plain point addition, so one colluding node's signature repeated
        # n-f times would otherwise verify as a quorum multi-sig (rogue
        # self-aggregation).
        if len(set(ms.participants)) != len(ms.participants):
            return self.PPR_BLS_MULTISIG_WRONG
        verkeys = [self._register.get_key_by_name(n) for n in ms.participants]
        if any(v is None for v in verkeys):
            return self.PPR_BLS_MULTISIG_WRONG
        if not self._quorums.bls_signatures.is_reached(len(ms.participants)):
            return self.PPR_BLS_MULTISIG_WRONG
        if not self._verifier.verify_multi_sig(ms.signature,
                                               ms.value.as_single_value(),
                                               verkeys):
            return self.PPR_BLS_MULTISIG_WRONG
        return None

    # --- COMMIT -----------------------------------------------------------

    def update_commit(self, params: dict, pre_prepare: PrePrepare) -> dict:
        if self._signer is not None:
            value = self._signed_value(pre_prepare)
            params["bls_sig"] = self._signer.sign(value.as_single_value())
        return params

    def validate_commit(self, commit: Commit, sender_node: str,
                        pre_prepare: PrePrepare) -> Optional[int]:
        if commit.bls_sig is None:
            return None
        verkey = self._register.get_key_by_name(sender_node)
        if verkey is None:
            return None           # node has no registered BLS key: sig ignored
        value = self._signed_value(pre_prepare)
        if not self._verifier.verify_sig(commit.bls_sig,
                                         value.as_single_value(), verkey):
            return self.CM_BLS_SIG_WRONG
        return None

    def process_commit(self, commit: Commit, sender_node: str) -> None:
        if commit.bls_sig is None:
            return
        key = (commit.view_no, commit.pp_seq_no)
        self._sigs.setdefault(key, {})[sender_node] = commit.bls_sig

    # --- order ------------------------------------------------------------

    def process_order(self, key: tuple[int, int],
                      pre_prepare: PrePrepare) -> Optional[MultiSignature]:
        sigs = self._sigs.get(key, {})
        if not self._quorums.bls_signatures.is_reached(len(sigs)):
            return None
        participants = tuple(sorted(sigs))
        agg = self._verifier.create_multi_sig([sigs[n] for n in participants])
        ms = MultiSignature(signature=agg, participants=participants,
                            value=self._signed_value(pre_prepare))
        self._recent_multi_sigs[pre_prepare.state_root] = ms
        if len(self._recent_multi_sigs) > 10:
            oldest = next(iter(self._recent_multi_sigs))
            del self._recent_multi_sigs[oldest]
        if self._store is not None:
            self._store.put(ms)
        return ms

    def gc(self, stable_3pc: tuple[int, int]) -> None:
        self._sigs = {k: v for k, v in self._sigs.items() if k > stable_3pc}
