"""BLS wiring into the 3PC flow: sign state roots at COMMIT, aggregate at
order time, embed the previous batch's multi-sig into the next PRE-PREPARE.

Reference behavior: plenum/bls/bls_bft_replica_plenum.py:21 —
update_pre_prepare :80 / validate_pre_prepare :43 / update_commit :99
(_sign_state :227) / validate_commit :55 / process_commit :144 /
process_order :154 (_calculate_all_multi_sigs :261) — and plenum/bls/
bls_store.py (root-hash → multi-sig KV used by state-proof reads).
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from plenum_tpu.common.metrics import MetricsName
from plenum_tpu.common.node_messages import Commit, PrePrepare
from plenum_tpu.common.quorums import Quorums
from plenum_tpu.common.serialization import json_dumps, json_loads
from plenum_tpu.crypto.bls import BlsCryptoSigner, BlsCryptoVerifier
from plenum_tpu.crypto.multi_signature import (MultiSignature,
                                               MultiSignatureValue)
from plenum_tpu.storage.kv_store import KeyValueStorage


class BlsKeyRegister:
    """node name → BLS verkey, sourced from the pool ledger NODE txns
    (ref plenum/bls/bls_key_register_pool_manager.py). Injectable for tests."""

    def __init__(self, keys: Optional[dict[str, str]] = None):
        self._keys: dict[str, str] = dict(keys or {})

    def get_key_by_name(self, node_name: str) -> Optional[str]:
        return self._keys.get(node_name)

    def set_key(self, node_name: str, verkey: Optional[str]) -> None:
        if verkey is None:
            self._keys.pop(node_name, None)
        else:
            self._keys[node_name] = verkey

    def known_nodes(self) -> list[str]:
        return list(self._keys)


class BlsStore:
    """Persistent root-hash → MultiSignature map consulted by state-proof
    reads (ref plenum/bls/bls_store.py)."""

    def __init__(self, kv: KeyValueStorage):
        self._kv = kv

    @property
    def kv(self) -> KeyValueStorage:
        return self._kv

    def put(self, multi_sig: MultiSignature) -> None:
        self._kv.put(multi_sig.value.state_root_hash.encode(),
                     json_dumps(multi_sig.to_list()).encode())

    def get(self, state_root_hash: str) -> Optional[MultiSignature]:
        data = self._kv.try_get(state_root_hash.encode())
        if data is None:
            return None
        return MultiSignature.from_list(json_loads(data))


class BlsBftReplica:
    PPR_NO_BLS_MULTISIG = 0      # benign: previous batch had no quorum yet
    PPR_BLS_MULTISIG_WRONG = 1
    CM_BLS_SIG_WRONG = 2

    def __init__(self,
                 node_name: str,
                 bls_signer: Optional[BlsCryptoSigner],
                 bls_verifier: BlsCryptoVerifier,
                 key_register: BlsKeyRegister,
                 bls_store: Optional[BlsStore] = None,
                 quorums: Optional[Quorums] = None,
                 node_reg_at: Optional[Callable[[str], Optional[list]]] = None,
                 key_at: Optional[Callable[[str, str],
                                           Optional[str]]] = None):
        self._node_name = node_name
        self._signer = bls_signer
        self._verifier = bls_verifier
        self._register = key_register
        self._store = bls_store
        self._quorums = quorums or Quorums(4)
        # pool-state-root -> node registry at that root (audit-ledger
        # lookup, wired by the node): a multi-sig is judged by the quorum
        # rules of the pool size it was CREATED under, not today's
        self._node_reg_at = node_reg_at
        # (name, pool_root_hex) -> BLS verkey at that pool state (historic
        # MPT read): after a key ROTATION the embedded sig from just before
        # the rotation batch verifies only against the OLD key
        self._key_at = key_at
        # (view_no, pp_seq_no) -> {node_name: sig}
        self._sigs: dict[tuple[int, int], dict[str, str]] = {}
        # state_root -> MultiSignature for recently ordered batches
        self._recent_multi_sigs: dict[str, MultiSignature] = {}
        # set by the node: called with the sender of a bad COMMIT signature
        # caught by the order-time per-signature fallback
        self.report_bad_signature: Optional[Callable[[str], None]] = None
        # set by the node: every freshly aggregated multi-sig (including
        # late pending-order retries) is announced so the read plane can
        # advance its signed-root anchor
        self.on_multi_sig: Optional[Callable[[MultiSignature], None]] = None
        # optional MetricsCollector (master instance only): commit-path
        # stage timer + the pairings-per-batch counter the batched-BLS
        # acceptance is judged by
        self.metrics = None
        # multi-sigs we aggregated (and therefore verified) ourselves: in
        # steady state the primary embeds exactly this into the next
        # PRE-PREPARE, so validate_pre_prepare can skip the pairing
        self._verified_ms_keys: dict[tuple, None] = {}
        # ordered batches whose multi-sig fell short of quorum, retried as
        # late COMMITs arrive; and senders whose sig already failed for a key
        self._pending_order: dict[tuple[int, int], PrePrepare] = {}
        self._known_bad: dict[tuple[int, int], set[str]] = {}
        # quorum-complete aggregates that a LATE honest sig may still
        # upgrade: key -> (pre_prepare, participants). Without this a
        # node on a slow WAN link whose COMMIT always lands after the
        # n-f quorum is permanently absent from every multi-sig this
        # node emits (and a just-re-keyed node never visibly rejoins)
        self._aggregated: dict[tuple[int, int],
                               tuple[PrePrepare, tuple]] = {}

    def set_quorums(self, quorums: Quorums) -> None:
        self._quorums = quorums

    # --- signed payload ---------------------------------------------------

    @staticmethod
    def _signed_value(pre_prepare: PrePrepare) -> MultiSignatureValue:
        return MultiSignatureValue(
            ledger_id=pre_prepare.ledger_id,
            state_root_hash=pre_prepare.state_root,
            pool_state_root_hash=pre_prepare.pool_state_root,
            txn_root_hash=pre_prepare.txn_root,
            timestamp=pre_prepare.pp_time)

    # --- PRE-PREPARE ------------------------------------------------------

    def update_pre_prepare(self, params: dict, state_root: str) -> dict:
        """Attach the previous batch's aggregated multi-sig (by state root)."""
        ms = self._recent_multi_sigs.get(state_root)
        if ms is not None:
            params["bls_multi_sig"] = tuple(ms.to_list())
        return params

    def validate_pre_prepare(self, pre_prepare: PrePrepare, sender: str) -> Optional[int]:
        if pre_prepare.bls_multi_sig is None:
            return None
        try:
            ms = MultiSignature.from_list(list(pre_prepare.bls_multi_sig))
        except (ValueError, TypeError, IndexError, KeyError):
            return self.PPR_BLS_MULTISIG_WRONG
        # Participants must be DISTINCT registered validators: aggregation is
        # plain point addition, so one colluding node's signature repeated
        # n-f times would otherwise verify as a quorum multi-sig (rogue
        # self-aggregation).
        if len(set(ms.participants)) != len(ms.participants):
            return self.PPR_BLS_MULTISIG_WRONG
        # A multi-sig we aggregated (or fully verified) OURSELVES passed the
        # quorum rules in force when it was created. This shortcut must come
        # BEFORE the current-quorum check: the first PRE-PREPARE after a pool
        # membership change legitimately embeds the previous batch's sig,
        # whose participant count satisfies the OLD n - f, not the new one —
        # re-judging it with the new quorums would mark every honest primary
        # suspicious and storm view changes on every pool growth.
        if self._ms_key(ms) in self._verified_ms_keys:
            return None
        # keys AND quorum AS OF the sig's cited pool state — the same
        # epoch resolution process_order aggregates under, so an honest
        # aggregate passes here BY CONSTRUCTION (each node's aggregate can
        # pick a different participant subset, so the self-verified
        # shortcut alone cannot cover membership changes)
        key_of, reg, quorums = self._epoch_of(ms.value.pool_state_root_hash)
        vk_of = {n: key_of(n) for n in ms.participants}
        if any(v is None for v in vk_of.values()):
            return self.PPR_BLS_MULTISIG_WRONG
        if reg is not None and not set(ms.participants) <= set(reg):
            return self.PPR_BLS_MULTISIG_WRONG
        if not quorums.bls_signatures.is_reached(len(ms.participants)):
            return self.PPR_BLS_MULTISIG_WRONG
        ok = self._verifier.verify_multi_sig(ms.signature,
                                             ms.value.as_single_value(),
                                             [vk_of[n] for n in
                                              ms.participants])
        self._drop_stale_points(vk_of)
        if not ok:
            return self.PPR_BLS_MULTISIG_WRONG
        self._remember_verified(ms)
        return None

    # --- COMMIT -----------------------------------------------------------

    def update_commit(self, params: dict, pre_prepare: PrePrepare) -> dict:
        if self._signer is not None:
            value = self._signed_value(pre_prepare)
            params["bls_sig"] = self._signer.sign(value.as_single_value())
        return params

    def validate_commit(self, commit: Commit, sender_node: str,
                        pre_prepare: PrePrepare) -> Optional[int]:
        """DEFERRED verification: only the cheap structural check happens per
        COMMIT. The ~74x more expensive pairing runs ONCE per batch when the
        commit quorum forms, as a random-linear-combination batch check with
        per-signature fallback to evict liars (process_order) — per-commit
        pairings were the dominant term in pool TPS (one pairing per peer
        COMMIT per batch per node)."""
        if commit.bls_sig is None:
            return None
        if not self._verifier.is_wellformed_sig(commit.bls_sig):
            return self.CM_BLS_SIG_WRONG
        return None

    def process_commit(self, commit: Commit, sender_node: str) -> None:
        if commit.bls_sig is None:
            return
        key = (commit.view_no, commit.pp_seq_no)
        self._sigs.setdefault(key, {})[sender_node] = commit.bls_sig
        # A batch can order before every honest COMMIT arrives; if its
        # multi-sig aggregation fell short of quorum (e.g. one bad signature
        # evicted by the bisection), late honest sigs must retry it — or a
        # single Byzantine racer could suppress multi-sigs forever.
        pending = self._pending_order.get(key)
        if pending is not None:
            self.process_order(key, pending)
            return
        # late sig for an already-aggregated batch: re-aggregate so the
        # sender joins the multi-sig (verdicts of the existing members
        # ride the process-wide cache — the upgrade prices one combined
        # check of the new sig, not n pairings)
        agg = self._aggregated.get(key)
        if agg is not None and sender_node not in agg[1]:
            self.process_order(key, agg[0])

    # --- order ------------------------------------------------------------

    def process_order(self, key: tuple[int, int],
                      pre_prepare: PrePrepare) -> Optional[MultiSignature]:
        # Aggregate under the keys and quorum of the EPOCH the sig value
        # cites (the pre-prepare's pool state root), not the node's current
        # register: around a rotation or demotion the two differ, and every
        # validator judging the embedded aggregate re-derives the CITED
        # epoch (validate_pre_prepare) — an aggregate judged by current
        # membership would fail on every honest peer and storm view changes
        # (churn-soak waves: 3-participant sigs citing a 5-node root, and
        # stale-register aggregates spanning a rotation window).
        key_of, _reg, quorums = self._epoch_of(pre_prepare.pool_state_root)
        # one historic-epoch key resolution per signer per call: _key_at
        # is a historic pool-state read, and this path re-runs on every
        # late COMMIT
        vk_of = {n: key_of(n) for n in self._sigs.get(key, {})}
        sigs = {n: s for n, s in self._sigs.get(key, {}).items()
                if vk_of[n] is not None
                and n not in self._known_bad.get(key, set())}
        if not quorums.bls_signatures.is_reached(len(sigs)):
            self._pending_order[key] = pre_prepare      # retry on late sigs
            return None
        value = self._signed_value(pre_prepare).as_single_value()
        t0 = time.perf_counter()
        from plenum_tpu.crypto.bn254 import PAIRING_STATS
        pairings_before = PAIRING_STATS["pairings"]
        good, bad = self._batch_verify_commits(sigs, value, vk_of)
        if self.metrics is not None:
            self.metrics.add_event(MetricsName.COMMIT_BLS_VERIFY_TIME,
                                   time.perf_counter() - t0)
            self.metrics.add_event(MetricsName.BLS_PAIRINGS_PER_BATCH,
                                   PAIRING_STATS["pairings"] - pairings_before)
        for sender in bad:
            self._known_bad.setdefault(key, set()).add(sender)
            if self.report_bad_signature is not None:
                self.report_bad_signature(sender)
        if not quorums.bls_signatures.is_reached(len(good)):
            self._pending_order[key] = pre_prepare      # retry on late sigs
            return None
        self._pending_order.pop(key, None)
        participants = tuple(sorted(good))
        prev = self._aggregated.get(key)
        if prev is not None and set(participants) <= set(prev[1]):
            return None         # no new honest signer: keep the aggregate
        agg = self._verifier.create_multi_sig([good[n] for n in participants])
        ms = MultiSignature(signature=agg, participants=participants,
                            value=self._signed_value(pre_prepare))
        self._remember_verified(ms)
        self._recent_multi_sigs[pre_prepare.state_root] = ms
        if len(self._recent_multi_sigs) > 10:
            oldest = next(iter(self._recent_multi_sigs))
            del self._recent_multi_sigs[oldest]
        self._aggregated.pop(key, None)     # re-insert newest-last
        self._aggregated[key] = (pre_prepare, participants)
        while len(self._aggregated) > 10:
            del self._aggregated[next(iter(self._aggregated))]
        if self._store is not None:
            self._store.put(ms)
        if self.on_multi_sig is not None:
            self.on_multi_sig(ms)
        return ms

    def _epoch_of(self, pool_root: str):
        """-> (key_of, reg, quorums) AS OF `pool_root` — the epoch a
        multi-sig value cites. Unresolvable history falls back to the
        current register/quorums. Aggregation (process_order) and
        validation (validate_pre_prepare) MUST share this resolution:
        any divergence makes honest aggregates look forged."""
        quorums = self._quorums
        reg = None
        if self._node_reg_at is not None:
            reg = self._node_reg_at(pool_root) or None
            if reg:
                quorums = Quorums(len(reg))

        def key_of(n: str) -> Optional[str]:
            vk = self._key_at(n, pool_root) \
                if self._key_at is not None else None
            return vk or self._register.get_key_by_name(n)
        return key_of, reg, quorums

    def _batch_verify_commits(self, sigs: dict[str, str], value: bytes,
                              vk_of: dict[str, Optional[str]]) \
            -> tuple[dict[str, str], list[str]]:
        """Validate the whole COMMIT set with ONE random-linear-combination
        pairing check (crypto.bls.BlsCryptoVerifier.batch_verify): every
        signer signs the same ordered-batch value, so the combined check
        costs 2 pairings regardless of pool size — amortized O(1) vs the
        Θ(n) independent 2-pairing checks of per-Commit verification. On
        failure the verifier falls back to per-signature checks, which name
        the culprit(s) exactly (no subset bisection: plain-aggregation
        subsets can be satisfied by error-cancelling signature pairs, the
        RLC cannot)."""
        names = sorted(sigs)
        items = [(sigs[n], value, vk_of[n]) for n in names]
        oks = self._verifier.batch_verify(items)
        self._drop_stale_points(vk_of)
        good = {n: sigs[n] for n, ok in zip(names, oks) if ok}
        bad = [n for n, ok in zip(names, oks) if not ok]
        return good, bad

    def _drop_stale_points(self, vk_of: dict[str, Optional[str]]) -> None:
        """A historic-epoch verify (a batch citing a pre-rotation pool
        root) legitimately decodes the rotated-OUT key — but it must not
        stay warm in the key table past the check, or the eviction
        contract node._on_pool_changed enforces is undone by the next
        in-flight batch."""
        for n, vk in vk_of.items():
            if vk is not None and vk != self._register.get_key_by_name(n):
                self._verifier.evict_key(vk)

    @staticmethod
    def _ms_key(ms: MultiSignature) -> tuple:
        return (ms.signature, tuple(ms.participants),
                ms.value.as_single_value())

    def _remember_verified(self, ms: MultiSignature) -> None:
        self._verified_ms_keys[self._ms_key(ms)] = None
        while len(self._verified_ms_keys) > 50:
            del self._verified_ms_keys[next(iter(self._verified_ms_keys))]

    def gc(self, stable_3pc: tuple[int, int]) -> None:
        self._sigs = {k: v for k, v in self._sigs.items() if k > stable_3pc}
        self._pending_order = {k: v for k, v in self._pending_order.items()
                               if k > stable_3pc}
        self._known_bad = {k: v for k, v in self._known_bad.items()
                           if k > stable_3pc}
        self._aggregated = {k: v for k, v in self._aggregated.items()
                            if k > stable_3pc}
