"""Turning suspicions into InstanceChange votes and votes into view changes.

Reference behavior: plenum/server/consensus/view_change_trigger_service.py:23
and server/view_change/instance_change_provider.py:30 — any local
VoteForViewChange (monitor degradation, primary disconnect, freshness stall,
protocol suspicion) becomes a broadcast InstanceChange for view+1; a quorum of
f+1 matching votes from distinct nodes starts the actual view change
(_try_start_view_change_by_instance_change :128). Votes expire after a TTL so
stale grievances can't combine across epochs, and they PERSIST across restart
(instance_change_provider.py:34-69 keeps them in the node-status DB) so a node
crash during a marginal f+1 accumulation doesn't reset the count.

Redesign note: the reference stamps votes with time.perf_counter and reloads
those stamps verbatim, so a restart (perf_counter restarts near zero) makes
old votes look FUTURE-dated and immortal until the interval catches up. Here
persisted stamps are wall-clock; on load each vote's wall age is converted
back into the node's TimerService timeline and anything older than the TTL is
dropped at the door.
"""
from __future__ import annotations

import time
from typing import Optional

from plenum_tpu.common.event_bus import ExternalBus, InternalBus
from plenum_tpu.common.internal_messages import (NeedViewChange,
                                                 VoteForViewChange)
from plenum_tpu.common.node_messages import InstanceChange
from plenum_tpu.common.serialization import pack, unpack
from plenum_tpu.common.timer import TimerService
from plenum_tpu.config import Config

from .consensus_shared_data import ConsensusSharedData


class InstanceChangeVoteStore:
    """Durable InstanceChange votes over the node-status KV.

    Key layout: b"ic/<view_no:08x>" -> msgpack {voter: wall_timestamp}.
    One row per proposed view keeps remove-on-view-change a single delete.
    """

    PREFIX = b"ic/"

    def __init__(self, kv, wall_now=time.time):
        self._kv = kv
        self._wall_now = wall_now

    def save_view(self, view_no: int, voters_wall_ts: dict[str, float]) -> None:
        key = self.PREFIX + b"%08x" % view_no
        if voters_wall_ts:
            self._kv.put(key, pack(voters_wall_ts))
        else:
            self.remove_view(view_no)

    def remove_view(self, view_no: int) -> None:
        try:
            self._kv.remove(self.PREFIX + b"%08x" % view_no)
        except KeyError:
            pass

    def load(self, ttl: float) -> dict[int, dict[str, float]]:
        """-> {view_no: {voter: age_seconds}}, TTL-filtered at load."""
        now = self._wall_now()
        out: dict[int, dict[str, float]] = {}
        for key, value in list(self._kv.iterator()):
            if not bytes(key).startswith(self.PREFIX):
                continue
            try:
                view_no = int(bytes(key)[len(self.PREFIX):], 16)
                votes = unpack(value)
            except Exception:   # corrupt row: skip, never brick startup
                continue
            kept = {voter: now - ts for voter, ts in votes.items()
                    if isinstance(ts, (int, float)) and 0 <= now - ts <= ttl}
            if kept:
                out[view_no] = kept
            else:
                self.remove_view(view_no)
        return out


class ViewChangeTriggerService:
    def __init__(self,
                 data: ConsensusSharedData,
                 timer: TimerService,
                 bus: InternalBus,
                 network: ExternalBus,
                 config: Optional[Config] = None,
                 vote_store: Optional[InstanceChangeVoteStore] = None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._config = config or Config()
        self._store = vote_store
        # proposed view -> node -> vote timestamp (TimerService timeline)
        self._votes: dict[int, dict[str, float]] = {}
        # parallel wall-clock stamps, mirrored to the store (persistence
        # must survive a TimerService restart, which timer stamps don't)
        self._wall: dict[int, dict[str, float]] = {}

        if self._store is not None:
            self._load_persisted()

        bus.subscribe(VoteForViewChange, self.process_vote_for_view_change)
        network.subscribe(InstanceChange, self.process_instance_change)

    def _load_persisted(self) -> None:
        """Re-seat surviving votes in the fresh timer timeline: a vote with
        wall age A gets timer stamp now-A, so its remaining TTL keeps
        ticking from where the crash left it."""
        ttl = self._config.INSTANCE_CHANGE_TIMEOUT
        now_t = self._timer.get_current_time()
        now_w = time.time()
        for view_no, ages in self._store.load(ttl).items():
            if view_no <= self._data.view_no:
                self._store.remove_view(view_no)
                continue
            for voter, age in ages.items():
                self._votes.setdefault(view_no, {})[voter] = now_t - age
                self._wall.setdefault(view_no, {})[voter] = now_w - age

    # --- local suspicion → broadcast vote ---------------------------------

    def process_vote_for_view_change(self, msg: VoteForViewChange) -> None:
        proposed = msg.view_no if msg.view_no is not None else self._data.view_no + 1
        ic = InstanceChange(view_no=proposed, reason=msg.suspicion_code)
        self._record_vote(proposed, self._data.node_name)
        self._network.send(ic)
        self._try_start(proposed)

    # --- peer votes -------------------------------------------------------

    # An InstanceChange may propose any future view, and each distinct
    # proposed view costs a tracked dict + a persisted KV row. Unbounded,
    # a Byzantine peer could grow both without limit by walking view_no
    # upward; views this far beyond reality have no honest proposer.
    MAX_FUTURE_VIEWS = 128

    def process_instance_change(self, msg: InstanceChange, sender: str) -> None:
        if msg.view_no <= self._data.view_no:
            return
        if msg.view_no > self._data.view_no + self.MAX_FUTURE_VIEWS:
            return
        self._record_vote(msg.view_no, sender)
        self._try_start(msg.view_no)

    def _record_vote(self, view_no: int, voter: str) -> None:
        self._votes.setdefault(view_no, {})[voter] = self._timer.get_current_time()
        self._wall.setdefault(view_no, {})[voter] = time.time()
        if self._store is not None:
            self._store.save_view(view_no, self._wall[view_no])

    def _live_votes(self, view_no: int) -> int:
        now = self._timer.get_current_time()
        ttl = self._config.INSTANCE_CHANGE_TIMEOUT
        votes = self._votes.get(view_no, {})
        expired = [v for v, ts in votes.items() if now - ts > ttl]
        for voter in expired:
            del votes[voter]
            self._wall.get(view_no, {}).pop(voter, None)
        if expired and self._store is not None:
            self._store.save_view(view_no, self._wall.get(view_no, {}))
        return len(votes)

    def _drop_view(self, view_no: int) -> None:
        self._votes.pop(view_no, None)
        self._wall.pop(view_no, None)
        if self._store is not None:
            self._store.remove_view(view_no)

    def purge_stale(self) -> None:
        """Drop every tracked/persisted proposal at or below the current
        view. Called after restart restore: the service is constructed
        before the audit ledger restores view_no, so the constructor's
        `view_no <= data.view_no` filter ran against 0 and votes for
        since-completed views may have been reloaded."""
        for stale in [v for v in set(self._votes) | set(self._wall)
                      if v <= self._data.view_no]:
            self._drop_view(stale)

    def _try_start(self, view_no: int) -> None:
        if view_no <= self._data.view_no:
            return
        if self._data.quorums.propagate.is_reached(self._live_votes(view_no)):
            # f+1 nodes want this view: at least one is honest, so join.
            # Retire every proposal at or below it — those votes are spent.
            for stale in [v for v in self._votes if v <= view_no]:
                self._drop_view(stale)
            self._bus.send(NeedViewChange(view_no=view_no))
