"""Turning suspicions into InstanceChange votes and votes into view changes.

Reference behavior: plenum/server/consensus/view_change_trigger_service.py:23
and server/view_change/instance_change_provider.py:30 — any local
VoteForViewChange (monitor degradation, primary disconnect, freshness stall,
protocol suspicion) becomes a broadcast InstanceChange for view+1; a quorum of
f+1 matching votes from distinct nodes starts the actual view change
(_try_start_view_change_by_instance_change :128). Votes expire after a TTL so
stale grievances can't combine across epochs.
"""
from __future__ import annotations

from typing import Optional

from plenum_tpu.common.event_bus import ExternalBus, InternalBus
from plenum_tpu.common.internal_messages import (NeedViewChange,
                                                 VoteForViewChange)
from plenum_tpu.common.node_messages import InstanceChange
from plenum_tpu.common.timer import TimerService
from plenum_tpu.config import Config

from .consensus_shared_data import ConsensusSharedData


class ViewChangeTriggerService:
    def __init__(self,
                 data: ConsensusSharedData,
                 timer: TimerService,
                 bus: InternalBus,
                 network: ExternalBus,
                 config: Optional[Config] = None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._config = config or Config()
        # proposed view -> node -> vote timestamp
        self._votes: dict[int, dict[str, float]] = {}

        bus.subscribe(VoteForViewChange, self.process_vote_for_view_change)
        network.subscribe(InstanceChange, self.process_instance_change)

    # --- local suspicion → broadcast vote ---------------------------------

    def process_vote_for_view_change(self, msg: VoteForViewChange) -> None:
        proposed = msg.view_no if msg.view_no is not None else self._data.view_no + 1
        ic = InstanceChange(view_no=proposed, reason=msg.suspicion_code)
        self._record_vote(proposed, self._data.node_name)
        self._network.send(ic)
        self._try_start(proposed)

    # --- peer votes -------------------------------------------------------

    def process_instance_change(self, msg: InstanceChange, sender: str) -> None:
        if msg.view_no <= self._data.view_no:
            return
        self._record_vote(msg.view_no, sender)
        self._try_start(msg.view_no)

    def _record_vote(self, view_no: int, voter: str) -> None:
        self._votes.setdefault(view_no, {})[voter] = self._timer.get_current_time()

    def _live_votes(self, view_no: int) -> int:
        now = self._timer.get_current_time()
        ttl = self._config.INSTANCE_CHANGE_TIMEOUT
        votes = self._votes.get(view_no, {})
        for voter in [v for v, ts in votes.items() if now - ts > ttl]:
            del votes[voter]
        return len(votes)

    def _try_start(self, view_no: int) -> None:
        if view_no <= self._data.view_no:
            return
        if self._data.quorums.propagate.is_reached(self._live_votes(view_no)):
            # f+1 nodes want this view: at least one is honest, so join.
            self._votes.pop(view_no, None)
            self._bus.send(NeedViewChange(view_no=view_no))
