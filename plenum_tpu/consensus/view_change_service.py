"""View change: replace the primary while preserving every batch that could
have been ordered anywhere.

Reference behavior: plenum/server/consensus/view_change_service.py:28 —
on NeedViewChange each node bumps the view, reverts in-flight work
(ViewChangeStarted → OrderingService), broadcasts a ViewChange message carrying
its prepared/preprepared certificates and checkpoints (_build_view_change_msg
:141), and acks other nodes' ViewChange messages to the new primary. The new
primary, holding n-f ViewChange messages each backed by an ack quorum, runs
NewViewBuilder (:358): pick the highest checkpoint supported by a strong
quorum (calc_checkpoint :363), then for every pp_seq_no in the window select
the batch certified prepared by a strong quorum of non-contradicting votes and
preprepared by a weak quorum (calc_batches :398), stopping at the first
null-batch gap. Everyone validates the NewView against their own collected
votes and finishes (_finish_view_change :314).
"""
from __future__ import annotations

import hashlib
from typing import Optional

from plenum_tpu.common.event_bus import ExternalBus, InternalBus
from plenum_tpu.common.internal_messages import (MissingMessage,
                                                 NeedViewChange,
                                                 VoteForViewChange,
                                                 NewViewAccepted,
                                                 NewViewCheckpointsApplied,
                                                 PrimarySelected,
                                                 RaisedSuspicion,
                                                 ViewChangeStarted)
from plenum_tpu.common.node_messages import (Checkpoint, NewView, ViewChange,
                                             ViewChangeAck)
from plenum_tpu.common.serialization import json_dumps
from plenum_tpu.common.stashing import (DISCARD, PROCESS, STASH, StashReason,
                                        StashingRouter)
from plenum_tpu.common.suspicion_codes import Suspicions
from plenum_tpu.common.timer import TimerService
from plenum_tpu.config import Config

from .batch_id import BatchID
from .consensus_shared_data import ConsensusSharedData
from .primary_selector import RoundRobinPrimariesSelector


def view_change_digest(vc: ViewChange) -> str:
    return hashlib.sha256(json_dumps(vc.to_dict()).encode()).hexdigest()


class NewViewBuilder:
    """Pure selection rules over a set of ViewChange votes (ref :358-493)."""

    def __init__(self, data: ConsensusSharedData):
        self._data = data

    def calc_checkpoint(self, vcs: list[ViewChange]) -> Optional[tuple]:
        best: Optional[tuple] = None
        for vc in vcs:
            for cp in vc.checkpoints:
                cp = tuple(cp)
                end = cp[2]
                # enough nodes could still use it (their stable <= end)
                usable = sum(1 for v in vcs if end >= v.stable_checkpoint)
                if not self._data.quorums.strong.is_reached(usable):
                    continue
                # enough nodes actually hold it
                holders = sum(1 for v in vcs if cp in {tuple(c) for c in v.checkpoints})
                if not self._data.quorums.strong.is_reached(holders):
                    continue
                if best is None or end > best[2]:
                    best = cp
        return best

    def calc_batches(self, cp: tuple, vcs: list[ViewChange]) -> Optional[list[BatchID]]:
        batches: list[BatchID] = []
        pp_seq_no = cp[2] + 1
        while pp_seq_no <= cp[2] + self._data.log_size:
            bid = self._find_batch(vcs, pp_seq_no)
            if bid is not None:
                batches.append(bid)
                pp_seq_no += 1
                continue
            if self._null_batch_certified(vcs, pp_seq_no):
                break                    # sequential ordering: stop at first gap
            return None                  # quorum not yet available
        return batches

    def _find_batch(self, vcs, pp_seq_no) -> Optional[BatchID]:
        # Among all certified candidates at this seq, pick the highest-view
        # certificate (PBFT selection rule: a batch prepared in a later view
        # supersedes earlier ones), tie-broken fully deterministically so the
        # primary and every validator compute the identical NewView.
        best: Optional[BatchID] = None
        for vc in vcs:
            for raw in vc.prepared:
                bid = BatchID.from_seq(raw)
                if bid.pp_seq_no != pp_seq_no:
                    continue
                if best is not None and (bid.view_no, bid.pp_view_no,
                                         bid.pp_digest) <= \
                        (best.view_no, best.pp_view_no, best.pp_digest):
                    continue
                if (self._prepared_certified(bid, vcs)
                        and self._preprepared_certified(bid, vcs)):
                    best = bid
        return best

    def _prepared_certified(self, bid: BatchID, vcs) -> bool:
        def not_contradicting(vc: ViewChange) -> bool:
            if bid.pp_seq_no <= vc.stable_checkpoint:
                return False
            for raw in vc.prepared:
                other = BatchID.from_seq(raw)
                if other.pp_seq_no != bid.pp_seq_no:
                    continue
                # A vote contradicts unless it is from an older view, or the
                # same view with identical identity.
                if other.view_no > bid.view_no:
                    return False
                if other.view_no >= bid.view_no and (
                        other.pp_digest != bid.pp_digest
                        or other.pp_view_no != bid.pp_view_no):
                    return False
            return True
        return self._data.quorums.strong.is_reached(
            sum(1 for vc in vcs if not_contradicting(vc)))

    def _preprepared_certified(self, bid: BatchID, vcs) -> bool:
        def witnessed(vc: ViewChange) -> bool:
            for raw in vc.preprepared:
                other = BatchID.from_seq(raw)
                if (other.pp_seq_no == bid.pp_seq_no
                        and other.pp_view_no == bid.pp_view_no
                        and other.pp_digest == bid.pp_digest
                        and other.view_no >= bid.view_no):
                    return True
            return False
        return self._data.quorums.weak.is_reached(
            sum(1 for vc in vcs if witnessed(vc)))

    def _null_batch_certified(self, vcs, pp_seq_no) -> bool:
        def has_no_prepare(vc: ViewChange) -> bool:
            if pp_seq_no <= vc.stable_checkpoint:
                return False
            return all(BatchID.from_seq(raw).pp_seq_no != pp_seq_no
                       for raw in vc.prepared)
        return self._data.quorums.strong.is_reached(
            sum(1 for vc in vcs if has_no_prepare(vc)))


class ViewChangeService:
    def __init__(self,
                 data: ConsensusSharedData,
                 timer: TimerService,
                 bus: InternalBus,
                 network: ExternalBus,
                 config: Optional[Config] = None,
                 selector: Optional[RoundRobinPrimariesSelector] = None,
                 instance_count: int = 1,
                 rtt=None):
        self._data = data
        self._timer = timer
        self._bus = bus
        self._network = network
        self._config = config or Config()
        self._selector = selector or RoundRobinPrimariesSelector()
        self._instance_count = instance_count
        self._builder = NewViewBuilder(data)
        # shared RTT estimate (node wires the catchup leecher's): a WAN
        # pool's view change legitimately takes many slow round trips —
        # the escalation timeout scales UP with measured RTT so a degraded
        # link doesn't read as a dead primary and storm view+2 escalations.
        # Never scales DOWN below the configured timeout: the flat config
        # stays the floor, so clean-LAN behavior is unchanged.
        self._rtt = rtt
        self._probe_backoff = None       # armed per view change
        # PBFT liveness: consecutive failed view changes DOUBLE the next
        # escalation timeout (reset on completion). Without growth, a WAN
        # where one view change takes 1.1x the flat timeout escalates
        # forever — each attempt aborted exactly before it can finish.
        self._escalations = 0

        # per view: author node -> ViewChange
        self._view_changes: dict[int, dict[str, ViewChange]] = {}
        # per view: vc digest -> set of ack'ing nodes
        self._acks: dict[int, dict[tuple[str, str], set[str]]] = {}
        self._new_view: Optional[NewView] = None
        # A NewView citing votes we haven't received yet, retried on each vote.
        self._pending_new_view: Optional[tuple[NewView, str]] = None

        self._stasher = StashingRouter()
        self._stasher.subscribe(ViewChange, self.process_view_change)
        self._stasher.subscribe(ViewChangeAck, self.process_view_change_ack)
        self._stasher.subscribe(NewView, self.process_new_view)
        self._stasher.subscribe_to(network)

        bus.subscribe(NeedViewChange, self.process_need_view_change)

    def set_instance_count(self, n: int) -> None:
        """Pool membership changed f: the NEXT view change selects this
        many primaries (ref adjustReplicas node.py:1260 — the instance
        count follows f, not the view)."""
        self._instance_count = n

    # --- starting a view change ------------------------------------------

    def process_need_view_change(self, msg: NeedViewChange) -> None:
        proposed = msg.view_no if msg.view_no is not None else self._data.view_no + 1
        if proposed <= self._data.view_no and self._data.view_no != 0:
            return
        self._start_view_change(proposed)

    def _start_view_change(self, proposed: int) -> None:
        self._data.view_no = proposed
        self._data.waiting_for_new_view = True
        self._new_view = None
        self._data.primaries = self._selector.select_primaries(
            proposed, self._instance_count, self._data.validators)
        # Snapshot the certificates BEFORE ViewChangeStarted: the ordering
        # service's revert clears the in-flight lists (ref _build_view_change_msg
        # :141 runs on pre-clean state).
        vc = ViewChange(
            view_no=proposed,
            stable_checkpoint=self._data.stable_checkpoint,
            prepared=tuple(b.to_list() for b in self._data.prepared),
            preprepared=tuple(b.to_list() for b in self._data.preprepared),
            checkpoints=tuple((c.view_no, c.seq_no_start, c.seq_no_end, c.digest)
                              for c in self._data.checkpoints),
        )
        # Votes for views this start skips past can never complete (only
        # the view we are WAITING in can finish) — retire them now, not
        # just in _finish: a node that escalates through many views
        # without ever finishing one otherwise accretes every dead view's
        # vote set (churn-soak bounded-growth violation: vc_votes grew
        # one full author-map per abandoned view).
        self._view_changes = {v: d for v, d in self._view_changes.items()
                              if v >= proposed}
        self._acks = {v: d for v, d in self._acks.items() if v >= proposed}
        self._bus.send(ViewChangeStarted(view_no=proposed))
        self._bus.send(PrimarySelected(view_no=proposed,
                                       primaries=tuple(self._data.primaries)))
        self._record_view_change(vc, self._data.node_name)
        self._network.send(vc)
        # Replay any ViewChange/NewView traffic that arrived before we moved.
        self._stasher.process_all_stashed(StashReason.FUTURE_VIEW)
        self._schedule_timeout(proposed)
        self._try_build_or_finish()

    def _new_view_timeout(self) -> float:
        """Escalation timeout: the flat config value, stretched (never
        shrunk) by the measured network RTT when adaptive timeouts are on.
        A view change is bounded by a handful of sequential round trips
        (VC broadcast -> acks -> NEW_VIEW), so `mult * rto` approximates
        the protocol's worst path on THIS network."""
        base = self._config.NEW_VIEW_TIMEOUT
        cap = getattr(self._config, "VC_TIMEOUT_MAX", 4 * base)
        if (self._rtt is not None
                and getattr(self._config, "VC_ADAPTIVE_TIMEOUTS", False)
                and self._rtt.srtt is not None):
            mult = getattr(self._config, "VC_RTT_TIMEOUT_MULT", 20.0)
            base = max(base, mult * self._rtt.timeout(
                floor=0.0, cap=cap, fallback=base))
        # binary growth per consecutive escalation (capped): attempt k
        # gets 2**k the budget, so SOME attempt outlives the network's
        # actual view-change latency no matter how wrong the config floor
        return min(cap, base * (2 ** min(self._escalations, 6)))

    def _schedule_timeout(self, view_no: int) -> None:
        timeout = self._new_view_timeout()

        def on_timeout():
            if self._data.waiting_for_new_view and self._data.view_no == view_no:
                # View change didn't complete: VOTE to escalate — through
                # the InstanceChange quorum, never unilaterally. A node that
                # jumps to view+1 alone strands itself views ahead of the
                # pool (found by the view-change fuzz: one node escalated to
                # view 11 while the quorum sat at 1). Ref: the reference
                # routes VC timeouts through instance changes too
                # (view_change_trigger_service + INSTANCE_CHANGE_TIMEOUT).
                self._escalations += 1      # next attempt gets 2x budget
                self._bus.send(VoteForViewChange(
                    suspicion_code=Suspicions.INSTANCE_CHANGE_TIMEOUT.code,
                    view_no=view_no + 1))
                self._schedule_timeout(view_no)     # keep voting while stuck
        self._timer.schedule(timeout, on_timeout)

        # Re-request probes: maybe only a MESSAGE was lost — far cheaper
        # to re-ask than to escalate views. The first probe fires at
        # half-time (as before); on a lossy WAN one probe is one more
        # coin-flip, so probes now REPEAT on a jittered exponential
        # backoff until the view change completes or escalates, each one
        # re-requesting the NEW_VIEW *and* any ViewChange votes a pending
        # NEW_VIEW cites that we still lack.
        from plenum_tpu.common.backoff import ExponentialBackoff
        self._probe_backoff = ExponentialBackoff(
            base=timeout / 2, cap=timeout, jitter=0.3,
            salt=f"vc_probe/{self._data.node_name}/{view_no}")
        self._schedule_probe(view_no)

    def _schedule_probe(self, view_no: int) -> None:
        backoff = self._probe_backoff
        if backoff is None:
            return

        def probe():
            if (not self._data.waiting_for_new_view
                    or self._data.view_no != view_no
                    or self._probe_backoff is not backoff):
                return                       # completed or escalated past us
            if self._new_view is None:
                self._bus.send(MissingMessage(
                    msg_type="NEW_VIEW", key={"view_no": view_no},
                    inst_id=self._data.inst_id, dst=None))
            if self._pending_new_view is not None:
                nv, _ = self._pending_new_view
                held = self._view_changes.get(view_no, {})
                for author, _digest in nv.view_changes:
                    if author not in held:
                        self._bus.send(MissingMessage(
                            msg_type="VIEW_CHANGE",
                            key={"view_no": view_no, "author": author},
                            inst_id=self._data.inst_id, dst=None))
            self._schedule_probe(view_no)
        self._timer.schedule(backoff.next(), probe)

    # --- collecting votes -------------------------------------------------

    def process_view_change(self, msg: ViewChange, sender: str):
        if msg.view_no < self._data.view_no:
            return DISCARD
        if msg.view_no > self._data.view_no or not self._data.waiting_for_new_view:
            return STASH(StashReason.FUTURE_VIEW)
        self._record_view_change(msg, sender)
        # Ack the author's vote to the would-be primary (ref: acks routed to
        # the new primary so it can prove vote authenticity).
        primary = self._data.primary_name
        ack = ViewChangeAck(view_no=msg.view_no, name=sender,
                            digest=view_change_digest(msg))
        if primary == self._data.node_name:
            self.process_view_change_ack(ack, self._data.node_name)
        else:
            self._network.send(ack, dst=[primary])
        self._try_build_or_finish()
        return PROCESS

    def _record_view_change(self, vc: ViewChange, author: str) -> None:
        self._view_changes.setdefault(vc.view_no, {})[author] = vc

    def process_view_change_ack(self, msg: ViewChangeAck, sender: str):
        if msg.view_no < self._data.view_no:
            return DISCARD
        if msg.view_no > self._data.view_no or not self._data.waiting_for_new_view:
            return STASH(StashReason.FUTURE_VIEW)
        self._acks.setdefault(msg.view_no, {}).setdefault(
            (msg.name, msg.digest), set()).add(sender)
        self._try_build_or_finish()
        return PROCESS

    # --- primary: building NEW_VIEW --------------------------------------

    def _is_new_primary(self) -> bool:
        return self._data.primary_name == self._data.node_name

    def _acked(self, view_no: int, author: str, vc: ViewChange) -> bool:
        votes = self._acks.get(view_no, {}).get(
            (author, view_change_digest(vc)), set())
        # The author's own broadcast counts implicitly; n-f-1 others must agree.
        return self._data.quorums.view_change_ack.is_reached(len(votes))

    def _try_build_or_finish(self) -> None:
        if not self._data.waiting_for_new_view:
            return
        view_no = self._data.view_no
        if self._is_new_primary() and self._new_view is None:
            self._try_build_new_view(view_no)
        if self._pending_new_view is not None:
            nv, nv_sender = self._pending_new_view
            if nv.view_no == view_no:
                self._pending_new_view = None
                self.process_new_view(nv, nv_sender)
            else:
                self._pending_new_view = None
        self._try_finish(view_no)

    def _try_build_new_view(self, view_no: int) -> None:
        vcs_by_author = self._view_changes.get(view_no, {})
        confirmed = {a: vc for a, vc in vcs_by_author.items()
                     if a == self._data.node_name or self._acked(view_no, a, vc)}
        if not self._data.quorums.view_change.is_reached(len(confirmed)):
            return
        # The primary may cite ANY view-change quorum (PBFT: n-f suffice).
        # Try the full confirmed set first; if the builder cannot produce a
        # consistent selection — one diverged member's conflicting batch
        # citations can poison calc_batches FOREVER, storming view changes
        # with a healthy quorum present (partition-heal fuzz seed 15906) —
        # fall back to subsets that exclude possible outliers.
        need = self._data.quorums.view_change.value
        authors = sorted(confirmed)
        candidates: list[list] = [authors]
        if len(authors) > need:
            for drop in authors:                       # leave-one-out
                candidates.append([a for a in authors if a != drop])
            if len(authors) <= 8:                      # exact quorums
                import itertools
                candidates.extend(
                    list(c) for c in itertools.combinations(authors, need))
        seen: set = set()
        for subset in candidates:
            key = tuple(subset)
            if len(subset) < need or key in seen:
                continue
            seen.add(key)
            ordered = sorted((a, confirmed[a]) for a in subset)
            # Iterate votes in the SAME author-sorted order process_new_view
            # will reconstruct from the published view_changes tuple: the
            # builder's selection is iteration-order-sensitive, and any
            # divergence makes validators reject a correct NewView.
            vcs = [vc for _, vc in ordered]
            cp = self._builder.calc_checkpoint(vcs)
            if cp is None:
                continue
            batches = self._builder.calc_batches(cp, vcs)
            if batches is None:
                continue
            nv = NewView(view_no=view_no,
                         view_changes=tuple(
                             (a, view_change_digest(vc)) for a, vc in ordered),
                         checkpoint=cp,
                         batches=tuple(b.to_list() for b in batches))
            self._new_view = nv
            self._network.send(nv)
            self._finish(nv)
            return

    # --- everyone: accepting NEW_VIEW -------------------------------------

    def process_new_view(self, msg: NewView, sender: str):
        if msg.view_no < self._data.view_no:
            return DISCARD
        if msg.view_no > self._data.view_no or not self._data.waiting_for_new_view:
            return STASH(StashReason.FUTURE_VIEW)
        if sender != self._data.primary_name:
            self._bus.send(RaisedSuspicion(
                inst_id=self._data.inst_id,
                code=Suspicions.NEW_VIEW_INVALID.code,
                reason=f"NEW_VIEW from non-primary {sender}"))
            return DISCARD
        # The primary's selection is never taken on trust: re-run the builder
        # over the cited votes and require an identical result (ref
        # _finish_view_change validates NewView against local state).
        if not self._data.quorums.view_change.is_reached(len(msg.view_changes)):
            return self._reject_new_view("NEW_VIEW cites too few ViewChanges")
        own = self._view_changes.get(msg.view_no, {})
        cited: list[ViewChange] = []
        for author, digest in msg.view_changes:
            if author not in self._data.validators:
                return self._reject_new_view(f"NEW_VIEW cites unknown node {author}")
            vc = own.get(author)
            if vc is None:
                # Wait for the missing vote — and actively re-request it from
                # peers (any holder can serve it; the cited digest vouches).
                self._pending_new_view = (msg, sender)
                self._bus.send(MissingMessage(
                    msg_type="VIEW_CHANGE",
                    key={"view_no": msg.view_no, "author": author},
                    inst_id=self._data.inst_id, dst=None))
                return PROCESS
            if view_change_digest(vc) != digest:
                return self._reject_new_view(
                    f"NEW_VIEW cites a ViewChange by {author} that differs "
                    f"from the one we received")
            cited.append(vc)
        cp = self._builder.calc_checkpoint(cited)
        if cp is None or tuple(cp) != tuple(msg.checkpoint):
            return self._reject_new_view("NEW_VIEW checkpoint does not follow "
                                         "from the cited votes")
        batches = self._builder.calc_batches(cp, cited)
        if batches is None or [tuple(b.to_list()) for b in batches] != \
                [tuple(b) for b in msg.batches]:
            return self._reject_new_view("NEW_VIEW batches do not follow "
                                         "from the cited votes")
        self._pending_new_view = None
        self._finish(msg)
        return PROCESS

    def process_requested_view_change(self, vc: ViewChange, author: str) -> None:
        """A peer-served ViewChange vote. Safe to record under the claimed
        author without proof: it is only ever USED where its digest is checked
        against a NewView's citation (process_new_view) or against an ack
        quorum (_acked) — a forged vote fails both."""
        if not author or vc.view_no < self._data.view_no:
            return
        # NEVER overwrite a vote we already hold: an unsolicited forged rep
        # could otherwise evict the genuine vote and wedge every view change.
        if author in self._view_changes.get(vc.view_no, {}):
            return
        self._record_view_change(vc, author)
        self._try_build_or_finish()

    def process_requested_new_view(self, nv: NewView) -> None:
        """A peer-served NewView: identical full validation, minus the
        sender-is-primary check (the responder is a relay, and the content is
        re-derived from our own collected votes anyway)."""
        if nv.view_no != self._data.view_no or not self._data.waiting_for_new_view:
            return
        self.process_new_view(nv, self._data.primary_name or "")

    def _reject_new_view(self, why: str):
        self._bus.send(RaisedSuspicion(inst_id=self._data.inst_id,
                                       code=Suspicions.NEW_VIEW_INVALID.code,
                                       reason=why))
        return DISCARD

    def _try_finish(self, view_no: int) -> None:
        if self._new_view is not None and not self._is_new_primary():
            self._finish(self._new_view)

    def _finish(self, nv: NewView) -> None:
        """_finish_view_change :314 — leave the waiting state and hand the
        selected checkpoint + batches to checkpoint/ordering services."""
        if not self._data.waiting_for_new_view:
            return
        self._new_view = nv
        self._probe_backoff = None          # stand the re-request loop down
        self._escalations = 0               # completed: budget back to floor
        self._data.waiting_for_new_view = False
        self._bus.send(NewViewAccepted(view_no=nv.view_no,
                                       checkpoint=tuple(nv.checkpoint),
                                       batches=tuple(nv.batches)))
        # Old vote state is now garbage.
        self._view_changes = {v: d for v, d in self._view_changes.items()
                              if v > nv.view_no}
        self._acks = {v: d for v, d in self._acks.items() if v > nv.view_no}
