"""BatchID: the identity of one 3PC batch across view changes.

Reference behavior: plenum/server/consensus/batch_id.py — a batch keeps its
original view number (`pp_view_no`) when re-ordered in a later view, so
prepared certificates survive view changes intact.
"""
from __future__ import annotations

from typing import NamedTuple


class BatchID(NamedTuple):
    view_no: int        # view in which the batch is being ordered now
    pp_view_no: int     # view in which its PRE-PREPARE was originally created
    pp_seq_no: int
    pp_digest: str

    def to_list(self) -> list:
        return [self.view_no, self.pp_view_no, self.pp_seq_no, self.pp_digest]

    @classmethod
    def from_seq(cls, items) -> "BatchID":
        return cls(int(items[0]), int(items[1]), int(items[2]), str(items[3]))
