"""The 3PC ordering hot loop: PRE-PREPARE → PREPARE → COMMIT → Ordered.

Reference behavior: plenum/server/consensus/ordering_service.py:60 —
process_preprepare :501, process_prepare :223, process_commit :436, batch
creation send_3pc_batch :1961 / create_3pc_batch :2038, in-order emission
_do_order :1475, out-of-order commit stash :191,1642, uncommitted apply/revert
_apply_pre_prepare :1138 / _revert :1229, and the view-change re-ordering hooks
:2380-2455. Message admission mirrors ordering_service_msg_validator.py:
discard stale traffic, stash future-view / outside-watermark / catching-up
traffic under typed reasons and replay when the blocking condition clears.

Only the master instance applies requests to uncommitted state; backups order
the same traffic for the RBFT monitor comparison without touching state
(SURVEY.md §2.3).
"""
from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Optional

from plenum_tpu.common.event_bus import ExternalBus, InternalBus
from plenum_tpu.common.metrics import MetricsName
from plenum_tpu.common.internal_messages import (MissingMessage,
                                                 NeedMasterCatchup,
                                                 NewViewCheckpointsApplied,
                                                 RaisedSuspicion, ReqKey,
                                                 RequestPropagates,
                                                 ViewChangeStarted)
from plenum_tpu.common.node_messages import (AUDIT_LEDGER_ID, DOMAIN_LEDGER_ID,
                                             VALID_LEDGER_IDS,
                                             Commit, Ordered, PrePrepare,
                                             Prepare)
from plenum_tpu.common.request import Request
from plenum_tpu.common.stashing import (DISCARD, PROCESS, STASH, StashReason,
                                        StashingRouter)
from plenum_tpu.common.suspicion_codes import Suspicions
from plenum_tpu.common.timer import TimerService
from plenum_tpu.common import tracing
from plenum_tpu.config import Config

from .batch_executor import AppliedBatch, BatchExecutor
from .batch_id import BatchID
from .bls_bft_replica import BlsBftReplica
from .consensus_shared_data import ConsensusSharedData


def _orig_view(pp: PrePrepare) -> int:
    """Original view of a (possibly re-ordered) batch; view 0 is a valid
    original view, so never use `or` here."""
    return pp.original_view_no if pp.original_view_no is not None else pp.view_no


class OrderingService:
    def __init__(self,
                 data: ConsensusSharedData,
                 timer: TimerService,
                 bus: InternalBus,
                 network: ExternalBus,
                 executor: Optional[BatchExecutor],
                 bls: Optional[BlsBftReplica] = None,
                 config: Optional[Config] = None,
                 get_request: Optional[Callable[[str], Optional[Request]]] = None,
                 metrics=None, tracer=None, controller=None):
        self._data = data
        self._timer = timer
        # per-phase 3PC timing (ref metrics_collector.py's 3PC names):
        # key -> (t_preprepare, t_prepared); emitted at quorum transitions
        self._metrics = metrics
        # tracing plane: batch-keyed span events (pp send/recv, prepare
        # quorum, commit send, ordered, apply) — master instance only
        self._tracer = tracer if tracer is not None else tracing.NULL_TRACER
        self._phase_ts: dict[tuple[int, int], list] = {}
        self._bus = bus
        self._network = network
        self._executor = executor
        self._bls = bls
        self._config = config or Config()
        self._get_request = get_request or (lambda digest: None)
        # closed-loop batch controller (batch_controller.py): when present
        # its steered knobs replace the static Max3PCBatchSize /
        # Max3PCBatchWait / Max3PCBatchesInFlight reads, and the primary
        # feeds it timer-stamped batch-lifecycle samples
        self._controller = controller
        # (view, pp_seq_no) -> cut stamp on the injectable timer; feeds
        # the controller's cut -> commit-quorum span on the primary
        self._cut_ts: dict[tuple[int, int], float] = {}

        # 3PC logs (all keyed by (view_no, pp_seq_no))
        self.sent_preprepares: dict[tuple[int, int], PrePrepare] = {}
        self.prePrepares: dict[tuple[int, int], PrePrepare] = {}
        self.prepares: dict[tuple[int, int], dict[str, Prepare]] = {}
        self.commits: dict[tuple[int, int], dict[str, Commit]] = {}
        self.ordered: set[tuple[int, int]] = set()
        # (original_view, pp_seq_no) -> digest of every batch this node has
        # EXECUTED; re-ordered incarnations of these re-certify (vote) but
        # must never re-apply or re-emit Ordered (see _order)
        self._ordered_originals: dict[tuple[int, int], str] = {}
        self._commits_sent: set[tuple[int, int]] = set()
        self._stashed_ooo_commits: dict[tuple[int, int], PrePrepare] = {}
        # Old-view pre-prepares kept for re-ordering after a view change,
        # keyed by (original view, pp_seq_no).
        self.old_view_preprepares: dict[tuple[int, int], PrePrepare] = {}

        # Finalized requests awaiting batching (primary only), per ledger.
        self.request_queues: dict[int, OrderedDict] = {
            lid: OrderedDict() for lid in VALID_LEDGER_IDS}
        # Master-only stack of applied-but-unordered batches for revert.
        self._applied_unordered: list[tuple[int, BatchID]] = []
        # Node-installed persistence hook for backup primaries' last-sent
        # PRE-PREPARE seq-no (ref last_sent_pp_store_helper.py).
        self.on_backup_pp_sent = None

        # wrong-instance traffic is rejected by the accept pre-filter
        # before any dispatch bookkeeping (at f+1 instances, 8 of 9 router
        # dispatches on the shared bus are another instance's messages);
        # _validate keeps its own inst_id check for direct callers
        self._stasher = StashingRouter(
            accept=lambda m: getattr(m, "inst_id", self._data.inst_id)
            == self._data.inst_id)
        self._stasher.subscribe(PrePrepare, self.process_preprepare)
        self._stasher.subscribe(Prepare, self.process_prepare)
        self._stasher.subscribe(Commit, self.process_commit)
        self._stasher.subscribe_to(network)

        bus.subscribe(ReqKey, self.process_req_key)
        bus.subscribe(ViewChangeStarted, self.process_view_change_started)
        bus.subscribe(NewViewCheckpointsApplied,
                      self.process_new_view_checkpoints_applied)

        # ledger_id -> absolute deadline for the next freshness batch
        self._freshness_deadline: dict[int, float] = {}
        # (orig_view, pp_seq_no) -> cited digest: NewView batches we lack
        # locally and have re-requested from peers
        self._awaited_old_view: dict[tuple[int, int], str] = {}
        # request digests a NewView re-proposal is blocked on (the new
        # primary lacked them); fresh batch minting pauses until resolved
        self._awaiting_reproposal: set = set()
        # the last accepted NewView payload, re-run when an awaited old-view
        # pre-prepare arrives
        self._last_new_view_msg: Optional[NewViewCheckpointsApplied] = None
        # backup instances joining a new view adopt the first pre-prepare
        # they see as their position (ref _setup_last_ordered_for_non_master)
        self._needs_last_ordered_setup = False

    def stop(self) -> None:
        """Detach from the shared network bus (replica removal): a removed
        instance must not keep consuming 3PC messages as a zombie."""
        self._stasher.unsubscribe_from_buses()

    # ------------------------------------------------------------------ #
    # request intake                                                     #
    # ------------------------------------------------------------------ #

    def process_req_key(self, msg: ReqKey) -> None:
        """A finalized request became available for ordering."""
        req = self._get_request(msg.digest)
        if req is None:
            return
        ledger_id = (self._executor.ledger_id_for(req)
                     if self._executor else DOMAIN_LEDGER_ID)
        # queue VALUES are enqueue stamps (injectable timer): the partial-
        # batch wait is measured from the oldest queued request's own
        # stamp, so no code path can restart a waiting request's clock.
        # setdefault: a duplicate ReqKey must not refresh the stamp.
        self.request_queues.setdefault(ledger_id, OrderedDict()).setdefault(
            msg.digest, self._timer.get_current_time())
        self._stasher.process_all_stashed(StashReason.MISSING_REQUESTS)
        # a NewView re-proposal deferred on THIS request (the primary
        # lacked it): resume the pass — idempotent, skips batches already
        # re-proposed. Gating on the pending set matters: an unconditional
        # re-entry would rerun the pass during normal post-view-change
        # operation and reset pp_seq_no under in-flight fresh batches.
        if (msg.digest in self._awaiting_reproposal
                and self._last_new_view_msg is not None
                and self.is_primary):
            self.process_new_view_checkpoints_applied(
                self._last_new_view_msg)

    # ------------------------------------------------------------------ #
    # batch creation (primary)                                           #
    # ------------------------------------------------------------------ #

    @property
    def is_primary(self) -> bool:
        return self._data.is_primary

    def service(self) -> None:
        """Called each prod cycle: primaries turn queued requests into batches."""
        if not self.is_primary or self._data.waiting_for_new_view:
            self._freshness_deadline.clear()
            return
        if not self._data.is_participating:
            return
        if self._awaited_old_view or self._awaiting_reproposal:
            # a new primary must finish re-proposing the NewView's cited
            # batches before cutting fresh ones — a fresh batch slotted
            # between pending re-proposals applies out of seq order and
            # corrupts the uncommitted stack (found by the view-change fuzz)
            return
        self.send_3pc_batch()
        self._send_freshness_batches()

    def _send_freshness_batches(self) -> None:
        """The master primary orders an EMPTY batch on any ledger that has
        gone STATE_FRESHNESS_UPDATE_INTERVAL without an update, so BLS
        state signatures stay fresh and non-primaries can tell a quiet
        primary from a dead one (ref ordering_service.py:1991
        _send_3pc_freshness_batch + FreshnessChecker)."""
        if not self._data.is_master:
            return
        interval = self._config.STATE_FRESHNESS_UPDATE_INTERVAL
        if interval <= 0:
            return
        now = self._timer.get_current_time()
        for lid in list(self.request_queues):
            if lid == AUDIT_LEDGER_ID:
                continue      # the audit ledger only moves with real batches
            due = self._freshness_deadline.get(lid)
            if due is None:
                self._freshness_deadline[lid] = now + interval
            elif now >= due:
                self.send_3pc_batch(lid, force_empty=True)

    def send_3pc_batch(self, ledger_id: Optional[int] = None,
                       force_empty: bool = False) -> int:
        """Create and broadcast PRE-PREPAREs from queued requests
        (ref send_3pc_batch :1961). Returns number of batches sent."""
        sent = 0
        now = self._timer.get_current_time()
        # effective knobs: controller-steered when the loop is closed,
        # static config otherwise
        ctl = self._controller
        max_size = (ctl.batch_size if ctl is not None
                    else self._config.Max3PCBatchSize)
        max_wait = (ctl.batch_wait if ctl is not None
                    else self._config.Max3PCBatchWait)
        depth = (ctl.depth if ctl is not None
                 else self._config.Max3PCBatchesInFlight)
        ledgers = [ledger_id] if ledger_id is not None else list(self.request_queues)
        for lid in ledgers:
            queue = self.request_queues.setdefault(lid, OrderedDict())
            if not queue and not force_empty:
                continue
            # Partial batches wait up to the batch wait for more requests
            # (full ones cut immediately). The wait is measured from the
            # OLDEST queued request's own enqueue stamp (the queue value):
            # the previous per-ledger clock was re-armed every prod tick
            # that left leftovers behind — e.g. while the in-flight gate
            # held — so under a steady trickle a partial batch could wait
            # far past the configured bound.
            if (not force_empty and len(queue) < max_size
                    and now - next(iter(queue.values())) < max_wait):
                continue
            while queue or force_empty:
                if self._data.pp_seq_no + 1 > self._data.high_watermark:
                    break
                # bound the SPECULATIVE window: how far uncommitted applies
                # may run ahead of the last committed batch. Deep by
                # default (the watermark window above is the hard protocol
                # bound; revert-on-view-change unwinds the whole stack),
                # controller-steered so a saturated pool backs off.
                if (not force_empty and self._data.pp_seq_no
                        - self._data.last_ordered_3pc[1] >= depth):
                    break
                digests = []
                oldest_cut = now
                bodyless = []
                while queue and len(digests) < max_size:
                    digest, enq_ts = queue.popitem(last=False)
                    # finalize-without-body guard (digest-gossip): a batch
                    # must never cite a request whose body this primary
                    # does not hold — re-queue it and pull the body
                    if self._get_request(digest) is None:
                        bodyless.append(digest)
                    else:
                        digests.append(digest)
                        oldest_cut = min(oldest_cut, enq_ts)
                # Bodyless digests are re-queued with a FRESH stamp: they
                # cannot be batched until a body lands anyway, so the
                # restart is harmless, it throttles the RequestPropagates
                # retry below to once per batch wait, and a byzantine
                # never-arriving body cannot sit at the queue head aging
                # the wait gate (and the controller's queue-wait
                # attribution) forever.
                for digest in bodyless:
                    queue[digest] = now
                if bodyless:
                    self._bus.send(RequestPropagates(
                        bad_requests=tuple(bodyless)))
                if not digests and not force_empty:
                    break        # everything queued is awaiting its body
                # queue wait attributed from the oldest request actually
                # CUT (a stale bodyless head must not inflate the sample)
                self._send_one_batch(lid, digests,
                                     queue_wait=max(0.0, now - oldest_cut))
                sent += 1
                if force_empty:
                    break
        return sent

    def _send_one_batch(self, ledger_id: int, digests: list[str],
                        queue_wait: float = 0.0) -> None:
        reqs = [r for r in (self._get_request(d) for d in digests) if r is not None]
        pp_time = self._timer.get_current_time()
        view_no = self._data.view_no
        pp_seq_no = self._data.pp_seq_no + 1
        applied = self._apply(ledger_id, reqs, pp_time, view_no, pp_seq_no)
        # req_idr carries ALL digests in apply order (valid AND rejected):
        # validators must re-apply the exact same sequence or a rejection that
        # depends on an earlier request in the same batch would diverge;
        # `discarded` marks which of them dynamic validation refused.
        all_digests = tuple(r.digest for r in reqs)
        params = dict(
            inst_id=self._data.inst_id,
            view_no=view_no,
            pp_seq_no=pp_seq_no,
            pp_time=pp_time,
            req_idr=all_digests,
            discarded=tuple(applied.discarded),
            ledger_id=ledger_id,
            state_root=applied.state_root,
            txn_root=applied.txn_root,
            pool_state_root=applied.pool_state_root,
            audit_txn_root=applied.audit_txn_root,
        )
        params["digest"] = self._batch_digest(params)
        if self._bls is not None:
            params = self._bls.update_pre_prepare(params, self._last_state_root(ledger_id))
        pre_prepare = PrePrepare(**params)
        self._freshness_deadline[ledger_id] = \
            pp_time + self._config.STATE_FRESHNESS_UPDATE_INTERVAL
        self._data.pp_seq_no = pp_seq_no
        self._data.last_batch_timestamp = pp_time
        key = (view_no, pp_seq_no)
        self.sent_preprepares[key] = pre_prepare
        self.prePrepares[key] = pre_prepare
        if self._controller is not None:
            self._controller.note_batch_cut(queue_wait, len(digests))
            self._cut_ts[key] = pp_time
        if self._metrics is not None:
            self._phase_ts[key] = [self._timer.get_current_time(), None]
        if self._tracer.enabled:
            # reqs list links request digests -> this batch for waterfall
            # assembly; seq links the batch -> the durable flush event
            self._tracer.emit(tracing.PP_SENT, pre_prepare.digest,
                              {"seq": pp_seq_no, "ledger": ledger_id,
                               "reqs": list(all_digests)})
        batch_id = BatchID(view_no, _orig_view(pre_prepare),
                           pp_seq_no, pre_prepare.digest)
        self._data.preprepare_batch(batch_id)
        if self._data.is_master:
            self._applied_unordered.append((ledger_id, batch_id))
        elif self.on_backup_pp_sent is not None:
            # backup primaries have no audit trail to restore from; the
            # node persists their last-sent seq-no so a restart resumes
            # the numbering instead of re-issuing pp_seq_no 1
            # (ref last_sent_pp_store_helper.py)
            self.on_backup_pp_sent(self._data.inst_id, view_no, pp_seq_no)
        self._network.send(pre_prepare)

    def _apply(self, ledger_id, reqs, pp_time, view_no, pp_seq_no) -> AppliedBatch:
        if self._data.is_master and self._executor is not None:
            # primaries resolution is the executor's: the audit ledger is
            # the exact historical record (write_manager._resolve_primaries)
            return self._timed_apply(
                ledger_id, reqs, pp_time, view_no, pp_seq_no,
                primaries=(list(self._data.primaries)
                           if view_no == self._data.view_no else None))
        digests = tuple(r.digest for r in reqs)
        return AppliedBatch("", "", "", "", digests, ())

    def _timed_apply(self, ledger_id, reqs, pp_time, view_no, pp_seq_no,
                     primaries=None) -> AppliedBatch:
        """executor.apply_batch under the commit-path apply-stage timer —
        every uncommitted apply (fresh batch, peer pre-prepare, view-change
        re-apply) lands in the same stage bucket."""
        t0 = time.perf_counter()
        try:
            return self._executor.apply_batch(
                ledger_id, reqs, pp_time, view_no, pp_seq_no,
                primaries=primaries)
        finally:
            if self._metrics is not None:
                self._metrics.add_event(MetricsName.COMMIT_APPLY_TIME,
                                        time.perf_counter() - t0)
            if self._tracer.enabled:
                # keyed by seq (the batch digest does not exist yet for a
                # fresh batch being minted); wall duration only when the
                # tracer allows it (replay determinism)
                data = {"seq": pp_seq_no, "n": len(reqs)}
                if self._tracer.wall_durations:
                    data["dur"] = time.perf_counter() - t0
                self._tracer.emit(tracing.APPLY, "", data)

    def _last_state_root(self, ledger_id: int) -> str:
        """State root of the previous batch on this ledger (what the previous
        multi-sig signed) — used to look up the sig to embed."""
        for key in sorted(self.prePrepares, reverse=True):
            pp = self.prePrepares[key]
            if pp.ledger_id == ledger_id and key in self.ordered:
                return pp.state_root
        return ""

    @staticmethod
    def _batch_digest(pp) -> str:
        """Digest binding the FULL batch content — req set, rejection set,
        roots, time, ledger — under its ORIGINAL view. Anything not bound
        here could be mutated by a lying MessageRep responder and still
        pass the f+1-prepare certification, framing the primary (or, on
        executor-less backups, forking the instance)."""
        import hashlib
        get = pp.get if isinstance(pp, dict) else \
            lambda k, d=None: getattr(pp, k, d)
        orig_view = get("original_view_no")
        view = orig_view if orig_view is not None else get("view_no")
        h = hashlib.sha256()
        h.update(f"{view}:{get('pp_seq_no')}:{get('ledger_id')}:"
                 f"{get('pp_time')!r}:".encode())
        for d in get("req_idr"):
            h.update(b"\x00" + d.encode())
        for d in get("discarded"):
            h.update(b"\x01" + d.encode())
        for root in (get("state_root"), get("txn_root"),
                     get("audit_txn_root"), get("pool_state_root")):
            h.update(b"\x02" + (root or "").encode())
        return h.hexdigest()

    # ------------------------------------------------------------------ #
    # admission control                                                  #
    # ------------------------------------------------------------------ #

    def _validate(self, msg) -> object:
        """PROCESS / DISCARD / STASH(reason) — ref ordering_service_msg_validator."""
        if msg.inst_id != self._data.inst_id:
            return DISCARD
        if not self._data.is_participating:
            return STASH(StashReason.CATCHING_UP)
        if msg.view_no < self._data.view_no:
            return DISCARD
        if msg.view_no > self._data.view_no:
            return STASH(StashReason.FUTURE_VIEW)
        if self._data.waiting_for_new_view:
            return STASH(StashReason.WAITING_FOR_NEW_VIEW)
        if (msg.view_no, msg.pp_seq_no) in self.ordered:
            return DISCARD
        if msg.pp_seq_no <= self._data.low_watermark:
            return DISCARD
        if msg.pp_seq_no > self._data.high_watermark:
            return STASH(StashReason.OUTSIDE_WATERMARKS)
        return PROCESS

    def _suspect(self, suspicion, sender: str) -> None:
        self._bus.send(RaisedSuspicion(inst_id=self._data.inst_id,
                                       code=suspicion.code,
                                       reason=f"{suspicion.reason} (from {sender})",
                                       sender=sender))

    # ------------------------------------------------------------------ #
    # PRE-PREPARE                                                        #
    # ------------------------------------------------------------------ #

    def process_preprepare(self, msg: PrePrepare, sender: str):
        verdict = self._validate(msg)
        if verdict is not PROCESS:
            return verdict
        if sender != self._data.primary_name:
            self._suspect(Suspicions.PPR_FRM_NON_PRIMARY, sender)
            return DISCARD
        key = (msg.view_no, msg.pp_seq_no)
        if key in self.prePrepares and self.prePrepares[key].digest != msg.digest:
            self._suspect(Suspicions.DUPLICATE_PPR_SENT, sender)
            return DISCARD
        # The digest must actually bind the batch content — everything
        # downstream (prepares, commits, message-req recovery) anchors on it.
        # Re-ordered batches keep the digest minted in their original view.
        if msg.digest != self._batch_digest(msg):
            self._suspect(Suspicions.PPR_DIGEST_WRONG, sender)
            return DISCARD
        if key in self.sent_preprepares:
            return PROCESS                         # our own broadcast echoed
        # Re-ordered batches legitimately carry their original timestamp; only
        # fresh batches face the clock-deviation check.
        is_reordered = (msg.original_view_no is not None
                        and msg.original_view_no != msg.view_no)
        now = self._timer.get_current_time()
        if (not is_reordered and
                abs(msg.pp_time - now) > self._config.ACCEPTABLE_DEVIATION_PREPREPARE_SECS):
            self._suspect(Suspicions.PPR_TIME_WRONG, sender)
            return DISCARD
        # A backup instance entering a new view adopts the first pre-prepare
        # it sees as its position — backup sequences have no cross-view
        # continuity guarantee, and without this a backup that lagged at
        # view-change time stalls forever (silently disabling the monitor's
        # master-vs-backup comparison). Ref _setup_last_ordered_for_non_master.
        if self._needs_last_ordered_setup and not self._data.is_master:
            if msg.pp_seq_no - 1 > self._data.last_ordered_3pc[1]:
                self._data.last_ordered_3pc = (msg.view_no, msg.pp_seq_no - 1)
                self._data.pp_seq_no = max(self._data.pp_seq_no,
                                           msg.pp_seq_no - 1)
            self._needs_last_ordered_setup = False
        # Expect strictly consecutive batches from one primary.
        expected = self._last_preprepared_seq() + 1
        if msg.pp_seq_no > expected:
            return STASH(StashReason.FUTURE_3PC)
        # All referenced requests must be finalized locally before we can apply.
        missing = [d for d in msg.req_idr if self._get_request(d) is None]
        if missing and self._data.is_master:
            self._bus.send(RequestPropagates(bad_requests=tuple(missing)))
            return STASH(StashReason.MISSING_REQUESTS)
        if self._bls is not None:
            fault = self._bls.validate_pre_prepare(msg, sender)
            if fault is not None:
                self._suspect(Suspicions.PPR_BLS_MULTISIG_WRONG, sender)
                return DISCARD
        return self._process_valid_preprepare(msg, sender)

    def _last_preprepared_seq(self) -> int:
        seqs = [k[1] for k in self.prePrepares if k[0] == self._data.view_no]
        floor = max(self._data.low_watermark, self._data.last_ordered_3pc[1])
        return max(seqs + [floor])

    def _process_valid_preprepare(self, msg: PrePrepare, sender: str):
        key = (msg.view_no, msg.pp_seq_no)
        # A re-ordered incarnation of a batch whose effects our state already
        # contains: either we executed it ourselves (digest recorded) or a
        # catchup advanced us past its seq_no. This pass only re-certifies it
        # into the new view (vote, count quorums) — never re-apply. If we
        # executed a DIFFERENT batch at this seq_no, voting would endorse a
        # fork — discard and let the suspicion machinery handle the primary.
        rerun = msg.pp_seq_no <= self._data.last_ordered_3pc[1]
        if rerun:
            known = self._ordered_originals.get(
                (_orig_view(msg), msg.pp_seq_no))
            if known is not None and known != msg.digest:
                self._suspect(Suspicions.PPR_DIGEST_WRONG, sender)
                return DISCARD
        # Re-apply the batch and cross-check every root (ref :871-931).
        if self._data.is_master and self._executor is not None and not rerun:
            reqs = [self._get_request(d) for d in msg.req_idr]
            # apply under the ORIGINAL view: the audit txn snapshots
            # (viewNo, primaries), and a re-ordered batch must reproduce the
            # audit root minted in its original view
            orig = _orig_view(msg)
            applied = self._timed_apply(
                msg.ledger_id, reqs, msg.pp_time, orig, msg.pp_seq_no,
                primaries=(list(self._data.primaries)
                           if orig == self._data.view_no else None))
            fault = None
            if tuple(applied.discarded) != tuple(msg.discarded):
                fault = Suspicions.PPR_REJECT_WRONG
            elif applied.state_root != msg.state_root:
                fault = Suspicions.PPR_STATE_WRONG
            elif applied.txn_root != msg.txn_root:
                fault = Suspicions.PPR_TXN_WRONG
            elif (msg.audit_txn_root and
                  applied.audit_txn_root != msg.audit_txn_root):
                fault = Suspicions.PPR_AUDIT_TXN_ROOT_WRONG
            if fault is not None:
                self._executor.revert_last_batch(msg.ledger_id)
                self._suspect(fault, sender)
                return DISCARD
            batch_id = BatchID(msg.view_no, _orig_view(msg),
                               msg.pp_seq_no, msg.digest)
            self._applied_unordered.append((msg.ledger_id, batch_id))
        else:
            batch_id = BatchID(msg.view_no, _orig_view(msg),
                               msg.pp_seq_no, msg.digest)
        self.prePrepares[key] = msg
        if self._metrics is not None:
            self._phase_ts[key] = [self._timer.get_current_time(), None]
        if self._tracer.enabled:
            self._tracer.emit(tracing.PP_RECV, msg.digest,
                              {"seq": msg.pp_seq_no, "frm": sender,
                               "reqs": list(msg.req_idr)})
        self._data.preprepare_batch(batch_id)
        # Commits that raced ahead of this pre-prepare: validate their BLS
        # sigs now that we know the signed roots; evict liars.
        if self._bls is not None:
            for voter, commit in list(self.commits.get(key, {}).items()):
                if self._bls.validate_commit(commit, voter, msg) is not None:
                    del self.commits[key][voter]
                    self._suspect(Suspicions.CM_BLS_WRONG, voter)
                else:
                    self._bls.process_commit(commit, voter)
        self._send_prepare(msg)
        # A stashed future pre-prepare may now be consecutive.
        self._stasher.process_all_stashed(StashReason.FUTURE_3PC)
        self._try_prepare_quorum(key)
        return PROCESS

    def _send_prepare(self, pp: PrePrepare) -> None:
        if self.is_primary:
            return                                  # primary never sends PREPARE
        prepare = Prepare(inst_id=pp.inst_id, view_no=pp.view_no,
                          pp_seq_no=pp.pp_seq_no, pp_time=pp.pp_time,
                          digest=pp.digest, state_root=pp.state_root,
                          txn_root=pp.txn_root, audit_txn_root=pp.audit_txn_root)
        self._network.send(prepare)
        # Our own vote counts toward the prepare quorum.
        key = (pp.view_no, pp.pp_seq_no)
        self.prepares.setdefault(key, {})[self._data.node_name] = prepare

    # ------------------------------------------------------------------ #
    # PREPARE                                                            #
    # ------------------------------------------------------------------ #

    def process_prepare(self, msg: Prepare, sender: str):
        verdict = self._validate(msg)
        if verdict is not PROCESS:
            return verdict
        if sender == self._data.primary_name:
            self._suspect(Suspicions.PR_FRM_PRIMARY, sender)
            return DISCARD
        key = (msg.view_no, msg.pp_seq_no)
        votes = self.prepares.setdefault(key, {})
        if sender in votes:
            if votes[sender].digest != msg.digest:
                self._suspect(Suspicions.DUPLICATE_PR_SENT, sender)
            return DISCARD
        pp = self.prePrepares.get(key)
        if pp is not None and msg.digest != pp.digest:
            self._suspect(Suspicions.PR_DIGEST_WRONG, sender)
            return DISCARD
        votes[sender] = msg
        if pp is None:
            self._maybe_request_preprepare(key)
        self._try_prepare_quorum(key)
        return PROCESS

    def _try_prepare_quorum(self, key: tuple[int, int]) -> None:
        pp = self.prePrepares.get(key)
        if pp is None or key in self._commits_sent:
            return
        votes = self.prepares.get(key, {})
        matching = sum(1 for p in votes.values() if p.digest == pp.digest)
        if not self._data.quorums.prepare.is_reached(matching):
            return
        self._data.prepare_batch(BatchID(pp.view_no, _orig_view(pp),
                                         pp.pp_seq_no, pp.digest))
        ts = self._phase_ts.get(key)
        if ts is not None and ts[1] is None:
            ts[1] = self._timer.get_current_time()
            self._metrics.add_event(MetricsName.PREPARE_PHASE_TIME,
                                    ts[1] - ts[0])
        if self._tracer.enabled:
            self._tracer.emit(tracing.PREPARE_QUORUM, pp.digest,
                              {"seq": key[1], "votes": matching})
        self._send_commit(pp, key)

    def _send_commit(self, pp: PrePrepare, key: tuple[int, int]) -> None:
        params = dict(inst_id=pp.inst_id, view_no=key[0], pp_seq_no=key[1])
        if self._bls is not None:
            params = self._bls.update_commit(params, pp)
        commit = Commit(**params)
        self._commits_sent.add(key)
        if self._tracer.enabled:
            self._tracer.emit(tracing.COMMIT_SENT, pp.digest,
                              {"seq": key[1]})
        self._network.send(commit)
        # Count our own commit vote.
        self.commits.setdefault(key, {})[self._data.node_name] = commit
        if self._bls is not None:
            self._bls.process_commit(commit, self._data.node_name)
        self._try_order(key)

    # ------------------------------------------------------------------ #
    # COMMIT                                                             #
    # ------------------------------------------------------------------ #

    def process_commit(self, msg: Commit, sender: str):
        verdict = self._validate(msg)
        if verdict is not PROCESS:
            # A COMMIT landing after its batch ordered is stale for 3PC but
            # may carry the BLS signature the pending multi-sig aggregation
            # is WAITING on: a batch orders at quorum n-f commits, and if a
            # bad signer is among those first arrivals the honest aggregate
            # falls short until a late sig lands — which used to be
            # discarded here, starving the retry forever (one Byzantine
            # signer suppressed multi-sigs on every node that counted its
            # commit toward the ordering quorum). Strictly this instance's
            # own sig-carrying commits (backup instances broadcast sig-less
            # commits that must not shadow the master's), and only the BLS
            # side sees them — the 3PC vote table stays untouched.
            if (verdict is DISCARD and self._bls is not None
                    and msg.inst_id == self._data.inst_id
                    and msg.bls_sig is not None):
                key = (msg.view_no, msg.pp_seq_no)
                pp = self.prePrepares.get(key)
                if (key in self.ordered and pp is not None
                        and self._bls.validate_commit(msg, sender, pp)
                        is None):
                    self._bls.process_commit(msg, sender)
            return verdict
        key = (msg.view_no, msg.pp_seq_no)
        votes = self.commits.setdefault(key, {})
        if sender in votes:
            return DISCARD
        pp = self.prePrepares.get(key)
        if pp is not None and self._bls is not None:
            fault = self._bls.validate_commit(msg, sender, pp)
            if fault is not None:
                self._suspect(Suspicions.CM_BLS_WRONG, sender)
                return DISCARD
        votes[sender] = msg
        # A commit arriving before its pre-prepare can't have its BLS sig
        # checked yet; _process_valid_preprepare re-validates stored votes, so
        # only validated sigs ever reach aggregation.
        if pp is not None and self._bls is not None:
            self._bls.process_commit(msg, sender)
        if pp is None:
            self._maybe_request_preprepare(key)
        self._try_order(key)
        return PROCESS

    # ------------------------------------------------------------------ #
    # missing-message recovery (ref message_req_processor.py)            #
    # ------------------------------------------------------------------ #

    def _maybe_request_preprepare(self, key: tuple[int, int]) -> None:
        """PREPARE votes certify a pre-prepare we never received (lost on the
        wire): ask peers for it instead of waiting for a full catchup."""
        votes = self.prepares.get(key, {})
        if not votes:
            return
        from collections import Counter
        digest, count = Counter(
            p.digest for p in votes.values()).most_common(1)[0]
        if not self._data.quorums.weak.is_reached(count):
            return
        self._bus.send(MissingMessage(
            msg_type="PREPREPARE",
            key={"inst_id": self._data.inst_id,
                 "view_no": key[0], "pp_seq_no": key[1]},
            inst_id=self._data.inst_id, dst=None, stash_data=(digest,)))

    def process_requested_preprepare(self, msg: PrePrepare) -> None:
        """A peer-served pre-prepare. NEVER taken on trust: it is only
        admitted if f+1 PREPARE votes we independently received certify its
        exact digest — a lying responder cannot inject state, because f+1
        matching prepares contain at least one honest vote for the real
        message."""
        key = (msg.view_no, msg.pp_seq_no)
        if key in self.ordered or key in self.prePrepares:
            return
        # The digest certified by the prepares must really hash THIS content —
        # otherwise a lying responder could attach the certified digest to a
        # mutated batch (different req_idr, roots, or time) and either frame
        # the primary or fork an executor-less backup.
        if msg.digest != self._batch_digest(msg):
            return
        votes = self.prepares.get(key, {})
        matching = sum(1 for p in votes.values() if p.digest == msg.digest)
        if not self._data.quorums.weak.is_reached(matching):
            return
        # Certified: run it through the NORMAL admission path (as if the
        # primary's original broadcast had just arrived) so every stash
        # reason — missing requests, catching up, watermarks — keeps its
        # usual replay semantics instead of silently dropping the recovery.
        self._stasher.dispatch(msg, self._data.primary_name)

    # ------------------------------------------------------------------ #
    # ordering                                                           #
    # ------------------------------------------------------------------ #

    def behind_evidence(self) -> Optional[int]:
        """Highest pp_seq_no with COMMITs from a weak quorum (f+1 distinct
        senders — at least one honest) strictly ahead of our next orderable
        position: proof a live pool is committing past this replica (it
        can never order those without recovering the gap). Weak, not full:
        a node that was down or syncing while the commits flew holds only
        a partial vote record (partition-heal fuzz seed 3362 sat forever
        behind a pool whose full-quorum messages it had half-missed).
        None when no such evidence exists."""
        last = self._data.last_ordered_3pc[1]
        votes_by_key: dict[tuple[int, int], set[str]] = {
            k: set(v) for k, v in self.commits.items() if k[1] > last + 1}
        # Commits the admission gate PARKED never reach self.commits, yet
        # a weak quorum of them is the same proof the pool committed past
        # us. The blind spot this closes (membership-churn fuzz): a node
        # whose stale registry makes it wait for a NEW_VIEW that will
        # never validate stashes the entire pool's ordering traffic under
        # WAITING_FOR_NEW_VIEW and looks "not behind" forever; likewise a
        # re-promoted straggler whose gap exceeds the watermark window
        # (OUTSIDE_WATERMARKS) or whose pool moved views (FUTURE_VIEW).
        for queue in self._stasher._queues.values():
            for message, args, _handler in queue:
                if isinstance(message, Commit) and message.pp_seq_no > last + 1:
                    votes_by_key.setdefault(
                        (message.view_no, message.pp_seq_no),
                        set()).add(args[0] if args else "")
        best = None
        for k, votes in votes_by_key.items():
            if self._data.quorums.weak.is_reached(len(votes)):
                best = k[1] if best is None else max(best, k[1])
        return best

    def _stage_batch(self, pp: PrePrepare) -> bool:
        """Re-stage an in-flight batch's uncommitted apply (the catchup
        re-apply twin of _process_valid_preprepare's admission apply):
        fetch requests, apply under the ORIGINAL view, cross-check every
        root the pre-prepare claims, consume the requests from the queues.
        -> False (with the apply reverted) when the batch cannot be staged
        faithfully — missing requests or non-reproducing roots."""
        reqs = [self._get_request(d) for d in pp.req_idr]
        if any(r is None for r in reqs):
            return False
        orig = _orig_view(pp)
        applied = self._timed_apply(
            pp.ledger_id, reqs, pp.pp_time, orig, pp.pp_seq_no,
            primaries=(list(self._data.primaries)
                       if orig == self._data.view_no else None))
        if (applied.state_root != pp.state_root
                or applied.txn_root != pp.txn_root
                or (pp.audit_txn_root
                    and applied.audit_txn_root != pp.audit_txn_root)):
            self._executor.revert_last_batch(pp.ledger_id)
            return False
        self._applied_unordered.append(
            (pp.ledger_id, BatchID(pp.view_no, orig,
                                   pp.pp_seq_no, pp.digest)))
        # catchup_started's revert re-queued these requests; they ride
        # THIS re-applied batch — leaving them queued would double-order
        # them in a later fresh batch (fuzz seed 45)
        for queue in self.request_queues.values():
            for d in pp.req_idr:
                queue.pop(d, None)
        return True

    def _can_order(self, key: tuple[int, int]) -> bool:
        if key in self.ordered:
            return False
        if self.prePrepares.get(key) is None:
            return False
        if key not in self._commits_sent:
            return False                 # we haven't prepared it ourselves yet
        votes = len(self.commits.get(key, {}))
        return self._data.quorums.commit.is_reached(votes)

    def _try_order(self, key: tuple[int, int]) -> None:
        if not self._can_order(key):
            return
        pp = self.prePrepares[key]
        # In-order constraint: pp_seq_no must directly follow the last ordered
        # batch; otherwise stash the completed commit (ref :191,1642).
        if key[1] != self._data.last_ordered_3pc[1] + 1:
            self._stashed_ooo_commits[key] = pp
            return
        self._order(key, pp)
        # Drain any consecutive stashed completions.
        while True:
            next_key = self._find_stashed_next()
            if next_key is None:
                break
            self._order(next_key, self._stashed_ooo_commits.pop(next_key))

    def _find_stashed_next(self):
        for k in sorted(self._stashed_ooo_commits):
            if k[1] == self._data.last_ordered_3pc[1] + 1 and self._can_order(k):
                return k
        return None

    def _order(self, key: tuple[int, int], pp: PrePrepare) -> None:
        ts = self._phase_ts.pop(key, None)
        if ts is not None and self._metrics is not None:
            now = self._timer.get_current_time()
            if ts[1] is not None:
                self._metrics.add_event(MetricsName.COMMIT_PHASE_TIME,
                                        now - ts[1])
            self._metrics.add_event(MetricsName.ORDERING_TIME, now - ts[0])
        t_cut = self._cut_ts.pop(key, None)
        if t_cut is not None and self._controller is not None:
            # cut -> commit quorum on the injectable timer: the 3PC span
            # sample the controller steers depth/size against
            self._controller.note_ordered(
                self._timer.get_current_time() - t_cut)
        if self._tracer.enabled:
            self._tracer.emit(tracing.ORDERED, pp.digest,
                              {"seq": key[1],
                               "votes": len(self.commits.get(key, {}))})
        orig_key = (_orig_view(pp), pp.pp_seq_no)
        rerun = self._ordered_originals.get(orig_key) == pp.digest
        self.ordered.add(key)
        self._ordered_originals[orig_key] = pp.digest
        self._data.last_ordered_3pc = key
        # Ordered requests must never be re-proposed from this node's queue.
        for queue in self.request_queues.values():
            for digest in pp.req_idr:
                queue.pop(digest, None)
        batch_id = BatchID(pp.view_no, _orig_view(pp),
                           pp.pp_seq_no, pp.digest)
        # NOTE: the batch's prepared/preprepared certificate deliberately
        # SURVIVES ordering (gc() drops it at checkpoint stabilization): a
        # view change before the covering checkpoint must still carry this
        # certificate, or peers that didn't order it can never recover it
        # and the new primary could even mint a different batch at this
        # seq_no (fork). Found by the seeded view-change fuzz.
        self._applied_unordered = [(lid, b) for (lid, b) in self._applied_unordered
                                   if b != batch_id]
        if self._bls is not None:
            self._bls.process_order(key, pp)
        if rerun:
            # already executed under its original view: this pass only
            # re-certified the batch into the new view's 3PC chain
            return
        discarded_set = set(pp.discarded)
        ordered = Ordered(inst_id=pp.inst_id, view_no=key[0],
                          pp_seq_no=key[1], pp_time=pp.pp_time,
                          req_idr=tuple(d for d in pp.req_idr
                                        if d not in discarded_set),
                          discarded=pp.discarded,
                          ledger_id=pp.ledger_id, state_root=pp.state_root,
                          txn_root=pp.txn_root,
                          audit_txn_root=pp.audit_txn_root,
                          original_view_no=pp.original_view_no)
        self._bus.send(ordered)

    # ------------------------------------------------------------------ #
    # revert / catchup / view change                                     #
    # ------------------------------------------------------------------ #

    def revert_unordered_batches(self) -> int:
        """Undo every applied-but-unordered batch, newest first (ref :1229)."""
        count = 0
        while self._applied_unordered:
            ledger_id, batch_id = self._applied_unordered.pop()
            if self._executor is not None and self._data.is_master:
                self._executor.revert_last_batch(ledger_id)
            # The certificate is NOT freed: a reverted-but-prepared batch
            # must keep appearing in this node's ViewChange messages across
            # ESCALATED view changes too (each escalation re-snapshots
            # data.prepared) — gc() at checkpoint stabilization is the only
            # legitimate certificate reaper.
            # Reverted requests go back in the queue (ref :2201) — they will
            # either be re-ordered from the old-view pre-prepare or re-batched.
            pp = self.prePrepares.get((batch_id.view_no, batch_id.pp_seq_no))
            if pp is not None:
                queue = self.request_queues.setdefault(ledger_id, OrderedDict())
                now = self._timer.get_current_time()
                for digest in pp.req_idr:
                    # re-enqueue with a fresh batch-wait stamp (the original
                    # enqueue time died with the reverted batch); setdefault
                    # so a digest already waiting keeps its older stamp
                    queue.setdefault(digest, now)
            count += 1
        return count

    def catchup_started(self) -> None:
        self.revert_unordered_batches()
        self._data.is_participating = False

    def caught_up_till_3pc(self, last_3pc: tuple[int, int]) -> None:
        """Adopt the 3PC position reached through catchup (ref :2223).

        The stable checkpoint is rounded DOWN to the CHK_FREQ boundary
        (ref checkpoint_service.py:137-139): claiming stability at an
        off-boundary seq-no the rest of the pool holds no certificate for
        deadlocks the next view change — NewViewBuilder.calc_checkpoint
        requires a strong quorum whose stable <= the selected checkpoint,
        and no candidate at the off-boundary height can exist. A node
        restored to seq 1 therefore reports stable 0 (which every node's
        'initial' checkpoint satisfies), not 1.
        """
        if last_3pc > self._data.last_ordered_3pc:
            chk = max(1, self._config.CHK_FREQ)
            boundary = last_3pc[1] // chk * chk
            self._data.last_ordered_3pc = last_3pc
            self._data.pp_seq_no = max(self._data.pp_seq_no, last_3pc[1])
            self._data.low_watermark = max(self._data.low_watermark, boundary)
            self._data.stable_checkpoint = max(self._data.stable_checkpoint,
                                               boundary)
        # Everything at or below the RESULTING position is history. The
        # bound is our CURRENT last_ordered, not the raw catchup target:
        # ordering can keep advancing while a (possibly stale-quorum)
        # catchup is in flight, and cleaning/re-staging against the lower
        # target re-staged batches whose effects were already committed —
        # the write manager then held phantom applies and crashed at the
        # next real commit ("commit out of order", partition-heal fuzz).
        # The pre-prepares themselves stay fetchable as old-view material:
        # a later NewView below a stable checkpoint may cite these exact
        # batches, and a pool where every retainer pruned them wedges all
        # re-proposal at the first unfetchable citation.
        pos = self._data.last_ordered_3pc[1]
        for k, pp in list(self.prePrepares.items()):
            if k[1] <= pos:
                orig = pp.original_view_no \
                    if pp.original_view_no is not None else k[0]
                self.old_view_preprepares[(orig, k[1])] = pp
        for store in (self.prePrepares, self.sent_preprepares,
                      self.prepares, self.commits):
            for k in [k for k in store if k[1] <= pos]:
                del store[k]
        self._stashed_ooo_commits = {
            k: v for k, v in self._stashed_ooo_commits.items()
            if k[1] > pos}
        # In-flight batches ABOVE the caught-up position lost their staged
        # applies when catchup_started reverted the uncommitted stack; the
        # stashed commits about to process would otherwise order them with
        # nothing staged to commit ("commit with no applied batches" —
        # partition-heal fuzz). Re-apply them in seq order via the shared
        # staging helper (same root cross-check as first admission).
        # ONLY current-view entries: a view-jump catchup (the node view is
        # adopted before this runs) leaves old-view pre-prepares that can
        # never order directly in this view — re-staging one would corrupt
        # the fresh uncommitted stack and make every later honest batch's
        # roots mismatch. They stay fetchable as old-view material.
        if self._data.is_master and self._executor is not None:
            applied_ids = {b for (_l, b) in self._applied_unordered}
            for key in sorted(self.prePrepares, key=lambda k: k[1]):
                pp = self.prePrepares[key]
                if key in self.ordered or key[0] != self._data.view_no:
                    continue
                if self._ordered_originals.get(
                        (_orig_view(pp), pp.pp_seq_no)) == pp.digest:
                    continue    # re-certified content: executed already
                bid = BatchID(pp.view_no, _orig_view(pp),
                              pp.pp_seq_no, pp.digest)
                if bid in applied_ids:
                    continue
                if not self._stage_batch(pp):
                    # cannot re-stage (requests gone, or roots no longer
                    # reproduce): this and every later in-flight batch is
                    # unrecoverable locally — drop them; the normal
                    # missing-PP recovery or the next NewView re-supplies
                    for k in [k for k in self.prePrepares
                              if k[1] >= key[1]
                              and k[0] == self._data.view_no
                              and k not in self.ordered]:
                        del self.prePrepares[k]
                    break
        self._data.is_participating = True
        if self._last_new_view_msg is not None:
            # a NewView accepted mid-catchup deferred its re-proposal
            # pass (see process_new_view_checkpoints_applied); run it on
            # the caught-up state before releasing the stashed traffic
            self.process_new_view_checkpoints_applied(
                self._last_new_view_msg)
        self._stasher.process_all_stashed(StashReason.CATCHING_UP)
        self._stasher.process_all_stashed(StashReason.OUTSIDE_WATERMARKS)
        # a catchup can JUMP views (audit adoption): messages stashed as
        # future-view are now current-view material — without this drain a
        # straggler that caught up mid-view never processes the 3PC
        # messages for the batches it missed (partition-heal fuzz); still-
        # future ones simply re-stash through _validate
        self._stasher.process_all_stashed(StashReason.FUTURE_VIEW)

    def process_view_change_started(self, msg: ViewChangeStarted) -> None:
        """Entering a view change: revert uncommitted work, remember old-view
        pre-prepares for possible re-ordering (ref :2380)."""
        self._phase_ts.clear()      # timings don't span views
        self._cut_ts.clear()        # controller spans don't span views
        self.revert_unordered_batches()
        # ALL pre-prepares (ordered ones too) become old-view material: a
        # NewView may cite an already-ordered batch, and both the re-sending
        # primary and the MessageReq server look it up by ORIGINAL view here
        for key, pp in self.prePrepares.items():
            orig = pp.original_view_no if pp.original_view_no is not None else key[0]
            self.old_view_preprepares[(orig, key[1])] = pp
        self.prePrepares = {k: v for k, v in self.prePrepares.items()
                            if k in self.ordered}
        self.sent_preprepares.clear()
        self.prepares.clear()
        self.commits.clear()
        self._commits_sent.clear()
        self._stashed_ooo_commits.clear()
        self._awaited_old_view.clear()
        self._awaiting_reproposal.clear()
        self._last_new_view_msg = None
        if not self._data.is_master:
            self._needs_last_ordered_setup = True

    def process_requested_old_view_preprepare(self, pp: PrePrepare) -> None:
        """A peer served an old-view pre-prepare the NewView cited but we
        lacked. Admitted ONLY if its digest matches the NewView citation
        (which a view-change quorum stands behind) and it binds its content."""
        orig = _orig_view(pp)
        key = (orig, pp.pp_seq_no)
        expected = self._awaited_old_view.get(key)
        if expected is None or pp.digest != expected:
            return
        if pp.digest != self._batch_digest(pp):
            return
        del self._awaited_old_view[key]
        self.old_view_preprepares[key] = pp
        if self._last_new_view_msg is not None:
            self.process_new_view_checkpoints_applied(self._last_new_view_msg)

    def process_new_view_checkpoints_applied(self, msg: NewViewCheckpointsApplied) -> None:
        """Re-order the prepared batches carried into the new view
        (ref process_new_view_checkpoints_applied :2380)."""
        self._last_new_view_msg = msg
        if not self._data.is_participating:
            # a view change can complete WHILE this replica catches up
            # (internal-bus traffic bypasses the wire stasher). Applying
            # re-proposals now would stage batches underneath a catchup
            # that writes the same txns straight to the ledgers — phantom
            # applies that crash the next real commit (partition-heal
            # fuzz seed 4175). Defer: caught_up_till_3pc re-enters with
            # the saved NewView once participation resumes.
            return
        self._awaiting_reproposal.clear()   # recomputed by this pass
        # Continue the sequence from what actually survives into the new view:
        # ordered prefix, selected checkpoint, re-ordered batches — and EVERY
        # seq_no the NewView cites, held locally or not. Minting a fresh batch
        # at a cited-but-locally-missing seq_no would be a consensus fork
        # (nodes that ordered the certified batch in the old view hold a
        # different txn at that seq). Only null-certified gaps may be reused.
        cited_seqs = [b[2] for b in msg.batches]
        self._data.pp_seq_no = max([self._data.last_ordered_3pc[1],
                                    msg.checkpoint[2]] + cited_seqs)
        # NOTE batches at or below our last_ordered are NOT skipped: a
        # lagging peer needs the whole quorum to re-run 3PC on them (we
        # vote without re-executing — see the rerun guards); skipping
        # here stranded laggards forever (found by the view-change fuzz).
        #
        # Pass 1: fetch EVERY missing old-view pre-prepare in parallel.
        todo = []
        for (_view, orig_view, pp_seq_no, digest) in sorted(
                msg.batches, key=lambda b: b[2]):
            if pp_seq_no <= msg.checkpoint[2]:
                continue      # below the quorum checkpoint: catchup ground
            if (self._data.view_no, pp_seq_no) in self.prePrepares:
                continue      # already re-ordered (idempotent re-entry)
            old_pp = self.old_view_preprepares.get((orig_view, pp_seq_no))
            if old_pp is None or old_pp.digest != digest:
                # ask peers for the certified old-view pre-prepare instead of
                # silently leaving the gap (ref OldViewPrePrepareRequest
                # ordering_service.py:2409); the rep is validated against the
                # NewView-cited digest before use
                self._awaited_old_view[(orig_view, pp_seq_no)] = digest
                self._bus.send(MissingMessage(
                    msg_type="OLD_VIEW_PREPREPARE",
                    key={"inst_id": self._data.inst_id,
                         "view_no": orig_view, "pp_seq_no": pp_seq_no},
                    inst_id=self._data.inst_id, dst=None))
                old_pp = None
            todo.append((orig_view, pp_seq_no, digest, old_pp))
        # Pass 2: re-send/apply STRICTLY in seq order, stopping at the first
        # still-missing batch — each reply re-enters this method, and
        # applying whatever happened to be available produced out-of-order
        # uncommitted applies (commit then crashed; found by the fuzz).
        for (orig_view, pp_seq_no, digest, old_pp) in todo:
            if old_pp is None:
                if pp_seq_no <= self._data.last_ordered_3pc[1]:
                    # Cited batch is unfetchable (e.g. the whole pool
                    # crash-restarted past it) but its effects are already
                    # in OUR committed state: nothing to re-run for us —
                    # skipping cannot fork us, PROVIDED what we ordered at
                    # this seq matches the citation when we still know it.
                    known = self._ordered_originals.get(
                        (orig_view, pp_seq_no))
                    if known is not None and known != digest:
                        # we ordered a DIFFERENT batch than the quorum
                        # certified (beyond-f damage): resync, don't vote
                        self._awaited_old_view.pop(
                            (orig_view, pp_seq_no), None)
                        self._bus.send(NeedMasterCatchup())
                        break
                    self._awaited_old_view.pop((orig_view, pp_seq_no), None)
                    continue
                break
            # These requests ride the re-ordered batch; don't re-batch them.
            for queue in self.request_queues.values():
                for d in old_pp.req_idr:
                    queue.pop(d, None)
            import dataclasses
            new_pp = dataclasses.replace(old_pp, view_no=self._data.view_no,
                                         original_view_no=orig_view)
            key = (self._data.view_no, pp_seq_no)
            # seq-based like _process_valid_preprepare: _ordered_originals is
            # in-memory only (empty after restart, trimmed by gc), but
            # last_ordered survives restart via the audit restore — a batch
            # at or below it is already in our committed state
            rerun = (pp_seq_no <= self._data.last_ordered_3pc[1]
                     or self._ordered_originals.get(
                         (orig_view, pp_seq_no)) == digest)
            if self.is_primary:
                if self._data.is_master and self._executor is not None \
                        and not rerun:
                    # the primary must HOLD every request to re-apply the
                    # cited batch faithfully; a gap (never propagated to
                    # us, or swept) is fetched and the re-proposal resumes
                    # from this seq when the requests land (process_req_key
                    # re-enters; strict order forbids skipping ahead) —
                    # applying with None holes crashed the write manager
                    # (byzantine fuzz seed 2453)
                    missing = tuple(d for d in new_pp.req_idr
                                    if self._get_request(d) is None)
                    if missing:
                        self._awaiting_reproposal = set(missing)
                        self._bus.send(
                            RequestPropagates(bad_requests=missing))
                        break
                self.sent_preprepares[key] = new_pp
                self.prePrepares[key] = new_pp
                self._data.pp_seq_no = max(self._data.pp_seq_no, pp_seq_no)
                if self._data.is_master and self._executor is not None \
                        and not rerun:
                    reqs = [self._get_request(d) for d in new_pp.req_idr]
                    self._timed_apply(
                        new_pp.ledger_id, reqs, new_pp.pp_time,
                        orig_view, pp_seq_no,
                        primaries=(list(self._data.primaries)
                                   if orig_view == self._data.view_no
                                   else None))
                    self._applied_unordered.append(
                        (new_pp.ledger_id,
                         BatchID(self._data.view_no, orig_view, pp_seq_no, digest)))
                self._data.preprepare_batch(
                    BatchID(self._data.view_no, orig_view, pp_seq_no, digest))
                self._network.send(new_pp)
            else:
                # Non-primaries re-admit the batch through the normal path when
                # the primary's re-sent PRE-PREPARE arrives; nothing to do now.
                self._data.pp_seq_no = max(self._data.pp_seq_no, pp_seq_no)
        self._stasher.process_all_stashed(StashReason.WAITING_FOR_NEW_VIEW)
        self._stasher.process_all_stashed(StashReason.FUTURE_VIEW)

    # ------------------------------------------------------------------ #
    # GC                                                                 #
    # ------------------------------------------------------------------ #

    def gc(self, stable_3pc: tuple[int, int]) -> None:
        """Drop 3PC log entries at or below a stabilized checkpoint."""
        seq = stable_3pc[1]
        for store in (self.prePrepares, self.sent_preprepares,
                      self.prepares, self.commits, self._phase_ts,
                      self._cut_ts):
            for k in [k for k in store if k[1] <= seq]:
                del store[k]
        # certificate lists follow the same lifetime as the 3PC logs
        self._data.preprepared = [b for b in self._data.preprepared
                                  if b.pp_seq_no > seq]
        self._data.prepared = [b for b in self._data.prepared
                               if b.pp_seq_no > seq]
        self.ordered = {k for k in self.ordered if k[1] > seq}
        self._ordered_originals = {k: v for k, v in
                                   self._ordered_originals.items()
                                   if k[1] > seq}
        self._stashed_ooo_commits = {k: v for k, v in
                                     self._stashed_ooo_commits.items()
                                     if k[1] > seq}
        self._commits_sent = {k for k in self._commits_sent if k[1] > seq}
        self.old_view_preprepares = {k: v for k, v in self.old_view_preprepares.items()
                                     if k[1] > seq}
        if self._bls is not None:
            self._bls.gc(stable_3pc)
        self._stasher.process_all_stashed(StashReason.OUTSIDE_WATERMARKS)
