"""Per-replica single source of truth shared by the consensus services.

Reference behavior: plenum/server/consensus/consensus_shared_data.py:19 — one
mutable record per protocol instance holding view state, watermarks, in-flight
batches, checkpoints, and primaries. Services read/write it; the buses carry
the events. Nothing here touches the network.
"""
from __future__ import annotations

from typing import Optional

from plenum_tpu.common.node_messages import Checkpoint, PrePrepare
from plenum_tpu.common.quorums import Quorums

from .batch_id import BatchID


def replica_name(node_name: str, inst_id: int) -> str:
    return f"{node_name}:{inst_id}"


def node_name_of(replica: str) -> str:
    return replica.rsplit(":", 1)[0]


class ConsensusSharedData:
    def __init__(self, name: str, validators: list[str], inst_id: int,
                 is_master: bool = True):
        self.name = name                        # replica name "Node:inst"
        self.inst_id = inst_id
        self.is_master = is_master
        self.view_no = 0
        self.waiting_for_new_view = False
        self.primaries: list[str] = []          # node names, rank == inst_id

        self.legacy_vc_in_progress = False
        self.is_participating = True

        # 3PC log state
        self.low_watermark = 0
        self.log_size = 300
        self.pp_seq_no = 0                      # last pp_seq_no this primary assigned
        self.last_ordered_3pc: tuple[int, int] = (0, 0)
        self.last_batch_timestamp = 0.0

        # In-flight batches (ordered by pp_seq_no)
        self.preprepared: list[BatchID] = []
        self.prepared: list[BatchID] = []

        # Checkpoints. Every node starts with the same virtual checkpoint at
        # seq 0 so the very first view change has a selectable candidate
        # (ref consensus_shared_data initial checkpoint).
        self.stable_checkpoint = 0
        self.checkpoints: list[Checkpoint] = [Checkpoint(
            inst_id=inst_id, view_no=0, seq_no_start=0, seq_no_end=0,
            digest="initial")]
        self.low_watermark = 0

        # View change artifacts
        self.new_view_votes = None
        self.prev_view_prepare_cert: Optional[int] = None

        self._validators: list[str] = []
        self.quorums = Quorums(len(validators) or 1)
        self.set_validators(validators)

    # --- pool membership --------------------------------------------------

    @property
    def validators(self) -> list[str]:
        return self._validators

    def set_validators(self, validators: list[str]) -> None:
        self._validators = list(validators)
        self.quorums = Quorums(len(validators))

    @property
    def total_nodes(self) -> int:
        return len(self._validators)

    @property
    def node_name(self) -> str:
        return node_name_of(self.name)

    # --- primary ----------------------------------------------------------

    @property
    def primary_name(self) -> Optional[str]:
        if self.inst_id < len(self.primaries):
            return self.primaries[self.inst_id]
        return None

    @property
    def is_primary(self) -> bool:
        return self.primary_name == self.node_name

    # --- watermarks -------------------------------------------------------

    @property
    def high_watermark(self) -> int:
        return self.low_watermark + self.log_size

    def is_in_watermarks(self, pp_seq_no: int) -> bool:
        return self.low_watermark < pp_seq_no <= self.high_watermark

    # --- in-flight batch helpers -----------------------------------------

    def preprepare_batch(self, batch_id: BatchID) -> None:
        if batch_id not in self.preprepared:
            self.preprepared.append(batch_id)

    def prepare_batch(self, batch_id: BatchID) -> None:
        if batch_id not in self.prepared:
            self.prepared.append(batch_id)

    def reset_in_flight(self) -> None:
        self.preprepared.clear()
        self.prepared.clear()
